"""Fused (grouped multi-tensor) optimizer update — r06 perf round.

The contract: `Optimizer.apply_fn(fused=True)` is BIT-IDENTICAL to the
sequential per-parameter loop on the same (params, grads, slots, lr, t) —
pinned here on state captured from a REAL TrainStep mid-training, jitted
like production. Whole-step trajectories across the knob are additionally
pinned to loss-equality (flipping the knob recompiles the step, and XLA
may re-fuse the unrelated backward — the update itself stays bit-exact,
which is what these tests isolate).
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.jit import TrainStep
from paddle_tpu.nn import functional as F


class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.fc2 = nn.Linear(32, 8)
        self.fc3 = nn.Linear(8, 4)

    def forward(self, x):
        return self.fc3(F.relu(self.fc2(F.relu(self.fc1(x)))))


def _batch():
    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.normal(size=(8, 16)).astype("float32"))
    y = paddle.to_tensor(rng.integers(0, 4, (8,)).astype("int64"))
    return x, y


def _make_step(opt_cls, fused, **kw):
    paddle.seed(0)
    m = _MLP()
    opt = opt_cls(learning_rate=1e-2, parameters=m.parameters(), **kw)
    return TrainStep(m, F.cross_entropy, opt, fused_opt=fused)


def _tree_bit_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


class TestBitParityOnTrainStep:
    """The acceptance pin: fused vs sequential update, bit-identical on
    real mid-training TrainStep state (params + slots evolved 3 steps,
    real grads from the model's backward)."""

    @pytest.mark.parametrize("opt_cls,kw", [
        (optimizer.SGD, {}),
        (optimizer.Momentum, dict(momentum=0.9)),
        (optimizer.Adam, {}),
        (optimizer.AdamW, dict(weight_decay=0.01)),
    ])
    def test_update_bit_identical_on_real_state(self, opt_cls, kw):
        x, y = _batch()
        st = _make_step(opt_cls, fused=True, **kw)
        assert st.fused_opt, "fused update did not engage"
        for _ in range(3):
            st(x, y)
        opt = st.optimizer
        params, state = st.params, st.opt_state

        # real grads at the evolved params, through the real loss
        def loss_of(p):
            out, _ = st.apply_fn(p, st.buffers, jax.random.PRNGKey(0),
                                 x.data)
            from paddle_tpu.framework.tensor import Tensor
            l = F.cross_entropy(jax.tree_util.tree_map(Tensor, out),
                                Tensor(y.data))
            return l.data if hasattr(l, "data") else l
        grads = jax.grad(loss_of)(params)

        seq = jax.jit(lambda p, g, s: opt.apply_fn(p, g, s, lr=0.01, t=7,
                                                   fused=False))
        fus = jax.jit(lambda p, g, s: opt.apply_fn(p, g, s, lr=0.01, t=7,
                                                   fused=True))
        ps, ss = seq(params, grads, state)
        pf, sf = fus(params, grads, state)
        assert _tree_bit_equal(ps, pf), "fused params differ bitwise"
        assert _tree_bit_equal(ss, sf), "fused slots differ bitwise"

    def test_trajectory_losses_and_structure(self):
        x, y = _batch()
        sf = _make_step(optimizer.AdamW, True, weight_decay=0.01)
        ss = _make_step(optimizer.AdamW, False, weight_decay=0.01)
        assert sf.fused_opt and not ss.fused_opt
        lf = [float(sf(x, y)) for _ in range(5)]
        ls = [float(ss(x, y)) for _ in range(5)]
        assert lf == ls, "fused/sequential loss trajectories diverged"
        # state TREES stay structurally identical (checkpoints, donation
        # and sharding code walk them)
        tf = jax.tree_util.tree_structure(sf.opt_state)
        ts = jax.tree_util.tree_structure(ss.opt_state)
        assert tf == ts


class TestGatesAndFallbacks:
    def test_env_kill_switch(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FUSED_OPT", "0")
        st = _make_step(optimizer.AdamW, None, weight_decay=0.01)
        assert not st.fused_opt

    def test_default_on(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_FUSED_OPT", raising=False)
        st = _make_step(optimizer.Adam, None)
        assert st.fused_opt

    def test_non_elementwise_optimizers_stay_sequential(self):
        for cls in (optimizer.Lamb, optimizer.LarsMomentum):
            paddle.seed(0)
            m = _MLP()
            o = cls(parameters=m.parameters())
            assert not o.fused_update_supported
            st = TrainStep(m, F.cross_entropy, o, fused_opt=True)
            assert not st.fused_opt

    def test_mixed_dtype_groups(self):
        """bf16 + f32 params group separately and stay bit-identical
        (the cast rules match the sequential loop's per-leaf casts)."""
        rng = np.random.default_rng(1)
        params = {
            "w_bf16": jnp.asarray(rng.normal(size=(32, 16)),
                                  jnp.bfloat16),
            "b_bf16": jnp.asarray(rng.normal(size=(16,)), jnp.bfloat16),
            "w_f32": jnp.asarray(rng.normal(size=(16, 8)).astype("f4")),
            "b_f32": jnp.asarray(rng.normal(size=(8,)).astype("f4")),
        }
        grads = {k: jnp.asarray(rng.normal(size=v.shape).astype("f4"))
                 for k, v in params.items()}
        opt = optimizer.Adam(parameters=[
            paddle.to_tensor(np.zeros(1, dtype=np.float32))])
        state = opt.init_state_tree(params)
        ps, ss = jax.jit(lambda: opt.apply_fn(params, grads, state,
                                              lr=0.01, t=2, fused=False))()
        pf, sf = jax.jit(lambda: opt.apply_fn(params, grads, state,
                                              lr=0.01, t=2, fused=True))()
        assert _tree_bit_equal(ps, pf) and _tree_bit_equal(ss, sf)
        assert pf["w_bf16"].dtype == jnp.bfloat16
        assert pf["w_f32"].dtype == jnp.float32

    def test_per_param_kw_groups(self):
        """AdamW decay exclusion splits groups; parity still holds."""
        rng = np.random.default_rng(2)
        params = {f"p{i}": jnp.asarray(
            rng.normal(size=(8, 8)).astype("f4")) for i in range(4)}
        grads = {k: jnp.asarray(rng.normal(size=v.shape).astype("f4"))
                 for k, v in params.items()}
        opt = optimizer.AdamW(
            parameters=[paddle.to_tensor(np.zeros(1, dtype=np.float32))],
            weight_decay=0.1,
            apply_decay_param_fun=lambda n: "p0" in n or "p2" in n)
        state = opt.init_state_tree(params)
        ps, _ = opt.apply_fn(params, grads, state, lr=0.01, t=3,
                             fused=False)
        pf, _ = opt.apply_fn(params, grads, state, lr=0.01, t=3,
                             fused=True)
        assert _tree_bit_equal(ps, pf)

    def test_odd_slot_shape_falls_back_solo(self):
        """A leaf whose loaded slot shape mismatches its param (a legacy
        state_dict) must not join a fused group — concatenation would be
        shape-nonsense. It runs solo and matches the sequential path."""
        rng = np.random.default_rng(3)
        params = {k: jnp.asarray(rng.normal(size=(8, 8)).astype("f4"))
                  for k in ("a", "b", "c")}
        grads = {k: jnp.asarray(rng.normal(size=v.shape).astype("f4"))
                 for k, v in params.items()}
        opt = optimizer.Momentum(
            parameters=[paddle.to_tensor(np.zeros(1, dtype=np.float32))])
        state = opt.init_state_tree(params)
        # scalar velocity broadcasts in _update — legal sequentially,
        # but must NOT be concatenated with the (8, 8) slots
        state["a"]["velocity"] = jnp.zeros((), jnp.float32)
        ps, ss = opt.apply_fn(params, grads, state, lr=0.01, t=1,
                              fused=False)
        pf, sf = opt.apply_fn(params, grads, state, lr=0.01, t=1,
                              fused=True)
        assert _tree_bit_equal(ps, pf) and _tree_bit_equal(ss, sf)


class TestDonationPreserved:
    def test_trainstep_donation_with_fused_opt(self):
        """Param/opt-state donation must survive the fused update (the
        acceptance criterion names tests/test_donation.py; this is the
        fused-path sibling at the Lowered.args_info level)."""
        x, y = _batch()
        st = _make_step(optimizer.AdamW, True, weight_decay=0.01)
        assert st.fused_opt
        lowered = st._step.lower(st.params, st.buffers, st.opt_state,
                                 jax.random.PRNGKey(0),
                                 jnp.float32(0.01), 1, x.data, y.data)
        donated = [a.donated for a in jax.tree_util.tree_leaves(
            lowered.args_info)]
        # params (arg 0) and opt_state (arg 2) leaves donate; count them
        n_params = len(jax.tree_util.tree_leaves(st.params))
        n_opt = len(jax.tree_util.tree_leaves(st.opt_state))
        assert sum(donated) == n_params + n_opt


class TestDuckTypedOptimizer:
    def test_legacy_apply_fn_protocol_still_works(self):
        """Review regression: a non-Optimizer duck-typed optimizer whose
        apply_fn lacks the new `fused` kwarg must keep working (the
        kwarg is only passed when fusing, which such optimizers never
        opt into)."""
        import jax.numpy as jnp

        class LegacySGD:
            def __init__(self, params):
                self._lr = 0.1

            def get_lr(self):
                return self._lr

            def init_state_tree(self, params):
                return {k: {} for k in params}

            def apply_fn(self, params, grads, state, lr=None, t=1):
                lr = self._lr if lr is None else lr
                new = {k: (params[k] - lr * grads[k]).astype(
                    params[k].dtype) for k in params}
                return new, state

        x, y = _batch()
        paddle.seed(0)
        m = _MLP()
        st = TrainStep(m, F.cross_entropy, LegacySGD(m.parameters()),
                       fused_opt=True)  # requested, but unsupported
        assert not st.fused_opt
        l0 = float(st(x, y))
        l1 = float(st(x, y))
        assert np.isfinite(l0) and l1 < l0
