"""ObservabilityServer (profiler/server.py): endpoint contracts, step
liveness, concurrent scrape-under-mutation, compile attribution on a forced
retrace, device-time attribution, and the metrics_dump --url path.
"""
import json
import os
import re
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import (compile_watch, device_time, events,
                                 metrics as metrics_mod)
from paddle_tpu.profiler import server as server_mod
from paddle_tpu.profiler.server import ObservabilityServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def _get(port, path, timeout=10):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


@pytest.fixture()
def srv():
    s = ObservabilityServer()
    s.start(0)
    yield s
    s.stop()


@pytest.fixture(autouse=True)
def _fresh_liveness():
    with server_mod._liveness_lock:
        server_mod._liveness.update(step=None, ts=None, wall_ts=None)
    yield


_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})?\s+[0-9eE.+-]+(\s+\d+)?$")


def _assert_valid_prometheus(body: str):
    assert body.startswith("# HELP ")
    for line in body.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _PROM_SAMPLE.match(line), f"bad exposition line: {line!r}"


class TestEndpoints:
    def test_metrics_serves_prometheus_text(self, srv):
        metrics_mod.default_registry().counter(
            "op_calls_total", "eager op dispatches by op name").inc(
            op="srvtest")
        status, body, headers = _get(srv.port, "/metrics")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        _assert_valid_prometheus(body)
        assert 'paddle_tpu_op_calls_total{op="srvtest"}' in body

    def test_snapshot_is_one_json_object(self, srv):
        status, body, _ = _get(srv.port, "/snapshot")
        assert status == 200
        doc = json.loads(body)
        for key in ("metrics", "watchdog", "compile_attribution",
                    "liveness", "events_tail", "ts"):
            assert key in doc
        assert "compiles" in doc["watchdog"]

    def test_events_endpoint_filters(self, srv):
        events.default_event_log().clear()
        events.emit("retrace", name="srvtest_a")
        events.emit("barrier_abort", severity="warn", step=1)
        status, body, _ = _get(srv.port, "/events?kind=retrace&n=10")
        assert status == 200
        evs = json.loads(body)["events"]
        assert len(evs) == 1 and evs[0]["name"] == "srvtest_a"

    def test_events_kind_and_n_combined(self, srv):
        """Satellite: direct coverage of the ?kind=&n= filter path — the
        kind filter applies BEFORE the n-truncation, n keeps the newest,
        and an unknown kind is an empty list, not an error."""
        events.default_event_log().clear()
        for i in range(6):
            events.emit("retrace", seq=i)
            events.emit("xla_compile", seq=i)
        status, body, _ = _get(srv.port, "/events?kind=retrace&n=3")
        assert status == 200
        evs = json.loads(body)["events"]
        assert [e["seq"] for e in evs] == [3, 4, 5]
        assert all(e["kind"] == "retrace" for e in evs)
        status, body, _ = _get(srv.port, "/events?n=4")
        assert len(json.loads(body)["events"]) == 4
        status, body, _ = _get(srv.port, "/events?kind=no_such_kind")
        assert status == 200 and json.loads(body)["events"] == []

    def test_events_garbled_n_is_400(self, srv):
        status, body, _ = _get(srv.port, "/events?n=lots")
        assert status == 400
        assert "n=" in json.loads(body)["error"]

    def test_unknown_path_is_404_with_directory(self, srv):
        status, body, _ = _get(srv.port, "/nope")
        assert status == 404
        assert "/metrics" in body

    def test_healthz_lifecycle_starting_healthy_stalled(self, srv,
                                                        monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_HEALTH_STALL_SEC", "0.25")
        status, body, _ = _get(srv.port, "/healthz")
        assert status == 200 and json.loads(body)["status"] == "starting"
        server_mod.note_step(3)
        status, body, _ = _get(srv.port, "/healthz")
        doc = json.loads(body)
        assert status == 200 and doc["status"] == "healthy"
        assert doc["last_step"] == 3
        time.sleep(0.4)  # steps stall -> unhealthy
        status, body, _ = _get(srv.port, "/healthz")
        doc = json.loads(body)
        assert status == 503 and doc["status"] == "stalled"
        assert doc["last_step_age_s"] > 0.25
        server_mod.note_step(4)  # progress resumes -> healthy again
        status, body, _ = _get(srv.port, "/healthz")
        assert status == 200

    def test_note_step_dedupes_and_tracks_new_runs(self):
        server_mod.note_step(5)
        with server_mod._liveness_lock:
            ts0 = server_mod._liveness["ts"]
        server_mod.note_step(5)  # second caller, same step: ignored
        with server_mod._liveness_lock:
            assert server_mod._liveness["ts"] == ts0
        server_mod.note_step(1)  # a NEW run's smaller step is followed
        assert server_mod.liveness()["last_step"] == 1

    def test_concurrent_scrape_during_registry_mutation(self, srv):
        """/metrics stays valid exposition text while a training-loop
        thread mutates the registry (satellite: server test coverage)."""
        reg = metrics_mod.default_registry()
        c = reg.counter("op_calls_total", "eager op dispatches by op name")
        h = reg.histogram("op_time_seconds", "latency")
        stop = threading.Event()
        errors = []

        def train_loop():
            i = 0
            try:
                while not stop.is_set():
                    i += 1
                    c.inc(op=f"mut_{i % 7}")
                    h.observe(0.001 * (i % 11), op=f"mut_{i % 3}")
                    reg.gauge("device_bytes_in_use",
                              "device memory currently allocated").set(
                        i, device=f"cpu:{i % 2}")
            except Exception as e:  # pragma: no cover
                errors.append(e)

        th = threading.Thread(target=train_loop)
        th.start()
        try:
            for _ in range(25):
                status, body, _ = _get(srv.port, "/metrics")
                assert status == 200
                _assert_valid_prometheus(body)
        finally:
            stop.set()
            th.join()
        assert not errors


class TestRelaunchAndCompileAttribution:
    def test_first_step_sets_relaunch_gauge(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_RESTART_NUM", "3")
        compile_watch.reset()
        server_mod.note_step(1)
        g = metrics_mod.default_registry().get(
            "relaunch_to_first_step_seconds")
        assert g is not None
        assert g.value(generation="3") > 0

    def test_forced_retrace_attributes_backend_compile(self):
        """A shape change at a jit entry point recompiles, and the compile
        lands under that entry's label in metrics + watchdog + events."""
        from paddle_tpu import jit as jit_mod
        from paddle_tpu.profiler.watchdog import get_watchdog
        compile_watch.reset()
        events.default_event_log().clear()

        @jit_mod.to_static
        def f(x):
            return x * 2.0 + 1.0

        f(paddle.to_tensor(np.ones((4, 4), np.float32)))
        f(paddle.to_tensor(np.ones((6, 4), np.float32)))  # forced retrace
        summ = compile_watch.summary()
        entries = [k for k in summ
                   if k.startswith("to_static:") and ".f#" in k or
                   k == "to_static:f#1"]
        assert entries, f"no to_static attribution in {summ}"
        entry = entries[0]
        assert summ[entry]["count"] >= 2  # first compile + the retrace
        assert summ[entry]["seconds"] > 0
        m = metrics_mod.default_registry().get("xla_compiles_total")
        assert m.value(entry=entry) >= 2
        assert get_watchdog().snapshot()["compiles"][entry]["count"] >= 2
        assert [r for r in events.recent(100, kind="xla_compile")
                if r.get("entry") == entry]

    def test_train_step_compile_attribution(self):
        from paddle_tpu import nn, optimizer
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.nn import functional as F
        compile_watch.reset()
        paddle.seed(0)
        model = nn.Linear(4, 2)
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        step = TrainStep(model, F.cross_entropy, opt)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        y = paddle.to_tensor(np.zeros((2,), np.int64))
        step(x, y)
        summ = compile_watch.summary()
        assert any(k.startswith("train_step:Linear") for k in summ), summ


class TestDeviceTimeAttribution:
    def test_spans_carry_estimate_split(self):
        from paddle_tpu.profiler.recorder import get_recorder
        rec = get_recorder()
        rec.clear()
        rec.enabled = True
        try:
            a = paddle.to_tensor(np.ones((64, 64), np.float32))
            b = paddle.to_tensor(np.ones((64, 64), np.float32))
            paddle.matmul(a, b)
        finally:
            rec.enabled = False
        spans = [s for s in rec.collect() if s.name == "matmul"]
        assert spans
        s = spans[-1]
        assert s.device_ns is not None and s.device_ns > 0
        assert s.device_src == "estimate"
        # roofline sanity: 2*64^3 flops at the CPU peak
        assert s.device_ns >= device_time.estimate_ns(2 * 64 ** 3, 0)

    def test_sync_mode_measures(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_DEVICE_TIME", "sync")
        from paddle_tpu.profiler.recorder import get_recorder
        rec = get_recorder()
        rec.clear()
        rec.enabled = True
        try:
            a = paddle.to_tensor(np.ones((32, 32), np.float32))
            paddle.nn.functional.relu(a)
        finally:
            rec.enabled = False
        spans = [s for s in rec.collect() if s.device_src == "measured"]
        assert spans and spans[-1].device_ns >= spans[-1].dur_ns

    def test_summary_report_gains_device_column(self):
        from paddle_tpu.profiler.recorder import HostSpan
        from paddle_tpu.profiler.statistic import (StatisticData,
                                                   summary_report)
        spans = [HostSpan(name="op_a", start_ns=0, end_ns=1000, tid=1,
                          device_ns=5000, device_src="estimate")]
        report = summary_report(StatisticData(spans))
        assert "Dev(ms)" in report and "estimate" in report
        # no device info -> classic table
        plain = summary_report(StatisticData(
            [HostSpan(name="op_a", start_ns=0, end_ns=1000, tid=1)]))
        assert "Dev(ms)" not in plain

    def test_chrome_export_includes_device_args(self, tmp_path):
        from paddle_tpu import profiler as prof_mod
        p = prof_mod.Profiler()
        with p:
            a = paddle.to_tensor(np.ones((16, 16), np.float32))
            paddle.matmul(a, a)
        out = p.export(str(tmp_path / "trace.json"))
        doc = json.load(open(out))
        ops = [e for e in doc["traceEvents"]
               if e.get("cat") == "Operator" and "device_us" in e["args"]]
        assert ops
        assert ops[0]["args"]["device_src"] in ("estimate", "measured")

    def test_bench_device_probe_shape(self):
        import bench
        probe = bench._device_time_probe()
        assert probe["mode"] == "estimate"
        assert probe["rows"], "probe produced no rows"
        row = probe["rows"][0]
        for key in ("op", "calls", "host_ms", "device_ms", "src"):
            assert key in row
        assert any(r["op"] == "matmul" for r in probe["rows"])


class TestMetricsDumpLive:
    def test_url_metrics_and_snapshot(self, srv):
        import metrics_dump
        metrics_mod.default_registry().counter(
            "op_calls_total", "eager op dispatches by op name").inc(
            op="live_dump")
        for path in ("/metrics", "/snapshot"):
            rc = metrics_dump.main(
                ["--url", f"http://127.0.0.1:{srv.port}{path}",
                 "--filter", "op_calls"])
            assert rc == 0

    def test_positional_url_works(self, srv, capsys):
        import metrics_dump
        rc = metrics_dump.main([f"http://127.0.0.1:{srv.port}/metrics"])
        assert rc == 0
        assert "op_calls_total" in capsys.readouterr().out

    def test_dead_endpoint_is_exit_2(self):
        import metrics_dump
        assert metrics_dump.main(
            ["--url", "http://127.0.0.1:1/metrics"]) == 2

    def test_prom_text_roundtrip_matches_snapshot(self, srv):
        import metrics_dump
        reg = metrics_mod.default_registry()
        reg.histogram("op_time_seconds", "latency").observe(
            0.003, op="rt_probe")
        _, body, _ = _get(srv.port, "/metrics")
        snap = metrics_dump.parse_prometheus_text(body)
        assert snap["op_time_seconds"]["kind"] == "histogram"
        series = [v for v in snap["op_time_seconds"]["values"]
                  if v["labels"].get("op") == "rt_probe"]
        assert series and series[0]["count"] >= 1
        assert metrics_dump.hist_quantile(series[0]["buckets"], 0.5) \
            is not None


class TestProfileEndpoint:
    """/profile?steps=N against a live loop: the acceptance path for the
    deep-profiling PR (remote zero-restart capture, 409 on concurrency,
    bounded by the hard wall-clock cap)."""

    @pytest.fixture()
    def train_loop(self):
        """A background loop dispatching real eager ops and noting steps —
        the 'running job' the endpoint profiles."""
        stop = threading.Event()

        def loop():
            a = paddle.to_tensor(np.ones((64, 64), np.float32))
            step = 0
            while not stop.is_set():
                step += 1
                paddle.nn.functional.softmax(paddle.matmul(a, a))
                server_mod.note_step(step)
                time.sleep(0.01)

        th = threading.Thread(target=loop, daemon=True)
        th.start()
        yield
        stop.set()
        th.join(10)

    def test_capture_against_running_loop(self, srv, train_loop,
                                          tmp_path, monkeypatch):
        """ISSUE acceptance: /profile?steps=2 on a running loop correlates
        >= 1 op span to device_src="xplane", the summary table shows the
        measured Dev(ms) column, and a step_diagnosis event names a
        dominant term."""
        monkeypatch.setenv("PADDLE_TPU_PROFILE_DIR", str(tmp_path))
        events.default_event_log().clear()
        status, body, _ = _get(srv.port, "/profile?steps=2", timeout=90)
        assert status == 200
        doc = json.loads(body)
        assert doc["status"] == "complete"
        assert doc["correlation"]["correlated"] >= 1, doc["correlation"]
        assert any(r["src"] == "xplane"
                   for r in doc["device_time"]["rows"])
        assert "Dev(ms)" in doc["summary_table"]
        assert "xplane" in doc["summary_table"]
        assert doc["diagnosis"]["dominant"]
        assert os.path.isdir(doc["session_dir"])
        assert doc["session_dir"].startswith(str(tmp_path))
        diags = events.recent(50, kind="step_diagnosis")
        assert diags and diags[-1]["dominant"]
        caps = events.recent(50, kind="profile_capture")
        assert caps and caps[-1]["status"] == "complete"

    def test_concurrent_capture_is_409(self, srv, train_loop, tmp_path,
                                       monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PROFILE_DIR", str(tmp_path))
        from paddle_tpu.profiler import xplane
        status, body, _ = _get(srv.port, "/profile?steps=200&wait=0")
        assert status == 202
        try:
            status2, body2, _ = _get(srv.port, "/profile?steps=2")
            assert status2 == 409
            assert "one session at a time" in json.loads(body2)["error"]
        finally:
            # force-finalize the long window so later tests see idle
            cap = xplane.default_capture()
            with cap._lock:
                if cap.state != "idle":
                    cap._finalize_locked("timeout")
            cap.wait(30)

    def test_profile_without_steps_reports_status(self, srv):
        status, body, _ = _get(srv.port, "/profile")
        assert status == 200
        assert json.loads(body)["state"] in ("idle", "armed", "recording")

    def test_profile_bad_params_are_400(self, srv):
        for q in ("steps=zero", "steps=-1", "steps=2&timeout=soon"):
            status, body, _ = _get(srv.port, f"/profile?{q}")
            assert status == 400, q


class TestMaybeStartServer:
    def test_disabled_without_env(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_METRICS_PORT", raising=False)
        assert server_mod.maybe_start_server() is None

    def test_env_opt_in_and_idempotent(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_METRICS_PORT", "0")
        try:
            s1 = server_mod.maybe_start_server()
            assert s1 is not None and s1.port
            assert server_mod.maybe_start_server() is s1
            status, body, _ = _get(s1.port, "/metrics")
            assert status == 200 and body.startswith("# HELP")
        finally:
            server_mod.stop_server()

    def test_garbled_port_warns_and_disables(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_METRICS_PORT", "not-a-port")
        with pytest.warns(UserWarning, match="not a port"):
            assert server_mod.maybe_start_server() is None

    def test_fit_autostarts_server(self, monkeypatch):
        """Model.fit with PADDLE_TPU_METRICS_PORT serves /healthz showing
        live step progress."""
        monkeypatch.setenv("PADDLE_TPU_METRICS_PORT", "0")
        from paddle_tpu import nn, optimizer
        from paddle_tpu.hapi import Model
        from paddle_tpu.nn import functional as F
        try:
            paddle.seed(0)
            model = Model(nn.Linear(4, 2))
            model.prepare(
                optimizer.SGD(learning_rate=0.1,
                              parameters=model.network.parameters()),
                F.cross_entropy)
            x = np.random.default_rng(0).normal(
                size=(8, 4)).astype("float32")
            y = np.zeros((8, 1), np.int64)
            ds = [(x[i], y[i]) for i in range(8)]
            model.fit(ds, batch_size=4, epochs=1, verbose=0)
            s = server_mod.get_server()
            assert s is not None
            status, body, _ = _get(s.port, "/healthz")
            doc = json.loads(body)
            assert status == 200 and doc["last_step"] >= 1
        finally:
            server_mod.stop_server()


class TestSupervisorRole:
    def test_supervisor_binds_port_plus_one(self, monkeypatch):
        """elastic_run's supervisor must not fight its trainer child for
        the configured port on the same host: it serves on
        PADDLE_TPU_SUPERVISOR_METRICS_PORT (default configured+1)."""
        monkeypatch.setenv("PADDLE_TPU_METRICS_PORT", "0")
        monkeypatch.delenv("PADDLE_TPU_SUPERVISOR_METRICS_PORT",
                           raising=False)
        monkeypatch.delenv("MASTER_ADDR", raising=False)
        try:
            s = server_mod.maybe_start_server(role="supervisor")
            assert s is not None
            status, body, _ = _get(s.port, "/metrics")
            assert status == 200
            # no master env -> process-local only, no crash
            assert s.aggregator is None
        finally:
            server_mod.stop_server()

    def test_supervisor_explicit_port_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_METRICS_PORT", "0")
        monkeypatch.setenv("PADDLE_TPU_SUPERVISOR_METRICS_PORT", "0")
        try:
            s = server_mod.maybe_start_server(role="supervisor")
            assert s is not None and s.port > 0
        finally:
            server_mod.stop_server()

    def test_elastic_run_serves_metrics_while_supervising(self, tmp_path):
        """tools/elastic_run.py with PADDLE_TPU_METRICS_PORT set serves
        the supervisor's /metrics (elastic_restarts_total visible) while
        the trainer runs."""
        import re as _re
        import subprocess
        port_file = tmp_path / "port.txt"
        child = ("import time; time.sleep(6)")
        env = dict(os.environ)
        env.update(PADDLE_TPU_METRICS_PORT="0",
                   PADDLE_TPU_SUPERVISOR_METRICS_PORT="0",
                   PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        env.pop("MASTER_ADDR", None)
        env.pop("MASTER_PORT", None)
        proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools", "elastic_run.py"),
             "--host-store", "--master", "127.0.0.1:0", "--np", "1",
             "--", sys.executable, "-c", child],
            env=env, stderr=subprocess.PIPE, text=True)
        try:
            # scrape the supervisor: find its bound port via its log line?
            # the server logs through logging (not stderr by default), so
            # probe /metrics by asking the OS for the listener instead:
            # simplest robust path — retry reading proc's /proc net table
            # is overkill; rely on the logging INFO line being absent and
            # instead verify the supervisor exits cleanly with the server
            # having been startable (no bind crash).
            out = proc.stderr.read()
            assert proc.wait(timeout=120) == 0
            assert "observability server unavailable" not in out
        finally:
            if proc.poll() is None:
                proc.kill()


def _post(port, path, body, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=body if isinstance(body, bytes) else body.encode(),
        method="POST", headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


class TestServingObservabilityEndpoints:
    """The serving introspection plane: /requests, /slo, and the
    shedding /generate inference endpoint (never hangs a client: 503
    when wedged/closed/absent, 429 when admission is saturated)."""

    @pytest.fixture(scope="class", autouse=True)
    def _serving_ccache(self):
        import tempfile
        from paddle_tpu.framework import flags as flags_mod
        cache = os.path.join(tempfile.gettempdir(), "pt_serving_ccache")
        os.makedirs(cache, exist_ok=True)
        flags_mod.set_flags({"FLAGS_compile_cache_dir": cache})
        yield
        flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})

    @staticmethod
    def _engine(name="obs_srv", **kw):
        from paddle_tpu.inference.serving import ServingEngine
        from paddle_tpu.models.gpt import GPT, GPTConfig
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=512, max_position_embeddings=128,
                        hidden_size=32, num_layers=2, num_heads=2,
                        dropout=0.0, attn_dropout=0.0)
        m = GPT(cfg)
        m.eval()
        kw.setdefault("max_batch", 2)
        return ServingEngine(m, max_len=48, page_size=8, name=name, **kw)

    @staticmethod
    def _no_engine(monkeypatch):
        from paddle_tpu.inference import serving as serving_mod
        from paddle_tpu.profiler import slo as slo_mod
        monkeypatch.setattr(serving_mod, "_engine_refs", [])
        monkeypatch.setattr(slo_mod, "_current", None)

    def test_requests_and_slo_404_without_engine(self, srv, monkeypatch):
        self._no_engine(monkeypatch)
        status, body, _ = _get(srv.port, "/requests")
        assert status == 404
        assert "no serving engine" in json.loads(body)["error"]
        status, body, _ = _get(srv.port, "/slo")
        assert status == 404
        assert "SLO" in json.loads(body)["error"]

    def test_requests_reports_live_engine(self, srv):
        eng = self._engine(name="obs_req")
        reqs = [eng.submit(list(range(1, 9)), max_new_tokens=3)
                for _ in range(2)]
        eng.run_until_idle()
        for r in reqs:
            r.result(timeout=10)
        status, body, _ = _get(srv.port, "/requests?n=5")
        assert status == 200
        doc = json.loads(body)
        assert doc["model"] == "obs_req"
        assert len(doc["completed"]) == 2
        phases = [s["phase"] for s in doc["completed"][0]["spans"]]
        assert "prefill" in phases and "decode" in phases
        assert doc["introspection"], "introspection ring missing"
        assert doc["queue_depth"] == 0

    def test_requests_garbled_n_is_400(self, srv):
        self._engine(name="obs_n")
        status, body, _ = _get(srv.port, "/requests?n=lots")
        assert status == 400
        assert "n=" in json.loads(body)["error"]

    def test_slo_serves_window_quantiles(self, srv):
        eng = self._engine(name="obs_slo")
        req = eng.submit(list(range(1, 9)), max_new_tokens=3)
        eng.run_until_idle()
        req.result(timeout=10)
        status, body, _ = _get(srv.port, "/slo")
        assert status == 200
        doc = json.loads(body)
        assert doc["model"] == "obs_slo" and doc["status"] == "ok"
        assert doc["signals"]["ttft"]["count"] >= 1
        assert doc["signals"]["ttft"]["p50"] <= doc["signals"]["ttft"]["p99"]

    def test_slo_falls_back_to_last_tracker_without_engine(
            self, srv, monkeypatch):
        from paddle_tpu.inference import serving as serving_mod
        from paddle_tpu.profiler.slo import SLOTracker
        monkeypatch.setattr(serving_mod, "_engine_refs", [])
        t = SLOTracker("obs_fallback", window=4, min_samples=1,
                       targets={})
        t.observe("e2e", 0.5)
        status, body, _ = _get(srv.port, "/slo")
        assert status == 200
        assert json.loads(body)["model"] == "obs_fallback"

    def test_generate_get_is_405_post_roundtrips(self, srv):
        eng = self._engine(name="obs_gen", max_batch=1)
        status, body, _ = _get(srv.port, "/generate")
        assert status == 405
        status, body = _post(srv.port, "/generate", json.dumps(
            {"prompt": list(range(1, 8)), "max_new_tokens": 3,
             "temperature": 0.0}))
        assert status == 200, body
        out = json.loads(body)
        assert out["model"] == "obs_gen"
        assert len(out["tokens"]) == 3
        assert all(isinstance(t, int) for t in out["tokens"])
        assert out["finish_reason"] in ("eos", "length", "stop")
        assert out["ttft_s"] >= 0 and out["e2e_s"] >= out["ttft_s"]
        # the HTTP request is itself traced
        tr = eng.tracer.get(out["request"])
        assert tr is not None and tr.trace_id == out["trace_id"]

    def test_generate_bad_bodies_are_400(self, srv):
        self._engine(name="obs_bad")
        status, body = _post(srv.port, "/generate", b"{not json")
        assert status == 400
        assert "not JSON" in json.loads(body)["error"]
        status, body = _post(srv.port, "/generate",
                             json.dumps({"prompt": "hello"}))
        assert status == 400
        assert "token ids" in json.loads(body)["error"]
        status, body = _post(srv.port, "/generate", json.dumps(
            {"prompt": [1, 2, 3], "temperature": -2.0}))
        assert status == 400
        assert "sampling" in json.loads(body)["error"]

    def test_generate_sheds_503_when_absent_closed_or_wedged(
            self, srv, monkeypatch):
        self._no_engine(monkeypatch)
        status, body = _post(srv.port, "/generate",
                             json.dumps({"prompt": [1, 2]}))
        assert status == 503
        assert "no serving engine" in json.loads(body)["error"]
        # a closed engine is invisible to current_engine -> same 503
        eng = self._engine(name="obs_closed")
        eng.close()
        status, body = _post(srv.port, "/generate",
                             json.dumps({"prompt": [1, 2]}))
        assert status == 503
        assert "no serving engine" in json.loads(body)["error"]
        # the close-after-lookup race guard answers "closed"
        monkeypatch.setattr(type(srv), "_engine",
                            staticmethod(lambda name=None: eng))
        code, doc = srv.generate_payload(b'{"prompt": [1, 2]}')
        assert code == 503 and "closed" in doc["error"]
        monkeypatch.undo()
        # wedged: holds work, zero decode progress past the threshold
        eng2 = self._engine(name="obs_wedged")
        eng2.submit(list(range(1, 6)), max_new_tokens=2)
        monkeypatch.setattr(eng2, "_last_progress",
                            eng2._last_progress - 3600.0)
        monkeypatch.setattr(srv, "stall_after", 1.0)
        status, body = _post(srv.port, "/generate",
                             json.dumps({"prompt": [1, 2]}))
        assert status == 503
        doc = json.loads(body)
        assert "wedged" in doc["error"] and doc["model"] == "obs_wedged"
        eng2.run_until_idle()  # drain so later tests see a clean engine

    def test_generate_sheds_429_when_queue_saturated(self, srv,
                                                     monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_SERVING_QUEUE_LIMIT", "2")
        eng = self._engine(name="obs_sat", max_batch=1)
        for _ in range(2):  # fill the admission queue, engine not running
            eng.submit(list(range(1, 6)), max_new_tokens=2)
        status, body = _post(srv.port, "/generate",
                             json.dumps({"prompt": [1, 2]}))
        assert status == 429
        doc = json.loads(body)
        assert doc["queue_depth"] >= 2 and doc["limit"] == 2
        assert "saturated" in doc["error"]
        eng.run_until_idle()  # drain

    def test_generate_routes_by_model_name(self, srv):
        a = self._engine(name="obs_route_a", max_batch=1)
        b = self._engine(name="obs_route_b", max_batch=1)
        for name in (a.name, b.name):
            status, body = _post(srv.port, "/generate", json.dumps(
                {"prompt": [1, 2, 3], "max_new_tokens": 2,
                 "model": name, "temperature": 0.0}))
            assert status == 200, body
            assert json.loads(body)["model"] == name
        # unknown name: 503 naming the missing model, never a silent
        # fallback to whichever engine happens to be newest
        status, body = _post(srv.port, "/generate", json.dumps(
            {"prompt": [1, 2], "model": "obs_route_nope"}))
        assert status == 503
        doc = json.loads(body)
        assert "no serving engine named 'obs_route_nope'" in doc["error"]
        assert doc["model"] == "obs_route_nope"

    def test_generate_suspended_is_503_with_retry_after(self, srv):
        eng = self._engine(name="obs_susp", max_batch=1)
        eng.suspend(reason="memory_pressure", retry_after_s=4.0)
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/generate",
            data=json.dumps({"prompt": [1, 2, 3],
                             "model": "obs_susp"}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                status, body, hdrs = (r.status, r.read().decode(),
                                      dict(r.headers))
        except urllib.error.HTTPError as e:
            status, body, hdrs = e.code, e.read().decode(), dict(e.headers)
        assert status == 503
        doc = json.loads(body)
        assert "suspended" in doc["error"] and "memory_pressure" in \
            doc["error"]
        assert doc["retry_after_s"] == 4.0
        assert hdrs["Retry-After"] == "4"  # degradation is machine-usable
        eng.resume_admissions()
        status, body = _post(srv.port, "/generate", json.dumps(
            {"prompt": [1, 2, 3], "max_new_tokens": 2,
             "model": "obs_susp", "temperature": 0.0}))
        assert status == 200, body

    def test_healthz_reports_serving_stall(self, srv, monkeypatch):
        eng = self._engine(name="obs_hz", max_batch=1)
        eng.submit(list(range(1, 6)), max_new_tokens=2)
        monkeypatch.setattr(eng, "_last_progress",
                            eng._last_progress - 3600.0)
        monkeypatch.setattr(srv, "stall_after", 1.0)
        status, body, _ = _get(srv.port, "/healthz")
        assert status == 503
        doc = json.loads(body)
        assert doc["status"] == "stalled"
        assert doc["stalled_by"] == "serving:obs_hz"
        s = doc["serving"]["obs_hz"]
        assert s["wedged"] is True and s["pending"] >= 1
        assert s["last_progress_age_s"] > 1.0
        assert s["suspended"] is False
        eng.run_until_idle()  # drain: healthz is clean again
        status, body, _ = _get(srv.port, "/healthz")
        assert json.loads(body).get("stalled_by") != "serving:obs_hz"
