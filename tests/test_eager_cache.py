"""Eager op-dispatch cache: jitted fwd+vjp per (op, shapes, dtypes, attrs).

Reference analog: the dygraph per-op dispatch perf tests
(`/root/reference/paddle/fluid/eager/tests/performance_tests/benchmark_eager_cpu.cc`)
— the reference's C++ tracer dispatches a ready kernel in microseconds; our
cache must put the jax eager path in the same class instead of re-tracing
`jax.vjp` twice per op call (VERDICT r4 weak #5, SURVEY §7 hard part #1).
"""
import time

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.framework import flags
from paddle_tpu.ops import _dispatch


@pytest.fixture()
def fresh_cache():
    _dispatch.clear_eager_cache()
    flags.set_flags({"FLAGS_eager_op_cache": True})
    yield
    flags.set_flags({"FLAGS_eager_op_cache": True})


def _train_steps(net, opt, x, y, steps):
    lossf = nn.CrossEntropyLoss()
    out = []
    for _ in range(steps):
        loss = lossf(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        out.append(float(loss))
    return out


def _build(seed=0):
    paddle.seed(seed)
    layers = []
    for _ in range(12):
        layers += [nn.Linear(32, 32), nn.ReLU()]
    net = nn.Sequential(*layers, nn.Linear(32, 4))
    opt = optimizer.Adam(parameters=net.parameters(), learning_rate=1e-3)
    return net, opt


class TestCorrectness:
    def test_cached_matches_uncached_losses(self, fresh_cache):
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(16, 32)).astype("float32"))
        y = paddle.to_tensor(np.arange(16) % 4)
        flags.set_flags({"FLAGS_eager_op_cache": False})
        net, opt = _build()
        opt._jit_step_broken = True  # pure eager optimizer too
        ref = _train_steps(net, opt, x, y, 6)
        flags.set_flags({"FLAGS_eager_op_cache": True})
        net, opt = _build()
        got = _train_steps(net, opt, x, y, 6)
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)

    def test_cache_hits_accumulate(self, fresh_cache):
        net, opt = _build()
        x = paddle.to_tensor(np.zeros((4, 32), "float32"))
        y = paddle.to_tensor(np.zeros((4,), "int64"))
        _train_steps(net, opt, x, y, 4)
        assert _dispatch._cache_stats["hit"] > 0
        assert len(_dispatch._eager_cache) > 0

    def test_distinct_attrs_distinct_entries(self, fresh_cache):
        """Same op code with different static attrs must not share an
        executable (softmax over different axes)."""
        from paddle_tpu.nn import functional as F
        x = paddle.to_tensor(
            np.random.default_rng(1).normal(size=(4, 5)).astype("float32"),
            stop_gradient=False)
        for _ in range(3):  # second sighting compiles, third hits
            a0 = F.softmax(x, axis=0)
            a1 = F.softmax(x, axis=1)
        np.testing.assert_allclose(np.asarray(a0.data.sum(axis=0)),
                                   np.ones(5), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(a1.data.sum(axis=1)),
                                   np.ones(4), rtol=1e-5)

    def test_dropout_stays_random_per_call(self, fresh_cache):
        """Ops that bake a fresh RNG key into their impl are uncacheable by
        construction — masks must differ across calls with the cache on."""
        from paddle_tpu.nn import functional as F
        x = paddle.to_tensor(np.ones((64, 64), "float32"))
        outs = [np.asarray(F.dropout(x, p=0.5, training=True).data)
                for _ in range(3)]
        assert not np.allclose(outs[0], outs[1])
        assert not np.allclose(outs[1], outs[2])

    def test_grads_match_uncached(self, fresh_cache):
        rng = np.random.default_rng(2)
        xv = rng.normal(size=(8, 16)).astype("float32")
        wv = rng.normal(size=(16, 4)).astype("float32")

        def run():
            x = paddle.to_tensor(xv, stop_gradient=False)
            w = paddle.to_tensor(wv, stop_gradient=False)
            out = paddle.matmul(x, w)
            loss = (out * out).sum()
            loss.backward()
            return np.asarray(x.grad.data), np.asarray(w.grad.data)

        flags.set_flags({"FLAGS_eager_op_cache": False})
        gx0, gw0 = run()
        flags.set_flags({"FLAGS_eager_op_cache": True})
        for _ in range(3):
            gx1, gw1 = run()
        np.testing.assert_allclose(gx1, gx0, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(gw1, gw0, rtol=1e-5, atol=1e-6)

    def test_create_graph_through_cached_op(self, fresh_cache):
        for _ in range(3):
            t = paddle.to_tensor(np.array([3.0], "float32"),
                                 stop_gradient=False)
            y = t * t * t
            (g,) = paddle.grad([y], [t], create_graph=True)
            (g2,) = paddle.grad([g], [t])
        np.testing.assert_allclose(np.asarray(g.data), [27.0], rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g2.data), [18.0], rtol=1e-5)


class TestNoGradPath:
    def test_eval_outputs_match_uncached(self, fresh_cache):
        net, _ = _build()
        net.eval()
        x = paddle.to_tensor(np.random.default_rng(3).normal(
            size=(4, 32)).astype("float32"))
        with paddle.no_grad():
            flags.set_flags({"FLAGS_eager_op_cache": False})
            ref = net(x).numpy()
            flags.set_flags({"FLAGS_eager_op_cache": True})
            for _ in range(3):
                got = net(x).numpy()
        np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)
        assert _dispatch._cache_stats["hit"] > 0

    def test_return_structure_stable_across_cache_warmup(self, fresh_cache):
        """A genuine 1-tuple op output must collapse to a single Tensor on
        BOTH the uncached first call and the cached hit — an op's return
        type may not change once the cache warms (ADVICE r5 #1)."""
        from paddle_tpu.framework.tensor import Tensor

        def one_tuple_impl(a):
            return (a * 2.0,)

        x = paddle.to_tensor(np.ones((4, 4), "float32"))
        types = []
        with paddle.no_grad():
            for _ in range(4):  # 1st: uncached; 2nd: compile; 3rd+: hit
                out = _dispatch.call(one_tuple_impl, (x,), name="one_tuple")
                types.append(type(out))
        assert _dispatch._cache_stats["hit"] > 0
        assert all(t is Tensor for t in types), types
        # multi-output ops keep their tuple structure in both states
        def two_tuple_impl(a):
            return (a + 1.0, a - 1.0)
        with paddle.no_grad():
            structs = [len(_dispatch.call(two_tuple_impl, (x,),
                                          name="two_tuple"))
                       for _ in range(4)]
        assert structs == [2, 2, 2, 2]

    def test_dynamic_shape_op_falls_back(self, fresh_cache):
        """masked_select's output shape is data-dependent — untraceable, so
        it must blacklist itself and stay on the eager path."""
        x = paddle.to_tensor(np.arange(6, dtype="float32"))
        m = paddle.to_tensor(np.array([1, 0, 1, 0, 1, 1], bool))
        with paddle.no_grad():
            for _ in range(3):
                out = paddle.masked_select(x, m)
        np.testing.assert_array_equal(out.numpy(), [0, 2, 4, 5])


class TestDispatchSpeed:
    def test_cached_step_much_faster(self, fresh_cache):
        """Full eager train step (fwd+bwd+Adam) >= 3x faster with the cache
        (measured ~17x on an idle box; 3x bounds CI noise)."""
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(16, 32)).astype("float32"))
        y = paddle.to_tensor(np.arange(16) % 4)

        def timed(cache_on, steps=8):
            flags.set_flags({"FLAGS_eager_op_cache": cache_on})
            _dispatch.clear_eager_cache()
            net, opt = _build()
            if not cache_on:
                opt._jit_step_broken = True
            _train_steps(net, opt, x, y, 3)  # warm: sight + compile
            t0 = time.perf_counter()
            _train_steps(net, opt, x, y, steps)
            return (time.perf_counter() - t0) / steps

        off = timed(False)
        on = timed(True)
        assert off / on >= 3.0, f"speedup only {off / on:.2f}x " \
                                f"(off {1e3 * off:.1f}ms on {1e3 * on:.1f}ms)"
