"""Fused layers / fused kernels tests.

Reference tests: `unittests/test_fused_attention_op.py`,
`test_fused_feedforward_op.py`, `test_softmax_mask_fuse_op.py`,
`test_graph_send_recv_op.py` — the fused op must match the unfused
composition numerically, and train.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.incubate import (graph_send_recv, softmax_mask_fuse,
                                 softmax_mask_fuse_upper_triangle)
from paddle_tpu.incubate.nn import (FusedFeedForward,
                                    FusedMultiHeadAttention,
                                    FusedTransformerEncoderLayer)
from paddle_tpu.ops.pallas.layer_norm import fused_layer_norm


class TestFusedLayerNorm:
    def test_matches_functional(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 6, 32)).astype(np.float32)
        g = rng.normal(size=(32,)).astype(np.float32)
        b = rng.normal(size=(32,)).astype(np.float32)
        got = np.asarray(fused_layer_norm(jnp.asarray(x), jnp.asarray(g),
                                          jnp.asarray(b), 1e-5))
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        want = (x - mean) / np.sqrt(var + 1e-5) * g + b
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_gradients_match_numeric(self):
        import jax
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(3, 16)).astype(np.float32))
        g = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))

        def f(x, g, b):
            return jnp.sum(fused_layer_norm(x, g, b, 1e-5) ** 2)

        def f_ref(x, g, b):
            mean = jnp.mean(x, -1, keepdims=True)
            var = jnp.var(x, -1, keepdims=True)
            return jnp.sum(((x - mean) / jnp.sqrt(var + 1e-5) * g + b) ** 2)

        got = jax.grad(f, argnums=(0, 1, 2))(x, g, b)
        want = jax.grad(f_ref, argnums=(0, 1, 2))(x, g, b)
        for a, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=1e-4, atol=1e-4)


class TestFusedMHA:
    def test_matches_unfused_reference(self):
        """Fused MHA (post-LN, no dropout) == manual composition."""
        paddle.seed(0)
        E, H = 32, 4
        layer = FusedMultiHeadAttention(E, H, dropout_rate=0.0,
                                        attn_dropout_rate=0.0)
        layer.eval()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 8, E)).astype(np.float32)
        out = layer(paddle.to_tensor(x)).numpy()

        qkv = x @ np.asarray(layer.qkv_weight.data) + np.asarray(layer.qkv_bias.data)
        q, k, v = np.split(qkv, 3, axis=-1)
        D = E // H
        q = q.reshape(2, 8, H, D).transpose(0, 2, 1, 3)
        k = k.reshape(2, 8, H, D).transpose(0, 2, 1, 3)
        v = v.reshape(2, 8, H, D).transpose(0, 2, 1, 3)
        s = q @ k.transpose(0, 1, 3, 2) / np.sqrt(D)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        ctx = (p @ v).transpose(0, 2, 1, 3).reshape(2, 8, E)
        proj = ctx @ np.asarray(layer.linear_weight.data) + \
            np.asarray(layer.linear_bias.data)
        resid = x + proj
        mean = resid.mean(-1, keepdims=True)
        var = resid.var(-1, keepdims=True)
        want = (resid - mean) / np.sqrt(var + 1e-5) * \
            np.asarray(layer.ln_scale.data) + np.asarray(layer.ln_bias.data)
        np.testing.assert_allclose(out, want, rtol=2e-4, atol=2e-4)

    @pytest.mark.slow
    def test_trains(self):
        paddle.seed(0)
        layer = FusedTransformerEncoderLayer(32, 4, 64, dropout_rate=0.1)
        head = nn.Linear(32, 1)
        params = layer.parameters() + head.parameters()
        opt = optimizer.Adam(learning_rate=1e-3, parameters=params)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 8, 32)).astype(np.float32)
        y = rng.normal(size=(4, 8, 1)).astype(np.float32)
        losses = []
        for _ in range(25):
            out = head(layer(paddle.to_tensor(x)))
            loss = ((out - paddle.to_tensor(y)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])

    def test_pre_layer_norm_and_causal(self):
        paddle.seed(1)
        layer = FusedMultiHeadAttention(16, 2, dropout_rate=0.0,
                                        attn_dropout_rate=0.0,
                                        normalize_before=True)
        layer.eval()
        x = np.random.default_rng(3).normal(size=(1, 6, 16)).astype(np.float32)
        out = layer(paddle.to_tensor(x), attn_mask="causal").numpy()
        assert out.shape == (1, 6, 16)
        # causal: output at position 0 must not depend on later positions
        x2 = x.copy()
        x2[:, 3:] += 100.0
        out2 = layer(paddle.to_tensor(x2), attn_mask="causal").numpy()
        np.testing.assert_allclose(out[:, 0], out2[:, 0], rtol=1e-4, atol=1e-4)


class TestFusedFFN:
    def test_matches_unfused(self):
        paddle.seed(0)
        ffn = FusedFeedForward(16, 32, dropout_rate=0.0, activation="gelu")
        ffn.eval()
        x = np.random.default_rng(0).normal(size=(2, 4, 16)).astype(np.float32)
        out = ffn(paddle.to_tensor(x)).numpy()
        import scipy.special as sp
        h = x @ np.asarray(ffn.linear1_weight.data) + np.asarray(ffn.linear1_bias.data)
        h = 0.5 * h * (1 + sp.erf(h / np.sqrt(2)))
        h = h @ np.asarray(ffn.linear2_weight.data) + np.asarray(ffn.linear2_bias.data)
        r = x + h
        mean, var = r.mean(-1, keepdims=True), r.var(-1, keepdims=True)
        want = (r - mean) / np.sqrt(var + 1e-5) * np.asarray(ffn.ln_scale.data) \
            + np.asarray(ffn.ln_bias.data)
        np.testing.assert_allclose(out, want, rtol=2e-4, atol=2e-4)


class TestSoftmaxMaskFuse:
    def test_additive_mask(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 2, 4, 4)).astype(np.float32)
        mask = np.where(rng.random((2, 1, 4, 4)) > 0.5, 0.0, -1e9).astype(np.float32)
        out = softmax_mask_fuse(paddle.to_tensor(x), paddle.to_tensor(mask)).numpy()
        z = x + mask
        e = np.exp(z - z.max(-1, keepdims=True))
        want = e / e.sum(-1, keepdims=True)
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    def test_upper_triangle(self):
        x = np.random.default_rng(0).normal(size=(1, 1, 5, 5)).astype(np.float32)
        out = softmax_mask_fuse_upper_triangle(paddle.to_tensor(x)).numpy()
        # strictly-upper entries masked out
        assert np.allclose(np.triu(out[0, 0], k=1), 0.0)
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


class TestGraphSendRecv:
    def test_pool_types(self):
        x = paddle.to_tensor(np.array([[1.0, 2], [3, 4], [5, 6]], np.float32))
        src = paddle.to_tensor(np.array([0, 1, 2, 0], np.int32))
        dst = paddle.to_tensor(np.array([1, 2, 1, 0], np.int32))
        out = graph_send_recv(x, src, dst, pool_type="sum").numpy()
        want = np.zeros((3, 2), np.float32)
        want[1] = [1, 2]; want[2] = [3, 4]; want[1] += [5, 6]; want[0] = [1, 2]
        np.testing.assert_allclose(out, want)
        out_mean = graph_send_recv(x, src, dst, pool_type="mean").numpy()
        np.testing.assert_allclose(out_mean[1], [3, 4])

    def test_gradient_flows(self):
        x = paddle.to_tensor(
            np.array([[1.0, 2], [3, 4], [5, 6]], np.float32),
            stop_gradient=False)
        src = paddle.to_tensor(np.array([0, 1], np.int32))
        dst = paddle.to_tensor(np.array([1, 1], np.int32))
        out = graph_send_recv(x, src, dst, pool_type="sum")
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   [[1, 1], [1, 1], [0, 0]])


class TestPallasFlashAttention:
    """The Pallas fwd+bwd kernels must be the path actually taken in
    training (round-1 review: the old fwd-only kernel silently fell back to
    score-materializing XLA under value_and_grad). Kernels run here in the
    Pallas interpreter on the CPU mesh — same kernel logic, no TPU needed."""

    def _arrays(self, B=2, L=512, H=2, D=64, dtype=np.float32):
        rng = np.random.default_rng(7)
        mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)).astype(dtype))
        return mk(), mk(), mk()

    @pytest.fixture(autouse=True)
    def _interpret_mode(self):
        from paddle_tpu.ops.pallas import flash_attention as fa
        old = fa._INTERPRET
        fa._INTERPRET = True
        yield
        fa._INTERPRET = old

    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_path_taken_under_value_and_grad(self, causal):
        import jax
        from paddle_tpu.ops.pallas import flash_attention as fa
        q, k, v = self._arrays()
        before = dict(fa._stats)

        def loss(q, k, v):
            return (fa.flash_attention(q, k, v, causal=causal) ** 2).sum()

        val, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        assert fa._stats["pallas"] > before["pallas"], fa._stats
        assert fa._stats["pallas_bwd"] > before["pallas_bwd"], (
            "custom_vjp backward was not traced — training would silently "
            "use the score-materializing fallback")
        # numerics vs the XLA composition
        gx = jax.grad(
            lambda q, k, v: (fa.flash_attention_xla(
                q, k, v, causal=causal) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(grads, gx):
            err = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
            assert err < 1e-4, err

    def test_seq128_and_masked_take_pallas(self):
        # round-3: the BERT/ERNIE seq-128 shape and masked attention are
        # Pallas-eligible (small single-shot kernel; VERDICT r2 missing #2)
        from paddle_tpu.ops.pallas import flash_attention as fa
        q, k, v = self._arrays(L=128)
        before = dict(fa._stats)
        fa.flash_attention(q, k, v, causal=True)
        assert fa._stats["pallas"] == before["pallas"] + 1
        mask = jnp.ones((1, 1, 128, 128), bool)
        fa.flash_attention(q, k, v, mask=mask)
        assert fa._stats["pallas"] == before["pallas"] + 2

    def test_tiny_seq_uses_xla(self):
        from paddle_tpu.ops.pallas import flash_attention as fa
        q, k, v = self._arrays(L=32)
        before = dict(fa._stats)
        fa.flash_attention(q, k, v, causal=True)
        assert fa._stats["xla"] == before["xla"] + 1

    @pytest.mark.parametrize("maskshape", [
        (2, 1, 1, 512),       # padding mask, broadcast
        (2, 2, 512, 512),     # full per-head mask
    ])
    def test_bool_masked_pallas_matches_xla_grads(self, maskshape):
        import jax
        from paddle_tpu.ops.pallas import flash_attention as fa
        rng = np.random.default_rng(11)
        q, k, v = self._arrays(L=512)
        mask = jnp.asarray(rng.random(maskshape) > 0.3)
        before = dict(fa._stats)
        g = jax.grad(lambda q, k, v: (
            fa.flash_attention(q, k, v, mask=mask) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        assert fa._stats["pallas"] > before["pallas"], fa._stats
        gx = jax.grad(lambda q, k, v: (
            fa.flash_attention_xla(q, k, v, mask=mask) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gx):
            err = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
            assert err < 2e-4, err

    def test_float_mask_stays_on_xla_and_keeps_mask_grads(self):
        """A FLOAT attn_mask may be a learned additive bias (ALiBi /
        relative-position); the fused kernel returns a zero mask cotangent,
        so dispatch must keep float masks on the XLA path where the bias
        gradient is real (review r3 finding)."""
        import jax
        from paddle_tpu.ops.pallas import flash_attention as fa
        rng = np.random.default_rng(12)
        q, k, v = self._arrays(L=128)
        bias = jnp.asarray(rng.normal(size=(1, 2, 128, 128)).astype(np.float32))
        before = dict(fa._stats)
        gm = jax.grad(lambda m: (
            fa.flash_attention(q, k, v, mask=m) ** 2).sum())(bias)
        assert fa._stats["xla"] > before["xla"], fa._stats
        assert float(jnp.abs(gm).max()) > 0, "learned bias silently frozen"

    @pytest.mark.slow  # 640-token grid walk; seq128/masked pallas paths stay fast
    def test_long_seq_walk_grid_tail_blocks(self):
        # 640 = 2.5 blocks of 256: exercises in-kernel tail masking on the
        # grid-walked path (round-2 kernel required % 256 == 0)
        import jax
        from paddle_tpu.ops.pallas import flash_attention as fa
        q, k, v = self._arrays(L=640)
        before = dict(fa._stats)
        g = jax.grad(lambda q, k, v: (
            fa.flash_attention(q, k, v, causal=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        assert fa._stats["pallas"] > before["pallas"], fa._stats
        assert not fa._use_small_path(640, 640, 2, 64)
        gx = jax.grad(lambda q, k, v: (
            fa.flash_attention_xla(q, k, v, causal=True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gx):
            err = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
            assert err < 2e-4, err

    def test_fwd_matches_xla(self):
        from paddle_tpu.ops.pallas import flash_attention as fa
        q, k, v = self._arrays(H=3)
        for causal in (False, True):
            out_p = fa.flash_attention(q, k, v, causal=causal)
            out_x = fa.flash_attention_xla(q, k, v, causal=causal)
            assert float(jnp.abs(out_p - out_x).max()) < 1e-5

    def test_additive_mask_does_not_clamp_real_logits(self):
        # ADVICE r1: the fp16 floor must clamp only the mask term
        from paddle_tpu.ops.pallas import flash_attention as fa
        rng = np.random.default_rng(3)
        q, k, v = (jnp.asarray(rng.normal(size=(1, 8, 1, 4)).astype(np.float16))
                   for _ in range(3))
        mask = jnp.full((1, 1, 8, 8), -1e9, jnp.float16)  # huge additive mask
        mask = mask.at[..., :4].set(0.0)
        out = fa.flash_attention_xla(q, k, v, mask=mask)
        ref = fa.flash_attention_xla(q[:, :, :, :], k[:, :4], v[:, :4])
        assert float(jnp.abs(out.astype(jnp.float32)
                             - ref.astype(jnp.float32)).max()) < 1e-2


class TestSDPADropoutSemantics:
    """VERDICT r2 weak #3: dropout must zero attention WEIGHTS (reference
    `nn/layer/transformer.py:412-415` drops the post-softmax probabilities
    before @V), not output features. With V columns duplicated, weight
    dropout keeps the duplicated output columns bit-identical (a dropped
    target vanishes coherently from every feature), while output-feature
    dropout zeroes elements independently and breaks the tie."""

    def _qkv(self, B=2, L=16, H=2, D=4, seed=0):
        rng = np.random.default_rng(seed)
        mk = lambda: jnp.asarray(rng.normal(size=(B, L, H, D)).astype(np.float32))
        q, k, v = mk(), mk(), mk()
        v = v.at[..., 1].set(v[..., 0])  # duplicate feature column
        return q, k, v

    def test_weight_dropout_keeps_duplicated_columns_tied(self):
        from paddle_tpu.nn import functional as F
        q, k, v = self._qkv()
        out = F.scaled_dot_product_attention(q, k, v, dropout_p=0.5,
                                             training=True)
        out = np.asarray(out)
        ref = np.asarray(F.scaled_dot_product_attention(q, k, v,
                                                        dropout_p=0.0))
        assert not np.allclose(out, ref), "dropout had no effect"
        np.testing.assert_array_equal(out[..., 0], out[..., 1])

    def test_weight_dropout_is_unbiased(self):
        # E[dropout(probs)] = probs -> mean over many seeds approaches the
        # no-dropout output
        from paddle_tpu.nn import functional as F
        from paddle_tpu.framework import random as prandom
        q, k, v = self._qkv(L=8)
        ref = np.asarray(F.scaled_dot_product_attention(q, k, v,
                                                        dropout_p=0.0))
        acc = np.zeros_like(ref)
        n = 200
        for s in range(n):
            prandom.seed(1234 + s)
            acc += np.asarray(F.scaled_dot_product_attention(
                q, k, v, dropout_p=0.3, training=True))
        err = np.abs(acc / n - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.15, err

    def test_eval_mode_ignores_dropout(self):
        from paddle_tpu.nn import functional as F
        q, k, v = self._qkv()
        out = F.scaled_dot_product_attention(q, k, v, dropout_p=0.9,
                                             training=False)
        ref = F.scaled_dot_product_attention(q, k, v, dropout_p=0.0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref))

    def test_weight_dropout_differentiable(self):
        import jax
        from paddle_tpu.ops.pallas.flash_attention import flash_attention_xla
        q, k, v = self._qkv()
        key = jax.random.PRNGKey(3)
        g = jax.grad(lambda q, k, v: float(0) + (flash_attention_xla(
            q, k, v, dropout_p=0.5, dropout_key=key) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a in g:
            assert np.isfinite(np.asarray(a)).all()
