"""Chaos drill A (slow tier): HA control-plane failover mid-incident.

Two leader-elected FleetControllers share ONE real TCPStore. The drill
kills the leader at the worst possible moments of a straggler incident
and proves the control plane stays single-writer:

* leader killed mid-debounce -> the standby takes over within one lease
  TTL and finishes the incident with exactly ONE eviction total;
* leader killed right AFTER evicting -> the successor inherits the
  replicated ledger and honors probation (no double-eviction while the
  held host's stale digest still reads slow);
* the deposed leader revives with a queued command at its old term ->
  the supervisor consumes it fenced (controller_fenced event, cursor
  advanced, no actuation) and the zombie demotes on its next tick.

fast-sibling: tests/test_leader.py
fast-sibling: tests/test_fleet_controller.py
"""
import time

import pytest

from paddle_tpu import fault
from paddle_tpu.distributed.fleet import leader as leader_mod
from paddle_tpu.distributed.fleet.controller import (ControllerCommandBus,
                                                     FleetController)
from paddle_tpu.distributed.fleet.elastic import ElasticSupervisor
from paddle_tpu.distributed.fleet.leader import LeaderLease
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.profiler import events

pytestmark = pytest.mark.slow

TTL = 0.3
WORLD = 3
HOSTS = ("trainer-0", "trainer-1", "trainer-2")


@pytest.fixture(autouse=True)
def _clean_state():
    fault.reset()
    leader_mod.reset_gate()
    events.default_event_log().clear()
    yield
    fault.reset()
    leader_mod.reset_gate()
    events.default_event_log().clear()


@pytest.fixture()
def store():
    s = TCPStore("127.0.0.1", 0, is_master=True)
    try:
        yield s
    finally:
        s.stop()


class _Agg:
    """Scripted aggregator: the controller only reads straggling(),
    straggler_factor and .last."""

    def __init__(self):
        self._straggling = []
        self.straggler_factor = 2.0
        self.last = {}

    def straggling(self):
        return list(self._straggling)


class _Fleet:
    """Drives one or both controllers through collect windows with FRESH
    digest evidence each window (the debounce only advances on a new
    (ts, step) observation)."""

    def __init__(self, store):
        self.step = 10
        self.agg = {}
        self.ctl = {}
        for cid in ("c1", "c2"):
            agg = _Agg()
            lease = LeaderLease(store, controller_id=cid, ttl=TTL)
            self.agg[cid] = agg
            self.ctl[cid] = FleetController(
                agg, ControllerCommandBus(store), WORLD,
                confirm_windows=3, readmit_after_s=9999.0, min_world=1,
                lease=lease)

    def digests(self, straggler=None):
        self.step += 1
        out = {}
        for r, host in enumerate(HOSTS):
            p50 = 0.5 if host == straggler else 0.01
            out[r] = {"host": host, "rank": r, "step": self.step,
                      "ts": time.time(), "health_status": "ok",
                      "wall_p50_s": p50, "window": 8}
        return out

    def tick(self, cids, straggler=None):
        d = self.digests(straggler)
        for cid in cids:
            agg = self.agg[cid]
            agg._straggling = [straggler] if straggler else []
            agg.last = d
            self.ctl[cid].on_collect(d)

    def evictions(self):
        bus = self.ctl["c1"].bus
        return [c for c in bus.poll(0) if c.get("action") == "evict"]


def _spin_leader(fleet, cid, straggler=None, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        fleet.tick([cid], straggler=straggler)
        if fleet.ctl[cid].is_leader():
            return
        time.sleep(0.02)
    raise AssertionError(f"{cid} never took leadership")


class TestFailoverChaos:
    def test_leader_killed_mid_debounce_single_eviction(self, store):
        """c1 dies two windows into a three-window eviction debounce;
        c2 takes over within one TTL and the fleet still sees exactly
        one eviction for the whole incident."""
        fleet = _Fleet(store)
        fleet.tick(["c1", "c2"])              # c1 bootstraps, c2 standby
        assert fleet.ctl["c1"].is_leader()
        assert not fleet.ctl["c2"].is_leader()

        # incident: trainer-1 goes slow; two of three confirm windows
        for _ in range(2):
            fleet.tick(["c1", "c2"], straggler="trainer-1")
            time.sleep(0.02)
        assert fleet.evictions() == []        # debounce still holding

        t0 = time.monotonic()                 # c1 dies: stops ticking
        _spin_leader(fleet, "c2", straggler="trainer-1")
        took = time.monotonic() - t0
        assert took < 2 * TTL + 0.5           # one TTL + poll slack

        # the successor finishes the incident on its OWN streak
        deadline = time.monotonic() + 5.0
        while not fleet.evictions() and time.monotonic() < deadline:
            fleet.tick(["c2"], straggler="trainer-1")
            time.sleep(0.02)
        evs = fleet.evictions()
        assert len(evs) == 1
        assert evs[0]["host"] == "trainer-1"
        assert evs[0]["term"] == fleet.ctl["c2"].lease.term

        # more straggling windows (stale digest reads slow while held):
        # hysteresis + probation keep it at one eviction
        for _ in range(4):
            fleet.tick(["c2"], straggler="trainer-1")
            time.sleep(0.02)
        assert len(fleet.evictions()) == 1

    def test_takeover_inherits_probation_no_double_evict(self, store):
        """c1 evicts trainer-1 (ledger replicated in the same tick) and
        dies; c2 takes over while the host still reads slow and must NOT
        evict it again — the inherited ledger holds the probation."""
        fleet = _Fleet(store)
        fleet.tick(["c1", "c2"])
        deadline = time.monotonic() + 5.0
        while not fleet.evictions() and time.monotonic() < deadline:
            fleet.tick(["c1", "c2"], straggler="trainer-1")
            time.sleep(0.02)
        assert len(fleet.evictions()) == 1    # c1 completed the eviction

        # c1 dies; c2 takes over and keeps seeing the stale-slow digest
        _spin_leader(fleet, "c2", straggler="trainer-1")
        for _ in range(6):                    # >> confirm_windows
            fleet.tick(["c2"], straggler="trainer-1")
            time.sleep(0.02)
        assert len(fleet.evictions()) == 1    # probation honored
        with fleet.ctl["c2"]._lock:
            assert "trainer-1" in fleet.ctl["c2"]._evicted

        # exactly one takeover event, attributed to c2
        tk = events.recent(kind="controller_takeover")
        assert tk[-1]["leader"] == "c2"
        assert tk[-1]["reason"] == "lease_expired"

    def test_revived_leader_queued_command_is_fenced(self, store):
        """The deposed leader wakes up and flushes a queued actuation at
        its old term: the supervisor must consume it WITHOUT acting, and
        the zombie must demote itself on its next election tick."""
        fleet = _Fleet(store)
        fleet.tick(["c1", "c2"])
        assert fleet.ctl["c1"].is_leader()
        old_term = fleet.ctl["c1"].lease.term

        _spin_leader(fleet, "c2")             # c1 pauses; c2 takes over
        assert fleet.ctl["c2"].lease.term > old_term

        leader_mod.reset_gate()               # supervisor = own process
        bus = ControllerCommandBus(store)
        sup = ElasticSupervisor(max_restarts=0, commands=bus,
                                self_member="trainer-sup")
        assert sup._next_command() is None    # anchors the ledger cursor

        # the zombie's queued eviction finally reaches the bus
        bus.publish({"action": "evict", "host": "trainer-2",
                     "policy": "straggler", "np": 2, "term": old_term})
        assert sup._next_command() is None    # fenced: never surfaced
        ev = events.recent(kind="controller_fenced")
        assert ev and ev[-1]["term"] == old_term
        assert ev[-1]["action"] == "evict"
        assert sup._next_command() is None    # consumed, not re-delivered

        # a current-term command still actuates (the fence is per-term,
        # not a lockout)
        bus.publish({"action": "evict", "host": "trainer-2",
                     "policy": "straggler", "np": 2,
                     "term": fleet.ctl["c2"].lease.term})
        cmd = sup._next_command()
        assert cmd is not None and cmd["host"] == "trainer-2"

        # the revived c1 demotes on its next tick (read-before-renew)
        deadline = time.monotonic() + 5.0
        res = None
        while time.monotonic() < deadline:
            res = fleet.ctl["c1"].lease.tick()
            if res == "demoted":
                break
            time.sleep(0.02)
        assert res == "demoted"
        assert not fleet.ctl["c1"].is_leader()
