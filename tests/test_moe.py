"""MoE expert parallelism (incubate.distributed.models.moe).

Reference test style: `unittests/test_moe_api.py` / collective
global_scatter tests assert routing correctness; here we check the dense
dispatch/combine math against a straightforward per-token reference, grads
to every expert, and ep-sharded execution on the 8-device mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn, optimizer
from paddle_tpu.nn import functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.topology import HybridCommunicateGroup
from paddle_tpu.incubate.distributed.models.moe import (
    ClipGradForMOEByGlobalNorm, Expert, GShardGate, MoELayer, NaiveGate,
    SwitchGate, top1_gate, top2_gate)


@pytest.fixture(autouse=True)
def _clean():
    yield
    dist.set_hybrid_communicate_group(None)


def _moe(E=4, d=8, hidden=16, gate="gshard", cf=4.0):
    paddle.seed(0)
    experts = [Expert(d, hidden) for _ in range(E)]
    return MoELayer(d_model=d, experts=experts, gate=gate,
                    capacity_factor=cf)


class TestGateMath:
    def test_top1_routes_every_token_with_capacity(self):
        rs = np.random.RandomState(0)
        logits = jnp.asarray(rs.randn(32, 4).astype(np.float32))
        combine, dispatch, aux = top1_gate(logits, capacity=32)
        # every token got exactly one slot with its softmax prob
        probs = jax.nn.softmax(logits, axis=-1)
        np.testing.assert_allclose(
            np.asarray(jnp.sum(combine, axis=(1, 2))),
            np.asarray(jnp.max(probs, axis=-1)), rtol=1e-6)
        # slots within an expert are distinct
        per_slot = np.asarray(jnp.sum(dispatch, axis=0))  # [E, C]
        assert per_slot.max() <= 1.0
        assert float(aux) > 0

    def test_top2_weights_normalized(self):
        rs = np.random.RandomState(1)
        logits = jnp.asarray(rs.randn(16, 4).astype(np.float32))
        combine, dispatch, aux = top2_gate(logits, capacity=16)
        tot = np.asarray(jnp.sum(combine, axis=(1, 2)))
        np.testing.assert_allclose(tot, np.ones(16), rtol=1e-5)

    def test_capacity_drops_overflow(self):
        # all tokens prefer expert 0; capacity 4 keeps only 4
        logits = jnp.tile(jnp.asarray([[5.0, 0, 0, 0]]), (32, 1))
        combine, dispatch, aux = top1_gate(logits, capacity=4)
        kept = float(jnp.sum(dispatch))
        assert kept == 4.0


class TestMoELayer:
    def test_single_expert_identity_routing(self):
        moe = _moe(E=1, gate="naive")
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(2, 6, 8).astype(np.float32))
        out = moe(x)
        ref = moe.experts[0](x)
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.asarray(ref.data), rtol=2e-5,
                                   atol=2e-5)

    def test_single_expert_gshard_keeps_full_weight(self):
        """Degenerate E=1 must not halve the output (second choice == first
        is dropped before normalization)."""
        moe = _moe(E=1, gate="gshard", cf=8.0)
        rs = np.random.RandomState(7)
        x = paddle.to_tensor(rs.randn(2, 6, 8).astype(np.float32))
        out = moe(x)
        ref = moe.experts[0](x)
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.asarray(ref.data), rtol=2e-5,
                                   atol=2e-5)

    def test_gshard_matches_dense_top2_reference(self):
        moe = _moe(E=4, gate="gshard", cf=8.0)  # capacity ample: no drops
        rs = np.random.RandomState(2)
        x = paddle.to_tensor(rs.randn(3, 5, 8).astype(np.float32))
        out = moe(x)
        # dense reference: run every expert on every token, mix by top-2
        xt = x.data.reshape(15, 8)
        logits = xt @ moe.gate.gate_proj.weight.data
        probs = jax.nn.softmax(logits, axis=-1)
        i1 = jnp.argmax(probs, axis=-1)
        m1 = jax.nn.one_hot(i1, 4)
        g1 = jnp.sum(probs * m1, -1)
        p2 = jnp.where(m1 > 0, -1e30, logits)
        i2 = jnp.argmax(p2, axis=-1)
        g2 = jnp.sum(probs * jax.nn.one_hot(i2, 4), -1)
        d = g1 + g2
        all_out = jnp.stack([np.asarray(moe.experts[e](
            paddle.to_tensor(xt)).data) for e in range(4)])  # [E, N, D]
        ref = (g1 / d)[:, None] * jnp.take_along_axis(
            all_out, i1[None, :, None], 0)[0] + \
            (g2 / d)[:, None] * jnp.take_along_axis(
            all_out, i2[None, :, None], 0)[0]
        np.testing.assert_allclose(np.asarray(out.data).reshape(15, 8),
                                   np.asarray(ref), rtol=3e-5, atol=3e-5)

    def test_eager_grads_reach_experts_and_gate(self):
        moe = _moe(E=4, gate="switch", cf=8.0)
        rs = np.random.RandomState(3)
        x = paddle.to_tensor(rs.randn(4, 4, 8).astype(np.float32))
        out = moe(x)
        loss = F.mse_loss(out, paddle.zeros_like(out)) + moe.aux_loss
        loss.backward()
        grads = {k: p.grad for k, p in moe.named_parameters()}
        assert grads["gate.gate_proj.weight"] is not None
        touched = [k for k, g in grads.items()
                   if "experts." in k and g is not None
                   and float(jnp.abs(g.data).sum()) > 0]
        assert len(touched) >= 4, touched  # several experts got gradient

    def test_ep_sharded_matches_unsharded(self):
        moe = _moe(E=8, gate="gshard", cf=8.0)
        rs = np.random.RandomState(4)
        x = paddle.to_tensor(rs.randn(4, 4, 8).astype(np.float32))
        ref = np.asarray(moe(x).data)
        fleet.init(is_collective=True, strategy=DistributedStrategy())
        hcg = HybridCommunicateGroup(dims={"ep": 8})
        dist.set_hybrid_communicate_group(hcg)
        got = np.asarray(moe(x).data)
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)

    @pytest.mark.slow  # heavy e2e; full-suite only (tier-1 budget)
    def test_moe_transformer_trains(self):
        """GPT-style block with MoE FFN: loss decreases (compiled engine)."""
        d, E = 16, 4

        class MoEBlock(nn.Layer):
            def __init__(self):
                super().__init__()
                self.ln = nn.LayerNorm(d)
                self.moe = MoELayer(
                    d_model=d, experts=[Expert(d, 32) for _ in range(E)],
                    gate="gshard", capacity_factor=4.0)
                self.head = nn.Linear(d, 10)

            def forward(self, x):
                h = x + self.moe(self.ln(x))
                return self.head(h.mean(axis=1))

        paddle.seed(0)
        model = MoEBlock()
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())
        rs = np.random.RandomState(0)
        X = rs.randn(16, 6, d).astype(np.float32)
        Y = rs.randint(0, 10, (16,)).astype(np.int32)
        losses = []
        for _ in range(8):
            x, y = paddle.to_tensor(X), paddle.to_tensor(Y)
            loss = F.cross_entropy(model(x), y) + 0.01 * model.moe.aux_loss
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    def test_moe_grad_clip(self):
        moe = _moe(E=2, gate="switch", cf=8.0)
        rs = np.random.RandomState(5)
        x = paddle.to_tensor(rs.randn(2, 3, 8).astype(np.float32))
        loss = F.mse_loss(moe(x), paddle.zeros([2, 3, 8]))
        loss.backward()
        clip = ClipGradForMOEByGlobalNorm(clip_norm=1e-6)
        pg = [(p, p.grad) for _, p in moe.named_parameters()
              if p.grad is not None]
        clipped = clip(pg)
        total = sum(float(jnp.sum(jnp.square(g.data))) for _, g in clipped)
        assert total <= 2e-12
