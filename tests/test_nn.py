"""nn layers + functional tests (reference analog: unittests/test_layers.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.tensor import Tensor

from op_test import check_grad


def t(x):
    return Tensor(np.asarray(x, np.float32))


class TestLayers:
    def test_linear(self):
        l = nn.Linear(4, 3)
        x = t(np.random.randn(2, 4))
        out = l(x)
        assert out.shape == [2, 3]
        ref = x.numpy() @ l.weight.numpy() + l.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)

    def test_conv2d_vs_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.randn(2, 3, 8, 8).astype(np.float32)
        conv = nn.Conv2D(3, 5, 3, stride=2, padding=1)
        out = conv(t(x))
        tref = torch.nn.functional.conv2d(
            torch.tensor(x), torch.tensor(np.asarray(conv.weight.numpy())),
            torch.tensor(np.asarray(conv.bias.numpy())), stride=2, padding=1)
        np.testing.assert_allclose(out.numpy(), tref.numpy(), atol=1e-4)

    def test_conv_grad(self):
        x = np.random.randn(1, 2, 5, 5).astype(np.float32)
        w = np.random.randn(3, 2, 3, 3).astype(np.float32)
        check_grad(lambda a, b: F.conv2d(a, b, padding=1), [x, w], wrt=1,
                   atol=2e-2, rtol=2e-2)

    def test_conv2d_transpose_vs_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.randn(2, 4, 5, 5).astype(np.float32)
        w = np.random.randn(4, 3, 3, 3).astype(np.float32)
        out = F.conv2d_transpose(t(x), t(w), stride=2, padding=1)
        tref = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), stride=2, padding=1)
        np.testing.assert_allclose(out.numpy(), tref.numpy(), atol=1e-4)

    def test_pools_vs_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.randn(2, 3, 8, 8).astype(np.float32)
        out = F.max_pool2d(t(x), 2, 2)
        ref = torch.nn.functional.max_pool2d(torch.tensor(x), 2, 2)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-6)
        out = F.avg_pool2d(t(x), 3, 2, 1)
        ref = torch.nn.functional.avg_pool2d(torch.tensor(x), 3, 2, 1,
                                             count_include_pad=False)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)
        out = F.adaptive_avg_pool2d(t(x), 2)
        ref = torch.nn.functional.adaptive_avg_pool2d(torch.tensor(x), 2)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-5)

    def test_batchnorm(self):
        bn = nn.BatchNorm2D(3)
        x = t(np.random.randn(4, 3, 5, 5) * 2 + 1)
        bn.train()
        out = bn(x)
        m = out.numpy().mean(axis=(0, 2, 3))
        np.testing.assert_allclose(m, np.zeros(3), atol=1e-5)
        # running stats moved toward batch stats
        assert not np.allclose(bn._mean.numpy(), 0.0)
        bn.eval()
        out2 = bn(x)
        assert out2.shape == out.shape

    def test_layernorm_vs_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.randn(2, 5, 8).astype(np.float32)
        ln = nn.LayerNorm(8)
        out = ln(t(x))
        tln = torch.nn.LayerNorm(8)
        with torch.no_grad():
            tln.weight.copy_(torch.tensor(np.asarray(ln.weight.numpy())))
            tln.bias.copy_(torch.tensor(np.asarray(ln.bias.numpy())))
        np.testing.assert_allclose(out.numpy(), tln(torch.tensor(x)).detach().numpy(),
                                   atol=1e-5)

    def test_embedding(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        idx = Tensor(np.array([[1, 0, 3]], np.int64))
        out = emb(idx)
        assert out.shape == [1, 3, 4]
        np.testing.assert_allclose(out.numpy()[0, 1], np.zeros(4))

    def test_dropout(self):
        d = nn.Dropout(0.5)
        x = t(np.ones((100, 100)))
        d.train()
        out = d(x)
        frac = (out.numpy() == 0).mean()
        assert 0.4 < frac < 0.6
        # upscale keeps expectation
        assert abs(out.numpy().mean() - 1.0) < 0.05
        d.eval()
        np.testing.assert_allclose(d(x).numpy(), x.numpy())

    def test_sequential_state_dict(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        sd = net.state_dict()
        assert "0.weight" in sd and "2.bias" in sd
        net2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        missing, unexpected = net2.set_state_dict(sd)
        assert not missing and not unexpected
        np.testing.assert_allclose(net2[0].weight.numpy(), net[0].weight.numpy())

    def test_hooks(self):
        l = nn.Linear(2, 2)
        calls = []
        h = l.register_forward_post_hook(lambda lay, inp, out: calls.append(1))
        l(t(np.ones((1, 2))))
        assert calls == [1]
        h.remove()
        l(t(np.ones((1, 2))))
        assert calls == [1]


class TestFunctional:
    def test_softmax_ce(self):
        torch = pytest.importorskip("torch")
        logits = np.random.randn(4, 7).astype(np.float32)
        labels = np.random.randint(0, 7, (4,))
        loss = F.cross_entropy(t(logits), Tensor(labels))
        ref = torch.nn.functional.cross_entropy(
            torch.tensor(logits), torch.tensor(labels))
        np.testing.assert_allclose(loss.numpy(), ref.numpy(), rtol=1e-5)

    def test_ce_soft_label_smoothing(self):
        logits = np.random.randn(4, 7).astype(np.float32)
        labels = np.random.randint(0, 7, (4,))
        l1 = F.cross_entropy(t(logits), Tensor(labels), label_smoothing=0.1)
        soft = np.eye(7, dtype=np.float32)[labels] * 0.9 + 0.1 / 7
        l2 = F.cross_entropy(t(logits), Tensor(soft), soft_label=True)
        np.testing.assert_allclose(l1.numpy(), l2.numpy(), rtol=1e-5)

    def test_ce_ignore_index(self):
        logits = np.random.randn(4, 7).astype(np.float32)
        labels = np.array([1, 2, 0, 0])
        l = F.cross_entropy(t(logits), Tensor(labels), ignore_index=0)
        lp = -np.log(np.exp(logits) / np.exp(logits).sum(-1, keepdims=True))
        ref = (lp[0, 1] + lp[1, 2]) / 2
        np.testing.assert_allclose(l.numpy(), ref, rtol=1e-5)

    def test_bce(self):
        torch = pytest.importorskip("torch")
        z = np.random.randn(8).astype(np.float32)
        y = np.random.randint(0, 2, 8).astype(np.float32)
        l = F.binary_cross_entropy_with_logits(t(z), t(y))
        ref = torch.nn.functional.binary_cross_entropy_with_logits(
            torch.tensor(z), torch.tensor(y))
        np.testing.assert_allclose(l.numpy(), ref.numpy(), rtol=1e-5)

    def test_activations_vs_torch(self):
        torch = pytest.importorskip("torch")
        x = np.random.randn(5, 5).astype(np.float32)
        for ours, theirs in [
            (F.relu, torch.nn.functional.relu),
            (F.gelu, lambda v: torch.nn.functional.gelu(v)),
            (F.silu, torch.nn.functional.silu),
            (F.softplus, torch.nn.functional.softplus),
            (F.elu, torch.nn.functional.elu),
            (F.hardswish, torch.nn.functional.hardswish),
        ]:
            np.testing.assert_allclose(ours(t(x)).numpy(),
                                       theirs(torch.tensor(x)).numpy(),
                                       atol=1e-5, err_msg=str(ours))

    def test_attention_causal(self):
        q = np.random.randn(2, 6, 2, 8).astype(np.float32)
        out = F.scaled_dot_product_attention(t(q), t(q), t(q), is_causal=True)
        assert out.shape == [2, 6, 2, 8]
        # first position attends only to itself -> equals v[0]
        np.testing.assert_allclose(out.numpy()[:, 0], q[:, 0], atol=1e-5)

    def test_interpolate(self):
        x = np.random.randn(1, 2, 4, 4).astype(np.float32)
        out = F.interpolate(t(x), scale_factor=2, mode="nearest")
        assert out.shape == [1, 2, 8, 8]

    def test_grad_clip(self):
        from paddle_tpu.nn import ClipGradByGlobalNorm
        from paddle_tpu.framework.param import Parameter
        p = Parameter(np.ones(4, np.float32))
        g = Tensor(np.full(4, 10.0, np.float32))
        clip = ClipGradByGlobalNorm(1.0)
        [(_, gc)] = clip([(p, g)])
        np.testing.assert_allclose(np.linalg.norm(gc.numpy()), 1.0, rtol=1e-5)


class TestTransformer:
    def test_encoder_shapes(self):
        layer = nn.TransformerEncoderLayer(d_model=16, nhead=4, dim_feedforward=32)
        enc = nn.TransformerEncoder(layer, 2)
        x = t(np.random.randn(2, 5, 16))
        out = enc(x)
        assert out.shape == [2, 5, 16]

    def test_mha_self_attention(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = t(np.random.randn(2, 5, 16))
        out = mha(x)
        assert out.shape == [2, 5, 16]

    def test_decoder_with_cache(self):
        layer = nn.TransformerDecoderLayer(d_model=16, nhead=4, dim_feedforward=32)
        dec = nn.TransformerDecoder(layer, 2)
        memory = t(np.random.randn(2, 7, 16))
        tgt = t(np.random.randn(2, 1, 16))
        cache = dec.gen_cache(memory)
        out, new_cache = dec(tgt, memory, cache=cache)
        assert out.shape == [2, 1, 16]
        assert new_cache[0][0].k.shape[1] == 1
