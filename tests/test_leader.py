"""Leader election + term fencing unit tests (fast tier).

Covers the HA control plane's building blocks over a real TCPStore:
lease bootstrap/renew/takeover, acquire-race resolution, read-before-
renew demotion, voluntary release, the in-process fencing gate
(note_term/check_term), lease_term (record term, not the raw counter),
the standby registry, the elastic command-bus fence, and ledger
replication/inheritance across a controller handoff. The full two-
controller chaos drill (leader killed mid-incident) lives in
tests/test_controller_failover_e2e.py (slow tier).
"""
import json
import time

import pytest

from paddle_tpu import fault
from paddle_tpu.distributed.fleet import leader as leader_mod
from paddle_tpu.distributed.fleet.leader import (ControllerFencedError,
                                                 LeaderLease, LEASE_KEY,
                                                 TERM_KEY, check_term,
                                                 lease_term, note_term)
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.profiler import events


@pytest.fixture(autouse=True)
def _clean_state():
    fault.reset()
    leader_mod.reset_gate()
    events.default_event_log().clear()
    yield
    fault.reset()
    leader_mod.reset_gate()
    events.default_event_log().clear()


@pytest.fixture()
def store():
    s = TCPStore("127.0.0.1", 0, is_master=True)
    try:
        yield s
    finally:
        s.stop()


def _spin(lease, until, timeout=5.0, sleep=0.01):
    deadline = time.monotonic() + timeout
    res = None
    while time.monotonic() < deadline:
        res = lease.tick()
        if until(res):
            return res
        time.sleep(sleep)
    raise AssertionError("condition not reached within timeout")


class TestLease:
    def test_bootstrap_acquires_on_first_tick(self, store):
        lease = LeaderLease(store, controller_id="c0", ttl=1.0)
        assert lease.tick() == "acquired"
        assert lease.is_leader and lease.term >= 1
        ev = events.recent(kind="controller_takeover")
        assert ev and ev[-1]["reason"] == "bootstrap"
        assert ev[-1]["leader"] == "c0"

    def test_standby_observes_while_leader_renews(self, store):
        a = LeaderLease(store, controller_id="a", ttl=0.3)
        b = LeaderLease(store, controller_id="b", ttl=0.3)
        assert a.tick() == "acquired"
        for _ in range(12):           # > one TTL of live renewing
            a.tick()
            assert b.tick() is None   # value keeps changing: no takeover
            time.sleep(0.05)
        assert a.is_leader and not b.is_leader
        assert b.leader_id() == "a"

    def test_standby_takes_over_within_one_ttl_of_silence(self, store):
        a = LeaderLease(store, controller_id="a", ttl=0.3)
        b = LeaderLease(store, controller_id="b", ttl=0.3)
        assert a.tick() == "acquired"
        b.tick()                      # observe the live lease once
        t0 = time.monotonic()         # a dies: stops ticking entirely
        _spin(b, lambda r: r == "acquired", timeout=5.0)
        took = time.monotonic() - t0
        assert b.is_leader and b.term > a.term
        # "within one lease TTL" plus one poll of slack
        assert took < 2 * 0.3 + 0.5
        ev = events.recent(kind="controller_takeover")
        assert ev[-1]["reason"] == "lease_expired"

    def test_release_hands_off_without_waiting_out_ttl(self, store):
        a = LeaderLease(store, controller_id="a", ttl=30.0)
        b = LeaderLease(store, controller_id="b", ttl=30.0)
        assert a.tick() == "acquired"
        b.tick()
        a.release()
        assert not a.is_leader
        # no TTL wait: the missing key acquires on b's next tick
        assert b.tick() == "acquired"
        assert b.term > a.term

    def test_deposed_leader_demotes_on_higher_term(self, store):
        a = LeaderLease(store, controller_id="a", ttl=0.3)
        assert a.tick() == "acquired"
        # a pauses (GC stall / SIGSTOP); b takes over meanwhile
        b = LeaderLease(store, controller_id="b", ttl=0.3)
        b.tick()
        time.sleep(0.4)
        _spin(b, lambda r: r == "acquired", timeout=5.0)
        # a resumes: its next renew read sees the higher term and demotes
        time.sleep(0.15)              # past a's renew cadence (ttl/3)
        assert _spin(a, lambda r: r == "demoted", timeout=5.0) == "demoted"
        assert not a.is_leader and b.is_leader

    def test_failed_renews_self_fence_after_one_ttl(self, store):
        a = LeaderLease(store, controller_id="a", ttl=0.3)
        assert a.tick() == "acquired"
        fault.configure("controller.lease", times=1000, kind="oserror")
        time.sleep(0.35)
        _spin(a, lambda r: r == "demoted", timeout=5.0)
        assert not a.is_leader

    def test_acquire_race_has_one_winner(self, store):
        """Two standbys racing an expired lease: last-writer-wins via the
        re-read — exactly one ends up leader, the loser re-arms."""
        a = LeaderLease(store, controller_id="a", ttl=0.2)
        b = LeaderLease(store, controller_id="b", ttl=0.2)
        c = LeaderLease(store, controller_id="c", ttl=0.2)
        assert a.tick() == "acquired"
        b.tick(), c.tick()
        time.sleep(0.3)               # a dead: lease frozen past TTL
        results = [b.tick(), c.tick()]
        assert results.count("acquired") == 1
        assert [b.is_leader, c.is_leader].count(True) == 1

    def test_terms_are_monotonic_across_takeovers(self, store):
        terms = []
        prev_term = 0
        for cid in ("a", "b", "c"):
            lease = LeaderLease(store, controller_id=cid, ttl=0.2)
            lease.term = prev_term    # fresh object, shared store state
            _spin(lease, lambda r: r == "acquired", timeout=5.0)
            terms.append(lease.term)
            prev_term = lease.term
            lease._leader = False     # "kill" it: stop renewing
            time.sleep(0.25)
        assert terms == sorted(terms) and len(set(terms)) == 3


class TestFencingGate:
    def test_none_term_always_passes(self):
        note_term(7)
        check_term(None, policy="serving_restart")  # operator action

    def test_stale_term_raises_and_meters(self, store):
        note_term(5)
        with pytest.raises(ControllerFencedError):
            check_term(4, policy="serving_shed")
        ev = events.recent(kind="controller_fenced")
        assert ev and ev[-1]["policy"] == "serving_shed"
        assert ev[-1]["term"] == 4 and ev[-1]["current_term"] == 5

    def test_current_and_future_terms_pass(self):
        note_term(5)
        check_term(5)
        check_term(6)                 # a renewal we haven't observed yet

    def test_gate_is_monotonic(self):
        note_term(9)
        note_term(3)                  # lower observation cannot regress it
        assert leader_mod.term_high_water() == 9

    def test_lease_term_reads_record_not_counter(self, store):
        lease = LeaderLease(store, controller_id="x", ttl=1.0)
        assert lease.tick() == "acquired"
        held = lease.term
        # a failed acquirer bumps the counter without holding the key —
        # fencing against the counter would depose the real leader
        store.add(TERM_KEY, 1)
        assert lease_term(store) == held
        assert lease_term(store) < int(store.add(TERM_KEY, 0))

    def test_lease_term_none_without_lease(self, store):
        assert lease_term(store) is None


class TestStandbyRegistry:
    def test_counts_exclude_leader(self, store):
        a = LeaderLease(store, controller_id="a", ttl=0.5)
        b = LeaderLease(store, controller_id="b", ttl=0.5)
        c = LeaderLease(store, controller_id="c", ttl=0.5)
        assert a.tick() == "acquired"
        for _ in range(3):            # let everyone beat + observe
            b.tick(), c.tick(), a.tick()
            time.sleep(0.02)
        assert a.standby_count() == 2
        st = a.status()
        assert st["is_leader"] and st["leader"] == "a"
        assert st["standbys"] == 2 and st["term"] == a.term

    def test_status_shape_for_observability(self, store):
        lease = LeaderLease(store, controller_id="s", ttl=1.0,
                            expected_standbys=2)
        lease.tick()
        st = lease.status()
        for key in ("id", "is_leader", "leader", "term", "lease_ttl_s",
                    "lease_age_s", "standbys", "expected_standbys",
                    "takeovers"):
            assert key in st
        assert st["expected_standbys"] == 2
        assert st["lease_age_s"] is not None


class TestElasticCommandFence:
    def _supervisor(self, store):
        from paddle_tpu.distributed.fleet.controller import (
            ControllerCommandBus)
        from paddle_tpu.distributed.fleet.elastic import ElasticSupervisor
        bus = ControllerCommandBus(store)
        sup = ElasticSupervisor(max_restarts=0, commands=bus,
                                self_member="trainer-sup")
        assert sup._next_command() is None  # anchors the ledger cursor
        return bus, sup

    def test_stale_term_command_is_consumed_not_actuated(self, store):
        lease = LeaderLease(store, controller_id="ctl", ttl=1.0)
        assert lease.tick() == "acquired"
        bus, sup = self._supervisor(store)
        bus.publish({"action": "evict", "host": "h1", "policy": "straggler",
                     "term": lease.term - 1})
        cmd = sup._next_command()
        assert cmd is None            # fenced: dropped, never surfaced
        ev = events.recent(kind="controller_fenced")
        assert ev and ev[-1]["action"] == "evict"
        assert ev[-1]["term"] == lease.term - 1
        # the cursor advanced: the fenced command is not re-delivered
        assert sup._next_command() is None

    def test_current_term_command_passes_and_raises_gate(self, store):
        lease = LeaderLease(store, controller_id="ctl", ttl=1.0)
        assert lease.tick() == "acquired"
        leader_mod.reset_gate()       # simulate a separate process
        bus, sup = self._supervisor(store)
        bus.publish({"action": "evict", "host": "h1", "policy": "straggler",
                     "term": lease.term})
        cmd = sup._next_command()
        assert cmd is not None and cmd["host"] == "h1"
        assert leader_mod.term_high_water() >= lease.term

    def test_untermed_command_passes(self, store):
        """Back-compat: commands from a pre-HA controller (or an operator
        tool) carry no term and must keep working."""
        bus, sup = self._supervisor(store)
        bus.publish({"action": "evict", "host": "h2", "policy": "manual"})
        cmd = sup._next_command()
        assert cmd is not None and cmd["host"] == "h2"


class TestLedgerReplication:
    def _controller(self, store, agg, cid):
        from paddle_tpu.distributed.fleet.controller import FleetController
        lease = LeaderLease(store, controller_id=cid, ttl=0.3)
        return FleetController(agg, None, 2, lease=lease)

    def test_new_leader_inherits_decision_state(self, store):
        """The successor must see the predecessor's cooldowns/evictions —
        NOT double-evict a host mid-probation after a takeover."""

        class _Agg:                   # collect() never called here
            pass

        c1 = self._controller(store, _Agg(), "c1")
        assert c1.lease.tick() == "acquired"
        with c1._lock:
            c1._evicted["trainer-1"] = {"step": 7, "since": time.time()}
            c1._decision_seq = 4
            c1._ledger_dirty = True
        blob = json.dumps(c1._ledger_snapshot())
        store.set(leader_mod.LEDGER_KEY, blob)
        c1.lease._leader = False      # c1 dies (stops renewing)
        time.sleep(0.35)
        c2 = self._controller(store, _Agg(), "c2")
        _spin(c2.lease, lambda r: r == "acquired", timeout=5.0)
        c2._load_ledger()
        with c2._lock:
            assert "trainer-1" in c2._evicted
            assert c2._evicted["trainer-1"]["step"] == 7
            assert c2._decision_seq >= 4
