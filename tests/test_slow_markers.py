"""Tier-1 audit: every slow-marked e2e test keeps a fast sibling.

PR 4 trimmed the tier-1 budget by pushing heavy e2e tests behind
``@pytest.mark.slow`` on the explicit contract that each one keeps a fast
sibling in tier-1 (same module, or a module named by a ``fast-sibling:``
annotation).  Nothing enforced that contract, so a future trim could
silently drop the last fast test from a module and tier-1 would lose the
subsystem entirely.  This audit makes the contract executable:

* a module whose slow tests sit next to fast ones passes on its own;
* a module that is slow end to end (``pytestmark = pytest.mark.slow``, or
  every collected test slow-marked) must carry a ``fast-sibling:`` line in
  its module docstring naming ``tests/...py`` files, and each named file
  must itself exist and collect at least one fast test.

The audit is pure ``ast`` — no imports of the test modules, no pytest
collection — so it costs milliseconds in tier-1.
"""
import ast
import re
from pathlib import Path

TESTS_DIR = Path(__file__).resolve().parent

SIBLING_RE = re.compile(r"tests/(test_\w+\.py)")


def _mark_names(deco):
    """Yield mark names reachable from one decorator expression.

    Handles ``@pytest.mark.slow``, ``@pytest.mark.slow(...)`` and bare
    ``@slow``-style aliases; parametrize marks inside the argument list are
    intentionally NOT walked here (a param-level slow mark still leaves the
    fast params collected, so the function counts as a fast sibling).
    """
    node = deco
    if isinstance(node, ast.Call):
        node = node.func
    while isinstance(node, ast.Attribute):
        yield node.attr
        node = node.value
    if isinstance(node, ast.Name):
        yield node.id


def _is_slow(decorator_list):
    return any("slow" in _mark_names(d) for d in decorator_list)


def _module_level_slow(tree):
    """True when the module sets ``pytestmark`` to something slow."""
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if not any(isinstance(t, ast.Name) and t.id == "pytestmark"
                   for t in targets):
            continue
        return "slow" in ast.dump(node.value)
    return False


def _audit_module(path):
    """Return (slow_count, fast_count) of test functions in one file."""
    tree = ast.parse(path.read_text(), filename=str(path))
    if _module_level_slow(tree):
        # everything in the file skips without --slow, whatever the
        # per-function marks say
        n = sum(isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
                and f.name.startswith("test_")
                for cls in [tree] + [n for n in ast.walk(tree)
                                     if isinstance(n, ast.ClassDef)]
                for f in cls.body)
        return n, 0

    slow = fast = 0
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name.startswith("Test"):
            cls_slow = _is_slow(node.decorator_list)
            for f in node.body:
                if (isinstance(f, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and f.name.startswith("test_")):
                    if cls_slow or _is_slow(f.decorator_list):
                        slow += 1
                    else:
                        fast += 1
    for node in tree.body:  # top-level test functions
        if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name.startswith("test_")):
            if _is_slow(node.decorator_list):
                slow += 1
            else:
                fast += 1
    return slow, fast


def _declared_siblings(path):
    doc = ast.get_docstring(ast.parse(path.read_text())) or ""
    m = re.search(r"fast-sibling:", doc)
    if not m:
        return None
    return SIBLING_RE.findall(doc[m.start():])


def test_every_slow_test_has_a_fast_sibling():
    failures = []
    for path in sorted(TESTS_DIR.glob("test_*.py")):
        if path.name == Path(__file__).name:
            continue
        slow, fast = _audit_module(path)
        if slow == 0 or fast > 0:
            continue  # no slow tests, or fast siblings live alongside
        siblings = _declared_siblings(path)
        if not siblings:
            failures.append(
                f"{path.name}: {slow} slow test(s), no fast test in the "
                f"module and no 'fast-sibling:' annotation in its docstring")
            continue
        for sib in siblings:
            sib_path = TESTS_DIR / sib
            if not sib_path.exists():
                failures.append(f"{path.name}: declared fast sibling "
                                f"{sib} does not exist")
                continue
            _, sib_fast = _audit_module(sib_path)
            if sib_fast == 0:
                failures.append(f"{path.name}: declared fast sibling "
                                f"{sib} collects no fast tests")
    assert not failures, (
        "slow-marked e2e tests lost their tier-1 fast siblings:\n  "
        + "\n  ".join(failures))


def test_audit_sees_the_known_slow_modules():
    """The audit must actually be looking at marks: the PR-4 trim and this
    PR's barrier e2e are known slow; their presence proves the parser
    didn't silently go blind (e.g. a marker-style change)."""
    slow_modules = {p.name for p in sorted(TESTS_DIR.glob("test_*.py"))
                    if p.name != Path(__file__).name
                    and _audit_module(p)[0] > 0}
    assert "test_elastic_e2e.py" in slow_modules
    assert "test_models.py" in slow_modules
    assert {"test_vision.py", "test_pipeline_parallel.py"} <= slow_modules


def test_elastic_e2e_siblings_declared_and_fast():
    """The new barrier e2e is wholly slow — its docstring must name its
    tier-1 siblings (regression pin for this PR's own contract)."""
    sibs = _declared_siblings(TESTS_DIR / "test_elastic_e2e.py")
    assert sibs is not None
    assert "test_coord_checkpoint.py" in sibs
    assert "test_elastic_supervisor.py" in sibs
