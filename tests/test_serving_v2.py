"""Serving v2 (inference/serving.py + inference/sampling.py): the
single-dispatch fused decode step, in-graph sampling policies, and the
refcounted copy-on-write shared-prefix page allocator.

Covers the ISSUE-16 contracts: fused-vs-eager bit parity, temperature=0
bit parity with the reference greedy paged decode, per-seed sampling
determinism across preemption, allocator refcount/fork/release-hook
semantics, CoW fork-on-divergent-write correctness (shared admission
changes page accounting but NEVER tokens), the no-leak audit (all
refcounts back to zero after EOS and after preemption), and that
preempting a request holding shared pages never frees pages another
request still references.

Every contract keeps a tier-1-fast test (tiny GPT, XLA decode path);
the heaviest cross-engine A/B replays ride the slow tier next to their
fast siblings, and the serving-at-scale A/Bs live in bench.py's
gpt2_decode config.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.sampling import SamplingParams, sample_logits
from paddle_tpu.inference.serving import PageAllocator, ServingEngine
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.profiler import events


@pytest.fixture(autouse=True)
def _clean_events():
    events.default_event_log().clear()
    yield
    events.default_event_log().clear()


@pytest.fixture(scope="module", autouse=True)
def _shared_compile_cache():
    """Same tiny-model engine rebuilt test after test: share one
    persistent XLA compilation cache dir (also shared with
    test_serving.py — identical _model() config, identical HLO) so only
    the first build pays backend compile on the 1-core tier-1 box.
    Nothing in this module asserts on backend-compile counters."""
    import os
    import tempfile
    from paddle_tpu.framework import flags as flags_mod
    cache = os.path.join(tempfile.gettempdir(), "pt_serving_ccache")
    os.makedirs(cache, exist_ok=True)
    flags_mod.set_flags({"FLAGS_compile_cache_dir": cache})
    yield
    flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})


def _model(vocab=512):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=vocab, max_position_embeddings=128,
                    hidden_size=32, num_layers=2, num_heads=2,
                    dropout=0.0, attn_dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m, cfg


def _serve(eng, prompts, max_new=6, sampling=None):
    if sampling is None:
        sampling = [None] * len(prompts)
    reqs = [eng.submit(p, max_new_tokens=max_new, sampling=s)
            for p, s in zip(prompts, sampling)]
    eng.run_until_idle()
    return [r.result(timeout=10) for r in reqs]


class TestSamplingPolicies:
    """sample_logits: the traceable policy kernel inside the fused step."""

    def _logits(self, B=4, V=64, seed=0):
        rng = np.random.default_rng(seed)
        return rng.normal(size=(B, V)).astype(np.float32) * 3.0

    def test_all_greedy_is_exact_argmax(self):
        import jax.numpy as jnp
        logits = self._logits()
        B = logits.shape[0]
        z = jnp.zeros((B,), jnp.int32)
        out = sample_logits(jnp.asarray(logits), jnp.zeros((B,)),
                            z, jnp.ones((B,)), z, z)
        assert np.asarray(out).tolist() == \
            np.argmax(logits, axis=-1).tolist()

    def test_top_k_one_is_argmax_at_any_temperature(self):
        import jax.numpy as jnp
        logits = self._logits()
        B = logits.shape[0]
        out = sample_logits(jnp.asarray(logits),
                            jnp.full((B,), 5.0),
                            jnp.ones((B,), jnp.int32),
                            jnp.ones((B,)),
                            jnp.arange(B, dtype=jnp.int32),
                            jnp.zeros((B,), jnp.int32))
        assert np.asarray(out).tolist() == \
            np.argmax(logits, axis=-1).tolist()

    def test_top_p_tiny_keeps_only_the_top_token(self):
        import jax.numpy as jnp
        logits = self._logits()
        B = logits.shape[0]
        out = sample_logits(jnp.asarray(logits),
                            jnp.full((B,), 2.0),
                            jnp.zeros((B,), jnp.int32),
                            jnp.full((B,), 1e-6),
                            jnp.arange(B, dtype=jnp.int32),
                            jnp.zeros((B,), jnp.int32))
        assert np.asarray(out).tolist() == \
            np.argmax(logits, axis=-1).tolist()

    def test_mixed_lanes_greedy_rows_stay_argmax(self):
        """A batch mixing greedy and sampled lanes: the greedy lanes are
        bit-exact argmax regardless of their neighbours."""
        import jax.numpy as jnp
        logits = self._logits(B=6)
        temp = jnp.asarray([0.0, 1.0, 0.0, 0.7, 0.0, 2.0])
        z = jnp.zeros((6,), jnp.int32)
        out = np.asarray(sample_logits(
            jnp.asarray(logits), temp, z, jnp.ones((6,)),
            jnp.arange(6, dtype=jnp.int32), z))
        am = np.argmax(logits, axis=-1)
        for i in (0, 2, 4):
            assert out[i] == am[i]

    def test_same_seed_same_step_is_deterministic(self):
        import jax.numpy as jnp
        logits = self._logits(B=8)
        B = logits.shape[0]
        args = (jnp.full((B,), 1.3), jnp.zeros((B,), jnp.int32),
                jnp.ones((B,)), jnp.full((B,), 42, jnp.int32),
                jnp.full((B,), 3, jnp.int32))
        a = np.asarray(sample_logits(jnp.asarray(logits), *args))
        b = np.asarray(sample_logits(jnp.asarray(logits), *args))
        assert a.tolist() == b.tolist()

    def test_distinct_seeds_diverge(self):
        import jax.numpy as jnp
        logits = np.zeros((16, 128), np.float32)  # uniform: pure RNG
        B = logits.shape[0]
        out = np.asarray(sample_logits(
            jnp.asarray(logits), jnp.ones((B,)),
            jnp.zeros((B,), jnp.int32), jnp.ones((B,)),
            jnp.arange(B, dtype=jnp.int32), jnp.zeros((B,), jnp.int32)))
        assert len(set(out.tolist())) > 1

    def test_params_validation(self):
        with pytest.raises(ValueError, match="temperature"):
            SamplingParams(temperature=-0.1)
        with pytest.raises(ValueError, match="top_k"):
            SamplingParams(top_k=-1)
        with pytest.raises(ValueError, match="top_p"):
            SamplingParams(top_p=0.0)
        assert SamplingParams().greedy
        assert not SamplingParams(temperature=0.5).greedy


class TestRefcountedAllocator:
    def test_fork_shares_and_last_free_recycles(self):
        a = PageAllocator(8)
        pages = a.alloc(3)
        assert all(a.refcount(p) == 1 for p in pages)
        a.fork(pages)
        assert all(a.refcount(p) == 2 for p in pages)
        assert all(a.is_shared(p) for p in pages)
        free0 = a.free_pages
        a.free(pages)  # first holder: decref only
        assert a.free_pages == free0
        assert all(a.refcount(p) == 1 for p in pages)
        a.free(pages)  # last holder: recycle
        assert a.free_pages == free0 + 3
        assert not a.outstanding()

    def test_shared_page_survives_one_holder_free(self):
        """The preemption-safety core: releasing one sharer's reference
        must not put the page back in the free list while another holder
        references it — a subsequent alloc can never hand it out."""
        a = PageAllocator(4)
        [page] = a.alloc(1)
        a.fork([page])
        a.free([page])  # holder 1 preempted
        got = a.alloc(2)  # drain the remaining pool
        assert page not in got
        assert a.refcount(page) == 1

    def test_on_release_fires_once_at_last_release(self):
        released = []
        a = PageAllocator(6, on_release=released.append)
        pages = a.alloc(2)
        a.fork(pages)
        a.free(pages)
        assert released == []
        a.free(pages)
        assert sorted(released) == sorted(pages)

    def test_null_page_ignored_by_fork_and_free(self):
        a = PageAllocator(4)
        a.fork([0])
        a.free([0])
        assert a.refcount(0) == 0
        assert a.free_pages == 3


class TestFusedVsEager:
    @pytest.mark.slow  # 5-stream A/B replay; temp-0 parity below stays fast
    def test_bit_identical_tokens_greedy_and_sampled(self):
        m, cfg = _model()
        rng = np.random.default_rng(5)
        prompts = [rng.integers(1, cfg.vocab_size,
                                (int(rng.integers(4, 20)),)).tolist()
                   for _ in range(5)]
        sampling = [None, SamplingParams(temperature=0.9, seed=7),
                    SamplingParams(temperature=1.4, top_k=20, seed=8),
                    SamplingParams(temperature=0.8, top_p=0.9, seed=9),
                    None]
        outs = {}
        for mode in ("fused", "eager"):
            eng = ServingEngine(m, max_batch=3, max_len=48, page_size=8,
                                name=f"fe_{mode}", decode_mode=mode)
            outs[mode] = _serve(eng, prompts, max_new=5, sampling=sampling)
            assert not eng.allocator.outstanding()
        assert outs["fused"] == outs["eager"]

    def test_temperature_zero_matches_reference_greedy(self):
        """SamplingParams(temperature=0) through the fused sampler is
        bit-identical to the model's reference greedy paged decode."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="t0")
        rng = np.random.default_rng(11)
        prompts = [rng.integers(1, cfg.vocab_size, (9,)).tolist(),
                   rng.integers(1, cfg.vocab_size, (14,)).tolist()]
        outs = _serve(eng, prompts, max_new=6,
                      sampling=[SamplingParams(temperature=0.0)] * 2)
        for p, out in zip(prompts, outs):
            ids = paddle.to_tensor(np.asarray([p], np.int32))
            ref = np.asarray(m.generate_paged(ids, 6, page_size=8).data)
            assert out == ref[0, len(p):].tolist()

    @pytest.mark.slow  # 3 fresh engines; sampling-level determinism stays fast
    def test_seeded_sampling_reproducible_across_engines(self):
        m, cfg = _model()
        prompt = list(range(1, 12))
        sp = SamplingParams(temperature=1.1, seed=123)
        runs = []
        for i in range(2):
            eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                                name=f"rep{i}")
            runs.append(_serve(eng, [prompt], max_new=8,
                               sampling=[sp])[0])
        assert runs[0] == runs[1]
        eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                            name="rep_other")
        other = _serve(eng, [prompt], max_new=8,
                       sampling=[SamplingParams(temperature=1.1,
                                                seed=124)])[0]
        assert other != runs[0]


class TestSharedPrefixCoW:
    def test_sharing_changes_pages_not_tokens(self):
        """Parallel sampling (identical prompt, distinct seeds) with
        share_prefix on vs off: identical tokens, but the on side admits
        through shared pages and forks on first divergent write."""
        m, cfg = _model()
        prompt = list(range(1, 20))  # 19 tokens: partial tail page
        sampling = [SamplingParams(temperature=0.9, seed=50 + i)
                    for i in range(3)]
        outs = {}
        for share in (True, False):
            eng = ServingEngine(m, max_batch=3, max_len=64, page_size=8,
                                name=f"shp{int(share)}",
                                share_prefix=share)
            outs[share] = _serve(eng, [prompt] * 3, max_new=5,
                                 sampling=sampling)
            st = eng.stats
            if share:
                assert st["shared_admissions"] == 2, st
                assert st["prefix_hit_tokens"] == 2 * len(prompt), st
                assert st["cow_copies"] >= 2, st
            else:
                assert st["shared_admissions"] == 0, st
                assert st["cow_copies"] == 0, st
            # no-leak audit: every refcount back to zero after EOS/length
            assert not eng.allocator.outstanding()
            assert eng.status()["free_pages"] == eng.cache.num_pages - 1
        assert outs[True] == outs[False]
        assert len({tuple(o) for o in outs[True]}) == 3  # seeds diverged

    @pytest.mark.slow  # CoW + no-leak contract stays fast in
    # test_sharing_changes_pages_not_tokens above
    def test_page_aligned_prefix_chain_shares_without_cow(self):
        """Distinct continuations of a page-aligned common prefix share
        the full-page chain only; each writes its own tail page, so no
        CoW is needed and tokens still match the unshared run."""
        m, cfg = _model()
        common = list(range(1, 17))  # exactly 2 pages at page_size=8
        prompts = [common + [100 + i] for i in range(3)]
        outs = {}
        for share in (True, False):
            eng = ServingEngine(m, max_batch=3, max_len=64, page_size=8,
                                name=f"chain{int(share)}",
                                share_prefix=share)
            outs[share] = _serve(eng, prompts, max_new=4)
            if share:
                assert eng.stats["shared_admissions"] == 2
                assert eng.stats["prefix_hit_tokens"] == 2 * len(common)
            assert not eng.allocator.outstanding()
        assert outs[True] == outs[False]

    def test_preempting_a_sharer_keeps_the_survivors_pages(self):
        """Preempting a request that holds shared pages must only drop
        its references: the survivor keeps decoding on intact pages and
        both finish with the share-off tokens."""
        m, cfg = _model()
        prompt = list(range(1, 19))
        sampling = [SamplingParams(temperature=0.8, seed=70 + i)
                    for i in range(2)]
        eng = ServingEngine(m, max_batch=2, max_len=64, page_size=8,
                            name="pshare")
        reqs = [eng.submit(prompt, max_new_tokens=6, sampling=s)
                for s in sampling]
        eng.step()  # admit both (shared pages) + first decode
        victim = eng._slots[1]
        survivor = eng._slots[0]
        shared_before = [p for p in survivor.pages
                         if eng.allocator.refcount(p) >= 1]
        eng._preempt(victim)
        # every page the survivor references is still live
        for p in shared_before:
            assert eng.allocator.refcount(p) >= 1
            assert p not in eng.allocator._free
        eng.run_until_idle()
        outs = [r.result(timeout=10) for r in reqs]
        # reference: the unshared, unpreempted run
        ref_eng = ServingEngine(m, max_batch=2, max_len=64, page_size=8,
                                name="pshare_ref", share_prefix=False)
        refs = _serve(ref_eng, [prompt] * 2, max_new=6, sampling=sampling)
        assert outs == refs
        assert not eng.allocator.outstanding()

    @pytest.mark.slow  # shared-page preemption safety stays fast in
    # test_preempting_a_sharer_keeps_the_survivors_pages above
    def test_pool_pressure_preemption_with_sharing_recovers(self):
        """A pool too small for the unshared batch: sharing + CoW +
        preemption still complete every request with the right tokens,
        and all refcounts drain to zero."""
        m, cfg = _model()
        prompt = list(range(1, 18))  # 17 tokens -> 3 pages
        sampling = [SamplingParams(temperature=0.7, seed=90 + i)
                    for i in range(3)]
        # unshared need: 3 seqs x ceil((17+8)/8)=4 pages = 12; give 8
        eng = ServingEngine(m, max_batch=3, max_len=32, page_size=8,
                            num_pages=9, name="tight")
        outs = _serve(eng, [prompt] * 3, max_new=6, sampling=sampling)
        assert not eng.allocator.outstanding()
        ref_eng = ServingEngine(m, max_batch=3, max_len=32, page_size=8,
                                name="tight_ref", share_prefix=False)
        refs = _serve(ref_eng, [prompt] * 3, max_new=6, sampling=sampling)
        assert outs == refs

    def test_released_prefix_is_not_resurrected(self):
        """Once the last holder of a registered prefix releases its
        pages, a new identical prompt must NOT share the recycled pages
        (the allocator release hook evicts the registry entries)."""
        m, cfg = _model()
        prompt = list(range(1, 15))
        eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                            name="evict")
        _serve(eng, [prompt], max_new=3)
        assert not eng.allocator.outstanding()
        assert eng.status()["prefix_entries"] == 0
        outs = _serve(eng, [prompt], max_new=3)
        assert eng.stats["shared_admissions"] == 0
        ids = paddle.to_tensor(np.asarray([prompt], np.int32))
        ref = np.asarray(m.generate_paged(ids, 3, page_size=8).data)
        assert outs[0] == ref[0, len(prompt):].tolist()


class TestServingV2Surface:
    def test_status_reports_v2_fields(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="st2")
        st = eng.status()
        assert st["decode_mode"] == "fused"
        assert st["share_prefix"] is True
        assert st["decode_buckets"] == sorted(st["decode_buckets"])
        assert st["decode_buckets"][-1] == 2
        for key in ("cow_copies", "prefix_hit_tokens",
                    "shared_admissions", "min_free_pages"):
            assert key in st["stats"]
        import json
        json.dumps(st)

    def test_bad_decode_mode_rejected(self):
        m, cfg = _model()
        with pytest.raises(ValueError, match="decode_mode"):
            ServingEngine(m, max_batch=1, max_len=32, page_size=8,
                          decode_mode="turbo")

    def test_latency_metrics_carry_path_label(self):
        from paddle_tpu.inference import serving as srv
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                            name="lbl")
        _serve(eng, [list(range(1, 8))], max_new=3)
        snap = srv._REG.snapshot()
        for fam in ("serving_ttft_seconds", "serving_tpot_seconds"):
            series = [v for v in snap[fam]["values"]
                      if v["labels"].get("model") == "lbl"]
            assert series, fam
            assert all(v["labels"].get("path") == "fused" for v in series)

    def test_audit_covers_fused_decode_and_prefill(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="aud2")
        reports = eng.audit(emit=False)
        by_entry = {r.entry: r for r in reports}
        assert set(by_entry) == {"serving_decode", "serving_prefill"}
        # the donated-cache fused step must audit high-clean
        for r in reports:
            assert not r.by_severity("high"), r.render()

    def test_snapshot_surfaces_recent_audit_reports(self):
        from paddle_tpu import analysis
        from paddle_tpu.profiler.server import ObservabilityServer
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=32, page_size=8,
                            name="snapaud")
        eng.audit(emit=True)
        snap = ObservabilityServer().snapshot()
        reports = snap["program_audit"]
        assert reports is analysis.recent_reports() or \
            reports == analysis.recent_reports()
        names = [r["name"] for r in reports]
        assert "serving_decode:snapaud" in names
        import json
        json.dumps(reports)
