"""Chaos drills for the self-healing serving plane: the full
detect->decide->actuate->recover loop under a live decode thread, with
fault injection driving the failures. Each drill asserts the event
trail (serving_swap / serving_restart / controller_decision), trace-id
continuity, and the zero-page-leak audit — the properties the fast
tests pin piecewise.

fast-sibling: tests/test_hotswap.py
fast-sibling: tests/test_serving_controller.py
"""
import os
import tempfile
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.controller import FleetController
from paddle_tpu.distributed.sharded_checkpoint import ShardedCheckpointManager
from paddle_tpu.fault import inject
from paddle_tpu.inference.governor import MemoryGovernor
from paddle_tpu.inference.hotswap import HotSwapManager
from paddle_tpu.inference.serving import EngineSuspended, ServingEngine
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.profiler import events

pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def _clean_events():
    events.default_event_log().clear()
    inject.reset()
    yield
    inject.reset()
    events.default_event_log().clear()


@pytest.fixture(scope="module", autouse=True)
def _shared_compile_cache():
    from paddle_tpu.framework import flags as flags_mod
    cache = os.path.join(tempfile.gettempdir(), "pt_serving_ccache")
    os.makedirs(cache, exist_ok=True)
    flags_mod.set_flags({"FLAGS_compile_cache_dir": cache})
    yield
    flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})


def _model(seed=0):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=512, max_position_embeddings=128,
                    hidden_size=32, num_layers=2, num_heads=2,
                    dropout=0.0, attn_dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m, cfg


def _params(m):
    return {k: p.data for k, p in m.named_parameters()}


def _save(tmpdir, state, step):
    mgr = ShardedCheckpointManager(str(tmpdir), prefix="ckpt",
                                   keep_last_n=10)
    assert mgr.save(state, step=step)


def _amplified(state, factor=50.0):
    return {k: paddle.to_tensor(
                (np.asarray(v) * factor).astype(np.asarray(v).dtype))
            for k, v in state.items()}


def _ctl(engines, **kw):
    kw.setdefault("confirm_windows", 3)
    kw.setdefault("readmit_after_s", 9999)
    kw.setdefault("restart_cooldown_s", 9999.0)
    kw.setdefault("swap_observe_s", 9999.0)

    class _Agg:
        straggler_factor = 2.0
        last = {}

        def straggling(self):
            return []
    return FleetController(_Agg(), None, world_size=1,
                           serving_provider=lambda: list(engines), **kw)


def _decisions(policy):
    return [e for e in events.recent(200, kind="controller_decision")
            if e.get("policy") == policy]


class TestWedgeRestartDrill:
    def test_wedged_loop_is_restarted_and_requests_complete(
            self, monkeypatch):
        """Inject `serving.wedge` (delay) into a LIVE decode loop until
        the controller's liveness watchdog confirms the stall and
        restarts the engine; every in-flight request must complete with
        its original trace id and zero pages may leak."""
        monkeypatch.setenv("PADDLE_TPU_HEALTH_STALL_SEC", "0.4")
        monkeypatch.setenv("PADDLE_TPU_FAULT_DELAY", "1.0")
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=64, page_size=8,
                            name="chaos-wedge")
        ctl = _ctl([eng], wedge_windows=2, dry_run=False)
        eng.start(poll_s=0.005)
        try:
            # wedge every iteration BEFORE submitting: each step sleeps
            # 1s, so the loop makes (slow) progress but spends most of
            # each cycle past the 0.4s stall window — and the requests
            # (24 tokens at ~1 token/s) cannot finish before the
            # watchdog fires
            inject.configure("serving.wedge", times=10_000, kind="delay")
            rng = np.random.default_rng(3)
            prompts = [rng.integers(1, cfg.vocab_size, (8,)).tolist()
                       for _ in range(2)]
            reqs = [eng.submit(p, max_new_tokens=24) for p in prompts]
            traces = [r.trace_id for r in reqs]
            # wait for both to be admitted into decode slots so the
            # restart exercises the in-flight requeue path
            deadline = time.time() + 20
            while (sum(s is not None for s in eng._slots) < 2
                   and time.time() < deadline):
                time.sleep(0.01)
            assert sum(s is not None for s in eng._slots) == 2
            for _ in range(200):
                ctl.on_collect({})
                if _decisions("serving_restart"):
                    break
                time.sleep(0.25)
            d = _decisions("serving_restart")
            assert d and d[-1]["outcome"] == "applied", \
                "watchdog never confirmed the wedge"
            inject.reset()  # the relaunched loop runs clean

            for p, r in zip(prompts, reqs):
                out = r.result(timeout=60)
                assert len(out) == 24 and r.state == "done"
                ids = paddle.to_tensor(np.asarray([p], np.int32))
                ref = np.asarray(
                    m.generate_paged(ids, 24, page_size=8).data)
                assert out == ref[0, len(p):].tolist(), \
                    "restart changed greedy decode"
            assert [r.trace_id for r in reqs] == traces
            assert eng.stats["restarts"] == 1

            rest = events.recent(50, kind="serving_restart")
            assert len(rest) == 1
            assert rest[0]["reason"] == "wedged"
            assert rest[0]["requeued"] == 2
            assert rest[0]["restarted_thread"] is True
        finally:
            inject.reset()
            eng.close()
        assert eng.allocator.outstanding() == {}


class TestBadPushDrill:
    def test_background_poller_rejects_bad_push_while_serving(self):
        """A confidently-wrong checkpoint lands in the watch dir while
        the engine serves traffic: the background poller's canary must
        reject it without ever touching the live weights."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=64, page_size=8,
                            name="chaos-push")
        with tempfile.TemporaryDirectory() as d:
            state = _params(m)
            _save(d, state, 100)
            hsm = HotSwapManager(eng, d, poll_s=0.05, canary=True,
                                 canary_tol=0.10)
            eng.start(poll_s=0.005)
            hsm.start()
            try:
                deadline = time.time() + 30
                while hsm.current_step != 100 and time.time() < deadline:
                    time.sleep(0.02)
                assert hsm.current_step == 100  # baseline push applied

                r1 = eng.submit([5, 9, 3, 17], max_new_tokens=8)
                good = r1.result(timeout=30)

                _save(d, _amplified(state), 200)
                deadline = time.time() + 30
                while 200 not in hsm.rejected and time.time() < deadline:
                    time.sleep(0.02)
                assert 200 in hsm.rejected, "canary never rejected step 200"
                assert eng.weights_step == 100  # live weights untouched

                r2 = eng.submit([5, 9, 3, 17], max_new_tokens=8)
                assert r2.result(timeout=30) == good, \
                    "rejected push changed live decode"
                acts = [e["action"] for e in
                        events.recent(100, kind="serving_swap")]
                assert acts.count("reject") == 1
                assert acts[:2] == ["stage", "swap"]  # the good baseline
            finally:
                hsm.stop()
                eng.close()


class TestForcedRegressionRollbackDrill:
    def test_controller_rolls_back_a_forced_bad_swap(self):
        """An operator force-pushes a blacklisted step; the controller's
        post-swap watch sees the canary regression and rolls back to the
        prior step automatically, leaving greedy decode bit-identical to
        the pre-push engine."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="chaos-roll")
        ctl = _ctl([eng], max_swap_rollbacks=2, dry_run=False)
        with tempfile.TemporaryDirectory() as d:
            state = _params(m)
            _save(d, state, 100)
            hsm = HotSwapManager(eng, d, poll_s=999, canary=True)
            eng.hotswap = hsm
            assert hsm.poll_once()["outcome"] == "staged"
            before = eng.generate([7, 1, 30, 2], max_new_tokens=8)["tokens"]
            ctl.on_collect({})  # healthy baseline: nothing to do
            assert _decisions("serving_swap_rollback") == []

            _save(d, _amplified(state), 200)
            rec = hsm.try_swap(step=200, force=True)
            assert rec["outcome"] == "staged" and rec["forced"]
            assert eng.weights_step == 200 and hsm.vetted is False

            ctl.on_collect({})  # the watch fires on this tick
            d2 = _decisions("serving_swap_rollback")
            assert len(d2) == 1 and d2[0]["outcome"] == "applied"
            assert d2[0]["evidence"]["reason"] == "canary"
            assert eng.weights_step == 100 and hsm.vetted is True
            after = eng.generate([7, 1, 30, 2], max_new_tokens=8)["tokens"]
            assert after == before, "rollback did not restore decode"
            acts = [e["action"] for e in
                    events.recent(100, kind="serving_swap")]
            # baseline push, forced push, then the restore (a rollback
            # stages the prior weights like any other swap)
            assert acts == ["stage", "swap", "stage", "swap",
                            "stage", "rollback"]
        eng.close()


class TestMemoryPressureDrill:
    def test_governor_degrades_and_recovers_under_live_load(self):
        """Two co-resident engines under memory pressure: the governor
        shrinks then suspends the low-priority one (503-style refusal
        with Retry-After) while the high-priority engine keeps serving;
        when pressure clears both recover and serve again."""
        m, cfg = _model()
        hi = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                           name="chaos-hi", priority=10)
        lo = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                           name="chaos-lo", priority=1)
        hi.start(poll_s=0.005)
        lo.start(poll_s=0.005)
        pressure = {"bytes": 100}
        gov = MemoryGovernor(limit_bytes=50, retry_after_s=2.5,
                             sampler=lambda: pressure["bytes"],
                             engines=lambda: [hi, lo])
        try:
            # keep lo busy so suspension provably spares in-flight work
            busy = lo.submit([9, 2, 4], max_new_tokens=8)
            assert gov.tick()["action"] == "shrink_pool"
            assert gov.tick()["action"] == "suspend"
            with pytest.raises(EngineSuspended) as ei:
                lo.submit([1, 2, 3], max_new_tokens=4)
            assert ei.value.retry_after_s == 2.5
            # the suspension refuses ADMISSION only: in-flight drains...
            assert len(busy.result(timeout=30)) == 8
            # ...and the high-priority engine never stopped serving
            r = hi.submit([1, 2, 3], max_new_tokens=4)
            assert len(r.result(timeout=30)) == 4

            pressure["bytes"] = 10
            seen = []
            for _ in range(4):
                rec = gov.tick()
                if rec:
                    seen.append(rec["action"])
            assert seen == ["resume", "restore_pool"]
            assert gov.status()["degraded"] == {}
            r = lo.submit([1, 2, 3], max_new_tokens=4)
            assert len(r.result(timeout=30)) == 4
        finally:
            hi.close()
            lo.close()
