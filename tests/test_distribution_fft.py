"""Tests: paddle_tpu.distribution, paddle_tpu.fft, paddle_tpu.signal.

Mirrors the reference suites `unittests/distribution/test_distribution_*.py`
and `unittests/fft/test_fft.py` style: numerical parity against numpy/scipy
closed forms, Monte-Carlo sanity for samplers, round-trip identities for
transforms and FFTs.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D
from paddle_tpu import fft as pfft
from paddle_tpu import signal as psignal


@pytest.fixture(autouse=True)
def _seed():
    paddle.seed(2024)


class TestNormal:
    def test_log_prob_entropy(self):
        loc, scale = 1.5, 2.0
        d = D.Normal(loc, scale)
        x = np.linspace(-3, 5, 11).astype(np.float32)
        lp = d.log_prob(paddle.to_tensor(x)).numpy()
        ref = -0.5 * ((x - loc) / scale) ** 2 - np.log(scale) - 0.5 * np.log(2 * np.pi)
        np.testing.assert_allclose(lp, ref, rtol=1e-5)
        ent = float(d.entropy().numpy())
        np.testing.assert_allclose(ent, 0.5 * np.log(2 * np.pi * np.e * scale**2),
                                   rtol=1e-5)

    def test_sample_moments(self):
        d = D.Normal(np.float32(1.0), np.float32(3.0))
        s = d.sample((20000,)).numpy()
        assert abs(s.mean() - 1.0) < 0.1
        assert abs(s.std() - 3.0) < 0.1

    def test_kl(self):
        p = D.Normal(0.0, 1.0)
        q = D.Normal(1.0, 2.0)
        kl = float(D.kl_divergence(p, q).numpy())
        ref = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
        np.testing.assert_allclose(kl, ref, rtol=1e-5)

    def test_rsample_grad(self):
        # reparameterized draws propagate gradients to loc/scale
        import jax
        import jax.numpy as jnp
        from paddle_tpu.framework import random as rmod

        def f(loc):
            d = D.Normal(loc, jnp.float32(1.0))
            return jnp.mean(d.rsample((16,)).data)
        g = jax.grad(f)(jnp.float32(0.5))
        np.testing.assert_allclose(np.asarray(g), 1.0, rtol=1e-4)

    def test_exponential_family_entropy_matches(self):
        d = D.Normal(np.float32(0.3), np.float32(1.7))
        closed = float(d.entropy().numpy())
        bregman = float(D.ExponentialFamily.entropy(d).numpy())
        np.testing.assert_allclose(closed, bregman, rtol=1e-4)


class TestTapeIntegration:
    """Distribution math must record on the eager tape (code-review regressions)."""

    def test_log_prob_backward_reaches_params(self):
        loc = paddle.to_tensor(np.float32(0.5)); loc.stop_gradient = False
        scale = paddle.to_tensor(np.float32(2.0)); scale.stop_gradient = False
        d = D.Normal(loc, scale)
        lp = d.log_prob(paddle.to_tensor(np.float32(1.0)))
        lp.backward()
        # d lp / d loc = (x - loc) / scale^2 = 0.5 / 4
        np.testing.assert_allclose(loc.grad.numpy(), 0.125, rtol=1e-5)
        assert scale.grad is not None

    def test_rsample_kl_training_step_moves_params(self):
        from paddle_tpu import nn, optimizer
        paddle.seed(7)
        enc = nn.Linear(4, 2)
        opt = optimizer.SGD(learning_rate=0.5, parameters=enc.parameters())
        x = paddle.to_tensor(np.random.randn(8, 4).astype(np.float32))
        w0 = enc.weight.numpy().copy()
        h = enc(x)
        q = D.Normal(h[:, :1], paddle.to_tensor(np.float32(1.0)))
        loss = D.kl_divergence(q, D.Normal(0.0, 1.0)).mean() \
            + (q.rsample() ** 2).mean()
        loss.backward()
        opt.step()
        assert np.abs(enc.weight.numpy() - w0).max() > 1e-6, \
            "params did not move — distribution math fell off the tape"

    def test_transform_backward(self):
        x = paddle.to_tensor(np.array([0.3, -0.7], np.float32))
        x.stop_gradient = False
        y = D.TanhTransform().forward(x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(),
                                   1 - np.tanh([0.3, -0.7]) ** 2, rtol=1e-5)

    def test_categorical_zero_prob_entropy(self):
        d = D.Categorical(probs=np.array([0.5, 0.5, 0.0], dtype=np.float32))
        assert float(d.entropy().numpy()) == pytest.approx(np.log(2.0), rel=1e-5)
        q = D.Categorical(probs=np.array([0.25, 0.25, 0.5], dtype=np.float32))
        kl = float(D.kl_divergence(d, q).numpy())
        assert np.isfinite(kl)

    def test_categorical_log_prob_rank_broadcast(self):
        # scalar / sub-batch-rank value against a batched Categorical
        probs = np.array([[0.5, 0.5], [0.2, 0.8]], dtype=np.float32)
        d = D.Categorical(probs=probs)
        lp = d.log_prob(paddle.to_tensor(np.int32(1))).numpy()
        np.testing.assert_allclose(lp, np.log(probs[:, 1]), rtol=1e-5)

    def test_transformed_shape_metadata(self):
        base = D.Normal(np.zeros(3, np.float32), np.ones(3, np.float32))
        d = D.TransformedDistribution(base, [D.StickBreakingTransform()])
        assert d.sample().shape == [4]
        assert d.batch_shape + d.event_shape == (4,)

    def test_frame_too_long_raises(self):
        with pytest.raises(ValueError, match="frame_length"):
            psignal.frame(paddle.to_tensor(np.arange(3, dtype=np.float32)), 8, 2)

    def test_register_kl_after_first_dispatch(self):
        class _MyNormal(D.Normal):
            pass
        p, q = _MyNormal(0.0, 1.0), _MyNormal(0.0, 1.0)
        assert float(D.kl_divergence(p, q).numpy()) == pytest.approx(0.0)

        @D.register_kl(_MyNormal, _MyNormal)
        def _kl_my(p_, q_):
            return paddle.to_tensor(np.float32(42.0))
        assert float(D.kl_divergence(p, q).numpy()) == 42.0


class TestUniformCategorical:
    def test_uniform(self):
        d = D.Uniform(1.0, 3.0)
        lp = d.log_prob(paddle.to_tensor(np.float32(2.0)))
        np.testing.assert_allclose(float(lp.numpy()), -np.log(2.0), rtol=1e-6)
        assert float(d.entropy().numpy()) == pytest.approx(np.log(2.0), rel=1e-6)
        s = d.sample((5000,)).numpy()
        assert s.min() >= 1.0 and s.max() < 3.0
        assert abs(s.mean() - 2.0) < 0.05

    def test_categorical(self):
        probs = np.array([0.1, 0.2, 0.7], dtype=np.float32)
        d = D.Categorical(probs=probs)
        lp = d.log_prob(paddle.to_tensor(np.array([0, 1, 2]))).numpy()
        np.testing.assert_allclose(lp, np.log(probs), rtol=1e-5)
        ent = float(d.entropy().numpy())
        np.testing.assert_allclose(ent, -(probs * np.log(probs)).sum(), rtol=1e-5)
        s = d.sample((8000,)).numpy()
        freq = np.bincount(s, minlength=3) / s.size
        np.testing.assert_allclose(freq, probs, atol=0.03)

    def test_categorical_kl(self):
        p = D.Categorical(probs=np.array([0.3, 0.7], dtype=np.float32))
        q = D.Categorical(probs=np.array([0.5, 0.5], dtype=np.float32))
        kl = float(D.kl_divergence(p, q).numpy())
        ref = 0.3 * np.log(0.3 / 0.5) + 0.7 * np.log(0.7 / 0.5)
        np.testing.assert_allclose(kl, ref, rtol=1e-5)


class TestBetaDirichletMultinomial:
    def test_beta(self):
        d = D.Beta(2.0, 3.0)
        assert float(d.mean.numpy()) == pytest.approx(0.4, rel=1e-5)
        from scipy import stats
        x = np.array([0.1, 0.4, 0.8], dtype=np.float32)
        np.testing.assert_allclose(d.log_prob(paddle.to_tensor(x)).numpy(),
                                   stats.beta.logpdf(x, 2.0, 3.0), rtol=1e-4)
        np.testing.assert_allclose(float(d.entropy().numpy()),
                                   stats.beta.entropy(2.0, 3.0), rtol=1e-4)

    def test_dirichlet(self):
        conc = np.array([1.0, 2.0, 3.0], dtype=np.float32)
        d = D.Dirichlet(conc)
        np.testing.assert_allclose(d.mean.numpy(), conc / conc.sum(), rtol=1e-5)
        s = d.sample((4000,)).numpy()
        assert s.shape == (4000, 3)
        np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-4)
        np.testing.assert_allclose(s.mean(0), conc / conc.sum(), atol=0.02)
        from scipy import stats
        x = np.array([0.2, 0.3, 0.5], dtype=np.float32)
        x64 = x.astype(np.float64)
        x64 = x64 / x64.sum()  # scipy enforces an exact simplex
        np.testing.assert_allclose(float(d.log_prob(paddle.to_tensor(x)).numpy()),
                                   stats.dirichlet.logpdf(x64, conc), rtol=1e-4)

    def test_multinomial(self):
        probs = np.array([0.2, 0.3, 0.5], dtype=np.float32)
        d = D.Multinomial(10, probs)
        np.testing.assert_allclose(d.mean.numpy(), 10 * probs, rtol=1e-5)
        s = d.sample((200,)).numpy()
        assert s.shape == (200, 3)
        np.testing.assert_allclose(s.sum(-1), 10.0)
        from scipy import stats
        x = np.array([2.0, 3.0, 5.0], dtype=np.float32)
        np.testing.assert_allclose(
            float(d.log_prob(paddle.to_tensor(x)).numpy()),
            stats.multinomial.logpmf(x, 10, probs.astype(np.float64)), rtol=1e-4)

    def test_beta_kl_vs_mc(self):
        p = D.Beta(2.0, 2.0)
        q = D.Beta(3.0, 1.5)
        kl = float(D.kl_divergence(p, q).numpy())
        s = p.sample((30000,)).numpy().clip(1e-5, 1 - 1e-5)
        from scipy import stats
        mc = np.mean(stats.beta.logpdf(s, 2, 2) - stats.beta.logpdf(s, 3, 1.5))
        assert abs(kl - mc) < 0.05


class TestIndependentTransformed:
    def test_independent(self):
        base = D.Normal(np.zeros((4, 3), np.float32), np.ones((4, 3), np.float32))
        d = D.Independent(base, 1)
        assert d.batch_shape == (4,)
        assert d.event_shape == (3,)
        x = np.random.randn(4, 3).astype(np.float32)
        np.testing.assert_allclose(d.log_prob(paddle.to_tensor(x)).numpy(),
                                   base.log_prob(paddle.to_tensor(x)).numpy().sum(-1),
                                   rtol=1e-5)

    def test_lognormal_via_transform(self):
        base = D.Normal(0.0, 1.0)
        d = D.TransformedDistribution(base, [D.ExpTransform()])
        x = np.array([0.5, 1.0, 2.0], dtype=np.float32)
        from scipy import stats
        np.testing.assert_allclose(d.log_prob(paddle.to_tensor(x)).numpy(),
                                   stats.lognorm.logpdf(x, 1.0), rtol=1e-4)

    def test_affine_sigmoid_tanh_roundtrip(self):
        x = np.linspace(-2, 2, 9).astype(np.float32)
        for t in [D.AffineTransform(1.0, 2.5), D.SigmoidTransform(),
                  D.TanhTransform(), D.ExpTransform()]:
            y = t.forward(paddle.to_tensor(x))
            back = t.inverse(y).numpy()
            np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)

    def test_ladj_matches_autodiff(self):
        import jax
        import jax.numpy as jnp
        x = np.linspace(-1.5, 1.5, 7).astype(np.float32)
        for t in [D.AffineTransform(0.5, 3.0), D.SigmoidTransform(),
                  D.TanhTransform(), D.ExpTransform(), D.PowerTransform(3.0)]:
            if isinstance(t, D.PowerTransform):
                xs = np.abs(x) + 0.5
            else:
                xs = x
            ladj = t.forward_log_det_jacobian(paddle.to_tensor(xs)).numpy()
            ref = np.log(np.abs(np.asarray(
                jax.vmap(jax.grad(lambda v: t.forward_arr(v)))(jnp.asarray(xs)))))
            np.testing.assert_allclose(ladj, ref, rtol=1e-4, atol=1e-5)

    def test_stickbreaking(self):
        t = D.StickBreakingTransform()
        x = np.array([0.3, -0.2, 0.5], dtype=np.float32)
        y = t.forward(paddle.to_tensor(x)).numpy()
        assert y.shape == (4,)
        np.testing.assert_allclose(y.sum(), 1.0, rtol=1e-5)
        back = t.inverse(paddle.to_tensor(y)).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)

    def test_chain_reshape_stack(self):
        t = D.ChainTransform([D.AffineTransform(0.0, 2.0), D.ExpTransform()])
        x = np.array([0.1, 0.7], dtype=np.float32)
        y = t.forward(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(y, np.exp(2 * x), rtol=1e-5)
        np.testing.assert_allclose(t.inverse(paddle.to_tensor(y)).numpy(), x,
                                   rtol=1e-5)
        r = D.ReshapeTransform((4,), (2, 2))
        z = r.forward(paddle.to_tensor(np.arange(4, dtype=np.float32)))
        assert z.shape == [2, 2]


class TestFFT:
    def test_fft_ifft_roundtrip(self):
        x = (np.random.randn(8, 16) + 1j * np.random.randn(8, 16)).astype(np.complex64)
        y = pfft.fft(paddle.to_tensor(x))
        np.testing.assert_allclose(y.numpy(), np.fft.fft(x), rtol=1e-3, atol=1e-4)
        back = pfft.ifft(y).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-4)

    def test_rfft_irfft(self):
        x = np.random.randn(4, 32).astype(np.float32)
        y = pfft.rfft(paddle.to_tensor(x))
        np.testing.assert_allclose(y.numpy(), np.fft.rfft(x).astype(np.complex64),
                                   rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(pfft.irfft(y).numpy(), x, rtol=1e-3, atol=1e-4)

    def test_hfft_ihfft(self):
        x = np.random.randn(20).astype(np.float32)
        spec = pfft.ihfft(paddle.to_tensor(x))
        np.testing.assert_allclose(spec.numpy(), np.fft.ihfft(x).astype(np.complex64),
                                   rtol=1e-3, atol=1e-4)
        back = pfft.hfft(spec, n=20).numpy()
        np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-3)

    def test_norms(self):
        x = np.random.randn(16).astype(np.float32)
        for norm in ("backward", "forward", "ortho"):
            y = pfft.fft(paddle.to_tensor(x.astype(np.complex64)), norm=norm)
            np.testing.assert_allclose(y.numpy(), np.fft.fft(x, norm=norm),
                                       rtol=1e-3, atol=1e-4)

    def test_2d_nd(self):
        x = (np.random.randn(3, 8, 8) + 1j * np.random.randn(3, 8, 8)).astype(np.complex64)
        np.testing.assert_allclose(pfft.fft2(paddle.to_tensor(x)).numpy(),
                                   np.fft.fft2(x), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(pfft.fftn(paddle.to_tensor(x)).numpy(),
                                   np.fft.fftn(x), rtol=1e-3, atol=1e-3)
        xr = np.random.randn(3, 8, 8).astype(np.float32)
        np.testing.assert_allclose(pfft.rfft2(paddle.to_tensor(xr)).numpy(),
                                   np.fft.rfft2(xr).astype(np.complex64),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(
            pfft.irfft2(pfft.rfft2(paddle.to_tensor(xr))).numpy(), xr,
            rtol=1e-3, atol=1e-3)

    def test_helpers(self):
        np.testing.assert_allclose(pfft.fftfreq(8, d=0.5).numpy(),
                                   np.fft.fftfreq(8, d=0.5), rtol=1e-6)
        np.testing.assert_allclose(pfft.rfftfreq(8).numpy(), np.fft.rfftfreq(8),
                                   rtol=1e-6)
        x = np.arange(8, dtype=np.float32)
        np.testing.assert_allclose(pfft.fftshift(paddle.to_tensor(x)).numpy(),
                                   np.fft.fftshift(x))
        np.testing.assert_allclose(
            pfft.ifftshift(pfft.fftshift(paddle.to_tensor(x))).numpy(), x)

    def test_fft_grad(self):
        # d/dx sum(|rfft(x)|^2) should match numeric finite difference
        x = paddle.to_tensor(np.random.randn(16).astype(np.float32))
        x.stop_gradient = False
        y = pfft.rfft(x)
        mag = (y.real() * y.real() + y.imag() * y.imag()).sum()
        mag.backward()
        g = x.grad.numpy()

        def f(v):
            s = np.fft.rfft(v)
            return float((s.real**2 + s.imag**2).sum())
        xn = x.numpy()
        num = np.zeros_like(xn)
        eps = 1e-3
        for i in range(16):
            xp = xn.copy(); xp[i] += eps
            xm = xn.copy(); xm[i] -= eps
            num[i] = (f(xp) - f(xm)) / (2 * eps)
        np.testing.assert_allclose(g, num, rtol=2e-2, atol=2e-2)


class TestSignal:
    def test_frame_overlap_add_roundtrip(self):
        x = np.arange(1, 17, dtype=np.float32)
        f = psignal.frame(paddle.to_tensor(x), 4, 4)  # non-overlapping
        assert f.shape == [4, 4]
        back = psignal.overlap_add(f, 4).numpy()
        np.testing.assert_allclose(back, x)

    def test_frame_values(self):
        x = np.arange(10, dtype=np.float32)
        f = psignal.frame(paddle.to_tensor(x), 4, 2).numpy()  # (4, num_frames=4)
        assert f.shape == (4, 4)
        np.testing.assert_allclose(f[:, 0], [0, 1, 2, 3])
        np.testing.assert_allclose(f[:, 1], [2, 3, 4, 5])

    def test_overlap_add_sums(self):
        frames = np.ones((4, 3), dtype=np.float32)  # frame_len 4, 3 frames
        out = psignal.overlap_add(paddle.to_tensor(frames), 2).numpy()
        # length = 2*2+4 = 8; middles overlap twice
        np.testing.assert_allclose(out, [1, 1, 2, 2, 2, 2, 1, 1])

    def test_stft_istft_roundtrip(self):
        sr = 512
        t = np.arange(sr, dtype=np.float32) / sr
        x = np.sin(2 * np.pi * 40 * t) + 0.5 * np.sin(2 * np.pi * 80 * t)
        win = np.hanning(128).astype(np.float32)
        spec = psignal.stft(paddle.to_tensor(x), n_fft=128, hop_length=32,
                            window=paddle.to_tensor(win))
        assert spec.shape == [65, (512 // 32) + 1]
        back = psignal.istft(spec, n_fft=128, hop_length=32,
                             window=paddle.to_tensor(win), length=sr).numpy()
        np.testing.assert_allclose(back, x, atol=1e-3)

    def test_stft_matches_scipy(self):
        from scipy import signal as ss
        x = np.random.randn(256).astype(np.float32)
        win = np.hanning(64).astype(np.float32)
        spec = psignal.stft(paddle.to_tensor(x), n_fft=64, hop_length=16,
                            window=paddle.to_tensor(win)).numpy()
        _, _, ref = ss.stft(x, window=win, nperseg=64, noverlap=48,
                            boundary='even', padded=False, return_onesided=True)
        # scipy scales by 1/win.sum(); undo
        ref = ref * win.sum()
        np.testing.assert_allclose(spec, ref.astype(np.complex64), atol=2e-3)

    def test_batched(self):
        x = np.random.randn(3, 200).astype(np.float32)
        spec = psignal.stft(paddle.to_tensor(x), n_fft=64, hop_length=32)
        assert spec.shape[0] == 3
        out = psignal.istft(spec, n_fft=64, hop_length=32, length=200)
        assert out.shape == [3, 200]
