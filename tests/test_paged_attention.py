"""Paged KV-cache decode stack (ops/pallas/paged_attention.py +
models/gpt.py decode path): kernel parity vs the dense gather reference
(Pallas interpreter on CPU), cache-append semantics (null page, donated
eager buffers), the autotune `paged_attn` op (impl axis + cross-process
disk-cache hit), and greedy-decode parity paged-vs-cacheless.

fast-sibling: every class here is tier-1 except the timing probe
(TestSuperLinear.test_per_token_cost_flat_vs_dense_slow), whose fast
sibling is test_paged_growth_structure.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig, PagedKVCache
from paddle_tpu.ops.pallas import autotune
from paddle_tpu.ops.pallas import paged_attention as pa

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def interp(monkeypatch):
    """Kernel under the Pallas interpreter + force-mode tuning with a
    private cache dir (the CI shortcut)."""
    autotune.reset_for_tests()
    monkeypatch.setattr(pa, "_INTERPRET", True)
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "force")
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_REPEATS", "1")
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_MAX_CONFIGS", "3")
    monkeypatch.delenv("PADDLE_TPU_AUTOTUNE_CACHE_DIR", raising=False)
    yield
    autotune.reset_for_tests()


def _rand_pool(rng, B, H, D, page_size, num_pages, pages_per_seq):
    q = jnp.asarray(rng.normal(size=(B, H, D)).astype(np.float32))
    kp = jnp.asarray(rng.normal(
        size=(num_pages, page_size, H, D)).astype(np.float32))
    vp = jnp.asarray(rng.normal(
        size=(num_pages, page_size, H, D)).astype(np.float32))
    bt = jnp.asarray(rng.integers(
        0, num_pages, (B, pages_per_seq)).astype(np.int32))
    return q, kp, vp, bt


class TestKernelParity:
    def test_pallas_matches_dense_reference(self, interp):
        rng = np.random.default_rng(0)
        q, kp, vp, bt = _rand_pool(rng, 3, 12, 64, 8, 10, 4)
        cl = jnp.asarray(np.array([13, 5, 32], np.int32))
        pa._stats["pallas"] = pa._stats["xla"] = 0
        out = pa.paged_attention(q, kp, vp, bt, cl)
        assert pa._stats["pallas"] == 1, "Pallas path not taken"
        ref = pa.paged_attention_xla(q, kp, vp, bt, cl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=0, atol=2e-6)

    def test_zero_context_slot_outputs_zero(self, interp):
        """An idle serving slot (ctx=0, block table on the null page)
        must output exactly zero on BOTH impls."""
        rng = np.random.default_rng(1)
        q, kp, vp, bt = _rand_pool(rng, 2, 4, 64, 8, 6, 3)
        cl = jnp.asarray(np.array([0, 17], np.int32))
        out = pa.paged_attention(q, kp, vp, bt, cl)
        ref = pa.paged_attention_xla(q, kp, vp, bt, cl)
        assert np.all(np.asarray(out)[0] == 0.0)
        assert np.all(np.asarray(ref)[0] == 0.0)
        np.testing.assert_allclose(np.asarray(out)[1], np.asarray(ref)[1],
                                   atol=2e-6)

    def test_partial_last_page_is_masked(self, interp):
        """Positions past ctx on the last live page must not contribute:
        poisoning them with huge values changes nothing."""
        rng = np.random.default_rng(2)
        q, kp, vp, bt = _rand_pool(rng, 1, 4, 64, 8, 6, 3)
        cl = jnp.asarray(np.array([11], np.int32))  # page 1 holds 3 live
        out = pa.paged_attention(q, kp, vp, bt, cl)
        last_page = int(np.asarray(bt)[0, 1])
        kp2 = kp.at[last_page, 3:].set(1e4)
        vp2 = vp.at[last_page, 3:].set(1e4)
        out2 = pa.paged_attention(q, kp2, vp2, bt, cl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2),
                                   atol=2e-6)

    def test_head_split_configs_agree(self, interp):
        """Every heads candidate regroups grid programs only — outputs
        are identical across head-block choices."""
        rng = np.random.default_rng(3)
        q, kp, vp, bt = _rand_pool(rng, 2, 8, 64, 8, 8, 3)
        cl = jnp.asarray(np.array([20, 9], np.int32))
        outs = [
            np.asarray(pa._paged_attn_pallas(q, kp, vp, bt, cl,
                                             1.0 / 8.0, bh, interpret=True))
            for bh in (2, 4, 8)]
        for o in outs[1:]:
            np.testing.assert_array_equal(outs[0], o)

    def test_cpu_without_interpret_takes_xla(self):
        rng = np.random.default_rng(4)
        q, kp, vp, bt = _rand_pool(rng, 1, 2, 32, 4, 4, 2)
        cl = jnp.asarray(np.array([5], np.int32))
        pa._stats["pallas"] = pa._stats["xla"] = 0
        pa.paged_attention(q, kp, vp, bt, cl)
        assert pa._stats["xla"] == 1 and pa._stats["pallas"] == 0


class TestCacheAppend:
    def test_append_lands_in_block_table_slot(self):
        page_size = 4
        kp = jnp.zeros((5, page_size, 2, 8), jnp.float32)
        vp = jnp.zeros_like(kp)
        bt = jnp.asarray(np.array([[2, 3], [4, 1]], np.int32))
        cl = jnp.asarray(np.array([5, 2], np.int32))
        k_new = jnp.ones((2, 2, 8), jnp.float32)
        v_new = 2.0 * jnp.ones((2, 2, 8), jnp.float32)
        kp, vp = pa.cache_append(kp, vp, k_new, v_new, bt, cl)
        kp_np = np.array(kp)
        # row 0: ctx 5 -> page bt[0, 1]=3, offset 1
        assert np.all(kp_np[3, 1] == 1.0)
        # row 1: ctx 2 -> page bt[1, 0]=4, offset 2
        assert np.all(kp_np[4, 2] == 1.0)
        assert np.all(np.asarray(vp)[3, 1] == 2.0)
        # nothing else touched
        kp_np[3, 1] = kp_np[4, 2] = 0.0
        assert np.all(kp_np == 0.0)

    def test_inactive_rows_write_only_the_null_page(self):
        page_size = 4
        kp = jnp.zeros((4, page_size, 2, 8), jnp.float32)
        vp = jnp.zeros_like(kp)
        bt = jnp.asarray(np.array([[1, 2], [3, 0]], np.int32))
        cl = jnp.asarray(np.array([0, 1], np.int32))
        active = jnp.asarray(np.array([False, True]))
        k_new = jnp.ones((2, 2, 8), jnp.float32)
        kp, vp = pa.cache_append(kp, vp, k_new, k_new, bt, cl, active)
        kp_np = np.asarray(kp)
        assert np.all(kp_np[3, 1] == 1.0)    # the active row's write
        assert np.all(kp_np[1] == 0.0)       # inactive row's pages clean
        assert np.all(kp_np[2] == 0.0)

    def test_eager_append_donates_the_pool(self):
        """The eager append routes through the donating jit: the passed
        pool buffer is consumed (deleted), not copied per token."""
        kp = jnp.zeros((4, 4, 2, 8), jnp.float32)
        vp = jnp.zeros_like(kp)
        bt = jnp.zeros((1, 2), jnp.int32)
        cl = jnp.zeros((1,), jnp.int32)
        k_new = jnp.ones((1, 2, 8), jnp.float32)
        kp2, vp2 = pa.cache_append(kp, vp, k_new, k_new, bt, cl)
        assert kp2 is not kp
        assert kp.is_deleted(), "pool was copied, not donated"
        assert vp.is_deleted()

    def test_prefill_append_scatter(self):
        page_size = 4
        kp = jnp.zeros((6, page_size, 2, 8), jnp.float32)
        vp = jnp.zeros_like(kp)
        page_ids = jnp.asarray(np.array([2, 5, 0], np.int32))
        L = 9
        k_seq = jnp.broadcast_to(
            jnp.arange(1, L + 1, dtype=jnp.float32)[:, None, None],
            (L, 2, 8))
        kp, vp = pa.prefill_append(kp, vp, k_seq, k_seq, page_ids,
                                   jnp.int32(6))  # only 6 of 9 live
        kp_np = np.asarray(kp)
        assert np.all(kp_np[2, 0] == 1.0) and np.all(kp_np[2, 3] == 4.0)
        assert np.all(kp_np[5, 0] == 5.0) and np.all(kp_np[5, 1] == 6.0)
        # padded positions (7, 8, 9) landed on the null page, not page 5
        assert np.all(kp_np[5, 2:] == 0.0)


class TestAutotunePagedAttn:
    def test_impl_axis_candidates_include_xla(self, interp, monkeypatch):
        """The candidate space registered for op paged_attn carries the
        measured impl axis: Pallas head-block shapes AND the impl=0 XLA
        gather, conv_bn-style."""
        seen = {}
        real = autotune.get_config

        def spy(op, key, candidates, default, bench, interpret=False):
            if op == "paged_attn":
                seen["cands"] = list(candidates)
            return real(op, key, candidates, default, bench,
                        interpret=interpret)

        monkeypatch.setattr(autotune, "get_config", spy)
        rng = np.random.default_rng(5)
        q, kp, vp, bt = _rand_pool(rng, 1, 8, 64, 8, 4, 2)
        pa.paged_attention(q, kp, vp, bt, jnp.asarray(np.array([9],
                                                              np.int32)))
        impls = {c["impl"] for c in seen["cands"]}
        assert impls == {0, 1}
        heads = {c["heads"] for c in seen["cands"] if c["impl"] == 1}
        assert 8 in heads and len(heads) > 1

    def test_tuned_log_names_the_op(self, interp):
        rng = np.random.default_rng(6)
        q, kp, vp, bt = _rand_pool(rng, 1, 4, 64, 8, 4, 2)
        pa.paged_attention(q, kp, vp, bt,
                           jnp.asarray(np.array([7], np.int32)))
        ops = [t["op"] for t in autotune.tuned_log()]
        assert "paged_attn" in ops


_XPROC_CHILD = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import json
import numpy as np
import jax.numpy as jnp
from paddle_tpu.ops.pallas import autotune
from paddle_tpu.ops.pallas import paged_attention as pa
pa._INTERPRET = True
rng = np.random.default_rng(0)
q = jnp.asarray(rng.normal(size=(2, 4, 64)).astype(np.float32))
kp = jnp.asarray(rng.normal(size=(4, 8, 4, 64)).astype(np.float32))
bt = jnp.zeros((2, 2), jnp.int32)
cl = jnp.asarray(np.array([9, 3], np.int32))
out = pa.paged_attention(q, kp, kp, bt, cl)
print("RESULT" + json.dumps({
    "o0": float(np.asarray(out).ravel()[0]),
    "hit": autotune._M_EVENTS.value(event="hit", op="paged_attn"),
    "miss": autotune._M_EVENTS.value(event="miss", op="paged_attn"),
    "tunes": autotune._M_TUNES.value(op="paged_attn"),
    "persist": autotune._M_EVENTS.value(event="persist", op="paged_attn"),
}))
"""


class TestPagedAttnCrossProcessCache:
    """Acceptance: op paged_attn shows a cross-process autotune cache
    hit — process A tunes + persists, process B resolves with ZERO
    probes (no tune, hit counter > 0)."""

    @staticmethod
    def _run_child(cache_dir):
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu",
                    "PADDLE_TPU_AUTOTUNE": "force",
                    "PADDLE_TPU_AUTOTUNE_CACHE_DIR": str(cache_dir),
                    "PADDLE_TPU_AUTOTUNE_REPEATS": "1",
                    "PADDLE_TPU_AUTOTUNE_MAX_CONFIGS": "3"})
        proc = subprocess.run(
            [sys.executable, "-c", _XPROC_CHILD], cwd=REPO, env=env,
            capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-1500:]
        for line in proc.stdout.splitlines():
            if line.startswith("RESULT"):
                return json.loads(line[len("RESULT"):])
        raise AssertionError(f"child printed no RESULT: {proc.stdout!r}")

    def test_tune_once_hit_everywhere(self, tmp_path):
        a = self._run_child(tmp_path)
        assert a["miss"] == 1 and a["tunes"] == 1 and a["persist"] == 1
        b = self._run_child(tmp_path)
        assert b["o0"] == a["o0"]
        assert b["hit"] > 0 and b["miss"] == 0 and b["tunes"] == 0


class TestGPTDecodeParity:
    """Greedy-token parity: the paged incremental decode must produce
    the SAME tokens as the cacheless full-recompute path (bit-exact on
    this box — both paths run f32 XLA on CPU; TPU tolerance is the
    kernels' documented f32-accumulation ULP)."""

    def _model(self):
        paddle.seed(0)
        cfg = GPTConfig.tiny()
        m = GPT(cfg)
        m.eval()
        return m, cfg

    @pytest.mark.slow  # dense-vs-paged walk; prefill/contract siblings stay fast
    def test_greedy_tokens_match_dense(self):
        m, cfg = self._model()
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(
            rng.integers(1, cfg.vocab_size, (2, 12)).astype("int32"))
        dense = np.asarray(m.generate_dense(ids, 8).data)
        paged = np.asarray(m.generate_paged(ids, 8, page_size=8).data)
        np.testing.assert_array_equal(dense, paged)

    @pytest.mark.slow  # interpret-mode kernel walk; prefill/contract/bucketed
    def test_greedy_parity_on_pallas_interpret(self, interp):  # stay fast
        """Same parity with the decode attention on the Pallas kernel
        (interpret mode): tokens still match the dense path."""
        m, cfg = self._model()
        rng = np.random.default_rng(1)
        ids = paddle.to_tensor(
            rng.integers(1, cfg.vocab_size, (1, 9)).astype("int32"))
        pa._stats["pallas"] = 0
        paged = np.asarray(m.generate_paged(ids, 6, page_size=8).data)
        assert pa._stats["pallas"] > 0, "decode did not use the kernel"
        dense = np.asarray(m.generate_dense(ids, 6).data)
        np.testing.assert_array_equal(dense, paged)

    def test_zero_new_tokens_matches_dense_contract(self):
        """Review regression: generate_paged(ids, 0) returned [B, L+1]
        (prefill's token appended before the budget check) while
        generate_dense returned [B, L]."""
        m, cfg = self._model()
        rng = np.random.default_rng(9)
        ids = paddle.to_tensor(
            rng.integers(1, cfg.vocab_size, (1, 6)).astype("int32"))
        assert tuple(m.generate_paged(ids, 0).shape) == (1, 6)
        assert tuple(m.generate_dense(ids, 0).shape) == (1, 6)

    def test_prefill_matches_training_forward_logits(self):
        """The prefill's last-position logits equal the training
        forward's — one source of truth for the first generated token."""
        m, cfg = self._model()
        rng = np.random.default_rng(2)
        ids_np = rng.integers(1, cfg.vocab_size, (1, 10)).astype("int32")
        ids = paddle.to_tensor(ids_np)
        full = np.asarray(m(ids).data)[0, -1]
        cache = m.init_cache(1, 32, page_size=8)
        import jax.numpy as jnp2
        cache.block_tables = jnp2.asarray(
            np.arange(1, 5, dtype=np.int32)[None])
        logits, cache = m.forward_prefill(ids, cache, 0, 10)
        np.testing.assert_allclose(np.asarray(logits.data)[0], full,
                                   rtol=1e-5, atol=1e-5)
        assert int(np.asarray(cache.context_lens)[0]) == 10

    def test_bucketed_prefill_padding_is_inert(self):
        """Padding the prompt to a shape bucket must not change the
        prefilled K/V or the last-position logits."""
        m, cfg = self._model()
        rng = np.random.default_rng(3)
        ids_np = rng.integers(1, cfg.vocab_size, (1, 7)).astype("int32")
        padded = np.zeros((1, 16), np.int32)
        padded[:, :7] = ids_np

        def run(arr):
            cache = m.init_cache(1, 32, page_size=8)
            import jax.numpy as jnp2
            cache.block_tables = jnp2.asarray(
                np.arange(1, 5, dtype=np.int32)[None])
            logits, cache = m.forward_prefill(
                paddle.to_tensor(arr), cache, 0, 7)
            return np.asarray(logits.data), \
                np.asarray(cache.k_pages[0])

        lo_a, kp_a = run(ids_np)
        lo_b, kp_b = run(padded)
        np.testing.assert_allclose(lo_a, lo_b, rtol=1e-6, atol=1e-6)
        # real pages identical; page 0 (the null page) is the designated
        # dump for padded positions' K/V and legitimately differs
        np.testing.assert_array_equal(kp_a[1:], kp_b[1:])


class TestSuperLinear:
    """Acceptance: per-token decode cost ~flat as context grows on the
    paged path while the cacheless path grows with context length."""

    def _model(self):
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=2048, max_position_embeddings=512,
                        hidden_size=128, num_layers=2, num_heads=4,
                        dropout=0.0, attn_dropout=0.0)
        m = GPT(cfg)
        m.eval()
        return m

    def test_paged_growth_structure(self):
        """Fast sibling: the A/B probe produces well-formed rows and the
        paged executable is context-INDEPENDENT by construction — the
        decode step compiled once serves every context length (no
        retrace as ctx grows), which is what makes its per-token cost
        flat."""
        import bench
        m = self._model()
        ab = bench._paged_vs_dense_ab(m, (16, 32), page_size=8,
                                      n_tokens=2, dense_iters=1)
        assert [r["ctx"] for r in ab["rows"]] == [16, 32]
        for r in ab["rows"]:
            assert r["paged_ms_per_token"] > 0
            assert r["dense_ms_per_token"] > 0

    @pytest.mark.slow
    def test_per_token_cost_flat_vs_dense_slow(self):
        """The measured acceptance A/B at CI scale: over a 4x context
        growth the dense per-token cost must grow markedly while the
        paged per-token cost stays ~flat (generous margins: CPU wall
        clocks on a busy CI box)."""
        import bench
        m = self._model()
        ab = bench._paged_vs_dense_ab(m, (64, 128, 256), page_size=8,
                                      n_tokens=6, dense_iters=3)
        assert ab["dense_growth"] > 1.4, ab
        assert ab["paged_growth"] < ab["dense_growth"] / 1.3, ab
        assert ab["speedup_at_max_ctx"] > 1.0, ab
