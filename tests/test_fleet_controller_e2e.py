"""Slow multi-process e2e for the self-driving fleet controller: the
full observe -> diagnose -> act loop through `tools/elastic_run.py
--controller`, with real supervisors, a real rendezvous store, real
digests, and the sharded coordinated checkpoint backend in one shared
directory.

Chaos evict/readmit: a 2-host fleet where host 1's trainer is
delay-faulted via the `fleet.step` `delay` kind (the PR-6 chaos hook).
The controller confirms the straggler over consecutive collect windows,
EVICTS it (every supervisor relaunches its trainer at N-1 with
re-densified ranks; the evicted host's supervisor holds on probation),
the surviving host resumes from the fleet-committed step and finishes
the work bit-identically to an unfaulted reference; once the probation
heartbeat has been fresh past the readmission cooldown the fleet scales
back to N — the delay fault "clears" because controller relaunches land
at generation >= GEN_STRIDE, where the chaos role disarms itself.

Dry-run: the same delay-faulted fleet under `--controller=dry-run` logs
the confirmed eviction decision (outcome=dry_run) and takes NO action:
no controller relaunch, generation stays 0, the fleet finishes at N.

Fleet-wide rollback: both hosts' weights deterministically poison to NaN
at one step (a bad batch in data-parallel reaches everyone); host 1's
HealthMonitor (action="fleet") trips and pins `diverged` into its
digest. The controller escalates to a COORDINATED rollback: every
supervisor hard-kills its trainer and relaunches under
PADDLE_TPU_RESUME_VALID_ONLY=1, so the fleet negotiates the last
numerically-valid committed step (the CRC-valid NaN checkpoints are
walked past on every host) and finishes with exact weight equality
across hosts, equal to a never-poisoned reference.

fast-sibling: tests/test_fleet_controller.py (debounce/hysteresis,
readmission, rollback policy, command bus, supervisor command
application, budget reset, valid-only resume) — keep those green in
tier-1; this file is the slow integration proof.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.distributed.store import TCPStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow

# Deterministic manual-loop trainer, shared by every scenario.
# argv: ckpt_dir out_json target_step. World/rank/master come from the
# trainer env contract that tools/elastic_run.py exports; chaos roles
# (CHAOS_ROLE=delay|poison) only arm in the ORIGINAL generation — a
# controller relaunch runs at generation >= GEN_STRIDE (1000) and the
# fault "clears", which is exactly how a transient bad host behaves.
_TRAINER = r"""
import json, os, sys, time

CKPT, OUT, TARGET = sys.argv[1], sys.argv[2], int(sys.argv[3])
GEN = int(os.environ.get("PADDLE_TPU_ELASTIC_RESTART_NUM", "0"))
ROLE = os.environ.get("CHAOS_ROLE", "") if GEN < 1000 else ""
if ROLE == "delay":
    # straggle: every note_step sleeps PADDLE_TPU_FAULT_DELAY (set by
    # the test) — the digest's rolling wall inflates like a slow host's
    os.environ["PADDLE_TPU_FAULT_SPEC"] = "fleet.step=100000:delay"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np
import jax.numpy as jnp
import paddle_tpu  # noqa: F401  (arms the fault injector from the env)
from paddle_tpu.distributed.checkpoint import coordinator_from_env
from paddle_tpu.distributed.sharded_checkpoint import (
    ShardedCheckpointManager)
from paddle_tpu.distributed.fleet.telemetry import reporter_from_env
from paddle_tpu.profiler import health
from paddle_tpu.profiler.metrics import default_registry

world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
step_sleep = float(os.environ.get("CHAOS_STEP_SLEEP", "0.01"))
save_every = int(os.environ.get("CHAOS_SAVE_EVERY", "3"))
# both hosts poison deterministically (a bad batch reaches every DP
# rank) but only the CHAOS_ROLE=poison host runs the health monitor
poison_at = int(os.environ.get("CHAOS_POISON_AT", "0")) if GEN < 1000 else 0

mgr = ShardedCheckpointManager(CKPT, coordinator=coordinator_from_env(),
                               keep_last_n=100)
reporter = reporter_from_env()
monitor = health.HealthMonitor(action="fleet", cooldown_steps=10 ** 9) \
    if ROLE == "poison" else None


def update(w, step):
    s = np.float32(step)
    return np.float32(0.98) * w + np.float32(step % 7) * np.float32(0.01) \
        + np.sin(s) * np.float32(0.001)


res = mgr.load_latest()
if res is not None:
    state, step = res
    w = np.asarray(state["w"], np.float32).copy()
else:
    w, step = np.zeros(8, np.float32), 0

while step < TARGET:
    step += 1
    w = update(w, step)
    if poison_at and step == poison_at:
        w = w + np.float32("nan")
    time.sleep(step_sleep)
    if reporter is not None:
        reporter.note_step(step)
    if monitor is not None:
        monitor.observe(loss=float(np.square(w).mean()), step=step)
    if step % save_every == 0 or step == TARGET:
        mgr.save({"w": jnp.asarray(w), "step": step}, step)

# post-evict N-1 incarnation: hold at the target publishing digests until
# the controller readmits the fleet (our supervisor then relaunches us)
while world == 1 and os.environ.get("CHAOS_IDLE_AT_TARGET") == "1":
    if reporter is not None:
        reporter.note_step(step)
    time.sleep(0.2)

with open(OUT, "w") as f:
    json.dump({"w": w.tolist(), "step": step, "world": world, "rank": rank,
               "gen": GEN,
               "cache_dir": os.environ.get("PADDLE_TPU_COMPILE_CACHE_DIR"),
               "metrics": default_registry().snapshot()}, f)
"""


def _reference(target):
    """The unfaulted trajectory: pure function of the step count."""
    w = np.zeros(8, np.float32)
    for step in range(1, target + 1):
        s = np.float32(step)
        w = np.float32(0.98) * w + np.float32(step % 7) * np.float32(0.01) \
            + np.sin(s) * np.float32(0.001)
    return w


def _base_env(extra=None):
    env = dict(os.environ)
    for k in ("PADDLE_TPU_FAULT_SPEC", "PADDLE_CURRENT_ENDPOINT",
              "PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM", "MASTER_ADDR",
              "MASTER_PORT", "PADDLE_TPU_EVENT_LOG",
              "PADDLE_TPU_METRICS_PORT", "PADDLE_TPU_COMPILE_CACHE_DIR",
              "PADDLE_TPU_ELASTIC_RESTART_NUM"):
        env.pop(k, None)
    env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
                "PADDLE_TPU_CONTROLLER_POLL_SEC": "0.25",
                "PADDLE_TPU_DIGEST_INTERVAL": "0.1",
                "PADDLE_TPU_CKPT_BARRIER_TIMEOUT": "20",
                "PADDLE_TPU_CKPT_RESUME_TIMEOUT": "60",
                "PADDLE_TPU_ELASTIC_BACKOFF": "0.2"})
    env.update(extra or {})
    return env


def _supervisor(tmp_path, master_port, rank, trainer_args, env,
                controller=None):
    cmd = [sys.executable, os.path.join(REPO, "tools", "elastic_run.py"),
           "--np", "2", "--rank", str(rank),
           "--master", f"127.0.0.1:{master_port}",
           "--max-restarts", "3"]
    if controller:
        cmd.append(f"--controller={controller}" if controller != "on"
                   else "--controller")
    cmd += ["--", sys.executable, str(tmp_path / "train.py")]
    cmd += [str(a) for a in trainer_args]
    return subprocess.Popen(cmd, env=env)


def _events(path, kind=None):
    out = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out


def _decisions(path, policy=None, outcome=None):
    return [e for e in _events(path, kind="controller_decision")
            if e.get("action") != "relaunch_observed"
            and (policy is None or e.get("policy") == policy)
            and (outcome is None or e.get("outcome") == outcome)]


def _wait_all(procs, timeout):
    deadline = time.monotonic() + timeout
    try:
        for p in procs:
            left = max(1.0, deadline - time.monotonic())
            assert p.wait(timeout=left) == 0, \
                f"supervisor exited rc={p.returncode}"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()


def _snapshot_total(snap, name, **labels):
    vals = snap.get(name, {}).get("values", [])
    return sum(v["value"] for v in vals
               if all(v["labels"].get(k) == lv for k, lv in labels.items()))


class TestChaosEvictReadmit:
    def test_straggler_evicted_then_readmitted(self, tmp_path):
        """The acceptance chaos e2e: delay-fault one host -> controller
        confirms -> evicts -> the N-1 fleet resumes from the
        fleet-committed step and finishes bit-identically -> the host is
        readmitted and the fleet ends back at N."""
        (tmp_path / "train.py").write_text(_TRAINER)
        target = 40
        master = TCPStore("127.0.0.1", 0, is_master=True)
        ev0 = tmp_path / "sup0_events.jsonl"
        ev1 = tmp_path / "sup1_events.jsonl"
        cache = tmp_path / "jaxcache"
        try:
            common = {"CHAOS_IDLE_AT_TARGET": "1",
                      "PADDLE_TPU_CONTROLLER_CONFIRM_WINDOWS": "2",
                      "PADDLE_TPU_CONTROLLER_READMIT_SEC": "2.5"}
            p0 = _supervisor(
                tmp_path, master.port, 0,
                [tmp_path / "ckpt", tmp_path / "out0.json", target],
                _base_env({**common, "PADDLE_TPU_EVENT_LOG": str(ev0),
                           "PADDLE_TPU_COMPILE_CACHE_DIR": str(cache)}),
                controller="on")
            p1 = _supervisor(
                tmp_path, master.port, 1,
                [tmp_path / "ckpt", tmp_path / "out1.json", target],
                _base_env({**common, "PADDLE_TPU_EVENT_LOG": str(ev1),
                           "CHAOS_ROLE": "delay",
                           "PADDLE_TPU_FAULT_DELAY": "0.3"}))
            _wait_all([p0, p1], timeout=240)
        finally:
            master.stop()

        # one confirmed eviction decision + one readmission, both applied
        evicts = _decisions(ev0, policy="straggler_evict",
                            outcome="applied")
        assert len(evicts) == 1, _decisions(ev0)
        assert evicts[0]["target"] == "trainer-1"
        assert evicts[0]["np"] == 1
        assert evicts[0]["evidence"]["windows"] >= 2  # debounce confirmed
        readmits = _decisions(ev0, policy="straggler_readmit",
                              outcome="applied")
        assert len(readmits) == 1
        assert readmits[0]["np"] == 2
        # the controller observed the relaunched fleet's first step
        observed = [e for e in _events(ev0, kind="controller_decision")
                    if e.get("action") == "relaunch_observed"]
        assert observed and all(
            e["relaunch_to_first_step_s"] >= 0 for e in observed)
        # the supervisors applied the commands as controller relaunches
        # (host 1's supervisor held, then readmitted)
        assert any(e.get("reason") == "controller_evict"
                   for e in _events(ev1, kind="elastic_restart"))
        assert any(e.get("reason") == "controller_readmit"
                   for e in _events(ev1, kind="elastic_restart"))

        ref = _reference(target)
        for r in range(2):
            with open(tmp_path / f"out{r}.json") as f:
                doc = json.load(f)
            # the fleet ended back at N with controller-driven generations
            assert doc["world"] == 2
            assert doc["gen"] >= 1000, doc["gen"]
            assert doc["step"] == target
            # compile-cache prewarm propagated through the relaunch env
            assert doc["cache_dir"] == str(cache)
            # bit-identical to the unfaulted reference trajectory
            assert np.array_equal(
                np.asarray(doc["w"], np.float32), ref), \
                f"host {r} diverged from the reference"

    def test_dry_run_logs_decision_but_takes_no_action(self, tmp_path):
        """--controller=dry-run: the confirmed decision is event-logged
        with outcome=dry_run and the fleet is left alone."""
        (tmp_path / "train.py").write_text(_TRAINER)
        target = 14
        master = TCPStore("127.0.0.1", 0, is_master=True)
        ev0 = tmp_path / "sup0_events.jsonl"
        ev1 = tmp_path / "sup1_events.jsonl"
        try:
            common = {"PADDLE_TPU_CONTROLLER_CONFIRM_WINDOWS": "2"}
            p0 = _supervisor(
                tmp_path, master.port, 0,
                [tmp_path / "ckpt", tmp_path / "out0.json", target],
                _base_env({**common, "PADDLE_TPU_EVENT_LOG": str(ev0)}),
                controller="dry-run")
            p1 = _supervisor(
                tmp_path, master.port, 1,
                [tmp_path / "ckpt", tmp_path / "out1.json", target],
                _base_env({**common, "PADDLE_TPU_EVENT_LOG": str(ev1),
                           "CHAOS_ROLE": "delay",
                           "PADDLE_TPU_FAULT_DELAY": "0.3"}))
            _wait_all([p0, p1], timeout=240)
        finally:
            master.stop()

        assert _decisions(ev0, policy="straggler_evict",
                          outcome="dry_run"), _decisions(ev0)
        assert _decisions(ev0, outcome="applied") == []
        # nobody was relaunched by the controller, on either host
        for ev in (ev0, ev1):
            assert not any(
                str(e.get("reason", "")).startswith("controller_")
                for e in _events(ev, kind="elastic_restart"))
        for r in range(2):
            with open(tmp_path / f"out{r}.json") as f:
                doc = json.load(f)
            assert doc["world"] == 2 and doc["gen"] == 0
            assert doc["step"] == target


class TestFleetWideRollback:
    def test_diverged_host_rolls_back_whole_fleet(self, tmp_path):
        """The acceptance rollback e2e: one host's monitor trips
        `diverged` -> the controller drives a coordinated rollback on ALL
        hosts to the same last numerically-valid committed step (the
        CRC-valid NaN checkpoints are skipped everywhere) -> exact weight
        equality across hosts afterward."""
        (tmp_path / "train.py").write_text(_TRAINER)
        target = 30
        master = TCPStore("127.0.0.1", 0, is_master=True)
        ev0 = tmp_path / "sup0_events.jsonl"
        ev1 = tmp_path / "sup1_events.jsonl"
        try:
            common = {"CHAOS_STEP_SLEEP": "0.2", "CHAOS_SAVE_EVERY": "2",
                      "CHAOS_POISON_AT": "5"}
            p0 = _supervisor(
                tmp_path, master.port, 0,
                [tmp_path / "ckpt", tmp_path / "out0.json", target],
                _base_env({**common, "PADDLE_TPU_EVENT_LOG": str(ev0)}),
                controller="on")
            p1 = _supervisor(
                tmp_path, master.port, 1,
                [tmp_path / "ckpt", tmp_path / "out1.json", target],
                _base_env({**common, "PADDLE_TPU_EVENT_LOG": str(ev1),
                           "CHAOS_ROLE": "poison"}))
            _wait_all([p0, p1], timeout=240)
        finally:
            master.stop()

        rollbacks = _decisions(ev0, policy="health_rollback",
                               outcome="applied")
        assert len(rollbacks) == 1, _decisions(ev0)
        assert rollbacks[0]["evidence"]["diverged"] == ["trainer-1"]
        assert rollbacks[0]["np"] == 2  # the whole fleet, not one host
        # every supervisor hard-relaunched on the rollback command
        for ev in (ev0, ev1):
            assert any(e.get("reason") == "controller_rollback"
                       for e in _events(ev, kind="elastic_restart"))

        ref = _reference(target)
        docs = {}
        for r in range(2):
            with open(tmp_path / f"out{r}.json") as f:
                docs[r] = json.load(f)
            doc = docs[r]
            assert doc["world"] == 2 and doc["gen"] >= 1000
            assert doc["step"] == target
            w = np.asarray(doc["w"], np.float32)
            assert np.all(np.isfinite(w)), f"host {r} finished nonfinite"
            # equal to the never-poisoned reference: the fleet resumed
            # BEFORE the poison step and replayed it clean
            assert np.array_equal(w, ref), \
                f"host {r} diverged from the reference"
            # the valid-only resume actually walked past NaN checkpoints
            assert _snapshot_total(
                doc["metrics"],
                "checkpoint_resume_skipped_nonfinite_total") >= 1
        # exact cross-host equality (implied by the reference equality,
        # stated explicitly because it is the acceptance criterion)
        assert np.array_equal(np.asarray(docs[0]["w"]),
                              np.asarray(docs[1]["w"]))
