"""Elastic + checkpoint subsystem tests (reference: elastic manager tests
`unittests/test_fleet_elastic_manager.py`, auto-checkpoint
`test_auto_checkpoint.py`, dist-save `auto_parallel` converter tests)."""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed import checkpoint as dist_ckpt
from paddle_tpu.distributed.fleet.elastic import (ELASTIC_EXIT_CODE,
                                                  ElasticManager,
                                                  ElasticStatus)
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.incubate.checkpoint import TrainEpochRange

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestDistCheckpoint:
    def test_roundtrip_plain(self, tmp_path):
        state = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                 "step": 7, "nested": {"b": np.ones(4, np.float32)}}
        p = str(tmp_path / "c.ckpt")
        dist_ckpt.save(state, p)
        back = dist_ckpt.load(p)
        np.testing.assert_array_equal(np.asarray(back["w"]), state["w"])
        assert back["step"] == 7

    def test_sharded_save_reshard_load(self, tmp_path):
        devs = np.array(jax.devices()[:8]).reshape(8)
        mesh1 = Mesh(devs, axis_names=("dp",))
        x = jax.device_put(np.arange(64, dtype=np.float32).reshape(8, 8),
                           NamedSharding(mesh1, P("dp", None)))
        p = str(tmp_path / "s.ckpt")
        dist_ckpt.save({"x": x}, p)
        # restore onto a DIFFERENT mesh: 2x4, dp axis now size 2
        mesh2 = Mesh(devs.reshape(2, 4), axis_names=("dp", "mp"))
        back = dist_ckpt.load(p, mesh=mesh2)
        arr = back["x"]
        np.testing.assert_array_equal(np.asarray(arr),
                                      np.arange(64).reshape(8, 8))
        assert arr.sharding.spec == P("dp", None)

    def test_reshard_missing_axis_replicates(self, tmp_path):
        devs = np.array(jax.devices()[:8])
        mesh1 = Mesh(devs.reshape(2, 4), axis_names=("dp", "mp"))
        x = jax.device_put(np.ones((4, 8), np.float32),
                           NamedSharding(mesh1, P(None, "mp")))
        p = str(tmp_path / "m.ckpt")
        dist_ckpt.save({"x": x}, p)
        mesh2 = Mesh(devs, axis_names=("dp",))  # no "mp" axis anymore
        back = dist_ckpt.load(p, mesh=mesh2)
        assert back["x"].sharding.spec == P(None, None)

    def test_async_save(self, tmp_path):
        p = str(tmp_path / "a.ckpt")
        dist_ckpt.save({"w": np.ones(3, np.float32)}, p, async_save=True)
        dist_ckpt.wait_all()
        assert os.path.exists(p)
        np.testing.assert_array_equal(np.asarray(dist_ckpt.load(p)["w"]),
                                      np.ones(3))

    def test_latest(self, tmp_path):
        for step in (3, 11, 7):
            dist_ckpt.save({"s": step}, str(tmp_path / f"ckpt_{step}"))
        assert dist_ckpt.latest(str(tmp_path)).endswith("ckpt_11")
        assert dist_ckpt.latest(str(tmp_path / "nope")) is None


class TestAutoCheckpoint:
    def _train(self, ckpt_dir, epochs, crash_at=None):
        """One 'job run': returns epochs actually executed."""
        paddle.seed(0)
        model = nn.Linear(4, 2)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())
        r = TrainEpochRange(epochs, name="job1", checkpoint_dir=ckpt_dir,
                            preemption_save=False)
        r.attach(model=model, optimizer=opt)
        ran = []
        for epoch in r:
            x = paddle.to_tensor(np.ones((2, 4), np.float32))
            loss = model(x).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            ran.append(epoch)
            if crash_at is not None and epoch == crash_at:
                raise KeyboardInterrupt  # simulated kill MID-epoch
        return ran, model

    def test_resume_after_crash(self, tmp_path):
        d = str(tmp_path)
        with pytest.raises(KeyboardInterrupt):
            self._train(d, epochs=6, crash_at=2)
        # epochs 0 and 1 were saved; the interrupted epoch 2 re-runs
        ran2, model2 = self._train(d, epochs=6)
        assert ran2 == [2, 3, 4, 5]

    def test_fresh_run_covers_all_epochs(self, tmp_path):
        ran, _ = self._train(str(tmp_path), epochs=3)
        assert ran == [0, 1, 2]


class TestElasticManager:
    def test_membership_and_heartbeats(self):
        master = TCPStore("127.0.0.1", 0, is_master=True)
        peer_store = TCPStore("127.0.0.1", master.port)
        m1 = ElasticManager(host_id="n1", ttl=1.0, np=2, store=master)
        m2 = ElasticManager(host_id="n2", ttl=1.0, np=2, store=peer_store)
        m1.join()
        m2.join()
        time.sleep(0.1)
        assert m1.alive_members() == ["n1", "n2"]
        # start watching while n2 is still alive, then let it die
        import threading
        result = {}

        def watch():
            result["status"] = m1.watch(timeout=5.0)

        t = threading.Thread(target=watch)
        t.start()
        time.sleep(0.3)
        m2.exit()  # stops beating + deletes its beat key
        t.join(timeout=10)
        assert not t.is_alive()
        assert result["status"] in (ElasticStatus.HOLD, ElasticStatus.RESTART)
        assert "n2" not in m1.alive_members()
        m1.exit()
        master.stop()

    def test_stable_membership_completes(self):
        master = TCPStore("127.0.0.1", 0, is_master=True)
        m1 = ElasticManager(host_id="solo", ttl=1.0, np=1, store=master)
        m1.join()
        assert m1.watch(timeout=1.0) == ElasticStatus.COMPLETED
        m1.exit()
        master.stop()


class TestElasticLaunchRestart:
    def test_exit_code_101_triggers_restart(self, tmp_path):
        """A worker exiting with ELASTIC_EXIT_CODE is redeployed by launch."""
        script = tmp_path / "flaky.py"
        marker = tmp_path / "ran_once"
        script.write_text(
            "import os, sys\n"
            f"m = {str(repr(str(marker)))}\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').write('x')\n"
            f"    sys.exit({ELASTIC_EXIT_CODE})\n"
            "print('recovered OK')\n")
        from paddle_tpu.distributed.launch.main import launch
        rc = launch(["--log_dir", str(tmp_path / "log"),
                     "--max_restart", "2", str(script)])
        assert rc == 0
        assert marker.exists()
