"""Pipelined heter-PS training + device-side hot-row embedding cache.

Covers the PR-4 sparse-path pipeline (`heter.py mode="pipelined"` +
`cache.py`): bounded staleness of the prefetched pulls, cache gather
correctness including eviction write-back / overflow / partial last
batches, chaos recovery of a faulted mid-pipeline pull, and the
multi-table one-round pull on the client.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fault, nn, optimizer
from paddle_tpu.distributed.ps import PSClient, PSServer
from paddle_tpu.distributed.ps.heter import HeterPSTrainStep
from paddle_tpu.models.wide_deep import WideDeep


@pytest.fixture()
def ps():
    server = PSServer(0)
    client = PSClient([server.endpoint])
    yield client
    client.stop_servers()


@pytest.fixture(autouse=True)
def _clean_faults():
    fault.reset()
    yield
    fault.reset()


def _data(n_batches=8, B=16, vocab=100, slots=4, seed=7, partial_at=None):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n_batches):
        b = 5 if i == partial_at else B
        ids = rng.integers(0, vocab, (b, slots))
        dense = rng.normal(size=(b, slots)).astype(np.float32)
        y = ((ids.sum(1) % 2) == 0).astype(np.float32)[:, None]
        out.append((paddle.to_tensor(ids.astype(np.int64)),
                    paddle.to_tensor(dense), paddle.to_tensor(y)))
    return out


def _trainer(client, mode="sync", cache_capacity=0, slots=4, lr=5e-2):
    paddle.seed(0)
    model = WideDeep(num_slots=slots, embedding_dim=8, dense_dim=slots,
                     hidden=32, client=client)
    opt = optimizer.SGD(learning_rate=lr, parameters=model.parameters())
    crit = nn.BCEWithLogitsLoss()
    step = HeterPSTrainStep(model, lambda o, y: crit(o, y), opt, mode=mode,
                            cache_capacity=cache_capacity)
    return model, step


def _run(step, data, prefetch=False):
    losses = []
    for i, batch in enumerate(data):
        losses.append(float(step(*batch)))
        if prefetch and i + 1 < len(data):
            step.prefetch(*data[i + 1])
    step.flush()
    return losses


def _server_rows(model, client, vocab):
    keys = np.arange(vocab, dtype=np.uint64)
    return {e._table_cfg.table_id:
            client.pull_sparse(e._table_cfg.table_id, keys).copy()
            for e in [*model.embeddings, model.wide]}


class TestPipelinedMode:
    def test_matches_sync_when_fully_cached(self, ps):
        """With every table cached, gradients are absorbed on-chip and
        there is no push to be stale against: pipelined losses must equal
        the sync-mode run step for step."""
        data = _data()
        _, s_sync = _trainer(ps, "sync")
        sync = _run(s_sync, data)
        s_sync.close()

        server2 = PSServer(0)
        client2 = PSClient([server2.endpoint])
        try:
            _, s_pipe = _trainer(client2, "pipelined", cache_capacity=256)
            pipe = _run(s_pipe, data, prefetch=True)
            s_pipe.close()
        finally:
            client2.stop_servers()
        np.testing.assert_allclose(pipe, sync, atol=1e-5)

    @pytest.mark.parametrize("prefetch", [False, True])
    def test_bounded_staleness(self, ps, monkeypatch, prefetch):
        """A pull for step t must observe every push through step t-2:
        outstanding push futures are drained before a new prepare may
        pull — inline for __call__-submitted prepares, chained onto the
        prefetch thread for prefetch()-issued ones (contract documented
        in heter.py; regression for the pipeline's staleness bound)."""
        import threading
        _, step = _trainer(ps, "pipelined")
        lock = threading.Lock()
        pushes_done = [0]
        pulls = []  # (pull_ordinal, pushes_done when the pull started)

        real_pull = HeterPSTrainStep._pull_round
        real_push = HeterPSTrainStep._push

        def rec_pull(pull_reqs):
            with lock:
                pulls.append(pushes_done[0])
            return real_pull(pull_reqs)

        def rec_push(self, grows, push_meta):
            real_push(self, grows, push_meta)
            with lock:
                pushes_done[0] += 1

        monkeypatch.setattr(HeterPSTrainStep, "_pull_round",
                            staticmethod(rec_pull))
        monkeypatch.setattr(HeterPSTrainStep, "_push", rec_push)
        data = _data(n_batches=8)
        _run(step, data, prefetch=prefetch)
        step.close()
        assert len(pulls) == len(data)
        for t, done in enumerate(pulls, start=1):
            # pushes for steps 1..t-2 must have completed before pull t
            assert done >= t - 2, (t, done, pulls)
            assert done <= t - 1, (t, done, pulls)

    def test_prefetch_batch_mismatch_raises(self, ps):
        _, step = _trainer(ps, "pipelined")
        data = _data(n_batches=3)
        step(*data[0])
        step.prefetch(*data[1])
        with pytest.raises(RuntimeError, match="prefetch"):
            step(*data[2])
        step.close()

    def test_prefetch_accepts_numpy_batches(self, ps):
        """The prefetch/step match is identity on the ORIGINAL batch
        objects: raw numpy inputs (converted to fresh device arrays on
        every call) must not trip a spurious mismatch."""
        _, step = _trainer(ps, "pipelined")
        rng = np.random.default_rng(11)
        data = [(rng.integers(0, 50, (8, 4)).astype(np.int64),
                 rng.normal(size=(8, 4)).astype(np.float32),
                 np.ones((8, 1), np.float32)) for _ in range(3)]
        losses = []
        for i, b in enumerate(data):
            losses.append(float(step(*b)))
            if i + 1 < len(data):
                step.prefetch(*data[i + 1])
        step.close()
        assert all(np.isfinite(l) for l in losses)

    @pytest.mark.slow
    def test_converges_on_learnable_task(self, ps):
        """Pipelined mode (staleness <= 1) still converges — the mirror of
        the async-mode convergence test."""
        rng = np.random.default_rng(3)
        vocab = 16
        ids_all = rng.integers(0, vocab, (256, 4))
        dense_all = rng.normal(size=(256, 4)).astype(np.float32)
        y_all = ((ids_all[:, 0] < vocab // 2)).astype(np.float32)[:, None]
        paddle.seed(0)
        model = WideDeep(num_slots=4, embedding_dim=8, dense_dim=4,
                         hidden=32, client=ps)
        opt = optimizer.Adam(learning_rate=5e-2,
                             parameters=model.parameters())
        crit = nn.BCEWithLogitsLoss()
        step = HeterPSTrainStep(model, lambda o, y: crit(o, y), opt,
                                mode="pipelined", cache_capacity=64)
        losses = []
        for ep in range(12):
            for s in range(0, 256, 64):
                losses.append(float(step(
                    paddle.to_tensor(ids_all[s:s + 64].astype(np.int64)),
                    paddle.to_tensor(dense_all[s:s + 64]),
                    paddle.to_tensor(y_all[s:s + 64]))))
        step.close()
        assert losses[-1] < 0.35, (losses[0], losses[-1])


class TestHotRowCache:
    VOCAB = 100

    def _rows_after_run(self, cache_capacity, partial_at=6):
        server = PSServer(0)
        client = PSClient([server.endpoint])
        try:
            model, step = _trainer(client, "sync",
                                   cache_capacity=cache_capacity)
            data = _data(vocab=self.VOCAB, partial_at=partial_at)
            losses = _run(step, data)
            stats = {t: dict(c.stats) for t, c in step.caches.items()}
            rows = _server_rows(model, client, self.VOCAB)
            step.close()
        finally:
            client.stop_servers()
        return losses, rows, stats

    def test_eviction_writeback_and_partial_batches(self):
        """Tiny capacity forces evictions mid-run (and overflow when a
        batch's unique count exceeds capacity); after flush the server
        must hold the same rows as an uncached run — deferred write-backs
        lose nothing. A partial last-ish batch rides along."""
        ref_losses, ref_rows, _ = self._rows_after_run(0)
        losses, rows, stats = self._rows_after_run(16)
        np.testing.assert_allclose(losses, ref_losses, atol=2e-4)
        assert any(s["eviction"] > 0 for s in stats.values()), stats
        assert any(s["writeback"] > 0 for s in stats.values()), stats
        for tid in ref_rows:
            np.testing.assert_allclose(rows[tid], ref_rows[tid], atol=1e-4)

    def test_hits_served_from_device(self, ps, monkeypatch):
        """Once rows are cached, repeated batches must pull NOTHING from
        the PS (the hit path is an on-chip gather)."""
        _, step = _trainer(ps, "sync", cache_capacity=256)
        data = _data(n_batches=2, seed=5)
        step(*data[0])
        pulled = []
        orig = PSClient.pull_sparse

        def spy(self, table_id, keys, handles=None):
            pulled.append(np.asarray(keys).size)
            return orig(self, table_id, keys, handles)

        monkeypatch.setattr(PSClient, "pull_sparse", spy)
        step(*data[0])  # same ids again: all hits
        assert sum(pulled) == 0, pulled
        step(*data[1])  # fresh ids: misses pull again
        assert sum(pulled) > 0
        total_hits = sum(c.stats["hit"] for c in step.caches.values())
        assert total_hits > 0
        step.close()

    def test_sum_table_cached_matches_uncached(self):
        """A "sum"/geo table (server OPT_SUM: w += g, lr ignored) is the
        lr = -1 case of the cache's local rule — cached and uncached runs
        must serve the same rows and land identical server state."""
        from paddle_tpu.distributed.ps import SparseEmbedding

        def run(cache_capacity):
            server = PSServer(0)
            client = PSClient([server.endpoint])
            try:
                paddle.seed(0)

                class M(nn.Layer):
                    def __init__(self):
                        super().__init__()
                        self.e = SparseEmbedding(
                            table_id=0, embedding_dim=4, optimizer="sum",
                            client=client)
                        self.lin = nn.Linear(4, 1)

                    def forward(self, ids):
                        return self.lin(self.e(ids))

                model = M()
                opt = optimizer.SGD(learning_rate=0.1,
                                    parameters=model.parameters())
                crit = nn.MSELoss()
                step = HeterPSTrainStep(model, lambda o, y: crit(o, y),
                                        opt, cache_capacity=cache_capacity)
                rng = np.random.default_rng(2)
                losses = []
                for _ in range(4):
                    ids = paddle.to_tensor(
                        rng.integers(0, 20, 8).astype(np.int64))
                    y = paddle.to_tensor(
                        rng.normal(size=(8, 1)).astype(np.float32))
                    losses.append(float(step(ids, y)))
                step.flush()
                rows = client.pull_sparse(
                    0, np.arange(20, dtype=np.uint64)).copy()
                step.close()
                return losses, rows
            finally:
                client.stop_servers()

        ref_losses, ref_rows = run(0)
        losses, rows = run(64)
        np.testing.assert_allclose(losses, ref_losses, atol=2e-4)
        np.testing.assert_allclose(rows, ref_rows, atol=1e-4)

    def test_shared_table_two_calls_drops_cache(self, ps):
        """A table consumed by TWO embedding calls in one step cannot be
        cached (each call's plan would hand the same slots to different
        keys and the double commit would corrupt the free list): the
        cache is dropped with a warning on the first prepare and the
        table rides the per-step pull/push path."""
        paddle.seed(0)
        from paddle_tpu.distributed.ps import SparseEmbedding

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.e = SparseEmbedding(table_id=0, embedding_dim=4,
                                         optimizer="sgd", client=ps)
                self.lin = nn.Linear(8, 1)

            def forward(self, a, b):
                return self.lin(paddle.concat([self.e(a), self.e(b)],
                                              axis=-1))

        model = M()
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        crit = nn.MSELoss()
        step = HeterPSTrainStep(model, lambda o, y: crit(o, y), opt,
                                cache_capacity=32)
        assert 0 in step.caches  # built at init; dropped on first prepare
        a = paddle.to_tensor(np.arange(8, dtype=np.int64))
        b = paddle.to_tensor((np.arange(8) + 4).astype(np.int64))
        y = paddle.to_tensor(np.ones((8, 1), np.float32))
        with pytest.warns(UserWarning, match="multiple embedding calls"):
            loss = float(step(a, b, y))
        assert np.isfinite(loss)
        assert step.caches == {}
        assert np.isfinite(float(step(a, b, y)))  # steady state post-drop
        step.close()

    def test_non_sgd_table_skipped_with_warning(self, ps):
        paddle.seed(0)
        from paddle_tpu.distributed.ps import SparseEmbedding

        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.e = SparseEmbedding(table_id=0, embedding_dim=4,
                                         optimizer="adam", client=ps)
                self.lin = nn.Linear(4, 1)

            def forward(self, ids):
                return self.lin(self.e(ids))

        model = M()
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        crit = nn.MSELoss()
        with pytest.warns(UserWarning, match="hot-row cache skipped"):
            step = HeterPSTrainStep(model, lambda o, y: crit(o, y), opt,
                                    cache_capacity=32)
        assert step.caches == {}
        ids = paddle.to_tensor(np.arange(8, dtype=np.int64))
        y = paddle.to_tensor(np.ones((8, 1), np.float32))
        assert np.isfinite(float(step(ids, y)))  # un-cached path still works
        step.close()


class TestShrinkInvalidatesCache:
    """Server-side table shrink/eviction must reach the device hot-row
    cache (PR-4 follow-up): before the fix a shrunk row stayed
    device-resident and every later batch HIT it — serving a row the
    server had already evicted."""

    def _serve(self, cache, client, tid, keys):
        """One cached serving round: plan -> pull misses -> commit ->
        combine. Returns (plan, served rows ndarray)."""
        import jax.numpy as jnp
        uniq = np.asarray(keys, np.uint64)
        plan = cache.plan(uniq, uniq.size)
        miss_rows = (client.pull_sparse(tid, plan.miss_keys)
                     if plan.miss_keys.size else
                     np.zeros((1, cache.dim), np.float32))
        cache.commit(plan)
        plan_dev = (jnp.asarray(plan.slot_idx), jnp.asarray(plan.hit_mask),
                    jnp.asarray(plan.miss_idx))
        rows = cache.combine(plan_dev, jnp.asarray(miss_rows))
        return plan, np.asarray(rows)

    def test_shrink_flushes_then_invalidates(self, ps):
        from paddle_tpu.distributed.ps import TableConfig
        from paddle_tpu.distributed.ps.cache import HotRowCache
        import jax.numpy as jnp
        tid, dim, lr = 60, 4, 0.5
        ps.create_table(TableConfig(table_id=tid, kind="sparse", dim=dim,
                                    optimizer="sgd", learning_rate=lr,
                                    init_range=0.1, seed=11))
        cache = HotRowCache(tid, dim, capacity=8, learning_rate=lr,
                            client=ps)
        k = np.array([7], np.uint64)
        server_row0 = ps.pull_sparse(tid, k).copy()
        plan, rows = self._serve(cache, ps, tid, k)
        assert not plan.hit_mask[0]  # first touch is a miss
        np.testing.assert_allclose(rows[0], server_row0[0], atol=1e-6)

        # accumulate a local (deferred) gradient on the cached row
        g = np.full((1, dim), 0.25, np.float32)
        plan_dev = (jnp.asarray(plan.slot_idx), jnp.asarray(plan.hit_mask),
                    jnp.asarray(plan.miss_idx))
        cache.apply(plan_dev, jnp.asarray(rows), jnp.asarray(g))

        # a non-evicting day tick: the pending gradient must be flushed
        # BEFORE the server's lifecycle pass, and the cache dropped after
        evicted = ps.shrink(tid, threshold=-1.0, max_unseen_days=30)
        assert evicted == 0
        assert len(cache) == 0 and cache.stats["invalidation"] == 1
        assert not np.any(np.asarray(cache.gsum))  # accumulators cleared
        server_row1 = ps.pull_sparse(tid, k).copy()
        np.testing.assert_allclose(server_row1[0], server_row0[0] - lr * g[0],
                                   atol=1e-5)  # flush landed exactly once

        # re-cache the row, then REALLY evict it server-side: the next
        # serving round must MISS and see the fresh (re-initialized) row,
        # never the stale device-resident copy
        plan, rows_cached = self._serve(cache, ps, tid, k)
        assert len(cache) == 1
        for _ in range(3):
            ps.shrink(tid, threshold=1.0, max_unseen_days=1)
        _, _, unseen = ps.pull_meta(tid, k)
        assert unseen[0] == -1  # evicted on the server
        assert len(cache) == 0, "shrink left the evicted row cached"
        plan2, rows_fresh = self._serve(cache, ps, tid, k)
        assert not plan2.hit_mask[0], \
            "post-shrink serve HIT the stale device cache"
        fresh_server = ps.pull_sparse(tid, k)
        np.testing.assert_allclose(rows_fresh[0], fresh_server[0], atol=1e-6)

    def test_unrelated_table_cache_untouched(self, ps):
        from paddle_tpu.distributed.ps import TableConfig
        from paddle_tpu.distributed.ps.cache import HotRowCache
        for t in (61, 62):
            ps.create_table(TableConfig(table_id=t, kind="sparse", dim=2,
                                        optimizer="sgd", learning_rate=0.1))
        c61 = HotRowCache(61, 2, capacity=4, learning_rate=0.1, client=ps)
        c62 = HotRowCache(62, 2, capacity=4, learning_rate=0.1, client=ps)
        self._serve(c61, ps, 61, np.array([1], np.uint64))
        self._serve(c62, ps, 62, np.array([2], np.uint64))
        ps.shrink(61, threshold=-1.0, max_unseen_days=30)
        assert len(c61) == 0 and c61.stats["invalidation"] == 1
        assert len(c62) == 1 and c62.stats["invalidation"] == 0


class TestPipelineChaos:
    def test_injected_pull_fault_recovers(self, ps):
        """A PS hiccup in the prepare stage retries under the HETER stage
        policy instead of wedging the prefetch thread (fault site
        heter.pull), and the recovery is visible in the metrics."""
        from paddle_tpu.profiler import metrics as metrics_mod
        _, step = _trainer(ps, "pipelined", cache_capacity=64)
        fault.configure("heter.pull", times=1, start=3)
        data = _data(n_batches=6)
        losses = _run(step, data, prefetch=True)
        step.close()
        assert all(np.isfinite(l) for l in losses)
        assert fault.default_injector().fired("heter.pull") == 1
        rec = metrics_mod.default_registry().get("retry_recovered_total")
        assert rec.value(op="heter.pull") >= 1

    def test_injected_push_fault_recovers(self, ps):
        _, step = _trainer(ps, "pipelined")  # uncached: pushes every step
        fault.configure("heter.push", times=1, start=2)
        data = _data(n_batches=5)
        losses = _run(step, data)
        step.close()
        assert all(np.isfinite(l) for l in losses)
        assert fault.default_injector().fired("heter.push") == 1


class TestPullSparseMulti:
    def test_matches_serial_pulls(self, ps):
        from paddle_tpu.distributed.ps import TableConfig
        rng = np.random.default_rng(0)
        for tid in range(3):
            ps.create_table(TableConfig(table_id=tid, kind="sparse", dim=4,
                                        seed=tid))
        reqs = [(tid, rng.integers(0, 1000, 64).astype(np.uint64))
                for tid in range(3)]
        reqs.append((1, np.empty(0, np.uint64)))  # empty request rides along
        multi = ps.pull_sparse_multi(reqs)
        serial = [ps.pull_sparse(tid, keys) for tid, keys in reqs]
        assert len(multi) == len(serial)
        for m, s in zip(multi, serial):
            np.testing.assert_array_equal(m, s)

    def test_single_request_fast_path(self, ps):
        from paddle_tpu.distributed.ps import TableConfig
        ps.create_table(TableConfig(table_id=9, kind="sparse", dim=4))
        keys = np.arange(10, dtype=np.uint64)
        (rows,) = ps.pull_sparse_multi([(9, keys)])
        np.testing.assert_array_equal(rows, ps.pull_sparse(9, keys))
