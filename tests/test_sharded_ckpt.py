"""Sharded/chunked checkpoint backend: format roundtrip, fleet ownership,
elastic re-sharding restore, corruption fuzz over chunks + manifests,
async saves off the step critical path, backpressure, coordinated
shared-directory commit, and the writer-death prompt-abort chaos contract.

These are the FAST siblings of tests/test_elastic_reshard_e2e.py (the
slow subprocess proof that a killed 2-host fleet resumes as 1 host and
vice versa, bit-identically).
"""
import json
import os
import threading
import time
import zlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu import fault
from paddle_tpu.distributed import checkpoint as dist_ckpt
from paddle_tpu.distributed import sharded_checkpoint as sc
from paddle_tpu.distributed.checkpoint import (CheckpointCorruptError,
                                               CheckpointCoordinator,
                                               detect_layout, open_manager)
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.profiler import metrics as metrics_mod


@pytest.fixture(autouse=True)
def _clean_injector():
    fault.reset()
    yield
    fault.reset()


@pytest.fixture()
def master():
    st = TCPStore("127.0.0.1", 0, is_master=True)
    yield st
    st.stop()


def _mgr(tmp_path, master=None, rank=0, world=1, **kw):
    """A sharded manager; with `master`, one coordinated 'host' sharing
    tmp_path (the shared-directory topology)."""
    coord = None
    if master is not None:
        store = TCPStore("127.0.0.1", master.port)
        coord = CheckpointCoordinator(store, rank, world, timeout=5.0,
                                      poll_interval=0.005)
    return open_manager(str(tmp_path), layout="sharded", coordinator=coord,
                        **kw)


def _state(seed=0.0):
    return {
        "net": {"w": np.arange(12, dtype=np.float32).reshape(3, 4) + seed,
                "b": np.full(4, 2.0 + seed, np.float32)},
        "slots": [np.zeros(3, np.float32), np.ones(3, np.float32) * seed],
        "cursor": {"epoch": 3, "step_in_epoch": int(seed), "done": False},
        "tag": "gen-" + str(seed),
        "shapes": (2, "a", None),
        "exotic": np.float32(1.25),  # not JSON-able: pickle fallback leaf
    }


def _assert_state_equal(a, b):
    assert set(a) == set(b)
    np.testing.assert_array_equal(np.asarray(a["net"]["w"]),
                                  np.asarray(b["net"]["w"]))
    np.testing.assert_array_equal(np.asarray(a["net"]["b"]),
                                  np.asarray(b["net"]["b"]))
    for x, y in zip(a["slots"], b["slots"]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    assert a["cursor"] == b["cursor"]
    assert a["tag"] == b["tag"]
    assert a["shapes"] == b["shapes"]
    assert float(a["exotic"]) == float(b["exotic"])


def _counter_total(name, **labels):
    m = metrics_mod.default_registry().get(name)
    if m is None:
        return 0.0
    return sum(v["value"] for v in m.snapshot()["values"]
               if all(v["labels"].get(k) == lv for k, lv in labels.items()))


def _hist_sum(name):
    m = metrics_mod.default_registry().get(name)
    if m is None:
        return 0.0
    return sum(v["sum"] for v in m.snapshot()["values"])


# ---------------------------------------------------------------------------
# format
# ---------------------------------------------------------------------------
class TestFormatRoundtrip:
    def test_roundtrip_preserves_tree_and_values(self, tmp_path):
        m = _mgr(tmp_path)
        st = _state(5.0)
        assert m.save(st, 1) is True
        got, step = m.load_latest()
        assert step == 1
        _assert_state_equal(got, st)

    def test_layout_detection(self, tmp_path):
        assert detect_layout(str(tmp_path)) is None
        _mgr(tmp_path).save(_state(), 1)
        assert detect_layout(str(tmp_path)) == "sharded"
        auto = open_manager(str(tmp_path))
        assert auto.layout == "sharded"
        # a file-layout dir still auto-detects as file
        d2 = tmp_path / "plain"
        dist_ckpt.CheckpointManager(str(d2)).save({"w": np.ones(2)}, 1)
        assert detect_layout(str(d2)) == "file"
        assert open_manager(str(d2)).layout == "file"

    def test_mixed_dir_resolves_to_newest_step_layout(self, tmp_path):
        """A directory holding BOTH layouts (in-place migration) must
        resume from the layout of the NEWEST step, not whichever entry
        os.listdir happens to yield first."""
        dist_ckpt.CheckpointManager(str(tmp_path)).save(
            {"w": np.ones(2, np.float32)}, 10)
        _mgr(tmp_path).save(_state(), 20)
        assert detect_layout(str(tmp_path)) == "sharded"
        assert open_manager(str(tmp_path)).load_latest()[1] == 20
        # and the reverse: a newer monolithic file wins
        d2 = tmp_path / "rev"
        open_manager(str(d2), layout="sharded").save(_state(), 3)
        dist_ckpt.CheckpointManager(str(d2)).save(
            {"w": np.ones(2, np.float32)}, 7)
        assert detect_layout(str(d2)) == "file"
        assert open_manager(str(d2)).load_latest()[1] == 7

    def test_manifest_records_world_specs_and_crcs(self, tmp_path):
        m = _mgr(tmp_path)
        m.save(_state(), 4)
        sd = m.path_for(4)
        with open(os.path.join(sd, "manifest-r0.json")) as f:
            man = json.load(f)
        assert man["magic"] == sc.MANIFEST_MAGIC
        assert man["world_size"] == 1 and man["rank"] == 0
        assert man["arrays"]["/net/w"]["shape"] == [3, 4]
        assert man["arrays"]["/net/w"]["dtype"] == "float32"
        for rec in man["chunks"]:
            with open(os.path.join(sd, rec["file"]), "rb") as f:
                data = f.read()
            assert len(data) == rec["bytes"]
            assert zlib.crc32(data) & 0xFFFFFFFF == rec["crc32"]
        assert sc.verify_step(sd, deep=True)[0] == "complete"

    def test_step_files_of_file_backend_ignore_step_dirs(self, tmp_path):
        """The file backend's latest_valid must not trip over sharded step
        DIRECTORIES sharing a directory tree."""
        _mgr(tmp_path).save(_state(), 2)
        assert dist_ckpt.latest_valid(str(tmp_path)) is None


class TestFleetOwnership:
    def test_each_array_written_exactly_once(self, tmp_path, master):
        world = 2
        ms = [_mgr(tmp_path, master, r, world) for r in range(world)]
        res = {}
        ts = [threading.Thread(
            target=lambda r=r: res.update({r: ms[r].save(_state(), 1)}))
            for r in range(world)]
        [t.start() for t in ts]
        [t.join(timeout=60) for t in ts]
        assert res == {0: True, 1: True}
        sd = ms[0].path_for(1)
        scan = sc.scan_step(sd)
        assert sorted(scan.manifests) == [0, 1]
        seen = {}
        for rank, man in scan.manifests.items():
            for rec in man["chunks"]:
                assert rec["path"] not in seen, "array written twice"
                seen[rec["path"]] = rank
        for path, rank in seen.items():
            assert rank == sc.owner_rank(path, world)
        assert set(seen) == set(scan.manifests[0]["arrays"])
        # either rank alone cannot have written everything (ownership is
        # spread), unless crc32 degenerately assigned all to one rank
        assert sc.verify_step(sd, deep=True)[0] == "complete"

    def test_scale_down_restore_from_shared_dir(self, tmp_path, master):
        """A world-2 checkpoint restores on a world-1 fleet: the single
        new host reassembles arrays from BOTH ranks' chunks."""
        world = 2
        ms = [_mgr(tmp_path, master, r, world) for r in range(world)]
        st = _state(7.0)
        ts = [threading.Thread(target=lambda r=r: ms[r].save(st, 3))
              for r in range(world)]
        [t.start() for t in ts]
        [t.join(timeout=60) for t in ts]
        m1 = open_manager(str(tmp_path))  # auto-detects sharded, world 1
        got, step = m1.load_latest()
        assert step == 3
        _assert_state_equal(got, st)

    def test_scale_up_restore_from_shared_dir(self, tmp_path, master):
        """A world-1 checkpoint restores on a world-2 fleet: both hosts
        negotiate over manifests and read rank 0's chunks."""
        _mgr(tmp_path).save(_state(9.0), 5)
        ms = [_mgr(tmp_path, master, r, 2) for r in range(2)]
        res = {}
        ts = [threading.Thread(
            target=lambda r=r: res.update({r: ms[r].load_latest()}))
            for r in range(2)]
        [t.start() for t in ts]
        [t.join(timeout=60) for t in ts]
        for r in range(2):
            got, step = res[r]
            assert step == 5
            _assert_state_equal(got, _state(9.0))


# ---------------------------------------------------------------------------
# elastic re-sharding (mesh-level)
# ---------------------------------------------------------------------------
class TestReshardingRestore:
    def _sharded_state(self, n_dev):
        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("x",))
        w = jax.device_put(
            jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
            NamedSharding(mesh, P("x")))
        return mesh, {"w": w, "b": np.ones(3, np.float32)}

    def test_restore_onto_smaller_mesh(self, tmp_path):
        mesh4, st = self._sharded_state(4)
        _mgr(tmp_path).save(st, 1)
        sd = os.path.join(str(tmp_path), "ckpt_1")
        with open(os.path.join(sd, "manifest-r0.json")) as f:
            man = json.load(f)
        assert man["arrays"]["/w"]["spec"] == ["x"]
        assert man["mesh_axes"] == {"x": 4}
        # four shard chunks, one per device
        w_chunks = [c for c in man["chunks"] if c["path"] == "/w"]
        assert len(w_chunks) == 4
        mesh2 = Mesh(np.array(jax.devices()[:2]), ("x",))
        got, step = open_manager(str(tmp_path), mesh=mesh2).load_latest()
        np.testing.assert_array_equal(np.asarray(got["w"]),
                                      np.asarray(st["w"]))
        assert got["w"].sharding.spec == P("x")
        assert got["w"].sharding.mesh.shape["x"] == 2

    def test_restore_onto_larger_mesh(self, tmp_path):
        _, st = self._sharded_state(2)
        _mgr(tmp_path).save(st, 1)
        mesh8 = Mesh(np.array(jax.devices()[:8]), ("x",))
        got, _ = open_manager(str(tmp_path), mesh=mesh8).load_latest()
        np.testing.assert_array_equal(np.asarray(got["w"]),
                                      np.asarray(st["w"]))
        assert got["w"].sharding.mesh.shape["x"] == 8

    def test_missing_axis_replicates_loudly(self, tmp_path):
        _, st = self._sharded_state(4)
        _mgr(tmp_path).save(st, 1)
        other = Mesh(np.array(jax.devices()[:2]), ("model",))
        got, _ = open_manager(str(tmp_path), mesh=other).load_latest()
        # axis "x" does not exist in the target mesh: replicated, same bits
        np.testing.assert_array_equal(np.asarray(got["w"]),
                                      np.asarray(st["w"]))
        assert got["w"].sharding.spec in (P(None), P())

    def test_reshard_fault_site_is_armed(self, tmp_path):
        m = _mgr(tmp_path)
        m.save(_state(), 1)
        fault.configure("ckpt.reshard", times=1)
        with pytest.raises(fault.InjectedFault):
            sc.load_step(m.path_for(1))
        assert fault.default_injector().fired("ckpt.reshard") == 1
        got, step = m.load_latest()  # disarmed: restore works again
        assert step == 1


# ---------------------------------------------------------------------------
# corruption fuzz (chunk-level extension of the PR-3 contract)
# ---------------------------------------------------------------------------
class TestCorruptionFuzz:
    def _three_steps(self, tmp_path):
        m = _mgr(tmp_path, keep_last_n=5)
        for s in (1, 2, 3):
            m.save(_state(float(s)), s)
        return m

    def _chunk_of(self, m, step, path="/net/w"):
        sd = m.path_for(step)
        with open(os.path.join(sd, "manifest-r0.json")) as f:
            man = json.load(f)
        rec = next(c for c in man["chunks"] if c["path"] == path)
        return os.path.join(sd, rec["file"])

    def test_bitflipped_chunk_falls_back(self, tmp_path):
        m = self._three_steps(tmp_path)
        cf = self._chunk_of(m, 3)
        data = bytearray(open(cf, "rb").read())
        data[len(data) // 2] ^= 0x40
        open(cf, "wb").write(bytes(data))
        with pytest.warns(UserWarning, match="skipping corrupt"):
            got, step = m.load_latest()
        assert step == 2
        _assert_state_equal(got, _state(2.0))

    def test_truncated_chunk_falls_back(self, tmp_path):
        m = self._three_steps(tmp_path)
        cf = self._chunk_of(m, 3)
        data = open(cf, "rb").read()
        open(cf, "wb").write(data[:len(data) // 2])
        assert sc.verify_step(m.path_for(3))[0] == "corrupt"
        with pytest.warns(UserWarning, match="skipping corrupt"):
            got, step = m.load_latest()
        assert step == 2

    def test_deleted_chunk_falls_back(self, tmp_path):
        m = self._three_steps(tmp_path)
        os.remove(self._chunk_of(m, 3))
        with pytest.warns(UserWarning, match="skipping corrupt"):
            got, step = m.load_latest()
        assert step == 2

    def test_deleted_manifest_falls_back(self, tmp_path):
        m = self._three_steps(tmp_path)
        os.remove(os.path.join(m.path_for(3), "manifest-r0.json"))
        got, step = m.load_latest()  # an EMPTY step skips silently
        assert step == 2

    def test_garbled_manifest_json_falls_back(self, tmp_path):
        m = self._three_steps(tmp_path)
        mf = os.path.join(m.path_for(3), "manifest-r0.json")
        open(mf, "wb").write(b"\x00garbage{{{")
        assert sc.verify_step(m.path_for(3))[0] == "corrupt"
        with pytest.warns(UserWarning, match="skipping corrupt"):
            got, step = m.load_latest()
        assert step == 2

    def test_bitflipped_pickle_leaf_is_corrupt_not_traceback(self, tmp_path):
        """A parseable manifest whose pickled leaf is damaged must raise
        CheckpointCorruptError from load_step — never a raw unpickling
        traceback (extends the PR-3 contract to the chunked layout)."""
        m = self._three_steps(tmp_path)
        mf = os.path.join(m.path_for(3), "manifest-r0.json")
        man = json.load(open(mf))
        node = man["tree"]["exotic"]
        assert "__ptpickle__" in node
        node["__ptpickle__"] = "AAAA" + node["__ptpickle__"][4:]
        json.dump(man, open(mf, "w"))
        with pytest.raises(CheckpointCorruptError):
            sc.load_step(m.path_for(3))
        with pytest.warns(UserWarning, match="skipping corrupt"):
            got, step = m.load_latest()
        assert step == 2

    def test_all_steps_corrupt_returns_none(self, tmp_path):
        m = _mgr(tmp_path)
        m.save(_state(), 1)
        os.remove(self._chunk_of(m, 1))
        with pytest.warns(UserWarning, match="skipping corrupt"):
            assert m.load_latest() is None

    def test_partial_step_still_restores(self, tmp_path, master):
        """A lost rank whose manifest owned NO chunks (everything this
        small state owns hashes to the other rank) downgrades the step to
        `partial` — and restore still works from the surviving chunks."""
        state = {}
        i = 0
        while len(state) < 3:  # keys all owned by rank 0 under world 2
            k = f"k{i}"
            if sc.owner_rank(f"/{k}", 2) == 0:
                state[k] = np.full(4, float(i), np.float32)
            i += 1
        ms = [_mgr(tmp_path, master, r, 2) for r in range(2)]
        ts = [threading.Thread(target=lambda r=r: ms[r].save(state, 1))
              for r in range(2)]
        [t.start() for t in ts]
        [t.join(timeout=60) for t in ts]
        sd = ms[0].path_for(1)
        assert sc.verify_step(sd, deep=True)[0] == "complete"
        os.remove(os.path.join(sd, "manifest-r1.json"))
        status, detail = sc.verify_step(sd, deep=True)
        assert status == "partial", detail
        got, step = open_manager(str(tmp_path)).load_latest()
        assert step == 1
        for k, v in state.items():
            np.testing.assert_array_equal(np.asarray(got[k]), v)

    def test_lost_owner_rank_is_unrestorable_corrupt(self, tmp_path,
                                                     master):
        """Losing the manifest of a rank that DID own chunks — AND the
        peer-written ``.mirror`` copy of it (PR 20) — makes the step
        corrupt (arrays cannot be reassembled), not partial."""
        ms = [_mgr(tmp_path, master, r, 2) for r in range(2)]
        st = _state()
        ts = [threading.Thread(target=lambda r=r: ms[r].save(st, 1))
              for r in range(2)]
        [t.start() for t in ts]
        [t.join(timeout=60) for t in ts]
        sd = ms[0].path_for(1)
        owners = {sc.owner_rank(p, 2)
                  for p in sc.scan_step(sd).manifests[0]["arrays"]}
        assert owners == {0, 1}  # this state really is spread
        os.remove(os.path.join(sd, "manifest-r1.json"))
        os.remove(os.path.join(sd, "manifest-r1.json.mirror"))
        status, _ = sc.verify_step(sd)
        assert status == "corrupt"


class TestManifestMirrorFuzz:
    """PR 20: each rank replicates peer ``(r+1)%world``'s committed
    manifest to a ``.mirror`` copy, so losing ONE owner's manifest
    downgrades the step to ``partial`` instead of ``corrupt``."""

    def _two_rank_save(self, tmp_path, master, step=1):
        ms = [_mgr(tmp_path, master, r, 2) for r in range(2)]
        st = _state()
        ts = [threading.Thread(target=lambda r=r: ms[r].save(st, step))
              for r in range(2)]
        [t.start() for t in ts]
        [t.join(timeout=60) for t in ts]
        return ms, st

    def test_every_rank_manifest_gets_a_peer_mirror(self, tmp_path, master):
        ms, _ = self._two_rank_save(tmp_path, master)
        sd = ms[0].path_for(1)
        files = set(os.listdir(sd))
        # ring topology: r0 mirrors r1's manifest and vice versa
        assert {"manifest-r0.json.mirror",
                "manifest-r1.json.mirror"} <= files
        for r in range(2):
            with open(os.path.join(sd, f"manifest-r{r}.json"), "rb") as a, \
                    open(os.path.join(sd, f"manifest-r{r}.json.mirror"),
                         "rb") as b:
                assert a.read() == b.read()
        # an intact step scans without touching the mirrors
        scan = sc.scan_step(sd)
        assert scan.mirrored == [] and set(scan.manifests) == {0, 1}
        assert sc.verify_step(sd, deep=True)[0] == "complete"

    def test_deleted_manifest_recovers_partial_via_mirror(self, tmp_path,
                                                          master):
        """The headline contract: losing one owner's manifest leaves the
        step partial-restorable from the peer's mirror — the restore
        returns the full state, and verify names the recovery."""
        ms, st = self._two_rank_save(tmp_path, master)
        sd = ms[0].path_for(1)
        owners = {sc.owner_rank(p, 2)
                  for p in sc.scan_step(sd).manifests[0]["arrays"]}
        assert owners == {0, 1}  # rank 1 really owned chunks
        os.remove(os.path.join(sd, "manifest-r1.json"))
        scan = sc.scan_step(sd)
        assert scan.mirrored == [1]
        assert set(scan.manifests) == {0, 1}
        status, detail = sc.verify_step(sd, deep=True)
        assert status == "partial", detail
        assert "recovered via peer-mirrored" in detail
        got, step = open_manager(str(tmp_path)).load_latest()
        assert step == 1
        _assert_state_equal(got, st)

    def test_garbled_manifest_recovers_partial_via_mirror(self, tmp_path,
                                                          master):
        """Bitrot, not loss: the torn original lands in bad_manifests
        but the mirror still reassembles the step."""
        ms, st = self._two_rank_save(tmp_path, master)
        sd = ms[0].path_for(1)
        open(os.path.join(sd, "manifest-r0.json"), "wb").write(
            b"\x00garbage{{{")
        scan = sc.scan_step(sd)
        assert scan.mirrored == [0] and scan.bad_manifests
        status, detail = sc.verify_step(sd, deep=True)
        assert status == "partial", detail
        got, _ = open_manager(str(tmp_path)).load_latest()
        _assert_state_equal(got, st)

    def test_corrupt_mirror_with_intact_original_is_harmless(self, tmp_path,
                                                             master):
        """Fuzzing the MIRROR must not downgrade a healthy step: an
        unreadable mirror is skipped silently (never bad_manifests) and
        an intact original always wins over a stale-but-valid mirror."""
        ms, st = self._two_rank_save(tmp_path, master)
        sd = ms[0].path_for(1)
        mirror = os.path.join(sd, "manifest-r1.json.mirror")
        open(mirror, "wb").write(b"\xff\xfe not json")
        scan = sc.scan_step(sd)
        assert scan.mirrored == [] and scan.bad_manifests == []
        assert sc.verify_step(sd, deep=True)[0] == "complete"
        # a VALID but divergent mirror must not shadow the original
        with open(os.path.join(sd, "manifest-r1.json")) as f:
            man = json.load(f)
        man["chunks"] = []
        open(mirror, "w").write(json.dumps(man))
        scan = sc.scan_step(sd)
        assert scan.mirrored == []
        assert scan.manifests[1]["chunks"], "mirror shadowed the original"
        got, _ = open_manager(str(tmp_path)).load_latest()
        _assert_state_equal(got, st)

    def test_single_rank_world_writes_no_mirror(self, tmp_path):
        """world=1 has no peer: a self-mirror would silently change the
        single-host corruption contract (a torn manifest must fall back
        to the previous step, not self-heal)."""
        m = _mgr(tmp_path)
        m.save(_state(), 1)
        m.save(_state(1.0), 2)  # the lag-1 backfill path runs too
        for s in (1, 2):
            assert not [fn for fn in os.listdir(m.path_for(s))
                        if fn.endswith(".mirror")]

    def test_orphan_sweep_drops_own_torn_mirror_tmp(self, tmp_path, master):
        ms, _ = self._two_rank_save(tmp_path, master)
        sd = ms[0].path_for(1)
        torn = os.path.join(sd, "manifest-r1.json.mirror.tmp.r0")
        open(torn, "wb").write(b"half")
        peer = os.path.join(sd, "manifest-r0.json.mirror.tmp.r1")
        open(peer, "wb").write(b"half")
        ms[0]._sweep_orphans()
        assert not os.path.exists(torn)   # own torn tmp swept
        assert os.path.exists(peer)       # peer's file never touched


# ---------------------------------------------------------------------------
# async: off the critical path + backpressure
# ---------------------------------------------------------------------------
class TestAsyncSave:
    def test_save_is_off_the_critical_path(self, tmp_path, monkeypatch):
        """Acceptance: step wall time during an in-flight background save
        stays within noise of no-save steps, and checkpoint_async_seconds
        records the hidden write cost."""
        monkeypatch.setenv("PADDLE_TPU_FAULT_DELAY", "0.4")
        fault.configure("ckpt.chunk_write", times=1, kind="delay")
        async_sum0 = _hist_sum("checkpoint_async_seconds")
        m = _mgr(tmp_path, async_save=True)
        st = {"w": np.random.default_rng(0).normal(
            size=(64, 64)).astype(np.float32)}

        # baseline: steps with no save in flight
        def step():
            t = time.perf_counter()
            time.sleep(0.002)
            return time.perf_counter() - t
        baseline = [step() for _ in range(20)]

        t0 = time.perf_counter()
        assert m.save(st, 1) is True
        enqueue = time.perf_counter() - t0
        assert enqueue < 0.2, \
            f"save() blocked {enqueue:.3f}s on the background write"
        during = []
        while m._writer.busy() and len(during) < 500:
            during.append(step())
        assert len(during) >= 3, "write finished too fast to measure"
        # within noise: nothing stalled for anything like the 0.4s write
        assert max(during) < max(baseline) + 0.1, (max(during), max(baseline))
        m._writer.drain()
        hidden = _hist_sum("checkpoint_async_seconds") - async_sum0
        assert hidden >= 0.4, hidden  # the sleep landed OFF the step path
        assert _counter_total("checkpoint_async_bytes") > 0
        got, step_n = m.load_latest()
        assert step_n == 1
        np.testing.assert_array_equal(np.asarray(got["w"]), st["w"])

    def test_backpressure_blocks_second_save(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FAULT_DELAY", "0.3")
        fault.configure("ckpt.chunk_write", times=2, kind="delay")
        m = _mgr(tmp_path, async_save=True)
        st = {"w": np.zeros(8, np.float32)}
        t0 = time.perf_counter()
        m.save(st, 1)
        first = time.perf_counter() - t0
        t0 = time.perf_counter()
        m.save(st, 2)  # must WAIT for save 1's writer to drain
        second = time.perf_counter() - t0
        assert first < 0.15, first
        assert second >= 0.15, \
            f"second save did not backpressure ({second:.3f}s)"
        m._writer.drain()
        assert m.load_latest()[1] == 2

    def test_save_in_flight_covers_background_writer(self, tmp_path,
                                                     monkeypatch):
        """The preemption handler keys off `_save_in_flight`: it must stay
        True for as long as a background save is queued OR running — a
        SIGTERM mid-write re-entering a nested coordinated save would
        desync barrier rounds fleet-wide."""
        monkeypatch.setenv("PADDLE_TPU_FAULT_DELAY", "0.3")
        fault.configure("ckpt.chunk_write", times=1, kind="delay")
        m = _mgr(tmp_path, async_save=True)
        m.save({"w": np.zeros(4, np.float32)}, 1)
        assert m._save_in_flight, "in-flight background save not reflected"
        m._writer.drain()
        assert not m._save_in_flight

    def test_background_failure_surfaces_on_drain(self, tmp_path):
        fault.configure("ckpt.chunk_write", times=1, kind="oserror")
        m = _mgr(tmp_path, async_save=True)
        m.save({"w": np.zeros(4, np.float32)}, 1)
        with pytest.raises(fault.InjectedIOError):
            m._writer.drain()
        # the failed attempt left nothing a reader could mistake for a
        # checkpoint
        assert m.load_latest() is None


# ---------------------------------------------------------------------------
# coordinated shared-directory commit
# ---------------------------------------------------------------------------
class TestCoordinatedSharedDir:
    def test_two_hosts_commit_one_directory(self, tmp_path, master):
        commits0 = _counter_total("ckpt_barrier_commits_total")
        ms = [_mgr(tmp_path, master, r, 2) for r in range(2)]
        res = {}
        ts = [threading.Thread(
            target=lambda r=r: res.update({r: ms[r].save(_state(), 1)}))
            for r in range(2)]
        [t.start() for t in ts]
        [t.join(timeout=60) for t in ts]
        assert res == {0: True, 1: True}
        assert _counter_total("ckpt_barrier_commits_total") >= commits0 + 2
        sd = ms[0].path_for(1)
        assert sc.verify_step(sd, deep=True)[0] == "complete"
        assert not any(f.endswith(".tmp.prep") for f in os.listdir(sd))

    def test_missing_peer_aborts_and_leaves_no_manifest(self, tmp_path,
                                                        master):
        m0 = _mgr(tmp_path, master, 0, 2)
        m0.coordinator.timeout = 0.5
        with pytest.warns(UserWarning, match="aborted"):
            assert m0.save(_state(), 7) is False
        sd = m0.path_for(7)
        # no committed manifest anywhere; tmp + chunks were GC'd
        assert not os.path.isdir(sd) or not any(
            sc._parse_manifest_name(f) is not None for f in os.listdir(sd))

    def test_writer_death_aborts_promptly_for_peer(self, tmp_path, master):
        """Chaos (satellite): a chunk-write fault killing one host's
        writer mid-prepare must poison the round so the peer aborts in
        ~poll-interval time, not after the full barrier timeout."""
        ms = [_mgr(tmp_path, master, r, 2) for r in range(2)]
        for m in ms:
            m.coordinator.timeout = 30.0
        # the two saves race for the single armed fault; whoever draws it
        # dies in prepare and poisons the round for the other
        fault.configure("ckpt.chunk_write", times=1)
        res, t0 = {}, time.perf_counter()

        def run(r):
            try:
                res[r] = ms[r].save(_state(), 1)
            except fault.InjectedFault:
                res[r] = "died"
        ts = [threading.Thread(target=run, args=(r,)) for r in range(2)]
        with pytest.warns(UserWarning, match="aborted"):
            [t.start() for t in ts]
            [t.join(timeout=60) for t in ts]
        elapsed = time.perf_counter() - t0
        assert sorted(map(str, res.values())) == ["False", "died"], res
        assert elapsed < 10, \
            f"peer burned the barrier timeout ({elapsed:.1f}s)"
        assert fault.default_injector().fired("ckpt.chunk_write") == 1

    def test_save_in_flight_during_sync_coordinated_save(self, tmp_path,
                                                         master):
        """The SYNC coordinated path must mark the save in flight for the
        whole prepare+commit too — a SIGTERM interrupting commit()'s wait
        loop re-entering a nested save would desync barrier rounds."""
        import warnings as _w
        m0 = _mgr(tmp_path, master, 0, 2)
        m0.coordinator.timeout = 1.5
        sampled = []

        def run():
            with _w.catch_warnings():
                _w.simplefilter("ignore")  # the abort warning (no peer)
                m0.save(_state(), 1)
        t = threading.Thread(target=run)
        t.start()
        time.sleep(0.4)  # commit() is waiting on the never-arriving peer
        sampled.append(m0._save_in_flight)
        t.join(timeout=30)
        assert sampled == [True], "sync coordinated save not marked in flight"
        assert not m0._save_in_flight

    def test_aborted_step_can_be_recommitted(self, tmp_path, master):
        ms = [_mgr(tmp_path, master, r, 2) for r in range(2)]
        ms[0].coordinator.timeout = 0.5
        with pytest.warns(UserWarning, match="aborted"):
            assert ms[0].save(_state(), 2) is False
        # peer poisons its next round to stay lockstep, then both retry
        ms[1].coordinator.abort_next_round(2)
        res = {}
        ts = [threading.Thread(
            target=lambda r=r: res.update({r: ms[r].save(_state(), 2)}))
            for r in range(2)]
        [t.start() for t in ts]
        [t.join(timeout=60) for t in ts]
        assert res == {0: True, 1: True}
        assert sc.verify_step(ms[0].path_for(2))[0] == "complete"


# ---------------------------------------------------------------------------
# manager plumbing
# ---------------------------------------------------------------------------
class TestManagerPlumbing:
    def test_gc_keeps_newest_step_dirs(self, tmp_path):
        m = _mgr(tmp_path, keep_last_n=2)
        for s in range(1, 6):
            m.save(_state(float(s)), s)
        assert m.steps() == [5, 4]

    def test_orphan_sweep_drops_own_tmps_and_unreferenced_chunks(
            self, tmp_path):
        m = _mgr(tmp_path)
        m.save(_state(), 1)
        sd = m.path_for(1)
        # simulate a crashed later attempt: stray tmp manifest + chunk
        open(os.path.join(sd, "manifest-r0.json.tmp.prep"), "w").write("x")
        open(os.path.join(sd, "r0-9999.g0a9.chunk"), "wb").write(b"zz")
        m2 = _mgr(tmp_path)  # init sweeps
        left = os.listdir(sd)
        assert "manifest-r0.json.tmp.prep" not in left
        assert "r0-9999.g0a9.chunk" not in left
        assert m2.load_latest()[1] == 1

    def test_orphan_sweep_never_touches_peer_files(self, tmp_path):
        m = _mgr(tmp_path)
        m.save(_state(), 1)
        sd = m.path_for(1)
        # a PEER's live prepare must survive this rank's sweep
        open(os.path.join(sd, "manifest-r1.json.tmp.prep"), "w").write("x")
        open(os.path.join(sd, "r1-0000.g0a1.chunk"), "wb").write(b"zz")
        _mgr(tmp_path)  # init sweep runs as rank 0
        left = os.listdir(sd)
        assert "manifest-r1.json.tmp.prep" in left
        assert "r1-0000.g0a1.chunk" in left

    def test_garbled_rank_env_raises_named_error(self, tmp_path,
                                                 monkeypatch):
        """A barrier-opted-out shared-dir fleet with a garbled rank env
        must fail loudly: a silent rank-0 fallback would have every host
        clobber the same rank namespace."""
        monkeypatch.setenv("PADDLE_TRAINER_ID", "not-a-rank")
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        with pytest.raises(ValueError, match="PADDLE_TRAINER_ID"):
            open_manager(str(tmp_path), layout="sharded")

    def test_newest_generation_wins_despite_clock_skew(self, tmp_path,
                                                       monkeypatch):
        """Manifest-group freshness orders by GENERATION first: a
        relaunched host whose wall clock runs behind must still beat the
        dead generation's stale other-world group."""
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_RESTART_NUM", "1")
        m = _mgr(tmp_path)  # world 1, generation 1
        m.save({"w": np.ones(4, np.float32)}, 1)
        sd = m.path_for(1)
        # forge a dead generation-0 world-2 manifest with a FUTURE clock
        with open(os.path.join(sd, "manifest-r0.json")) as f:
            man = json.load(f)
        stale = dict(man, world_size=2, rank=1, generation=0,
                     wall_time=man["wall_time"] + 1e6, chunks=[])
        with open(os.path.join(sd, "manifest-r1.json"), "w") as f:
            json.dump(stale, f)
        scan = sc.scan_step(sd)
        assert scan.world_size == 1, \
            "clock skew resurrected the dead generation's manifest group"
        got, step = open_manager(str(tmp_path)).load_latest()
        assert step == 1
        np.testing.assert_array_equal(np.asarray(got["w"]),
                                      np.ones(4, np.float32))

    def test_fit_drains_async_writer_at_train_end(self, tmp_path,
                                                  monkeypatch):
        """fit() must not return while the daemon writer still holds the
        final epoch-end save — a prompt process exit would reap it
        mid-write and silently lose the checkpoint."""
        import paddle_tpu as paddle
        from paddle_tpu import nn, optimizer
        from paddle_tpu.hapi.callbacks import FaultTolerantCheckpoint
        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __len__(self):
                return 2

            def __getitem__(self, i):
                rng = np.random.RandomState(i)
                return (rng.randn(4).astype(np.float32),
                        rng.randn(2).astype(np.float32))

        monkeypatch.setenv("PADDLE_TPU_FAULT_DELAY", "0.05")
        fault.configure("ckpt.chunk_write", times=999, kind="delay")
        paddle.seed(0)
        net = nn.Linear(4, 2)
        mdl = paddle.Model(net)
        mdl.prepare(optimizer.SGD(learning_rate=1e-2,
                                  parameters=net.parameters()),
                    loss=nn.MSELoss())
        cb = FaultTolerantCheckpoint(str(tmp_path / "ck"),
                                     layout="sharded", async_save=True,
                                     preemption_save=False)
        mdl.fit(DS(), batch_size=2, epochs=1, shuffle=False, verbose=0,
                callbacks=[cb])
        assert not cb.manager._writer.busy(), \
            "fit returned with the final save still on the daemon writer"
        step_dir = cb.manager.latest_valid_path()
        assert step_dir is not None
        assert sc.verify_step(step_dir, deep=True)[0] == "complete"

    def test_publish_sync_drains_writer_first(self, tmp_path, monkeypatch):
        """The preemption save (SIGTERM path) must let an in-flight
        background save finish publishing before its own synchronous
        publish — both checkpoints must exist afterwards."""
        monkeypatch.setenv("PADDLE_TPU_FAULT_DELAY", "0.25")
        fault.configure("ckpt.chunk_write", times=1, kind="delay")
        m = _mgr(tmp_path, async_save=True)
        m.save(_state(1.0), 1)
        assert m._publish_sync(_state(2.0), 2) is True
        assert m.steps() == [2, 1]
        for s in (1, 2):
            assert sc.verify_step(m.path_for(s), deep=True)[0] == "complete"

    def test_latest_valid_path_and_steps(self, tmp_path):
        m = _mgr(tmp_path)
        m.save(_state(), 3)
        m.save(_state(), 8)
        assert m.steps() == [8, 3]
        assert m.latest_valid_path() == m.path_for(8)

    def test_fit_resume_roundtrip_sharded(self, tmp_path):
        """FaultTolerantCheckpoint(layout='sharded') + fit(resume=): the
        interrupted run restores through the chunked backend and the tail
        matches an uninterrupted run bit for bit."""
        import paddle_tpu as paddle
        from paddle_tpu import nn, optimizer
        from paddle_tpu.hapi.callbacks import FaultTolerantCheckpoint
        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                rng = np.random.RandomState(100 + i)
                return (rng.randn(4).astype(np.float32),
                        rng.randn(2).astype(np.float32))

        def build():
            paddle.seed(7)
            net = nn.Linear(4, 2)
            mdl = paddle.Model(net)
            mdl.prepare(optimizer.Adam(learning_rate=1e-2,
                                       parameters=net.parameters()),
                        loss=nn.MSELoss())
            return mdl

        d = str(tmp_path / "ck")
        m1 = build()
        cb = FaultTolerantCheckpoint(d, save_freq_steps=1, layout="sharded",
                                     preemption_save=False)
        m1.fit(DS(), batch_size=2, epochs=1, shuffle=False, verbose=0,
               callbacks=[cb], num_iters=2)
        assert detect_layout(d) == "sharded"

        m2 = build()  # relaunch: resume + finish both epochs
        cb2 = FaultTolerantCheckpoint(d, save_freq_steps=1,
                                      preemption_save=False)  # layout auto
        assert cb2.manager.layout == "sharded"
        m2.fit(DS(), batch_size=2, epochs=2, shuffle=False, verbose=0,
               callbacks=[cb2], resume=d)

        ref = build()
        ref.fit(DS(), batch_size=2, epochs=2, shuffle=False, verbose=0)
        for mm in (m2, ref):
            mm._sync_from_train_step()
        for k, v in ref.network.state_dict().items():
            np.testing.assert_array_equal(
                np.asarray(m2.network.state_dict()[k].data),
                np.asarray(v.data), err_msg=k)
