"""AES-encrypted model IO (reference `framework/io/crypto/cipher.cc` —
the AES model-file cipher for industrial PS deployments): FIPS-197 known
answers for the native kernel, save/load roundtrip, wrong-key behavior."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.io import _aes_ctr

KEY16 = b"0123456789abcdef"


class TestAesKernel:
    def test_fips197_aes128(self):
        """Appendix C.1 known answer (via CTR keystream of the block)."""
        key = bytes(range(16))
        block = bytes(range(0, 256, 17))
        ks = _aes_ctr(key, block, b"\x00" * 16)
        assert ks.hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"

    def test_fips197_aes256(self):
        """Appendix C.3 known answer."""
        key = bytes(range(32))
        block = bytes(range(0, 256, 17))
        ks = _aes_ctr(key, block, b"\x00" * 16)
        assert ks.hex() == "8ea2b7ca516745bfeafc49904b496089"

    def test_ctr_symmetric_any_length(self):
        data = os.urandom(1000)  # not a multiple of 16
        iv = os.urandom(16)
        enc = _aes_ctr(KEY16, iv, data)
        assert enc != data
        assert _aes_ctr(KEY16, iv, enc) == data

    def test_bad_key_length_raises(self):
        with pytest.raises(ValueError, match="16/24/32"):
            _aes_ctr(b"short", b"\x00" * 16, b"data")


class TestEncryptedCheckpoint:
    def test_roundtrip(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        t = paddle.to_tensor(np.arange(8, dtype="float32"))
        paddle.save({"w": t, "step": 7}, p, cipher_key=KEY16)
        back = paddle.load(p, cipher_key=KEY16)
        assert back["step"] == 7
        np.testing.assert_array_equal(back["w"].numpy(), t.numpy())

    def test_ciphertext_not_plaintext(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        paddle.save({"secret": "sauce"}, p, cipher_key=KEY16)
        blob = open(p, "rb").read()
        assert b"secret" not in blob and b"sauce" not in blob

    def test_missing_key_raises(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        paddle.save({"w": 1}, p, cipher_key=KEY16)
        with pytest.raises(ValueError, match="cipher_key"):
            paddle.load(p)

    def test_wrong_key_fails_to_unpickle(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        paddle.save({"w": 1}, p, cipher_key=KEY16)
        with pytest.raises(Exception):
            paddle.load(p, cipher_key=b"fedcba9876543210")

    def test_unencrypted_unaffected(self, tmp_path):
        p = str(tmp_path / "m.pd")
        paddle.save({"w": 1}, p)
        assert paddle.load(p)["w"] == 1
