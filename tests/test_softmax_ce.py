"""Fused Pallas softmax-cross-entropy (LM-head loss hot path).

Reference analog: `c_softmax_with_cross_entropy`
(`operators/collective/c_softmax_with_cross_entropy_op.cu`) and the phi
cross_entropy kernels — softmax+NLL fused so the [N, V] probability array
never round-trips HBM. Kernels run in the Pallas interpreter on CPU.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F
from paddle_tpu.ops.pallas import softmax_ce as sce


@pytest.fixture(autouse=True)
def _interpret_mode():
    old = sce._INTERPRET
    sce._INTERPRET = True
    yield
    sce._INTERPRET = old


def _ref_nll(lg, lb):
    lgf = np.asarray(lg, np.float32)
    N, V = lgf.shape
    m = lgf.max(-1)
    lse = m + np.log(np.exp(lgf - m[:, None]).sum(-1))
    lbn = np.asarray(lb)
    ok = (lbn >= 0) & (lbn < V)
    picked = np.where(ok, lgf[np.arange(N), np.clip(lbn, 0, V - 1)], 0.0)
    return lse - picked, ok


class TestFusedSoftmaxCE:
    @pytest.mark.parametrize("N,V", [(128, 8192), (256, 50257), (100, 5000)])
    def test_forward_matches_reference(self, N, V):
        rng = np.random.default_rng(0)
        lg = jnp.asarray(rng.normal(size=(N, V)).astype(np.float32))
        lb = jnp.asarray(rng.integers(0, V, N).astype(np.int32))
        nll = sce.fused_softmax_ce(lg, lb)
        ref, _ = _ref_nll(lg, lb)
        np.testing.assert_allclose(np.asarray(nll), ref, atol=1e-4)

    def test_backward_matches_softmax_minus_onehot(self):
        rng = np.random.default_rng(1)
        N, V = 64, 8192
        lg = jnp.asarray(rng.normal(size=(N, V)).astype(np.float32))
        lb = jnp.asarray(rng.integers(0, V, N).astype(np.int32))
        w = jnp.asarray(rng.normal(size=(N,)).astype(np.float32))
        g = jax.grad(lambda x: jnp.sum(sce.fused_softmax_ce(x, lb) * w))(lg)
        p = jax.nn.softmax(lg, -1)
        want = (p - jax.nn.one_hot(lb, V)) * w[:, None]
        np.testing.assert_allclose(np.asarray(g), np.asarray(want), atol=1e-5)

    def test_bf16_logits_bf16_cotangent(self):
        """The whole point: dlogits comes back in the LOGITS dtype, no
        fp32 [N, V] intermediate surfaced to the caller."""
        rng = np.random.default_rng(2)
        N, V = 64, 8192
        lg = jnp.asarray(rng.normal(size=(N, V)), jnp.bfloat16)
        lb = jnp.asarray(rng.integers(0, V, N).astype(np.int32))
        g = jax.grad(lambda x: sce.fused_softmax_ce(x, lb).sum())(lg)
        assert g.dtype == jnp.bfloat16
        p = jax.nn.softmax(lg.astype(jnp.float32), -1)
        want = p - jax.nn.one_hot(lb, V)
        err = float(jnp.abs(g.astype(jnp.float32) - want).max())
        assert err < 1e-2, err

    def test_cross_entropy_routes_to_kernel_and_matches(self):
        """nn.functional.cross_entropy takes the fused path for big-vocab
        hard labels and stays numerically identical to the XLA path,
        including ignore_index rows (zero loss AND zero grad)."""
        rng = np.random.default_rng(3)
        B, L, V = 4, 32, 8192
        lg = rng.normal(size=(B, L, V)).astype(np.float32)
        lb = rng.integers(0, V, (B, L)).astype(np.int32)
        lb[0, :5] = -100  # ignore
        before = dict(sce._stats)
        tl, tb = paddle.to_tensor(lg), paddle.to_tensor(lb)
        tl.stop_gradient = False
        loss = F.cross_entropy(tl, tb, ignore_index=-100)
        loss.backward()
        assert sce._stats["pallas"] > before["pallas"], sce._stats
        assert sce._stats["pallas_bwd"] > before["pallas_bwd"], sce._stats
        grad = tl.grad.numpy()
        # XLA reference path: force eligibility OFF so this comparison is
        # pallas-vs-XLA even when the suite runs on a real TPU (where
        # _INTERPRET=False alone would leave the fused path eligible)
        orig = sce.fused_softmax_ce_eligible
        sce.fused_softmax_ce_eligible = lambda *a, **k: False
        try:
            tl2 = paddle.to_tensor(lg)
            tl2.stop_gradient = False
            loss2 = F.cross_entropy(tl2, tb, ignore_index=-100)
            loss2.backward()
        finally:
            sce.fused_softmax_ce_eligible = orig
        np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-5)
        np.testing.assert_allclose(grad, tl2.grad.numpy(), atol=1e-5)
        # ignored rows: exactly zero gradient
        assert np.abs(grad[0, :5]).max() == 0.0

    def test_small_vocab_stays_on_xla(self):
        rng = np.random.default_rng(4)
        lg = jnp.asarray(rng.normal(size=(64, 100)).astype(np.float32))
        lb = jnp.asarray(rng.integers(0, 100, 64).astype(np.int32))
        assert not sce.fused_softmax_ce_eligible(lg, lb)
