"""The bench harness itself must be unkillable (round-3 lesson: one backend
failure produced rc=1 and no JSON, losing the whole round's perf record).

These tests pin the harness's degradation contract without any real device:
- backend-init failure → one JSON line with an `error` field, rc 0;
- any single config raising → structured per-config error, others intact;
- flagship failure → JSON still printed, `value: null` + `error`.
"""
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_main(bench, capsys):
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, f"bench must print exactly ONE line, got {out}"
    return json.loads(out[0])


def test_backend_init_failure_emits_error_json(capsys, monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_init_backend_with_retry",
                        lambda: "RuntimeError: TPU is wedged")
    rec = _run_main(bench, capsys)
    assert "TPU is wedged" in rec["error"]
    assert rec["value"] is None
    assert rec["metric"]  # schema intact for the driver

def test_one_config_failure_does_not_sink_others(capsys, monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_init_backend_with_retry", lambda: None)
    monkeypatch.setattr(bench, "bench_gpt2", lambda: {
        "tokens_per_sec_chip": 123.0, "step_time_ms": 1.0, "mfu": 0.5})
    monkeypatch.setattr(bench, "bench_resnet50",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    for name in ("bench_bert_base", "bench_wide_deep_ps",
                 "bench_wide_deep_ps_tpu"):
        monkeypatch.setattr(bench, name, lambda: {"ok": 1})
    rec = _run_main(bench, capsys)
    assert rec["value"] == 123.0
    assert "boom" in rec["configs"]["resnet50"]["error"]
    assert rec["configs"]["bert_base_seq128"] == {"ok": 1}
    assert "error" not in rec


def test_flagship_failure_still_prints_json(capsys, monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_init_backend_with_retry", lambda: None)
    for name in ("bench_gpt2", "bench_resnet50", "bench_bert_base",
                 "bench_wide_deep_ps", "bench_wide_deep_ps_tpu"):
        monkeypatch.setattr(
            bench, name,
            lambda: (_ for _ in ()).throw(RuntimeError("all dead")))
    rec = _run_main(bench, capsys)
    assert rec["value"] is None
    assert "flagship" in rec["error"]
    assert "all dead" in rec["configs"]["gpt2_small"]["error"]


def test_import_paddle_tpu_does_not_init_backend():
    """`import paddle_tpu` must never touch the jax backend: a subprocess
    that merely imports the package must not bind (or hang on) the TPU.
    Round-3 root cause: framework/random.py built a PRNGKey at import."""
    import subprocess
    code = (
        "import paddle_tpu\n"
        "from jax._src import xla_bridge as xb\n"
        "assert not getattr(xb, '_backends', None), 'backend initialized'\n"
        "print('LAZY_OK')\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("JAX_PLATFORMS", None)  # the real-world (driver) condition
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0 and "LAZY_OK" in r.stdout, r.stderr[-2000:]
