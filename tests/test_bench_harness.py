"""The bench harness itself must be unkillable (round-3 lesson: one backend
failure produced rc=1 and no JSON, losing the whole round's perf record).

These tests pin the harness's degradation contract without any real device:
- backend-init failure → one JSON line with an `error` field, rc 0;
- any single config raising → structured per-config error, others intact;
- flagship failure → JSON still printed, `value: null` + `error`.
"""
import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_main(bench, capsys):
    bench.main()
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, f"bench must print exactly ONE line, got {out}"
    return json.loads(out[0])


def test_backend_init_failure_emits_error_json(capsys, monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_init_backend_with_retry",
                        lambda: "RuntimeError: TPU is wedged")
    rec = _run_main(bench, capsys)
    assert "TPU is wedged" in rec["error"]
    assert rec["value"] is None
    assert rec["metric"]  # schema intact for the driver

def test_one_config_failure_does_not_sink_others(capsys, monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_init_backend_with_retry", lambda: None)
    monkeypatch.setattr(bench, "bench_gpt2", lambda: {
        "tokens_per_sec_chip": 123.0, "step_time_ms": 1.0, "mfu": 0.5})
    monkeypatch.setattr(bench, "bench_resnet50",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    for name in ("bench_gpt2_decode", "bench_bert_base",
                 "bench_wide_deep_ps", "bench_wide_deep_ps_tpu"):
        monkeypatch.setattr(bench, name, lambda: {"ok": 1})
    rec = _run_main(bench, capsys)
    assert rec["value"] == 123.0
    assert "boom" in rec["configs"]["resnet50"]["error"]
    bert = rec["configs"]["bert_base_seq128"]
    assert bert["ok"] == 1
    # every config carries its autotune activity block (PR-10), valid per
    # the check_bench_result schema
    assert isinstance(bert["autotune"], dict)
    assert isinstance(bert["autotune"]["enabled"], bool)
    from tools import check_bench_result as gate
    assert not [p for p in gate.validate_observability(rec)
                if "autotune" in p]
    assert "error" not in rec


def test_flagship_failure_still_prints_json(capsys, monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_init_backend_with_retry", lambda: None)
    for name in ("bench_gpt2", "bench_gpt2_decode", "bench_resnet50",
                 "bench_bert_base", "bench_wide_deep_ps",
                 "bench_wide_deep_ps_tpu"):
        monkeypatch.setattr(
            bench, name,
            lambda: (_ for _ in ()).throw(RuntimeError("all dead")))
    rec = _run_main(bench, capsys)
    assert rec["value"] is None
    assert "flagship" in rec["error"]
    assert "all dead" in rec["configs"]["gpt2_small"]["error"]


def test_bench_json_includes_observability_snapshot(capsys, monkeypatch):
    """PR 2: the bench line must carry the metrics snapshot + retrace
    summary + schema-valid step records under `observability`."""
    from paddle_tpu.profiler.monitor import (make_step_record,
                                             validate_step_record)
    bench = _load_bench()
    monkeypatch.setattr(bench, "_init_backend_with_retry", lambda: None)
    monkeypatch.setattr(bench, "bench_gpt2", lambda: {
        "tokens_per_sec_chip": 1.0, "step_time_ms": 1.0, "mfu": 0.5})
    for name in ("bench_gpt2_decode", "bench_resnet50", "bench_bert_base",
                 "bench_wide_deep_ps", "bench_wide_deep_ps_tpu"):
        monkeypatch.setattr(bench, name, lambda: {"ok": 1})
    # a timed run would have appended one of these (schema from monitor.py)
    bench._STEP_RECORDS.append(make_step_record(
        step=40, window_steps=40, window_time_s=2.0, samples=320,
        flops_per_step=1e12, peak_flops=197e12, retraces=0))
    rec = _run_main(bench, capsys)
    obs = rec["observability"]
    assert isinstance(obs["metrics"], dict)
    # counter families registered at import are in the snapshot even on CPU
    assert "op_calls_total" in obs["metrics"]
    assert "collective_bytes_total" in obs["metrics"]
    assert "jit_retraces_total" in obs["metrics"]
    assert isinstance(obs["retraces_total"], int)
    assert obs["step_records"], "step records must be folded in"
    for sr in obs["step_records"]:
        validate_step_record(sr)
    assert sr["ips"] == 160.0  # 320 samples / 2 s
    # fleet-observability PR: compile attribution + device split + events
    from paddle_tpu.profiler.events import validate_event
    assert isinstance(obs["compile_attribution"], dict)
    for entry, stats in obs["compile_attribution"].items():
        assert stats["count"] >= 1 and stats["seconds"] >= 0
    # --profile-steps is default-ON (ROADMAP 1c), so the eager probe runs
    # under an xplane capture unless opted out
    assert obs["device_time"]["mode"] in ("estimate", "measured", "xplane")
    assert obs["device_time"]["rows"], "device-time probe produced no rows"
    for ev in obs["events_tail"]:
        validate_event(ev)


def test_run_config_emits_step_record(monkeypatch):
    """bench._run_config appends a schema-valid step record per timed run
    (exercised with a stub compiled step — no device needed)."""
    from paddle_tpu.profiler.monitor import validate_step_record
    bench = _load_bench()
    import jax.numpy as jnp

    class _Opt:
        def get_lr(self):
            return 0.1

    class _Compiled:
        def cost_analysis(self):
            return {"flops": 2e9, "bytes accessed": 1e6}

        def __call__(self, params, buffers, opt_state, rng, lr, t, *arrs):
            return jnp.zeros(()), params, buffers, opt_state

    class _Lowered:
        def compile(self):
            return _Compiled()

    class _Step:
        optimizer = _Opt()
        params, buffers, opt_state = {}, {}, {}

        class _S:
            @staticmethod
            def lower(*a, **kw):
                return _Lowered()
        _step = _S()

    class _Arg:
        data = jnp.ones((4, 8), jnp.float32)

    n0 = len(bench._STEP_RECORDS)
    sec, loss, flops, nbytes = bench._run_config(
        _Step(), (_Arg(),), iters=3, warmup=1)
    assert flops == 2e9 and loss == 0.0
    assert len(bench._STEP_RECORDS) == n0 + 1
    sr = bench._STEP_RECORDS[-1]
    validate_step_record(sr)
    assert sr["window_steps"] == 3
    assert sr["samples"] == 12  # batch 4 x 3 iters
    assert sr["flops_per_step_est"] == 2e9


def test_import_paddle_tpu_does_not_init_backend():
    """`import paddle_tpu` must never touch the jax backend: a subprocess
    that merely imports the package must not bind (or hang on) the TPU.
    Round-3 root cause: framework/random.py built a PRNGKey at import."""
    import subprocess
    code = (
        "import paddle_tpu\n"
        "from jax._src import xla_bridge as xb\n"
        "assert not getattr(xb, '_backends', None), 'backend initialized'\n"
        "print('LAZY_OK')\n")
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("JAX_PLATFORMS", None)  # the real-world (driver) condition
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0 and "LAZY_OK" in r.stdout, r.stderr[-2000:]


def test_profile_steps_captures_compiled_run(monkeypatch, tmp_path):
    """--profile-steps: _run_config with a profile label runs a bounded
    xplane capture of the compiled step and records a measured-vs-estimate
    result under _PROFILE_RESULTS (stub executable, CPU-fast)."""
    bench = _load_bench()
    import jax.numpy as jnp

    class _Opt:
        def get_lr(self):
            return 0.1

    class _Compiled:
        def cost_analysis(self):
            return {"flops": 2e9, "bytes accessed": 1e6}

        def __call__(self, params, buffers, opt_state, rng, lr, t, *arrs):
            # enough real jax work for the trace to hold backend events
            x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
            return x.sum() * 0.0, params, buffers, opt_state

    class _Lowered:
        def compile(self):
            return _Compiled()

    class _Step:
        optimizer = _Opt()
        params, buffers, opt_state = {}, {}, {}

        class _S:
            @staticmethod
            def lower(*a, **kw):
                return _Lowered()
        _step = _S()

    class _Arg:
        data = jnp.ones((4, 8), jnp.float32)

    monkeypatch.setenv("PADDLE_TPU_PROFILE_DIR", str(tmp_path))
    monkeypatch.setattr(bench, "_PROFILE_STEPS", 2)
    bench._run_config(_Step(), (_Arg(),), iters=2, warmup=1,
                      profile_label="stub_cfg")
    prof = bench._PROFILE_RESULTS["stub_cfg"]
    assert "error" not in prof, prof
    assert prof["status"] == "complete"
    assert prof["steps"] == 2
    assert prof["device_ms_per_step_cost_model"] is not None
    # the capture correlated the train_step span from the real trace
    assert prof["correlation"]["spans"] >= 2
    assert os.path.isdir(prof["session_dir"])


def test_main_rejects_unknown_args_only_from_cli():
    """bench.main() with no argv must ignore the caller's sys.argv (the
    harness tests run under pytest whose flags argparse would reject)."""
    bench = _load_bench()
    import argparse
    old = sys.argv
    sys.argv = ["bench.py", "--definitely-not-a-bench-flag"]
    try:
        # only reaches argparse: init is stubbed to fail fast
        bench._init_backend_with_retry = lambda: "stop here"
        bench.main()  # must not SystemExit on pytest-style argv
    finally:
        sys.argv = old


def test_device_time_probe_xplane_mode(monkeypatch, tmp_path):
    """With --profile-steps set, the bench's eager device-time probe runs
    inside a capture session: rows carry src="xplane" and the correlation
    block reports the measured-vs-estimate delta per op."""
    bench = _load_bench()
    monkeypatch.setenv("PADDLE_TPU_PROFILE_DIR", str(tmp_path))
    monkeypatch.setattr(bench, "_PROFILE_STEPS", 1)
    probe = bench._device_time_probe()
    assert probe["mode"] == "xplane", probe
    assert any(r["src"] == "xplane" for r in probe["rows"])
    assert probe["correlation"]["correlated"] >= 1
    by_op = {r["op"]: r for r in probe["correlation"]["by_op"]}
    assert "matmul" in by_op
    assert by_op["matmul"]["xplane_ms"] > 0


import pytest


@pytest.mark.slow  # compiles 8 small resnet TrainStep variants (~2 min)
# fast-sibling: test_resnet_conv_fusion_block_shape validates the block
# contract without the full probe sweep
def test_bench_resnet50_emits_conv_fusion_block():
    """The r06 conv-fusion A/B probe rides bench_resnet50 at CPU-feasible
    shapes and validates against the gate."""
    bench = _load_bench()
    cfg = bench.bench_resnet50(B=4, hw=32, depth=18, probe_iters=2)
    cf = cfg["conv_fusion"]
    assert cf["enabled"] is True
    assert isinstance(cf.get("engaged"), bool)
    assert cf["probe_ms_on"] > 0 and cf["probe_ms_off"] > 0
    assert cfg["platform"] == "cpu"
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_bench_result as gate
    doc = {"configs": {"resnet50": cfg}}
    assert [p for p in gate.validate_observability(doc)
            if "conv_fusion" in p] == []


def test_resnet_conv_fusion_block_shape():
    """Fast sibling: the emitted block's field contract (no probe sweep)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import check_bench_result as gate
    block = {"enabled": True, "engaged": False,
             "kernel_stats": {"pallas_fwd": 0, "xla_fwd": 0,
                              "pallas_bwd": 0, "xla_bwd": 0},
             "probe_ms_on": 10.0, "probe_ms_off": 11.0,
             "speedup_vs_off": 1.1, "hbm_gb_per_step_on": 1.0,
             "hbm_gb_per_step_off": 1.2, "hbm_pct_saved": 16.7,
             "note": "x"}
    doc = {"configs": {"resnet50": {"samples_per_sec_chip": 1.0,
                                    "conv_fusion": block}}}
    assert gate.validate_observability(doc) == []
