"""Training-health numerics plane (profiler/health.py): in-graph
sentinel, eager first-NaN attribution, trend detection, divergence
auto-response.

Acceptance contract (ISSUE 10): with the health plane armed, a NaN
injected into a named layer mid-run is (a) detected by the in-graph
sentinel within the fetch interval, (b) attributed to that layer in a
`tensor_health` event, and (c) `action=rollback` resumes from the last
numerically-valid checkpoint bit-identically.

fast-sibling: every slow test here has fast siblings throughout this
module (sentinel, attribution, rollback e2e all run in tier-1).
"""
import math
import os

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.hapi.callbacks import (Callback, FaultTolerantCheckpoint,
                                       HealthMonitor)
from paddle_tpu.jit import TrainStep
from paddle_tpu.nn import functional as F
from paddle_tpu.profiler import events as events_mod
from paddle_tpu.profiler import health
from paddle_tpu.profiler import metrics as metrics_mod


@pytest.fixture(autouse=True)
def _clean_health_state():
    health.reset()
    yield
    health.reset()


class MLP(nn.Layer):
    def __init__(self, din=8, hidden=16, dout=4):
        super().__init__()
        self.fc1 = nn.Linear(din, hidden)
        self.fc2 = nn.Linear(hidden, dout)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def _mlp_step(health_on=True, lr=1e-2):
    paddle.seed(7)
    m = MLP()
    opt = optimizer.Adam(learning_rate=lr, parameters=m.parameters())
    step = TrainStep(m, F.cross_entropy, opt, health=health_on)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 8).astype("float32"))
    y = paddle.to_tensor(np.array([0, 1, 2, 3], dtype="int64"))
    return m, step, x, y


class TestHealthProbe:
    def test_grouping_drops_leaf_and_caps_depth(self):
        assert health._group_name("blocks.3.attn.qkv.weight") == "blocks.3"
        assert health._group_name("fc2.bias") == "fc2"
        assert health._group_name("weight") == "(root)"

    def test_bounded_cardinality(self):
        params = {f"layer{i}.weight": jnp.zeros((2,)) for i in range(100)}
        probe = health.HealthProbe(params, max_groups_=8)
        assert len(probe.group_names) == 8
        assert all(g.startswith("bucket") for g in probe.group_names)
        # every param maps into a bucket
        assert set(probe._group_of) == set(params)

    def test_stats_vec_decode_roundtrip(self):
        params = {"fc1.weight": jnp.ones((3, 2)), "fc2.weight": jnp.ones((2,))}
        grads = {"fc1.weight": jnp.full((3, 2), 2.0),
                 "fc2.weight": jnp.full((2,), 3.0)}
        new_params = {k: v - 0.5 for k, v in params.items()}
        probe = health.HealthProbe(params)
        stats = probe.decode(probe.stats_vec(
            jnp.asarray(1.25), grads, params, new_params))
        assert stats["loss"] == pytest.approx(1.25)
        assert not stats["nonfinite"]
        assert stats["grad_norm"] == pytest.approx(
            math.sqrt(6 * 4.0 + 2 * 9.0))
        assert stats["group_grad_norms"]["fc1"] == pytest.approx(
            math.sqrt(24.0))
        assert stats["group_grad_norms"]["fc2"] == pytest.approx(
            math.sqrt(18.0))
        # update ratio: ||0.5 * ones(8)|| / ||ones(8)||
        assert stats["update_ratio"] == pytest.approx(0.5)
        assert stats["bad_param_groups"] == []

    def test_nonfinite_flag_and_bad_param_group(self):
        params = {"fc1.weight": jnp.ones((2,)),
                  "fc2.weight": jnp.asarray([jnp.nan, 1.0])}
        grads = {k: jnp.zeros_like(v) for k, v in params.items()}
        probe = health.HealthProbe(params)
        stats = probe.decode(probe.stats_vec(
            jnp.asarray(0.5), grads, params, params))
        assert stats["nonfinite"]
        assert stats["bad_param_groups"] == ["fc2"]

    def test_nan_loss_trips_flag(self):
        params = {"w": jnp.ones((2,))}
        grads = {"w": jnp.zeros((2,))}
        probe = health.HealthProbe(params)
        stats = probe.decode(probe.stats_vec(
            jnp.asarray(jnp.nan), grads, params, params))
        assert stats["nonfinite"]


class TestTrainStepSentinel:
    def test_healthy_steps_record_stats(self):
        _, step, x, y = _mlp_step()
        for _ in range(2):
            step(x, y)
        stats = health.last_stats()
        assert stats is not None and stats["step"] == 2
        assert not stats["nonfinite"]
        assert stats["grad_norm"] > 0
        assert set(stats["group_grad_norms"]) == {"fc1", "fc2"}
        assert health.last_status() == "ok"
        # gauges live
        reg = metrics_mod.default_registry()
        assert reg.get("health_grad_norm").value() > 0

    def test_health_off_returns_plain_tuple(self):
        _, step, x, y = _mlp_step(health_on=False)
        step(x, y)
        assert step.last_health is None
        assert health.last_stats() is None

    def test_interval_bounds_fetch_cadence(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_HEALTH_INTERVAL", "3")
        _, step, x, y = _mlp_step()
        for _ in range(5):
            step(x, y)
        # fetched at steps 3 only within 1..5 (6 would be next)
        assert health.last_stats()["step"] == 3

    def test_injected_nan_attributed_to_layer(self):
        """Acceptance (a)+(b): poison fc2's weight -> the sentinel trips
        on the next step and the tensor_health event names fc2."""
        _, step, x, y = _mlp_step()
        for _ in range(2):
            step(x, y)
        events_mod.default_event_log().clear()
        step.params["fc2.weight"] = \
            step.params["fc2.weight"].at[0, 0].set(jnp.nan)
        step(x, y)
        assert step.last_health["nonfinite"]
        assert health.tripped()
        sentinel = [e for e in events_mod.recent(20, kind="tensor_health")
                    if e.get("src") == "sentinel"]
        assert len(sentinel) == 1
        assert sentinel[0]["bad_groups"] == ["fc2"]
        assert sentinel[0]["severity"] == "error"
        # the one-shot eager replay produced an op-level attribution too
        assert step.last_attribution is not None
        assert step.last_attribution["bad_kind"] == "nan"
        # nonfinite counter incremented for the sentinel source
        reg = metrics_mod.default_registry()
        assert reg.get("health_nonfinite_total").value(src="sentinel") >= 1

    def test_replay_runs_once_per_trip(self):
        _, step, x, y = _mlp_step()
        step(x, y)
        events_mod.default_event_log().clear()
        step.params["fc1.weight"] = \
            step.params["fc1.weight"].at[0, 0].set(jnp.inf)
        step(x, y)
        step(x, y)  # still bad: no second replay, no second trip event
        sentinel = [e for e in events_mod.recent(50, kind="tensor_health")
                    if e.get("src") == "sentinel"]
        eager = [e for e in events_mod.recent(50, kind="tensor_health")
                 if e.get("src") == "eager"]
        assert len(sentinel) == 1
        assert len(eager) == 1


class TestEagerCheckFlag:
    """FLAGS_check_nan_inf routes to the health plane; jax_debug_nans is
    the explicit FLAGS_debug_nans / PADDLE_TPU_DEBUG_NANS escape hatch."""

    def test_runtime_set_flags_arms_dispatch_check(self):
        import jax
        prev_debug = jax.config.jax_debug_nans
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            events_mod.default_event_log().clear()
            a = paddle.to_tensor(np.array([1.0], np.float32))
            b = paddle.to_tensor(np.array([0.0], np.float32))
            with pytest.raises(FloatingPointError) as ei:
                a / b
            assert "inf" in str(ei.value)
            ev = events_mod.recent(10, kind="tensor_health")
            assert ev and ev[-1]["src"] == "eager"
            assert ev[-1]["bad_kind"] == "inf"
            assert ev[-1]["op"]
            # the flag no longer touches jax_debug_nans
            assert jax.config.jax_debug_nans == prev_debug
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_eager_attribution_names_layer_path(self):
        paddle.seed(0)
        net = MLP()
        net.fc2.weight.data = net.fc2.weight.data.at[0, 0].set(jnp.nan)
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            health.index_model(net)
            x = paddle.to_tensor(np.ones((2, 8), np.float32))
            with pytest.raises(FloatingPointError) as ei:
                net(x)
            assert "fc2" in str(ei.value)
            ev = events_mod.recent(10, kind="tensor_health")[-1]
            assert ev["layer"] == "fc2"
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})

    def test_debug_nans_escape_hatch(self):
        import jax
        prev = jax.config.jax_debug_nans
        try:
            paddle.set_flags({"FLAGS_debug_nans": True})
            assert jax.config.jax_debug_nans is True
            paddle.set_flags({"FLAGS_debug_nans": False})
            assert jax.config.jax_debug_nans is False
        finally:
            jax.config.update("jax_debug_nans", prev)

    def test_health_enabled_follows_flag(self):
        assert not health.enabled()
        paddle.set_flags({"FLAGS_check_nan_inf": True})
        try:
            assert health.enabled()
        finally:
            paddle.set_flags({"FLAGS_check_nan_inf": False})


class TestHealthMonitor:
    def _monitor(self, **kw):
        kw.setdefault("action", "warn")
        kw.setdefault("window", 10)
        return HealthMonitor(**kw)

    def test_loss_spike_confirmed_after_streak(self):
        hm = self._monitor(confirm_steps=3, z_threshold=4.0)
        for i in range(20):
            hm.observe(loss=1.0 + 0.01 * (i % 3))
        for i in range(3):
            hm.observe(loss=100.0 * (i + 1))
        sigs = [a["signal"] for a in hm.alerts]
        assert "loss_spike_suspect" in sigs
        assert "loss_spike" in sigs
        assert health.last_status() == "diverged"

    def test_single_outlier_not_confirmed(self):
        hm = self._monitor(confirm_steps=3, z_threshold=4.0)
        for i in range(20):
            hm.observe(loss=1.0 + 0.01 * (i % 3))
        hm.observe(loss=100.0)
        for _ in range(5):
            hm.observe(loss=1.0)
        assert "loss_spike" not in [a["signal"] for a in hm.alerts]

    def test_nonfinite_is_immediate(self):
        hm = self._monitor()
        hm.observe(loss=float("nan"), nonfinite=False)  # detected from loss
        assert hm.alerts and hm.alerts[0]["signal"] == "nonfinite"

    def test_halt_sets_stop_training(self):
        hm = self._monitor(action="halt")

        class M:
            stop_training = False
        hm.model = M()
        hm.observe(nonfinite=True)
        assert hm.model.stop_training

    def test_grad_explosion_and_vanishing_warn(self):
        hm = self._monitor(explode_factor=10.0, vanish_steps=3,
                           vanish_threshold=1e-8)
        for _ in range(10):
            hm.observe(loss=1.0, grad_norm=1.0)
        hm.observe(loss=1.0, grad_norm=500.0)
        assert "grad_explosion" in [a["signal"] for a in hm.alerts]
        for _ in range(3):
            hm.observe(loss=1.0, grad_norm=0.0)
        assert "grad_vanishing" in [a["signal"] for a in hm.alerts]
        # warn-level signals never run the response
        assert health.last_status() in ("warn", "ok")

    def test_stagnation_alert(self):
        hm = self._monitor(stagnation_steps=10, stagnation_rel=1e-3)
        for _ in range(25):
            hm.observe(loss=1.0)
        assert "stagnation" in [a["signal"] for a in hm.alerts]

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError):
            HealthMonitor(action="explode")

    def test_confirmed_spike_rebaselines_not_floods(self):
        """A legitimate plateau shift under action=warn: ONE confirmed
        loss_spike, then the detectors re-learn the new level instead of
        re-confirming (and emitting an error alert) every step."""
        hm = self._monitor(confirm_steps=2, z_threshold=4.0,
                           cooldown_steps=5)
        for i in range(20):
            hm.observe(loss=1.0 + 0.01 * (i % 3))
        for _ in range(30):  # loss moved to a new, stable plateau
            hm.observe(loss=50.0)
        confirmed = [a for a in hm.alerts if a["signal"] == "loss_spike"]
        assert len(confirmed) == 1

    def test_persistent_nonfinite_respects_cooldown(self):
        hm = self._monitor(cooldown_steps=10)
        for _ in range(12):
            hm.observe(nonfinite=True)
        nf = [a for a in hm.alerts if a["signal"] == "nonfinite"]
        assert len(nf) == 2  # once per cooldown window, not per step

    def test_midrun_step_numbers_need_warmup_observations(self):
        """The z-test warmup gate counts OBSERVED losses, not the
        caller's absolute step number: a manual loop feeding mid-run
        step counters must not confirm a spurious divergence on its
        first few observations."""
        hm = self._monitor(action="halt", confirm_steps=3, z_threshold=6.0)

        class M:
            stop_training = False
        hm.model = M()
        for i in range(5):  # normal noise at big step numbers
            hm.observe(loss=1.0 + 0.01 * (i % 2), step=1000 + i)
        assert not hm.model.stop_training
        assert "loss_spike" not in [a["signal"] for a in hm.alerts]

    def test_constant_warmup_loss_tolerates_noise(self):
        """Near-zero variance must not turn normal noise into a
        five-digit z-score (relative std floor)."""
        hm = self._monitor(confirm_steps=3, z_threshold=6.0)
        for _ in range(20):
            hm.observe(loss=2.0)       # constant: var == 0
        for _ in range(5):
            hm.observe(loss=2.004)     # 0.2% wiggle
        assert "loss_spike" not in [a["signal"] for a in hm.alerts]

    def test_logs_only_monitor_status_recovers(self):
        """Without a sentinel, a confirmed spike must not pin the host's
        digest status at 'diverged' forever (fleet re-arm semantics)."""
        hm = self._monitor(confirm_steps=2, z_threshold=4.0,
                           cooldown_steps=3)
        for i in range(20):
            hm.observe(loss=1.0 + 0.01 * (i % 3))
        for _ in range(3):
            hm.observe(loss=500.0)
        assert health.last_status() == "diverged"
        for i in range(20):  # past cooldown, clean steps
            hm.observe(loss=500.0 + 0.5 * (i % 3))
        assert health.last_status() == "ok"

    def test_rollback_walkback_on_sharded_layout(self, tmp_path):
        """The finiteness walk-back must read sharded step DIRECTORIES
        through the chunked backend, not open(dir) and skip them all."""
        from paddle_tpu.distributed.sharded_checkpoint import \
            ShardedCheckpointManager
        mgr = ShardedCheckpointManager(str(tmp_path), rank=0, world_size=1)
        good = {"network": {"w": np.ones((4,), np.float32)},
                "optimizer": None, "train_step": None, "rng": None}
        bad = {"network": {"w": np.full((4,), np.nan, np.float32)},
               "optimizer": None, "train_step": None, "rng": None}
        mgr.save(good, step=1)
        mgr.save(bad, step=2)
        mgr.drain()
        hm = HealthMonitor(action="rollback", checkpoint=mgr)
        found = hm._load_numerically_valid(mgr, step=3)
        assert found is not None
        blob, step = found
        assert step == 1
        np.testing.assert_array_equal(
            np.asarray(blob["network"]["w"]), np.ones((4,), np.float32))

    def test_rollback_without_model_degrades_to_halt(self, tmp_path):
        """Manual-loop monitor with no set_model(): the response must not
        raise out of observe() (the plane never takes down training)."""
        from paddle_tpu.distributed.checkpoint import CheckpointManager
        mgr = CheckpointManager(str(tmp_path))
        mgr.save({"network": {"w": np.ones((2,), np.float32)}}, step=1)
        hm = HealthMonitor(action="rollback", checkpoint=mgr)
        hm.observe(nonfinite=True)  # no model attached — must not raise
        assert hm.rollbacks == 0
        assert any(a["signal"] == "rollback_failed" for a in hm.alerts)

    def test_env_action_default(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_HEALTH_ACTION", "halt")
        assert HealthMonitor().action == "halt"


class _FixedDS(paddle.io.Dataset):
    """Deterministic per-index dataset (index-seeded, resume-friendly)."""

    def __init__(self, n=8):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        rng = np.random.RandomState(1000 + i)
        return (rng.randn(4).astype(np.float32),
                rng.randn(2).astype(np.float32))


class _PoisonAt(Callback):
    """Write NaN into the compiled step's params at step-counter `at`."""

    def __init__(self, at):
        super().__init__()
        self.at = at
        self.done = False

    def on_train_batch_end(self, step, logs=None):
        ts = self.model._train_step
        if ts is not None and ts._t == self.at and not self.done:
            self.done = True
            ts.params["weight"] = \
                ts.params["weight"].at[0, 0].set(jnp.nan)


class TestRollbackE2E:
    """Acceptance (c): divergence -> rollback restores the last
    numerically-valid checkpoint bit-identically and training continues."""

    def test_rollback_restores_bit_identical_state(self, tmp_path,
                                                   monkeypatch):
        from paddle_tpu.distributed.checkpoint import CheckpointManager
        from paddle_tpu.framework.random import get_rng_state, set_rng_state
        monkeypatch.setenv("PADDLE_TPU_HEALTH", "1")
        paddle.seed(11)
        net = nn.Linear(4, 2)
        m = paddle.Model(net)
        m.prepare(optimizer.Adam(learning_rate=1e-2,
                                 parameters=net.parameters()),
                  loss=nn.MSELoss())
        x = np.random.RandomState(3).randn(4, 4).astype(np.float32)
        y = np.random.RandomState(4).randn(4, 2).astype(np.float32)
        for _ in range(5):
            m.train_batch([x], [y])
        # checkpoint the exact state at step 5 (the _capture shape)
        m._sync_from_train_step()
        blob = {
            "network": {k: np.asarray(v.data)
                        for k, v in net.state_dict().items()},
            "optimizer": m._optimizer.state_dict(),
            "train_step": m._train_step.state_dict(),
            "rng": np.asarray(get_rng_state()),
            "epoch": 0, "step_in_epoch": 5, "global_step": 5,
            "epoch_done": False,
        }
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(blob, step=5)
        saved_w = {k: np.asarray(v.data)
                   for k, v in net.state_dict().items()}
        # diverge: poison and take a step (tripping the sentinel)
        m._train_step.params["weight"] = \
            m._train_step.params["weight"].at[0, 0].set(jnp.nan)
        m.train_batch([x], [y])
        assert health.tripped()
        hm = HealthMonitor(action="rollback", checkpoint=mgr)
        hm.set_model(m)
        hm.observe(nonfinite=True, step=6)
        assert hm.rollbacks == 1
        # (1) restored state is bit-identical to the checkpoint
        for k, v in net.state_dict().items():
            np.testing.assert_array_equal(np.asarray(v.data), saved_w[k])
        assert m._train_step is None  # rebuilt on next batch
        assert not health.tripped()
        # (2) continued training == a control resumed from the same file
        cont = [np.asarray(m.train_batch([x], [y])) for _ in range(3)]
        paddle.seed(99)  # control must not depend on ambient RNG
        net2 = nn.Linear(4, 2)
        m2 = paddle.Model(net2)
        m2.prepare(optimizer.Adam(learning_rate=1e-2,
                                  parameters=net2.parameters()),
                   loss=nn.MSELoss())
        blob2, step2 = mgr.load_latest()
        assert step2 == 5
        net2.set_state_dict(blob2["network"])
        m2._optimizer.set_state_dict(blob2["optimizer"])
        m2._pending_ts_state = blob2["train_step"]
        set_rng_state(np.asarray(blob2["rng"]))
        ctrl = [np.asarray(m2.train_batch([x], [y])) for _ in range(3)]
        np.testing.assert_array_equal(np.asarray(cont), np.asarray(ctrl))
        for k, v in net.state_dict().items():
            m._sync_from_train_step()
            m2._sync_from_train_step()
            np.testing.assert_array_equal(
                np.asarray(v.data),
                np.asarray(dict(net2.state_dict())[k].data))

    def test_fit_poison_rollback_recovers(self, tmp_path, monkeypatch):
        """Full fit loop: poison mid-run -> exactly one rollback, the
        poisoned epoch-end checkpoint is skipped by the finiteness
        walk-back, and the run ends with finite weights."""
        monkeypatch.setenv("PADDLE_TPU_HEALTH", "1")
        paddle.seed(42)
        net = nn.Linear(4, 2)
        m = paddle.Model(net)
        m.prepare(optimizer.Adam(learning_rate=1e-2,
                                 parameters=net.parameters()),
                  loss=nn.MSELoss())
        ftc = FaultTolerantCheckpoint(str(tmp_path), save_freq_steps=3)
        hm = HealthMonitor(action="rollback", checkpoint=ftc,
                           cooldown_steps=2)
        events_mod.default_event_log().clear()
        m.fit(_FixedDS(), batch_size=2, epochs=3, shuffle=False, verbose=0,
              callbacks=[hm, ftc, _PoisonAt(4)])
        assert hm.rollbacks == 1
        rb = events_mod.recent(20, kind="health_rollback")
        assert len(rb) == 1 and rb[0]["restored_step"] == 3
        # epoch-end save at step 4 raced detection and captured NaN: the
        # walk-back skipped it
        assert any(a["signal"] == "rollback_skip_nonfinite"
                   for a in hm.alerts)
        w = np.asarray(dict(net.state_dict())["weight"].data)
        assert np.all(np.isfinite(w))
        reg = metrics_mod.default_registry()
        assert reg.get("health_rollback_total").total() >= 1

    def test_saves_skipped_while_tripped(self, tmp_path, monkeypatch):
        """FaultTolerantCheckpoint never persists known-bad state."""
        monkeypatch.setenv("PADDLE_TPU_HEALTH", "1")
        paddle.seed(1)
        net = nn.Linear(4, 2)
        m = paddle.Model(net)
        m.prepare(optimizer.Adam(learning_rate=1e-2,
                                 parameters=net.parameters()),
                  loss=nn.MSELoss())
        ftc = FaultTolerantCheckpoint(str(tmp_path), save_freq_steps=1)
        # no HealthMonitor: nothing clears the trip, so every save after
        # the poison must be skipped
        m.fit(_FixedDS(), batch_size=2, epochs=2, shuffle=False, verbose=0,
              callbacks=[ftc, _PoisonAt(3)])
        from paddle_tpu.distributed.checkpoint import load as load_ckpt
        steps = sorted(ftc.manager.steps())
        # step 3's save ran before the poison callback; step 4 raced
        # detection (sentinel fetches during step 4's train_batch, save
        # happens at its batch end -> skipped). Nothing newer than 4.
        assert max(steps) <= 4
        for s in steps:
            blob = load_ckpt(ftc.manager.path_for(s))
            if s < 4:
                for v in blob["network"].values():
                    assert np.all(np.isfinite(np.asarray(v)))
        ev = [e for e in events_mod.recent(100, kind="health_alert")
              if e.get("signal") == "checkpoint_skipped"]
        assert ev

    @pytest.mark.slow
    def test_rollback_long_run_loss_recovers(self, tmp_path, monkeypatch):
        """Slow full version: a longer fit with a mid-run poison keeps
        training after the rollback and ends at a loss comparable to an
        uninterrupted run's."""
        monkeypatch.setenv("PADDLE_TPU_HEALTH", "1")

        def run(poison):
            paddle.seed(5)
            net = MLP(din=4, hidden=32, dout=2)
            m = paddle.Model(net)
            m.prepare(optimizer.Adam(learning_rate=5e-3,
                                     parameters=net.parameters()),
                      loss=nn.MSELoss())
            cbs = [HealthMonitor(action="rollback",
                                 checkpoint=str(tmp_path / "ckpt"),
                                 cooldown_steps=2),
                   FaultTolerantCheckpoint(str(tmp_path / "ckpt"),
                                           save_freq_steps=5)]
            if poison:
                cbs.append(_PoisonAtMLP(17))
            m.fit(_FixedDS(n=40), batch_size=4, epochs=6, shuffle=False,
                  verbose=0, callbacks=cbs)
            m._sync_from_train_step()
            x = np.random.RandomState(3).randn(8, 4).astype(np.float32)
            y = np.random.RandomState(4).randn(8, 2).astype(np.float32)
            return float(np.asarray(m.eval_batch([x], [y])[0]))

        import shutil
        clean = run(poison=False)
        shutil.rmtree(tmp_path / "ckpt")
        health.reset()
        poisoned = run(poison=True)
        assert math.isfinite(poisoned)
        assert poisoned < clean * 5 + 1.0  # recovered, not diverged


class _PoisonAtMLP(Callback):
    def __init__(self, at):
        super().__init__()
        self.at = at
        self.done = False

    def on_train_batch_end(self, step, logs=None):
        ts = self.model._train_step
        if ts is not None and ts._t == self.at and not self.done:
            self.done = True
            ts.params["fc1.weight"] = \
                ts.params["fc1.weight"].at[0, 0].set(jnp.nan)


class TestAmpScaler:
    """Satellite: found_inf is ONE fused all-leaves reduction with a
    single device fetch, metered on /metrics."""

    def _opt_with_grads(self, grad_value):
        from paddle_tpu.framework.tensor import Tensor
        paddle.seed(0)
        net = nn.Linear(2, 2)
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=net.parameters())
        for p in opt._parameter_list:
            p.grad = Tensor(jnp.full_like(p.data, grad_value))
        return net, opt

    def test_finite_grads_update_and_unscale(self):
        from paddle_tpu.amp import GradScaler
        net, opt = self._opt_with_grads(4.0)
        w0 = np.asarray(opt._parameter_list[0].data).copy()
        sc = GradScaler(enable=True, init_loss_scaling=4.0)
        sc.unscale_(opt)
        assert not sc._found_inf
        # grads unscaled by 1/4
        np.testing.assert_allclose(
            np.asarray(opt._parameter_list[0].grad.data), 1.0)
        sc.step(opt)
        assert not np.allclose(
            w0, np.asarray(opt._parameter_list[0].data))

    def test_inf_grads_skip_step_and_meter(self):
        from paddle_tpu.amp import GradScaler
        reg = metrics_mod.default_registry()
        before = reg.get("amp_found_inf_total").total()
        net, opt = self._opt_with_grads(float("inf"))
        w0 = np.asarray(opt._parameter_list[0].data).copy()
        sc = GradScaler(enable=True, init_loss_scaling=4.0,
                        decr_every_n_nan_or_inf=1)
        sc.step(opt)
        assert sc._scale == 2.0  # backed off
        np.testing.assert_array_equal(
            w0, np.asarray(opt._parameter_list[0].data))  # step skipped
        assert reg.get("amp_found_inf_total").total() == before + 1
        assert reg.get("amp_loss_scale").value() == 2.0

    def test_partial_nan_found(self):
        from paddle_tpu.amp import GradScaler
        from paddle_tpu.framework.tensor import Tensor
        net, opt = self._opt_with_grads(1.0)
        # only ONE leaf, one element bad
        p = opt._parameter_list[1]
        p.grad = Tensor(p.grad.data.at[0].set(jnp.nan))
        sc = GradScaler(enable=True, init_loss_scaling=2.0)
        sc.unscale_(opt)
        assert sc._found_inf

    def test_disabled_scaler_passthrough(self):
        from paddle_tpu.amp import GradScaler
        net, opt = self._opt_with_grads(1.0)
        sc = GradScaler(enable=False)
        w0 = np.asarray(opt._parameter_list[0].data).copy()
        sc.step(opt)
        assert not np.allclose(
            w0, np.asarray(opt._parameter_list[0].data))


class TestPlaneSurfaces:
    """/snapshot health section + fleet digest/aggregator wiring."""

    def test_server_snapshot_has_health_section(self):
        from paddle_tpu.profiler.server import ObservabilityServer
        health.record_step_stats(
            {"loss": 1.0, "nonfinite": False, "grad_norm": 2.0,
             "update_ratio": 0.1, "group_grad_norms": {"fc1": 2.0}},
            step=7)
        snap = ObservabilityServer().snapshot()
        h = snap["health"]
        assert h["status"] == "ok"
        assert h["last"]["step"] == 7
        assert "enabled" in h and "action" in h
        import json
        json.dumps(snap)  # the whole snapshot stays JSON-serializable

    def test_snapshot_sanitizes_nonfinite(self):
        import json
        health.record_step_stats(
            {"loss": float("nan"), "nonfinite": True,
             "grad_norm": float("inf"), "update_ratio": 0.0,
             "group_grad_norms": {"fc1": float("nan")}}, step=1)
        # gauges skipped the nonfinite values
        reg = metrics_mod.default_registry()
        text = reg.to_prometheus_text()
        assert "health_loss nan" not in text.lower()
        # and a TRIPPED snapshot stays strict JSON (no NaN literals)
        snap = health.snapshot()
        payload = json.dumps(snap)
        assert "NaN" not in payload and "Infinity" not in payload
        assert snap["last"]["loss"] is None
        assert snap["tripped"] is True

    def test_tensor_health_served_on_events_endpoint(self):
        """Acceptance (b): the attribution event is visible on /events."""
        import json as _json
        from urllib.request import urlopen
        from paddle_tpu.profiler.server import ObservabilityServer
        _, step, x, y = _mlp_step()
        step(x, y)
        step.params["fc2.weight"] = \
            step.params["fc2.weight"].at[0, 0].set(jnp.nan)
        step(x, y)
        srv = ObservabilityServer()
        port = srv.start(0)
        try:
            body = urlopen(f"http://127.0.0.1:{port}/events"
                           f"?kind=tensor_health", timeout=10).read()
            evs = _json.loads(body)["events"]
            assert any(e.get("src") == "sentinel"
                       and e.get("bad_groups") == ["fc2"] for e in evs)
            snap = _json.loads(urlopen(
                f"http://127.0.0.1:{port}/snapshot", timeout=10).read())
            assert snap["health"]["tripped"] is True
        finally:
            srv.stop()

    def test_fleet_digest_and_aggregator(self):
        from paddle_tpu.distributed.fleet.telemetry import (FleetAggregator,
                                                            FleetReporter)

        class FakeStore:
            def __init__(self):
                self.d = {}

            def set(self, k, v):
                self.d[k] = v.encode() if isinstance(v, str) else v

            def get(self, k):
                return self.d[k]

            def check(self, k):
                return k in self.d

        store = FakeStore()
        rep = FleetReporter(store, rank=0, min_interval_s=0.0,
                            host="trainer-0")
        health.record_step_stats(
            {"loss": float("nan"), "nonfinite": True, "grad_norm": 1.0,
             "update_ratio": 0.0, "group_grad_norms": {}}, step=3)
        rep.publish(3)
        import json as _json
        digest = _json.loads(store.get("obs/digest/0").decode())
        assert digest["health_status"] == "diverged"
        events_mod.default_event_log().clear()
        agg = FleetAggregator(store, world_size=1)
        agg.collect()
        reg = metrics_mod.default_registry()
        assert reg.get("fleet_health_status").value(host="trainer-0") == 2
        ev = events_mod.recent(10, kind="fleet_health")
        assert len(ev) == 1 and ev[0]["unhealthy"] == "trainer-0"
        # no duplicate event while still unhealthy
        agg.collect()
        assert len(events_mod.recent(10, kind="fleet_health")) == 1
        # recovery re-arms
        health.record_step_stats(
            {"loss": 1.0, "nonfinite": False, "grad_norm": 1.0,
             "update_ratio": 0.0, "group_grad_norms": {}}, step=4)
        rep.publish(4)
        agg.collect()
        assert reg.get("fleet_health_status").value(host="trainer-0") == 0
        assert agg.snapshot()["unhealthy"] == []
        # warn -> diverged ESCALATION fires a second (error) event
        health.set_status("warn")
        rep.publish(5)
        agg.collect()
        health.record_step_stats(
            {"loss": float("nan"), "nonfinite": True, "grad_norm": 1.0,
             "update_ratio": 0.0, "group_grad_norms": {}}, step=6)
        rep.publish(6)
        agg.collect()
        fh = events_mod.recent(10, kind="fleet_health")
        assert [e["status"] for e in fh[-2:]] == ["warn", "diverged"]
        assert fh[-1]["severity"] == "error"
