"""Sparse 3-D convolution / pooling (point-cloud family).

Reference: `phi/kernels/sparse/convolution_kernel.h` (rulebook conv,
subm mode) and `sparse_pool_kernel.h`. Parity target: a dense numpy
conv3d/pool over the densified voxel grid.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer, sparse


def _grid(seed=0, N=2, D=6, H=6, W=6, C=3, density=0.2):
    rng = np.random.default_rng(seed)
    mask = rng.random((N, D, H, W)) < density
    coords = np.argwhere(mask)
    vals = rng.normal(size=(coords.shape[0], C)).astype(np.float32)
    x = sparse.sparse_coo_tensor(coords.T, vals, shape=(N, D, H, W, C))
    return x, coords, vals, (N, D, H, W, C)


def _dense_conv_ref(coords, vals, shape, wt, stride, pad):
    N, D, H, W, C = shape
    k = wt.shape[0]
    dense = np.zeros(shape, np.float32)
    dense[tuple(coords.T)] = vals
    Do = (D + 2 * pad - k) // stride + 1
    Ho = (H + 2 * pad - k) // stride + 1
    Wo = (W + 2 * pad - k) // stride + 1
    out = np.zeros((N, Do, Ho, Wo, wt.shape[-1]), np.float32)
    padded = np.pad(dense, ((0, 0), (pad, pad), (pad, pad), (pad, pad),
                            (0, 0)))
    for n in range(N):
        for d in range(Do):
            for h in range(Ho):
                for w in range(Wo):
                    patch = padded[n, d * stride:d * stride + k,
                                   h * stride:h * stride + k,
                                   w * stride:w * stride + k]
                    out[n, d, h, w] = np.einsum("dhwc,dhwco->o", patch, wt)
    return out


class TestSparseConv3D:
    @pytest.mark.parametrize("stride,pad", [(1, 1), (2, 1), (1, 0)])
    def test_matches_dense_conv(self, stride, pad):
        x, coords, vals, shape = _grid()
        rng = np.random.default_rng(1)
        wt = rng.normal(size=(3, 3, 3, shape[-1], 4)).astype(np.float32)
        y = sparse.conv3d(x, wt, stride=stride, padding=pad)
        got = np.asarray(y.to_dense().numpy())
        want = _dense_conv_ref(coords, vals, shape, wt, stride, pad)
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_subm_preserves_active_set_and_values(self):
        x, coords, vals, shape = _grid(seed=2)
        rng = np.random.default_rng(3)
        wt = rng.normal(size=(3, 3, 3, shape[-1], 5)).astype(np.float32)
        y = sparse.subm_conv3d(x, wt, padding=1)
        np.testing.assert_array_equal(np.asarray(y._b.indices), coords)
        want = _dense_conv_ref(coords, vals, shape, wt, 1, 1)
        got = np.asarray(y.to_dense().numpy())
        for c in coords:
            np.testing.assert_allclose(got[tuple(c)], want[tuple(c)],
                                       atol=1e-4)

    def test_bias_and_gradients_flow(self):
        x, coords, vals, shape = _grid(seed=4)
        paddle.seed(0)
        conv = sparse.nn.SubmConv3D(shape[-1], 4, 3, padding=1)
        out = conv(x)
        loss = (out.values() ** 2).sum()
        loss.backward()
        assert conv.weight.grad is not None
        assert float(np.abs(conv.weight.grad.numpy()).max()) > 0
        assert conv.bias.grad is not None

    @pytest.mark.slow
    def test_point_cloud_toy_network_trains(self):
        """subm conv -> relu -> pool -> subm conv -> global readout, loss
        goes down (the reference's point-cloud workload class, eager)."""
        x, coords, vals, shape = _grid(seed=5, density=0.3)
        paddle.seed(0)
        c1 = sparse.nn.SubmConv3D(shape[-1], 8, 3, padding=1)
        c2 = sparse.nn.SubmConv3D(8, 8, 3, padding=1)
        act = sparse.nn.ReLU()
        pool = sparse.nn.MaxPool3D(2, stride=2)
        head = nn.Linear(8, 1)
        params = (c1.parameters() + c2.parameters() + head.parameters())
        opt = optimizer.Adam(learning_rate=5e-3, parameters=params)
        target = paddle.to_tensor(np.array([[1.5]], np.float32))
        losses = []
        for _ in range(25):
            h = pool(act(c1(x)))
            h = c2(h)
            pooled = h.values().mean(axis=0, keepdim=True)
            loss = ((head(pooled) - target) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


class TestSparsePool3D:
    def test_max_pool_matches_neginf_dense(self):
        x, coords, vals, shape = _grid(seed=6)
        N, D, H, W, C = shape
        y = sparse.max_pool3d(x, 2, stride=2)
        dense = np.full(shape, -np.inf, np.float32)
        dense[tuple(coords.T)] = vals
        got = np.asarray(y.to_dense().numpy())
        for c in np.asarray(y._b.indices):
            n, d, h, w = c
            want = dense[n, 2 * d:2 * d + 2, 2 * h:2 * h + 2,
                         2 * w:2 * w + 2].reshape(-1, C).max(0)
            np.testing.assert_allclose(got[tuple(c)], want, atol=1e-6)

    def test_avg_pool_divides_by_present_count(self):
        # one window with exactly two active voxels: mean of the two, not
        # sum/8 (absent voxels are NOT zeros in sparse semantics)
        coords = np.array([[0, 0, 0, 0], [0, 1, 1, 1]]).T
        vals = np.array([[2.0], [4.0]], np.float32)
        x = sparse.sparse_coo_tensor(coords, vals, shape=(1, 2, 2, 2, 1))
        y = sparse.avg_pool3d(x, 2, stride=2)
        assert float(np.asarray(y.values().numpy())[0, 0]) == pytest.approx(3.0)


class TestSubmPaddingSemantics:
    def test_padding_shifts_the_window(self):
        """subm honors `padding` like the reference rulebook
        (out = in + pad - off): padding=0 anchors the window one-sided,
        kernel-center padding gives the symmetric window (review r3)."""
        x, coords, vals, shape = _grid(seed=9)
        rng = np.random.default_rng(10)
        wt = rng.normal(size=(3, 3, 3, shape[-1], 2)).astype(np.float32)
        y_center = sparse.subm_conv3d(x, wt, padding=1)
        y_corner = sparse.subm_conv3d(x, wt, padding=0)
        assert not np.allclose(np.asarray(y_center.values().numpy()),
                               np.asarray(y_corner.values().numpy()))
        # corner-anchored window: site s sums w[off] * dense[s + off]
        dense = np.zeros(shape, np.float32)
        dense[tuple(coords.T)] = vals
        N, D, H, W, C = shape
        pd = np.pad(dense, ((0, 0), (0, 2), (0, 2), (0, 2), (0, 0)))
        got = np.asarray(y_corner.to_dense().numpy())
        for c in coords[:10]:
            n, d, h, w = c
            want = np.einsum("dhwc,dhwco->o", pd[n, d:d+3, h:h+3, w:w+3], wt)
            np.testing.assert_allclose(got[tuple(c)], want, atol=1e-4)


class TestSparseOpChainGradients:
    def test_residual_add_keeps_upstream_grads(self):
        """review r3: add/softmax/multiply previously severed the tape."""
        paddle.seed(0)
        rng = np.random.default_rng(11)
        mask = rng.random((1, 4, 4, 4)) < 0.4
        coords = np.argwhere(mask)
        vals = rng.normal(size=(coords.shape[0], 3)).astype(np.float32)
        x = sparse.sparse_coo_tensor(coords.T, vals, shape=(1, 4, 4, 4, 3))
        conv = sparse.nn.SubmConv3D(3, 3, 3, padding=1)
        z = sparse.add(conv(x), conv(x))
        (z.values() ** 2).sum().backward()
        g = conv.weight.grad
        assert g is not None and float(np.abs(g.numpy()).max()) > 0
