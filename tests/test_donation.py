"""Buffer-donation audit (ROADMAP item 1a / PR-10 satellite).

Every compiled train-step entry point donates its params and opt-state so
XLA can alias the update in place instead of holding two copies of the
model + optimizer slots live across the step (on an HBM-bound chip the
extra copy is real step time, and on big models it is the OOM line):

* `jit.TrainStep`                      — donate_argnums (0, 2), default on
* `static` Executor train fn          — donate_argnums (1, 2)
* `meta_parallel` engine / pipeline    — donate_argnums (0, 2), default on
* `auto_parallel.engine`               — donate_argnums (0, 2)
* `auto_parallel.planner` score probes — donate=False ON PURPOSE: they are
  lower+compile-only cost probes, never executed (justified in comments at
  the two construction sites)

The assertions use `jax.stages.Lowered.args_info`, which reports the
donation marks the executable was ACTUALLY lowered with (works on CPU,
where the runtime itself ignores donation) — not the constructor args.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.jit import TrainStep
from paddle_tpu.nn import functional as F


def _donated_by_arg(lowered, n_args):
    """[all-leaves-donated?] per positional arg of a lowered step (None
    for args with no array leaves)."""
    info = lowered.args_info
    args = info[0] if isinstance(info, tuple) and len(info) == 2 else info
    out = []
    for i in range(n_args):
        leaves = jax.tree_util.tree_leaves(args[i])
        if not leaves:
            out.append(None)
            continue
        flags = {bool(l.donated) for l in leaves}
        out.append(flags == {True} if len(flags) == 1 else "mixed")
    return out


def _lower_trainstep(step, *arrs):
    from paddle_tpu.framework import random as random_mod
    rng = random_mod.default_generator().split()
    lr = jnp.asarray(step.optimizer.get_lr(), jnp.float32)
    return step._step.lower(step.params, step.buffers, step.opt_state,
                            rng, lr, 1, *arrs)


class TestTrainStepDonation:
    """The default TrainStep path must donate params + opt_state (and
    nothing else: buffers feed the eager Layer back, batch is caller's)."""

    def _build(self, **kw):
        paddle.seed(0)
        model = nn.Linear(8, 4)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        step = TrainStep(model, F.cross_entropy, opt, **kw)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 8)).astype("float32"))
        y = jnp.asarray(rng.integers(0, 4, (4,)).astype("int32"))
        return step, x, y

    def test_default_path_donates_params_and_opt_state(self):
        step, x, y = self._build()
        lowered = _lower_trainstep(step, x, y)
        donated = _donated_by_arg(lowered, 8)
        # (params, buffers, opt_state, rng, lr, t, x, y)
        assert donated[0] is True, f"params not donated: {donated}"
        assert donated[2] is True, f"opt_state not donated: {donated}"
        for i in (3, 4, 6, 7):  # rng, lr, batch stay caller-owned
            assert donated[i] in (False, None), \
                f"arg {i} unexpectedly donated: {donated}"

    def test_donate_false_opt_out_lowered_without_donation(self):
        step, x, y = self._build(donate=False)
        lowered = _lower_trainstep(step, x, y)
        donated = _donated_by_arg(lowered, 8)
        assert donated[0] in (False, None) and donated[2] in (False, None), \
            f"donate=False still donated: {donated}"

    def test_step_still_runs_and_updates(self):
        # donation must not break the eager call path (TrainStep keeps
        # private copies exactly because the executable consumes them)
        step, x, y = self._build()
        l0 = float(step(x, y))
        l1 = float(step(x, y))
        assert np.isfinite(l0) and np.isfinite(l1)


class TestDonationAuditSourceContract:
    """Executable audit of the OTHER train-step entry points: the
    donate_argnums marks named in the PR-10 audit must stay present at
    their construction sites (a pure-source check — building a mesh/hcg or
    a static program per entry point would cost tier-1 seconds for the
    same signal)."""

    SITES = (
        ("jit/__init__.py", "donate_args = (0, 2) if donate else ()"),
        ("static/__init__.py",
         "@functools.partial(jax.jit, donate_argnums=(1, 2))"),
        ("distributed/meta_parallel/engine.py",
         "donate_args = (0, 2) if donate else ()"),
        ("distributed/meta_parallel/pipeline_parallel.py",
         "donate_args = (0, 2) if donate else ()"),
        ("distributed/auto_parallel/engine.py",
         "jax.jit(train_step, donate_argnums=(0, 2))"),
        ("distributed/ps/heter.py",
         "donate_args = (0, 2) if donate else ()"),
    )

    def test_every_entry_point_donates_params_and_opt_state(self):
        import os
        root = os.path.dirname(os.path.abspath(paddle.__file__))
        for rel, needle in self.SITES:
            with open(os.path.join(root, rel)) as f:
                src = f.read()
            assert needle in src, \
                f"{rel}: donation mark {needle!r} missing — the audit " \
                f"contract (params + opt-state donated) was broken"

    def test_planner_probe_opt_out_is_justified(self):
        # the two donate=False sites must keep their justification comment
        import os
        root = os.path.dirname(os.path.abspath(paddle.__file__))
        with open(os.path.join(root,
                               "distributed/auto_parallel/planner.py")) as f:
            src = f.read()
        # two call sites (comments also say donate=False; count code form)
        assert src.count("donate=False)") == 2
        assert "donation audit" in src, \
            "planner donate=False sites lost their justification comment"
