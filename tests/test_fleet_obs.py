"""Fleet telemetry (distributed/fleet/telemetry.py): digest publication,
rank-0 aggregation into host-labeled fleet_* gauges, straggler detection —
including the acceptance scenario: a 2-host job where one host is slowed
via the injected `fleet.step` delay fault produces exactly ONE
fleet_straggler event naming the slow host.
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

from paddle_tpu.distributed.fleet.telemetry import (FleetAggregator,
                                                    FleetReporter,
                                                    DIGEST_KEY_FMT)
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.profiler import events
from paddle_tpu.profiler import metrics as metrics_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeStore:
    """Minimal in-memory store (set/get/check) for single-process tests."""

    def __init__(self):
        self.kv = {}
        self.lock = threading.Lock()

    def set(self, key, value):
        with self.lock:
            self.kv[key] = value.encode() if isinstance(value, str) else value

    def get(self, key):
        with self.lock:
            return self.kv[key]

    def check(self, key):
        with self.lock:
            return key in self.kv


@pytest.fixture(autouse=True)
def _clean_events():
    events.default_event_log().clear()
    yield
    events.default_event_log().clear()


def _feed(reporter, walls, start_step=1):
    for i, w in enumerate(walls):
        reporter.note_step(start_step + i, wall_s=w)


class TestReporter:
    def test_digest_shape_and_publication(self):
        store = FakeStore()
        rep = FleetReporter(store, rank=1, window=8, min_interval_s=0)
        _feed(rep, [0.01, 0.02, 0.03], start_step=5)
        raw = store.get(DIGEST_KEY_FMT.format(rank=1))
        d = json.loads(raw.decode())
        assert d["rank"] == 1 and d["step"] == 7
        assert d["window"] == 3
        assert abs(d["wall_p50_s"] - 0.02) < 1e-9
        assert "heter" in d and "barrier_wait_s" in d
        assert d["host"]

    def test_digest_carries_last_diagnosis_dominant(self):
        """Deep-profiling PR: each host's digest names its newest
        step_diagnosis dominant term so the fleet aggregator can show
        every host's bottleneck."""
        from paddle_tpu.profiler.monitor import diag_signals, diagnose_window
        store = FakeStore()
        rep = FleetReporter(store, rank=2, window=8, min_interval_s=0)
        diagnose_window(diag_signals(), wall_s=0.1, steps=1, emit=False)
        _feed(rep, [0.01, 0.02])
        d = json.loads(store.get(DIGEST_KEY_FMT.format(rank=2)).decode())
        assert d["diag_dominant"] == "unattributed"

    def test_measured_walls_from_consecutive_notes(self):
        store = FakeStore()
        rep = FleetReporter(store, rank=0, window=8, min_interval_s=0)
        rep.note_step(1)
        time.sleep(0.05)
        rep.note_step(2)
        d = json.loads(store.get(DIGEST_KEY_FMT.format(rank=0)).decode())
        assert d["last_wall_s"] >= 0.04

    def test_store_failure_disables_after_streak(self):
        class DeadStore(FakeStore):
            def set(self, key, value):
                raise RuntimeError("gone")

        rep = FleetReporter(DeadStore(), rank=0, min_interval_s=0)
        for step in range(1, rep.MAX_FAIL_STREAK):
            rep.note_step(step, wall_s=0.01)  # must not raise
            assert not rep._disabled  # a hiccup is tolerated
        rep.note_step(rep.MAX_FAIL_STREAK, wall_s=0.01)
        assert rep._disabled  # a full streak means the store is gone

    def test_publish_success_resets_fail_streak(self):
        calls = {"n": 0}

        class FlakyStore(FakeStore):
            def set(self, key, value):
                calls["n"] += 1
                if calls["n"] % 2 == 1:  # every other publish blips
                    raise RuntimeError("blip")
                super().set(key, value)

        rep = FleetReporter(FlakyStore(), rank=0, min_interval_s=0)
        for step in range(1, 9):
            rep.note_step(step, wall_s=0.01)
        assert not rep._disabled  # alternating blips never reach the streak


class TestAggregator:
    def _fleet(self, slow_factor=10.0, n_steps=6):
        store = FakeStore()
        fast = FleetReporter(store, rank=0, window=8, host="trainer-0", min_interval_s=0)
        slow = FleetReporter(store, rank=1, window=8, host="trainer-1", min_interval_s=0)
        _feed(fast, [0.01] * n_steps)
        _feed(slow, [0.01 * slow_factor] * n_steps)
        return store, FleetAggregator(store, world_size=2,
                                      straggler_factor=2.0)

    def test_collect_mirrors_fleet_gauges_with_host_labels(self):
        store, agg = self._fleet()
        digests = agg.collect()
        assert sorted(digests) == [0, 1]
        reg = metrics_mod.default_registry()
        hosts = {d["host"] for d in digests.values()}
        g = reg.get("fleet_last_step")
        labeled = {v["labels"]["host"] for v in g.snapshot()["values"]}
        assert hosts <= labeled
        p50 = reg.get("fleet_step_wall_p50_seconds")
        assert p50 is not None and p50.snapshot()["values"]

    def test_prometheus_text_carries_host_labels(self):
        store, agg = self._fleet()
        agg.collect()
        txt = metrics_mod.default_registry().to_prometheus_text()
        assert "paddle_tpu_fleet_last_step{host=" in txt

    def test_straggler_fires_exactly_once_and_rearms(self):
        c = metrics_mod.default_registry().counter(
            "fleet_straggler_total",
            "straggler excursions detected (host p50 exceeded fleet median "
            "by the configured factor), by host")
        c0 = c.value(host="trainer-1")
        store, agg = self._fleet(slow_factor=10.0)
        slow_host = json.loads(
            store.get(DIGEST_KEY_FMT.format(rank=1)).decode())["host"]
        for _ in range(4):  # repeated collects must not duplicate
            agg.collect()
        recs = events.recent(50, kind="fleet_straggler")
        assert len(recs) == 1
        assert recs[0]["straggler"] == slow_host
        assert agg.straggling() == [slow_host]
        assert c.value(host=slow_host) == c0 + 1
        # the slow host recovers: state re-arms, a relapse fires ONE more
        rep1 = FleetReporter(store, rank=1, window=8, host="trainer-1", min_interval_s=0)
        _feed(rep1, [0.01] * 6, start_step=50)
        agg.collect()
        assert agg.straggling() == []
        _feed(rep1, [0.5] * 8, start_step=60)
        agg.collect()
        assert len(events.recent(50, kind="fleet_straggler")) == 2

    def test_short_windows_do_not_vote(self):
        store = FakeStore()
        _feed(FleetReporter(store, rank=0, window=8, host="trainer-0", min_interval_s=0),
              [0.01] * 2)
        _feed(FleetReporter(store, rank=1, window=8, host="trainer-1", min_interval_s=0),
              [0.5] * 2)
        agg = FleetAggregator(store, 2, straggler_factor=2.0)
        agg.collect()
        assert events.recent(50, kind="fleet_straggler") == []

    def test_single_host_fleet_has_no_straggler_semantics(self):
        store = FakeStore()
        _feed(FleetReporter(store, rank=0, window=8, min_interval_s=0), [0.5] * 6)
        FleetAggregator(store, 1).collect()
        assert events.recent(50, kind="fleet_straggler") == []

    def test_snapshot_shape(self):
        store, agg = self._fleet()
        agg.collect()
        snap = agg.snapshot()
        assert snap["world_size"] == 2
        assert set(snap["hosts"]) == {"0", "1"}


_HOST_SCRIPT = r"""
import os, sys, time
sys.path.insert(0, {repo!r})
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.distributed.fleet.telemetry import FleetReporter
store = TCPStore("127.0.0.1", int(sys.argv[1]))
rep = FleetReporter(store, rank=int(sys.argv[2]), window=8, min_interval_s=0)
for step in range(1, 14):
    time.sleep(0.02)        # the base step wall
    rep.note_step(step)     # fleet.step fault site fires in here
print("HOST_DONE", flush=True)
"""


class TestTwoHostStragglerE2E:
    def test_injected_delay_makes_exactly_one_straggler_event(self, tmp_path):
        """Acceptance: 2 hosts over a real TCPStore, one slowed via the
        armed `fleet.step` delay fault, aggregator emits exactly one
        fleet_straggler naming the slow host (trainer-1)."""
        master = TCPStore("127.0.0.1", 0, is_master=True)
        procs = []
        try:
            script = _HOST_SCRIPT.format(repo=REPO)
            for rank in range(2):
                env = dict(os.environ)
                env["PADDLE_CURRENT_ENDPOINT"] = f"trainer-{rank}"
                env.pop("PADDLE_TPU_FAULT_SPEC", None)
                if rank == 1:  # the slow host: every step sleeps +80ms
                    env["PADDLE_TPU_FAULT_SPEC"] = "fleet.step=100:delay"
                    env["PADDLE_TPU_FAULT_DELAY"] = "0.08"
                procs.append(subprocess.Popen(
                    [sys.executable, "-c", script, str(master.port),
                     str(rank)],
                    env=env, stdout=subprocess.PIPE, text=True))
            agg = FleetAggregator(TCPStore("127.0.0.1", master.port),
                                  world_size=2, straggler_factor=2.0)
            deadline = time.time() + 60
            while time.time() < deadline:
                agg.collect()
                if agg.straggling():
                    break
                time.sleep(0.05)
            for p in procs:
                out, _ = p.communicate(timeout=60)
                assert "HOST_DONE" in out
                assert p.returncode == 0
            agg.collect()  # final pass over the complete digests
            recs = events.recent(50, kind="fleet_straggler")
            assert len(recs) == 1, recs
            assert recs[0]["straggler"] == "trainer-1"
            assert recs[0]["p50_s"] > recs[0]["fleet_median_s"] * 2.0
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            master.stop()
