"""XPlane measured device time (profiler/xplane.py): trace parsing and
lane classification, span correlation (synthetic + live CPU capture),
the armed N-step ProfileCapture state machine with its hard wall-clock
cap, and the persistent-compile-cache flag wiring.
"""
import gzip
import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import device_time, xplane
from paddle_tpu.profiler.recorder import HostSpan, get_recorder


def _ev(name, ts, dur, pid=1, tid=1, ph="X", args=None):
    e = {"ph": ph, "name": name, "ts": ts, "dur": dur, "pid": pid,
         "tid": tid}
    if args is not None:
        e["args"] = args
    return e


def _meta(pid, tid=None, name=""):
    if tid is None:
        return {"ph": "M", "name": "process_name", "pid": pid,
                "args": {"name": name}}
    return {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": name}}


def _synthetic_trace():
    """Host lane (python thread, annotations at known windows) + one work
    lane with overlapping backend events + infra noise."""
    return [
        _meta(1, name="/host:CPU"),
        _meta(1, tid=10, name="python"),
        # annotations: matmul [100, 200), softmax [300, 380)
        _ev("$somefile.py:1 frame", 0, 500, tid=10),
        _ev("matmul", 100, 100, tid=10),
        _ev("softmax", 300, 80, tid=10),
        # work lane: overlaps matmul by 60us, softmax by 40us, plus noise
        _ev("dot.3", 120, 60, tid=20),
        _ev("reduce_fusion.1", 320, 40, tid=20),
        _ev("ThreadpoolListener::StartRegion", 100, 300, tid=20),
        _ev("TaskDispatcher::dispatch", 0, 600, tid=21),
    ]


def _span(name, start_ns, end_ns, device_ns=None, src=None):
    return HostSpan(name=name, start_ns=start_ns, end_ns=end_ns, tid=10,
                    device_ns=device_ns, device_src=src)


class TestParseAndClassify:
    def test_classify_lanes_host_vs_work(self):
        host, work = classified = xplane.classify_lanes(_synthetic_trace())
        assert (1, 10) in host
        assert (1, 20) in work
        # a lane with ONLY infra events is neither host nor work
        assert (1, 21) not in host and (1, 21) not in work

    def test_device_process_is_always_work(self):
        evs = [_meta(7, name="/device:TPU:0"),
               _ev("fusion.9", 0, 10, pid=7, tid=1)]
        host, work = xplane.classify_lanes(evs)
        assert (7, 1) in work and not host

    def test_work_events_filters_infra_and_annotations(self):
        works = xplane.work_events(_synthetic_trace(),
                                   span_names=["matmul", "softmax"])
        assert [e["name"] for e in works] == ["dot.3", "reduce_fusion.1"]

    def test_load_trace_gz_and_plain(self, tmp_path):
        doc = {"traceEvents": _synthetic_trace()}
        plain = tmp_path / "t.json"
        plain.write_text(json.dumps(doc))
        gz = tmp_path / "t.trace.json.gz"
        with gzip.open(gz, "wt") as f:
            json.dump(doc, f)
        assert xplane.load_trace(str(plain)) == doc
        assert xplane.load_trace(str(gz)) == doc

    def test_find_trace_file_session_layout(self, tmp_path):
        d = tmp_path / "plugins" / "profile" / "2026_01_01"
        d.mkdir(parents=True)
        (d / "host.trace.json.gz").write_bytes(gzip.compress(b"{}"))
        found = xplane.find_trace_file(str(tmp_path))
        assert found and found.endswith("host.trace.json.gz")
        assert xplane.find_trace_file(str(tmp_path / "nope")) is None


class TestCorrelate:
    def test_overlap_attribution_and_estimate_delta(self):
        spans = [_span("matmul", 0, 1000, device_ns=50_000, src="estimate"),
                 _span("softmax", 2000, 3000, device_ns=10_000,
                       src="estimate")]
        stats = xplane.correlate(spans, _synthetic_trace())
        assert stats["correlated"] == 2
        # matmul window [100,200) overlaps dot.3 [120,180) -> 60us
        assert spans[0].device_ns == 60_000
        assert spans[0].device_src == "xplane"
        # softmax window [300,380) overlaps reduce_fusion.1 [320,360) -> 40us
        assert spans[1].device_ns == 40_000
        by_op = {r["op"]: r for r in stats["by_op"]}
        assert by_op["matmul"]["est_ms"] == 0.05
        assert by_op["matmul"]["xplane_ms"] == 0.06
        assert by_op["matmul"]["xplane_vs_est"] == 1.2

    def test_unmatched_span_keeps_estimate(self):
        spans = [_span("relu", 0, 1000, device_ns=5_000, src="estimate")]
        stats = xplane.correlate(spans, _synthetic_trace())
        assert stats["correlated"] == 0
        assert spans[0].device_src == "estimate"

    def test_extra_spans_align_from_newest(self):
        # two matmul spans, one annotation: only the NEWEST span matches
        spans = [_span("matmul", 0, 10, device_ns=1, src="estimate"),
                 _span("matmul", 20, 30, device_ns=1, src="estimate")]
        stats = xplane.correlate(spans, _synthetic_trace())
        assert stats["correlated"] == 1
        assert spans[0].device_src == "estimate"
        assert spans[1].device_src == "xplane"

    def test_args_name_match_attributes_regardless_of_overlap(self):
        # TPU metadata path: a work event far outside the window whose
        # args name the op still lands on the annotation
        evs = _synthetic_trace() + [
            _ev("fusion.77", 5000, 25, tid=20, args={"tf_op": "matmul"})]
        spans = [_span("matmul", 0, 1000, device_ns=1, src="estimate")]
        xplane.correlate(spans, evs)
        assert spans[0].device_ns == (60 + 25) * 1000

    def test_split_rows_and_table_show_xplane_src(self):
        spans = [_span("matmul", 0, 1000, device_ns=60_000, src="xplane"),
                 _span("matmul", 0, 1000, device_ns=50_000, src="estimate")]
        rows = device_time.split_rows(spans)
        assert rows[0]["src"] == "xplane"
        from paddle_tpu.profiler.statistic import (StatisticData,
                                                   summary_report)
        table = summary_report(StatisticData(spans))
        assert "Dev(ms)" in table and "xplane" in table


class TestCaptureSessionLive:
    def test_capture_correlates_eager_ops_on_cpu(self, tmp_path):
        """The acceptance path: a capture session over real eager ops on
        the CPU backend correlates >= 1 op span to device_src="xplane" and
        the summary table gains the measured Dev(ms) column."""
        sess = xplane.CaptureSession(str(tmp_path / "s1"))
        sess.start()
        try:
            a = paddle.to_tensor(np.ones((96, 96), np.float32))
            for _ in range(3):
                paddle.nn.functional.softmax(paddle.matmul(a, a))
        finally:
            summary = sess.stop(steps=3)
        assert summary["status"] == "complete"
        corr = summary["correlation"]
        assert corr["correlated"] >= 1, corr
        assert summary["device_time"]["mode"] == "xplane"
        assert any(r["src"] == "xplane"
                   for r in summary["device_time"]["rows"])
        assert "Dev(ms)" in summary["summary_table"]
        assert "xplane" in summary["summary_table"]
        # diagnosis rode along and named a dominant term
        assert summary["diagnosis"]["dominant"]
        # the summary is persisted into the session dir
        on_disk = json.load(open(tmp_path / "s1" / "summary.json"))
        assert on_disk["status"] == "complete"

    def test_profiler_device_window_correlates(self, tmp_path):
        """The classic Profiler's device-trace window (trace_dir + a
        device target) now correlates its spans on stop: summary rows
        carry device_src="xplane" without any /profile involvement."""
        from paddle_tpu.profiler.profiler import Profiler, ProfilerTarget
        p = Profiler(targets=[ProfilerTarget.CPU, ProfilerTarget.GPU],
                     trace_dir=str(tmp_path / "prof"))
        with p:
            a = paddle.to_tensor(np.ones((96, 96), np.float32))
            for _ in range(3):
                paddle.nn.functional.softmax(paddle.matmul(a, a))
        assert p.xplane_stats is not None
        assert p.xplane_stats["correlated"] >= 1
        assert any(s.device_src == "xplane" for s in p._spans)
        assert not xplane.annotating()  # flag cleared on stop

    def test_capture_refuses_busy_recorder(self, tmp_path):
        rec = get_recorder()
        rec.enabled = True
        try:
            with pytest.raises(xplane.CaptureBusyError):
                xplane.CaptureSession(str(tmp_path / "s2")).start()
        finally:
            rec.enabled = False


class TestProfileCapture:
    def test_arm_step_finalize(self, tmp_path):
        cap = xplane.ProfileCapture()
        ack = cap.arm(2, session_dir=str(tmp_path / "p1"), timeout_s=60)
        assert ack["status"] == "armed"
        a = paddle.to_tensor(np.ones((64, 64), np.float32))
        step = 0
        while cap.state != "idle":
            step += 1
            paddle.matmul(a, a)
            cap.on_step(step)
            assert step < 10, "capture never finalized"
        summary = cap.wait(1)
        assert summary["status"] == "complete"
        assert summary["steps"] == 2
        assert (summary["correlation"] or {}).get("correlated", 0) >= 1

    def test_concurrent_arm_is_busy(self, tmp_path):
        cap = xplane.ProfileCapture()
        cap.arm(1, session_dir=str(tmp_path / "p2"), timeout_s=60)
        with pytest.raises(xplane.CaptureBusyError):
            cap.arm(1, session_dir=str(tmp_path / "p3"))
        cap.on_step(1)
        cap.on_step(2)  # finalizes
        assert cap.state == "idle"

    def test_armed_but_stalled_times_out(self, tmp_path):
        """The hard wall-clock cap: a job that never steps cannot hold the
        capture armed forever."""
        cap = xplane.ProfileCapture()
        cap.arm(1, session_dir=str(tmp_path / "p4"), timeout_s=0.2)
        summary = cap.wait(5)
        assert summary["status"] == "timeout"
        assert cap.state == "idle"
        # and the slot is reusable afterwards
        cap.arm(1, session_dir=str(tmp_path / "p5"), timeout_s=60)
        cap.on_step(1)
        cap.on_step(2)
        assert cap.wait(1)["status"] == "complete"

    def test_recording_window_capped_mid_flight(self, tmp_path):
        """A capture whose step flow stalls mid-window is force-finalized
        at the cap with whatever was recorded."""
        cap = xplane.ProfileCapture()
        cap.arm(100, session_dir=str(tmp_path / "p6"), timeout_s=1.0)
        a = paddle.to_tensor(np.ones((32, 32), np.float32))
        paddle.matmul(a, a)
        cap.on_step(1)  # starts recording; steps then stall
        summary = cap.wait(10)
        assert summary["status"] == "timeout"
        assert cap.state == "idle"

    def test_on_step_never_raises_while_idle(self):
        xplane.default_capture().on_step(123)  # no session: cheap no-op

    def test_compiled_loop_gets_train_step_spans(self, tmp_path):
        """A loop whose whole step is ONE compiled executable emits no
        eager op spans — the capture brackets each inter-note interval in
        a synthesized `train_step` span so the production (jit) path still
        yields measured per-step device time."""
        import jax
        import jax.numpy as jnp
        f = jax.jit(lambda x: (x @ x).sum())
        x = jnp.ones((96, 96))
        float(f(x))  # compile outside the capture window
        cap = xplane.ProfileCapture()
        cap.arm(2, session_dir=str(tmp_path / "jit"), timeout_s=60)
        for step in range(1, 5):
            float(f(x))  # compiled-only work, no eager dispatch
            cap.on_step(step)
            if cap.state == "idle":
                break
        summary = cap.wait(10)
        assert summary["status"] == "complete"
        rows = [r for r in summary["device_time"]["rows"]
                if r["op"] == "train_step"]
        assert rows and rows[0]["src"] == "xplane", summary["device_time"]
        assert rows[0]["calls"] == 2
        assert "train_step" in summary["summary_table"]


class TestPeaksCacheRegression:
    def test_platform_peaks_follow_env_changes(self, monkeypatch):
        """Satellite regression: _peaks_cache was computed once per
        process, so changing BENCH_PEAK_FLOPS / PADDLE_TPU_PEAK_HBM_GBS
        mid-process silently kept the old peaks."""
        monkeypatch.setattr(device_time, "_platform", lambda: "tpu")
        device_time.reset_peaks()
        try:
            monkeypatch.setenv("BENCH_PEAK_FLOPS", "100e12")
            monkeypatch.setenv("PADDLE_TPU_PEAK_HBM_GBS", "500")
            plat, flops, bw = device_time.platform_peaks()
            assert flops == 100e12 and bw == 500e9
            monkeypatch.setenv("BENCH_PEAK_FLOPS", "200e12")
            _, flops2, _ = device_time.platform_peaks()
            assert flops2 == 200e12, "stale peaks served after env change"
            monkeypatch.delenv("BENCH_PEAK_FLOPS")
            monkeypatch.delenv("PADDLE_TPU_PEAK_HBM_GBS")
            _, flops3, bw3 = device_time.platform_peaks()
            assert flops3 == 197e12 and bw3 == 819e9
        finally:
            device_time.reset_peaks()

    def test_reset_peaks_reprobes_platform(self, monkeypatch):
        device_time.reset_peaks()
        monkeypatch.setattr(device_time, "_platform", lambda: "cpu")
        assert device_time.platform_peaks()[0] == "cpu"
        monkeypatch.setattr(device_time, "_platform", lambda: "tpu")
        # cached platform survives env-key-identical calls...
        assert device_time.platform_peaks()[0] == "cpu"
        device_time.reset_peaks()  # ...until an explicit reset
        assert device_time.platform_peaks()[0] == "tpu"
        device_time.reset_peaks()


class TestCompileCacheWiring:
    @pytest.mark.slow  # child-process cache roundtrip; flag plumbing is
    def test_flag_points_jax_at_persistent_cache(self, tmp_path):  # pinned fast elsewhere
        """Satellite: PADDLE_TPU_COMPILE_CACHE_DIR -> jax's persistent
        compilation cache, making xla_compile_cache_events_total count
        real hits/misses (it sat at zero with the cache unwired)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.framework import flags as flags_mod
        from paddle_tpu.profiler import metrics as metrics_mod
        cache_dir = str(tmp_path / "ccache")
        os.makedirs(cache_dir)
        ctr = metrics_mod.default_registry().get(
            "xla_compile_cache_events_total")
        before = {k: ctr.value(event=k) for k in ("hit", "miss", "request")}
        flags_mod.set_flags({"FLAGS_compile_cache_dir": cache_dir})
        try:
            assert jax.config.jax_compilation_cache_dir == cache_dir
            f = jax.jit(lambda x: x * 3.0 + 1.0)
            f(jnp.ones((4, 4))).block_until_ready()
            assert os.listdir(cache_dir), "no cache entries written"
            assert ctr.value(event="miss") > before["miss"]
            # same program after dropping jax's in-memory caches: a HIT
            jax.clear_caches()
            f2 = jax.jit(lambda x: x * 3.0 + 1.0)
            f2(jnp.ones((4, 4))).block_until_ready()
            assert ctr.value(event="hit") > before["hit"]
        finally:
            flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})
            assert jax.config.jax_compilation_cache_dir is None


class TestSegmentBreakdown:
    """Measured per-segment attribution (r06): work events classified by
    XLA op-metadata scope tags, fwd/bwd split by autodiff markers,
    unattributed bucket for metadata-free exports."""

    @staticmethod
    def _tpu_style_trace():
        """Device-lane events whose args carry op_name metadata the way
        the TPU TB export does."""
        def dev(name, ts, dur, op_name):
            return _ev(name, ts, dur, pid=5, tid=50,
                       args={"name": op_name})
        return [
            _meta(5, name="/device:TPU:0"),
            _meta(5, tid=50, name="XLA Op"),
            dev("fusion.1", 0, 100,
                "jit(step)/attention/dot_general"),
            dev("fusion.2", 100, 300,
                "jit(step)/transpose(jvp(attention))/dot_general"),
            dev("fusion.3", 400, 80, "jit(step)/mlp/dot_general"),
            dev("fusion.4", 480, 160,
                "jit(step)/transpose(jvp(mlp))/dot_general"),
            dev("fusion.5", 640, 20, "jit(step)/ln/reduce"),
            dev("fusion.6", 660, 30, "jit(step)/loss/reduce"),
            dev("fusion.7", 690, 40, "jit(step)/optimizer/multiply"),
            dev("fusion.8", 730, 25, "jit(step)/embed/gather"),
            dev("custom-call.9", 755, 55, "flash_attention_fwd"),
            dev("fusion.10", 810, 90, "something_opaque"),
            # backward LN spelling: no /ln/ path component, only the
            # autodiff-wrapped scope — must still classify as ln
            dev("fusion.11", 900, 10,
                "jit(step)/transpose(jvp(ln))/reduce"),
        ]

    def test_classification_and_fractions(self):
        out = xplane.segment_breakdown(self._tpu_style_trace())
        seg = out["segments"]
        assert seg["attention_fwd"]["device_ms"] == pytest.approx(0.155)
        assert seg["attention_bwd"]["device_ms"] == pytest.approx(0.3)
        assert seg["mlp_fwd"]["device_ms"] == pytest.approx(0.08)
        assert seg["mlp_bwd"]["device_ms"] == pytest.approx(0.16)
        assert seg["ln"]["events"] == 2  # fwd (/ln/) + bwd (jvp(ln))
        assert seg["ln"]["device_ms"] == pytest.approx(0.03)
        assert seg["loss"]["device_ms"] == pytest.approx(0.03)
        assert seg["optimizer"]["device_ms"] == pytest.approx(0.04)
        assert seg["embed"]["events"] == 1
        assert seg["unattributed"]["device_ms"] == pytest.approx(0.09)
        total = out["total_device_ms"]
        assert total == pytest.approx(0.91)
        assert out["attributed_frac"] == pytest.approx(1 - 0.09 / 0.91,
                                                       abs=1e-4)
        fracs = sum(r["frac"] for r in seg.values())
        assert fracs == pytest.approx(1.0, abs=1e-3)

    def test_metadata_free_trace_is_all_unattributed(self):
        out = xplane.segment_breakdown(_synthetic_trace())
        seg = out["segments"]
        assert set(seg) == {"unattributed"}
        assert out["attributed_frac"] == 0.0

    def test_empty_trace(self):
        out = xplane.segment_breakdown([])
        assert out["segments"] == {}
        assert out["total_device_ms"] == 0.0
        assert out["attributed_frac"] is None
