/* C consumer of the pd_inference C API (reference parity test for
 * capi_exp/pd_inference_api.h): load a saved LeNet artifact, run one
 * batch read from argv[2] (raw float32), write outputs to argv[3].
 * Usage: capi_main <model_prefix> <input.bin> <output.bin> <N> <C> <H> <W>
 */
#include <stdio.h>
#include <stdlib.h>

#include "pd_inference_api.h"

int main(int argc, char** argv) {
  if (argc != 8) {
    fprintf(stderr, "usage: %s prefix in.bin out.bin N C H W\n", argv[0]);
    return 2;
  }
  PD_Predictor* p = pd_predictor_create(argv[1]);
  if (!p) {
    fprintf(stderr, "create failed: %s\n", pd_last_error());
    return 1;
  }
  if (pd_predictor_num_inputs(p) != 1 || pd_predictor_num_outputs(p) != 1) {
    fprintf(stderr, "unexpected io arity\n");
    return 1;
  }
  char name[128];
  if (pd_predictor_input_name(p, 0, name, sizeof name) < 0) return 1;
  printf("input: %s\n", name);

  int64_t shape[4];
  int64_t n = 1;
  for (int d = 0; d < 4; ++d) {
    shape[d] = atoll(argv[4 + d]);
    n *= shape[d];
  }
  float* in = malloc(n * sizeof(float));
  FILE* f = fopen(argv[2], "rb");
  if (!f || fread(in, sizeof(float), n, f) != (size_t)n) {
    fprintf(stderr, "bad input file\n");
    return 1;
  }
  fclose(f);

  enum { CAP = 1 << 20 };
  float* out = malloc(CAP * sizeof(float));
  int64_t out_shape[8];
  int out_nd = 0;
  const float* datas[1] = {in};
  const int64_t* shapes[1] = {shape};
  int ndims[1] = {4};
  float* outs[1] = {out};
  size_t caps[1] = {CAP};
  int64_t* oshapes[1] = {out_shape};
  int onds[1] = {0};
  if (pd_predictor_run(p, 1, datas, shapes, ndims, 1, outs, caps, oshapes,
                       onds) != 0) {
    fprintf(stderr, "run failed: %s\n", pd_last_error());
    return 1;
  }
  out_nd = onds[0];
  int64_t total = 1;
  for (int d = 0; d < out_nd; ++d) total *= out_shape[d];
  printf("output dims: %d total: %lld\n", out_nd, (long long)total);

  f = fopen(argv[3], "wb");
  fwrite(out, sizeof(float), total, f);
  fclose(f);
  pd_predictor_destroy(p);
  free(in);
  free(out);
  printf("CAPI_OK\n");
  return 0;
}
