"""Auto-parallel tests (reference `unittests/auto_parallel/` suite): mesh
construction, shard_tensor physical layout, Engine fit on an 8-device
virtual mesh, and checkpoint re-shard-on-restore."""
import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.nn import functional as F
from paddle_tpu.distributed import ProcessMesh, shard_tensor
from paddle_tpu.distributed.auto_parallel import Engine, TensorDistAttr


class TestProcessMesh:
    def test_shape_and_names(self):
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])
        assert mesh.shape == [2, 4]
        assert mesh.get_dim_size("y") == 4
        assert mesh.process_ids == list(range(8))
        jm = mesh.to_jax()
        assert jm.axis_names == ("x", "y")
        assert jm.devices.shape == (2, 4)

    def test_dim_names_mismatch(self):
        with pytest.raises(ValueError):
            ProcessMesh([[0, 1], [2, 3]], dim_names=["only_one"])


class TestDistAttr:
    def test_shard_spec_to_partition_spec(self):
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
        attr = TensorDistAttr.from_shard_spec(mesh, ["dp", None, "mp"])
        assert attr.dims_mapping == [0, -1, 1]
        assert attr.to_partition_spec() == P("dp", None, "mp")

    def test_unknown_dim_raises(self):
        mesh = ProcessMesh(np.arange(4), dim_names=["dp"])
        with pytest.raises(ValueError, match="unknown mesh dim"):
            TensorDistAttr.from_shard_spec(mesh, ["tp"])


class TestShardTensor:
    def test_physical_layout(self):
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
        x = paddle.to_tensor(np.arange(64, dtype=np.float32).reshape(8, 8))
        x = shard_tensor(x, mesh, ["dp", "mp"])
        shards = x.data.addressable_shards
        assert len(shards) == 8
        assert shards[0].data.shape == (4, 2)  # 8/2 x 8/4
        assert x.dist_attr.dims_mapping == [0, 1]

    def test_context_mesh(self):
        with ProcessMesh(np.arange(8), dim_names=["dp"]):
            x = shard_tensor(paddle.to_tensor(np.zeros((8, 2), np.float32)),
                             shard_spec=["dp", None])
        assert len(x.data.addressable_shards) == 8

    def test_parameter_gets_dist_spec(self):
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
        fc = nn.Linear(16, 32)
        shard_tensor(fc.weight, mesh, [None, "mp"])
        assert fc.weight.dist_spec == P(None, "mp")


class TestEngine:
    def _data(self, n=64, din=16):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(n, din)).astype(np.float32)
        w = rng.normal(size=(din, 1)).astype(np.float32)
        y = x @ w + 0.1 * rng.normal(size=(n, 1)).astype(np.float32)
        return x, y

    def test_fit_dp(self):
        mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 1))
        opt = optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
        eng = Engine(model, loss=lambda out, y: ((out - y) ** 2).mean(),
                     optimizer=opt, process_mesh=mesh)
        x, y = self._data()
        batches = [(x[i:i + 16], y[i:i + 16]) for i in range(0, 64, 16)]
        hist = eng.fit(batches, epochs=5)
        assert hist["loss"][-1] < hist["loss"][0] * 0.5

    def test_fit_dp_mp_annotated(self):
        """2x4 mesh: batch over dp, Linear weights column/row-sharded over mp."""
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["dp", "mp"])
        model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 1))
        shard_tensor(model[0].weight, mesh, [None, "mp"])   # column parallel
        shard_tensor(model[2].weight, mesh, ["mp", None])   # row parallel
        opt = optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
        eng = Engine(model, loss=lambda out, y: ((out - y) ** 2).mean(),
                     optimizer=opt, process_mesh=mesh, data_dim_name="dp")
        x, y = self._data()
        l0 = eng.train_batch(x[:16], y[:16])
        for _ in range(30):
            l1 = eng.train_batch(x[:16], y[:16])
        assert l1 < l0 * 0.5
        # TP placement is physically real: first weight is column-sharded
        w0 = eng.params["0.weight"]
        assert w0.sharding.spec == P(None, "mp")

    def test_matches_single_device(self):
        """Sharded engine loss == single-device eager loss, step by step."""
        x, y = self._data(32)
        paddle.seed(7)
        model1 = nn.Linear(16, 1)
        paddle.seed(7)
        model2 = nn.Linear(16, 1)
        np.testing.assert_allclose(np.asarray(model1.weight.data),
                                   np.asarray(model2.weight.data))
        opt1 = optimizer.SGD(learning_rate=0.1, parameters=model1.parameters())
        mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
        opt2 = optimizer.SGD(learning_rate=0.1, parameters=model2.parameters())
        eng = Engine(model2, loss=lambda o, t: ((o - t) ** 2).mean(),
                     optimizer=opt2, process_mesh=mesh)
        for i in range(3):
            xb, yb = x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8]
            out = model1(paddle.to_tensor(xb))
            loss1 = ((out - paddle.to_tensor(yb)) ** 2).mean()
            loss1.backward()
            opt1.step()
            opt1.clear_grad()
            loss2 = eng.train_batch(xb, yb)
            np.testing.assert_allclose(float(loss1), loss2, rtol=2e-5)

    def test_save_load_reshards(self, tmp_path):
        x, y = self._data(32)
        mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
        model = nn.Linear(16, 1)
        opt = optimizer.Adam(learning_rate=1e-2, parameters=model.parameters())
        eng = Engine(model, loss=lambda o, t: ((o - t) ** 2).mean(),
                     optimizer=opt, process_mesh=mesh)
        eng.train_batch(x[:16], y[:16])
        path = str(tmp_path / "auto.ckpt")
        eng.save(path)
        want = {k: np.asarray(v) for k, v in eng.params.items()}

        # restore into a DIFFERENT mesh shape (2x4) — re-shard on load
        mesh2 = ProcessMesh(np.arange(8).reshape(2, 4),
                            dim_names=["dp", "mp"])
        model2 = nn.Linear(16, 1)
        shard_tensor(model2.weight, mesh2, ["mp", None])
        opt2 = optimizer.Adam(learning_rate=1e-2,
                              parameters=model2.parameters())
        eng2 = Engine(model2, loss=lambda o, t: ((o - t) ** 2).mean(),
                      optimizer=opt2, process_mesh=mesh2)
        eng2.load(path)
        for k in want:
            np.testing.assert_allclose(np.asarray(eng2.params[k]), want[k])
        assert eng2.params["weight"].sharding.spec == P("mp", None)

    def test_predict_and_evaluate(self):
        x, y = self._data(32)
        mesh = ProcessMesh(np.arange(8), dim_names=["dp"])
        model = nn.Linear(16, 1)
        opt = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        eng = Engine(model, loss=lambda o, t: ((o - t) ** 2).mean(),
                     optimizer=opt, process_mesh=mesh)
        out = eng.predict(x[:8])
        assert tuple(out.shape) == (8, 1)
        val = eng.evaluate([(x[:8], y[:8]), (x[8:16], y[8:16])])
        assert np.isfinite(val)


class TestPlanner:
    """Reference planner.py / cost_model.py equivalent: candidate search
    scored by the compiler's cost_analysis."""

    def _wide_mlp(self, d=1024):
        paddle.seed(0)

        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(d, 4 * d)
                self.fc2 = nn.Linear(4 * d, d)
                self.head = nn.Linear(d, 8)

            def forward(self, x):
                return self.head(self.fc2(F.relu(self.fc1(x))))

        return MLP()

    def test_planner_picks_tp_for_wide_mlp_small_batch(self):
        """Tiny batch, wide weights: replicated-DP re-reads the full weights
        on every device, TP splits them — the roofline score must prefer a
        plan with mp > 1 (compute-optimal for this shape)."""
        from paddle_tpu.distributed.auto_parallel import Planner
        model = self._wide_mlp()
        planner = Planner(model, lambda o, y: F.cross_entropy(o, y))
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(8, 1024)).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 8, (8,)).astype(np.int32))
        best = planner.plan(x, y)
        assert best.cost["n_candidates"] >= 4
        assert best.mesh_dims.get("mp", 1) > 1, (
            f"planner chose {best.mesh_dims} ({best.template}) over TP")
        planner.apply(best)
        named = dict(model.named_parameters())
        assert getattr(named["fc1.weight"], "dist_spec", None) is not None

    def test_engine_plan_auto_trains(self):
        from paddle_tpu.distributed.auto_parallel import Engine
        model = self._wide_mlp(d=256)
        opt = optimizer.Adam(learning_rate=5e-3,
                             parameters=model.parameters())
        eng = Engine(model, loss=lambda o, y: F.cross_entropy(o, y),
                     optimizer=opt, plan="auto")
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(8, 256)).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 8, (8,)).astype(np.int32))
        losses = [eng.train_batch(x, y) for _ in range(8)]
        assert eng.plan_result is not None
        assert losses[-1] < losses[0], losses
        # the chosen mesh drives the engine's process mesh
        assert dict(zip(eng.process_mesh.dim_names,
                        eng.process_mesh.mesh.shape)) == eng.plan_result.mesh_dims

    def test_engine_plan_auto_fit_entrypoint(self):
        """Regression: fit() (the flagship entry) must plan before
        prepare(); predict/save before any batch raise a clear error."""
        from paddle_tpu.distributed.auto_parallel import Engine
        model = self._wide_mlp(d=128)
        opt = optimizer.Adam(learning_rate=5e-3,
                             parameters=model.parameters())
        eng = Engine(model, loss=lambda o, y: F.cross_entropy(o, y),
                     optimizer=opt, plan="auto")
        with pytest.raises(RuntimeError, match="plan"):
            eng.predict(np.zeros((8, 128), np.float32))
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(16, 128)).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 8, (16,)).astype(np.int32))
        hist = eng.fit([(x, y)], epochs=3)
        assert eng.plan_result is not None
        assert hist["loss"][-1] < hist["loss"][0]


    def test_engine_plan_auto_fit_batch_size_path(self):
        """Regression: fit((x, y), batch_size=N) must plan before touching
        the mesh (crashed with AttributeError on None process_mesh)."""
        from paddle_tpu.distributed.auto_parallel import Engine
        model = self._wide_mlp(d=64)
        opt = optimizer.Adam(learning_rate=5e-3,
                             parameters=model.parameters())
        eng = Engine(model, loss=lambda o, y: F.cross_entropy(o, y),
                     optimizer=opt, plan="auto")
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(32, 64)).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 8, (32,)).astype(np.int32))
        hist = eng.fit((x, y), epochs=2, batch_size=16)
        assert eng.plan_result is not None
        assert hist["loss"][-1] < hist["loss"][0]


class TestPlannerV2:
    """Round-3 planner: pp and sp axes in the search space, ICI term in the
    score (VERDICT r2 missing #6 / weak #6)."""

    @pytest.mark.slow
    def test_planner_picks_pp_for_deep_narrow_model(self):
        """Deep stack of narrow blocks, tiny batch: every dp replica
        re-reads ALL params + optimizer state per step, the pipeline
        shards them over stages — pp must win the roofline. hidden is
        chosen indivisible by 2 so tp templates find nothing."""
        from paddle_tpu import optimizer
        from paddle_tpu.distributed.auto_parallel import Planner
        from paddle_tpu.models.gpt import GPT, GPTConfig
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=125, num_layers=8,
                        num_heads=5, max_position_embeddings=16,
                        dropout=0.0, attn_dropout=0.0)
        model = GPT(cfg)
        opt = optimizer.Adam(learning_rate=1e-3,
                             parameters=model.parameters())
        planner = Planner(model, lambda o, y: F.cross_entropy(o, y),
                          optimizer=opt, templates=("dp", "pp"))
        rng = np.random.default_rng(0)
        ids = paddle.to_tensor(rng.integers(0, 64, (8, 16)).astype(np.int32))
        lab = paddle.to_tensor(rng.integers(0, 64, (8, 16)).astype(np.int32))
        best = planner.plan(ids, lab)
        assert best.template == "pp", (best.template, best.mesh_dims,
                                       best.cost)
        assert best.mesh_dims.get("pp", 1) > 1, best.mesh_dims

    def test_planner_still_picks_tp_for_wide_model_over_pp_sp(self):
        """Wide-shallow MLP (not pipeline-able, no seq axis): the search
        runs all four templates, pp/sp drop out gracefully, dp x mp wins."""
        from paddle_tpu import optimizer
        from paddle_tpu.distributed.auto_parallel import Planner
        paddle.seed(0)
        d = 1024

        class MLP(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(d, 4 * d)
                self.fc2 = nn.Linear(4 * d, d)
                self.head = nn.Linear(d, 8)

            def forward(self, x):
                return self.head(self.fc2(F.relu(self.fc1(x))))

        model = MLP()
        opt = optimizer.SGD(learning_rate=1e-2,
                            parameters=model.parameters())
        planner = Planner(model, lambda o, y: F.cross_entropy(o, y),
                          optimizer=opt)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(8, d)).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 8, (8,)).astype(np.int32))
        best = planner.plan(x, y)
        assert best.mesh_dims.get("mp", 1) > 1, (
            f"planner chose {best.mesh_dims} ({best.template})")

    def test_score_includes_ici_term(self):
        """A tp plan's cost must report nonzero collective bytes (the HLO
        really contains all-reduces) and the score must be >= each ratio."""
        from paddle_tpu.distributed.auto_parallel import planner as pmod
        from paddle_tpu.distributed.auto_parallel import Planner
        paddle.seed(0)
        model = TestPlanner._wide_mlp(TestPlanner(), d=512)
        planner = Planner(model, lambda o, y: F.cross_entropy(o, y),
                          templates=("tp_alternating",))
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(8, 512)).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 8, (8,)).astype(np.int32))
        best = planner.plan(x, y)
        assert best.cost["ici_bytes"] > 0, best.cost
        assert best.score >= best.cost["ici_bytes"] / pmod.ICI_BW - 1e-12

    def test_collective_bytes_parses_tuple_results(self):
        """XLA's all-reduce combiner emits TUPLE-result collectives; the
        parser must count every member shape (review r3)."""
        from paddle_tpu.distributed.auto_parallel.planner import (
            _collective_bytes)

        class FakeCompiled:
            def as_text(self):
                return "\n".join([
                    "%ar = (f32[64000]{0}, f32[500]{0}) all-reduce(a, b)",
                    "%cp = bf16[128,256]{1,0} collective-permute(x)",
                    "%ars = (f32[10]{0}) all-reduce-start(y)",
                    "%ard = (f32[10]{0}) all-reduce-done(%ars)",  # skip
                    "%mm = f32[512,512]{1,0} dot(p, q)",          # skip
                ])

        got = _collective_bytes(FakeCompiled())
        want = (64000 + 500) * 4 + 128 * 256 * 2 + 10 * 4
        assert got == want, (got, want)
