"""Program rewrite-pass framework tests (reference framework/ir pass system,
exercised in the reference's "assert on transformed IR" style — SURVEY §4.4).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, static
from paddle_tpu.static import PassRegistry, apply_pass


def _build_program():
    paddle.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", shape=[4, 8], dtype="float32")
            lin = nn.Linear(8, 8)
            h = lin(x)
            y = paddle.matmul(h, paddle.transpose(h, [1, 0]))
            out = paddle.mean(y)
        return main, startup, out
    finally:
        paddle.disable_static()


class TestPassFramework:
    def test_registry_lists_builtins(self):
        names = PassRegistry.list()
        for n in ("amp_cast_pass", "quant_insertion_pass",
                  "constant_folding_pass"):
            assert n in names

    def test_unknown_pass_raises(self):
        main, _, _ = _build_program()
        with pytest.raises(KeyError):
            apply_pass(main, "does_not_exist_pass")

    def test_amp_cast_pass_keeps_shapes_changes_numerics_to_bf16(self):
        paddle.enable_static()
        try:
            main, startup, out = _build_program()
            exe = static.Executor()
            exe.run(startup)
            feed = {"x": np.linspace(-1, 1, 32).reshape(4, 8)
                    .astype(np.float32)}
            (before,) = exe.run(main, feed=feed, fetch_list=[out])
            version0 = main.version
            apply_pass(main, "amp_cast_pass")
            assert main.version > version0  # caches must invalidate
            (after,) = exe.run(main, feed=feed, fetch_list=[out])
            assert after.dtype == before.dtype  # outputs cast back
            # bf16 compute: close to fp32 but NOT bit-identical
            np.testing.assert_allclose(after, before, rtol=3e-2, atol=3e-2)
            assert not np.array_equal(after, before)
        finally:
            paddle.disable_static()

    def test_quant_insertion_pass_quantizes_inputs(self):
        paddle.enable_static()
        try:
            main, startup, out = _build_program()
            exe = static.Executor()
            exe.run(startup)
            feed = {"x": np.linspace(-1, 1, 32).reshape(4, 8)
                    .astype(np.float32)}
            (before,) = exe.run(main, feed=feed, fetch_list=[out])
            apply_pass(main, "quant_insertion_pass", bits=8)
            (after,) = exe.run(main, feed=feed, fetch_list=[out])
            np.testing.assert_allclose(after, before, rtol=0.1, atol=0.1)
            assert not np.array_equal(after, before)
        finally:
            paddle.disable_static()

    def test_constant_folding_removes_const_ops(self):
        paddle.enable_static()
        try:
            main = static.Program()
            startup = static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", shape=[4], dtype="float32")
                c = paddle.to_tensor(np.ones(4, np.float32))
                folded = paddle.add(c, c)      # const + const: foldable
                folded2 = paddle.multiply(folded, c)
                out = paddle.add(x, folded2)   # depends on feed: kept
            n_before = len(main.ops)
            apply_pass(main, "constant_folding_pass")
            assert len(main.ops) < n_before, (n_before, len(main.ops))
            exe = static.Executor()
            feed = {"x": np.arange(4, dtype=np.float32)}
            (got,) = exe.run(main, feed=feed, fetch_list=[out])
            np.testing.assert_allclose(got, np.arange(4) + 2.0)
        finally:
            paddle.disable_static()
