"""Shared env-knob parse helper (paddle_tpu/utils/envparse.py) + one
regression test per offender the convention lint surfaced: every
consumer that used to detonate with an anonymous int()/float()
ValueError on a garbled PADDLE_TPU_* value now warns (naming the knob)
and uses its documented default instead.
"""
import warnings

import pytest

from paddle_tpu.utils import envparse
from paddle_tpu.utils.envparse import (EnvKnobError, env_bool, env_float,
                                       env_int, env_str)


@pytest.fixture(autouse=True)
def _fresh_warn_state():
    envparse._reset_warned()
    yield
    envparse._reset_warned()


class TestHelper:
    def test_unset_returns_default(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_TEST_K", raising=False)
        assert env_int("PADDLE_TPU_TEST_K", 7) == 7
        assert env_float("PADDLE_TPU_TEST_K", 2.5) == 2.5
        assert env_str("PADDLE_TPU_TEST_K", "d") == "d"
        assert env_bool("PADDLE_TPU_TEST_K", True) is True

    def test_empty_string_counts_as_unset(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_TEST_K", "")
        assert env_int("PADDLE_TPU_TEST_K", 7) == 7
        assert env_str("PADDLE_TPU_TEST_K", "d") == "d"

    def test_valid_values_parse(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_TEST_K", "42")
        assert env_int("PADDLE_TPU_TEST_K", 7) == 42
        assert env_float("PADDLE_TPU_TEST_K", 2.5) == 42.0

    def test_garbled_warns_once_naming_knob_and_default(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_TEST_K", "ten")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            assert env_int("PADDLE_TPU_TEST_K", 7) == 7
            assert env_int("PADDLE_TPU_TEST_K", 7) == 7  # second: silent
        assert len(w) == 1
        msg = str(w[0].message)
        assert "PADDLE_TPU_TEST_K" in msg and "'ten'" in msg and "7" in msg

    def test_strict_raises_named_error(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_TEST_K", "ten")
        with pytest.raises(EnvKnobError, match="PADDLE_TPU_TEST_K"):
            env_int("PADDLE_TPU_TEST_K", 7, strict=True)
        with pytest.raises(ValueError):  # EnvKnobError IS a ValueError
            env_float("PADDLE_TPU_TEST_K", 7.0, strict=True)

    def test_bool_conventions(self, monkeypatch):
        for off in ("0", "false", "OFF", "No"):
            monkeypatch.setenv("PADDLE_TPU_TEST_K", off)
            assert env_bool("PADDLE_TPU_TEST_K", True) is False
        monkeypatch.setenv("PADDLE_TPU_TEST_K", "1")
        assert env_bool("PADDLE_TPU_TEST_K", False) is True


class TestOffenderRegressions:
    """Each consumer the lint found parsing PADDLE_TPU_* numerics
    directly: garbled value -> default behavior, never a raw
    ValueError."""

    def test_event_buffer(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_EVENT_BUFFER", "lots")
        from paddle_tpu.profiler.events import EventLog
        log = EventLog()  # was: int('lots') ValueError at construction
        assert log._ring.maxlen == 512

    def test_retrace_warn(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_RETRACE_WARN", "many")
        from paddle_tpu.profiler.watchdog import RetraceWatchdog
        wd = RetraceWatchdog()
        assert wd.warn_threshold == 0

    def test_health_interval_and_groups(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_HEALTH_INTERVAL", "x")
        monkeypatch.setenv("PADDLE_TPU_HEALTH_GROUPS", "y")
        from paddle_tpu.profiler import health
        assert health.interval() == 1
        assert health.max_groups() == 32

    def test_profile_timeout(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PROFILE_TIMEOUT", "forever")
        from paddle_tpu.profiler import xplane
        assert xplane.capture_timeout() == xplane.DEFAULT_CAPTURE_TIMEOUT

    def test_health_stall_sec(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_HEALTH_STALL_SEC", "soon")
        from paddle_tpu.profiler import server
        out = server.liveness()
        assert out["stall_after_s"] == server.DEFAULT_STALL_SEC

    def test_ckpt_barrier_timeouts(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_CKPT_BARRIER_TIMEOUT", "slow")
        monkeypatch.setenv("PADDLE_TPU_CKPT_RESUME_TIMEOUT", "slower")
        from paddle_tpu.distributed.checkpoint import CheckpointCoordinator
        coord = CheckpointCoordinator(store=object(), rank=0, world_size=2)
        assert coord.timeout == 60.0
        assert coord.resume_timeout == 120.0

    def test_digest_window_and_interval(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_DIGEST_WINDOW", "wide")
        monkeypatch.setenv("PADDLE_TPU_DIGEST_INTERVAL", "often")
        from paddle_tpu.distributed.fleet.telemetry import FleetReporter
        rep = FleetReporter(store=None, rank=0)
        assert rep.walls.maxlen == 20
        assert rep.min_interval_s == 0.5

    def test_straggler_factor_and_stale_sec(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_STRAGGLER_FACTOR", "big")
        monkeypatch.setenv("PADDLE_TPU_DIGEST_STALE_SEC", "old")
        from paddle_tpu.distributed.fleet.telemetry import FleetAggregator
        agg = FleetAggregator(store=None, world_size=2)
        assert agg.straggler_factor == 2.0
        assert agg.stale_sec == 120.0

    def test_elastic_restart_num(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_RESTART_NUM", "zero")
        from paddle_tpu.distributed.fleet.telemetry import FleetReporter
        assert FleetReporter._generation() == 0

    def test_elastic_supervisor_knobs(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_MAX_RESTARTS", "lots")
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_BACKOFF", "fast")
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_BACKOFF_MAX", "slow")
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_BUDGET_RESET_SEC", "never")
        monkeypatch.setenv("PADDLE_TPU_CONTROLLER_POLL_SEC", "often")
        from paddle_tpu.distributed.fleet.elastic import ElasticSupervisor
        sup = ElasticSupervisor()
        assert sup.max_restarts == 3
        assert sup.backoff == 1.0
        assert sup.backoff_max == 30.0
        assert sup.budget_reset_s == 300.0
        assert sup.cmd_poll == 1.0

    def test_collective_timeout(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_COLLECTIVE_TIMEOUT", "soon")
        from paddle_tpu.distributed.collective import _deadline_seconds
        assert _deadline_seconds() == 0.0

    def test_retry_policy_from_env(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_STORE_RETRIES", "many")
        monkeypatch.setenv("PADDLE_TPU_STORE_BACKOFF", "fast")
        from paddle_tpu.fault.retry import RetryPolicy
        pol = RetryPolicy.from_env("store", max_attempts=5,
                                   base_delay=0.2)
        assert pol.max_attempts == 5
        assert pol.base_delay == 0.2

    def test_autotune_budget_knobs(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_MAX_CONFIGS", "all")
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_BUDGET_S", "unbounded")
        from paddle_tpu.ops.pallas.autotune import _float_knob, _int_knob
        assert _int_knob("PADDLE_TPU_AUTOTUNE_MAX_CONFIGS", 8) == 8
        assert _float_knob("PADDLE_TPU_AUTOTUNE_BUDGET_S", 20.0) == 20.0

    def test_supervisor_metrics_port(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_SUPERVISOR_METRICS_PORT", "auto")
        assert env_int("PADDLE_TPU_SUPERVISOR_METRICS_PORT", 8081) == 8081

    def test_ckpt_abort_exit_still_raises_named_error(self, monkeypatch):
        """This knob keeps the PR-5 STRICT contract: construction fails
        with an error NAMING the knob (not mid-training on the first
        aborted save)."""
        monkeypatch.setenv("PADDLE_TPU_CKPT_ABORT_EXIT", "twice")
        from paddle_tpu.hapi.callbacks import FaultTolerantCheckpoint
        with pytest.raises(ValueError, match="PADDLE_TPU_CKPT_ABORT_EXIT"):
            FaultTolerantCheckpoint("/tmp/nonexistent_ckpt_dir")
