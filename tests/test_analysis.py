"""Static program auditor (paddle_tpu/analysis): every check fires on a
seeded-hazard fixture naming the right param/layer, clean programs audit
clean, findings land on the events/metrics plane, and the runtime
PADDLE_TPU_AUDIT hook audits each jit entry exactly once.

The complementary direction — the SHIPPED GPT-2/ResNet-50/BERT
TrainSteps and the gpt2_decode serving path audit high-clean — is
pinned by tests/test_program_audit_gate.py over the real CLI.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.analysis import (AuditReport, Finding, audit_program,
                                 audit_sharding)
from paddle_tpu.analysis import auditor as auditor_mod
from paddle_tpu.profiler import events
from paddle_tpu.profiler import metrics as metrics_mod


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    events.default_event_log().clear()
    auditor_mod.reset_seen()
    monkeypatch.delenv("PADDLE_TPU_AUDIT", raising=False)
    yield
    events.default_event_log().clear()
    auditor_mod.reset_seen()


def _update_step(params, x):
    """The classic train-step shape: params replaced by same-shaped
    outputs (dead after the step)."""
    return jax.tree_util.tree_map(lambda p: p * 0.9, params), (x * 2).sum()


def _big_params():
    return {"w": jnp.ones((512, 1024), jnp.float32)}  # 2 MiB


class TestDonationCheck:
    def test_undonated_large_dead_input_fires_naming_the_param(self):
        rep = audit_program(_update_step, (_big_params(), jnp.ones((8,))),
                            name="fix", emit=False)
        f = [x for x in rep.findings if x.code == "undonated-large-input"]
        assert len(f) == 1 and f[0].severity == "high"
        assert "'w'" in f[0].param
        assert "donate_argnums" in f[0].fix_hint
        assert f[0].nbytes == 512 * 1024 * 4

    def test_donated_program_is_clean(self):
        rep = audit_program(_update_step, (_big_params(), jnp.ones((8,))),
                            donate_argnums=(0,), name="ok", emit=False)
        assert rep.clean

    def test_small_undonated_buffer_is_not_flagged(self):
        small = {"w": jnp.ones((8, 8), jnp.float32)}
        rep = audit_program(_update_step, (small, jnp.ones((8,))),
                            name="small", emit=False)
        assert rep.clean

    def test_rejected_donation_fires(self):
        # donated arg with NO alias-compatible output -> XLA drops the
        # donation; the lowered text carries no aliasing entry
        def step(big, x):
            return big.astype(jnp.bfloat16)[:1], x

        rep = audit_program(step, (jnp.ones((1024, 1024)), jnp.ones((4,))),
                            donate_argnums=(0,), name="rej", emit=False)
        f = [x for x in rep.findings if x.code == "donation-rejected"]
        assert len(f) == 1 and f[0].severity == "high"

    def test_accepted_donations_parsed_from_lowered_text(self):
        jitted = jax.jit(_update_step, donate_argnums=(0,))
        text = jitted.lower(_big_params(), jnp.ones((8,))).as_text()
        accepted = auditor_mod.accepted_donations(text)
        assert 0 in accepted  # the single param leaf is arg0

    def test_aliasing_attr_survives_quoted_sharding_attr(self):
        """Sharded lowerings prefix the attr dict with mhlo.sharding =
        "{devices=...}" — the quoted `}` must not truncate the match
        before tf.aliasing_output (a false donation-rejected otherwise)."""
        text = ('func.func public @main(%arg0: tensor<4x4xf32> '
                '{mhlo.sharding = "{devices=[2,1]<=[2]}", '
                'tf.aliasing_output = 0 : i32}, '
                '%arg1: tensor<3xf32>) -> (tensor<4x4xf32>) {')
        assert auditor_mod.accepted_donations(text) == {0}


class TestDtypeCheck:
    def test_f64_upcast_fires_high(self):
        from jax.experimental import enable_x64

        def step(x):
            with jax.named_scope("bad_layer"):
                return (x.astype(jnp.float64) * 2).sum()

        with enable_x64():
            rep = audit_program(step, (jnp.ones((8, 8), jnp.float32),),
                                name="f64", emit=False)
        f = [x for x in rep.findings if x.code == "f64-compute"]
        assert f and all(x.severity == "high" for x in f)
        assert any("bad_layer" in x.scope for x in f)

    def test_silent_upcast_and_f32_matmul_in_bf16_region(self):
        def step(x, w, w2):
            h = jnp.dot(x, w)                  # bf16 region
            with jax.named_scope("leaky"):
                h32 = h.astype(jnp.float32)    # large silent upcast
                return jnp.dot(h32, w2).sum()  # f32-operand matmul

        rep = audit_program(
            step, (jnp.ones((512, 1024), jnp.bfloat16),
                   jnp.ones((1024, 1024), jnp.bfloat16),
                   jnp.ones((1024, 1024), jnp.float32)),
            name="leak", emit=False)
        up = [x for x in rep.findings if x.code == "silent-upcast"]
        mm = [x for x in rep.findings if x.code == "f32-matmul-in-bf16"]
        assert up and up[0].severity == "medium" and "leaky" in up[0].scope
        assert mm and mm[0].severity == "medium" and "leaky" in mm[0].scope

    def test_f32_accumulation_from_bf16_operands_is_not_flagged(self):
        def step(x, w):
            return jax.lax.dot(x, w,
                               preferred_element_type=jnp.float32).sum()

        rep = audit_program(
            step, (jnp.ones((512, 1024), jnp.bfloat16),
                   jnp.ones((1024, 1024), jnp.bfloat16)),
            name="accum", emit=False)
        assert not [x for x in rep.findings
                    if x.code == "f32-matmul-in-bf16"]

    def test_pure_f32_model_has_no_region_findings(self):
        def step(x, w):
            return jnp.dot(x, w).sum()

        rep = audit_program(step, (jnp.ones((256, 256)),
                                   jnp.ones((256, 256))),
                            name="f32", emit=False)
        assert rep.clean


class TestShardingCheck:
    def test_replicated_param_fires_on_metadata(self):
        from jax.sharding import PartitionSpec as P
        rep = audit_sharding(
            {"emb": ((8192, 512), "float32", P(None, None)),
             "sharded": ((8192, 512), "float32", P("data", None)),
             "tiny": ((4, 4), "float32", P(None, None))},
            {"data": 8}, name="params", emit=False)
        f = [x for x in rep.findings if x.code == "replicated-param"]
        assert len(f) == 1 and f[0].severity == "high"
        assert "emb" in f[0].param and "'data'" in f[0].fix_hint

    def test_no_usable_axis_means_clean(self):
        from jax.sharding import PartitionSpec as P
        rep = audit_sharding(
            {"emb": ((8192, 512), "float32", P(None, None))},
            {"data": 1}, name="params", emit=False)
        assert rep.clean

    def test_indivisible_shape_is_not_flagged(self):
        from jax.sharding import PartitionSpec as P
        rep = audit_sharding(
            {"odd": ((8191, 513), "float32", P(None, None))},
            {"data": 8}, name="params", emit=False)
        assert rep.clean

    def test_collective_budget_fires(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_AUDIT_COLLECTIVE_BUDGET_MB", "1")
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()[:1]), ("i",))
        f = shard_map(lambda x: jax.lax.psum(x, "i"), mesh=mesh,
                      in_specs=P(), out_specs=P())
        rep = audit_program(f, (jnp.ones((1024, 1024)),),
                            donate_argnums=(0,), name="coll", emit=False)
        hits = [x for x in rep.findings
                if x.code == "collective-budget-exceeded"]
        assert len(hits) == 1 and hits[0].severity == "high"
        assert "psum" in hits[0].message


class TestBloatCheck:
    def test_baked_constant_fires(self):
        baked = np.ones((1024, 512), np.float32)  # 2 MiB closure capture

        def step(x):
            return x @ jnp.asarray(baked)

        rep = audit_program(step, (jnp.ones((8, 1024)),), name="baked",
                            emit=False)
        f = [x for x in rep.findings if x.code == "baked-constant"]
        assert len(f) == 1 and f[0].severity == "high"
        assert "argument" in f[0].fix_hint

    def test_passed_as_argument_is_clean(self):
        def step(x, w):
            return x @ w

        rep = audit_program(step, (jnp.ones((8, 1024)),
                                   jnp.ones((1024, 512))),
                            name="arg", emit=False)
        assert rep.clean

    def test_retrace_risk_static_arg_flagged(self):
        rep = AuditReport(name="s", entry="offline")
        auditor_mod._check_bloat(rep, (), {"temperature": 0.7})
        f = [x for x in rep.findings if x.code == "retrace-risk-static"]
        assert len(f) == 1 and f[0].severity == "low"
        assert "temperature" in f[0].param


class TestEmission:
    def test_findings_land_as_events_and_metrics(self):
        reg = metrics_mod.default_registry()

        def val(fam, **labels):
            snap = reg.snapshot().get(fam, {})
            for v in snap.get("values", []):
                if all(v.get("labels", {}).get(k) == lv
                       for k, lv in labels.items()):
                    return v["value"]
            return 0

        before = val("analysis_findings_total", check="donation",
                     severity="high")
        audits_before = val("analysis_audits_total", entry="offline")
        rep = audit_program(_update_step, (_big_params(), jnp.ones((8,))),
                            name="emitting", emit=True)
        assert not rep.clean
        evs = events.recent(20, kind="analysis_finding")
        assert evs, "no analysis_finding event emitted"
        ev = evs[-1]
        assert ev["severity"] == "error"  # high -> error
        assert ev["program"] == "emitting" and ev["check"] == "donation"
        assert ev["finding_severity"] == "high" and ev["fix_hint"]
        assert val("analysis_findings_total", check="donation",
                   severity="high") == before + 1
        assert val("analysis_audits_total", entry="offline") == \
            audits_before + 1

    def test_finding_validates_severity_and_check(self):
        with pytest.raises(ValueError):
            Finding(check="donation", severity="fatal", code="x",
                    message="m")
        with pytest.raises(ValueError):
            Finding(check="nonsense", severity="high", code="x",
                    message="m")

    def test_report_to_dict_ranks_by_severity(self):
        rep = AuditReport(name="r", entry="offline")
        rep.add(Finding(check="dtype", severity="low", code="a",
                        message="m"))
        rep.add(Finding(check="bloat", severity="high", code="b",
                        message="m"))
        d = rep.to_dict()
        assert d["findings"][0]["code"] == "b"
        assert d["counts"] == {"info": 0, "low": 1, "medium": 0, "high": 1}
        assert rep.by_severity("high")[0].code == "b"


def _tiny_train_step():
    from paddle_tpu import optimizer
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.nn import functional as F
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, max_position_embeddings=32,
                    hidden_size=16, num_layers=1, num_heads=2,
                    dropout=0.0, attn_dropout=0.0)
    m = GPT(cfg)
    opt = optimizer.AdamW(learning_rate=1e-4, parameters=m.parameters())
    step = TrainStep(m, F.cross_entropy, opt)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (2, 16)).astype("int32"))
    return step, ids


class TestEntryPoints:
    def test_train_step_audit_method(self):
        step, ids = _tiny_train_step()
        rep = step.audit(ids, ids, emit=False)
        assert rep.entry == "train_step"
        assert not rep.by_severity("high")

    def test_static_layer_audit_method(self):
        from paddle_tpu.jit import to_static
        from paddle_tpu.models.lenet import LeNet
        paddle.seed(0)
        st = to_static(LeNet())
        x = paddle.to_tensor(
            np.zeros((2, 1, 28, 28), np.float32))
        rep = st.audit(x, emit=False)
        assert rep.entry == "to_static"
        assert not rep.by_severity("high")

    def test_audit_env_hook_audits_train_step_once(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_AUDIT", "1")
        reg = metrics_mod.default_registry()

        def audits():
            snap = reg.snapshot().get("analysis_audits_total", {})
            return sum(v["value"] for v in snap.get("values", [])
                       if v.get("labels", {}).get("entry") == "train_step")

        step, ids = _tiny_train_step()
        before = audits()
        step(ids, ids)
        assert audits() == before + 1
        step(ids, ids)  # same site: audited once per process
        assert audits() == before + 1

    def test_audit_env_hook_handles_nested_batch(self, monkeypatch):
        """The runtime hook must trace the SAME signature the real step
        compiles: a nested batch element stays unflattened (flattening
        it used to TypeError inside maybe_audit and silently disable
        runtime auditing for the model)."""
        import warnings as _w
        from paddle_tpu import nn, optimizer
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.nn import functional as F
        monkeypatch.setenv("PADDLE_TPU_AUDIT", "1")

        class PairNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 4)

            def forward(self, pair):
                a, b = pair
                return self.fc(a + b)

        paddle.seed(0)
        m = PairNet()
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=m.parameters())
        step = TrainStep(m, F.cross_entropy, opt)
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        y = paddle.to_tensor(np.zeros((4,), np.int64))
        reg = metrics_mod.default_registry()

        def audits():
            snap = reg.snapshot().get("analysis_audits_total", {})
            return sum(v["value"] for v in snap.get("values", [])
                       if v.get("labels", {}).get("entry") == "train_step")

        before = audits()
        with _w.catch_warnings():
            _w.simplefilter("error")  # an audit-failed warning FAILS here
            step((x, x), y)
        assert audits() == before + 1

    def test_audit_env_off_means_no_audit(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_AUDIT", "0")
        reg = metrics_mod.default_registry()
        step, ids = _tiny_train_step()
        snap0 = reg.snapshot().get("analysis_audits_total", {})
        n0 = sum(v["value"] for v in snap0.get("values", []))
        step(ids, ids)
        snap1 = reg.snapshot().get("analysis_audits_total", {})
        n1 = sum(v["value"] for v in snap1.get("values", []))
        assert n1 == n0

    def test_eager_entry_only_under_all(self, monkeypatch):
        assert not auditor_mod.enabled("eager") if not \
            __import__("os").environ.get("PADDLE_TPU_AUDIT") else True
        monkeypatch.setenv("PADDLE_TPU_AUDIT", "1")
        assert auditor_mod.enabled("train_step")
        assert not auditor_mod.enabled("eager")
        monkeypatch.setenv("PADDLE_TPU_AUDIT", "all")
        assert auditor_mod.enabled("eager")

    def test_maybe_audit_swallows_failures(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_AUDIT", "1")

        def broken(x):
            raise RuntimeError("boom")

        with pytest.warns(UserWarning, match="program audit"):
            out = auditor_mod.maybe_audit("train_step", "broken#1",
                                          broken, (jnp.ones((2,)),))
        assert out is None

    def test_serving_engine_audit(self):
        from paddle_tpu.inference.serving import ServingEngine
        from paddle_tpu.models.gpt import GPT, GPTConfig
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, max_position_embeddings=64,
                        hidden_size=16, num_layers=1, num_heads=2,
                        dropout=0.0, attn_dropout=0.0)
        m = GPT(cfg)
        m.eval()
        eng = ServingEngine(m, max_batch=2, max_len=32, page_size=8,
                            name="audit_t")
        reports = eng.audit(emit=False)
        assert [r.entry for r in reports] == ["serving_decode",
                                              "serving_prefill"]
        assert not any(r.by_severity("high") for r in reports)
