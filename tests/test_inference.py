"""Predictor API tests (reference: inference/tests/api golden tests +
`test_inference_api.py`): save a model, load through Config/create_predictor,
run via handles, match eager outputs."""
import numpy as np

import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, static
from paddle_tpu.inference import Config, PrecisionType, create_predictor


def _export_static_mlp(tmp_path):
    """Build + save a static-graph MLP; returns (prefix, W, b)."""
    paddle.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", shape=[None, 8], dtype="float32")
            out = static.nn.fc(x, 4)
        exe = static.Executor()
        exe.run(startup)
        scope = static.global_scope()
        # restrict to THIS program's params: the global scope accumulates
        # vars from other tests in the same process
        own = set(main.params.keys())
        wname = [n for n in own if "_w_" in n][0]
        bname = [n for n in own if "_b_" in n][0]
        W = np.asarray(scope.vars[wname])
        b = np.asarray(scope.vars[bname])
        prefix = str(tmp_path / "model")
        static.save_inference_model(prefix, [x], [out], exe, program=main)
        return prefix, W, b
    finally:
        paddle.disable_static()


class TestPredictorStaticArtifact:
    def test_handles_roundtrip(self, tmp_path):
        prefix, W, b = _export_static_mlp(tmp_path)
        cfg = Config(prefix)
        assert cfg.prog_file().endswith(".pdmodel")
        pred = create_predictor(cfg)
        assert pred.get_input_names() == ["x"]
        xin = np.random.default_rng(1).normal(size=(3, 8)).astype(np.float32)
        h = pred.get_input_handle("x")
        h.copy_from_cpu(xin)
        pred.run()
        out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_allclose(out, xin @ W + b, rtol=1e-5, atol=1e-5)

    def test_positional_run(self, tmp_path):
        prefix, W, b = _export_static_mlp(tmp_path)
        pred = create_predictor(Config(prefix))
        xin = np.ones((2, 8), np.float32)
        outs = pred.run([xin])
        np.testing.assert_allclose(outs[0], xin @ W + b, rtol=1e-5, atol=1e-5)

    def test_dynamic_batch(self, tmp_path):
        """None batch dim exported shape-polymorphically: different batch
        sizes run without re-export."""
        prefix, W, b = _export_static_mlp(tmp_path)
        pred = create_predictor(Config(prefix))
        for bs in (1, 5, 9):
            xin = np.full((bs, 8), 0.5, np.float32)
            outs = pred.run([xin])
            assert outs[0].shape == (bs, 4)

    def test_clone_shares_weights(self, tmp_path):
        prefix, W, b = _export_static_mlp(tmp_path)
        pred = create_predictor(Config(prefix))
        c = pred.clone()
        assert c._params is pred._params
        xin = np.ones((2, 8), np.float32)
        np.testing.assert_allclose(c.run([xin])[0], pred.run([xin])[0])


class TestPredictorJitArtifact:
    def test_jit_saved_layer(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 2))
        xin = np.random.default_rng(0).normal(size=(4, 6)).astype(np.float32)
        want = net(paddle.to_tensor(xin)).numpy()
        prefix = str(tmp_path / "jitmodel")
        paddle.jit.save(net, prefix,
                        input_spec=[paddle.static.InputSpec([4, 6], "float32")])
        pred = create_predictor(Config(prefix))
        outs = pred.run([xin])
        np.testing.assert_allclose(outs[0], want, rtol=1e-5, atol=1e-5)


    def test_jit_saved_layer_with_buffers(self, tmp_path):
        """BatchNorm holds running-stat buffers: the export signature splits
        params/buffers and the Predictor must reconstruct both trees."""
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(6, 8), nn.BatchNorm1D(8), nn.ReLU(),
                            nn.Linear(8, 2))
        net.eval()
        xin = np.random.default_rng(0).normal(size=(4, 6)).astype(np.float32)
        want = net(paddle.to_tensor(xin)).numpy()
        prefix = str(tmp_path / "bnmodel")
        paddle.jit.save(net, prefix,
                        input_spec=[paddle.static.InputSpec([4, 6], "float32")])
        pred = create_predictor(Config(prefix))
        np.testing.assert_allclose(pred.run([xin])[0], want,
                                   rtol=1e-5, atol=1e-5)
        # jit.load path splits the same way
        tl = paddle.jit.load(prefix)
        np.testing.assert_allclose(tl(paddle.to_tensor(xin)).numpy(), want,
                                   rtol=1e-5, atol=1e-5)


class TestConfig:
    def test_device_toggles(self):
        cfg = Config()
        cfg.enable_use_gpu(100, 0, PrecisionType.Bfloat16)
        assert cfg.use_gpu()
        cfg.disable_gpu()
        assert not cfg.use_gpu()
        assert "Config" in cfg.summary()

    def test_missing_model_raises(self):
        with pytest.raises(ValueError):
            create_predictor(Config())


class TestInt8Predictor:
    """PTQ int8 artifact served by the Predictor (reference slim
    post_training_quantization feeding the int8 inference engine)."""

    def _calibrated_lenet(self):
        from paddle_tpu.models import LeNet
        from paddle_tpu.quantization import PTQ
        paddle.seed(0)
        model = LeNet()
        model.eval()
        rng = np.random.default_rng(0)
        batches = [paddle.to_tensor(
            rng.normal(size=(8, 1, 28, 28)).astype(np.float32))
            for _ in range(4)]
        ptq = PTQ(algo="abs_max")
        ptq.sample(model, batches)
        fp32_out = model(batches[0]).numpy()
        ptq.convert(model)
        return ptq, model, batches, fp32_out

    def test_quantized_artifact_served_within_tolerance(self, tmp_path):
        from paddle_tpu import inference
        ptq, qmodel, batches, fp32_out = self._calibrated_lenet()
        path = str(tmp_path / "lenet_int8")
        spec = [jax.ShapeDtypeStruct((8, 1, 28, 28), jnp.float32)]
        ptq.save_quantized_model(qmodel, path, input_spec=spec)

        cfg = inference.Config(path + ".pdmodel", path + ".pdiparams")
        pred = inference.create_predictor(cfg)
        (out,) = pred.run([batches[0].numpy()])
        # int8 path matches the eager quantized model bit-for-bit
        np.testing.assert_allclose(out, qmodel(batches[0]).numpy(),
                                   rtol=1e-5, atol=1e-5)
        # and the fp32 model within quantization tolerance
        rel = np.abs(out - fp32_out).max() / (np.abs(fp32_out).max() + 1e-9)
        assert rel < 0.15, rel

    def test_int8_artifact_actually_smaller(self, tmp_path):
        import os
        from paddle_tpu.models import LeNet
        from paddle_tpu import jit as pjit
        ptq, qmodel, batches, _ = self._calibrated_lenet()
        qpath = str(tmp_path / "lenet_int8")
        spec = [jax.ShapeDtypeStruct((8, 1, 28, 28), jnp.float32)]
        ptq.save_quantized_model(qmodel, qpath, input_spec=spec)
        paddle.seed(0)
        fp32 = LeNet()
        fp32.eval()
        fpath = str(tmp_path / "lenet_fp32")
        pjit.save(fp32, fpath, input_spec=spec)
        q_bytes = os.path.getsize(qpath + ".pdiparams")
        f_bytes = os.path.getsize(fpath + ".pdiparams")
        # conv/fc weights dominate LeNet; int8 storage must cut the
        # artifact to well under half of fp32 (ideally ~1/4)
        assert q_bytes < 0.5 * f_bytes, (q_bytes, f_bytes)
        # the served params really are int8
        from paddle_tpu.framework import io as io_mod
        raw = io_mod.load(qpath + ".pdiparams", return_numpy=True)
        int8_keys = [k for k, v in raw.items()
                     if np.asarray(v).dtype == np.int8]
        assert len(int8_keys) >= 3, list(raw)
