"""Regression tests for review findings."""
import pytest

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.framework.param import Parameter
from paddle_tpu.framework.tensor import Tensor


def test_cross_entropy_default_ignore_index():
    # -100-padded labels must be masked with the DEFAULT ignore_index
    logits = np.random.randn(4, 7).astype(np.float32)
    labels = np.array([1, -100, 3, -100])
    loss = F.cross_entropy(Tensor(logits), Tensor(labels))
    lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    ref = -(lp[0, 1] + lp[2, 3]) / 2  # mean over the 2 valid tokens only
    np.testing.assert_allclose(loss.numpy(), ref, rtol=1e-5)


def test_nll_loss_ignore_index():
    logp = np.log(np.full((3, 4), 0.25, np.float32))
    labels = np.array([0, -100, 2])
    loss = F.nll_loss(Tensor(logp), Tensor(labels))
    np.testing.assert_allclose(loss.numpy(), -np.log(0.25), rtol=1e-6)


def test_grad_scaler_no_double_unscale():
    p = Parameter(np.ones(2, np.float32))
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[p])
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
    loss = (p * 3.0).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(opt)      # user unscales manually (e.g. to clip)
    g_before = p.grad.numpy().copy()
    scaler.step(opt)          # must NOT unscale a second time
    np.testing.assert_allclose(g_before, [3.0, 3.0], rtol=1e-6)
    np.testing.assert_allclose(p.numpy(), [1.0 - 3.0] * 2, rtol=1e-6)


def test_backward_preserves_other_graphs():
    x = Parameter(np.array([2.0], np.float32))
    l1 = (x * 3.0).sum()
    l2 = (x * 4.0).sum()
    l1.backward()
    l2.backward()  # second graph must still be intact
    np.testing.assert_allclose(x.grad.numpy(), [7.0])


@pytest.mark.slow
def test_tape_id_reuse_safe():
    # discarded outputs (dead tensors) must never swallow cotangents
    import gc
    x = Parameter(np.ones(4, np.float32))
    for _ in range(50):
        tmp = x * 2.0  # dropped immediately; id may be reused
        del tmp
        gc.collect()
    loss = (x * 5.0).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0] * 4)


def test_adamw_decay_exclusion():
    p_w = Parameter(np.ones(2, np.float32))
    p_w.name = "linear.weight"
    p_b = Parameter(np.ones(2, np.float32))
    p_b.name = "norm.bias"
    opt = paddle.optimizer.AdamW(
        learning_rate=0.1, parameters=[p_w, p_b], weight_decay=0.5,
        apply_decay_param_fun=lambda n: "bias" not in n and "norm" not in n)
    # zero grads -> pure decay effect
    p_w.grad = Tensor(np.zeros(2, np.float32))
    p_b.grad = Tensor(np.zeros(2, np.float32))
    opt.step()
    assert p_w.numpy()[0] < 1.0          # decayed
    np.testing.assert_allclose(p_b.numpy(), [1.0, 1.0])  # excluded


@pytest.mark.slow  # thread-churn soak; the dataloader fast paths stay tier-1
def test_dataloader_abandoned_iterator_no_leak():
    import gc
    import threading
    from paddle_tpu.io import DataLoader, TensorDataset
    X = Tensor(np.random.randn(64, 4).astype(np.float32))
    dl = DataLoader(TensorDataset([X]), batch_size=4)
    before = threading.active_count()
    for _ in range(5):
        it = iter(dl)
        next(it)
        del it  # abandon mid-epoch
        gc.collect()
    import time
    time.sleep(0.5)
    after = threading.active_count()
    assert after <= before + 1, f"leaked threads: {before} -> {after}"


def test_split_indivisible_raises():
    import pytest
    with pytest.raises(ValueError):
        paddle.split(paddle.ones([2, 5]), 3, axis=1)
