"""Pipeline parallelism on the 8-device CPU mesh.

Reference test style: `test_parallel_dygraph_pipeline_parallel.py` asserts
the pipelined model's losses track the plain model. Here the pp axis is a
mesh dim and the 1F1B schedule is a compiled rotation
(meta_parallel/pipeline_parallel.py), so the comparison is exact-math
(same ops, fp32) up to reduction-order tolerance.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn, optimizer
from paddle_tpu.nn import functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.meta_parallel import (
    LayerDesc, PipelineLayer, PipelineParallel, PipelineParallelTrainStep,
    SharedLayerDesc)
from paddle_tpu.distributed.topology import HybridCommunicateGroup
from paddle_tpu.models.gpt import GPT, GPTConfig


@pytest.fixture(autouse=True)
def _clean_topology():
    yield
    dist.set_hybrid_communicate_group(None)
    dist.destroy_process_group()


def _setup(dims, strategy=None):
    fleet.init(is_collective=True, strategy=strategy or DistributedStrategy())
    hcg = HybridCommunicateGroup(dims=dims)
    dist.set_hybrid_communicate_group(hcg)
    return hcg


def _gpt_batch(cfg, B=8, L=32, seed=0):
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, cfg.vocab_size, (B, L)).astype(np.int32)
    labels = rs.randint(0, cfg.vocab_size, (B, L)).astype(np.int32)
    return ids, labels


def _single_device_losses(model_fn, batches, lr=1e-2, steps=3):
    """Ground truth: plain TrainStep on one device."""
    from paddle_tpu.jit import TrainStep
    paddle.seed(0)
    model = model_fn()
    opt = optimizer.Adam(learning_rate=lr, parameters=model.parameters())
    step = TrainStep(model, F.cross_entropy, opt, donate=False)
    return [float(step(paddle.to_tensor(a), paddle.to_tensor(b)))
            for a, b in batches]


class TestPipelineGPT:
    @pytest.mark.slow  # heavy e2e; full-suite only (tier-1 budget)
    def test_pp_matches_single_device(self):
        cfg = GPTConfig.tiny()  # 2 blocks -> 2 stages
        batches = [_gpt_batch(cfg, B=16, seed=s) for s in range(3)]
        ref = _single_device_losses(lambda: GPT(cfg), batches)

        hcg = _setup({"pp": 2, "dp": 4})
        paddle.seed(0)
        model = GPT(cfg)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())
        step = PipelineParallelTrainStep(
            model, F.cross_entropy, opt, hcg=hcg, num_micro=4, donate=False)
        got = [float(step(paddle.to_tensor(a), paddle.to_tensor(b)))
               for a, b in batches]
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    @pytest.mark.slow  # heavy e2e; full-suite only (tier-1 budget)
    def test_pp_with_tp(self):
        cfg = GPTConfig.tiny()
        batches = [_gpt_batch(cfg, seed=s) for s in range(2)]
        ref = _single_device_losses(lambda: GPT(cfg), batches)

        from jax.sharding import PartitionSpec as P
        hcg = _setup({"pp": 2, "mp": 2, "dp": 2})
        paddle.seed(0)
        model = GPT(cfg)
        for name, p in model.named_parameters():
            if name.endswith(("qkv.weight", "fc1.weight")):
                p.dist_spec = P(None, "mp")
            elif name.endswith(("qkv.bias", "fc1.bias")):
                p.dist_spec = P("mp")
            elif name.endswith(("proj.weight", "fc2.weight")):
                p.dist_spec = P("mp", None)
            elif name.endswith("wte.weight"):
                p.dist_spec = P("mp", None)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())
        step = PipelineParallelTrainStep(
            model, F.cross_entropy, opt, hcg=hcg, num_micro=2, donate=False)
        # block params really sharded over pp (stage dim) and mp
        qkv = step.params["blocks"]["attn.qkv.weight"]
        assert "pp" in str(qkv.sharding.spec)
        assert "mp" in str(qkv.sharding.spec)
        got = [float(step(paddle.to_tensor(a), paddle.to_tensor(b)))
               for a, b in batches]
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_sync_to_layer_roundtrip(self):
        cfg = GPTConfig.tiny()
        hcg = _setup({"pp": 2})
        paddle.seed(0)
        model = GPT(cfg)
        before = {k: np.asarray(p.data).copy()
                  for k, p in model.named_parameters()}
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        step = PipelineParallelTrainStep(
            model, F.cross_entropy, opt, hcg=hcg, num_micro=2, donate=False)
        a, b = _gpt_batch(cfg)
        step(paddle.to_tensor(a), paddle.to_tensor(b))
        step.sync_to_layer()
        changed = sum(
            not np.allclose(before[k], np.asarray(p.data))
            for k, p in model.named_parameters())
        assert changed >= len(before) - 1  # everything trained moved


class TestPipelineLayerAPI:
    def test_segmentation(self):
        descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(9)]
        pl = PipelineLayer(layers=descs, num_stages=4)
        assert pl.segment() == [0, 3, 5, 7, 9]
        assert pl.get_stage_of(0) == 0 and pl.get_stage_of(8) == 3

    def test_seg_method_layer(self):
        layers = [LayerDesc(nn.Embedding, 16, 8)]
        layers += [LayerDesc(nn.Linear, 8, 8) for _ in range(4)]
        pl = PipelineLayer(layers=layers, num_stages=2,
                           seg_method="layer:Linear")
        b = pl.segment()
        assert b[0] == 0 and b[-1] == 5 and len(b) == 3

    def test_scan_region_detects_homogeneous_run(self):
        layers = [LayerDesc(nn.Embedding, 16, 8)]
        layers += [LayerDesc(nn.Linear, 8, 8) for _ in range(4)]
        layers += [LayerDesc(nn.Linear, 8, 2)]
        pl = PipelineLayer(layers=layers, num_stages=2)
        start, stop = pl.scan_region()
        assert (start, stop) == (1, 5)

    def test_shared_layer_desc_ties_weights(self):
        def head(layer, x):
            from paddle_tpu.ops import matmul
            return matmul(x, layer.weight, transpose_y=True)

        layers = [
            SharedLayerDesc("embed", nn.Embedding, None, "weight", 32, 8),
            LayerDesc(nn.Linear, 8, 8),
            SharedLayerDesc("embed", nn.Embedding, head, "weight", 32, 8),
        ]
        pl = PipelineLayer(layers=layers, num_stages=1)
        names = [k for k, _ in pl.named_parameters()]
        assert sum("embedding" in n.lower() or "embed" in n
                   for n in names) == 1  # tied -> single registration
        x = paddle.to_tensor(np.array([[1, 2, 3]], dtype=np.int32))
        out = pl(x)
        assert tuple(out.shape) == (1, 3, 32)

    def test_pipeline_layer_e2e_train(self):
        """PipelineLayer path through PipelineParallel.train_batch."""
        hcg = _setup({"pp": 2})
        paddle.seed(0)
        layers = [LayerDesc(nn.Linear, 16, 16) for _ in range(4)]
        pl = PipelineLayer(layers=layers, num_stages=2,
                           loss_fn=lambda out, y: F.mse_loss(out, y))
        model = PipelineParallel(pl, hcg=hcg)
        opt = optimizer.SGD(learning_rate=0.05,
                            parameters=pl.parameters())
        rs = np.random.RandomState(0)
        X = rs.randn(8, 16).astype(np.float32)
        Y = rs.randn(8, 16).astype(np.float32)
        losses = [float(model.train_batch(
            [paddle.to_tensor(X), paddle.to_tensor(Y)], opt))
            for _ in range(5)]
        assert losses[-1] < losses[0]


class TestPipeline1F1BMemory:
    @pytest.mark.slow  # M=8*S compiled-memory probe; e2e siblings stay fast
    def test_peak_memory_bounded_by_boundary_activations(self):
        """M=8*S micro-batches: compiled temp memory may grow only by the
        per-tick boundary-activation residuals (~linear, small constant) —
        NOT by a pp-replicated [M, B, T, D] collection buffer (round-1
        design). Budget: 4x the boundary activation per extra micro-batch."""
        from paddle_tpu.framework import random as random_mod
        S, dp = 2, 4
        temps = {}
        cfg = GPTConfig.tiny()
        for M in (2 * S, 8 * S):
            hcg = _setup({"pp": S, "dp": dp})
            paddle.seed(0)
            model = GPT(cfg)
            opt = optimizer.AdamW(learning_rate=1e-4,
                                  parameters=model.parameters())
            step = PipelineParallelTrainStep(model, F.cross_entropy, opt,
                                             hcg=hcg, num_micro=M)
            B, L = M * 4, 32
            ids, labels = _gpt_batch(cfg, B=B, L=L)
            arrs = step.shard_batch(ids, labels)
            rng = random_mod.default_generator().split()
            lr = jnp.asarray(1e-4, jnp.float32)
            with step.mesh:
                compiled = step._step.lower(
                    step._flat_params, step.buffers, step.opt_state,
                    step.scaler_state, rng, lr, 1, *arrs).compile()
                temps[M] = compiled.memory_analysis().temp_size_in_bytes
            dist.set_hybrid_communicate_group(None)
        D = cfg.hidden_size
        boundary = (4 // dp or 1) * 32 * D * 4  # one [B/dp, T, D] f32 tile
        budget = temps[2 * S] + (8 * S - 2 * S) * 4 * boundary
        assert temps[8 * S] <= budget, (temps, budget)

    def test_batchnorm_block_raises_with_guidance(self):
        hcg = _setup({"pp": 2, "dp": 4})
        try:
            blocks = [nn.Sequential(nn.Linear(8, 8), nn.BatchNorm1D(8))
                      for _ in range(2)]

            class BNModel(nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.blocks = nn.LayerList(blocks)

                def pipeline_pre(self, x):
                    return x

                def pipeline_post(self, h):
                    return h

                def forward(self, x):
                    for b in self.blocks:
                        x = b(x)
                    return x

            model = BNModel()
            opt = optimizer.AdamW(learning_rate=1e-3,
                                  parameters=model.parameters())
            with pytest.raises(ValueError, match="BatchNorm"):
                PipelineParallelTrainStep(model, lambda o, y: o.mean(),
                                          opt, hcg=hcg)
        finally:
            dist.set_hybrid_communicate_group(None)


class TestPipelineUnevenSegmentation:
    """VERDICT r2 missing #5: non-divisible layer counts (reference
    SegmentLayers supports uneven + cost splits, pp_layers.py:63,282).
    The compiled pipeline pads stages to max(counts) with masked slots."""

    @pytest.mark.slow
    def test_pp_13_layers_over_4_stages_matches_single_device(self):
        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=13,
                        num_heads=2, max_position_embeddings=32,
                        dropout=0.0, attn_dropout=0.0)
        batches = [_gpt_batch(cfg, B=8, L=16, seed=s) for s in range(3)]
        ref = _single_device_losses(lambda: GPT(cfg), batches)

        hcg = _setup({"pp": 4, "dp": 2})
        paddle.seed(0)
        model = GPT(cfg)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())
        step = PipelineParallelTrainStep(
            model, F.cross_entropy, opt, hcg=hcg, num_micro=4, donate=False)
        assert step.run.counts == [4, 3, 3, 3]
        got = [float(step(paddle.to_tensor(a), paddle.to_tensor(b)))
               for a, b in batches]
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)

    def test_uneven_sync_to_layer_skips_pad_slots(self):
        cfg = GPTConfig(vocab_size=32, hidden_size=8, num_layers=3,
                        num_heads=2, max_position_embeddings=16,
                        dropout=0.0, attn_dropout=0.0)
        hcg = _setup({"pp": 2})
        paddle.seed(0)
        model = GPT(cfg)
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        step = PipelineParallelTrainStep(
            model, F.cross_entropy, opt, hcg=hcg, num_micro=2, donate=False)
        assert step.run.counts == [2, 1]
        a, b = _gpt_batch(cfg, B=8, L=8)
        step(paddle.to_tensor(a), paddle.to_tensor(b))
        step.sync_to_layer()  # must not crash or write pad slots
        # all real block params moved
        for k, p in model.named_parameters():
            assert np.isfinite(np.asarray(p.data)).all(), k

    def test_seg_method_layer_compiled_path(self):
        """seg_method='layer:Linear' drives the compiled stage counts."""
        hcg = _setup({"pp": 2})
        paddle.seed(0)
        layers = [LayerDesc(nn.Embedding, 16, 8)]
        layers += [LayerDesc(nn.Linear, 8, 8) for _ in range(5)]
        pl = PipelineLayer(layers=layers, num_stages=2,
                           seg_method="layer:Linear",
                           loss_fn=lambda out, y: F.mse_loss(out, y))
        model = PipelineParallel(pl, hcg=hcg)
        opt = optimizer.SGD(learning_rate=0.05, parameters=pl.parameters())
        rs = np.random.RandomState(0)
        X = rs.randint(0, 16, (8,)).astype(np.int32)
        Y = rs.randn(8, 8).astype(np.float32)
        losses = [float(model.train_batch(
            [paddle.to_tensor(X), paddle.to_tensor(Y)], opt))
            for _ in range(5)]
        assert losses[-1] < losses[0]
        assert model._train_step.run.counts == [2, 3]


class TestHealthProbeWiring:
    """r06 satellite: the PR-9 sentinel in the pipeline engine's compiled
    step (regression per parallelism mode; hybrid has its own sibling)."""

    @pytest.mark.slow  # full pipeline trace; test_health_off_default stays fast
    def test_sentinel_records_on_pipeline_step(self):
        cfg = GPTConfig.tiny()
        hcg = _setup({"pp": 2})
        paddle.seed(0)
        model = GPT(cfg)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())
        step = PipelineParallelTrainStep(
            model, F.cross_entropy, opt, hcg=hcg, num_micro=2,
            donate=False, health=True)
        assert step._health_probe is not None
        # B=16 over 2 micro-batches of 8: divisible by the dp axis that
        # fills the rest of the 8-device mesh
        a, b = _gpt_batch(cfg, B=16, L=16)
        loss = float(step(paddle.to_tensor(a), paddle.to_tensor(b)))
        rec = step.last_health
        assert rec is not None
        assert rec["loss"] == pytest.approx(loss, rel=1e-5)
        assert np.isfinite(rec["grad_norm"]) and rec["grad_norm"] > 0
        assert not rec["nonfinite"]

    def test_health_off_default(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_HEALTH", raising=False)
        cfg = GPTConfig.tiny()
        hcg = _setup({"pp": 2})
        paddle.seed(0)
        model = GPT(cfg)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())
        step = PipelineParallelTrainStep(
            model, F.cross_entropy, opt, hcg=hcg, num_micro=2,
            donate=False)
        assert step._health_probe is None
