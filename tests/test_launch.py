"""Launcher CLI + spawn (reference test style: `test_fleet_launch_*.sh`
run the CLI against localhost scripts and assert the env contract)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_group(cmd, env, cwd=None, timeout=120):
    """subprocess.run equivalent that kills the WHOLE process group on
    timeout — plain run() kills only the direct child, leaking pod workers
    that can wedge the one shared TPU chip (round-3 failure mode)."""
    import signal
    proc = subprocess.Popen(cmd, env=env, cwd=cwd, text=True,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout)
    finally:
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait(timeout=10)
    return subprocess.CompletedProcess(cmd, proc.returncode, out, err)


def _run_launch(tmp_path, script_body, extra_args=(), nproc=2):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(script_body))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nproc_per_node", str(nproc),
           "--log_dir", str(tmp_path / "log"), *extra_args, str(script)]
    return _run_group(cmd, env, cwd=str(tmp_path))


class TestLaunchCLI:
    def test_env_contract_and_success(self, tmp_path):
        r = _run_launch(tmp_path, """
            import os, json
            rank = int(os.environ["PADDLE_TRAINER_ID"])
            n = int(os.environ["PADDLE_TRAINERS_NUM"])
            eps = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")
            cur = os.environ["PADDLE_CURRENT_ENDPOINT"]
            assert n == 2 and len(eps) == 2 and eps[rank] == cur, (eps, cur)
            assert os.environ["MASTER_ADDR"]
            with open(f"ok.{rank}", "w") as f:
                f.write(cur)
        """)
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "ok.0").exists() and (tmp_path / "ok.1").exists()
        # distinct endpoints per rank
        assert (tmp_path / "ok.0").read_text() != \
            (tmp_path / "ok.1").read_text()

    def test_failure_propagates_exit_code(self, tmp_path):
        r = _run_launch(tmp_path, """
            import os, sys
            sys.exit(7 if os.environ["PADDLE_TRAINER_ID"] == "1" else 0)
        """)
        assert r.returncode == 7

    @pytest.mark.slow
    def test_elastic_restarts_then_gives_up(self, tmp_path):
        r = _run_launch(tmp_path, """
            import sys
            sys.exit(3)
        """, extra_args=("--elastic_level", "1", "--max_restart", "2"),
            nproc=1)
        assert r.returncode == 3
        assert r.stderr.count("restart") == 2

    def test_worker_logs_written(self, tmp_path):
        r = _run_launch(tmp_path, """
            import os
            print("hello from", os.environ["PADDLE_TRAINER_ID"])
        """)
        assert r.returncode == 0
        assert (tmp_path / "log" / "workerlog.1").exists()


class TestSpawn:
    @pytest.mark.slow
    def test_spawn_runs_workers(self, tmp_path):
        # spawn in a subprocess to avoid forking the jax-laden test process
        script = tmp_path / "sp.py"
        script.write_text(textwrap.dedent("""
            import os
            os.environ.setdefault("JAX_PLATFORMS", "cpu")

            def work(base):
                import os
                rank = int(os.environ["PADDLE_TRAINER_ID"])
                with open(f"{base}/spawn.{rank}", "w") as f:
                    f.write(os.environ["PADDLE_CURRENT_ENDPOINT"])

            if __name__ == "__main__":
                import sys
                from paddle_tpu.distributed import spawn
                spawn(work, args=(sys.argv[1],), nprocs=2)
        """))
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        r = _run_group([sys.executable, str(script), str(tmp_path)],
                       env, timeout=120)
        assert r.returncode == 0, r.stderr
        assert (tmp_path / "spawn.0").exists()
        assert (tmp_path / "spawn.1").exists()
