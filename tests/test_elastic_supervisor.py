"""Elastic auto-restart supervisor: in-process + subprocess relaunch with
bounded budget/backoff, generation env export, membership-driven restart,
done-flag semantics, and the tools/elastic_run.py CLI face.
"""
import os
import subprocess
import sys
import time
import warnings

import pytest

from paddle_tpu.distributed.fleet.elastic import (ELASTIC_EXIT_CODE,
                                                  RESTART_NUM_ENV,
                                                  ElasticManager,
                                                  ElasticSupervisor,
                                                  RestartBudgetExceeded,
                                                  run_elastic)
from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.profiler import metrics as metrics_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _restarts(reason=None):
    m = metrics_mod.default_registry().get("elastic_restarts_total")
    if m is None:
        return 0.0
    return sum(v["value"] for v in m.snapshot()["values"]
               if reason is None or v["labels"].get("reason") == reason)


@pytest.fixture(autouse=True)
def _fresh_restart_env(monkeypatch):
    monkeypatch.delenv(RESTART_NUM_ENV, raising=False)


def _quiet(fn, *a, **kw):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return fn(*a, **kw)


class TestInProcessSupervisor:
    def test_restarts_until_success_and_exports_generation(self):
        gens = []

        def train():
            gens.append(os.environ[RESTART_NUM_ENV])
            if len(gens) < 3:
                raise RuntimeError("boom")
            return "done"

        before = _restarts(reason="failure")
        sup = ElasticSupervisor(max_restarts=3, backoff=0.001)
        assert _quiet(sup.run, train) == "done"
        assert gens == ["0", "1", "2"]  # each generation sees its number
        assert sup.restarts == 2
        assert _restarts(reason="failure") >= before + 2

    def test_budget_exhaustion_raises_with_cause(self):
        def train():
            raise RuntimeError("persistent")

        sup = ElasticSupervisor(max_restarts=1, backoff=0.001)
        with pytest.raises(RestartBudgetExceeded) as ei:
            _quiet(sup.run, train)
        assert ei.value.budget == 1
        assert ei.value.last_reason == "failure"
        assert isinstance(ei.value.__cause__, RuntimeError)

    def test_elastic_exit_code_counts_as_restart_requested(self):
        calls = []

        def train():
            calls.append(1)
            if len(calls) == 1:
                raise SystemExit(ELASTIC_EXIT_CODE)
            return 7

        before = _restarts(reason="restart_requested")
        assert _quiet(run_elastic, train, max_restarts=2, backoff=0.001) == 7
        assert _restarts(reason="restart_requested") >= before + 1

    def test_clean_systemexit_is_not_a_restart(self):
        sup = ElasticSupervisor(max_restarts=2, backoff=0.001)
        assert sup.run(lambda: (_ for _ in ()).throw(SystemExit(0))) is None
        assert sup.restarts == 0

    def test_keyboard_interrupt_propagates(self):
        sup = ElasticSupervisor(max_restarts=5, backoff=0.001)
        with pytest.raises(KeyboardInterrupt):
            sup.run(lambda: (_ for _ in ()).throw(KeyboardInterrupt()))

    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_MAX_RESTARTS", "9")
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_BACKOFF", "0.25")
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_BACKOFF_MAX", "2.5")
        sup = ElasticSupervisor()
        assert (sup.max_restarts, sup.backoff, sup.backoff_max) == (9, 0.25, 2.5)


_FLAKY_CHILD = """
import os, sys
marker = sys.argv[1]
with open(sys.argv[2], "a") as f:
    f.write(os.environ["PADDLE_TPU_ELASTIC_RESTART_NUM"] + "\\n")
if not os.path.exists(marker):
    open(marker, "w").write("x")
    sys.exit(int(sys.argv[3]) if len(sys.argv) > 3 else 3)
sys.exit(0)
"""


class TestSubprocessSupervisor:
    def _spawn(self, tmp_path, exit_code=3, max_restarts=2):
        child = tmp_path / "child.py"
        child.write_text(_FLAKY_CHILD)
        gens = tmp_path / "gens.txt"
        sup = ElasticSupervisor(max_restarts=max_restarts, backoff=0.001)
        rc = _quiet(sup.supervise,
                    [sys.executable, str(child), str(tmp_path / "marker"),
                     str(gens), str(exit_code)])
        return sup, rc, gens.read_text().split()

    def test_relaunches_failed_child_with_bumped_generation(self, tmp_path):
        sup, rc, gens = self._spawn(tmp_path)
        assert rc == 0 and sup.restarts == 1
        assert gens == ["0", "1"]

    def test_elastic_exit_code_from_child(self, tmp_path):
        before = _restarts(reason="restart_requested")
        sup, rc, _ = self._spawn(tmp_path, exit_code=ELASTIC_EXIT_CODE)
        assert rc == 0
        assert _restarts(reason="restart_requested") >= before + 1

    def test_budget_returns_last_exit_code(self, tmp_path):
        child = tmp_path / "always_fail.py"
        child.write_text("import sys; sys.exit(5)\n")
        sup = ElasticSupervisor(max_restarts=1, backoff=0.001)
        rc = _quiet(sup.supervise, [sys.executable, str(child)])
        assert rc == 5 and sup.restarts == 2  # 1 allowed + the final denial


class _FakeManager:
    """Scripted membership view: full fleet, then one member goes stale."""

    def __init__(self, stale_after=0.4):
        self.np = 2
        self.ttl = 0.3  # fast membership cadence (checked every ttl/3)
        self._t0 = time.time()
        self._stale_after = stale_after

    def _member_ids(self):
        return ["a", "b"]

    def alive_members(self):
        if time.time() - self._t0 > self._stale_after:
            return ["a"]
        return ["a", "b"]

    def is_done(self, host_id):
        return False

    def mark_done(self, host_id=None):
        pass


class TestMembershipWatch:
    def test_stale_peer_triggers_local_restart(self, tmp_path):
        """A peer whose heartbeat goes stale (and that is not done) makes
        the supervisor SIGTERM the healthy local trainer and relaunch it,
        so the whole fleet re-enters the same generation together."""
        child = tmp_path / "sleepy.py"
        child.write_text("import time\ntime.sleep(60)\n")
        before = _restarts(reason="membership")
        sup = ElasticSupervisor(max_restarts=0, backoff=0.001,
                                manager=_FakeManager(), poll=0.05,
                                stop_grace=5.0)
        t0 = time.time()
        rc = _quiet(sup.supervise, [sys.executable, str(child)])
        assert time.time() - t0 < 30  # did not wait out the child's sleep
        assert rc != 0 and sup.last_reason == "membership"
        # budget 0: the membership restart is denied, but still attempted
        assert _restarts(reason="membership") == before

    def test_own_member_staleness_is_ignored(self, tmp_path):
        """The supervisor watches PEERS by heartbeat; its own trainer it
        watches by process exit. A stale SELF entry — exactly what the
        child's restart gap looks like while the relaunch is still
        importing — must not trigger a membership restart, or the
        supervisor SIGTERMs its own fresh child and the fleet's generation
        numbering desyncs (regression: the 2-host e2e flaked this way)."""
        fake = _FakeManager(stale_after=0.4)  # full fleet, then "b" stale
        child = tmp_path / "quick.py"
        child.write_text("import time\ntime.sleep(2.0)\n")
        sup = ElasticSupervisor(max_restarts=0, manager=fake, poll=0.05,
                                self_member="b")
        # without self_member="b" this exact setup restarts (see
        # test_stale_peer_triggers_local_restart); with it, the child runs
        # to completion
        assert sup.supervise([sys.executable, str(child)]) == 0
        assert sup.restarts == 0

    def test_clean_child_exit_publishes_done_flag(self, tmp_path):
        """supervise() must publish its child's done-flag on clean exit:
        the trainer's beats stop at job end, and without the flag every
        PEER's watch reads the silence as death and SIGTERMs its own
        healthy trainer until its budget exhausts (most trainers never
        call mark_done() themselves)."""
        fake = _FakeManager(stale_after=60)
        done = []
        fake.mark_done = lambda host_id=None: done.append(host_id)
        child = tmp_path / "quick.py"
        child.write_text("pass\n")
        sup = ElasticSupervisor(max_restarts=0, manager=fake, poll=0.05,
                                self_member="b")
        assert sup.supervise([sys.executable, str(child)]) == 0
        assert done == ["b"]

    def test_in_process_clean_completion_publishes_done_flag(self):
        """run() must publish the done-flag too — a mixed fleet (one host
        in-process, peers under --watch supervisors) would otherwise read
        the finished in-process host as dead at job end."""
        fake = _FakeManager(stale_after=60)
        done = []
        fake.mark_done = lambda host_id=None: done.append(host_id)
        sup = ElasticSupervisor(max_restarts=0, manager=fake)
        assert sup.run(lambda: 42) == 42
        # self_member unset: the flag lands on the manager's own id
        assert done == [None]

    def test_done_peer_is_not_a_failure(self, tmp_path):
        """A host whose training completed stops heartbeating too — its
        done-flag must keep peers from restarting healthy trainers."""
        fake = _FakeManager(stale_after=0.0)  # "b" never beats...
        fake.is_done = lambda host_id: host_id == "b"  # ...because it's done
        child = tmp_path / "quick.py"
        child.write_text("import time\ntime.sleep(0.5)\n")
        sup = ElasticSupervisor(max_restarts=0, manager=fake, poll=0.05)
        assert sup.supervise([sys.executable, str(child)]) == 0
        assert sup.restarts == 0


class TestManagerDoneFlags:
    def test_abandon_keeps_member_registered_with_staling_beat(self):
        """A budget-exhausted supervisor must abandon(), not exit(): the
        member stays registered while its beat goes stale, so peers'
        watches DETECT the dead host instead of seeing the member list
        shrink below np (which reads as 'fleet never assembled')."""
        master = TCPStore("127.0.0.1", 0, is_master=True)
        try:
            mgr = ElasticManager(host_id="dead", store=master, np=2,
                                 ttl=0.5)
            mgr.join()
            assert "dead" in mgr._member_ids()
            assert "dead" in mgr.alive_members()
            mgr.abandon()
            time.sleep(0.8)  # beat stales past ttl
            assert "dead" in mgr._member_ids()      # still registered...
            assert "dead" not in mgr.alive_members()  # ...but visibly dead
        finally:
            master.stop()

    def test_mark_done_roundtrip_and_rejoin_clears(self):
        master = TCPStore("127.0.0.1", 0, is_master=True)
        try:
            mgr = ElasticManager(host_id="h0", store=master, np=1)
            assert not mgr.is_done("h0")
            mgr.mark_done()
            assert mgr.is_done("h0")
            # a rejoining generation is not done anymore
            mgr2 = ElasticManager(host_id="h0", store=master, np=1)
            mgr2.join()
            assert not mgr2.is_done("h0")
            mgr2.exit("completed")
        finally:
            master.stop()


class TestElasticRunCLI:
    def _parse(self, argv):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import elastic_run
        finally:
            sys.path.pop(0)
        return elastic_run.parse_args(argv)

    def test_parse_splits_command(self):
        args = self._parse(["--master", "10.0.0.1:7777", "--watch",
                            "--np", "4", "--rank", "2",
                            "--", "python", "train.py"])
        assert args.cmd == ["python", "train.py"]
        assert args.master == "10.0.0.1:7777"
        assert args.watch and args.np == 4 and args.rank == 2

    def test_parse_requires_command(self):
        with pytest.raises(SystemExit):
            self._parse(["--master", "x:1"])

    def test_invalid_master_fails_loudly(self):
        """A garbled --master (empty port) must error out, not propagate
        MASTER_PORT="" to the trainer — that silently disables the
        checkpoint barrier (single-host fallback) while peers wait on it."""
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import elastic_run
        finally:
            sys.path.pop(0)
        for bad in ("127.0.0.1:", ":7777", "nocolon", "h:port"):
            assert elastic_run.main(["--master", bad, "--", "echo"]) == 2

    def test_watch_requires_stable_member_id(self, monkeypatch):
        """--watch with neither --rank nor $PADDLE_CURRENT_ENDPOINT must
        exit 2: the trainer would register as host-<pid>, which changes
        every relaunch — after its first crash the dead id stays in the
        member set forever and every watching supervisor SIGTERMs each
        fresh relaunch until its restart budget exhausts."""
        monkeypatch.delenv("PADDLE_CURRENT_ENDPOINT", raising=False)
        monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import elastic_run
        finally:
            sys.path.pop(0)
        assert elastic_run.main(["--watch", "--np", "2",
                                 "--master", "127.0.0.1:7777",
                                 "--", "echo"]) == 2
        # a stable id from either source is accepted (parse-level check:
        # endpoint export, no supervise run needed)
        args = elastic_run.parse_args(["--watch", "--np", "2", "--rank",
                                       "1", "--master", "127.0.0.1:7777",
                                       "--", "echo"])
        assert args.rank == 1

    def test_multi_host_without_rank_fails_fast(self, monkeypatch):
        """np>1 with no rank must exit 2 up front: coordinator_from_env
        raises in the child, so the supervisor would burn its whole
        restart budget relaunching an unfixable config error."""
        monkeypatch.delenv("PADDLE_CURRENT_ENDPOINT", raising=False)
        monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
        monkeypatch.delenv("PADDLE_TPU_CKPT_BARRIER", raising=False)
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import elastic_run
        finally:
            sys.path.pop(0)
        assert elastic_run.main(["--np", "2", "--master", "127.0.0.1:7777",
                                 "--", "echo"]) == 2
        # explicit barrier opt-out makes rankless multi-host legitimate
        monkeypatch.setenv("PADDLE_TPU_CKPT_BARRIER", "0")
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_MAX_RESTARTS", "0")
        assert elastic_run.main(["--np", "2", "--master", "127.0.0.1:7777",
                                 "--", sys.executable, "-c", "pass"]) == 0

    def test_end_to_end_restart(self, tmp_path):
        """CLI smoke: host the store, relaunch a child that fails once."""
        child = tmp_path / "child.py"
        child.write_text(_FLAKY_CHILD)
        gens = tmp_path / "gens.txt"
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
                   PADDLE_TPU_ELASTIC_BACKOFF="0.001")
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "elastic_run.py"),
             "--host-store", "--master", "127.0.0.1:0", "--",
             sys.executable, str(child), str(tmp_path / "marker"),
             str(gens)],
            env=env, capture_output=True, text=True, timeout=180)
        assert out.returncode == 0, out.stderr[-2000:]
        assert gens.read_text().split() == ["0", "1"]
        assert "hosting rendezvous store" in out.stderr
