"""FleetController (distributed/fleet/controller.py): the
observe->diagnose->act loop — straggler-eviction debounce + hysteresis,
readmission, fleet-wide divergence rollback, dry-run, command-bus
roundtrip, and the ElasticSupervisor side of command application.

These are the fast tier-1 siblings of the slow chaos e2e in
tests/test_fleet_controller_e2e.py.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.distributed.fleet.controller import (ControllerCommandBus,
                                                     FleetController,
                                                     GEN_STRIDE,
                                                     get_controller,
                                                     set_controller)
from paddle_tpu.distributed.fleet.elastic import ElasticSupervisor
from paddle_tpu.distributed.fleet.telemetry import (FleetAggregator,
                                                    FleetReporter)
from paddle_tpu.profiler import events
from paddle_tpu.profiler import metrics as metrics_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeStore:
    """In-memory store with the subset of the TCPStore API the
    controller/bus/aggregator use (set/get/check/add/delete_key)."""

    def __init__(self):
        self.kv = {}
        self.lock = threading.Lock()

    def set(self, key, value):
        with self.lock:
            self.kv[key] = value.encode() if isinstance(value, str) else value

    def get(self, key):
        with self.lock:
            return self.kv[key]

    def check(self, key):
        with self.lock:
            return key in self.kv

    def add(self, key, delta):
        with self.lock:
            cur = int(self.kv.get(key, b"0").decode())
            cur += int(delta)
            self.kv[key] = str(cur).encode()
            return cur

    def delete_key(self, key):
        with self.lock:
            self.kv.pop(key, None)


@pytest.fixture(autouse=True)
def _clean_events(monkeypatch):
    # an earlier module's in-process ElasticSupervisor.run() leaves the
    # generation env behind; it would shift every child's recorded gen
    monkeypatch.delenv("PADDLE_TPU_ELASTIC_RESTART_NUM", raising=False)
    events.default_event_log().clear()
    yield
    events.default_event_log().clear()


def _feed(reporter, walls, start_step=1):
    for i, w in enumerate(walls):
        reporter.note_step(start_step + i, wall_s=w)


def _mk_fleet(store, slow_walls, fast_walls=None, n=6):
    """Two reporters on `store`; returns (fast, slow)."""
    fast = FleetReporter(store, rank=0, window=8, host="trainer-0",
                         min_interval_s=0)
    slow = FleetReporter(store, rank=1, window=8, host="trainer-1",
                         min_interval_s=0)
    _feed(fast, (fast_walls or [0.01]) * n)
    _feed(slow, [slow_walls] * n)
    return fast, slow


def _decisions(kind="controller_decision"):
    return [e for e in events.recent(100, kind=kind)
            if e.get("action") != "relaunch_observed"]


class TestCommandBus:
    def test_publish_poll_roundtrip_in_order(self):
        bus = ControllerCommandBus(FakeStore())
        assert bus.last_id() == 0
        assert bus.poll(0) == []
        i1 = bus.publish({"action": "evict", "host": "h1", "np": 1})
        i2 = bus.publish({"action": "readmit", "host": "h1", "np": 2})
        assert (i1, i2) == (1, 2)
        cmds = bus.poll(0)
        assert [c["action"] for c in cmds] == ["evict", "readmit"]
        assert all("ts" in c for c in cmds)
        assert bus.poll(i1) == [cmds[1]]
        assert bus.poll(i2) == []

    def test_claimed_but_unwritten_id_stops_the_scan(self):
        store = FakeStore()
        bus = ControllerCommandBus(store)
        bus.publish({"action": "evict"})
        store.add("ctl/seq", 1)  # claimed id 2, value never written
        bus.publish({"action": "readmit"})  # id 3
        got = bus.poll(0)
        # order matters: id 3 must NOT be applied before the missing id 2
        assert [c["id"] for c in got] == [1]

    def test_permanent_hole_is_skipped_after_timeout(self):
        """Review regression: a publisher that died between the id claim
        and the value write must not wedge every supervisor's command
        scan forever — after HOLE_TIMEOUT_S the hole is abandoned as a
        synthetic skipped_hole record so cursors advance past it."""
        store = FakeStore()
        bus = ControllerCommandBus(store)
        bus.publish({"action": "evict"})
        store.add("ctl/seq", 1)  # claimed id 2, never written
        bus.publish({"action": "readmit"})  # id 3
        bus.HOLE_TIMEOUT_S = 0.05
        assert [c["id"] for c in bus.poll(0)] == [1]  # hole observed
        time.sleep(0.08)
        with pytest.warns(UserWarning, match="never written"):
            got = bus.poll(1)
        # the hole is surfaced as a consumable skip record, then id 3
        assert [(c["id"], c["action"]) for c in got] == \
            [(2, "skipped_hole"), (3, "readmit")]
        # a supervisor consumes skipped_hole like any unknown action
        sup_seen = [c for c in got
                    if c.get("action") in ("evict", "readmit", "rollback")]
        assert [c["id"] for c in sup_seen] == [3]

    def test_ready_beat_and_job_done(self):
        bus = ControllerCommandBus(FakeStore())
        assert bus.ready_age("h1") is None
        bus.beat_ready("h1")
        age = bus.ready_age("h1")
        assert age is not None and age < 1.0
        assert not bus.job_done()
        bus.mark_job_done()
        assert bus.job_done()
        # reset clears a previous job's flag (long-lived host-store):
        # without it the NEXT job's first evicted host would exit
        # instead of holding for readmission
        bus.reset_job_done()
        assert not bus.job_done()

    def test_controller_from_env_clears_stale_job_done(self):
        from paddle_tpu.distributed.fleet.controller import (
            controller_from_env)
        store = FakeStore()
        ControllerCommandBus(store).mark_job_done()  # previous job's flag
        ctl = controller_from_env(_Agg(), store, world_size=2)
        try:
            assert not ctl.bus.job_done()
        finally:
            set_controller(None)

    def test_presence_marked_by_publish_and_from_env(self):
        """Review regression: supervisors only scan the ledger once a
        controller has marked the presence key — both attach paths must
        arm it (controller_from_env up front, publish as the backstop)."""
        from paddle_tpu.distributed.fleet.controller import (
            controller_from_env)
        store = FakeStore()
        bus = ControllerCommandBus(store)
        assert not bus.present()
        bus.publish({"action": "evict"})
        assert bus.present()
        store2 = FakeStore()
        ctl = controller_from_env(_Agg(), store2, world_size=2)
        try:
            # armed at startup, before any decision publishes
            assert ctl.bus.present()
        finally:
            set_controller(None)


class _Agg:
    """Scripted aggregator: the controller only reads straggling(),
    straggler_factor and .last."""

    def __init__(self):
        self._straggling = []
        self.straggler_factor = 2.0
        self.last = {}

    def straggling(self):
        return list(self._straggling)


def _tick(ctl, agg, straggling=(), digests=None):
    agg._straggling = list(straggling)
    agg.last = digests or {}
    ctl.on_collect(agg.last)


def _digest(host, rank, step=10, ts=None, health="ok", p50=0.01):
    return {"host": host, "rank": rank, "step": step,
            "ts": time.time() if ts is None else ts,
            "health_status": health, "wall_p50_s": p50, "window": 8}


def _base_digests(over=None):
    d = {0: _digest("trainer-0", 0), 1: _digest("trainer-1", 1)}
    d.update(over or {})
    return d


class TestStragglerDebounce:
    def _ctl(self, bus=None, **kw):
        agg = _Agg()
        kw.setdefault("confirm_windows", 3)
        kw.setdefault("readmit_after_s", 9999)
        ctl = FleetController(agg, bus, world_size=2, **kw)
        return ctl, agg

    def test_one_window_does_not_evict(self):
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus)
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert bus.last_id() == 0
        assert _decisions() == []

    def test_streak_needs_fresh_digest_evidence(self):
        """Review regression: the aggregator re-flagging the SAME cached
        digest on every poll tick must not build the eviction streak —
        one slow published sample would otherwise confirm in
        confirm_windows poll ticks, defeating the documented
        N-consecutive-collect-windows debounce."""
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus)
        frozen = _base_digests()
        for _ in range(5):
            _tick(ctl, agg, ["trainer-1"], frozen)
        # one published sample, no matter how many ticks re-read it
        assert bus.last_id() == 0
        assert ctl._streaks.get("trainer-1") == 1
        for _ in range(2):  # fresh digests (new ts) still confirm
            _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert bus.last_id() == 1
        assert bus.poll(0)[0]["action"] == "evict"

    def test_confirmed_after_n_consecutive_windows(self):
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus)
        for _ in range(3):
            _tick(ctl, agg, ["trainer-1"], _base_digests())
        cmds = bus.poll(0)
        assert len(cmds) == 1
        cmd = cmds[0]
        assert cmd["action"] == "evict"
        assert cmd["host"] == "trainer-1"
        assert cmd["np"] == 1
        assert cmd["ranks"] == {"trainer-0": 0}
        recs = _decisions()
        assert len(recs) == 1
        assert recs[0]["policy"] == "straggler_evict"
        assert recs[0]["outcome"] == "applied"
        # confirmed decision does not re-fire while the excursion persists
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert bus.last_id() == 1

    def test_interrupted_streak_rearms_from_zero(self):
        """Hysteresis half 1: an excursion that recovers before the
        confirm window must reset the streak — windows are CONSECUTIVE."""
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus)
        for _ in range(2):
            _tick(ctl, agg, ["trainer-1"], _base_digests())
        _tick(ctl, agg, [], _base_digests())  # recovered
        for _ in range(2):
            _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert bus.last_id() == 0  # 2+2 non-consecutive never confirms
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert bus.last_id() == 1  # the third consecutive one does

    def test_excursion_recover_excursion_yields_two_decisions(self):
        """Satellite regression: a host that excursions, recovers, and
        excursions again produces TWO confirmed decisions, not one —
        recovery re-arms the suppression, dry-run mode so the fleet
        state stays at full strength for the second round."""
        ctl, agg = self._ctl(bus=None, dry_run=True, confirm_windows=2)
        for _ in range(3):
            _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert len(_decisions()) == 1
        _tick(ctl, agg, [], _base_digests())  # recovery re-arms
        for _ in range(2):
            _tick(ctl, agg, ["trainer-1"], _base_digests())
        recs = _decisions()
        assert len(recs) == 2
        assert all(r["policy"] == "straggler_evict" for r in recs)
        assert all(r["outcome"] == "dry_run" for r in recs)

    def test_never_shrinks_below_min_world(self):
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus, min_world=2)
        for _ in range(5):
            _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert bus.last_id() == 0
        assert _decisions() == []

    def test_quorum_floor_caps_simultaneous_evictions(self):
        """Multi-straggler handling is bounded by min_world: once the
        fleet is at the floor, further confirmed stragglers are held
        back (no decision, no publish)."""
        bus = ControllerCommandBus(FakeStore())
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=3, confirm_windows=1,
                              readmit_after_s=9999, min_world=2)
        d = {0: _digest("trainer-0", 0), 1: _digest("trainer-1", 1),
             2: _digest("trainer-2", 2)}
        _tick(ctl, agg, ["trainer-1"], d)
        _tick(ctl, agg, ["trainer-1", "trainer-2"], d)
        cmds = bus.poll(0)
        # trainer-2 confirmed too, but evicting it would breach the floor
        assert [c["host"] for c in cmds] == ["trainer-1"]
        assert ctl.current_world() == 2

    def test_two_simultaneous_stragglers_both_evict(self):
        """Regression for the PR-13 carried follow-up: two hosts slow at
        once each confirm their own debounced streak and BOTH evict in
        ONE batched decision (down to the min_world floor) — a single
        command carrying the full host list and a rank map that excludes
        every held host, instead of two overlapping relaunch specs."""
        bus = ControllerCommandBus(FakeStore())
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=3, confirm_windows=2,
                              readmit_after_s=9999, min_world=1)
        for i in range(2):
            d = {0: _digest("trainer-0", 0, step=10 + i),
                 1: _digest("trainer-1", 1, step=10 + i),
                 2: _digest("trainer-2", 2, step=10 + i)}
            _tick(ctl, agg, ["trainer-1", "trainer-2"], d)
        cmds = bus.poll(0)
        assert [c["action"] for c in cmds] == ["evict"]
        assert set(cmds[0]["hosts"]) == {"trainer-1", "trainer-2"}
        assert cmds[0]["host"] in cmds[0]["hosts"]  # back-compat field
        assert cmds[0]["np"] == 1
        assert cmds[0]["ranks"] == {"trainer-0": 0}
        assert ctl.current_world() == 1
        # both readmit independently once their probation beats are fresh
        ctl.readmit_after_s = 0.0
        bus.beat_ready("trainer-1")
        bus.beat_ready("trainer-2")
        seen = bus.last_id()  # one batched evict == one bus command
        d = {0: _digest("trainer-0", 0, step=20)}
        _tick(ctl, agg, [], d)  # observes beats; readmits one
        _tick(ctl, agg, [], d)  # readmits the other
        back = bus.poll(seen)
        assert [c["action"] for c in back] == ["readmit", "readmit"]
        assert {c["host"] for c in back} == {"trainer-1", "trainer-2"}
        # partial readmission covers N-1; the last one restores full N
        assert sorted(c["np"] for c in back) == [2, 3]
        last = [c for c in back if c["np"] == 3][0]
        assert last["ranks"] == {"trainer-0": 0, "trainer-1": 1,
                                 "trainer-2": 2}
        assert ctl.current_world() == 3

    def test_dry_run_publishes_nothing(self):
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus, dry_run=True, confirm_windows=1)
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert bus.last_id() == 0
        recs = _decisions()
        assert len(recs) == 1 and recs[0]["outcome"] == "dry_run"
        assert recs[0]["dry_run"] is True

    def test_no_evict_until_full_fleet_has_reported(self):
        """A survivor the controller has never seen a digest from would
        be missing from the relaunch rank map and relaunch with an
        out-of-range rank — the controller stays observe-only until the
        full fleet has reported once."""
        bus = ControllerCommandBus(FakeStore())
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=3, confirm_windows=1,
                              readmit_after_s=9999)
        two = {0: _digest("trainer-0", 0), 1: _digest("trainer-1", 1)}
        for _ in range(4):
            _tick(ctl, agg, ["trainer-1"], two)
        assert bus.last_id() == 0  # trainer-2 never reported: no actuation
        # the third host reports: the confirmed straggler is now evictable
        three = dict(two)
        three[2] = _digest("trainer-2", 2)
        _tick(ctl, agg, ["trainer-1"], three)
        cmds = bus.poll(0)
        assert [c["host"] for c in cmds] == ["trainer-1"]
        assert cmds[0]["ranks"] == {"trainer-0": 0, "trainer-2": 1}

    def test_failed_publish_degrades_to_failed_outcome(self):
        class DeadStore(FakeStore):
            def add(self, key, delta):
                raise RuntimeError("store gone")

        ctl, agg = self._ctl(ControllerCommandBus(DeadStore()),
                             confirm_windows=1)
        with pytest.warns(UserWarning, match="could not publish"):
            _tick(ctl, agg, ["trainer-1"], _base_digests())
        recs = _decisions()
        assert len(recs) == 1 and recs[0]["outcome"] == "failed"
        assert recs[0]["severity"] == "error"
        # the fleet is still at full strength: nothing was actuated
        assert ctl.current_world() == 2

    def test_decision_counter_by_policy_and_outcome(self):
        c = metrics_mod.default_registry().get("controller_decisions_total")
        before = c.value(policy="straggler_evict", outcome="applied")
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus, confirm_windows=1)
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert c.value(policy="straggler_evict",
                       outcome="applied") == before + 1

    def test_evict_env_carries_prewarm_and_forced_reporter(self):
        bus = ControllerCommandBus(FakeStore())
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=2, confirm_windows=1,
                              readmit_after_s=9999,
                              prewarm_cache_dir="/tmp/jaxcache")
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        cmd = bus.poll(0)[0]
        assert cmd["env"]["PADDLE_TPU_COMPILE_CACHE_DIR"] == "/tmp/jaxcache"
        assert cmd["env"]["PADDLE_TPU_FLEET_REPORTER"] == "1"


class TestDiagAwareEviction:
    """ROADMAP item-3 follow-up: step_diagnosis feeds the eviction
    evidence — a confirmed straggler whose dominant wall-time term is
    data_wait is slow because of the INPUT PIPELINE, so the controller
    decides action="skip" naming the culprit instead of evicting the
    host (the stall would just move to the relaunched N-1 fleet)."""

    def _ctl(self, bus, **kw):
        agg = _Agg()
        kw.setdefault("confirm_windows", 2)
        kw.setdefault("readmit_after_s", 9999)
        return FleetController(agg, bus, world_size=2, **kw), agg

    @staticmethod
    def _digests(dominant):
        d = _base_digests()
        d[1]["diag_dominant"] = dominant
        return d

    def test_data_wait_dominant_skips_instead_of_evicting(self):
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus)
        for _ in range(4):
            _tick(ctl, agg, ["trainer-1"], self._digests("data_wait"))
        # nothing published, fleet stays at N, but the decision is logged
        assert bus.last_id() == 0
        assert not ctl._evicted
        recs = _decisions()
        assert len(recs) == 1
        rec = recs[0]
        assert rec["policy"] == "straggler_skip"
        assert rec["action"] == "skip"
        assert rec["target"] == "trainer-1"
        assert rec["outcome"] == "applied"
        assert rec["evidence"]["diag_dominant"] == "data_wait"
        assert rec["evidence"]["culprit"] == "input_pipeline"

    def test_skip_suppresses_until_recovery_then_redecides(self):
        """The skip is one decision per excursion (hysteresis like an
        eviction); after recovery a relapse re-decides."""
        ctl, agg = self._ctl(None, dry_run=False)
        digests = self._digests("data_wait")
        for _ in range(5):
            _tick(ctl, agg, ["trainer-1"], self._digests("data_wait"))
        assert len(_decisions()) == 1
        _tick(ctl, agg, [], digests)  # recovery re-arms
        for _ in range(2):
            _tick(ctl, agg, ["trainer-1"], self._digests("data_wait"))
        assert len(_decisions()) == 2

    def test_other_dominant_term_still_evicts(self):
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus)
        for _ in range(2):
            _tick(ctl, agg, ["trainer-1"], self._digests("device_compute"))
        cmds = bus.poll(0)
        assert [c["action"] for c in cmds] == ["evict"]
        assert _decisions()[0]["policy"] == "straggler_evict"
        # the eviction evidence names the diagnosed dominant term
        assert _decisions()[0]["evidence"]["diag_dominant"] == \
            "device_compute"

    def test_skip_fires_even_when_eviction_is_infeasible(self):
        """Review regression: the skip sat BELOW the eviction-only
        feasibility guards, so the input-pipeline diagnosis was silently
        dropped exactly when eviction was impossible (min_world floor /
        a host already held / partial rank map) — the operator never
        learned the real culprit. A skip publishes nothing and needs
        none of those guards."""
        # min_world == world: eviction impossible, skip must still log
        ctl, agg = self._ctl(None, min_world=2)
        for _ in range(2):
            _tick(ctl, agg, ["trainer-1"], self._digests("data_wait"))
        recs = _decisions()
        assert len(recs) == 1 and recs[0]["policy"] == "straggler_skip"
        events.default_event_log().clear()
        # partial assignment (one host never reported): same story
        ctl2, agg2 = self._ctl(None)
        for _ in range(2):
            d = {1: _digest("trainer-1", 1)}  # fresh ts: streak advances
            d[1]["diag_dominant"] = "data_wait"
            _tick(ctl2, agg2, ["trainer-1"], d)
        recs = _decisions()
        assert len(recs) == 1 and recs[0]["policy"] == "straggler_skip"

    def test_skip_decision_never_closes_as_a_relaunch(self):
        """A skip (cmd_id None) actuates nothing: the first-steps
        observer must not report relaunch_to_first_step_s for it."""
        ctl, agg = self._ctl(None)
        for _ in range(3):
            _tick(ctl, agg, ["trainer-1"], self._digests("data_wait"))
        rec = ctl.decisions[-1]
        assert rec["policy"] == "straggler_skip"
        assert rec["relaunch_to_first_step_s"] is None
        assert all(e.get("action") != "relaunch_observed"
                   for e in events.recent(100, kind="controller_decision"))


class TestReadmission:
    def test_readmit_after_fresh_ready_beat_and_cooldown(self):
        bus = ControllerCommandBus(FakeStore())
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=2, confirm_windows=1,
                              readmit_after_s=0.05)
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert bus.poll(0)[0]["action"] == "evict"
        # no ready beat yet: held past the cooldown, still not readmitted
        time.sleep(0.06)
        _tick(ctl, agg, [], {0: _digest("trainer-0", 0)})
        assert bus.last_id() == 1
        bus.beat_ready("trainer-1")
        _tick(ctl, agg, [], {0: _digest("trainer-0", 0)})
        cmds = bus.poll(1)
        assert len(cmds) == 1 and cmds[0]["action"] == "readmit"
        assert cmds[0]["np"] == 2
        assert cmds[0]["ranks"] == {"trainer-0": 0, "trainer-1": 1}
        recs = _decisions()
        assert [r["policy"] for r in recs] == ["straggler_evict",
                                               "straggler_readmit"]
        assert ctl.current_world() == 2

    def test_cooldown_blocks_early_readmission(self):
        bus = ControllerCommandBus(FakeStore())
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=2, confirm_windows=1,
                              readmit_after_s=60)
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        bus.beat_ready("trainer-1")
        _tick(ctl, agg, [], {0: _digest("trainer-0", 0)})
        assert bus.last_id() == 1  # evict only

    def test_host_dead_during_hold_is_not_readmitted(self):
        """Review regression: the beat must be observed on EVERY tick,
        including during the hold window — a supervisor that beat once
        and died mid-probation previously read age=0 at the first
        post-window look and a dead host was readmitted into the rank
        map (trainers wedge in rendezvous on the missing rank)."""
        store = FakeStore()
        bus = ControllerCommandBus(store)
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=2, confirm_windows=1,
                              readmit_after_s=0.08)
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert bus.last_id() == 1
        # one beat during the hold, observed by the next tick, then the
        # held supervisor dies (value never changes again)
        store.set("ctl/ready/trainer-1", "beat-1")
        _tick(ctl, agg, [], {0: _digest("trainer-0", 0)})
        assert "trainer-1" in ctl._ready_obs  # observed DURING the hold
        assert bus.last_id() == 1             # hold window not over
        # age the in-hold observation past the freshness window (stands
        # in for a long hold with no further beats) and pass the hold
        ctl._ready_obs["trainer-1"] = ("beat-1",
                                       time.monotonic() - 3600.0)
        time.sleep(0.09)
        _tick(ctl, agg, [], {0: _digest("trainer-0", 0)})
        assert bus.last_id() == 1  # dead during probation: no readmit

    def test_readmit_freshness_is_clock_skew_immune(self):
        """Review regression: probation freshness must be judged by the
        beat VALUE changing on the controller's own clock — a held host
        whose wall clock lags far behind ours must still readmit, and a
        dead host's frozen beat must not."""
        store = FakeStore()
        bus = ControllerCommandBus(store)
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=2, confirm_windows=1,
                              readmit_after_s=0.01)
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        time.sleep(0.02)
        # a beat stamped by a clock ONE HOUR behind ours: ready_age-style
        # wall-clock comparison would read it as hopelessly stale
        store.set("ctl/ready/trainer-1", repr(time.time() - 3600.0))
        _tick(ctl, agg, [], {0: _digest("trainer-0", 0)})
        assert bus.poll(1)[0]["action"] == "readmit"

    def test_frozen_beat_blocks_readmission(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_CONTROLLER_POLL_SEC", "0.01")
        store = FakeStore()
        bus = ControllerCommandBus(store)
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=2, confirm_windows=1,
                              readmit_after_s=0.01)
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        time.sleep(0.02)
        # one beat, then the held supervisor dies: the value never
        # changes again. First observation reads fresh; once the
        # freshness window (3*poll + 5s, monkeypatched via a tiny poll
        # and a shrunken constant below) passes with no change, the
        # readmit must stop firing.
        store.set("ctl/ready/trainer-1", "beat-1")
        _tick(ctl, agg, [], {0: _digest("trainer-0", 0)})
        assert bus.last_id() == 2  # first observation: readmitted
        # simulate the post-readmit relapse: evict again, beat frozen
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert bus.last_id() == 3
        time.sleep(0.02)
        # age the frozen observation past the window artificially
        ctl._ready_obs["trainer-1"] = ("beat-1",
                                       time.monotonic() - 3600.0)
        _tick(ctl, agg, [], {0: _digest("trainer-0", 0)})
        assert bus.last_id() == 3  # frozen beat: no readmission

    def test_status_never_blocks_behind_slow_probation_read(self):
        """Review regression: _readmit_policy's probation read is a
        store RPC (up to the client timeout) — it must run outside the
        status lock like _act's publish, or every /controller scrape
        stalls behind the store once per tick during an eviction hold."""
        store = FakeStore()
        real_get = store.get

        def slow_get(key):
            if key.startswith("ctl/ready/"):
                time.sleep(0.8)
            return real_get(key)

        store.get = slow_get
        bus = ControllerCommandBus(store)
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=2, confirm_windows=1,
                              readmit_after_s=60)
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        assert bus.last_id() == 1  # trainer-1 held
        bus.beat_ready("trainer-1")  # probation key exists: get() runs
        t = threading.Thread(target=_tick, args=(
            ctl, agg, [], {0: _digest("trainer-0", 0)}))
        t.start()
        time.sleep(0.2)  # the tick is now inside the slow probation read
        t0 = time.monotonic()
        ctl.status()
        took = time.monotonic() - t0
        t.join()
        assert took < 0.4, f"status() serialized behind the RPC ({took:.2f}s)"


class TestRollback:
    def _ctl(self, bus, **kw):
        agg = _Agg()
        kw.setdefault("confirm_windows", 99)
        ctl = FleetController(agg, bus, world_size=2, **kw)
        return ctl, agg

    def test_diverged_host_triggers_fleet_rollback(self):
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus)
        d = _base_digests({1: _digest("trainer-1", 1, health="diverged")})
        _tick(ctl, agg, [], d)
        cmds = bus.poll(0)
        assert len(cmds) == 1
        cmd = cmds[0]
        assert cmd["action"] == "rollback"
        assert cmd["host"] == "trainer-1"
        assert cmd["np"] == 2  # rollback keeps the world size
        # valid-only is ONE-SHOT: next-launch overlay, not persistent env
        assert cmd["env_once"]["PADDLE_TPU_RESUME_VALID_ONLY"] == "1"
        assert "PADDLE_TPU_RESUME_VALID_ONLY" not in cmd["env"]
        recs = _decisions()
        assert recs[0]["policy"] == "health_rollback"
        assert recs[0]["evidence"]["diverged"] == ["trainer-1"]

    def test_persistent_diverged_status_rolls_back_once(self):
        """The diverged host's stale digest keeps saying diverged until
        its relaunch publishes a fresh one — that must not re-fire."""
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus)
        d = _base_digests({1: _digest("trainer-1", 1, health="diverged")})
        for _ in range(4):
            _tick(ctl, agg, [], d)
        assert bus.last_id() == 1

    def test_recovered_then_rediverged_rolls_back_again(self):
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus, rollback_cooldown_s=0.0)
        bad = _base_digests({1: _digest("trainer-1", 1, health="diverged")})
        _tick(ctl, agg, [], bad)
        _tick(ctl, agg, [], _base_digests())  # fresh generation reports ok
        _tick(ctl, agg, [], bad)
        assert bus.last_id() == 2
        assert len(_decisions()) == 2

    def test_warn_status_does_not_roll_back(self):
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus)
        _tick(ctl, agg, [],
              _base_digests({1: _digest("trainer-1", 1, health="warn")}))
        assert bus.last_id() == 0

    def test_stale_diverged_digest_does_not_roll_back(self):
        """Review regression: a dead host's (or, with a long-lived
        host-store, a previous incarnation's) frozen 'diverged' digest
        must not hard-kill a healthy fleet — health votes are
        stale-filtered like the aggregator's straggler votes."""
        bus = ControllerCommandBus(FakeStore())
        ctl, agg = self._ctl(bus)
        agg.stale_sec = 1.0
        stale = _base_digests(
            {1: _digest("trainer-1", 1, health="diverged",
                        ts=time.time() - 5.0)})
        _tick(ctl, agg, [], stale)
        assert bus.last_id() == 0  # frozen verdict: no actuation
        fresh = _base_digests(
            {1: _digest("trainer-1", 1, health="diverged")})
        _tick(ctl, agg, [], fresh)
        assert bus.last_id() == 1  # a live diverged digest still fires

    def test_no_rollback_until_full_fleet_has_reported(self):
        """Review regression: like eviction, a rollback's re-densified
        rank map needs the FULL assignment — a partial map hands two
        hosts the same rank and wedges every relaunch in rendezvous."""
        bus = ControllerCommandBus(FakeStore())
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=3, confirm_windows=99,
                              readmit_after_s=9999)
        partial = {2: _digest("trainer-2", 2, health="diverged")}
        for _ in range(3):
            _tick(ctl, agg, [], partial)
        assert bus.last_id() == 0  # two hosts never reported: observe-only
        full = {0: _digest("trainer-0", 0), 1: _digest("trainer-1", 1),
                2: _digest("trainer-2", 2, health="diverged")}
        _tick(ctl, agg, [], full)
        cmds = bus.poll(0)
        assert [c["action"] for c in cmds] == ["rollback"]
        assert cmds[0]["ranks"] == {"trainer-0": 0, "trainer-1": 1,
                                    "trainer-2": 2}

    def test_rollback_during_eviction_excludes_held_host(self):
        """Review regression: a rollback while a host is evicted covers
        the N-1 fleet — the held host must be OUT of the rank map or a
        survivor lands on rank >= np and wedges every relaunch."""
        bus = ControllerCommandBus(FakeStore())
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=3, confirm_windows=1,
                              readmit_after_s=9999)
        d = {0: _digest("trainer-0", 0), 1: _digest("trainer-1", 1),
             2: _digest("trainer-2", 2)}
        _tick(ctl, agg, ["trainer-1"], d)  # evict trainer-1
        assert ctl.current_world() == 2
        d2 = {0: _digest("trainer-0", 0, health="diverged"),
              1: _digest("trainer-1", 1), 2: _digest("trainer-2", 2)}
        _tick(ctl, agg, [], d2)
        cmds = bus.poll(0)
        assert [c["action"] for c in cmds] == ["evict", "rollback"]
        rb = cmds[1]
        assert rb["np"] == 2
        assert rb["ranks"] == {"trainer-0": 0, "trainer-2": 1}

    def test_failed_publish_is_retried_next_tick(self):
        """Review regression: a store blip at publish time must not
        permanently suppress the decision — the diverged host stays
        pinned and the rollback is retried once the store recovers."""
        class FlakyStore(FakeStore):
            fail = 1

            def add(self, key, delta):
                if self.fail:
                    self.fail -= 1
                    raise RuntimeError("store blip")
                return super().add(key, delta)

        bus = ControllerCommandBus(FlakyStore())
        ctl, agg = self._ctl(bus, rollback_cooldown_s=0.0)
        d = _base_digests({1: _digest("trainer-1", 1, health="diverged")})
        with pytest.warns(UserWarning, match="could not publish"):
            _tick(ctl, agg, [], d)
        assert [r for r in ctl.decisions if r["outcome"] == "failed"]
        assert bus.last_id() == 0
        _tick(ctl, agg, [], d)  # store recovered: the retry actuates
        cmds = bus.poll(0)
        assert [c["action"] for c in cmds] == ["rollback"]
        applied = [r for r in ctl.decisions if r["outcome"] == "applied"]
        assert len(applied) == 1


class TestRelaunchObservation:
    def test_first_fresh_digest_closes_the_decision(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_CONTROLLER_POLL_SEC", "0.01")
        bus = ControllerCommandBus(FakeStore())
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=2, confirm_windows=1,
                              readmit_after_s=9999)
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        rec = ctl.decisions[-1]
        assert rec["relaunch_to_first_step_s"] is None
        # stale digests (pre-decision ts) must not close it
        _tick(ctl, agg, [], {0: _digest("trainer-0", 0,
                                        ts=rec["ts"] - 1.0)})
        assert ctl.decisions[-1]["relaunch_to_first_step_s"] is None
        time.sleep(0.05)
        _tick(ctl, agg, [], {0: _digest("trainer-0", 0)})
        dt = ctl.decisions[-1]["relaunch_to_first_step_s"]
        assert dt is not None and 0 <= dt < 5
        obs = [e for e in events.recent(50, kind="controller_decision")
               if e.get("action") == "relaunch_observed"]
        assert len(obs) == 1
        assert obs[0]["relaunch_to_first_step_s"] == dt
        g = metrics_mod.default_registry().get(
            "controller_relaunch_to_first_step_seconds")
        assert g.value(policy="straggler_evict") == dt

    def test_generation_tells_pre_from_post_relaunch(self, monkeypatch):
        """A PRE-relaunch digest published during command-poll +
        SIGTERM-drain latency (fresh ts, old generation) must not close
        the decision; a digest from the command's generation closes it
        immediately."""
        monkeypatch.setenv("PADDLE_TPU_CONTROLLER_POLL_SEC", "60")
        bus = ControllerCommandBus(FakeStore())
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=2, confirm_windows=1,
                              readmit_after_s=9999)
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        rec = ctl.decisions[-1]
        # fresh timestamp but generation 0: the straggler's last gasp
        d = _digest("trainer-0", 0)
        d["gen"] = 0
        _tick(ctl, agg, [], {0: d})
        assert ctl.decisions[-1]["relaunch_to_first_step_s"] is None
        # the relaunched generation reports: closes despite the 60s
        # ts floor that the fallback path would still be waiting on
        d2 = _digest("trainer-0", 0)
        d2["gen"] = rec["cmd_id"] * GEN_STRIDE
        _tick(ctl, agg, [], {0: d2})
        assert ctl.decisions[-1]["relaunch_to_first_step_s"] is not None


class TestStatusEndpointPlumbing:
    def test_status_shape_and_registration(self):
        bus = ControllerCommandBus(FakeStore())
        agg = _Agg()
        ctl = FleetController(agg, bus, world_size=2, confirm_windows=1,
                              readmit_after_s=9999, dry_run=True)
        _tick(ctl, agg, ["trainer-1"], _base_digests())
        st = ctl.status()
        json.dumps(st)  # must be strictly serializable
        assert st["dry_run"] is True
        assert st["world_size"] == 2
        assert st["assignment"] == {"trainer-0": 0, "trainer-1": 1}
        assert len(st["decisions"]) == 1
        set_controller(ctl)
        try:
            assert get_controller() is ctl
        finally:
            set_controller(None)
        assert get_controller() is None

    def test_tick_never_raises(self):
        class BadAgg:
            straggler_factor = 2.0
            last = {}

            def straggling(self):
                raise RuntimeError("boom")

        ctl = FleetController(BadAgg(), None, world_size=2)
        with pytest.warns(UserWarning, match="controller tick failed"):
            ctl.on_collect({})  # must not raise


class TestAggregatorPolling:
    def test_polling_off_by_default_without_hook(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_FLEET_POLL_SEC", raising=False)
        agg = FleetAggregator(FakeStore(), 2)
        assert agg.start_polling() is False
        assert agg._poll_thread is None

    def test_polling_defaults_on_with_hook(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_FLEET_POLL_SEC", raising=False)
        monkeypatch.setenv("PADDLE_TPU_CONTROLLER_POLL_SEC", "0.01")
        store = FakeStore()
        _mk_fleet(store, 0.01)
        agg = FleetAggregator(store, 2)
        seen = []
        assert agg.start_polling(hook=seen.append) is True
        try:
            deadline = time.time() + 5
            while not seen and time.time() < deadline:
                time.sleep(0.01)
            assert seen and sorted(seen[0]) == [0, 1]
        finally:
            agg.stop_polling()
        assert agg._poll_thread is None

    def test_env_knob_enables_polling_without_hook(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FLEET_POLL_SEC", "0.01")
        store = FakeStore()
        _mk_fleet(store, 0.2)  # trainer-1 is a straggler
        agg = FleetAggregator(store, 2, straggler_factor=2.0)
        assert agg.start_polling() is True
        try:
            deadline = time.time() + 5
            while not agg.straggling() and time.time() < deadline:
                time.sleep(0.01)
            # detection ran with NO scrape and NO hook
            assert agg.straggling() == ["trainer-1"]
        finally:
            agg.stop_polling()

    def test_hook_exception_does_not_kill_the_loop(self, monkeypatch):
        store = FakeStore()
        _mk_fleet(store, 0.01)
        agg = FleetAggregator(store, 2)
        calls = []

        def bad_hook(digests):
            calls.append(1)
            raise RuntimeError("consumer bug")

        import warnings as _w
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            assert agg.start_polling(interval=0.01, hook=bad_hook)
            try:
                deadline = time.time() + 5
                while len(calls) < 2 and time.time() < deadline:
                    time.sleep(0.01)
            finally:
                agg.stop_polling()
        assert len(calls) >= 2  # survived its own hook failing

    def test_late_hook_rearms_a_running_loop(self, monkeypatch):
        """Review regression: elastic_run starts a hookless poll loop via
        the metrics server BEFORE attaching the controller; the second
        start_polling(hook=) must re-arm the loop with the hook instead
        of returning True and silently discarding it (which would leave
        the whole controller inert)."""
        monkeypatch.setenv("PADDLE_TPU_FLEET_POLL_SEC", "0.01")
        store = FakeStore()
        _mk_fleet(store, 0.01)
        agg = FleetAggregator(store, 2)
        seen = []
        hook = seen.append
        try:
            assert agg.start_polling() is True          # hookless first
            assert agg.start_polling(hook=hook) is True
            deadline = time.time() + 5
            while not seen and time.time() < deadline:
                time.sleep(0.01)
            assert seen, "late hook never received a collect tick"
            # the SAME hook again: already armed, no restart churn
            assert agg.start_polling(hook=hook) is True
            assert agg._poll_hook is hook
        finally:
            agg.stop_polling()

    def test_stale_digests_leave_the_straggler_vote(self):
        store = FakeStore()
        fast, slow = _mk_fleet(store, 0.5)
        agg = FleetAggregator(store, 2, straggler_factor=2.0,
                              stale_sec=0.2)
        agg.collect()
        assert agg.straggling() == ["trainer-1"]
        time.sleep(0.3)
        # trainer-0 keeps publishing; trainer-1's digest goes stale
        _feed(fast, [0.01] * 3, start_step=50)
        agg.collect()
        recs = events.recent(50, kind="fleet_straggler")
        assert len(recs) == 1  # no duplicate event from stale data
        # review regression: the stale host LEAVES the straggler set —
        # its frozen verdict is no longer evidence, and the controller's
        # eviction debounce counts set membership as consecutive
        # straggling windows (a reporter hiccup must not build a streak)
        assert agg.straggling() == []


class TestForcedReporter:
    def test_force_knob_builds_reporter_at_world_one(self, monkeypatch):
        from paddle_tpu.distributed.fleet import telemetry
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
        monkeypatch.setenv("MASTER_ADDR", "127.0.0.1")
        monkeypatch.setenv("PADDLE_TPU_FLEET_REPORTER", "1")
        store = FakeStore()
        monkeypatch.setattr(telemetry, "_store_from_env", lambda: store)
        rep = telemetry.reporter_from_env()
        assert rep is not None and rep.rank == 0

    def test_force_off_disables_at_any_world(self, monkeypatch):
        from paddle_tpu.distributed.fleet import telemetry
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
        monkeypatch.setenv("PADDLE_TPU_FLEET_REPORTER", "0")
        monkeypatch.setattr(telemetry, "_store_from_env",
                            lambda: FakeStore())
        assert telemetry.reporter_from_env() is None

    def test_default_unchanged_world_one_is_none(self, monkeypatch):
        from paddle_tpu.distributed.fleet import telemetry
        monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
        monkeypatch.delenv("PADDLE_TPU_FLEET_REPORTER", raising=False)
        monkeypatch.setattr(telemetry, "_store_from_env",
                            lambda: FakeStore())
        assert telemetry.reporter_from_env() is None


# ---------------------------------------------------------------------------
# supervisor-side command application
# ---------------------------------------------------------------------------

_SLEEPY = "import time\ntime.sleep(60)\n"
_RECORD = """
import json, os, sys
with open(sys.argv[1], "a") as f:
    f.write(json.dumps({
        "np": os.environ.get("PADDLE_TRAINERS_NUM"),
        "rank": os.environ.get("PADDLE_TRAINER_ID"),
        "gen": os.environ.get("PADDLE_TPU_ELASTIC_RESTART_NUM"),
        "valid_only": os.environ.get("PADDLE_TPU_RESUME_VALID_ONLY"),
    }) + "\\n")
import time
time.sleep({sleep})
"""


def _quiet(fn, *a, **kw):
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("ignore")
        return fn(*a, **kw)


class TestSupervisorCommandApplication:
    def _sup(self, bus, member, **kw):
        kw.setdefault("max_restarts", 0)
        kw.setdefault("cmd_poll", 0.05)
        kw.setdefault("stop_grace", 5.0)
        return ElasticSupervisor(manager=None, self_member=member,
                                 commands=bus, poll=0.05, **kw)

    def test_peer_evict_relaunches_with_new_contract(self, tmp_path):
        """A survivor's supervisor applying `evict(trainer-1)` relaunches
        its child at np=1 rank 0 with the command's env overlay and the
        GEN_STRIDE generation floor — without consuming restart budget."""
        bus = ControllerCommandBus(FakeStore())
        child = tmp_path / "child.py"
        child.write_text(_RECORD.replace("{sleep}", "1.2"))
        out = tmp_path / "out.jsonl"
        sup = self._sup(bus, "trainer-0")
        changes = []
        sup.on_fleet_change = lambda cmd, held: changes.append(
            (cmd["action"], held))
        t = threading.Thread(target=_quiet, args=(
            sup.supervise, [sys.executable, str(child), str(out)]), kwargs={
            "env": {"PADDLE_TRAINERS_NUM": "2", "PADDLE_TRAINER_ID": "0"}})
        t.start()
        time.sleep(0.3)  # first generation is up
        cid = bus.publish({"action": "evict", "host": "trainer-1", "np": 1,
                           "ranks": {"trainer-0": 0},
                           "env": {"PADDLE_TPU_FLEET_REPORTER": "1"}})
        t.join(timeout=30)
        assert not t.is_alive()
        recs = [json.loads(l) for l in out.read_text().splitlines()]
        assert len(recs) == 2
        assert recs[0]["np"] == "2" and recs[0]["rank"] == "0"
        assert recs[1]["np"] == "1" and recs[1]["rank"] == "0"
        assert int(recs[1]["gen"]) == cid * GEN_STRIDE
        assert sup.restarts == 0  # controller actions are not failures
        assert changes == [("evict", False)]

    def test_self_evict_holds_then_readmits(self, tmp_path):
        bus = ControllerCommandBus(FakeStore())
        child = tmp_path / "child.py"
        child.write_text(_RECORD.replace("{sleep}", "1.0"))
        out = tmp_path / "out.jsonl"
        sup = self._sup(bus, "trainer-1")
        rc = {}
        t = threading.Thread(target=lambda: rc.setdefault("v", _quiet(
            sup.supervise, [sys.executable, str(child), str(out)],
            env={"PADDLE_TRAINERS_NUM": "2", "PADDLE_TRAINER_ID": "1"})))
        t.start()
        time.sleep(0.3)
        bus.publish({"action": "evict", "host": "trainer-1", "np": 1,
                     "ranks": {"trainer-0": 0}})
        # held: probation beats appear, no relaunch yet
        deadline = time.time() + 10
        while bus.ready_age("trainer-1") is None \
                and time.time() < deadline:
            time.sleep(0.02)
        assert bus.ready_age("trainer-1") is not None
        assert len(out.read_text().splitlines()) == 1
        rid = bus.publish({"action": "readmit", "host": "trainer-1",
                           "np": 2,
                           "ranks": {"trainer-0": 0, "trainer-1": 1}})
        t.join(timeout=30)
        assert not t.is_alive() and rc["v"] == 0
        recs = [json.loads(l) for l in out.read_text().splitlines()]
        assert len(recs) == 2  # held generation never launched
        assert recs[1]["np"] == "2" and recs[1]["rank"] == "1"
        assert int(recs[1]["gen"]) == rid * GEN_STRIDE

    def test_held_supervisor_exits_cleanly_on_job_done(self, tmp_path):
        bus = ControllerCommandBus(FakeStore())
        child = tmp_path / "child.py"
        child.write_text(_SLEEPY)
        sup = self._sup(bus, "trainer-1")
        rc = {}
        t = threading.Thread(target=lambda: rc.setdefault("v", _quiet(
            sup.supervise, [sys.executable, str(child)])))
        t.start()
        time.sleep(0.3)
        bus.publish({"action": "evict", "host": "trainer-1", "np": 1,
                     "ranks": {"trainer-0": 0}})
        deadline = time.time() + 10
        while bus.ready_age("trainer-1") is None \
                and time.time() < deadline:
            time.sleep(0.02)
        bus.mark_job_done()
        t.join(timeout=15)
        assert not t.is_alive() and rc["v"] == 0

    def test_rollback_kills_hard_and_sets_valid_only(self, tmp_path):
        """Rollback must NOT SIGTERM (the preemption handler would
        checkpoint the diverged state): the child dies by SIGKILL and
        the relaunch carries PADDLE_TPU_RESUME_VALID_ONLY=1 — for that
        ONE launch only (env_once): a failure AFTER the startup retry
        window (the child got past its resume) must not inherit the
        rollback's resume mode."""
        bus = ControllerCommandBus(FakeStore())
        child = tmp_path / "child.py"
        # a SIGTERM-trapping child: only SIGKILL gets it down fast.
        # Launch 1 sleeps (awaiting the rollback kill); launch 2 runs
        # PAST the (shrunken) startup window then exits 3 to force an
        # ordinary failure restart; launch 3 exits clean.
        child.write_text(
            "import json, os, signal, sys, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: None)\n"
            "out = sys.argv[1]\n"
            "n = len(open(out).read().splitlines()) "
            "if os.path.exists(out) else 0\n"
            "with open(out, 'a') as f:\n"
            "    f.write(json.dumps({'valid_only': "
            "os.environ.get('PADDLE_TPU_RESUME_VALID_ONLY')}) + '\\n')\n"
            "if n == 0:\n"
            "    time.sleep(30.0)\n"
            "if n == 1:\n"
            "    time.sleep(0.4)\n"
            "    sys.exit(3)\n"
            "sys.exit(0)\n")
        out = tmp_path / "out.jsonl"
        sup = self._sup(bus, "trainer-0", stop_grace=30.0, max_restarts=1,
                        backoff=0.01)
        sup.ENV_ONCE_RETRY_S = 0.2  # launch 2's 0.4s run is "past resume"
        t = threading.Thread(target=_quiet, args=(
            sup.supervise, [sys.executable, str(child), str(out)]))
        t.start()
        time.sleep(0.3)
        t0 = time.time()
        bus.publish({"action": "rollback", "host": "trainer-1", "np": 2,
                     "ranks": {"trainer-0": 0, "trainer-1": 1},
                     "env_once": {"PADDLE_TPU_RESUME_VALID_ONLY": "1"}})
        t.join(timeout=20)
        assert not t.is_alive()
        # SIGKILL path: far faster than the 30s stop_grace a trapped
        # SIGTERM would have burned
        assert time.time() - t0 < 15
        recs = [json.loads(l) for l in out.read_text().splitlines()]
        assert len(recs) == 3
        assert recs[0]["valid_only"] is None
        assert recs[1]["valid_only"] == "1"   # the rollback relaunch
        assert recs[2]["valid_only"] is None  # one-shot: did not leak

    def test_env_once_rearms_when_resume_itself_fails(self, tmp_path):
        """Review regression: a rollback relaunch whose valid-only
        resume RAISES (nonfinite fleet-agreed step -> renegotiation)
        exits within the startup window — the retry must run valid-only
        again, or it silently restores exactly the diverged state the
        rollback existed to skip."""
        bus = ControllerCommandBus(FakeStore())
        child = tmp_path / "child.py"
        # launch 1 awaits the rollback kill; launch 2 (valid-only) dies
        # INSTANTLY like a resume failure; launch 3 must still be
        # valid-only and exits clean
        child.write_text(
            "import json, os, signal, sys, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: None)\n"
            "out = sys.argv[1]\n"
            "n = len(open(out).read().splitlines()) "
            "if os.path.exists(out) else 0\n"
            "with open(out, 'a') as f:\n"
            "    f.write(json.dumps({'valid_only': "
            "os.environ.get('PADDLE_TPU_RESUME_VALID_ONLY')}) + '\\n')\n"
            "if n == 0:\n"
            "    time.sleep(30.0)\n"
            "sys.exit(3 if n == 1 else 0)\n")
        out = tmp_path / "out.jsonl"
        sup = self._sup(bus, "trainer-0", stop_grace=30.0, max_restarts=1,
                        backoff=0.01)
        t = threading.Thread(target=_quiet, args=(
            sup.supervise, [sys.executable, str(child), str(out)]))
        t.start()
        time.sleep(0.3)
        bus.publish({"action": "rollback", "host": "trainer-1", "np": 2,
                     "ranks": {"trainer-0": 0, "trainer-1": 1},
                     "env_once": {"PADDLE_TPU_RESUME_VALID_ONLY": "1"}})
        t.join(timeout=20)
        assert not t.is_alive()
        recs = [json.loads(l) for l in out.read_text().splitlines()]
        assert [r["valid_only"] for r in recs] == [None, "1", "1"]

    def test_commands_without_self_member_are_dropped(self):
        with pytest.warns(UserWarning, match="needs self_member"):
            sup = ElasticSupervisor(commands=ControllerCommandBus(
                FakeStore()))
        assert sup.commands is None

    def test_commands_published_before_start_are_ignored(self, tmp_path):
        """Ledger entries from a previous incarnation of the job must not
        actuate on a freshly started supervisor."""
        bus = ControllerCommandBus(FakeStore())
        bus.publish({"action": "evict", "host": "trainer-0", "np": 1,
                     "ranks": {}})
        child = tmp_path / "child.py"
        child.write_text("pass\n")
        sup = self._sup(bus, "trainer-0")
        assert _quiet(sup.supervise, [sys.executable, str(child)]) == 0
        assert sup.restarts == 0 and sup.generation == 0

    def test_cursor_anchor_blip_does_not_replay_old_ledger(self, tmp_path):
        """Review regression: a store blip during cursor initialization
        must leave the cursor UNANCHORED (retried on the next poll) — a
        0 fallback would replay the previous incarnation's ledger, e.g.
        a stale rollback hard-killing a healthy fresh trainer."""
        bus = ControllerCommandBus(FakeStore())
        bus.publish({"action": "rollback", "host": "trainer-1", "np": 2,
                     "ranks": {"trainer-0": 0, "trainer-1": 1}})
        fail = {"n": 1}
        real_last_id = bus.last_id

        def flaky_last_id():
            if fail["n"]:
                fail["n"] -= 1
                raise RuntimeError("store blip")
            return real_last_id()

        bus.last_id = flaky_last_id
        child = tmp_path / "child.py"
        child.write_text("import time\ntime.sleep(0.5)\n")
        sup = self._sup(bus, "trainer-0")
        assert _quiet(sup.supervise, [sys.executable, str(child)]) == 0
        # the blip consumed the startup anchor; the poll-tick retry
        # re-anchored at the head — the stale rollback never applied
        assert sup.generation == 0 and sup.last_reason is None
        assert sup._cmd_cursor == 1

    def test_controller_relaunch_credits_healthy_budget(self, tmp_path):
        """Review regression: a long-healthy child stopped by a
        controller command earns the budget reset like any other stop —
        without the credit, the post-reshape relaunch (the likeliest
        moment for a rendezvous hiccup) sits one short-lived failure
        away from a permanent wedge on a stale exhausted counter."""
        bus = ControllerCommandBus(FakeStore())
        child = tmp_path / "child.py"
        child.write_text(_RECORD.replace("{sleep}", "3.0"))
        out = tmp_path / "out.jsonl"
        sup = self._sup(bus, "trainer-0", max_restarts=3,
                        budget_reset_s=0.3)
        sup.restarts = 3  # an earlier flap exhausted the budget
        t = threading.Thread(target=_quiet, args=(
            sup.supervise, [sys.executable, str(child), str(out)]))
        t.start()
        time.sleep(0.8)  # the child has been healthy > budget_reset_s
        bus.publish({"action": "evict", "host": "trainer-1", "np": 1,
                     "ranks": {"trainer-0": 0}})
        deadline = time.time() + 10
        while sup.restarts != 0 and time.time() < deadline:
            time.sleep(0.02)
        assert sup.restarts == 0  # the healthy window was credited
        t.join(timeout=30)
        assert not t.is_alive()

    def test_no_ledger_scan_until_controller_present(self, tmp_path):
        """Review regression: a job with no controller anywhere must not
        pay a per-supervisor ledger scan every cmd_poll against the
        shared rendezvous store — supervisors probe the ONE presence key
        at a relaxed cadence until a controller marks it."""
        store = FakeStore()
        calls = {"seq": 0, "present": 0}
        real_add, real_check = store.add, store.check

        def counting_add(key, delta):
            if key == "ctl/seq":
                calls["seq"] += 1
            return real_add(key, delta)

        def counting_check(key):
            if key == "ctl/present":
                calls["present"] += 1
            return real_check(key)

        store.add = counting_add
        store.check = counting_check
        bus = ControllerCommandBus(store)
        child = tmp_path / "child.py"
        child.write_text("import time\ntime.sleep(1.0)\n")
        sup = self._sup(bus, "trainer-0")
        assert _quiet(sup.supervise, [sys.executable, str(child)]) == 0
        # one ledger RPC total (the startup cursor anchor); every poll
        # tick in between probed only the presence key, and sparsely
        assert calls["seq"] == 1
        assert calls["present"] >= 1

    def test_generation_floor_is_net_of_restart_num_base(self, tmp_path,
                                                         monkeypatch):
        """Review regression: a supervisor relaunched with a pre-existing
        RESTART_NUM base must land controller relaunches on the same
        K*GEN_STRIDE namespace as its base-0 peers — exporting
        base + K*GEN_STRIDE would split the checkpoint-barrier namespace
        and every later coordinated save would time out fleet-wide."""
        monkeypatch.setenv("PADDLE_TPU_ELASTIC_RESTART_NUM", "5")
        bus = ControllerCommandBus(FakeStore())
        child = tmp_path / "child.py"
        child.write_text(_RECORD.replace("{sleep}", "1.2"))
        out = tmp_path / "out.jsonl"
        sup = self._sup(bus, "trainer-0")
        t = threading.Thread(target=_quiet, args=(
            sup.supervise, [sys.executable, str(child), str(out)]))
        t.start()
        time.sleep(0.3)
        cid = bus.publish({"action": "evict", "host": "trainer-1", "np": 1,
                           "ranks": {"trainer-0": 0}})
        t.join(timeout=30)
        assert not t.is_alive()
        recs = [json.loads(l) for l in out.read_text().splitlines()]
        assert len(recs) == 2
        assert int(recs[0]["gen"]) == 5  # base honored pre-command
        # the floor is net of the base: K*GEN_STRIDE, not 5 + K*GEN_STRIDE
        assert int(recs[1]["gen"]) == cid * GEN_STRIDE

    def test_hold_expires_when_controller_dies(self, tmp_path, monkeypatch):
        """Review regression: readmit and job_done are both published by
        the controller host — if it dies hard, the held supervisor must
        escape probation after PADDLE_TPU_CONTROLLER_HOLD_MAX_SEC instead
        of beating ctl/ready forever."""
        monkeypatch.setenv("PADDLE_TPU_CONTROLLER_HOLD_MAX_SEC", "0.6")
        bus = ControllerCommandBus(FakeStore())
        child = tmp_path / "child.py"
        child.write_text(_RECORD.replace("{sleep}", "1.0"))
        out = tmp_path / "out.jsonl"
        sup = self._sup(bus, "trainer-1")
        rc = {}
        t = threading.Thread(target=lambda: rc.setdefault("v", _quiet(
            sup.supervise, [sys.executable, str(child), str(out)])))
        t.start()
        time.sleep(0.3)
        bus.publish({"action": "evict", "host": "trainer-1", "np": 1,
                     "ranks": {"trainer-0": 0}})
        # no readmit and no job_done ever arrive (controller died)
        t.join(timeout=15)
        assert not t.is_alive() and rc["v"] == 0
        assert len(out.read_text().splitlines()) == 1  # held gen never ran


class TestBudgetReset:
    def test_sustained_healthy_window_resets_budget(self, tmp_path):
        """Satellite: fail, run healthy past the reset window, fail again
        — the second failure must find a FRESH budget instead of a stale
        exhausted counter. Generations keep climbing monotonically."""
        marker = tmp_path / "marker"
        child = tmp_path / "child.py"
        child.write_text(
            "import os, sys, time\n"
            "m = sys.argv[1]\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').write('x')\n"
            "    sys.exit(3)\n"          # first run: instant failure
            "if os.path.exists(m + '2'):\n"
            "    sys.exit(0)\n"          # third run: success
            "open(m + '2', 'w').write('x')\n"
            "time.sleep(0.5)\n"          # second run: healthy window
            "sys.exit(3)\n")
        sup = ElasticSupervisor(max_restarts=1, backoff=0.001,
                                budget_reset_s=0.3)
        rc = _quiet(sup.supervise, [sys.executable, str(child), str(marker)])
        assert rc == 0
        # restarts were reset after the healthy run: the final counter
        # only holds the post-reset failure
        assert sup.restarts == 1
        assert sup.generation == 2
        resets = events.recent(50, kind="elastic_budget_reset")
        assert len(resets) == 1
        assert resets[0]["restarts_forgiven"] == 1

    def test_zero_disables_reset(self, tmp_path):
        child = tmp_path / "child.py"
        child.write_text("import time\ntime.sleep(0.3)\nimport sys\n"
                         "sys.exit(3)\n")
        sup = ElasticSupervisor(max_restarts=1, backoff=0.001,
                                budget_reset_s=0)
        rc = _quiet(sup.supervise, [sys.executable, str(child)])
        assert rc == 3  # budget exhausted, never reset
        assert events.recent(50, kind="elastic_budget_reset") == []

    def test_in_process_run_resets_too(self):
        calls = {"n": 0}

        def train():
            calls["n"] += 1
            if calls["n"] < 3:
                time.sleep(0.25)
                raise RuntimeError("flap")
            return "done"

        sup = ElasticSupervisor(max_restarts=1, backoff=0.001,
                                budget_reset_s=0.2)
        assert _quiet(sup.run, train) == "done"
        assert len(events.recent(50, kind="elastic_budget_reset")) >= 1

    def test_quick_failures_still_exhaust(self, tmp_path):
        child = tmp_path / "child.py"
        child.write_text("import sys; sys.exit(5)\n")
        sup = ElasticSupervisor(max_restarts=1, backoff=0.001,
                                budget_reset_s=300)
        assert _quiet(sup.supervise, [sys.executable, str(child)]) == 5


class TestValidOnlyResume:
    def _save(self, mgr, step, poison=False):
        import jax.numpy as jnp
        w = np.full((4,), float(step), np.float32)
        if poison:
            w[1] = np.nan
        mgr.save({"network": {"w": jnp.asarray(w)}, "step": step}, step)

    def test_file_layout_skips_nonfinite_blob(self, tmp_path, monkeypatch):
        from paddle_tpu.distributed.checkpoint import CheckpointManager
        mgr = CheckpointManager(str(tmp_path), keep_last_n=10)
        self._save(mgr, 1)
        self._save(mgr, 2, poison=True)
        # default resume: the newest (poisoned) CRC-valid step wins
        state, step = mgr.load_latest()
        assert step == 2
        monkeypatch.setenv("PADDLE_TPU_RESUME_VALID_ONLY", "1")
        with pytest.warns(UserWarning, match="numerically-invalid"):
            state, step = mgr.load_latest()
        assert step == 1
        assert np.all(np.isfinite(np.asarray(state["network"]["w"])))

    def test_sharded_layout_skips_nonfinite_step(self, tmp_path,
                                                 monkeypatch):
        from paddle_tpu.distributed.sharded_checkpoint import (
            ShardedCheckpointManager)
        mgr = ShardedCheckpointManager(str(tmp_path), keep_last_n=10)
        self._save(mgr, 1)
        self._save(mgr, 2, poison=True)
        _, step = mgr.load_latest()
        assert step == 2
        monkeypatch.setenv("PADDLE_TPU_RESUME_VALID_ONLY", "1")
        with pytest.warns(UserWarning, match="numerically-invalid"):
            state, step = mgr.load_latest()
        assert step == 1
        skipped = metrics_mod.default_registry().get(
            "checkpoint_resume_skipped_nonfinite_total")
        assert skipped.value() >= 1

    def test_latest_valid_path_does_not_pin_resume_cache(self, tmp_path,
                                                         monkeypatch):
        """Review regression: under valid-only resume the walk caches the
        loaded full model state for load_latest's agreed-step reuse — a
        path-only query (the health-rollback callback path) must not
        leave that copy pinned on the manager for the rest of the run."""
        from paddle_tpu.distributed.sharded_checkpoint import (
            ShardedCheckpointManager)
        mgr = ShardedCheckpointManager(str(tmp_path), keep_last_n=10)
        self._save(mgr, 1)
        self._save(mgr, 2, poison=True)
        monkeypatch.setenv("PADDLE_TPU_RESUME_VALID_ONLY", "1")
        with pytest.warns(UserWarning, match="numerically-invalid"):
            path = mgr.latest_valid_path()
        assert path == mgr.path_for(1)
        assert mgr._resume_cache is None

    def test_agreed_step_nonfinite_raises_under_valid_only(
            self, tmp_path, monkeypatch):
        """Review regression: when the fleet-agreed resume step is NOT
        this host's newest valid file, the valid-only guarantee must
        still hold — a nonfinite local copy raises (supervisor relaunch
        + renegotiation) instead of silently restoring NaN weights."""
        from paddle_tpu.distributed.checkpoint import (
            CheckpointCorruptError, CheckpointManager)
        mgr = CheckpointManager(str(tmp_path), keep_last_n=10)
        self._save(mgr, 1, poison=True)
        self._save(mgr, 2)
        assert mgr._read_agreed(1)  # default mode: readable
        monkeypatch.setenv("PADDLE_TPU_RESUME_VALID_ONLY", "1")
        with pytest.raises(CheckpointCorruptError, match="nonfinite"):
            mgr._read_agreed(1)

    def test_tree_finite_walks_nested_and_accepts_ints(self):
        from paddle_tpu.distributed.checkpoint import tree_finite
        good = {"a": [np.ones(3, np.float32)],
                "b": {"c": np.arange(4)},  # int leaves never judged
                "d": "str", "e": 7}
        assert tree_finite(good)
        bad = {"a": {"b": [np.asarray([1.0, np.inf], np.float32)]}}
        assert not tree_finite(bad)


class TestFleetHealthAction:
    """PADDLE_TPU_HEALTH_ACTION=fleet: the monitor reports diverged and
    DEFERS — the supervisor-side controller owns the response."""

    @pytest.fixture(autouse=True)
    def _clean_health(self):
        from paddle_tpu.profiler import health
        health.reset()
        yield
        health.reset()

    def test_fleet_action_pins_diverged_until_relaunch(self):
        from paddle_tpu.profiler import health
        mon = health.HealthMonitor(action="fleet", cooldown_steps=0)
        mon.observe(loss=1.0)
        mon.observe(loss=float("nan"))
        assert health.last_status() == "diverged"
        # clean successors must NOT flap the status back to ok: the
        # controller's poll cadence would race a one-step excursion
        for s in range(3, 10):
            mon.observe(loss=1.0, step=s)
        assert health.last_status() == "diverged"

    def test_fleet_action_takes_no_local_response(self):
        from paddle_tpu.profiler import health

        class _Boom:
            def __getattr__(self, name):  # any rollback/halt use explodes
                raise AssertionError("fleet action must not act locally")

        mon = health.HealthMonitor(action="fleet", checkpoint=_Boom(),
                                   cooldown_steps=0)
        mon.model = _Boom()
        mon.observe(loss=float("inf"))  # must not touch model/checkpoint
        assert health.last_status() == "diverged"
        assert mon.rollbacks == 0

    def test_warn_action_still_rearms_to_ok(self):
        from paddle_tpu.profiler import health
        mon = health.HealthMonitor(action="warn", cooldown_steps=0)
        mon.observe(loss=float("nan"))
        assert health.last_status() == "diverged"
        mon.observe(loss=1.0)
        assert health.last_status() == "ok"

    def test_unknown_action_still_rejected(self):
        from paddle_tpu.profiler import health
        with pytest.raises(ValueError, match="fleet"):
            health.HealthMonitor(action="bogus")
