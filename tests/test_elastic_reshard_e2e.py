"""Slow multi-process e2e: elastic re-sharding restore across a CHANGED
world size, on the sharded/chunked checkpoint backend in ONE shared
directory.

Scale-down: a 2-host fleet checkpoints every step (async, chunked,
coordinated two-phase commit, one shared dir). Both hosts are killed
inside step 7's commit phase (`ckpt.commit` kill — between prepare and
commit), so step 7 is torn everywhere and the newest fully-committed step
is 6. The supervisor then relaunches the job as a ONE-host fleet
(`--np` changed by the operator): the single trainer re-shards the
world-2 checkpoint, resumes from the barrier-committed step 6, and
finishes with weights bit-identical to an uninterrupted single-host run.

Scale-up is symmetric: a 1-host run killed mid-epoch resumes as a 2-host
fleet from the same shared directory; both hosts negotiate the resume
step over manifests, restore rank-independently, and finish
bit-identically.

fast-sibling: tests/test_sharded_ckpt.py (format, ownership,
re-sharding restore, async off-critical-path, corruption fuzz, chaos) —
keep those green in tier-1; this file is the slow integration proof.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.distributed import sharded_checkpoint as sc
from paddle_tpu.distributed.store import TCPStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow

# Deterministic trainer, shared by every phase. argv: ckpt_dir out_json.
# World/rank/master come from the standard trainer env contract; the kill
# phases arm PADDLE_TPU_FAULT_SPEC (ckpt.commit kill) or KILL_AT (SIGKILL
# after N batches, for the single-host phase that has no barrier site).
_TRAIN_SCRIPT = r"""
import json, os, signal, sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.hapi.callbacks import Callback, FaultTolerantCheckpoint
from paddle_tpu.io import Dataset

CKPT, OUT = sys.argv[1], sys.argv[2]
KILL_AT = int(os.environ.get("KILL_AT", "0"))


class DS(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        rng = np.random.RandomState(1000 + i)
        return rng.randn(4).astype(np.float32), rng.randn(2).astype(np.float32)


class KillSwitch(Callback):
    def __init__(self):
        super().__init__()
        self.n = 0

    def on_train_batch_end(self, step, logs=None):
        self.n += 1
        if KILL_AT and self.n >= KILL_AT:
            os.kill(os.getpid(), signal.SIGKILL)  # no goodbye


def build():
    paddle.seed(42)
    net = nn.Linear(4, 2)
    m = paddle.Model(net)
    m.prepare(optimizer.Adam(learning_rate=1e-2,
                             parameters=net.parameters()),
              loss=nn.MSELoss())
    return m


m = build()
# save_freq_epochs high: only per-step saves + the final epoch-end save,
# so ckpt.commit occurrence N == global step N's coordinated save
cbs = [FaultTolerantCheckpoint(CKPT, save_freq_steps=1, save_freq_epochs=10,
                               layout="sharded", async_save=True)]
if KILL_AT:
    cbs.append(KillSwitch())
m.fit(DS(), batch_size=2, epochs=2, shuffle=False, verbose=0,
      callbacks=cbs, resume=CKPT)

# uninterrupted single-host reference, trained in THIS process: the
# resumed-across-world-sizes tail must match it bit for bit
m2 = build()
m2.fit(DS(), batch_size=2, epochs=2, shuffle=False, verbose=0)
for mm in (m, m2):
    mm._sync_from_train_step()

from paddle_tpu.profiler.metrics import default_registry
out = {
    "weights": {k: np.asarray(v.data).tolist()
                for k, v in m.network.state_dict().items()},
    "ref_weights": {k: np.asarray(v.data).tolist()
                    for k, v in m2.network.state_dict().items()},
    "metrics": default_registry().snapshot(),
}
with open(OUT, "w") as f:
    json.dump(out, f)
"""


def _env(master_port=None, world=1, rank=0, extra=None):
    env = dict(os.environ)
    env.pop("PADDLE_TPU_FAULT_SPEC", None)
    env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TPU_CKPT_BARRIER_TIMEOUT": "20",
                "PADDLE_TPU_CKPT_RESUME_TIMEOUT": "120"})
    if master_port is not None:
        env["MASTER_ADDR"] = "127.0.0.1"
        env["MASTER_PORT"] = str(master_port)
    else:
        env.pop("MASTER_ADDR", None)
        env.pop("MASTER_PORT", None)
    env.update(extra or {})
    return env


def _run_trainer(script, ckpt, out, env, timeout=300):
    return subprocess.run([sys.executable, str(script), str(ckpt), str(out)],
                          env=env, timeout=timeout)


def _weights(out_path):
    with open(out_path) as f:
        doc = json.load(f)
    return doc


def _snapshot_total(snap, name, **labels):
    vals = snap.get(name, {}).get("values", [])
    return sum(v["value"] for v in vals
               if all(v["labels"].get(k) == lv for k, lv in labels.items()))


def _assert_bit_identical(doc, who):
    assert doc["weights"].keys() == doc["ref_weights"].keys()
    for k in doc["weights"]:
        assert np.array_equal(np.asarray(doc["weights"][k]),
                              np.asarray(doc["ref_weights"][k])), \
            f"{who}: {k} diverged from the uninterrupted run"


class TestScaleDownTwoToOne:
    def test_killed_two_host_fleet_resumes_as_one_host(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ElasticSupervisor
        script = tmp_path / "train.py"
        script.write_text(_TRAIN_SCRIPT)
        shared = tmp_path / "ckpt"  # ONE directory for the whole fleet

        master = TCPStore("127.0.0.1", 0, is_master=True)
        try:
            # phase 1: 2-host fleet, both killed between prepare and
            # commit of step 7's coordinated save (same occurrence on
            # both — the fleet dies, like a pod preemption)
            procs = [subprocess.Popen(
                [sys.executable, str(script), str(shared),
                 str(tmp_path / f"out{r}.json")],
                env=_env(master.port, world=2, rank=r,
                         extra={"PADDLE_TPU_FAULT_SPEC":
                                "ckpt.commit=1@7:kill"}))
                for r in range(2)]
            for p in procs:
                assert p.wait(timeout=300) == 17  # the injector's exit code
        finally:
            master.stop()

        # the barrier held: steps 1..6 are complete in the shared dir,
        # step 7 exists only as torn prepares, nothing ever committed it
        steps = {s: sc.verify_step(p)[0]
                 for s, p in sc._step_dirs(str(shared), "ckpt")}
        assert steps.get(7) == "torn", steps
        committed = sorted(s for s, st in steps.items() if st == "complete")
        assert committed and max(committed) == 6, steps

        # phase 2: the operator relaunches with --np 1; the supervisor
        # drives the single-host fleet, which re-shards the world-2
        # checkpoint and resumes from the barrier-committed step 6
        out = tmp_path / "out_resume.json"
        sup = ElasticSupervisor(max_restarts=1, backoff=0.2)
        rc = sup.supervise(
            [sys.executable, str(script), str(shared), str(out)],
            env=_env(None, world=1, rank=0))
        assert rc == 0
        doc = _weights(out)
        _assert_bit_identical(doc, "scale-down host")
        snap = doc["metrics"]
        assert _snapshot_total(snap, "checkpoint_loads_total") >= 1
        # async saves happened in the resumed generation too
        assert _snapshot_total(snap, "checkpoint_async_bytes") > 0


class TestScaleUpOneToTwo:
    def test_killed_one_host_run_resumes_as_two_host_fleet(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import ElasticSupervisor
        script = tmp_path / "train.py"
        script.write_text(_TRAIN_SCRIPT)
        shared = tmp_path / "ckpt"

        # phase 1: single host (no barrier), SIGKILLed right after step
        # 5's batch — its async save may be committed or torn; resume
        # replays from whatever is newest-committed either way
        p = subprocess.run(
            [sys.executable, str(script), str(shared),
             str(tmp_path / "out_kill.json")],
            env=_env(None, world=1, rank=0, extra={"KILL_AT": "5"}),
            timeout=300)
        assert p.returncode == -9
        steps = {s: sc.verify_step(pth)[0]
                 for s, pth in sc._step_dirs(str(shared), "ckpt")}
        assert any(st == "complete" for st in steps.values()), steps

        # phase 2: relaunched as a 2-host fleet sharing the directory;
        # both negotiate the resume step over manifests and finish
        master = TCPStore("127.0.0.1", 0, is_master=True)
        sups, rcs = {}, {}
        try:
            import threading

            def host(r):
                sup = ElasticSupervisor(max_restarts=1, backoff=0.2)
                sups[r] = sup
                rcs[r] = sup.supervise(
                    [sys.executable, str(script), str(shared),
                     str(tmp_path / f"out_up{r}.json")],
                    env=_env(master.port, world=2, rank=r))

            ts = [threading.Thread(target=host, args=(r,)) for r in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=420)
                assert not t.is_alive(), "supervisor wedged"
        finally:
            master.stop()
        assert rcs == {0: 0, 1: 0}

        docs = {r: _weights(tmp_path / f"out_up{r}.json") for r in range(2)}
        for r in range(2):
            _assert_bit_identical(docs[r], f"scale-up host {r}")
            snap = docs[r]["metrics"]
            assert _snapshot_total(snap, "checkpoint_loads_total") >= 1
            assert _snapshot_total(snap, "ckpt_barrier_commits_total") >= 1
        for k in docs[0]["weights"]:
            assert np.array_equal(np.asarray(docs[0]["weights"][k]),
                                  np.asarray(docs[1]["weights"][k]))
