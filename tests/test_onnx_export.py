"""Real ONNX emission (reference `python/paddle/onnx/export.py:36`):
`paddle.onnx.export` writes an actual ONNX protobuf; the test decodes it
with the in-repo wire reader and EXECUTES the graph with a numpy
interpreter of the emitted op subset, asserting 1e-4 parity against the
eager model (onnxruntime is not in this environment; the interpreter
plays its role — same consumption contract, independent of the encoder's
jnp semantics)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.onnx import proto


# ---------------------------------------------------------------------------
# minimal numpy ONNX runtime for the exported subset
# ---------------------------------------------------------------------------
def _conv2d_np(x, w, b, strides, pads, dilations, group):
    hl, wl, hh, wh = pads
    x = np.pad(x, ((0, 0), (0, 0), (hl, hh), (wl, wh)))
    N, C, H, W = x.shape
    O, Cg, kh, kw = w.shape
    sh, sw = strides
    dh, dw = dilations
    Ho = (H - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W - (dw * (kw - 1) + 1)) // sw + 1
    out = np.zeros((N, O, Ho, Wo), np.float32)
    og = O // group
    for g in range(group):
        xs = x[:, g * Cg:(g + 1) * Cg]
        for i in range(kh):
            for j in range(kw):
                patch = xs[:, :, i * dh:i * dh + Ho * sh:sh,
                           j * dw:j * dw + Wo * sw:sw]
                out[:, g * og:(g + 1) * og] += np.einsum(
                    "nchw,oc->nohw", patch, w[g * og:(g + 1) * og, :, i, j])
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out


def _pool_np(x, kernel, strides, pads, mode, count_include_pad=0):
    hl, wl, hh, wh = pads
    fill = -np.inf if mode == "max" else 0.0
    x = np.pad(x, ((0, 0), (0, 0), (hl, hh), (wl, wh)),
               constant_values=fill)
    N, C, H, W = x.shape
    kh, kw = kernel
    sh, sw = strides
    Ho = (H - kh) // sh + 1
    Wo = (W - kw) // sw + 1
    out = np.zeros((N, C, Ho, Wo), np.float32)
    for i in range(Ho):
        for j in range(Wo):
            win = x[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
            if mode == "max":
                out[:, :, i, j] = win.max(axis=(2, 3))
            elif count_include_pad:
                out[:, :, i, j] = win.mean(axis=(2, 3))
            else:
                cnt = np.isfinite(win).all() and (
                    min(i * sh + kh, H) - i * sh) * (
                        min(j * sw + kw, W) - j * sw)
                out[:, :, i, j] = win.sum(axis=(2, 3)) / cnt
    return out


def run_onnx(model: dict, feeds: dict) -> list:
    g = model["graph"]
    env = dict(g["initializers"])
    env.update(feeds)
    for nd in g["nodes"]:
        i = [env[x] if x else None for x in nd["inputs"]]
        a = nd["attrs"]
        t = nd["op_type"]
        if t == "Conv":
            assert "pads" in a, "exporter always writes explicit pads here"
            o = _conv2d_np(i[0], i[1], i[2] if len(i) > 2 else None,
                           a.get("strides", [1, 1]), a["pads"],
                           a.get("dilations", [1, 1]), a.get("group", 1))
        elif t == "BatchNormalization":
            x, sc, b, m, v = i
            o = (x - m.reshape(1, -1, 1, 1)) / np.sqrt(
                v.reshape(1, -1, 1, 1) + a.get("epsilon", 1e-5))
            o = o * sc.reshape(1, -1, 1, 1) + b.reshape(1, -1, 1, 1)
        elif t == "MaxPool":
            o = _pool_np(i[0], a["kernel_shape"], a["strides"], a["pads"],
                         "max")
        elif t == "AveragePool":
            o = _pool_np(i[0], a["kernel_shape"], a["strides"], a["pads"],
                         "avg", a.get("count_include_pad", 0))
        elif t == "GlobalAveragePool":
            o = i[0].mean(axis=(2, 3), keepdims=True)
        elif t == "Relu":
            o = np.maximum(i[0], 0)
        elif t == "Sigmoid":
            o = 1.0 / (1.0 + np.exp(-i[0]))
        elif t == "Tanh":
            o = np.tanh(i[0])
        elif t == "Erf":
            from math import erf
            o = np.vectorize(erf)(i[0]).astype(np.float32)
        elif t == "Identity":
            o = i[0]
        elif t == "Add":
            o = i[0] + i[1]
        elif t == "Sub":
            o = i[0] - i[1]
        elif t == "Mul":
            o = i[0] * i[1]
        elif t == "Div":
            o = i[0] / i[1]
        elif t == "Reshape":
            tgt = [int(d) for d in i[1]]
            # ONNX semantics: 0 copies the input dim, -1 infers
            tgt = [i[0].shape[k] if d == 0 else d
                   for k, d in enumerate(tgt)]
            o = i[0].reshape(tgt)
        elif t == "Transpose":
            o = i[0].transpose(a["perm"])
        elif t == "Gemm":
            A = i[0].T if a.get("transA") else i[0]
            B = i[1].T if a.get("transB") else i[1]
            o = a.get("alpha", 1.0) * (A @ B)
            if len(i) > 2 and i[2] is not None:
                o = o + a.get("beta", 1.0) * i[2]
        elif t == "MatMul":
            o = i[0] @ i[1]
        elif t == "Softmax":
            z = i[0] - i[0].max(axis=a.get("axis", -1), keepdims=True)
            e = np.exp(z)
            o = e / e.sum(axis=a.get("axis", -1), keepdims=True)
        elif t == "ReduceMean":
            o = i[0].mean(axis=tuple(a["axes"]) if "axes" in a else None,
                          keepdims=bool(a.get("keepdims", 0)))
        else:
            raise NotImplementedError(f"interpreter: {t}")
        outs = nd["outputs"]
        if t in ("MatMul",) and len(outs) == 1:
            env[outs[0]] = o
        else:
            env[outs[0]] = o
    return [env[vo["name"]] for vo in g["outputs"]]


def _export_and_run(net, shape, seed=0, atol=1e-4):
    from paddle_tpu.static import InputSpec
    net.eval()
    x = np.random.default_rng(seed).normal(size=shape).astype("float32")
    golden = net(paddle.to_tensor(x)).numpy()
    import tempfile
    import os
    with tempfile.TemporaryDirectory() as d:
        p = paddle.onnx.export(net, os.path.join(d, "m"),
                               input_spec=[InputSpec(shape, "float32", "x")])
        assert p.endswith(".onnx") and os.path.exists(p)
        with open(p, "rb") as f:
            model = proto.parse_model(f.read())
    assert model["ir_version"] == 8
    assert model["graph"]["inputs"][0]["name"] == "x"
    (got,) = run_onnx(model, {"x": x})
    np.testing.assert_allclose(got, golden, atol=atol, rtol=1e-4)
    return model


class TestWireFormat:
    def test_tensor_roundtrip(self):
        arr = np.random.default_rng(0).normal(size=(3, 4)).astype("float32")
        name, back = proto.parse_tensor(proto.tensor_proto("w", arr))
        assert name == "w"
        np.testing.assert_array_equal(back, arr)

    def test_node_roundtrip(self):
        nb = proto.node("Conv", ["x", "w"], ["y"], name="c1",
                        attrs={"strides": [2, 2], "group": 1,
                               "epsilon": 0.5, "auto_pad": "VALID"})
        nd = proto.parse_node(nb)
        assert nd["op_type"] == "Conv"
        assert nd["inputs"] == ["x", "w"]
        assert nd["attrs"]["strides"] == [2, 2]
        assert nd["attrs"]["epsilon"] == 0.5
        assert nd["attrs"]["auto_pad"] == "VALID"

    def test_protoc_decodes_model(self, tmp_path):
        """The emitted bytes must be valid protobuf: protoc --decode_raw
        accepts them (structure check independent of our reader)."""
        import shutil
        import subprocess
        if shutil.which("protoc") is None:
            pytest.skip("protoc binary not available in this environment")
        g = proto.graph([proto.node("Relu", ["x"], ["y"])], "g", [],
                        [proto.value_info("x", "float32", (2, 2))],
                        [proto.value_info("y", "float32", (2, 2))])
        data = proto.model(g)
        r = subprocess.run(["protoc", "--decode_raw"], input=data,
                           capture_output=True, timeout=60)
        assert r.returncode == 0, r.stderr[:300]
        assert b"Relu" in r.stdout


class TestZooExport:
    def test_lenet_parity(self):
        from paddle_tpu.models import LeNet
        paddle.seed(3)
        model = _export_and_run(LeNet(), (2, 1, 28, 28))
        ops = {n["op_type"] for n in model["graph"]["nodes"]}
        assert "Conv" in ops and ("Gemm" in ops or "MatMul" in ops)

    def test_resnet18_parity(self):
        from paddle_tpu.models.resnet import resnet18
        paddle.seed(4)
        model = _export_and_run(resnet18(), (1, 3, 32, 32), atol=5e-4)
        ops = {n["op_type"] for n in model["graph"]["nodes"]}
        assert {"Conv", "BatchNormalization", "MaxPool",
                "GlobalAveragePool"} <= ops

    def test_dynamic_batch_preserved(self, tmp_path):
        """InputSpec with None batch exports a dim_param graph input and a
        batch-copying Reshape (ONNX dim 0 semantics) — runnable at any
        batch size, like the reference paddle2onnx dynamic axes."""
        import os
        from paddle_tpu.models import LeNet
        from paddle_tpu.static import InputSpec
        paddle.seed(6)
        net = LeNet()
        net.eval()
        p = paddle.onnx.export(
            net, os.path.join(str(tmp_path), "m"),
            input_spec=[InputSpec((None, 1, 28, 28), "float32", "x")])
        with open(p, "rb") as f:
            model = proto.parse_model(f.read())
        assert model["graph"]["inputs"][0]["shape"][0] == "batch"
        # run at TWO batch sizes through the interpreter
        for B in (1, 5):
            x = np.random.default_rng(B).normal(
                size=(B, 1, 28, 28)).astype("float32")
            golden = net(paddle.to_tensor(x)).numpy()
            (got,) = run_onnx(model, {"x": x})
            np.testing.assert_allclose(got, golden, atol=1e-4, rtol=1e-4)

    def test_nhwc_model_refused(self):
        from paddle_tpu.models.resnet import resnet18
        from paddle_tpu.static import InputSpec
        net = resnet18(data_format="NHWC")
        net.eval()
        with pytest.raises(NotImplementedError, match="NCHW"):
            paddle.onnx.export(
                net, "/tmp/nhwc",
                input_spec=[InputSpec((1, 32, 32, 3), "float32", "x")])

    def test_unsupported_op_raises_with_name(self):
        from paddle_tpu import nn
        from paddle_tpu.static import InputSpec

        class Odd(nn.Layer):
            def forward(self, x):
                return paddle.cumsum(x, axis=1)

        with pytest.raises(NotImplementedError, match="cumsum"):
            paddle.onnx.export(Odd(), "/tmp/odd",
                               input_spec=[InputSpec((2, 3), "float32")])
