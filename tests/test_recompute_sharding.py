"""fleet.utils.recompute + distributed.sharding.group_sharded_parallel.

Reference test style: `unittests/test_dygraph_recompute.py` asserts
recomputed forward/backward equals the plain run (incl. dropout RNG
replay); sharding-stage tests assert training equivalence
(`test_dygraph_group_sharded_api.py`).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn, optimizer
from paddle_tpu.nn import functional as F
from paddle_tpu.distributed.fleet.utils import recompute
from paddle_tpu.distributed.sharding import (group_sharded_parallel,
                                             save_group_sharded_model)


@pytest.fixture(autouse=True)
def _clean():
    yield
    dist.set_hybrid_communicate_group(None)


class Net(nn.Layer):
    def __init__(self, d=16, use_dropout=False):
        super().__init__()
        self.fc1 = nn.Linear(d, 32)
        self.fc2 = nn.Linear(32, 32)
        self.fc3 = nn.Linear(32, d)
        self.p = 0.3 if use_dropout else 0.0

    def block(self, x):
        h = F.relu(self.fc1(x))
        h = F.dropout(h, p=self.p, training=self.training)
        return F.relu(self.fc2(h))

    def forward(self, x, use_recompute=False):
        h = recompute(self.block, x) if use_recompute else self.block(x)
        return self.fc3(h)


class TestRecompute:
    def test_matches_plain_forward_backward(self):
        paddle.seed(0)
        net = Net()
        rs = np.random.RandomState(0)
        X = rs.randn(8, 16).astype(np.float32)

        def run(use_rc):
            for p in net.parameters():
                p.clear_grad()
            out = net(paddle.to_tensor(X), use_recompute=use_rc)
            loss = (out * out).mean()
            loss.backward()
            return (float(loss),
                    {k: np.asarray(p.grad.data)
                     for k, p in net.named_parameters()})

        l0, g0 = run(False)
        l1, g1 = run(True)
        assert abs(l0 - l1) < 1e-6
        for k in g0:
            np.testing.assert_allclose(g1[k], g0[k], rtol=1e-5, atol=1e-6,
                                       err_msg=k)

    def test_dropout_rng_replay_consistent(self):
        """Recompute with dropout must replay the SAME mask in backward:
        grads are finite and deterministic given the generator state."""
        paddle.seed(7)
        net = Net(use_dropout=True)
        rs = np.random.RandomState(0)
        X = rs.randn(8, 16).astype(np.float32)
        out = net(paddle.to_tensor(X), use_recompute=True)
        loss = (out * out).mean()
        loss.backward()
        for k, p in net.named_parameters():
            assert p.grad is not None, k
            assert bool(jnp.all(jnp.isfinite(p.grad.data))), k

    def test_lambda_closure_params_get_grads(self):
        """recompute(lambda a: net.block(a), x) must thread the closed-over
        layer's params (reference supports arbitrary callables)."""
        paddle.seed(0)
        net = Net()
        rs = np.random.RandomState(0)
        X = rs.randn(8, 16).astype(np.float32)
        out = recompute(lambda a: net.block(a), paddle.to_tensor(X))
        (out * out).mean().backward()
        assert net.fc1.weight.grad is not None
        assert float(jnp.abs(net.fc1.weight.grad.data).sum()) > 0

    def test_plain_function_recompute(self):
        x = paddle.to_tensor(np.ones((4, 4), np.float32))
        x.stop_gradient = False
        y = recompute(lambda a: (a * a).sum(), x)
        y.backward()
        np.testing.assert_allclose(np.asarray(x.grad.data),
                                   2 * np.ones((4, 4)), rtol=1e-6)


class TestGroupSharded:
    @pytest.mark.parametrize("level", ["os", "os_g", "p_g_os"])
    def test_training_matches_unsharded(self, level):
        rs = np.random.RandomState(0)
        X = rs.randn(16, 16).astype(np.float32)
        Y = rs.randn(16, 16).astype(np.float32)

        def run(sharded):
            dist.set_hybrid_communicate_group(None)
            paddle.seed(0)
            net = Net()
            opt = optimizer.Adam(learning_rate=1e-2,
                                 parameters=net.parameters())
            scaler = None
            if sharded:
                net, opt, scaler = group_sharded_parallel(
                    net, opt, level)
            losses = []
            for _ in range(4):
                out = net(paddle.to_tensor(X))
                loss = F.mse_loss(out, paddle.to_tensor(Y))
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
            return losses

        ref = run(False)
        got = run(True)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_slots_actually_sharded(self):
        paddle.seed(0)
        net = Net(d=16)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=net.parameters())
        net, opt, _ = group_sharded_parallel(net, opt, "os")
        out = net(paddle.to_tensor(np.ones((8, 16), np.float32)))
        out.mean().backward()
        opt.step()
        sharded = 0
        for slots in opt._slots.values():
            for v in slots.values():
                if hasattr(v, "sharding") and "sharding" in str(
                        getattr(v.sharding, "spec", "")):
                    sharded += 1
        assert sharded > 0, "no optimizer slot is sharded"

    def test_minimize_path_shards_slots(self):
        paddle.seed(0)
        net = Net(d=16)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=net.parameters())
        net, opt, _ = group_sharded_parallel(net, opt, "os")
        loss = F.mse_loss(net(paddle.to_tensor(
            np.ones((8, 16), np.float32))), paddle.zeros([8, 16]))
        opt.minimize(loss)
        sharded = sum(
            1 for slots in opt._slots.values() for v in slots.values()
            if hasattr(v, "sharding") and "sharding" in str(
                getattr(v.sharding, "spec", "")))
        assert sharded > 0

    def test_existing_topology_without_sharding_axis_raises(self):
        dist.set_hybrid_communicate_group(
            __import__("paddle_tpu.distributed.topology",
                       fromlist=["HybridCommunicateGroup"]
                       ).HybridCommunicateGroup(dims={"dp": 8}))
        net = Net()
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=net.parameters())
        with pytest.raises(ValueError, match="sharding"):
            group_sharded_parallel(net, opt, "os")

    def test_stage3_params_sharded_and_save(self, tmp_path):
        paddle.seed(0)
        net = Net(d=16)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=net.parameters())
        net, opt, _ = group_sharded_parallel(net, opt, "p_g_os")
        sharded = sum(
            1 for p in net.parameters()
            if "sharding" in str(getattr(p.data.sharding, "spec", "")))
        assert sharded > 0, "no parameter is sharded"
        save_group_sharded_model(net, str(tmp_path / "out"), opt)
        assert (tmp_path / "out" / "model.pdparams").exists()
        assert (tmp_path / "out" / "model.pdopt").exists()
