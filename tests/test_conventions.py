"""Framework convention lints (paddle_tpu/analysis/conventions.py):
the package source itself must lint clean (THE enforcement — a new
unregistered fault site, undocumented env knob, direct int(environ)
parse, non-daemon thread, or undeclared event kind fails tier-1 here),
and each lint must catch its seeded violation on synthetic source.

Also pins the event-kind <-> obs_tail pairing: every kind declared in
events.KIND_SEVERITY renders through the tool (never dropped as
garbage), including by the operator views.
"""
import os
import sys
import textwrap

import pytest

from paddle_tpu.analysis import conventions as C
from paddle_tpu.profiler import events

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))
import obs_tail  # noqa: E402


class TestPackageIsClean:
    """The real package + README must pass every lint."""

    def test_env_knob_parses(self):
        assert C.lint_env_knob_parses() == []

    def test_env_knob_docs(self):
        assert C.lint_env_knob_docs() == []

    def test_fault_sites(self):
        assert C.lint_fault_sites() == []

    def test_threads(self):
        assert C.lint_threads() == []

    def test_event_kinds(self):
        assert C.lint_event_kinds() == []

    def test_run_all_shape(self):
        res = C.run_all()
        assert set(res) == {"env-knob-parses", "env-knob-docs",
                            "fault-sites", "threads", "event-kinds"}
        assert all(v == [] for v in res.values())


def _write_pkg(tmp_path, source: str, name="mod.py"):
    root = tmp_path / "pkg"
    root.mkdir(exist_ok=True)
    (root / name).write_text(textwrap.dedent(source))
    return str(root)


class TestEnvParseLint:
    def test_catches_direct_int_parse(self, tmp_path):
        root = _write_pkg(tmp_path, """
            import os
            N = int(os.environ.get("PADDLE_TPU_FOO", "3"))
        """)
        v = C.lint_env_knob_parses(root)
        assert len(v) == 1 and "PADDLE_TPU_FOO" in v[0] \
            and "envparse" in v[0]

    def test_catches_float_of_subscript(self, tmp_path):
        root = _write_pkg(tmp_path, """
            import os
            X = float(os.environ["PADDLE_TPU_BAR"])
        """)
        v = C.lint_env_knob_parses(root)
        assert len(v) == 1 and "PADDLE_TPU_BAR" in v[0]

    def test_helper_module_is_exempt(self, tmp_path):
        root = _write_pkg(tmp_path, """
            import os
            N = int(os.environ.get("PADDLE_TPU_FOO", "3"))
        """, name=os.path.join("envparse.py"))
        utils = tmp_path / "pkg" / "utils"
        utils.mkdir()
        (tmp_path / "pkg" / "envparse.py").rename(utils / "envparse.py")
        assert C.lint_env_knob_parses(str(tmp_path / "pkg")) == []

    def test_non_paddle_knobs_ignored(self, tmp_path):
        root = _write_pkg(tmp_path, """
            import os
            N = int(os.environ.get("OTHER_KNOB", "3"))
        """)
        assert C.lint_env_knob_parses(root) == []

    def test_collect_env_knobs_sees_helper_and_from_env(self, tmp_path):
        root = _write_pkg(tmp_path, """
            import os
            from paddle_tpu.utils.envparse import env_int
            A = os.environ.get("PADDLE_TPU_A")
            B = env_int("PADDLE_TPU_B", 1)
            policy = RetryPolicy.from_env("store")
        """)
        knobs = C.collect_env_knobs(root)
        assert "PADDLE_TPU_A" in knobs and "PADDLE_TPU_B" in knobs
        assert "PADDLE_TPU_STORE_RETRIES" in knobs
        assert "PADDLE_TPU_STORE_TIMEOUT" in knobs

    def test_collect_env_knobs_sees_aliased_helper_import(self, tmp_path):
        """`from ...envparse import env_int as _int_knob` (the autotune/
        controller pattern) must still feed the knob-docs lint."""
        root = _write_pkg(tmp_path, """
            from paddle_tpu.utils.envparse import env_int as _int_knob
            from ...utils.envparse import env_float as _env_float
            A = _int_knob("PADDLE_TPU_ALIASED_A", 8)
            B = _env_float("PADDLE_TPU_ALIASED_B", 1.0)
        """)
        knobs = C.collect_env_knobs(root)
        assert "PADDLE_TPU_ALIASED_A" in knobs
        assert "PADDLE_TPU_ALIASED_B" in knobs

    def test_doc_lint_names_undocumented_knob(self, tmp_path):
        root = _write_pkg(tmp_path, """
            import os
            A = os.environ.get("PADDLE_TPU_UNDOCUMENTED_KNOB")
        """)
        readme = tmp_path / "README.md"
        readme.write_text("# nothing here\n")
        v = C.lint_env_knob_docs(str(readme), root)
        assert len(v) == 1 and "PADDLE_TPU_UNDOCUMENTED_KNOB" in v[0]


class TestFaultSiteLint:
    def test_catches_unregistered_site(self, tmp_path):
        root = _write_pkg(tmp_path, """
            from ..fault import site
            site("made.up.site")
        """)
        readme = tmp_path / "README.md"
        readme.write_text("\n".join(
            f"`{s}`" for s in __import__(
                "paddle_tpu.fault.inject",
                fromlist=["KNOWN_SITES"]).KNOWN_SITES))
        v = C.lint_fault_sites(root, str(readme))
        assert any("made.up.site" in x and "not registered" in x
                   for x in v)

    def test_dead_registered_site_is_reported(self, tmp_path):
        # a package with NO call sites: every registered site is dead
        root = _write_pkg(tmp_path, "x = 1\n")
        v = C.lint_fault_sites(root, readme_path=os.path.join(
            os.path.dirname(C.package_root()), "README.md"))
        assert any("no call site left" in x for x in v)

    def test_dynamic_prefix_accepted(self, tmp_path):
        root = _write_pkg(tmp_path, """
            from ..fault import site as _fault_site
            def f(op):
                _fault_site(f"ps.{op}")
                _fault_site("dataloader.worker")
        """)
        readme = os.path.join(os.path.dirname(C.package_root()),
                              "README.md")
        v = C.lint_fault_sites(root, readme)
        assert not any("ps." in x and "not registered" in x for x in v)
        assert not any("dataloader" in x and "not registered" in x
                       for x in v)


class TestThreadLint:
    def test_catches_non_daemon_unjoined_thread(self, tmp_path):
        root = _write_pkg(tmp_path, """
            import threading
            t = threading.Thread(target=print)
            t.start()
        """)
        v = C.lint_threads(root)
        assert len(v) == 1 and "neither" in v[0]

    def test_daemon_kwarg_passes(self, tmp_path):
        root = _write_pkg(tmp_path, """
            import threading
            t = threading.Thread(target=print, daemon=True)
        """)
        assert C.lint_threads(root) == []

    def test_join_in_module_passes(self, tmp_path):
        root = _write_pkg(tmp_path, """
            import threading
            class W:
                def start(self):
                    self._thread = threading.Thread(target=print)
                    self._thread.start()
                def stop(self):
                    self._thread.join()
        """)
        assert C.lint_threads(root) == []

    def test_daemon_attribute_assignment_passes(self, tmp_path):
        root = _write_pkg(tmp_path, """
            import threading
            t = threading.Thread(target=print)
            t.daemon = True
            t.start()
        """)
        assert C.lint_threads(root) == []

    def test_unassigned_non_daemon_thread_flagged(self, tmp_path):
        root = _write_pkg(tmp_path, """
            import threading
            threading.Thread(target=print).start()
        """)
        v = C.lint_threads(root)
        assert len(v) == 1 and "not assigned" in v[0]


class TestEventKindLint:
    def test_catches_undeclared_kind(self, tmp_path):
        root = _write_pkg(tmp_path, """
            from ..profiler import events as _events_mod
            _events_mod.emit("totally_new_kind", thing=1)
        """)
        v = C.lint_event_kinds(root)
        assert len(v) == 1 and "totally_new_kind" in v[0]

    def test_bare_emit_needs_events_import(self, tmp_path):
        # a local emit() helper (the ONNX builder pattern) must not lint
        root = _write_pkg(tmp_path, """
            def emit(node, **kw):
                return node
            emit("Conv", x=1)
        """)
        assert C.lint_event_kinds(root) == []

    def test_imported_bare_emit_is_linted(self, tmp_path):
        root = _write_pkg(tmp_path, """
            from ..profiler.events import emit
            emit("another_new_kind")
        """)
        v = C.lint_event_kinds(root)
        assert len(v) == 1 and "another_new_kind" in v[0]


class TestKindSeverityTable:
    def test_every_kind_has_a_legal_severity(self):
        for kind, sev in events.KIND_SEVERITY.items():
            assert sev in events.SEVERITIES, (kind, sev)

    def test_kinds_view_matches_table(self):
        assert set(events.KINDS) == set(events.KIND_SEVERITY)

    def test_every_declared_kind_renders_in_obs_tail(self):
        """No registered kind may drop as garbage: parse_lines accepts
        it and format_event (plus every operator view that claims it)
        renders a line naming the kind's payload."""
        import json
        for kind in events.KINDS:
            rec = {"ts": 1e9, "kind": kind, "host": "h",
                   "severity": events.KIND_SEVERITY[kind]}
            evs, bad = obs_tail.parse_lines([json.dumps(rec)])
            assert bad == 0 and len(evs) == 1, kind
            line = obs_tail.format_event(evs[0])
            assert kind in line

    def test_analysis_finding_operator_rendering(self):
        rec = {"ts": 1e9, "kind": "analysis_finding", "host": "h",
               "severity": "error", "program": "GPT#1",
               "entry": "train_step", "check": "donation",
               "code": "undonated-large-input", "finding_severity": "high",
               "param": "['w']", "scope": "", "nbytes": 123,
               "message": "big and dead", "fix_hint": "donate it"}
        line = obs_tail.format_analysis(rec)
        assert "GPT#1[train_step]" in line
        assert "donation/undonated-large-input" in line
        assert "donate it" in line and "high" in line

    def test_operator_views_fall_back_for_other_kinds(self):
        rec = {"ts": 1e9, "kind": "retrace", "host": "h"}
        assert "retrace" in obs_tail.format_analysis(rec) or True
        # format_analysis is only dispatched for ANALYSIS_KINDS; the
        # _emit dispatcher must route unrelated kinds to format_event
        import io
        out = io.StringIO()
        obs_tail._emit([rec], as_json=False, out=out, analysis=True)
        assert "retrace" in out.getvalue()
