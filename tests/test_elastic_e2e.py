"""Slow multi-process e2e: the full distributed fault-tolerance story.

Two "hosts" (subprocesses sharing one rendezvous TCPStore, each with its
own checkpoint directory) train under per-host elastic supervisors. Host 1
is killed between prepare and commit of step 3's coordinated checkpoint
(`ckpt.commit` fault site, kind=kill): the barrier guarantees NO host
publishes a final file for that step. Host 0's supervisor notices the
stale heartbeat (watch -> membership restart), host 1's notices the corpse
(failure restart); both relaunch with a bumped generation, negotiate the
newest fleet-committed step (2), and train a bit-identical tail.

fast-sibling: tests/test_coord_checkpoint.py (barrier protocol state
machine), tests/test_elastic_supervisor.py (restart loop) — keep those
green in tier-1; this file is the slow integration proof.
"""
import json
import os
import sys
import threading

import numpy as np
import pytest

from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.profiler import metrics as metrics_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.slow

# Per-host trainer. argv: ckpt_dir out_json events_jsonl. Generation and
# rank come from the supervisor env (PADDLE_TPU_ELASTIC_RESTART_NUM /
# PADDLE_TRAINER_ID). Deterministic end to end, as in test_fault_resume.
_TRAIN_SCRIPT = r"""
import json, os, sys

GEN = int(os.environ.get("PADDLE_TPU_ELASTIC_RESTART_NUM", "0"))
if GEN > 0:
    # the injected kill belongs to the incarnation that died; a relaunched
    # generation must not re-arm it (clear BEFORE the injector's import)
    os.environ.pop("PADDLE_TPU_FAULT_SPEC", None)
CKPT, OUT, EVENTS = sys.argv[1], sys.argv[2], sys.argv[3]
RANK = int(os.environ["PADDLE_TRAINER_ID"])

# snapshot the on-disk state BEFORE any manager construction (init sweeps
# orphan tmps): this is the evidence of what the dead generation left
listing = sorted(os.listdir(CKPT)) if os.path.isdir(CKPT) else []
finals = sorted(int(f.rsplit("_", 1)[1]) for f in listing
                if f.startswith("ckpt_") and f.rsplit("_", 1)[1].isdigit())
with open(EVENTS, "a") as f:
    f.write(json.dumps({"host": RANK, "gen": GEN, "listing": listing,
                        "final_steps": finals}) + "\n")

import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.fleet.elastic import ElasticManager
from paddle_tpu.hapi.callbacks import FaultTolerantCheckpoint
from paddle_tpu.io import Dataset

mgr = ElasticManager(host_id=f"host{RANK}", np=2)  # master addr from env
mgr.join()


class DS(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        rng = np.random.RandomState(1000 + i)
        return rng.randn(4).astype(np.float32), rng.randn(2).astype(np.float32)


def build():
    paddle.seed(42)
    net = nn.Linear(4, 2)
    m = paddle.Model(net)
    m.prepare(optimizer.Adam(learning_rate=1e-2,
                             parameters=net.parameters()),
              loss=nn.MSELoss())
    return m


m = build()
cbs = [FaultTolerantCheckpoint(CKPT, save_freq_steps=1)]
m.fit(DS(), batch_size=2, epochs=2, shuffle=False, verbose=0,
      callbacks=cbs, resume=CKPT)

# uninterrupted reference, trained in THIS process: the resumed tail must
# match it bit for bit (optimizer slots, RNG, LR cursor all restored)
m2 = build()
m2.fit(DS(), batch_size=2, epochs=2, shuffle=False, verbose=0)
for mm in (m, m2):
    mm._sync_from_train_step()

from paddle_tpu.profiler.metrics import default_registry
out = {
    "gen": GEN,
    "weights": {k: np.asarray(v.data).tolist()
                for k, v in m.network.state_dict().items()},
    "ref_weights": {k: np.asarray(v.data).tolist()
                    for k, v in m2.network.state_dict().items()},
    "metrics": default_registry().snapshot(),
}
with open(OUT, "w") as f:
    json.dump(out, f)
mgr.mark_done()  # beats stop now; peers must read this as done, not dead
"""


def _snapshot_total(snap, name, **labels):
    vals = snap.get(name, {}).get("values", [])
    return sum(v["value"] for v in vals
               if all(v["labels"].get(k) == lv for k, lv in labels.items()))


class TestTwoHostKillBetweenPrepareAndCommit:
    def test_barrier_holds_and_fleet_auto_resumes(self, tmp_path):
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticSupervisor)
        script = tmp_path / "train.py"
        script.write_text(_TRAIN_SCRIPT)

        master = TCPStore("127.0.0.1", 0, is_master=True)
        common = {
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO,
            "MASTER_ADDR": "127.0.0.1",
            "MASTER_PORT": str(master.port),
            "PADDLE_TRAINERS_NUM": "2",
            # generous TTL: on a loaded 2-core box a child's beat thread
            # can wake seconds late during import/compile oversubscription;
            # a TTL tighter than that reads a healthy peer as dead, fires a
            # second membership restart, and desyncs the fleet's generation
            # numbering (every later barrier round then times out)
            "PADDLE_ELASTIC_TTL": "6",
            "PADDLE_TPU_CKPT_BARRIER_TIMEOUT": "5",
            "PADDLE_TPU_CKPT_RESUME_TIMEOUT": "120",
        }

        sups, codes = {}, {}

        def host(rank, fault_spec, watch):
            d = str(tmp_path / f"host{rank}")
            env = dict(common)
            env["PADDLE_TRAINER_ID"] = str(rank)
            env["PADDLE_TPU_FAULT_SPEC"] = fault_spec
            manager = None
            if watch:
                # watch-only manager (never joins/beats): the supervisor
                # must not mask its child's death with its own heartbeat
                manager = ElasticManager(host_id=f"sup{rank}",
                                         master=f"127.0.0.1:{master.port}",
                                         ttl=6.0, np=2)
            # the killed host backs off 8s before relaunching — longer than
            # peer staleness detection (TTL 6s + 0.1s poll), so host 0's
            # membership restart is ordered before host 1's beats resume
            # self_member: the watch must only react to PEER staleness —
            # this host's own trainer is monitored by process exit, and its
            # restart gap (preemption save + relaunch import) outlives any
            # sane TTL
            sup = ElasticSupervisor(max_restarts=3,
                                    backoff=8.0 if rank == 1 else 0.5,
                                    backoff_max=10.0, manager=manager,
                                    poll=0.1, stop_grace=20.0,
                                    self_member=f"host{rank}")
            sups[rank] = sup
            codes[rank] = sup.supervise(
                [sys.executable, str(script), d,
                 str(tmp_path / f"out{rank}.json"),
                 str(tmp_path / f"events{rank}.jsonl")], env=env)

        threads = [
            # host 1 dies between prepare and commit of step 3's save
            threading.Thread(target=host,
                             args=(1, "ckpt.commit=1@3:kill", False)),
            threading.Thread(target=host, args=(0, "", True)),
        ]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=420)
                assert not t.is_alive(), "supervisor wedged"
        finally:
            master.stop()

        assert codes == {0: 0, 1: 0}, "a supervisor gave up"
        # both hosts relaunched exactly once, for the right reasons
        assert sups[1].restarts == 1 and sups[1].last_reason == "failure"
        assert sups[0].restarts == 1 and sups[0].last_reason == "membership"
        reg = metrics_mod.default_registry()
        snap = reg.snapshot()
        assert _snapshot_total(snap, "elastic_restarts_total",
                               reason="failure") >= 1
        assert _snapshot_total(snap, "elastic_restarts_total",
                               reason="membership") >= 1

        events = {}
        for rank in (0, 1):
            with open(tmp_path / f"events{rank}.jsonl") as f:
                events[rank] = [json.loads(line) for line in f]
        gen1 = {r: next(e for e in events[r] if e["gen"] == 1)
                for r in (0, 1)}
        # the barrier held: step 3 was never published as a FINAL file on
        # either host — the newest fully-committed step everywhere is 2
        for rank in (0, 1):
            assert gen1[rank]["final_steps"], f"host {rank} lost everything"
            assert max(gen1[rank]["final_steps"]) == 2, \
                f"host {rank} relaunched seeing {gen1[rank]['final_steps']}"
        # the kill landed where advertised: host 1 left a torn prepare tmp
        assert any(f.startswith("ckpt_3.tmp.") for f in gen1[1]["listing"])

        outs = {r: json.load(open(tmp_path / f"out{r}.json"))
                for r in (0, 1)}
        for rank in (0, 1):
            out = outs[rank]
            assert out["gen"] == 1  # the OUTPUT came from the relaunch
            assert out["weights"].keys() == out["ref_weights"].keys()
            for k in out["weights"]:
                assert np.array_equal(np.asarray(out["weights"][k]),
                                      np.asarray(out["ref_weights"][k])), \
                    f"host {rank} {k} diverged after coordinated resume"
            # resume negotiated + loaded, and the relaunched generation's
            # coordinated saves committed again
            m = out["metrics"]
            assert _snapshot_total(m, "checkpoint_loads_total") >= 1
            assert _snapshot_total(m, "ckpt_barrier_commits_total") >= 1
        # both hosts trained the identical tail
        for k in outs[0]["weights"]:
            assert np.array_equal(np.asarray(outs[0]["weights"][k]),
                                  np.asarray(outs[1]["weights"][k]))
