"""Quantization (QAT/PTQ) and ASP 2:4 sparsity tests.

Reference test models: slim quantization unit tests
(`unittests/test_imperative_qat.py`, `test_post_training_quantization_*`)
and the ASP suite (`unittests/asp/test_asp_pruning_1d.py`,
`test_asp_optimize.py`).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.incubate import asp
from paddle_tpu.quantization import (PTQ, QAT, QuantedLinear,
                                     QuantizedInferenceLayer, fake_quant,
                                     kl_threshold)


class TestFakeQuant:
    def test_roundtrip_error_small(self):
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(64,)).astype(np.float32))
        q = fake_quant(x, bits=8)
        err = np.abs(q.numpy() - x.numpy()).max()
        scale = np.abs(x.numpy()).max()
        assert err <= scale / 127 + 1e-6

    def test_straight_through_gradient(self):
        x = paddle.to_tensor(np.linspace(-1, 1, 16).astype(np.float32),
                             stop_gradient=False)
        y = (fake_quant(x, bits=8) ** 2).sum()
        y.backward()
        # STE: d/dx fake_quant = identity, so grad == 2*quant(x) ~ 2x
        np.testing.assert_allclose(x.grad.numpy(), 2 * x.numpy(), atol=0.05)

    def test_per_channel(self):
        rng = np.random.default_rng(1)
        w = rng.normal(size=(4, 8)).astype(np.float32)
        w[:, 3] *= 100  # huge channel must not destroy others' resolution
        q = fake_quant(paddle.to_tensor(w), bits=8, channel_axis=1)
        err = np.abs(q.numpy() - w)
        assert err[:, :3].max() < np.abs(w[:, :3]).max() / 100


class TestQAT:
    def test_swaps_layers_and_trains(self):
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        QAT().quantize(model)
        assert isinstance(model[0], QuantedLinear)
        assert isinstance(model[2], QuantedLinear)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 8)).astype(np.float32)
        y = (x.sum(1, keepdims=True) > 0).astype(np.float32)
        losses = []
        for _ in range(30):
            out = model(paddle.to_tensor(x))
            loss = ((out - paddle.to_tensor(y)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

    def test_qat_close_to_float(self):
        paddle.seed(3)
        model = nn.Linear(8, 4)
        x = paddle.to_tensor(
            np.random.default_rng(2).normal(size=(16, 8)).astype(np.float32))
        ref = model(x).numpy()
        QAT().quantize(parent := nn.Sequential(model))
        out = parent(x).numpy()
        assert np.abs(out - ref).max() < np.abs(ref).max() * 0.05


class TestPTQ:
    def _calib(self, model, n=8):
        rng = np.random.default_rng(0)
        return [paddle.to_tensor(rng.normal(size=(16, 8)).astype(np.float32))
                for _ in range(n)]

    @pytest.mark.parametrize("algo", ["abs_max", "avg", "KL"])
    def test_convert_int8(self, algo):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        batches = self._calib(model)
        ref = model(batches[0]).numpy()
        ptq = PTQ(algo=algo)
        ptq.sample(model, batches)
        ptq.convert(model)
        assert isinstance(model[0], QuantizedInferenceLayer)
        assert model[0].w_int8.dtype == np.int8
        out = model(batches[0]).numpy()
        # int8 weights + clipped activations: small relative error on the
        # calibration data (KL deliberately clips the activation tail, so
        # its bound is looser than pure abs_max)
        tol = 0.25 if algo == "KL" else 0.1
        assert np.abs(out - ref).max() < max(np.abs(ref).max(), 1) * tol

    def test_act_scale_actually_applied(self):
        """The calibrated activation scale must affect inference: data far
        outside the calibration range gets clipped."""
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 4))
        ptq = PTQ(algo="abs_max")
        small = [paddle.to_tensor(0.01 * np.ones((4, 8), np.float32))]
        ptq.sample(model, small)
        ptq.convert(model)
        big = paddle.to_tensor(100.0 * np.ones((4, 8), np.float32))
        out_big = model(big).numpy()
        # with act clipping at ~0.01, the 100x input saturates: output must
        # be far from the unclipped linear response
        w = model[0].dequant_weight().numpy()
        unclipped = 100.0 * np.ones((4, 8)) @ w + np.asarray(model[0].bias.data)
        assert np.abs(out_big).max() < np.abs(unclipped).max() * 0.01

    def test_int8_weights_in_state_dict(self):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 4))
        ptq = PTQ()
        ptq.sample(model, [paddle.to_tensor(
            np.random.default_rng(0).normal(size=(4, 8)).astype(np.float32))])
        ptq.convert(model)
        sd = model.state_dict()
        keys = set(sd.keys())
        assert any("w_int8" in k for k in keys), keys
        assert not any(k.endswith("weight") for k in keys), keys

    def test_kl_threshold_prefers_bulk(self):
        # non-uniform mass near 0 + tiny outlier tail: coarse binning of the
        # bulk costs KL, so the calibrated clip lands below the max range
        hist = np.zeros(512)
        hist[:128] = 1000 * np.exp(-np.arange(128) / 16.0)
        hist[-1] = 1
        t = kl_threshold(hist, bin_width=0.01)
        assert 128 * 0.01 <= t < 512 * 0.01, t


class TestASP:
    def test_create_mask_2_4(self):
        rng = np.random.default_rng(0)
        w = rng.normal(size=(16, 8)).astype(np.float32)
        mask = asp.create_mask(w)
        assert mask.shape == w.shape
        assert asp.check_sparsity(w * mask)
        # exactly half survive
        assert mask.sum() == w.size // 2
        # kept entries are the 2 largest |.| of each group of 4 along dim 0
        col = (w * mask)[:, 0]
        g = np.abs(w[:4, 0])
        kept = np.nonzero(mask[:4, 0])[0]
        assert set(kept) == set(np.argsort(g)[-2:])

    def test_prune_model_and_density(self):
        model = nn.Sequential(nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 4))
        asp.prune_model(model)
        for _, layer in model.named_sublayers(include_self=True):
            if isinstance(layer, nn.Linear):
                assert asp.check_sparsity(layer.weight)
                assert abs(asp.calculate_density(layer.weight) - 0.5) < 1e-6

    def test_optimizer_guarantee_keeps_sparsity(self):
        model = nn.Sequential(nn.Linear(16, 8), nn.ReLU(), nn.Linear(8, 1))
        asp.prune_model(model)
        opt = asp.decorate(
            optimizer.SGD(learning_rate=0.1, parameters=model.parameters()),
            model)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 16)).astype(np.float32)
        y = rng.normal(size=(8, 1)).astype(np.float32)
        for _ in range(5):
            loss = ((model(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        assert asp.check_sparsity(model[0].weight)
        assert asp.check_sparsity(model[2].weight)

    def test_conv_mask_along_reduction_axis(self):
        """Conv [out, in, kh, kw]: each out-filter's in*kh*kw reduction dim
        carries the 2:4 groups (reference reshapes to [out, in*kh*kw])."""
        rng = np.random.default_rng(0)
        w = rng.normal(size=(8, 4, 3, 3)).astype(np.float32)
        mask = asp.create_mask(w)
        flat = mask.reshape(8, -1)  # 36 values per filter
        for row in flat:
            full = np.concatenate([row, np.zeros((-len(row)) % 4)])
            assert (full.reshape(-1, 4).sum(1) <= 2).all()
        assert asp.check_sparsity(w * mask)

    def test_prune_conv_model(self):
        model = nn.Sequential(nn.Conv2D(4, 8, 3), nn.ReLU())
        asp.prune_model(model)
        assert asp.check_sparsity(model[0].weight)
        assert abs(asp.calculate_density(model[0].weight) - 0.5) < 0.05

    def test_excluded_layers(self):
        model = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 8))
        asp.set_excluded_layers(model, ["0.weight"])
        asp.prune_model(model)
        assert asp.calculate_density(model[0].weight) == 1.0
        assert abs(asp.calculate_density(model[1].weight) - 0.5) < 1e-6
        asp.reset_excluded_layers(model)


class TestAsp2D:
    def test_mask_2d_structures(self):
        rng = np.random.default_rng(0)
        w = rng.normal(size=(16, 12)).astype(np.float32)
        for algo in ("mask_2d_greedy", "mask_2d_best"):
            mask = asp.create_mask(w, func_name=algo, n=2, m=4)
            assert mask.shape == w.shape
            assert asp.check_mask_2d(w * mask), algo
            # 2-D structure implies the 1-D row constraint as well
            assert asp.check_mask_1d(w * mask), algo
            # best fills exactly n:m; greedy can strand a few slots but
            # must stay close to (and never exceed) half for 2:4
            if algo == "mask_2d_best":
                assert mask.sum() == w.size // 2, algo
            else:
                assert w.size * 0.4 <= mask.sum() <= w.size // 2, algo

    def test_mask_2d_best_is_blockwise_optimal(self):
        """best = argmax retained |mass| over ALL exact-n:m block patterns
        (greedy's <=n masks are not always extendable to exact-n, so greedy
        can occasionally retain more — same trade as the reference algos)."""
        from paddle_tpu.incubate.asp import (_mask_2d_best_rows,
                                             _valid_2d_patterns)
        rng = np.random.default_rng(1)
        w = rng.normal(size=(4, 4)).astype(np.float32)
        bm = _mask_2d_best_rows(w, 2, 4)
        pats = _valid_2d_patterns(2, 4)
        brute = max(float((np.abs(w) * p).sum()) for p in pats)
        np.testing.assert_allclose(float((np.abs(w) * bm).sum()), brute,
                                   rtol=1e-6)

    def test_unknown_algo_raises(self):
        import pytest as _pytest
        with _pytest.raises(ValueError):
            asp.create_mask(np.ones((4, 4)), func_name="mask_3d")
