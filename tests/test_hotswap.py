"""Zero-downtime checkpoint hot-swap (inference/hotswap.py +
ServingEngine.request_swap): manifest discovery, the canary gate,
between-iteration swap semantics (in-flight requests keep pages),
rollback, the `serving.swap` chaos site, and the swap x preemption
interleaving audit.

fast-sibling: everything here is tier-1-fast (tiny GPT, shared compile
cache); the thread-under-load swap drills live in
tests/test_serving_chaos_e2e.py (slow tier).
"""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.sharded_checkpoint import (
    ShardedCheckpointManager, newest_committed_step)
from paddle_tpu.fault import inject
from paddle_tpu.inference.hotswap import HotSwapManager, default_probe_batch
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.profiler import events
from paddle_tpu.profiler import metrics as metrics_mod


@pytest.fixture(autouse=True)
def _clean_events():
    events.default_event_log().clear()
    inject.reset()
    yield
    inject.reset()
    events.default_event_log().clear()


@pytest.fixture(scope="module", autouse=True)
def _shared_compile_cache():
    """Same shared persistent-compile-cache dir as test_serving.py: every
    test rebuilds the same tiny-model executables, only the first
    construction across the serving test modules pays XLA."""
    from paddle_tpu.framework import flags as flags_mod
    cache = os.path.join(tempfile.gettempdir(), "pt_serving_ccache")
    os.makedirs(cache, exist_ok=True)
    flags_mod.set_flags({"FLAGS_compile_cache_dir": cache})
    yield
    flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})


def _model(seed=0, vocab=512):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=vocab, max_position_embeddings=128,
                    hidden_size=32, num_layers=2, num_heads=2,
                    dropout=0.0, attn_dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m, cfg


def _params(m):
    return {k: p.data for k, p in m.named_parameters()}


def _save(tmpdir, state, step):
    mgr = ShardedCheckpointManager(str(tmpdir), prefix="ckpt",
                                   keep_last_n=10)
    assert mgr.save(state, step=step)


def _amplified(state, factor=50.0):
    """Confidently-wrong weights: same shapes/dtypes, huge logits —
    the canary's perplexity check must reject them."""
    return {k: paddle.to_tensor(
                (np.asarray(v) * factor).astype(np.asarray(v).dtype))
            for k, v in state.items()}


def _swap_events(action=None):
    evs = [e for e in events.recent(200, kind="serving_swap")]
    return [e for e in evs if action is None or e.get("action") == action]


class TestNewestCommittedStep:
    def test_empty_dir_and_min_step_and_skip(self, tmp_path):
        assert newest_committed_step(str(tmp_path)) is None
        m, _ = _model()
        _save(tmp_path, _params(m), 100)
        _save(tmp_path, _params(m), 200)
        step, path = newest_committed_step(str(tmp_path))
        assert step == 200 and path.endswith("ckpt_200")
        # min_step: nothing newer than 200
        assert newest_committed_step(str(tmp_path), min_step=200) is None
        # skip: a blacklisted newest falls back to the next committed one
        step, _ = newest_committed_step(str(tmp_path), skip={200})
        assert step == 100

    def test_torn_step_is_invisible(self, tmp_path):
        """A step dir without a committed manifest (a save that died
        mid-write) must never be offered for a swap."""
        m, _ = _model()
        _save(tmp_path, _params(m), 100)
        os.makedirs(str(tmp_path / "ckpt_200"))  # empty = no manifest
        step, _ = newest_committed_step(str(tmp_path))
        assert step == 100


class TestHotSwap:
    def test_poll_swaps_and_records_metrics(self, tmp_path):
        m, _ = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="hs1")
        _save(tmp_path, _params(m), 100)
        hsm = HotSwapManager(eng, str(tmp_path), poll_s=999, canary=True)
        rec = hsm.poll_once()
        assert rec["outcome"] == "staged"
        # threadless idle engine applies immediately
        assert eng.weights_step == 100 and hsm.current_step == 100
        assert eng.last_swap["pause_s"] >= 0.0
        assert hsm.last_canary["step"] == 100
        actions = [e["action"] for e in _swap_events()]
        assert actions == ["stage", "swap"]
        if metrics_mod.enabled():
            reg = metrics_mod.default_registry()
            vals = {tuple(sorted(v["labels"].items())): v["value"]
                    for v in reg.get("serving_swap_step").snapshot()["values"]}
            assert vals[(("model", "hs1"),)] == 100
        # nothing newer: the next poll is a no-op
        assert hsm.poll_once() is None
        eng.close()

    def test_canary_rejects_and_blacklists_bad_push(self, tmp_path):
        m, _ = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="hs2")
        state = _params(m)
        _save(tmp_path, state, 100)
        hsm = HotSwapManager(eng, str(tmp_path), poll_s=999, canary=True,
                             canary_tol=0.10)
        assert hsm.poll_once()["outcome"] == "staged"
        _save(tmp_path, _amplified(state), 200)
        rec = hsm.poll_once()
        assert rec["outcome"] == "rejected"
        assert rec["canary"]["cand_ppl"] > rec["canary"]["live_ppl"] * 1.1
        # live weights untouched, step blacklisted, poller moves on
        assert eng.weights_step == 100
        assert 200 in hsm.rejected
        assert hsm.poll_once() is None
        ev = _swap_events("reject")
        assert len(ev) == 1 and ev[0]["to_step"] == 200
        assert hsm.stats["rejects"] == 1
        eng.close()

    def test_forced_bad_swap_then_rollback_restores_weights(self, tmp_path):
        """Operator force-push of a rejected step: the post-swap watch
        (post_swap_regressed) flags it and rollback() restores the prior
        step, blacklists the bad one, and greedy decode is bit-identical
        to the pre-swap engine."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="hs3")
        state = _params(m)
        _save(tmp_path, state, 100)
        hsm = HotSwapManager(eng, str(tmp_path), poll_s=999, canary=True)
        hsm.poll_once()
        prompt = [5, 9, 3, 17]
        before = eng.generate(prompt, max_new_tokens=6)["tokens"]

        _save(tmp_path, _amplified(state), 200)
        rec = hsm.try_swap(step=200, force=True)
        assert rec["outcome"] == "staged" and rec["forced"]
        assert eng.weights_step == 200
        assert hsm.vetted is False  # forced swaps still need the watch
        regress = hsm.post_swap_regressed()
        assert regress["regressed"]

        rb = hsm.rollback("canary")
        assert rb == {"rolled_back_step": 200, "restored_step": 100,
                      "reason": "canary"}
        assert eng.weights_step == 100 and hsm.vetted is True
        assert 200 in hsm.rejected
        after = eng.generate(prompt, max_new_tokens=6)["tokens"]
        assert after == before, "rollback changed the greedy tokens"
        ev = _swap_events("rollback")
        assert len(ev) == 1 and ev[0]["severity"] == "warn"
        eng.close()

    def test_post_swap_requests_decode_on_new_weights(self, tmp_path):
        """Determinism across the swap: temperature=0 requests admitted
        entirely post-swap produce exactly the NEW model's reference
        greedy tokens (and pre-swap ones the old model's)."""
        m_old, cfg = _model(seed=0)
        m_new, _ = _model(seed=7)
        eng = ServingEngine(m_old, max_batch=2, max_len=48, page_size=8,
                            name="hs4")
        prompt = [11, 4, 2, 9, 31]
        pre = eng.generate(prompt, max_new_tokens=6)["tokens"]
        ids = paddle.to_tensor(np.asarray([prompt], np.int32))
        ref_old = np.asarray(
            m_old.generate_paged(ids, 6, page_size=8).data)
        assert pre == ref_old[0, len(prompt):].tolist()

        _save(tmp_path, _params(m_new), 300)
        hsm = HotSwapManager(eng, str(tmp_path), poll_s=999, canary=False)
        assert hsm.poll_once()["outcome"] == "staged"
        assert eng.weights_step == 300
        post = eng.generate(prompt, max_new_tokens=6)["tokens"]
        ref_new = np.asarray(
            m_new.generate_paged(ids, 6, page_size=8).data)
        assert post == ref_new[0, len(prompt):].tolist(), \
            "post-swap decode did not run on the swapped-in weights"
        eng.close()

    def test_swap_rejects_shape_mismatch(self, tmp_path):
        m, _ = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="hs5")
        good = _params(m)
        k = next(iter(good))
        bad = dict(good)
        bad[k] = paddle.to_tensor(
            np.zeros((3, 3), np.asarray(good[k]).dtype))
        with pytest.raises(ValueError, match="swap rejected"):
            eng.request_swap(bad)
        assert eng._pending_swap is None
        eng.close()

    def test_fault_site_fails_push_not_weights(self, tmp_path):
        """Chaos `serving.swap`: an armed error lands as outcome=failed
        (with the event trail) and NEVER reaches the live weights;
        repeated failures blacklist the step."""
        m, _ = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="hs6")
        _save(tmp_path, _params(m), 100)
        hsm = HotSwapManager(eng, str(tmp_path), poll_s=999, canary=False)
        inject.configure("serving.swap", times=3)
        for i in range(3):
            rec = hsm.poll_once()
            assert rec["outcome"] == "failed"
            assert eng.weights_step is None  # never swapped
        assert 100 in hsm.rejected  # 3 strikes: stop retrying the push
        assert hsm.poll_once() is None
        ev = _swap_events("fail")
        assert len(ev) == 3 and ev[-1]["blacklisted"]
        inject.reset()
        eng.close()

    def test_probe_batch_shape_and_determinism(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="hs7")
        ids = default_probe_batch(eng)
        assert ids.shape[0] == 2 and 2 <= ids.shape[1] <= 32
        assert ids.min() >= 1 and ids.max() < cfg.vocab_size
        assert np.array_equal(ids, default_probe_batch(eng))
        p1 = eng.run_canary(ids)
        p2 = eng.run_canary(ids)
        assert np.isfinite(p1) and p1 == p2
        eng.close()


class TestSwapPreemptionInterleave:
    def test_preempted_mid_swap_request_resumes_on_new_weights(
            self, tmp_path):
        """The satellite audit: a request preempted while a swap is
        pending resumes (same trace id) and completes on the post-swap
        weights, with zero leaked pages and intact refcounts."""
        m, cfg = _model()
        # pool sized to force a preemption mid-run (see test_serving's
        # pool-exhaustion test: 2 x 24-token sequences on 5 usable pages)
        eng = ServingEngine(m, max_batch=2, max_len=40, page_size=8,
                            num_pages=6, name="hsx")
        rng = np.random.default_rng(17)
        prompts = [rng.integers(1, cfg.vocab_size, (14,)).tolist()
                   for _ in range(2)]
        reqs = [eng.submit(p, max_new_tokens=12) for p in prompts]
        traces = [r.trace_id for r in reqs]
        for _ in range(3):
            eng.step()  # admit + a few decode iterations on old weights

        # stage a swap while both requests are in flight (threadless +
        # pending: it must NOT apply synchronously here...)
        _save(tmp_path, _params(m), 100)
        hsm = HotSwapManager(eng, str(tmp_path), poll_s=999, canary=False)
        rec = hsm.poll_once()
        assert rec["outcome"] == "staged"
        assert eng._pending_swap is not None and eng.weights_step is None

        # ...it lands at the next iteration boundary, in-flight intact
        eng.step()
        assert eng.weights_step == 100
        # pool pressure may have preempted one already; at least one
        # request rode through the swap in place
        assert eng.last_swap["in_flight"] >= 1

        eng.run_until_idle()
        assert eng.stats["preemptions"] >= 1
        assert sum(r.preemptions for r in reqs) >= 1
        for p, r in zip(prompts, reqs):
            out = r.result(timeout=10)
            assert len(out) == 12 and r.state == "done"
            # same weights before/after: the interleaved swap +
            # preemption must not change greedy decode
            ids = paddle.to_tensor(np.asarray([p], np.int32))
            ref = np.asarray(m.generate_paged(ids, 12, page_size=8).data)
            assert out == ref[0, len(p):].tolist()
        assert [r.trace_id for r in reqs] == traces
        # the no-leak audit: every page refcount returned to the pool
        assert eng.allocator.outstanding() == {}
        assert eng.status()["free_pages"] == eng.cache.num_pages - 1
        eng.close()
