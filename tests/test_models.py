"""Model-zoo tests in one place: forward shapes, loss-decreases training,
jit save/load round trips for gpt / bert / ernie / deepfm / wide&deep.

Reference test style: per-model forward+convergence tests under
`/root/reference/python/paddle/fluid/tests/unittests/` (e.g. dygraph model
tests, `test_dist_fleet_ctr.py` for the PS CTR family)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.nn import functional as F


def _ids(rng, vocab, shape):
    return paddle.to_tensor(rng.integers(0, vocab, shape).astype(np.int32))


@pytest.fixture
def ps_client():
    """Local PS pair for the sparse CTR models (reference
    `ps_local_client` pattern)."""
    from paddle_tpu.distributed.ps import PSClient, PSServer
    server = PSServer(0)
    client = PSClient([server.endpoint])
    yield client
    client.stop_servers()


class TestGPT:
    @pytest.mark.slow  # heavy e2e; full-suite only (tier-1 budget)
    def test_forward_shape_and_loss_decreases(self):
        from paddle_tpu.models.gpt import GPT, GPTConfig
        paddle.seed(0)
        cfg = GPTConfig.tiny()
        model = GPT(cfg)
        rng = np.random.default_rng(0)
        ids = _ids(rng, cfg.vocab_size, (2, 16))
        logits = model(ids)
        assert tuple(logits.shape) == (2, 16, cfg.vocab_size)

        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        labels = _ids(rng, cfg.vocab_size, (2, 16))
        losses = []
        for _ in range(8):
            loss = model.loss(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    def test_jit_save_load_roundtrip(self, tmp_path):
        from paddle_tpu.models.gpt import GPT, GPTConfig
        paddle.seed(0)
        cfg = GPTConfig.tiny()
        model = GPT(cfg)
        model.eval()
        rng = np.random.default_rng(1)
        ids_np = rng.integers(0, cfg.vocab_size, (2, 16)).astype(np.int32)
        want = model(paddle.to_tensor(ids_np)).numpy()
        prefix = str(tmp_path / "gpt")
        paddle.jit.save(model, prefix, input_spec=[
            paddle.static.InputSpec([2, 16], "int32")])
        loaded = paddle.jit.load(prefix)
        got = loaded(paddle.to_tensor(ids_np)).numpy()
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


class TestBert:
    def test_forward_shapes_and_mask(self):
        from paddle_tpu.models.bert import Bert, BertConfig
        paddle.seed(0)
        cfg = BertConfig.tiny()
        model = Bert(cfg)
        model.eval()
        rng = np.random.default_rng(0)
        ids = _ids(rng, cfg.vocab_size, (3, 12))
        seq, pooled = model(ids)
        assert tuple(seq.shape) == (3, 12, cfg.hidden_size)
        assert tuple(pooled.shape) == (3, cfg.hidden_size)
        # padding mask changes attention-dependent outputs
        am = np.ones((3, 12), np.float32)
        am[:, 8:] = 0.0
        seq2, _ = model(ids, attention_mask=paddle.to_tensor(am))
        assert not np.allclose(seq.numpy()[:, :8], seq2.numpy()[:, :8])

    @pytest.mark.slow  # heavy e2e; full-suite only (tier-1 budget)
    def test_pretraining_loss_decreases(self):
        from paddle_tpu.models.bert import BertConfig, BertForPretraining
        paddle.seed(0)
        cfg = BertConfig.tiny()
        model = BertForPretraining(cfg)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        rng = np.random.default_rng(0)
        ids = _ids(rng, cfg.vocab_size, (2, 16))
        mlm_labels = _ids(rng, cfg.vocab_size, (2, 16))
        nsp = paddle.to_tensor(np.array([0, 1], np.int32))
        losses = []
        for _ in range(8):
            mlm_logits, nsp_logits = model(ids)
            loss = (F.cross_entropy(mlm_logits, mlm_labels)
                    + F.cross_entropy(nsp_logits, nsp))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses


class TestErnie:
    @pytest.mark.slow  # full pretrain step; the jit roundtrip below stays fast
    def test_forward_and_loss_decreases(self):
        from paddle_tpu.models.ernie import ErnieConfig, ErnieForPretraining
        paddle.seed(0)
        cfg = ErnieConfig.tiny()
        model = ErnieForPretraining(cfg)
        rng = np.random.default_rng(0)
        ids = _ids(rng, cfg.vocab_size, (2, 16))
        logits = model(ids)
        assert tuple(logits.shape) == (2, 16, cfg.vocab_size)
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        labels = _ids(rng, cfg.vocab_size, (2, 16))
        losses = []
        for _ in range(8):
            loss = F.cross_entropy(model(ids), labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    def test_jit_save_load_roundtrip(self, tmp_path):
        from paddle_tpu.models.ernie import Ernie, ErnieConfig
        paddle.seed(0)
        cfg = ErnieConfig.tiny()

        class Cls(nn.Layer):
            def __init__(self):
                super().__init__()
                self.ernie = Ernie(cfg)
                self.head = nn.Linear(cfg.hidden_size, 3)

            def forward(self, ids):
                _, pooled = self.ernie(ids)
                return self.head(pooled)

        model = Cls()
        model.eval()
        rng = np.random.default_rng(2)
        ids_np = rng.integers(0, cfg.vocab_size, (2, 12)).astype(np.int32)
        want = model(paddle.to_tensor(ids_np)).numpy()
        prefix = str(tmp_path / "ernie")
        paddle.jit.save(model, prefix, input_spec=[
            paddle.static.InputSpec([2, 12], "int32")])
        got = paddle.jit.load(prefix)(paddle.to_tensor(ids_np)).numpy()
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


class TestDeepFM:
    def test_forward_shape_and_loss_decreases(self, ps_client):
        from paddle_tpu.models.deepfm import DeepFM
        paddle.seed(0)
        model = DeepFM(num_slots=3, embedding_dim=4, hidden=16,
                       client=ps_client)
        rng = np.random.default_rng(0)
        ids_np = rng.integers(0, 100, (8, 3)).astype(np.int64)
        logit = model(paddle.to_tensor(ids_np))
        assert tuple(logit.shape) == (8, 1)

        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        y = paddle.to_tensor(
            ((ids_np.sum(1) % 2) == 0).astype(np.float32).reshape(-1, 1))
        crit = nn.BCEWithLogitsLoss()
        losses = []
        for _ in range(25):
            loss = crit(model(paddle.to_tensor(ids_np)), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, losses[::8]


class TestWideDeep:
    def test_forward_shape_and_loss_decreases(self, ps_client):
        from paddle_tpu.models.wide_deep import WideDeep
        paddle.seed(0)
        model = WideDeep(num_slots=2, embedding_dim=4, dense_dim=3,
                         hidden=16, client=ps_client)
        rng = np.random.default_rng(0)
        ids_np = rng.integers(0, 100, (8, 2)).astype(np.int64)
        x_np = rng.normal(size=(8, 3)).astype(np.float32)
        logit = model(paddle.to_tensor(ids_np), paddle.to_tensor(x_np))
        assert tuple(logit.shape) == (8, 1)

        opt = optimizer.SGD(learning_rate=0.05,
                            parameters=model.parameters())
        y = paddle.to_tensor(
            ((ids_np.sum(1) % 2) == 0).astype(np.float32).reshape(-1, 1))
        losses = []
        for _ in range(25):
            logit = model(paddle.to_tensor(ids_np), paddle.to_tensor(x_np))
            loss = F.binary_cross_entropy_with_logits(logit, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, losses[::8]
