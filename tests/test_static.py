"""Static-graph mode: Program/Executor/append_backward/inference model.

Mirrors the reference's static tests (e.g.
`python/paddle/fluid/tests/unittests/test_executor_and_use_program_cache.py`
style: build Program, run Executor with feed/fetch, assert numerics).
"""
import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture
def static_mode():
    paddle.seed(0)
    paddle.enable_static()
    yield
    paddle.disable_static()


def _build_mlp():
    main = paddle.static.Program()
    startup = paddle.static.Program()
    with paddle.static.program_guard(main, startup):
        x = paddle.static.data("x", [None, 4], "float32")
        y = paddle.static.data("y", [None, 1], "float32")
        h = paddle.static.nn.fc(x, 8, activation="relu")
        pred = paddle.static.nn.fc(h, 1)
        loss = paddle.mean((pred - y) ** 2)
    return main, startup, x, y, pred, loss


def test_forward_fetch(static_mode):
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [None, 3], "float32")
        out = paddle.exp(x) + 1.0
    exe = paddle.static.Executor()
    xs = np.random.randn(5, 3).astype(np.float32)
    res, = exe.run(main, feed={"x": xs}, fetch_list=[out])
    np.testing.assert_allclose(res, np.exp(xs) + 1.0, rtol=1e-5)


def test_training_converges(static_mode):
    main, startup, x, y, pred, loss = _build_mlp()
    with paddle.static.program_guard(main, startup):
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    exe = paddle.static.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    xs = rng.randn(16, 4).astype(np.float32)
    ys = (xs.sum(1, keepdims=True) * 0.5).astype(np.float32)
    losses = [float(exe.run(main, feed={"x": xs, "y": ys},
                            fetch_list=[loss])[0]) for _ in range(50)]
    assert losses[-1] < losses[0] * 0.2


def test_append_backward_grads(static_mode):
    main = paddle.static.Program()
    with paddle.static.program_guard(main):
        x = paddle.static.data("x", [2, 3], "float32")
        w_t = paddle.ones([3, 3])
        import paddle_tpu.static.nn as snn
        h = snn.fc(x, 3, bias_attr=False)
        loss = paddle.sum(h)
        pairs = paddle.static.append_backward(loss)
    assert len(pairs) == 1
    p, g = pairs[0]
    exe = paddle.static.Executor()
    xs = np.ones((2, 3), np.float32)
    gval, = exe.run(main, feed={"x": xs}, fetch_list=[g])
    # d(sum(x@W))/dW = x^T @ ones = col-sums of x broadcast
    np.testing.assert_allclose(gval, np.full((3, 3), 2.0), rtol=1e-5)


def test_startup_reinitializes(static_mode):
    main, startup, x, y, pred, loss = _build_mlp()
    with paddle.static.program_guard(main, startup):
        paddle.optimizer.SGD(learning_rate=0.5).minimize(loss)
    exe = paddle.static.Executor()
    exe.run(startup)
    scope = paddle.static.global_scope()
    name = next(iter(main.params))
    before = np.asarray(scope.vars[name]).copy()
    xs = np.random.randn(8, 4).astype(np.float32)
    ys = np.random.randn(8, 1).astype(np.float32)
    exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
    after_step = np.asarray(scope.vars[name])
    assert not np.allclose(before, after_step)
    exe.run(startup)  # re-init resets
    np.testing.assert_allclose(np.asarray(scope.vars[name]), before)


def test_save_load_inference_model(static_mode, tmp_path):
    main = paddle.static.Program()
    startup = paddle.static.Program()
    with paddle.static.program_guard(main, startup):
        x = paddle.static.data("x", [4, 6], "float32")
        out = paddle.static.nn.fc(x, 2)
    exe = paddle.static.Executor()
    exe.run(startup)
    xs = np.random.randn(4, 6).astype(np.float32)
    want, = exe.run(main, feed={"x": xs}, fetch_list=[out])
    prefix = str(tmp_path / "model")
    paddle.static.save_inference_model(prefix, [x], [out], exe, program=main)
    prog, feed_names, fetch_names = paddle.static.load_inference_model(prefix, exe)
    got, = exe.run(prog, feed={"x": xs}, fetch_list=fetch_names)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_clone_for_test_drops_optimizer(static_mode):
    main, startup, x, y, pred, loss = _build_mlp()
    with paddle.static.program_guard(main, startup):
        paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
    test_prog = main.clone(for_test=True)
    assert test_prog.optimizer is None and main.optimizer is not None


def test_eager_mode_restored():
    paddle.enable_static()
    paddle.disable_static()
    t = paddle.ones([2, 2]) * 3.0
    assert float(t.numpy().sum()) == 12.0
    assert paddle.in_dynamic_mode()
