"""Completeness-sweep API tests: sparse, text, reader decorators, hub,
cpp_extension, cost model, regularizer, onnx export (SURVEY §2.7 rows)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import reader as rd
from paddle_tpu import sparse


class TestSparse:
    def test_coo_roundtrip(self):
        indices = [[0, 1, 2], [1, 2, 0]]
        values = [1.0, 2.0, 3.0]
        s = sparse.sparse_coo_tensor(indices, values, shape=[3, 3])
        assert s.nnz == 3
        dense = s.to_dense().numpy()
        want = np.zeros((3, 3), np.float32)
        want[0, 1], want[1, 2], want[2, 0] = 1, 2, 3
        np.testing.assert_array_equal(dense, want)

    def test_csr(self):
        s = sparse.sparse_csr_tensor([0, 1, 3], [2, 0, 1], [5.0, 6.0, 7.0],
                                     shape=[2, 3])
        d = s.to_dense().numpy()
        want = np.array([[0, 0, 5], [6, 7, 0]], np.float32)
        np.testing.assert_array_equal(d, want)

    def test_ops(self):
        d = np.array([[1.0, -2], [0, 3]], np.float32)
        s = sparse.to_sparse_coo(paddle.to_tensor(d))
        r = sparse.relu(s).to_dense().numpy()
        np.testing.assert_array_equal(r, np.maximum(d, 0))
        two = sparse.add(s, s).to_dense().numpy()
        np.testing.assert_array_equal(two, 2 * d)

    def test_spmm_grad(self):
        adj = np.array([[0, 1.0], [1.0, 0]], np.float32)
        s = sparse.to_sparse_coo(paddle.to_tensor(adj))
        x = paddle.to_tensor(np.array([[1.0, 2], [3, 4]], np.float32),
                             stop_gradient=False)
        out = sparse.matmul(s, x)
        np.testing.assert_allclose(out.numpy(), adj @ np.asarray(x.data))
        out.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), adj.T @ np.ones((2, 2)))


class TestTextDatasets:
    def test_imdb_synthetic(self):
        ds = paddle.text.Imdb(mode="train")
        doc, label = ds[0]
        assert doc.dtype == np.int64 and label in (0, 1)
        assert len(ds) > 0

    def test_uci_housing(self):
        tr = paddle.text.UCIHousing(mode="train")
        te = paddle.text.UCIHousing(mode="test")
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)
        assert len(tr) > len(te)

    def test_imikolov_windows(self):
        ds = paddle.text.Imikolov(window_size=5)
        assert ds[0].shape == (5,)

    def test_viterbi_decoder(self):
        """Viterbi beats greedy decoding on a chain with transitions."""
        rng = np.random.default_rng(0)
        B, L, N = 2, 6, 4
        pot = rng.normal(size=(B, L, N)).astype(np.float32)
        trans = rng.normal(size=(N, N)).astype(np.float32)
        dec = paddle.text.ViterbiDecoder(trans, include_bos_eos_tag=False)
        scores, path = dec(paddle.to_tensor(pot))
        assert tuple(path.shape) == (B, L)
        # brute force check on batch 0
        import itertools
        best, best_path = -1e30, None
        for seq in itertools.product(range(N), repeat=L):
            sc = pot[0, 0, seq[0]] + sum(
                trans[seq[i - 1], seq[i]] + pot[0, i, seq[i]]
                for i in range(1, L))
            if sc > best:
                best, best_path = sc, seq
        np.testing.assert_allclose(float(scores.numpy()[0]), best, rtol=1e-5)
        np.testing.assert_array_equal(path.numpy()[0], best_path)


class TestReaderDecorators:
    def test_compose_pipeline(self):
        r1 = lambda: iter(range(10))
        r2 = lambda: iter(range(10, 20))
        comp = rd.compose(r1, r2)
        assert next(comp()) == (0, 10)

    def test_shuffle_buffered_firstn(self):
        r = lambda: iter(range(100))
        out = list(rd.firstn(rd.buffered(rd.shuffle(r, 32), 8), 10)())
        assert len(out) == 10 and set(out) <= set(range(100))

    def test_xmap_ordered(self):
        r = lambda: iter(range(20))
        out = list(rd.xmap_readers(lambda x: x * 2, r, 3, 4, order=True)())
        assert out == [x * 2 for x in range(20)]

    def test_cache(self):
        calls = []
        def r():
            calls.append(1)
            yield from range(3)
        c = rd.cache(r)
        assert list(c()) == [0, 1, 2]
        assert list(c()) == [0, 1, 2]
        assert len(calls) == 1


class TestHub:
    def test_local_hubconf(self, tmp_path):
        (tmp_path / "hubconf.py").write_text(
            "def tiny(num_classes=10):\n"
            "    'a tiny model'\n"
            "    from paddle_tpu import nn\n"
            "    return nn.Linear(4, num_classes)\n")
        assert "tiny" in paddle.hub.list(str(tmp_path))
        assert "tiny model" in paddle.hub.help(str(tmp_path), "tiny")
        m = paddle.hub.load(str(tmp_path), "tiny", num_classes=3)
        assert m.weight.shape[1] == 3

    def test_remote_refused(self):
        with pytest.raises(RuntimeError, match="egress"):
            paddle.hub.load("owner/repo", "m", source="github")


class TestCppExtension:
    SRC = r"""
#include <cmath>
extern "C" void square_op(const float* x, float* y, long long n) {
  for (long long i = 0; i < n; ++i) y[i] = x[i] * x[i];
}
extern "C" void square_grad(const float* x, const float* gy, float* gx,
                            long long n) {
  for (long long i = 0; i < n; ++i) gx[i] = 2.0f * x[i] * gy[i];
}
"""

    def test_build_and_autograd(self, tmp_path):
        src = tmp_path / "square.cc"
        src.write_text(self.SRC)
        ext = paddle.utils.cpp_extension.load(
            "square_ext", [str(src)], build_directory=str(tmp_path))
        op = ext.custom_op("square_op", backward_symbol="square_grad")
        x = paddle.to_tensor(np.array([1.0, -2, 3], np.float32),
                             stop_gradient=False)
        y = op(x)
        np.testing.assert_allclose(y.numpy(), [1, 4, 9])
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [2, -4, 6])


class TestCostModelRegularizer:
    def test_cost_callable(self):
        import jax.numpy as jnp, jax
        cm = paddle.cost_model.CostModel()
        f = jax.jit(lambda a: (a @ a).sum())
        ms = cm.profile_callable(f, jnp.ones((64, 64)))
        assert ms > 0

    def test_regularizer_objects(self):
        from paddle_tpu.regularizer import L1Decay, L2Decay
        from paddle_tpu import nn, optimizer
        net = nn.Linear(4, 2)
        opt = optimizer.Momentum(learning_rate=0.1,
                                 parameters=net.parameters(),
                                 weight_decay=L2Decay(1e-4))
        assert opt._weight_decay == pytest.approx(1e-4)
        assert L1Decay(0.01).coeff == pytest.approx(0.01)


class TestOnnxExport:
    def test_writes_onnx_and_stablehlo_artifacts(self, tmp_path):
        """r5: export returns a REAL .onnx (see test_onnx_export.py for
        parity) and still writes the StableHLO Predictor artifact."""
        from paddle_tpu import nn
        net = nn.Linear(4, 2)
        onnx_path = paddle.onnx.export(
            net, str(tmp_path / "m.onnx"),
            input_spec=[paddle.static.InputSpec([2, 4], "float32")])
        assert onnx_path.endswith(".onnx") and os.path.exists(onnx_path)
        assert os.path.exists(str(tmp_path / "m") + ".pdmodel")


from paddle_tpu.io.dataset import Dataset as _Dataset


class _NpDataset(_Dataset):
    """Module-level: spawn workers must pickle the dataset."""

    def __init__(self, n):
        self.x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
        self.y = np.arange(n, dtype=np.int64)

    def __len__(self):
        return len(self.y)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


class _BadDataset(_Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        raise ValueError("boom in worker")


class TestMultiprocessDataLoader:
    def _dataset(self, n=40):
        return _NpDataset(n)

    def test_two_workers_order_and_content(self):
        from paddle_tpu.io import DataLoader
        ds = self._dataset(40)
        dl = DataLoader(ds, batch_size=8, num_workers=2, shuffle=False,
                        use_buffer_reader=False)
        ys = []
        for xb, yb in dl:
            assert tuple(xb.shape) == (8, 4)
            ys.extend(yb.numpy().tolist())
        assert ys == list(range(40))  # order preserved across workers

    def test_matches_single_process(self):
        import numpy as np
        from paddle_tpu.io import DataLoader
        ds = self._dataset(24)
        single = [np.asarray(y.numpy()) for _, y in
                  DataLoader(ds, batch_size=6, num_workers=0, shuffle=False)]
        multi = [np.asarray(y.numpy()) for _, y in
                 DataLoader(ds, batch_size=6, num_workers=2, shuffle=False,
                            use_shared_memory=True)]
        for a, b in zip(single, multi):
            np.testing.assert_array_equal(a, b)

    def test_worker_error_surfaces(self):
        import pytest
        from paddle_tpu.io import DataLoader
        dl = DataLoader(_BadDataset(), batch_size=4, num_workers=1)
        with pytest.raises(RuntimeError, match="boom"):
            list(dl)


class TestSharedTensor:
    def test_share_roundtrip(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.incubate.multiprocessing import share_tensor
        t = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        h = share_tensor(t)
        try:
            np.testing.assert_array_equal(h.numpy(), t.numpy())
        finally:
            h.unlink()

    def test_cross_process(self):
        import numpy as np
        import multiprocessing as mp
        import paddle_tpu as paddle
        from paddle_tpu.incubate.multiprocessing import share_tensor

        t = paddle.to_tensor(np.ones((4,), np.float32) * 7)
        h = share_tensor(t)
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        p = ctx.Process(target=_read_shared, args=(h.name, h.shape, h.dtype, q))
        p.start()
        got = q.get(timeout=60)
        p.join(timeout=30)
        try:
            np.testing.assert_array_equal(got, np.ones((4,), np.float32) * 7)
        finally:
            h.unlink()


def _read_shared(name, shape, dtype, q):
    import os
    os.environ["JAX_PLATFORMS"] = "cpu"
    from paddle_tpu.incubate.multiprocessing import SharedTensor
    q.put(SharedTensor(name, shape, dtype).numpy())


class TestModelZooAdditions:
    @pytest.mark.slow
    def test_ernie_pretraining_step(self):
        from paddle_tpu.models.ernie import (ErnieConfig, ErnieForPretraining,
                                             ernie_mask_tokens)
        from paddle_tpu import optimizer
        paddle.seed(0)
        cfg = ErnieConfig.tiny()
        model = ErnieForPretraining(cfg)
        rng = np.random.default_rng(0)
        ids = rng.integers(5, cfg.vocab_size, (2, 16)).astype(np.int64)
        masked, labels = ernie_mask_tokens(ids, [[(2, 5)], [(0, 3), (8, 10)]],
                                           mask_token_id=3)
        assert (masked[0, 2:5] == 3).all()
        assert (labels[0, :2] == -100).all()
        opt = optimizer.AdamW(learning_rate=1e-3,
                              parameters=model.parameters())
        l0 = None
        for _ in range(5):
            loss = model.loss(paddle.to_tensor(masked),
                              paddle.to_tensor(labels))
            loss.backward()
            opt.step()
            opt.clear_grad()
            l0 = l0 or float(loss)
        assert float(loss) < l0

    def test_deepfm_trains_on_ps(self):
        from paddle_tpu.distributed.ps import PSServer, PSClient
        from paddle_tpu.models.deepfm import DeepFM
        from paddle_tpu import optimizer
        server = PSServer(0)
        client = PSClient([server.endpoint])
        try:
            paddle.seed(0)
            model = DeepFM(num_slots=3, embedding_dim=4, hidden=16,
                           client=client)
            opt = optimizer.Adam(learning_rate=0.01,
                                 parameters=model.parameters())
            rng = np.random.default_rng(0)
            ids = rng.integers(0, 50, (16, 3)).astype(np.int64)
            y = ((ids.sum(1) % 2) == 0).astype(np.float32).reshape(-1, 1)
            losses = []
            for _ in range(25):
                logit = model(paddle.to_tensor(ids))
                loss = paddle.nn.functional.binary_cross_entropy_with_logits(
                    logit, paddle.to_tensor(y))
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
            assert losses[-1] < losses[0], (losses[0], losses[-1])
        finally:
            client.stop_servers()


class TestZooBreadth:
    """Round-2 zoo additions (reference vision/models + text/datasets)."""

    @pytest.mark.slow
    def test_new_vision_models_forward(self):
        from paddle_tpu.vision import models as M
        paddle.seed(0)
        x = paddle.to_tensor(np.random.default_rng(0).normal(
            size=(2, 3, 64, 64)).astype(np.float32))
        for fn in (lambda: M.googlenet(num_classes=7),
                   lambda: M.shufflenet_v2_x0_25(num_classes=7),
                   lambda: M.densenet121(num_classes=7, growth_rate=8),
                   lambda: M.squeezenet1_1(num_classes=7)):
            m = fn()
            m.eval()
            assert tuple(m(x).shape) == (2, 7)

    @pytest.mark.slow  # heavy e2e; full-suite only (tier-1 budget)
    def test_googlenet_train_returns_aux_heads(self):
        from paddle_tpu.vision import models as M
        paddle.seed(0)
        g = M.googlenet(num_classes=5)
        g.train()
        x = paddle.to_tensor(np.random.default_rng(1).normal(
            size=(2, 3, 64, 64)).astype(np.float32))
        out, a1, a2 = g(x)
        assert tuple(out.shape) == tuple(a1.shape) == tuple(a2.shape) == (2, 5)

    def test_wmt_datasets(self):
        from paddle_tpu.text import WMT14, WMT16
        ds = WMT14(mode="train")
        src, trg, trg_next = ds[3]
        assert trg[0] == 0 and trg_next[-1] == 1  # <s> ... / ... <e>
        assert len(trg) == len(trg_next)
        assert len(WMT16(mode="test")) > 0

    def test_flowers_voc_require_local_files(self):
        from paddle_tpu.vision.datasets import Flowers, VOC2012
        with pytest.raises(ValueError, match="data_file"):
            Flowers()
        with pytest.raises(ValueError, match="data_file"):
            VOC2012()
        with pytest.raises((ValueError, RuntimeError)):
            Flowers(download=True)

    def test_dataset_folder_and_image_folder(self, tmp_path):
        from PIL import Image
        from paddle_tpu.vision.datasets import DatasetFolder, ImageFolder
        for cls in ("ant", "bee"):
            os.makedirs(tmp_path / cls)
            for i in range(2):
                Image.fromarray(
                    np.full((8, 8, 3), 50 + i, np.uint8)).save(
                    str(tmp_path / cls / f"{i}.png"))
        ds = DatasetFolder(str(tmp_path))
        assert ds.classes == ["ant", "bee"] and len(ds) == 4
        img, y = ds[3]
        assert img.shape == (8, 8, 3) and int(y) == 1
        flat = ImageFolder(str(tmp_path))
        assert len(flat) == 4 and flat[0][0].shape == (8, 8, 3)
        with pytest.raises(ValueError, match="exactly one"):
            DatasetFolder(str(tmp_path), extensions=(".png",),
                          is_valid_file=lambda p: True)
        empty = tmp_path / "empty"
        os.makedirs(empty)
        with pytest.raises(ValueError, match="no class directories"):
            DatasetFolder(str(empty))


class TestSparseExtended:
    """Round-2 sparse op set (reference phi/kernels/sparse/ activation +
    elementwise + SDDMM + softmax families)."""

    def _mat(self, seed=0, shape=(4, 6)):
        rng = np.random.default_rng(seed)
        return (rng.normal(size=shape).astype(np.float32)
                * (rng.random(shape) > 0.5))

    def test_unary_family_matches_dense(self):
        from paddle_tpu import sparse
        d = self._mat()
        x = sparse.to_sparse_coo(d)
        for name, ref in (("tanh", np.tanh), ("sin", np.sin),
                          ("expm1", np.expm1),
                          ("square", np.square), ("neg", np.negative)):
            got = getattr(sparse, name)(x).to_dense().numpy()
            np.testing.assert_allclose(got, ref(d), rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(
            sparse.pow(x, 2).to_dense().numpy(), d ** 2, rtol=1e-6)

    def test_elementwise_and_transpose(self):
        from paddle_tpu import sparse
        d, m = self._mat(0), self._mat(1)
        x, z = sparse.to_sparse_coo(d), sparse.to_sparse_coo(m)
        np.testing.assert_allclose(
            sparse.subtract(x, z).to_dense().numpy(), d - m, rtol=1e-6)
        np.testing.assert_allclose(
            sparse.multiply(x, z).to_dense().numpy(), d * m,
            rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(
            sparse.divide(x, 4.0).to_dense().numpy(), d / 4.0, rtol=1e-6)
        np.testing.assert_allclose(
            sparse.transpose(x).to_dense().numpy(), d.T)

    def test_masked_matmul_never_dense(self):
        from paddle_tpu import sparse
        rng = np.random.default_rng(2)
        a = rng.normal(size=(4, 3)).astype(np.float32)
        b = rng.normal(size=(3, 6)).astype(np.float32)
        mask_d = self._mat(3)
        mask = sparse.to_sparse_coo(mask_d)
        out = sparse.masked_matmul(paddle.to_tensor(a),
                                   paddle.to_tensor(b), mask)
        ref = (a @ b) * (mask_d != 0)
        np.testing.assert_allclose(out.to_dense().numpy(), ref,
                                   rtol=1e-4, atol=1e-5)
        assert out.nnz == mask.nnz  # result keeps the mask's pattern

    def test_sparse_softmax_normalizes_stored_entries(self):
        from paddle_tpu import sparse
        d = self._mat(4)
        sm = sparse.softmax(sparse.to_sparse_coo(d)).to_dense().numpy()
        for r in range(d.shape[0]):
            nz = d[r] != 0
            if nz.any():
                np.testing.assert_allclose(sm[r, nz].sum(), 1.0, rtol=1e-5)
                assert (sm[r, ~nz] == 0).all()  # implicit zeros excluded
        # CSR round-trip path stays sparse end-to-end
        smc = sparse.softmax(sparse.to_sparse_csr(d)).to_dense().numpy()
        np.testing.assert_allclose(smc, sm, rtol=1e-6)
        with pytest.raises(ValueError, match="last axis"):
            sparse.softmax(sparse.to_sparse_coo(d), axis=0)

    def test_sparse_multiply_edge_cases(self):
        from paddle_tpu import sparse
        d = self._mat(5)
        x = sparse.to_sparse_coo(d)
        z = sparse.to_sparse_coo(np.zeros_like(d))
        assert np.allclose(sparse.multiply(x, z).to_dense().numpy(), 0)
        assert np.allclose(sparse.multiply(z, x).to_dense().numpy(), 0)
        # adjacency-scale coordinates: int64 key matching, no collisions
        idx = np.array([[50000, 99998], [99999, 50000]])
        a = sparse.sparse_coo_tensor(idx, np.array([2.0, 3.0], np.float32),
                                     shape=(100000, 100000))
        b = sparse.sparse_coo_tensor(idx, np.array([5.0, 7.0], np.float32),
                                     shape=(100000, 100000))
        got = sorted(np.asarray(
            sparse.multiply(a, b).values().numpy()).tolist())
        assert got == [10.0, 21.0], got
