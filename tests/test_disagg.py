"""Disaggregated prefill/decode pipeline (inference/disagg.py): prefill
workers own a private single-slot cache on their own device, produce KV
pages into a handoff queue, and the decode engine drains the queue at
the top of its own step (ALL cache mutation on the decode thread — the
``handoff_source`` peek/pop protocol).  Greedy tokens must stay
bit-exact vs the co-located engine in sync, threaded, and TP-composed
modes; preemption requeues to the PIPELINE (re-prefill by a worker);
the ``serving_handoff_*`` / per-stage occupancy metric families feed
the SLO plane.

fast-sibling: tier-1-fast tiny GPT; disagg-at-scale A/B numbers live
in bench.py's gpt2_decode ``disagg`` block.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu.inference.disagg import DisaggPipeline, KVHandoff
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.profiler import events
from paddle_tpu.profiler import metrics as metrics_mod


@pytest.fixture(autouse=True)
def _clean_events():
    events.default_event_log().clear()
    yield
    events.default_event_log().clear()


@pytest.fixture(scope="module", autouse=True)
def _shared_compile_cache():
    """Shares test_serving.py's persistent-compile-cache dir — the
    decode engine here compiles the same tiny-model executables."""
    import os
    import tempfile
    from paddle_tpu.framework import flags as flags_mod
    cache = os.path.join(tempfile.gettempdir(), "pt_serving_ccache")
    os.makedirs(cache, exist_ok=True)
    flags_mod.set_flags({"FLAGS_compile_cache_dir": cache})
    yield
    flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})


def _model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, max_position_embeddings=128,
                    hidden_size=32, num_layers=2, num_heads=2,
                    dropout=0.0, attn_dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m, cfg


def _ref(m, prompt, n, page_size=8):
    # disarm for the reference run: generate_paged on a TP-armed model
    # (the TP-composed test) would shard instead of running single-chip
    mesh, axis = m.tp_mesh(), getattr(m, "_tp_axis", "tp")
    m.set_tp_mesh(None)
    try:
        ids = paddle.to_tensor(np.asarray([prompt], np.int32))
        out = np.asarray(m.generate_paged(ids, n,
                                          page_size=page_size).data)
    finally:
        m.set_tp_mesh(mesh, axis)
    return out[0, len(prompt):].tolist()


_PROMPTS = [[5, 7, 11, 13], [3, 1, 4, 1, 5, 9, 2, 6], [42] * 17, [9, 9]]


class TestDisaggParity:
    def test_sync_pipeline_matches_colocated_tokens(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=4, max_len=64, page_size=8,
                            name="dis")
        pipe = DisaggPipeline(eng, num_workers=2)
        reqs = [pipe.submit(p, max_new_tokens=10) for p in _PROMPTS]
        pipe.run_until_idle()
        for p, r in zip(_PROMPTS, reqs):
            assert r.result(timeout=5) == _ref(m, p, 10), \
                "disagg handoff changed the greedy tokens"
        assert eng.stats["handoffs"] == len(_PROMPTS)
        assert eng.stats["prefills"] == 0  # every prefill ran on a worker
        st = pipe.status()
        assert st["handoffs"] == len(_PROMPTS)
        assert st["worker_prefills"] == len(_PROMPTS)
        assert st["queue_depth"] == 0 and st["handoff_depth"] == 0
        # pages fully recycled on the DECODE pools
        assert eng.status()["free_pages"] == eng.cache.num_pages - 1
        pipe.close()

    @pytest.mark.slow
    @pytest.mark.skipif(len(jax.devices()) < 2,
                        reason="TP-composed disagg needs >=2 devices")
    def test_tp_composed_pipeline_matches_single_chip(self):
        """Disagg over a TP decode mesh: prefill workers land on
        non-mesh devices, payloads re-place onto the replicated mesh
        sharding at inject, tokens stay bit-exact.  Slow: composes the
        two heavy compile sets (mesh decode programs + sharded-pool
        inject); each half is pinned tier-1-fast on its own.

        fast-sibling: tests/test_tp_decode.py, tests/test_disagg.py
        (sync-pipeline parity stays tier-1-fast)."""
        from jax.sharding import Mesh
        m, cfg = _model()
        mesh = Mesh(np.array(jax.devices()[:2]), ("tp",))
        eng = ServingEngine(m, max_batch=4, max_len=64, page_size=8,
                            name="distp", mesh=mesh)
        pipe = DisaggPipeline(eng, num_workers=1)
        if len(jax.devices()) > 2:  # a spare device exists off the mesh
            mesh_devs = {str(d) for d in mesh.devices.flat}
            for d in pipe.status()["stages"]["prefill"]["devices"]:
                assert d not in mesh_devs
        reqs = [pipe.submit(p, max_new_tokens=10) for p in _PROMPTS]
        pipe.run_until_idle()
        for p, r in zip(_PROMPTS, reqs):
            assert r.result(timeout=5) == _ref(m, p, 10)
        assert eng.stats["handoffs"] == len(_PROMPTS)
        pipe.close()

    def test_threaded_pipeline_matches_and_stops_clean(self):
        """Worker threads + engine loop: handoffs drain INSIDE the
        decode thread's step (the drainer-thread race regression)."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=4, max_len=64, page_size=8,
                            name="thr")
        pipe = DisaggPipeline(eng, num_workers=2)
        pipe.start(poll_s=0.002)
        reqs = [pipe.submit(p, max_new_tokens=10) for p in _PROMPTS]
        outs = [r.result(timeout=60) for r in reqs]
        pipe.close()
        for p, out in zip(_PROMPTS, outs):
            assert out == _ref(m, p, 10)
        assert eng._closed


class TestDisaggLifecycle:
    def test_preemption_requeues_to_pipeline_and_reprefills(self):
        """Pool exhaustion on the DECODE engine: the victim goes back
        to the pipeline queue (on_preempt_requeue hook), a worker
        re-prefills prompt+generated, and tokens stay exact."""
        m, cfg = _model()
        rng = np.random.default_rng(17)
        prompts = [rng.integers(1, cfg.vocab_size, (14,)).tolist()
                   for _ in range(2)]
        eng = ServingEngine(m, max_batch=2, max_len=40, page_size=8,
                            num_pages=6, name="dispre")
        pipe = DisaggPipeline(eng, num_workers=1)
        reqs = [pipe.submit(p, max_new_tokens=12) for p in prompts]
        pipe.run_until_idle()
        assert eng.stats["preemptions"] >= 1
        assert eng.stats["handoffs"] >= len(prompts) + 1  # re-prefill handoff
        for p, r in zip(prompts, reqs):
            out = r.result(timeout=5)
            assert len(out) == 12 and out == _ref(m, p, 12), \
                "preempt->re-prefill through the pipeline changed tokens"
        pipe.close()

    def test_finished_at_prefill_never_hands_off(self):
        """max_new_tokens=1 finishes inside the worker (first token is
        the last): no KV payload crosses stages for it."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=64, page_size=8,
                            name="dis1")
        pipe = DisaggPipeline(eng, num_workers=1)
        r = pipe.submit([4, 5, 6], max_new_tokens=1)
        pipe.run_until_idle()
        assert r.result(timeout=5) == _ref(m, [4, 5, 6], 1)
        assert eng.stats["handoffs"] == 0
        pipe.close()

    def test_close_fails_queued_requests_loudly(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=64, page_size=8,
                            name="discl")
        pipe = DisaggPipeline(eng, num_workers=1)
        req = pipe.submit([1, 2, 3], max_new_tokens=4)
        pipe.close()  # never stepped: request still queued at the pipeline
        with pytest.raises(RuntimeError, match="pipeline closed"):
            req.result(timeout=5)
        assert eng._closed

    def test_handoff_payload_is_pow2_bucketed(self):
        """Payload page-count pads to a power of two so the inject jit
        compiles once per bucket, not once per sequence length."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=64, page_size=8,
                            name="dispad")
        pipe = DisaggPipeline(eng, num_workers=1)
        captured = []
        orig = pipe._enqueue_handoff

        def spy(h):
            captured.append(h)
            orig(h)

        pipe._enqueue_handoff = spy
        r = pipe.submit(list(range(1, 18)), max_new_tokens=2)  # 3 pages
        pipe.run_until_idle()
        assert r.result(timeout=5) == _ref(m, list(range(1, 18)), 2)
        assert len(captured) == 1
        h = captured[0]
        assert isinstance(h, KVHandoff)
        assert h.k_payload[0].shape[0] == 4  # 3 pages -> pow2 bucket 4
        assert h.nbytes > 0
        pipe.close()


class TestDisaggObservability:
    def test_handoff_and_occupancy_metric_families(self):
        m, cfg = _model()
        reg = metrics_mod.default_registry()
        eng = ServingEngine(m, max_batch=4, max_len=64, page_size=8,
                            name="disobs")
        pipe = DisaggPipeline(eng, num_workers=2)
        reqs = [pipe.submit(p, max_new_tokens=4) for p in _PROMPTS]
        pipe.run_until_idle()
        for r in reqs:
            r.result(timeout=5)
        wait = [v for v in reg.get("serving_handoff_wait_seconds")
                .snapshot()["values"]
                if v["labels"].get("model") == "disobs"]
        assert wait and wait[0]["count"] == len(_PROMPTS)
        assert reg.get("serving_handoff_bytes_total").value(
            model="disobs") > 0
        assert reg.get("serving_handoff_depth").value(
            model="disobs") == 0  # drained
        occ = reg.get("serving_stage_occupancy")
        # published for both stages at least once
        stages = {v["labels"].get("stage")
                  for v in occ.snapshot()["values"]
                  if v["labels"].get("model") == "disobs"}
        assert stages == {"prefill", "decode"}
        # handoff_wait wired into the SLO plane's signal set
        from paddle_tpu.profiler.slo import SIGNALS
        assert "handoff_wait" in SIGNALS
        pipe.close()

    def test_ttft_attributed_to_worker_prefill(self):
        """TTFT lands when the WORKER emits the first token (before the
        handoff), labelled with the engine decode path."""
        m, cfg = _model()
        reg = metrics_mod.default_registry()
        eng = ServingEngine(m, max_batch=2, max_len=64, page_size=8,
                            name="disttft")
        pipe = DisaggPipeline(eng, num_workers=1)
        reqs = [pipe.submit(p, max_new_tokens=3) for p in _PROMPTS[:2]]
        pipe.run_until_idle()
        for r in reqs:
            r.result(timeout=5)
            assert r.ttft_s is not None and r.ttft_s >= 0
        ttft = [v for v in reg.get("serving_ttft_seconds")
                .snapshot()["values"]
                if v["labels"].get("model") == "disttft"]
        assert ttft and ttft[0]["count"] == 2
        assert ttft[0]["labels"]["path"] == eng.decode_mode
        pipe.close()


class TestDisaggWorkerFaults:
    """Prefill-worker fault tolerance (PR 20): a dead/wedged worker is
    retired, its request rerouted with the ORIGINAL trace id under a
    bounded attempt count, a replacement respawned into the slot (the
    PR-3 DataLoader respawn contract), and with no survivor the decode
    engine's colocated prefill is the last resort.

    fast-sibling of tests/test_disagg_chaos_e2e.py (live-traffic drill)."""

    def test_worker_error_requeues_respawns_and_tokens_survive(self):
        from paddle_tpu import fault
        fault.reset()
        m, cfg = _model()
        reg = metrics_mod.default_registry()
        requeued0 = reg.get("disagg_requeue_total").value(
            reason="worker_error")
        eng = ServingEngine(m, max_batch=4, max_len=64, page_size=8,
                            name="disflt")
        pipe = DisaggPipeline(eng, num_workers=2)
        fault.configure("disagg.prefill", times=1)  # first dispatch dies
        reqs = [pipe.submit(p, max_new_tokens=8) for p in _PROMPTS]
        tids = [r.trace_id for r in reqs]
        pipe.run_until_idle()
        for p, r, tid in zip(_PROMPTS, reqs, tids):
            assert r.result(timeout=5) == _ref(m, p, 8)
            assert r.trace_id == tid, "reroute must keep the trace id"
        assert reg.get("disagg_requeue_total").value(
            reason="worker_error") == requeued0 + 1
        st = pipe.status()["stages"]["prefill"]
        assert st["restarts"] and sum(st["restarts"].values()) == 1
        assert st["alive"] == 2  # the slot came back
        ev = events.recent(kind="disagg_worker_restart")
        assert ev and ev[-1]["cause"] == "worker_error"
        assert ev[-1]["respawned"] is True
        assert eng.status()["free_pages"] == eng.cache.num_pages - 1
        fault.reset()
        pipe.close()

    def test_attempt_exhaustion_fails_the_request_loudly(self):
        from paddle_tpu import fault
        fault.reset()
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=64, page_size=8,
                            name="disexh")
        pipe = DisaggPipeline(eng, num_workers=1, max_attempts=1,
                              max_worker_restarts=0)
        fault.configure("disagg.prefill", times=100)
        req = pipe.submit([7, 8, 9], max_new_tokens=4)
        pipe.run_until_idle()
        with pytest.raises(RuntimeError, match="gave up after 1 attempt"):
            req.result(timeout=5)
        fault.reset()
        pipe.close()

    def test_colocated_fallback_when_no_worker_survives(self):
        from paddle_tpu import fault
        fault.reset()
        m, cfg = _model()
        reg = metrics_mod.default_registry()
        colo0 = reg.get("disagg_requeue_total").value(reason="colocated")
        eng = ServingEngine(m, max_batch=4, max_len=64, page_size=8,
                            name="discolo")
        pipe = DisaggPipeline(eng, num_workers=1, max_worker_restarts=0)
        fault.configure("disagg.prefill", times=1)
        reqs = [pipe.submit(p, max_new_tokens=6) for p in _PROMPTS[:2]]
        tids = [r.trace_id for r in reqs]
        pipe.run_until_idle()
        for p, r, tid in zip(_PROMPTS[:2], reqs, tids):
            assert r.result(timeout=5) == _ref(m, p, 6)
            assert r.trace_id == tid
        # the only worker died and its slot is disabled: every prefill
        # ran colocated on the decode engine
        assert eng.stats["prefills"] == 2
        assert pipe.status()["stages"]["prefill"]["alive"] == 0
        assert reg.get("disagg_requeue_total").value(
            reason="colocated") > colo0
        ev = events.recent(kind="disagg_worker_restart")
        assert ev and ev[-1]["respawned"] is False  # cap 0: disabled
        fault.reset()
        pipe.close()

    def test_silent_worker_reaped_by_heartbeat_ttl(self):
        import time as _time
        m, cfg = _model()
        reg = metrics_mod.default_registry()
        dead0 = reg.get("disagg_requeue_total").value(reason="worker_dead")
        eng = ServingEngine(m, max_batch=2, max_len=64, page_size=8,
                            name="disttl")
        pipe = DisaggPipeline(eng, num_workers=2, worker_ttl_s=0.05)
        req = pipe.submit(_PROMPTS[0], max_new_tokens=6)
        tid = req.trace_id
        w = pipe.workers[0]
        with pipe._lock:  # simulate a wedged dispatch: busy, never beats
            pipe._queue.clear()
            w.busy = True
            w.current = req
            pipe._attempts[req.rid] = 1
        w.last_beat = _time.monotonic() - 1.0
        pipe._reap_dead_workers()  # the decode side's _handoff_peek tick
        assert w.retired and not w.alive
        assert pipe.workers[0] is not w  # replacement in the slot
        assert reg.get("disagg_requeue_total").value(
            reason="worker_dead") == dead0 + 1
        ev = events.recent(kind="disagg_worker_restart")
        assert ev and ev[-1]["cause"] == "worker_dead"
        pipe.run_until_idle()
        assert req.result(timeout=5) == _ref(m, _PROMPTS[0], 6)
        assert req.trace_id == tid
        pipe.close()

    def test_late_result_from_reaped_worker_is_dropped(self):
        """A worker retired mid-prefill must not land its stale handoff:
        the request was already requeued — running it twice would decode
        a duplicate (the double-run race)."""
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=64, page_size=8,
                            name="dislate")
        pipe = DisaggPipeline(eng, num_workers=1)
        req = pipe.submit([1, 2, 3], max_new_tokens=2)
        w = pipe.workers[0]
        with pipe._lock:
            pipe._queue.clear()
            w.retired = True
        assert pipe._finish_dispatch(w, req, None) is False
        with pipe._lock:
            assert not pipe._handoffs
        pipe.close()
