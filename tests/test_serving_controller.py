"""SLO-driven serving policies (distributed/fleet/controller.py) +
budget-based degradation (inference/governor.py): wedge-watchdog
restart with confirm-streak debounce and cooldown, shed/un-shed on
sustained breach, post-swap canary/SLO rollback with the max-rollbacks
halt breaker, the MemoryGovernor shrink->suspend ladder, and the
engine-side actuators (queue cap, suspend, pool shrink).

These are the fast tier-1 siblings of the slow chaos e2e in
tests/test_serving_chaos_e2e.py.
"""
import os
import tempfile
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.controller import FleetController
from paddle_tpu.inference.governor import MemoryGovernor
from paddle_tpu.inference.serving import EngineSuspended, ServingEngine
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.profiler import events


@pytest.fixture(autouse=True)
def _clean_events():
    events.default_event_log().clear()
    yield
    events.default_event_log().clear()


@pytest.fixture(scope="module", autouse=True)
def _shared_compile_cache():
    """Shared persistent-compile-cache dir (see test_serving.py) for the
    real-engine governor/actuator tests below."""
    from paddle_tpu.framework import flags as flags_mod
    cache = os.path.join(tempfile.gettempdir(), "pt_serving_ccache")
    os.makedirs(cache, exist_ok=True)
    flags_mod.set_flags({"FLAGS_compile_cache_dir": cache})
    yield
    flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})


# -- scripted engine/manager doubles (the policy tests drive evidence,
# -- not XLA) ----------------------------------------------------------------
class _FakeSLO:
    def __init__(self):
        self.breaches = {}

    def breached(self):
        return dict(self.breaches)


class _FakeHotswap:
    def __init__(self):
        self.vetted = True
        self.halted = False
        self.swapped_ts = None
        self.current_step = -1
        self.regress = None
        self.calls = []

    def post_swap_regressed(self):
        return self.regress

    def rollback(self, reason):
        self.calls.append(("rollback", reason))
        self.vetted = True
        self.swapped_ts = None

    def halt(self, reason):
        self.calls.append(("halt", reason))
        self.halted = True


class _FakeEngine:
    def __init__(self, name="gpt"):
        self.name = name
        self.priority = 0
        self._closed = False
        self.slo = _FakeSLO()
        self.hotswap = _FakeHotswap()
        self.queue_limit = None
        self.is_wedged = False
        self.restarts = []
        self.restart_error = None

    def wedged(self, stall_after=None):
        return self.is_wedged

    def last_progress_age(self):
        return 12.0 if self.is_wedged else 0.0

    def queue_depth(self):
        return 3

    def set_queue_limit(self, limit, term=None):
        self.queue_limit = limit

    def restart(self, reason="wedged", term=None):
        if self.restart_error is not None:
            raise self.restart_error
        self.restarts.append(reason)
        self.is_wedged = False
        return {"requeued": 1, "leaked_pages": 0, "restarted_thread": True}


class _Agg:
    def __init__(self):
        self._straggling = []
        self.straggler_factor = 2.0
        self.last = {}

    def straggling(self):
        return list(self._straggling)


def _ctl(engines, **kw):
    agg = _Agg()
    kw.setdefault("confirm_windows", 3)
    kw.setdefault("readmit_after_s", 9999)
    kw.setdefault("wedge_windows", 2)
    kw.setdefault("slo_windows", 2)
    kw.setdefault("shed_queue_cap", 4)
    kw.setdefault("restart_cooldown_s", 9999.0)
    kw.setdefault("swap_observe_s", 9999.0)
    kw.setdefault("max_swap_rollbacks", 1)
    ctl = FleetController(agg, None, world_size=2,
                          serving_provider=lambda: list(engines), **kw)
    return ctl, agg


def _tick(ctl, agg):
    ctl.on_collect(agg.last)


def _decisions(policy=None):
    out = [e for e in events.recent(200, kind="controller_decision")
           if e.get("action") != "relaunch_observed"]
    return [e for e in out if policy is None or e.get("policy") == policy]


class TestWedgeWatchdog:
    def test_confirm_streak_then_restart_then_cooldown(self):
        eng = _FakeEngine()
        ctl, agg = _ctl([eng])
        eng.is_wedged = True
        _tick(ctl, agg)                       # streak 1 of 2: no action
        assert eng.restarts == [] and _decisions("serving_restart") == []
        _tick(ctl, agg)                       # confirmed: restart
        assert eng.restarts == ["wedged"]
        d = _decisions("serving_restart")
        assert len(d) == 1 and d[0]["outcome"] == "applied"
        assert d[0]["action"] == "restart" and d[0]["target"] == eng.name
        # wedged again immediately: cooldown holds the trigger
        eng.is_wedged = True
        _tick(ctl, agg)
        _tick(ctl, agg)
        assert len(eng.restarts) == 1
        assert ctl.status()["serving"]["wedge_streaks"][eng.name] >= 2

    def test_recovery_clears_the_streak(self):
        eng = _FakeEngine()
        ctl, agg = _ctl([eng])
        eng.is_wedged = True
        _tick(ctl, agg)
        eng.is_wedged = False
        _tick(ctl, agg)                       # healthy window resets
        eng.is_wedged = True
        _tick(ctl, agg)                       # streak back to 1
        assert eng.restarts == []

    def test_failed_restart_is_a_failed_decision_without_cooldown(self):
        eng = _FakeEngine()
        eng.restart_error = RuntimeError("decode loop did not stop")
        ctl, agg = _ctl([eng])
        eng.is_wedged = True
        with pytest.warns(UserWarning, match="could not actuate"):
            _tick(ctl, agg)
            _tick(ctl, agg)
        d = _decisions("serving_restart")
        assert d and d[-1]["outcome"] == "failed"
        # no cooldown on failure: the next confirmed tick retries
        eng.restart_error = None
        _tick(ctl, agg)
        assert eng.restarts == ["wedged"]

    def test_one_sick_engine_does_not_mute_the_others(self):
        bad, good = _FakeEngine("bad"), _FakeEngine("good")

        def _boom(*a, **k):
            raise RuntimeError("boom")
        bad.wedged = _boom  # blows up inside the policy tick
        ctl, agg = _ctl([bad, good])
        good.is_wedged = True
        with pytest.warns(UserWarning, match="serving policy tick"):
            _tick(ctl, agg)
            _tick(ctl, agg)
        assert good.restarts == ["wedged"]


class TestSheddingPolicy:
    def test_sustained_breach_sheds_and_recovery_unsheds(self):
        eng = _FakeEngine()
        ctl, agg = _ctl([eng])
        eng.slo.breaches = {"ttft": {"value": 0.9}}
        _tick(ctl, agg)
        assert eng.queue_limit is None        # streak 1 of 2
        _tick(ctl, agg)
        assert eng.queue_limit == 4           # shed at the configured cap
        d = _decisions("serving_shed")
        assert d[-1]["action"] == "shed"
        assert d[-1]["evidence"]["breached"] == ["ttft"]
        assert ctl.status()["serving"]["shed"] == [eng.name]
        # two clean windows: un-shed
        eng.slo.breaches = {}
        _tick(ctl, agg)
        assert eng.queue_limit == 4
        _tick(ctl, agg)
        assert eng.queue_limit is None
        assert _decisions("serving_shed")[-1]["action"] == "unshed"
        assert ctl.status()["serving"]["shed"] == []

    def test_non_admission_signals_do_not_shed(self):
        """tpot/e2e breaches are decode-side — a queue cap cannot
        relieve them, so the shed policy must ignore them."""
        eng = _FakeEngine()
        ctl, agg = _ctl([eng])
        eng.slo.breaches = {"tpot": {}, "e2e": {}}
        for _ in range(4):
            _tick(ctl, agg)
        assert eng.queue_limit is None
        assert _decisions("serving_shed") == []


class TestSwapRollbackPolicy:
    def _swapped(self, eng, step=200):
        eng.hotswap.vetted = False
        eng.hotswap.swapped_ts = time.time()
        eng.hotswap.current_step = step

    def test_canary_regression_rolls_back(self):
        eng = _FakeEngine()
        ctl, agg = _ctl([eng])
        self._swapped(eng)
        eng.hotswap.regress = {"regressed": True, "live_ppl": 9000.0,
                               "baseline_ppl": 500.0, "tol": 0.1}
        _tick(ctl, agg)
        assert eng.hotswap.calls == [("rollback", "canary")]
        d = _decisions("serving_swap_rollback")
        assert d[-1]["outcome"] == "applied"
        assert d[-1]["evidence"]["reason"] == "canary"
        assert d[-1]["evidence"]["live_ppl"] == 9000.0

    def test_slo_breach_inside_observe_window_rolls_back(self):
        eng = _FakeEngine()
        ctl, agg = _ctl([eng])
        self._swapped(eng)
        eng.slo.breaches = {"tpot": {}}
        _tick(ctl, agg)
        assert eng.hotswap.calls == [("rollback", "slo:tpot")]

    def test_healthy_swap_is_vetted_after_observe_window(self):
        eng = _FakeEngine()
        ctl, agg = _ctl([eng], swap_observe_s=0.0)
        self._swapped(eng)
        time.sleep(0.01)
        _tick(ctl, agg)
        assert eng.hotswap.vetted is True
        assert eng.hotswap.calls == []
        assert _decisions("serving_swap_rollback") == []

    def test_max_rollbacks_trips_the_halt_breaker(self):
        eng = _FakeEngine()
        ctl, agg = _ctl([eng], max_swap_rollbacks=1)
        self._swapped(eng)
        eng.hotswap.regress = {"regressed": True, "live_ppl": 9.0,
                               "baseline_ppl": 1.0, "tol": 0.1}
        _tick(ctl, agg)                       # rollback #1
        assert eng.hotswap.calls == [("rollback", "canary")]
        self._swapped(eng, step=300)          # a second bad push lands
        _tick(ctl, agg)                       # #2 > max: roll AND halt
        assert eng.hotswap.calls[1:] == [("rollback", "canary"),
                                         ("halt", "max_rollbacks")]
        assert eng.hotswap.halted
        d = _decisions("serving_swap_halt")
        assert len(d) == 1 and d[0]["evidence"]["rollbacks"] == 2
        # a halted manager is left alone from then on
        _tick(ctl, agg)
        assert len(eng.hotswap.calls) == 3

    def test_dry_run_records_but_does_not_actuate(self):
        eng = _FakeEngine()
        ctl, agg = _ctl([eng], dry_run=True)
        self._swapped(eng)
        eng.hotswap.regress = {"regressed": True, "live_ppl": 9.0,
                               "baseline_ppl": 1.0, "tol": 0.1}
        eng.is_wedged = True
        _tick(ctl, agg)
        _tick(ctl, agg)
        assert eng.hotswap.calls == [] and eng.restarts == []
        recs = _decisions()
        assert recs and all(r["outcome"] == "dry_run" for r in recs)


# -- the real engine actuators + the memory governor -------------------------
def _model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, max_position_embeddings=128,
                    hidden_size=32, num_layers=2, num_heads=2,
                    dropout=0.0, attn_dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m, cfg


class TestEngineActuators:
    def test_queue_cap_sheds_submit(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                            name="cap")
        eng.set_queue_limit(2)
        eng.submit([1, 2, 3], max_new_tokens=2)
        eng.submit([4, 5, 6], max_new_tokens=2)
        with pytest.raises(RuntimeError, match="shed cap"):
            eng.submit([7, 8, 9], max_new_tokens=2)
        eng.set_queue_limit(None)
        eng.submit([7, 8, 9], max_new_tokens=2)   # uncapped again
        eng.run_until_idle()
        eng.close()

    def test_suspend_refuses_admission_with_retry_after(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                            name="susp")
        eng.suspend(reason="memory_pressure", retry_after_s=7.5)
        with pytest.raises(EngineSuspended) as ei:
            eng.submit([1, 2, 3], max_new_tokens=2)
        assert ei.value.retry_after_s == 7.5
        assert ei.value.reason == "memory_pressure"
        assert eng.status()["suspended"]["reason"] == "memory_pressure"
        eng.resume_admissions()
        r = eng.submit([1, 2, 3], max_new_tokens=2)
        eng.run_until_idle()
        assert len(r.result(timeout=10)) == 2
        eng.close()

    def test_shrink_and_restore_pool(self):
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="shrink")
        free0 = eng.allocator.free_pages
        parked = eng.shrink_pool()
        assert parked == max(1, (eng.cache.num_pages - 1) // 2)
        assert eng.allocator.free_pages == free0 - parked
        assert eng.allocator.reserved_pages == parked
        restored = eng.restore_pool()
        assert restored == parked and eng.allocator.free_pages == free0
        eng.close()

    def test_mem_budget_caps_the_page_pool(self):
        m, cfg = _model()
        ref = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="ref")
        full_pages = ref.cache.num_pages
        budget = ref.pool_bytes() // 2
        ref.close()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="budget", mem_budget_bytes=budget)
        assert eng.cache.num_pages < full_pages
        assert eng.pool_bytes() <= budget
        capped = eng.status()["budget_capped_pages"]
        assert capped == (full_pages, eng.cache.num_pages)
        eng.close()


class TestMemoryGovernor:
    def _engines(self):
        m, _ = _model()
        hi = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                           name="hi", priority=10)
        lo = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                           name="lo", priority=1)
        return hi, lo

    def test_inert_without_a_limit(self):
        hi, lo = self._engines()
        gov = MemoryGovernor(limit_bytes=0, sampler=lambda: 10**12,
                             engines=lambda: [hi, lo])
        assert gov.tick() is None
        hi.close(), lo.close()

    def test_ladder_degrades_lowest_priority_then_recovers(self):
        hi, lo = self._engines()
        pressure = {"bytes": 100}
        gov = MemoryGovernor(limit_bytes=50, retry_after_s=3.0,
                             sampler=lambda: pressure["bytes"],
                             engines=lambda: [hi, lo])
        d1 = gov.tick()                       # rung 1: shrink lo's pool
        assert (d1["action"], d1["model"]) == ("shrink_pool", "lo")
        assert lo.allocator.reserved_pages > 0
        d2 = gov.tick()                       # rung 2: suspend lo
        assert (d2["action"], d2["model"]) == ("suspend", "lo")
        with pytest.raises(EngineSuspended):
            lo.submit([1, 2, 3], max_new_tokens=2)
        hi.submit([1, 2, 3], max_new_tokens=2)  # hi keeps serving
        hi.run_until_idle()
        d3 = gov.tick()                       # lo exhausted: shrink hi
        assert (d3["action"], d3["model"]) == ("shrink_pool", "hi")
        assert gov.status()["degraded"] == {"lo": "suspended",
                                            "hi": "shrunk"}

        pressure["bytes"] = 10                # pressure clears (hysteresis)
        d4 = gov.tick()                       # highest priority first
        assert (d4["action"], d4["model"]) == ("restore_pool", "hi")
        d5 = gov.tick()
        assert (d5["action"], d5["model"]) == ("resume", "lo")
        lo.submit([1, 2, 3], max_new_tokens=2)
        lo.run_until_idle()
        d6 = gov.tick()
        assert (d6["action"], d6["model"]) == ("restore_pool", "lo")
        assert gov.status()["degraded"] == {}
        assert gov.tick() is None             # steady state
        kinds = [e["action"] for e in
                 events.recent(50, kind="controller_decision")
                 if e.get("policy") == "serving_memory"]
        assert kinds == ["shrink_pool", "suspend", "shrink_pool",
                         "restore_pool", "resume", "restore_pool"]
        hi.close(), lo.close()

    def test_hysteresis_band_holds_state(self):
        hi, lo = self._engines()
        pressure = {"bytes": 100}
        gov = MemoryGovernor(limit_bytes=50, resume_frac=0.85,
                             sampler=lambda: pressure["bytes"],
                             engines=lambda: [hi, lo])
        gov.tick()
        pressure["bytes"] = 45                # below limit, above 0.85*50
        assert gov.tick() is None             # no flapping
        assert gov.status()["degraded"] == {"lo": "shrunk"}
        hi.close(), lo.close()
