"""End-to-end fault-tolerant training: kill -9 mid-epoch -> relaunch ->
bit-identical tail, plus the chaos run (worker kill + store fault under
PADDLE_TPU_FAULT_SPEC).

Reference: `test_auto_checkpoint.py` proves epoch-level resume; here the
contract is stronger — step-level resume with optimizer slots, RNG, and LR
cursor restored, verified bit-exactly against an uninterrupted run.
"""
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fault
from paddle_tpu.profiler import metrics as metrics_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Training script for the subprocess runs. Deterministic end to end: seeded
# init, index-seeded dataset, no shuffle. `--kill-at N` SIGKILLs the process
# (no cleanup, like a preemption that missed its grace window) right after
# global step N's checkpoint; `--resume` restores and continues.
_TRAIN_SCRIPT = r"""
import json, os, signal, sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import fault, nn, optimizer
from paddle_tpu.hapi.callbacks import Callback, FaultTolerantCheckpoint
from paddle_tpu.io import Dataset

CKPT = sys.argv[1]
OUT = sys.argv[2]
KILL_AT = int(os.environ.get("KILL_AT", "0"))
RESUME = os.environ.get("RESUME") == "1"


class DS(Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        rng = np.random.RandomState(1000 + i)
        return rng.randn(4).astype(np.float32), rng.randn(2).astype(np.float32)


class KillSwitch(Callback):
    def __init__(self):
        super().__init__()
        self.n = 0

    def on_train_batch_end(self, step, logs=None):
        self.n += 1
        if KILL_AT and self.n >= KILL_AT:
            os.kill(os.getpid(), signal.SIGKILL)  # no goodbye


def main():
    paddle.seed(42)
    net = nn.Linear(4, 2)
    m = paddle.Model(net)
    m.prepare(optimizer.Adam(learning_rate=1e-2,
                             parameters=net.parameters()),
              loss=nn.MSELoss())
    cbs = [FaultTolerantCheckpoint(CKPT, save_freq_steps=1)]
    if KILL_AT:
        cbs.append(KillSwitch())  # runs AFTER the checkpoint callback
    m.fit(DS(), batch_size=2, epochs=2, shuffle=False, verbose=0,
          callbacks=cbs, resume=CKPT if RESUME else None)

    out = {}
    if RESUME:
        # exercise one fault-injected, retried distributed op so the
        # snapshot proves the retry machinery ran in this process
        from paddle_tpu.distributed.store import TCPStore
        fault.configure("store.get", times=1)
        store = TCPStore("127.0.0.1", 0, is_master=True,
                         retry=fault.RetryPolicy(max_attempts=3,
                                                 base_delay=0.001))
        store.set("probe", "alive")
        assert store.get("probe") == b"alive"
        store.stop()

        # reference: the SAME schedule uninterrupted, in this process —
        # the resumed tail must match it bit-for-bit
        paddle.seed(42)
        net2 = nn.Linear(4, 2)
        m2 = paddle.Model(net2)
        m2.prepare(optimizer.Adam(learning_rate=1e-2,
                                  parameters=net2.parameters()),
                   loss=nn.MSELoss())
        m2.fit(DS(), batch_size=2, epochs=2, shuffle=False, verbose=0)
        m2._sync_from_train_step()
        out["ref_weights"] = {k: np.asarray(v.data).tolist()
                              for k, v in m2.network.state_dict().items()}

    m._sync_from_train_step()
    out["weights"] = {k: np.asarray(v.data).tolist()
                      for k, v in m.network.state_dict().items()}
    from paddle_tpu.profiler.metrics import default_registry
    out["metrics"] = default_registry().snapshot()
    with open(OUT, "w") as f:
        json.dump(out, f)


if __name__ == "__main__":
    main()
"""


def _run(script, args, env_extra, timeout=240):
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO})
    env.pop("PADDLE_TPU_FAULT_SPEC", None)
    env.update(env_extra)
    return subprocess.run([sys.executable, script] + args, env=env,
                          capture_output=True, text=True, timeout=timeout)


class TestKillAndResume:
    def test_sigkill_midepoch_resumes_bit_identical(self, tmp_path):
        script = tmp_path / "train.py"
        script.write_text(_TRAIN_SCRIPT)
        ckpt = str(tmp_path / "ckpt")
        res_out = str(tmp_path / "resumed.json")

        # run 1: SIGKILL after global step 3 (mid-epoch 0 of 2x4 steps)
        r1 = _run(str(script), [ckpt, str(tmp_path / "unused.json")],
                  {"KILL_AT": "3"})
        assert r1.returncode == -signal.SIGKILL
        assert not os.path.exists(str(tmp_path / "unused.json"))

        # run 2: relaunch with resume — must finish, and its weights must
        # match an uninterrupted reference run (trained in run 2's process)
        # bit-for-bit: optimizer slots, RNG, and step cursor all restored
        r2 = _run(str(script), [ckpt, res_out], {"RESUME": "1"})
        assert r2.returncode == 0, r2.stderr[-2000:]

        res = json.load(open(res_out))
        assert res["ref_weights"].keys() == res["weights"].keys()
        for k in res["ref_weights"]:
            assert np.array_equal(np.asarray(res["ref_weights"][k]),
                                  np.asarray(res["weights"][k])), \
                f"{k} diverged after resume"

        # the metrics snapshot must record the recovery story:
        snap = res["metrics"]

        def total(name, **labels):
            vals = snap.get(name, {}).get("values", [])
            return sum(v["value"] for v in vals
                       if all(v["labels"].get(k) == lv
                              for k, lv in labels.items()))

        assert total("checkpoint_loads_total") >= 1     # resume loaded
        assert total("checkpoint_saves_total") >= 1     # and kept saving
        assert total("fault_injected_total", site="store.get") >= 1
        assert total("retry_attempts_total", op="store.get") >= 1
        assert total("retry_recovered_total", op="store.get") >= 1

    @pytest.mark.slow
    def test_corrupt_newest_checkpoint_falls_back(self, tmp_path):
        """Torn final snapshot (host died mid-publish, pre-atomic-rename
        kernel crash, disk corruption): resume uses the previous one.
        (slow: two subprocess runs; the same fallback is covered
        in-process by test_fault.py TestCheckpointManager.)"""
        script = tmp_path / "train.py"
        script.write_text(_TRAIN_SCRIPT)
        ckpt = str(tmp_path / "ckpt")
        r1 = _run(str(script), [ckpt, str(tmp_path / "u.json")],
                  {"KILL_AT": "3"})
        assert r1.returncode == -signal.SIGKILL
        from paddle_tpu.distributed import checkpoint as dist_ckpt
        newest = dist_ckpt.latest(ckpt)
        raw = open(newest, "rb").read()
        open(newest, "wb").write(raw[:len(raw) - 11])  # tear it
        res_out = str(tmp_path / "r.json")
        r2 = _run(str(script), [ckpt, res_out], {"RESUME": "1"})
        assert r2.returncode == 0, r2.stderr[-2000:]
        snap = json.load(open(res_out))["metrics"]
        skipped = sum(v["value"] for v in snap.get(
            "checkpoint_corrupt_skipped_total", {}).get("values", []))
        assert skipped >= 1


# ---------------------------------------------------------------------------
# chaos: worker kill + store fault during a hapi fit
# ---------------------------------------------------------------------------
class _ChaosDS(paddle.io.Dataset):
    def __len__(self):
        return 24

    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        return rng.randn(4).astype(np.float32), rng.randn(2).astype(np.float32)


@pytest.mark.slow
class TestChaosTraining:
    def test_fit_survives_worker_kill_and_store_fault(self, monkeypatch):
        from paddle_tpu import nn, optimizer
        from paddle_tpu.distributed.store import TCPStore

        # arm via the env spec — the DataLoader worker PROCESSES inherit it
        monkeypatch.setenv(fault.SPEC_ENV,
                           "dataloader.worker0=1:kill;store.get=1")
        fault.reload_spec()
        try:
            reg = metrics_mod.default_registry()
            restarts0 = reg.get("dataloader_worker_restarts_total").total()

            paddle.seed(0)
            net = nn.Linear(4, 2)
            m = paddle.Model(net)
            m.prepare(optimizer.Adam(learning_rate=1e-2,
                                     parameters=net.parameters()),
                      loss=nn.MSELoss())
            with pytest.warns(UserWarning, match="died .* respawning"):
                m.fit(_ChaosDS(), batch_size=4, epochs=1, shuffle=False,
                      verbose=0, num_workers=2)

            # worker 0 was killed mid-epoch and respawned; training finished
            assert reg.get("dataloader_worker_restarts_total").total() > \
                restarts0

            # one store op faulted and recovered under retry
            store = TCPStore("127.0.0.1", 0, is_master=True,
                             retry=fault.RetryPolicy(max_attempts=3,
                                                     base_delay=0.001))
            store.set("k", "v")
            assert store.get("k") == b"v"
            store.stop()
            snap = reg.snapshot()
            injected = {(tuple(sorted(v["labels"].items()))): v["value"]
                        for v in snap["fault_injected_total"]["values"]}
            assert injected.get((("kind", "error"),
                                 ("site", "store.get"))) >= 1
            assert sum(v["value"]
                       for v in snap["retry_attempts_total"]["values"]
                       if v["labels"].get("op") == "store.get") >= 1
        finally:
            fault.reset()
