"""vision: model zoo forward shapes, transforms math, dataset readers.

Reference test style: `unittests/test_vision_models.py` (forward shape per
model), `test_transforms.py` (functional math), dataset tests with local
fixture files.
"""
import gzip
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import datasets, models, transforms as T


def _img(h=32, w=48, c=3, seed=0):
    return np.random.RandomState(seed).randint(
        0, 256, (h, w, c)).astype(np.uint8)


class TestTransforms:
    def test_to_tensor_scales_and_chw(self):
        x = T.to_tensor(_img())
        assert x.shape == (3, 32, 48)
        assert x.dtype == np.float32 and x.max() <= 1.0

    def test_resize_and_crop(self):
        img = _img()
        assert T.resize(img, (16, 24)).shape == (16, 24, 3)
        assert T.resize(img, 16).shape[0] == 16  # short side
        assert T.center_crop(img, 20).shape == (20, 20, 3)

    def test_flips_and_pad(self):
        img = _img()
        np.testing.assert_array_equal(T.hflip(img), img[:, ::-1])
        np.testing.assert_array_equal(T.vflip(img), img[::-1])
        assert T.pad(img, 2).shape == (36, 52, 3)

    def test_normalize(self):
        chw = T.to_tensor(_img())
        out = T.normalize(chw, mean=[0.5] * 3, std=[0.5] * 3)
        assert abs(float(out.mean())) < 1.2

    def test_compose_pipeline(self):
        tr = T.Compose([T.Resize(40), T.CenterCrop(32),
                        T.RandomHorizontalFlip(0.5), T.ToTensor(),
                        T.Normalize([0.5] * 3, [0.5] * 3)])
        out = tr(_img(64, 80))
        assert out.shape == (3, 32, 32)

    def test_random_resized_crop(self):
        out = T.RandomResizedCrop(24)(_img())
        assert out.shape == (24, 24, 3)

    def test_grayscale(self):
        assert T.to_grayscale(_img(), 3).shape == (32, 48, 3)


class TestModels:
    @pytest.mark.parametrize("factory,ch", [
        pytest.param(lambda: models.vgg11(num_classes=10), 10,
                     marks=pytest.mark.slow),
        pytest.param(lambda: models.mobilenet_v1(scale=0.25, num_classes=10),
                     10, marks=pytest.mark.slow),
        # the slowest-to-trace families keep default coverage via
        # the alexnet row; run the rest with --slow
        pytest.param(lambda: models.mobilenet_v2(scale=0.25, num_classes=10),
                     10, marks=pytest.mark.slow),
        (lambda: models.alexnet(num_classes=10), 10),
        pytest.param(lambda: models.mobilenet_v3_small(scale=0.5,
                                                       num_classes=10),
                     10, marks=pytest.mark.slow),
        pytest.param(lambda: models.mobilenet_v3_large(scale=0.5,
                                                       num_classes=10),
                     10, marks=pytest.mark.slow),
    ])
    def test_forward_shape(self, factory, ch):
        paddle.seed(0)
        net = factory()
        net.eval()
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 3, 64, 64).astype(np.float32))
        out = net(x)
        assert tuple(out.shape) == (2, ch)

    def test_pretrained_raises(self):
        with pytest.raises(NotImplementedError):
            models.vgg16(pretrained=True)

    def test_resnet_reexported(self):
        assert models.resnet18 is not None
        net = models.resnet18(num_classes=7)
        net.eval()
        x = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
        assert tuple(net(x).shape) == (1, 7)


def _write_mnist(tmp_path, n=20):
    imgs = np.random.RandomState(0).randint(
        0, 256, (n, 28, 28)).astype(np.uint8)
    labels = (np.arange(n) % 10).astype(np.uint8)
    ip = str(tmp_path / "img.gz")
    lp = str(tmp_path / "lab.gz")
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return ip, lp


def _write_cifar(tmp_path, n=8):
    data = {b"data": np.random.RandomState(0).randint(
        0, 256, (n, 3072)).astype(np.uint8),
        b"labels": list(range(n))}
    path = str(tmp_path / "cifar.tar.gz")
    import io as _io
    with tarfile.open(path, "w:gz") as tf:
        raw = pickle.dumps(data)
        info = tarfile.TarInfo("cifar-10-batches-py/data_batch_1")
        info.size = len(raw)
        tf.addfile(info, _io.BytesIO(raw))
    return path


class TestDatasets:
    def test_mnist_reader(self, tmp_path):
        ip, lp = _write_mnist(tmp_path)
        ds = datasets.MNIST(image_path=ip, label_path=lp)
        assert len(ds) == 20
        img, label = ds[3]
        assert img.shape == (1, 28, 28) and img.dtype == np.float32
        assert int(label) == 3

    def test_cifar_reader(self, tmp_path):
        path = _write_cifar(tmp_path)
        ds = datasets.Cifar10(data_file=path)
        assert len(ds) == 8
        img, label = ds[1]
        assert img.shape == (3, 32, 32)
        assert int(label) == 1

    def test_dataset_with_transform_trains(self):
        """FakeData -> transforms -> hapi Model: one epoch runs."""
        from paddle_tpu import nn, optimizer
        tr = T.Compose([T.Resize(16), T.ToTensor()])
        ds = datasets.FakeData(num_samples=16, shape=(28, 28, 3),
                               num_classes=4, transform=tr)
        paddle.seed(0)
        net = nn.Sequential(nn.Flatten(), nn.Linear(3 * 16 * 16, 4))
        model = paddle.Model(net)
        model.prepare(optimizer.SGD(learning_rate=0.1,
                                    parameters=model.parameters()),
                      nn.CrossEntropyLoss())
        model.fit(ds, epochs=1, batch_size=8, verbose=0)

    def test_download_raises(self):
        with pytest.raises(NotImplementedError):
            datasets.MNIST(download=True)


class TestResNetRecompute:
    @pytest.mark.slow  # heavy e2e; full-suite only (tier-1 budget)
    def test_per_stage_remat_matches_baseline_and_updates_bn(self):
        """ResNet(recompute=True) remats residual stages (reference
        RecomputeFunction at stage granularity): losses AND BatchNorm
        running stats must match the no-remat run exactly — round-3
        review found buffer updates silently frozen inside checkpointed
        regions before the recompute util threaded them back out."""
        import jax
        from paddle_tpu import optimizer
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.nn import functional as F

        rng = np.random.RandomState(0)
        imgs = rng.randn(4, 3, 32, 32).astype(np.float32)
        labels = rng.randint(0, 10, (4,)).astype(np.int32)

        def run(rc):
            paddle.seed(0)
            m = models.resnet18(num_classes=10, recompute=rc)
            opt = optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                     parameters=m.parameters())
            step = TrainStep(m, F.cross_entropy, opt, donate=False)
            ls = [float(step(paddle.to_tensor(imgs),
                             paddle.to_tensor(labels))) for _ in range(3)]
            return ls, {k: np.asarray(v) for k, v in step.buffers.items()}

        l0, b0 = run(False)
        l1, b1 = run(True)
        # checkpoint replay reorders the BN one-pass stat reductions inside
        # XLA fusions; bf16 activations make ~1e-4 absolute drift expected
        np.testing.assert_allclose(l1, l0, rtol=1e-4, atol=5e-4)
        for k in b0:
            np.testing.assert_allclose(b1[k], b0[k], rtol=1e-4, atol=1e-3,
                                       err_msg=k)
