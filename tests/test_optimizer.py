"""Optimizer + LR scheduler tests (reference analog: unittests/test_adam_op.py etc.)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.param import Parameter
from paddle_tpu.optimizer import SGD, Adam, AdamW, Lamb, Momentum, RMSProp
from paddle_tpu.optimizer import lr as lr_mod


def quad_problem(opt_cls, steps=50, **kw):
    paddle.seed(0)
    p = Parameter(np.array([5.0, -3.0], np.float32))
    opt = opt_cls(parameters=[p], **kw)
    for _ in range(steps):
        loss = (p * p).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return np.abs(p.numpy()).max()


def test_sgd_converges():
    assert quad_problem(SGD, learning_rate=0.1) < 0.1


def test_momentum_converges():
    assert quad_problem(Momentum, steps=150, learning_rate=0.02, momentum=0.9) < 0.2


def test_adam_converges():
    assert quad_problem(Adam, steps=200, learning_rate=0.1) < 0.05


def test_adamw_decay():
    p = Parameter(np.array([1.0], np.float32))
    opt = AdamW(learning_rate=0.0, parameters=[p], weight_decay=0.1)
    # zero lr => only decay term, which is scaled by lr => no change
    (p * 1.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [1.0])


def test_adam_matches_torch():
    torch = pytest.importorskip("torch")
    w0 = np.random.randn(4).astype(np.float32)
    g = np.random.randn(4).astype(np.float32)

    p = Parameter(w0.copy())
    opt = Adam(learning_rate=0.01, parameters=[p])
    for _ in range(3):
        (p * paddle.to_tensor(g)).sum().backward()
        opt.step()
        opt.clear_grad()

    tp = torch.nn.Parameter(torch.tensor(w0.copy()))
    topt = torch.optim.Adam([tp], lr=0.01, eps=1e-8)
    for _ in range(3):
        topt.zero_grad()
        (tp * torch.tensor(g)).sum().backward()
        topt.step()
    np.testing.assert_allclose(p.numpy(), tp.detach().numpy(), atol=1e-6)


def test_lamb_runs():
    assert quad_problem(Lamb, steps=100, learning_rate=0.05) < 5.0


def test_rmsprop_converges():
    assert quad_problem(RMSProp, steps=100, learning_rate=0.05) < 0.5


def test_grad_clip_in_optimizer():
    from paddle_tpu.nn import ClipGradByGlobalNorm
    p = Parameter(np.array([1.0], np.float32))
    opt = SGD(learning_rate=1.0, parameters=[p],
              grad_clip=ClipGradByGlobalNorm(0.5))
    (p * 100.0).sum().backward()
    opt.step()
    np.testing.assert_allclose(p.numpy(), [0.5], rtol=1e-5)


class TestMutableHyperparams:
    """Hyperparameters read inside `_update` ride the jitted per-parameter
    update as TRACED arguments (like lr/t): mutating them mid-run must take
    effect instead of being baked in at first trace (ADVICE r5 #4)."""

    def test_weight_decay_mutation_applies(self):
        import jax.numpy as jnp
        p = Parameter(jnp.ones(4, jnp.float32))
        p.stop_gradient = False
        opt = SGD(learning_rate=1.0, parameters=[p], weight_decay=0.0)
        for i in range(4):
            p.grad = Parameter(jnp.zeros(4, jnp.float32))
            if i == 2:  # jitted update already compiled by now
                opt._weight_decay = 0.5
            opt.step()
        # wd=0 steps are no-ops on zero grads; the two wd=0.5 steps decay
        # p twice: 1 * 0.5 * 0.5
        np.testing.assert_allclose(p.numpy(), 0.25, rtol=1e-6)

    def test_beta1_mutation_applies(self):
        import jax.numpy as jnp
        p = Parameter(jnp.ones(4, jnp.float32))
        p.stop_gradient = False
        opt = Adam(learning_rate=0.1, parameters=[p])
        for _ in range(3):
            p.grad = Parameter(jnp.ones(4, jnp.float32))
            opt.step()
        before = p.numpy().copy()
        opt._beta1 = 0.0  # kill momentum: next step follows the NEW grad
        p.grad = Parameter(-jnp.ones(4, jnp.float32))
        opt.step()
        assert (p.numpy() > before).all(), \
            "beta1 mutation was baked into the jitted update"

    def test_mutation_matches_pure_eager(self):
        """Jitted trajectory with a mid-run hyper change == eager one."""
        import jax.numpy as jnp

        def run(broken):
            p = Parameter(jnp.full(3, 2.0, jnp.float32))
            p.stop_gradient = False
            opt = Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
            if broken:
                opt._jit_step_broken = True
            for i in range(6):
                p.grad = Parameter(jnp.ones(3, jnp.float32))
                if i == 3:
                    opt._momentum = 0.0
                opt.step()
            return p.numpy()

        np.testing.assert_allclose(run(False), run(True), rtol=1e-6)


def test_state_dict_roundtrip():
    p = Parameter(np.ones(3, np.float32))
    opt = Adam(learning_rate=0.1, parameters=[p])
    (p * 2.0).sum().backward()
    opt.step()
    sd = opt.state_dict()
    p2 = Parameter(np.ones(3, np.float32))
    opt2 = Adam(learning_rate=0.1, parameters=[p2])
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1
    np.testing.assert_allclose(
        np.asarray(opt2._slots[id(p2)]["moment1"]),
        np.asarray(opt._slots[id(p)]["moment1"]))


class TestLRSchedulers:
    def test_step_decay(self):
        s = lr_mod.StepDecay(0.1, step_size=2, gamma=0.5)
        lrs = []
        for _ in range(5):
            lrs.append(round(s.get_lr(), 6))
            s.step()
        assert lrs == [0.1, 0.1, 0.05, 0.05, 0.025]

    def test_warmup(self):
        s = lr_mod.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
        s.step(5)
        assert abs(s.get_lr() - 0.05) < 1e-6
        s.step(20)
        assert abs(s.get_lr() - 0.1) < 1e-6

    def test_cosine(self):
        s = lr_mod.CosineAnnealingDecay(1.0, T_max=10)
        s.step(10)
        assert s.get_lr() < 1e-6

    def test_noam(self):
        s = lr_mod.NoamDecay(d_model=512, warmup_steps=100, learning_rate=1.0)
        vals = []
        for i in range(200):
            s.step(i)
            vals.append(s.get_lr())
        assert np.argmax(vals) in range(95, 105)

    def test_optimizer_integration(self):
        p = Parameter(np.ones(1, np.float32))
        sched = lr_mod.StepDecay(0.1, step_size=1, gamma=0.1)
        opt = SGD(learning_rate=sched, parameters=[p])
        assert abs(opt.get_lr() - 0.1) < 1e-9
        sched.step()
        assert abs(opt.get_lr() - 0.01) < 1e-9

    def test_reduce_on_plateau(self):
        s = lr_mod.ReduceOnPlateau(0.1, patience=1, factor=0.5)
        for m in [1.0, 1.0, 1.0, 1.0]:
            s.step(m)
        assert s.get_lr() < 0.1
