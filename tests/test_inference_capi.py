"""Predictor C API (reference `inference/capi_exp/pd_inference_api.h`):
build the shim, compile a real C consumer (tests/capi_main.c), run LeNet
through it in a fresh process, and match the Python Predictor's output."""
import os
import pathlib
import subprocess

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import _native, nn
from paddle_tpu.models import LeNet

HERE = pathlib.Path(__file__).resolve().parent


@pytest.fixture(scope="module")
def capi_lib():
    try:
        return _native.build_capi()
    except Exception as e:  # toolchain missing: skip, don't fail the suite
        pytest.skip(f"cannot build C API shim: {e}")


@pytest.fixture(scope="module")
def lenet_artifact(tmp_path_factory):
    d = tmp_path_factory.mktemp("capi_model")
    paddle.seed(5)
    net = LeNet()
    net.eval()
    from paddle_tpu.static import InputSpec
    prefix = str(d / "lenet")
    paddle.jit.save(net, prefix,
                    input_spec=[InputSpec((2, 1, 28, 28), "float32", "x")])
    return net, prefix


def test_c_program_matches_python_predictor(capi_lib, lenet_artifact,
                                            tmp_path):
    net, prefix = lenet_artifact
    x = np.random.default_rng(0).normal(size=(2, 1, 28, 28)).astype(
        "float32")

    # golden from the Python Predictor over the same artifact
    from paddle_tpu import inference as inf
    cfg = inf.Config(prefix)
    cfg.disable_gpu()
    pred = inf.create_predictor(cfg)
    iname = pred.get_input_names()[0]
    pred.get_input_handle(iname).copy_from_cpu(x)
    pred.run()
    golden = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()

    # compile the C consumer and run it in a clean process
    exe = str(tmp_path / "capi_main")
    inc = str(pathlib.Path(_native.__file__).parent / "csrc_capi")

    def cfgout(*args):
        return subprocess.run(["python3-config", *args], check=True,
                              capture_output=True, text=True).stdout.split()
    try:
        ldflags = cfgout("--ldflags", "--embed")
    except subprocess.CalledProcessError:
        ldflags = cfgout("--ldflags")
    cmd = (["gcc", "-O1", str(HERE / "capi_main.c"), f"-I{inc}",
            "-o", exe, f"-L{capi_lib.parent}", "-lpd_inference_c"]
           + ldflags + [f"-Wl,-rpath,{capi_lib.parent}"])
    subprocess.run(cmd, check=True, capture_output=True)

    inp = tmp_path / "in.bin"
    outp = tmp_path / "out.bin"
    inp.write_bytes(x.tobytes())
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(HERE.parent) + os.pathsep + env.get(
        "PYTHONPATH", "")
    r = subprocess.run(
        [exe, prefix, str(inp), str(outp), "2", "1", "28", "28"],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "CAPI_OK" in r.stdout
    got = np.frombuffer(outp.read_bytes(), np.float32).reshape(golden.shape)
    np.testing.assert_allclose(got, golden, atol=1e-5, rtol=1e-5)


def test_name_and_arity_queries(capi_lib, lenet_artifact):
    """Drive the shim in-process via ctypes for the metadata calls."""
    import ctypes
    net, prefix = lenet_artifact
    lib = ctypes.CDLL(str(capi_lib))
    lib.pd_predictor_create.restype = ctypes.c_void_p
    lib.pd_predictor_create.argtypes = [ctypes.c_char_p]
    lib.pd_predictor_num_inputs.argtypes = [ctypes.c_void_p]
    lib.pd_predictor_num_outputs.argtypes = [ctypes.c_void_p]
    lib.pd_predictor_input_name.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.pd_predictor_destroy.argtypes = [ctypes.c_void_p]
    p = lib.pd_predictor_create(prefix.encode())
    assert p, "create failed"
    assert lib.pd_predictor_num_inputs(p) == 1
    assert lib.pd_predictor_num_outputs(p) == 1
    buf = ctypes.create_string_buffer(128)
    assert lib.pd_predictor_input_name(p, 0, buf, 128) > 0
    from paddle_tpu import inference as inf
    cfg = inf.Config(prefix)
    cfg.disable_gpu()
    assert buf.value.decode() == inf.create_predictor(
        cfg).get_input_names()[0]
    lib.pd_predictor_destroy(p)
