"""End-to-end training slices: MNIST-style LeNet (the §7 minimum slice),
compiled TrainStep, AMP, DataLoader, checkpoint/resume."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.io import DataLoader, TensorDataset


def make_blobs(n=256, d=16, classes=4):
    rng = np.random.RandomState(0)
    centers = rng.randn(classes, d) * 3
    X = np.concatenate([centers[i] + rng.randn(n // classes, d)
                        for i in range(classes)]).astype(np.float32)
    y = np.concatenate([np.full(n // classes, i) for i in range(classes)])
    perm = rng.permutation(n)
    return X[perm], y[perm].astype(np.int64)


def test_mlp_eager_training():
    X, y = make_blobs()
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    lossf = nn.CrossEntropyLoss()
    for _ in range(30):
        out = net(Tensor(X))
        loss = lossf(out, Tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
    pred = net(Tensor(X)).numpy().argmax(-1)
    assert (pred == y).mean() > 0.95


def test_lenet_compiled_train_step():
    rng = np.random.RandomState(0)
    X = rng.randn(64, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, 64).astype(np.int64)
    from paddle_tpu.models.lenet import LeNet
    net = LeNet(num_classes=10)
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=net.parameters())
    step = paddle.jit.TrainStep(net, nn.CrossEntropyLoss(), opt)
    losses = [float(step(Tensor(X), Tensor(y)).item()) for _ in range(8)]
    assert losses[-1] < losses[0]
    step.sync_to_layer()  # params propagate back to eager layer
    out = net(Tensor(X))
    assert out.shape == [64, 10]


def test_dataloader():
    X, y = make_blobs(64, 8, 2)
    ds = TensorDataset([Tensor(X), Tensor(y)])
    dl = DataLoader(ds, batch_size=16, shuffle=True, drop_last=True)
    batches = list(dl)
    assert len(batches) == 4
    xb, yb = batches[0]
    assert xb.shape == [16, 8] and yb.shape == [16]
    # two epochs work
    assert len(list(dl)) == 4


def test_dataloader_collate_numpy():
    class DS(paddle.io.Dataset):
        def __len__(self):
            return 10

        def __getitem__(self, i):
            return np.full((3,), i, np.float32), i

    dl = DataLoader(DS(), batch_size=5)
    xb, yb = next(iter(dl))
    assert xb.shape == [5, 3]
    np.testing.assert_allclose(yb.numpy(), np.arange(5))


def test_amp_autocast():
    import jax.numpy as jnp
    net = nn.Linear(8, 8)
    x = Tensor(np.random.randn(4, 8).astype(np.float32))
    with paddle.amp.auto_cast(level="O1"):
        out = net(x)
    assert out.dtype == jnp.bfloat16
    out2 = net(x)
    assert out2.dtype == jnp.float32


def test_amp_training_converges():
    X, y = make_blobs()
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    scaler = paddle.amp.GradScaler()
    lossf = nn.CrossEntropyLoss()
    for _ in range(20):
        with paddle.amp.auto_cast():
            out = net(Tensor(X))
            loss = lossf(out, Tensor(y))
        scaler.scale(loss).backward()
        scaler.step(opt)
        opt.clear_grad()
    pred = net(Tensor(X)).numpy().argmax(-1)
    assert (pred == y).mean() > 0.9


def test_checkpoint_resume(tmp_path):
    X, y = make_blobs()
    def build():
        paddle.seed(42)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        return net, opt

    net, opt = build()
    lossf = nn.CrossEntropyLoss()
    for _ in range(5):
        lossf(net(Tensor(X)), Tensor(y)).backward()
        opt.step()
        opt.clear_grad()
    paddle.save(net.state_dict(), str(tmp_path / "model.pd"))
    paddle.save(opt.state_dict(), str(tmp_path / "opt.pd"))

    net2, opt2 = build()
    net2.set_state_dict(paddle.load(str(tmp_path / "model.pd")))
    opt2.set_state_dict(paddle.load(str(tmp_path / "opt.pd")))
    for p, q in zip(net.parameters(), net2.parameters()):
        np.testing.assert_allclose(p.numpy(), q.numpy())
    # one more identical step on both stays in lockstep
    for n, o in ((net, opt), (net2, opt2)):
        lossf(n(Tensor(X)), Tensor(y)).backward()
        o.step()
        o.clear_grad()
    for p, q in zip(net.parameters(), net2.parameters()):
        np.testing.assert_allclose(p.numpy(), q.numpy(), atol=1e-6)


def test_to_static():
    net = nn.Sequential(nn.Linear(8, 8), nn.ReLU())
    net.eval()
    snet = paddle.jit.to_static(net)
    x = Tensor(np.random.randn(2, 8).astype(np.float32))
    np.testing.assert_allclose(snet(x).numpy(), net(x).numpy(), atol=1e-6)


def test_metric_accuracy():
    m = paddle.metric.Accuracy()
    pred = Tensor(np.array([[0.1, 0.9], [0.8, 0.2]], np.float32))
    label = Tensor(np.array([[1], [1]], np.int64))
    correct = m.compute(pred, label)
    m.update(correct)
    assert abs(m.accumulate() - 0.5) < 1e-6
