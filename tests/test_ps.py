"""Parameter-server (native C++) tests.

Mirrors the reference's PS test strategy: in-process unit tests against the
tables (like `ps_local_client`, /root/reference/paddle/fluid/distributed/ps/
service/ps_local_client.h) plus a subprocess localhost cluster
(`test_dist_base.py:968` pattern — fork pserver + 2 trainers, assert results).
"""
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (PSClient, PSServer, SparseEmbedding,
                                       TableConfig)
from paddle_tpu.distributed.store import TCPStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def ps_pair():
    server = PSServer(0)
    client = PSClient([server.endpoint])
    yield server, client
    client.stop_servers()


class TestTables:
    def test_dense_sgd(self, ps_pair):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=0, kind="dense", dense_size=6,
                                   optimizer="sgd", learning_rate=0.1))
        w0 = np.arange(6, dtype=np.float32)
        c.set_dense(0, w0)
        g = np.full(6, 2.0, np.float32)
        c.push_dense(0, g)
        np.testing.assert_allclose(c.pull_dense(0), w0 - 0.2, rtol=1e-6)

    def test_dense_adam_matches_numpy(self, ps_pair):
        _, c = ps_pair
        lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
        c.create_table(TableConfig(table_id=1, kind="dense", dense_size=4,
                                   optimizer="adam", learning_rate=lr))
        w = np.array([1.0, -2.0, 3.0, 0.5], np.float32)
        c.set_dense(1, w)
        m = np.zeros(4); v = np.zeros(4)
        rng = np.random.default_rng(0)
        for t in range(1, 4):
            g = rng.normal(size=4).astype(np.float32)
            c.push_dense(1, g)
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            w = w - lr * (m / (1 - b1 ** t)) / (np.sqrt(v / (1 - b2 ** t)) + eps)
        np.testing.assert_allclose(c.pull_dense(1), w, rtol=1e-4, atol=1e-6)

    def test_sparse_lazy_init_deterministic(self, ps_pair):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=2, dim=8, init_range=0.1, seed=3))
        keys = np.array([5, 17, 5], np.uint64)
        rows = c.pull_sparse(2, keys)
        assert rows.shape == (3, 8)
        np.testing.assert_array_equal(rows[0], rows[2])
        assert np.abs(rows).max() <= 0.1
        assert c.table_size(2) == 2
        # same key again -> same row (no re-init)
        again = c.pull_sparse(2, np.array([17], np.uint64))
        np.testing.assert_array_equal(again[0], rows[1])

    def test_sparse_push_applies_sgd(self, ps_pair):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=3, dim=4, optimizer="sgd",
                                   learning_rate=0.5, init_range=0.0))
        keys = np.array([7, 9], np.uint64)
        before = c.pull_sparse(3, keys)
        g = np.ones((2, 4), np.float32)
        c.push_sparse(3, keys, g)
        after = c.pull_sparse(3, keys)
        np.testing.assert_allclose(after, before - 0.5, rtol=1e-6)

    def test_save_load_roundtrip(self, ps_pair):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=4, dim=4, learning_rate=0.1))
        keys = np.array([1, 2, 3], np.uint64)
        c.push_sparse(4, keys, np.ones((3, 4), np.float32))
        want = c.pull_sparse(4, keys)
        with tempfile.TemporaryDirectory() as d:
            c.save(d)
            c.push_sparse(4, keys, np.ones((3, 4), np.float32))  # mutate
            c.load(d)
            np.testing.assert_allclose(c.pull_sparse(4, keys), want)


class TestMultiServerSharding:
    def test_two_servers(self):
        s1, s2 = PSServer(0), PSServer(0)
        c = PSClient([s1.endpoint, s2.endpoint])
        try:
            c.create_table(TableConfig(table_id=0, dim=4, optimizer="sgd",
                                       learning_rate=1.0, init_range=0.0))
            keys = np.arange(10, dtype=np.uint64)
            c.push_sparse(0, keys, np.ones((10, 4), np.float32))
            vals = c.pull_sparse(0, keys)
            np.testing.assert_allclose(vals, -np.ones((10, 4)), rtol=1e-6)
            # rows really are split across the two servers
            assert c.table_size(0) == 10
            lib = c._lib
            n1 = lib.ps_table_size(c._handles[0], 0)
            n2 = lib.ps_table_size(c._handles[1], 0)
            assert n1 > 0 and n2 > 0 and n1 + n2 == 10
        finally:
            c.stop_servers()


class TestSparseEmbeddingAutograd:
    def test_forward_backward_pushes_grads(self, ps_pair):
        _, c = ps_pair
        emb = SparseEmbedding(table_id=10, embedding_dim=4, optimizer="sgd",
                              learning_rate=1.0, init_range=0.0, client=c)
        ids = paddle.to_tensor(np.array([[1, 2], [1, 3]], np.int64))
        out = emb(ids)                      # [2, 2, 4], all zeros
        assert tuple(out.shape) == (2, 2, 4)
        loss = out.sum()
        loss.backward()
        # d loss/d emb = 1 per element; key 1 appears twice -> grad 2
        vals = c.pull_sparse(10, np.array([1, 2, 3], np.uint64))
        np.testing.assert_allclose(vals[0], -2 * np.ones(4), rtol=1e-6)
        np.testing.assert_allclose(vals[1], -np.ones(4), rtol=1e-6)
        np.testing.assert_allclose(vals[2], -np.ones(4), rtol=1e-6)

    def test_trains_with_dense_layers(self, ps_pair):
        _, c = ps_pair
        from paddle_tpu import nn, optimizer
        emb = SparseEmbedding(table_id=11, embedding_dim=8, optimizer="sgd",
                              learning_rate=0.1, client=c)
        fc = nn.Linear(8, 1)
        opt = optimizer.SGD(learning_rate=0.1, parameters=fc.parameters())
        rng = np.random.default_rng(0)
        ids_np = rng.integers(0, 50, (16,)).astype(np.int64)
        y = (ids_np % 2).astype(np.float32).reshape(-1, 1)
        losses = []
        for _ in range(30):
            out = fc(emb(paddle.to_tensor(ids_np)))
            loss = ((out - paddle.to_tensor(y)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses[::10]


class TestTCPStore:
    def test_kv_and_counter(self):
        master = TCPStore("127.0.0.1", 0, is_master=True)
        peer = TCPStore("127.0.0.1", master.port, is_master=False)
        master.set("addr", "1.2.3.4:85")
        assert peer.get("addr") == b"1.2.3.4:85"
        assert peer.add("ranks", 1) == 1
        assert master.add("ranks", 1) == 2
        assert peer.check("addr") is True
        assert peer.check("gone") is False
        peer.wait(["addr", "ranks"])
        master.delete_key("addr")
        assert master.check("addr") is False
        master.stop()


_CLUSTER_SCRIPT = r"""
import os, sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.ps import runtime as ps_rt

role = os.environ["TRAINING_ROLE"]
fleet.init(is_collective=False)
if fleet.is_server():
    fleet.init_server(port=int(os.environ["PADDLE_PORT"]))
    fleet.run_server()
    sys.exit(0)

# trainer
from paddle_tpu.models.wide_deep import WideDeep
from paddle_tpu import optimizer
fleet.init_worker()
tid = ps_rt.trainer_id()
model = WideDeep(num_slots=2, embedding_dim=4, dense_dim=3, hidden=16)
opt = optimizer.SGD(learning_rate=0.05, parameters=model.parameters())
rng = np.random.default_rng(100 + tid)
losses = []
# FIXED batches cycled over the run: a fresh random batch per step made
# losses[0] vs losses[-1] a coin flip at 20 steps (observed flaking to the
# fail side for entire rounds, each costing the suite a 420s communicate
# timeout) — memorizing a deterministic set is what the assert can promise
batches = []
for _ in range(4):
    ids = rng.integers(0, 100, (8, 2)).astype(np.int64)
    x = rng.normal(size=(8, 3)).astype(np.float32)
    yv = ((ids.sum(1) % 2) == 0).astype(np.float32).reshape(-1, 1)
    batches.append((ids, x, yv))
for step in range(40):
    ids, x, yv = batches[step % 4]
    logit = model(paddle.to_tensor(ids), paddle.to_tensor(x))
    label = paddle.to_tensor(yv)
    loss = paddle.nn.functional.binary_cross_entropy_with_logits(logit, label)
    loss.backward()
    opt.step()
    opt.clear_grad()
    losses.append(float(loss))
fleet.barrier_worker()
print(f"TRAINER {tid} first={losses[0]:.4f} last={losses[-1]:.4f}", flush=True)
assert losses[-1] < losses[0], (losses[0], losses[-1])
fleet.stop_worker()
"""


class TestPSCluster:
    @pytest.mark.slow  # 3-process e2e; in-process PS tests keep coverage
    def test_localhost_cluster_1server_2trainers(self, tmp_path):
        """Subprocess cluster: 1 pserver + 2 trainers on localhost."""
        script = tmp_path / "ps_train.py"
        script.write_text(_CLUSTER_SCRIPT)
        from paddle_tpu.distributed.env import find_free_port
        port = find_free_port()
        eps = f"127.0.0.1:{port}"
        base_env = dict(os.environ,
                        PADDLE_PSERVERS_IP_PORT_LIST=eps,
                        PADDLE_TRAINERS_NUM="2",
                        JAX_PLATFORMS="cpu",
                        PYTHONPATH=REPO)
        procs = [subprocess.Popen(
            [sys.executable, str(script)],
            env={**base_env, "TRAINING_ROLE": "PSERVER",
                 "PADDLE_PORT": str(port)},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)]
        for tid in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, str(script)],
                env={**base_env, "TRAINING_ROLE": "TRAINER",
                     "PADDLE_TRAINER_ID": str(tid)},
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        outs = [None] * len(procs)
        try:
            # TRAINERS first (they do the work and signal server shutdown
            # via stop_worker); waiting on the server first meant a failed
            # trainer left it serving forever and the test burned the whole
            # 420s on a process that could never exit
            for i in (1, 2):
                # generous: the full-suite run can load the machine heavily
                out, _ = procs[i].communicate(timeout=420)
                outs[i] = out.decode()
            # trainers are done: the server has been told to stop (or never
            # will be) — a short grace is all it legitimately needs
            try:
                out, _ = procs[0].communicate(timeout=30)
                outs[0] = out.decode()
            except subprocess.TimeoutExpired:
                procs[0].kill()
                out, _ = procs[0].communicate()
                outs[0] = "SERVER LINGERED (trainers never stopped it):\n" \
                    + out.decode()
        finally:
            # a timed-out child must NOT outlive the test: a leaked trainer
            # can hold the one shared TPU chip and poison every later run
            # (observed in round 3: a ps_train.py alive for 21h)
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for p in procs:
                if p.poll() is None:
                    try:
                        p.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        pass
        for p, out in zip(procs, outs):
            assert p.returncode == 0, f"proc failed:\n{out}"
        assert "TRAINER 0" in outs[1] + outs[2]
        assert "TRAINER 1" in outs[1] + outs[2]


class TestCtrLifecycle:
    """CTR feature lifecycle (reference ps/table/ctr_accessor.cc): show/click
    accumulation, day-tick decay + aging, below-threshold eviction."""

    def test_show_click_and_meta(self, ps_pair):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=10, kind="sparse", dim=4))
        keys = np.array([1, 2, 3], np.uint64)
        c.pull_sparse(10, keys)  # materialize rows
        c.push_show_click(10, keys, np.array([5, 1, 0], np.float32),
                          np.array([2, 0, 0], np.float32))
        show, click, unseen = c.pull_meta(10, keys)
        np.testing.assert_allclose(show, [5, 1, 0])
        np.testing.assert_allclose(click, [2, 0, 0])
        assert list(unseen) == [0, 0, 0]

    def test_shrink_evicts_stale_low_score_rows(self, ps_pair):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=11, kind="sparse", dim=4))
        hot = np.array([100], np.uint64)
        cold = np.array([200, 201, 202], np.uint64)
        c.pull_sparse(11, hot)
        c.pull_sparse(11, cold)
        c.push_show_click(11, hot, np.array([50.0], np.float32),
                          np.array([10.0], np.float32))
        assert c.table_size(11) == 4
        # 3 day-ticks with unseen>2 required: cold rows (score 0) evicted
        # on the 3rd tick, hot row's decayed score stays above threshold
        evicted = 0
        for _ in range(3):
            evicted += c.shrink(11, threshold=1.0, max_unseen_days=2)
        assert evicted == 3, evicted
        assert c.table_size(11) == 1
        show, click, unseen = c.pull_meta(11, cold[:1])
        assert unseen[0] == -1  # evicted marker
        show, click, unseen = c.pull_meta(11, hot)
        assert unseen[0] == 3 and show[0] > 40  # decayed but alive

    def test_touch_resets_unseen(self, ps_pair):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=12, kind="sparse", dim=4))
        k = np.array([7], np.uint64)
        c.pull_sparse(12, k)
        c.shrink(12, threshold=1.0, max_unseen_days=10)  # ages to 1
        _, _, unseen = c.pull_meta(12, k)
        assert unseen[0] == 1
        c.pull_sparse(12, k)  # touch
        _, _, unseen = c.pull_meta(12, k)
        assert unseen[0] == 0

    def test_ctr_meta_survives_save_load(self, ps_pair, tmp_path):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=13, kind="sparse", dim=4))
        k = np.array([42], np.uint64)
        c.pull_sparse(13, k)
        c.push_show_click(13, k, np.array([9.0], np.float32),
                          np.array([3.0], np.float32))
        c.save(str(tmp_path))
        c.push_show_click(13, k, np.array([100.0], np.float32),
                          np.array([100.0], np.float32))
        c.load(str(tmp_path))
        show, click, unseen = c.pull_meta(13, k)
        np.testing.assert_allclose(show, [9.0])
        np.testing.assert_allclose(click, [3.0])


class TestGeoMode:
    """Geo-SGD (reference GeoCommunicator + memory_sparse_geo_table):
    trainers apply SGD locally, push weight deltas; the server table
    (optimizer="sum") merges deltas from all trainers."""

    def test_sum_table_merges_deltas(self, ps_pair):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=20, kind="sparse", dim=2,
                                   optimizer="sum", init_range=0.0))
        k = np.array([5], np.uint64)
        base = c.pull_sparse(20, k)[0]
        c.push_sparse(20, k, np.array([[1.0, 2.0]], np.float32))
        c.push_sparse(20, k, np.array([[0.5, -1.0]], np.float32))
        np.testing.assert_allclose(c.pull_sparse(20, k)[0],
                                   base + [1.5, 1.0], rtol=1e-6)

    def test_two_geo_trainers_converge_to_shared_state(self, ps_pair):
        from paddle_tpu.distributed.ps.communicator import GeoCommunicator
        server, c = ps_pair
        c.create_table(TableConfig(table_id=21, kind="sparse", dim=3,
                                   optimizer="sum", init_range=0.0))
        c2 = PSClient([server.endpoint])
        # every worker declares the table (idempotent server-side)
        c2.create_table(TableConfig(table_id=21, kind="sparse", dim=3,
                                    optimizer="sum", init_range=0.0))
        g1 = GeoCommunicator(c, lr=0.1, geo_push_steps=4)
        g2 = GeoCommunicator(c2, lr=0.1, geo_push_steps=4)
        keys = np.array([1, 2], np.uint64)
        target = np.array([[1.0, 2.0, 3.0], [-1.0, 0.5, 2.0]], np.float32)
        # both trainers descend the same quadratic toward `target`
        for step in range(60):
            for g in (g1, g2):
                w = g.pull_sparse(21, keys)
                g.push_sparse(21, keys, 2.0 * (w - target) / 2.0)
        g1.geo_sync()
        g2.geo_sync()
        g1.geo_sync()  # see g2's last contribution
        merged = c.pull_sparse(21, keys)
        np.testing.assert_allclose(merged, target, atol=0.15)
        # geo invariant: local cache equals server state after sync
        np.testing.assert_allclose(
            g1.pull_sparse(21, keys), merged, atol=1e-5)

    def test_geo_local_steps_do_not_touch_server(self, ps_pair):
        from paddle_tpu.distributed.ps.communicator import GeoCommunicator
        _, c = ps_pair
        c.create_table(TableConfig(table_id=22, kind="sparse", dim=2,
                                   optimizer="sum", init_range=0.0))
        geo = GeoCommunicator(c, lr=0.1, geo_push_steps=100)
        k = np.array([9], np.uint64)
        before = c.pull_sparse(22, k).copy()
        for _ in range(5):
            w = geo.pull_sparse(22, k)
            geo.push_sparse(22, k, np.ones((1, 2), np.float32))
        np.testing.assert_allclose(c.pull_sparse(22, k), before)  # untouched
        assert not np.allclose(geo.pull_sparse(22, k), before)  # local moved
        geo.flush()
        np.testing.assert_allclose(c.pull_sparse(22, k),
                                   before - 0.5, rtol=1e-5)  # 5 * 0.1 * 1


class TestChunkedDense:
    def test_large_dense_table_roundtrip(self, ps_pair):
        """Dense tables above one 64MB transport chunk move in pieces
        (round-2 review: a 51M-float embedding must not hit the frame cap)."""
        _, c = ps_pair
        n = 20_000_000  # > 16M-float chunk => 2 chunks
        c.create_table(TableConfig(table_id=30, kind="dense", dense_size=n,
                                   optimizer="sgd", learning_rate=0.5))
        vals = np.arange(n, dtype=np.float32) % 1000.0
        c.set_dense(30, vals)
        got = c.pull_dense(30)
        np.testing.assert_array_equal(got, vals)
        g = np.ones(n, np.float32)
        c.push_dense(30, g)
        got = c.pull_dense(30)
        np.testing.assert_allclose(got[:5], vals[:5] - 0.5, rtol=1e-6)
        np.testing.assert_allclose(got[-5:], vals[-5:] - 0.5, rtol=1e-6)


class TestDiskSpill:
    """Disk-spill sparse tables (reference ps/table/ssd_sparse_table.cc):
    cold rows live on disk with only a key->offset index in RAM, restoring
    transparently on access — the bounded-memory piece of the reference's
    100B-feature capability."""

    def test_spill_and_transparent_restore(self, ps_pair, tmp_path):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=40, kind="sparse", dim=4,
                                   optimizer="sgd", learning_rate=0.5))
        c.set_spill(40, str(tmp_path))
        hot = np.array([1], np.uint64)
        cold = np.arange(100, 120, dtype=np.uint64)
        cold_vals = c.pull_sparse(40, cold).copy()
        c.pull_sparse(40, hot)
        # shrink owns the day tick (spill_cold only COMPARES — running both
        # daily must not double-age); negative threshold = age-only
        for _ in range(2):
            c.shrink(40, threshold=-1.0, max_unseen_days=10**6)
            c.pull_sparse(40, hot)  # touching hot keeps it resident
        n = c.spill_cold(40, max_unseen_days=1)
        assert n == 20, n  # all cold rows went to disk
        assert c.spilled_size(40) == 20
        assert c.table_size(40) == 1  # only the hot row in RAM
        # transparent restore: exact values come back, spilled count drops
        got = c.pull_sparse(40, cold)
        np.testing.assert_array_equal(got, cold_vals)
        assert c.spilled_size(40) == 0
        assert c.table_size(40) == 21

    def test_set_spill_refuses_when_rows_on_disk(self, ps_pair, tmp_path):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=43, kind="sparse", dim=2))
        c.set_spill(43, str(tmp_path / "a"))
        k = np.array([5], np.uint64)
        c.pull_sparse(43, k)
        for _ in range(2):
            c.shrink(43, threshold=-1.0, max_unseen_days=10**6)
        c.spill_cold(43, max_unseen_days=1)
        assert c.spilled_size(43) == 1
        # re-pointing the spill would orphan the only copy of that row
        import pytest as _pytest
        with _pytest.raises(RuntimeError):
            c.set_spill(43, str(tmp_path / "b"))

    def test_push_updates_spilled_row(self, ps_pair, tmp_path):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=41, kind="sparse", dim=2,
                                   optimizer="sgd", learning_rate=1.0))
        c.set_spill(41, str(tmp_path))
        k = np.array([7], np.uint64)
        v0 = c.pull_sparse(41, k)[0].copy()
        for _ in range(2):
            c.shrink(41, threshold=-1.0, max_unseen_days=10**6)
        c.spill_cold(41, max_unseen_days=1)
        assert c.spilled_size(41) == 1
        c.push_sparse(41, k, np.ones((1, 2), np.float32))  # restores + sgd
        np.testing.assert_allclose(c.pull_sparse(41, k)[0], v0 - 1.0,
                                   rtol=1e-6)

    def test_checkpoint_materializes_spilled_rows(self, ps_pair, tmp_path):
        _, c = ps_pair
        c.create_table(TableConfig(table_id=42, kind="sparse", dim=3))
        c.set_spill(42, str(tmp_path / "spill"))
        keys = np.arange(10, dtype=np.uint64)
        vals = c.pull_sparse(42, keys).copy()
        for _ in range(2):
            c.shrink(42, threshold=-1.0, max_unseen_days=10**6)
        c.spill_cold(42, max_unseen_days=1)
        assert c.spilled_size(42) == 10
        ck = str(tmp_path / "ck")
        import os
        os.makedirs(ck, exist_ok=True)
        c.save(ck)
        # wipe: new rows would re-init randomly; load must bring all back
        c.load(ck)
        got = c.pull_sparse(42, keys)
        np.testing.assert_array_equal(got, vals)


class TestGraphTable:
    """Graph tables (reference ps/table/common_graph_table.cc): adjacency +
    weighted neighbor sampling for GNN data pipelines."""

    def test_add_sample_degree(self, ps_pair):
        _, c = ps_pair
        src = np.array([1, 1, 1, 2, 2, 3], np.uint64)
        dst = np.array([10, 11, 12, 20, 21, 30], np.uint64)
        c.graph_add_edges(50, src, dst)
        np.testing.assert_array_equal(
            c.graph_degree(50, np.array([1, 2, 3, 4], np.uint64)),
            [3, 2, 1, 0])
        nb, cnt = c.graph_sample_neighbors(
            50, np.array([1, 2, 3, 4], np.uint64), k=5)
        assert list(cnt) == [3, 2, 1, 0]
        assert set(nb[0, :3].tolist()) == {10, 11, 12}
        assert set(nb[1, :2].tolist()) == {20, 21}
        assert nb[2, 0] == 30

    def test_sample_k_without_replacement_deterministic(self, ps_pair):
        _, c = ps_pair
        src = np.full(20, 7, np.uint64)
        dst = np.arange(100, 120, dtype=np.uint64)
        c.graph_add_edges(51, src, dst)
        nb1, cnt1 = c.graph_sample_neighbors(
            51, np.array([7], np.uint64), k=8, seed=123)
        nb2, _ = c.graph_sample_neighbors(
            51, np.array([7], np.uint64), k=8, seed=123)
        assert cnt1[0] == 8
        np.testing.assert_array_equal(nb1, nb2)  # same seed, same sample
        assert len(set(nb1[0].tolist())) == 8    # without replacement
        assert set(nb1[0].tolist()) <= set(dst.tolist())
        nb3, _ = c.graph_sample_neighbors(
            51, np.array([7], np.uint64), k=8, seed=999)
        assert not np.array_equal(nb1, nb3)  # different seed differs

    def test_weighted_sampling_prefers_heavy_edges(self, ps_pair):
        _, c = ps_pair
        # node 9: one heavy edge (w=100) among 49 light ones (w=0.01)
        n_nb = 50
        src = np.full(n_nb, 9, np.uint64)
        dst = np.arange(200, 200 + n_nb, dtype=np.uint64)
        w = np.full(n_nb, 0.01, np.float32)
        w[0] = 100.0
        c.graph_add_edges(52, src, dst, w)
        hits = 0
        for seed in range(20):
            nb, _ = c.graph_sample_neighbors(
                52, np.array([9], np.uint64), k=5, seed=seed)
            if 200 in nb[0].tolist():
                hits += 1
        assert hits >= 18, hits  # heavy edge nearly always sampled


    def test_graph_checkpoint_roundtrip(self, ps_pair, tmp_path):
        import os
        _, c = ps_pair
        src = np.array([1, 1, 2], np.uint64)
        dst = np.array([10, 11, 20], np.uint64)
        c.graph_add_edges(53, src, dst, np.array([1, 2, 3], np.float32))
        assert c.table_size(53) == 2  # node_count via CMD_TABLE_SIZE
        ck = str(tmp_path / "gck")
        os.makedirs(ck, exist_ok=True)
        c.save(ck)
        # overwrite in-memory state, then restore
        c.graph_add_edges(53, np.array([1], np.uint64),
                          np.array([99], np.uint64))
        c.load(ck)
        nb, cnt = c.graph_sample_neighbors(
            53, np.array([1, 2], np.uint64), k=5)
        assert cnt.tolist() == [2, 1]
        assert set(nb[0, :2].tolist()) == {10, 11}
        assert 99 not in nb[0].tolist()


    def test_graph_khop_sample(self, ps_pair):
        """Two-hop sampling: chain graph 1->2->3->4; frontier advances."""
        _, c = ps_pair
        src = np.array([1, 2, 3], np.uint64)
        dst = np.array([2, 3, 4], np.uint64)
        c.graph_add_edges(54, src, dst)
        hops = c.graph_khop_sample(54, np.array([1], np.uint64), [2, 2])
        assert len(hops) == 2
        nb0, cnt0, f0 = hops[0]
        assert f0.tolist() == [1] and cnt0[0] == 1 and nb0[0, 0] == 2
        nb1, cnt1, f1 = hops[1]
        assert f1.tolist() == [2] and nb1[0, 0] == 3
        # dead-end frontier stops early
        hops2 = c.graph_khop_sample(54, np.array([4], np.uint64), [2, 2])
        assert len(hops2) == 1 and hops2[0][1][0] == 0


class TestSpillCompaction:
    def test_spill_restore_cycles_do_not_grow_file_unboundedly(
            self, ps_pair, tmp_path):
        """ADVICE r2: the spill file is append-only and every restore
        leaves a dead record; daily maintenance must compact once dead
        records dominate, or the file grows without bound."""
        import glob
        import os
        _, c = ps_pair
        c.create_table(TableConfig(table_id=45, kind="sparse", dim=4,
                                   optimizer="sgd", learning_rate=0.5))
        c.set_spill(45, str(tmp_path))
        cold = np.arange(1000, 2500, dtype=np.uint64)  # 1500 rows
        c.pull_sparse(45, cold)
        sizes = []
        for cycle in range(3):
            for _ in range(2):
                c.shrink(45, threshold=-1.0, max_unseen_days=10**6)
            n = c.spill_cold(45, max_unseen_days=1)
            assert n == 1500, (cycle, n)
            f = max(glob.glob(str(tmp_path) + "/*"), key=os.path.getsize)
            sizes.append(os.path.getsize(f))
            c.pull_sparse(45, cold)  # restore everything -> all dead
        # generation size = first spill; after compaction the file must be
        # back near ONE generation, not cycle x generations
        assert sizes[-1] <= sizes[0] * 1.5, sizes


class TestGeoCadence:
    def test_geo_sync_fires_per_training_step_with_multiple_tables(
            self, ps_pair):
        """ADVICE r2: with N sparse tables pushed once per step, geo_sync
        must fire every geo_push_steps STEPS (per-table counters with a
        min-trigger), not every geo_push_steps/N push calls."""
        from paddle_tpu.distributed.ps.communicator import GeoCommunicator
        _, c = ps_pair
        c.create_table(TableConfig(table_id=50, kind="sparse", dim=4))
        c.create_table(TableConfig(table_id=51, kind="sparse", dim=4))
        geo = GeoCommunicator(c, geo_push_steps=4)
        synced_at = []
        orig = geo.geo_sync
        step_box = [0]
        geo.geo_sync = lambda: (synced_at.append(step_box[0]), orig())[1]
        keys = np.arange(8, dtype=np.uint64)
        g = np.ones((8, 4), np.float32)
        for s in range(1, 13):
            step_box[0] = s
            geo.push_sparse(50, keys, g)
            geo.push_sparse(51, keys, g)
        assert synced_at == [4, 8, 12], synced_at
