"""Unified structured event log (profiler/events.py): schema contract,
ring + JSONL sink, emitter wiring across subsystems, and the
tools/obs_tail.py renderer.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fault
from paddle_tpu.profiler import events
from paddle_tpu.profiler.events import (EventLog, validate_event,
                                        default_event_log)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    fault.reset()
    default_event_log().clear()
    yield
    fault.reset()
    default_event_log().clear()


class TestSchema:
    def test_emit_produces_valid_record(self):
        rec = events.emit("retrace", site="eager", name="matmul",
                          delta="dim0 4->6")
        validate_event(rec)
        assert rec["kind"] == "retrace"
        assert rec["host"]
        assert rec["severity"] == "info"
        assert rec["site"] == "eager"

    def test_payload_cannot_override_reserved_keys(self):
        rec = events.emit("retrace", **{"site": "eager"})
        before = rec["ts"]
        rec2 = default_event_log().emit("retrace", site="x")
        assert rec2["ts"] >= before  # ts always stamped by the log

    def test_validate_rejects_bad_records(self):
        good = {"ts": time.time(), "kind": "retrace", "host": "h"}
        validate_event(good)
        for mutate in (
                lambda r: r.pop("ts"),
                lambda r: r.pop("kind"),
                lambda r: r.pop("host"),
                lambda r: r.__setitem__("kind", "Not-Valid!"),
                lambda r: r.__setitem__("kind", ""),
                lambda r: r.__setitem__("severity", "fatal"),
                lambda r: r.__setitem__("ts", "yesterday"),
                lambda r: r.__setitem__("host", "")):
            bad = dict(good)
            mutate(bad)
            with pytest.raises(ValueError, match="invalid event"):
                validate_event(bad)

    def test_known_kinds_are_schema_legal(self):
        for kind in events.KINDS:
            validate_event({"ts": 0.0, "kind": kind, "host": "h"})


class TestRingAndSink:
    def test_ring_is_bounded_and_draining_reads(self):
        log = EventLog(capacity=5)
        for i in range(9):
            log.emit("retrace", i=i)
        recs = log.recent(100)
        assert len(recs) == 5
        assert [r["i"] for r in recs] == [4, 5, 6, 7, 8]
        assert log.counts()["retrace"] == 9

    def test_kind_and_severity_filters(self):
        log = EventLog(capacity=32)
        log.emit("retrace", i=1)
        log.emit("barrier_abort", severity="warn", i=2)
        log.emit("device_oom", severity="error", i=3)
        assert [r["i"] for r in log.recent(10, kind="retrace")] == [1]
        assert [r["i"] for r in log.recent(10, min_severity="warn")] == [2, 3]

    def test_jsonl_sink_appends_valid_lines(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        log = EventLog(capacity=8, jsonl_path=path)
        log.emit("retrace", site="eager", name="op")
        log.emit("fleet_straggler", severity="warn", straggler="trainer-1")
        log.close()
        lines = open(path).read().strip().splitlines()
        assert len(lines) == 2
        for line in lines:
            validate_event(json.loads(line))

    def test_kill_switch(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_EVENTS", "0")
        log = EventLog(capacity=8)
        assert log.emit("retrace") is None
        assert log.recent(10) == []


class TestEmitterWiring:
    """The subsystems actually funnel into the default log."""

    def test_retrace_emits_event(self):
        from paddle_tpu.profiler.watchdog import RetraceWatchdog
        wd = RetraceWatchdog()
        wd.observe("eager", "evtest_op", [np.zeros((2, 2), np.float32)])
        wd.observe("eager", "evtest_op", [np.zeros((3, 2), np.float32)])
        recs = [r for r in events.recent(50, kind="retrace")
                if r.get("name") == "evtest_op"]
        assert len(recs) == 1
        assert "dim0 2->3" in recs[0]["delta"]

    def test_fault_injection_emits_event(self):
        fault.configure("evtest.site", times=1)
        with pytest.raises(Exception):
            fault.site("evtest.site")
        recs = [r for r in events.recent(50, kind="fault_injected")
                if r.get("site") == "evtest.site"]
        assert len(recs) == 1
        assert recs[0]["severity"] == "warn"

    def test_retry_exhausted_and_recovered_emit(self):
        from paddle_tpu.fault import RetryPolicy, RetryExhaustedError
        pol = RetryPolicy(max_attempts=2, base_delay=0.001)
        with pytest.raises(RetryExhaustedError):
            pol.call(lambda: (_ for _ in ()).throw(ValueError("x")),
                     op="evtest.op")
        assert [r for r in events.recent(50, kind="retry_exhausted")
                if r.get("op") == "evtest.op"]
        attempts = {"n": 0}

        def flaky():
            attempts["n"] += 1
            if attempts["n"] == 1:
                raise ValueError("first")
            return 7

        assert pol.call(flaky, op="evtest.flaky") == 7
        assert [r for r in events.recent(50, kind="retry_recovered")
                if r.get("op") == "evtest.flaky"]

    def test_device_oom_emits_event(self, monkeypatch):
        from paddle_tpu.fault import DeviceOOMError
        fault.configure("device.alloc", times=1)
        a = paddle.to_tensor(np.ones((4,), np.float32))
        b = paddle.to_tensor(np.ones((4,), np.float32))
        with pytest.raises(DeviceOOMError):
            a + b
        recs = events.recent(50, kind="device_oom")
        assert recs and recs[-1]["severity"] == "error"

    def test_delay_kind_sleeps_instead_of_raising(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_FAULT_DELAY", "0.08")
        fault.configure("evtest.slow", times=1, kind="delay")
        t0 = time.perf_counter()
        fault.site("evtest.slow")  # must NOT raise
        assert time.perf_counter() - t0 >= 0.07
        t0 = time.perf_counter()
        fault.site("evtest.slow")  # rule exhausted: no delay
        assert time.perf_counter() - t0 < 0.05
        assert [r for r in events.recent(50, kind="fault_injected")
                if r.get("site") == "evtest.slow"
                and r.get("fault_kind") == "delay"]


class TestObsTail:
    def _write(self, tmp_path, extra_garbage=True):
        path = str(tmp_path / "events.jsonl")
        now = time.time()
        recs = [
            {"ts": now - 3, "kind": "retrace", "host": "trainer-0",
             "severity": "info", "name": "matmul"},
            {"ts": now - 2, "kind": "barrier_abort", "host": "trainer-1",
             "severity": "warn", "step": 4, "reason": "timeout"},
            {"ts": now - 1, "kind": "fleet_straggler", "host": "trainer-0",
             "severity": "warn", "straggler": "trainer-1"},
        ]
        with open(path, "w") as f:
            if extra_garbage:
                f.write("not json\n")
            for r in recs:
                f.write(json.dumps(r) + "\n")
        return path

    def _run(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "obs_tail.py"),
             *args], capture_output=True, text=True, timeout=60)

    def test_renders_all_and_reports_garbage(self, tmp_path):
        r = self._run(self._write(tmp_path))
        assert r.returncode == 0
        assert "retrace" in r.stdout and "fleet_straggler" in r.stdout
        assert "skipped 1" in r.stderr

    def test_kind_filter_and_last_n(self, tmp_path):
        path = self._write(tmp_path)
        r = self._run(path, "--kind", "barrier_abort")
        assert r.returncode == 0
        lines = [l for l in r.stdout.splitlines() if l.strip()]
        assert len(lines) == 1 and "reason=timeout" in lines[0]
        r = self._run(path, "-n", "1", "--json")
        rec = json.loads(r.stdout.strip())
        assert rec["kind"] == "fleet_straggler"

    def test_severity_and_host_filters(self, tmp_path):
        path = self._write(tmp_path)
        r = self._run(path, "--min-severity", "warn", "--host", "trainer-1")
        lines = [l for l in r.stdout.splitlines() if l.strip()]
        assert len(lines) == 1 and "barrier_abort" in lines[0]

    def test_unusable_input(self, tmp_path):
        bad = str(tmp_path / "bad.jsonl")
        open(bad, "w").write("nope\n")
        assert self._run(bad).returncode == 2

    def test_runtime_sink_is_tailable(self, tmp_path, monkeypatch):
        """The PADDLE_TPU_EVENT_LOG file the runtime writes parses through
        obs_tail end to end."""
        path = str(tmp_path / "runtime.jsonl")
        log = EventLog(capacity=8, jsonl_path=path)
        log.emit("elastic_restart", severity="warn", reason="failure",
                 restart=1)
        log.close()
        r = self._run(path, "--kind", "elastic_restart")
        assert r.returncode == 0 and "reason=failure" in r.stdout


class TestSinkRotation:
    """Size-based JSONL sink rotation (PADDLE_TPU_EVENT_LOG_MAX_MB,
    keep-last-K) and obs_tail's transparent rotated-sibling reads."""

    def _fill(self, tmp_path, monkeypatch, n=200, max_mb="0.0005", keep="2"):
        monkeypatch.setenv("PADDLE_TPU_EVENT_LOG_MAX_MB", max_mb)
        monkeypatch.setenv("PADDLE_TPU_EVENT_LOG_KEEP", keep)
        path = str(tmp_path / "ev.jsonl")
        log = EventLog(capacity=8, jsonl_path=path)
        for i in range(n):
            log.emit("retrace", seq=i)
        log.close()
        return path

    def test_rotates_and_keeps_last_k(self, tmp_path, monkeypatch):
        path = self._fill(tmp_path, monkeypatch)
        files = sorted(os.listdir(tmp_path))
        assert "ev.jsonl" in files and "ev.jsonl.1" in files \
            and "ev.jsonl.2" in files
        assert "ev.jsonl.3" not in files  # keep=2 bounds the rotated set
        # every retained file respects the size cap (+ one line of slack)
        cap = 0.0005 * (1 << 20) + 200
        for f in files:
            assert os.path.getsize(tmp_path / f) <= cap

    def test_no_rotation_without_knob(self, tmp_path, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_EVENT_LOG_MAX_MB", raising=False)
        path = str(tmp_path / "ev.jsonl")
        log = EventLog(capacity=8, jsonl_path=path)
        for i in range(300):
            log.emit("retrace", seq=i)
        log.close()
        assert os.listdir(tmp_path) == ["ev.jsonl"]

    def test_garbled_knob_disables_rotation(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_EVENT_LOG_MAX_MB", "lots")
        path = str(tmp_path / "ev.jsonl")
        log = EventLog(capacity=8, jsonl_path=path)
        for i in range(50):
            log.emit("retrace", seq=i)
        log.close()
        assert os.listdir(tmp_path) == ["ev.jsonl"]

    def test_obs_tail_reads_rotated_stream_in_order(self, tmp_path,
                                                    monkeypatch):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import obs_tail
            path = self._fill(tmp_path, monkeypatch)
            recs, bad = obs_tail.parse_lines(obs_tail.read_lines(path))
            assert bad == 0 and len(recs) > 10
            seqs = [r["seq"] for r in recs]
            # one chronological stream across path.2, path.1, path
            assert seqs == sorted(seqs)
            # and strictly more than the live file alone holds
            live, _ = obs_tail.parse_lines(open(path).readlines())
            assert len(recs) > len(live)
        finally:
            sys.path.remove(os.path.join(REPO, "tools"))

    def test_health_kinds_validate(self):
        """The new health event kinds are schema-legal end to end."""
        log = EventLog(capacity=8)
        for kind, payload in (
                ("tensor_health", {"op": "matmul", "layer": "fc2",
                                   "bad_kind": "nan", "src": "eager"}),
                ("health_alert", {"signal": "loss_spike", "z": 8.1}),
                ("health_rollback", {"restored_step": 40,
                                     "reason": "nonfinite"}),
                ("fleet_health", {"unhealthy": "trainer-1",
                                  "status": "diverged"})):
            rec = log.emit(kind, severity="warn", **payload)
            validate_event(rec)
