"""Namespace-alias audit (VERDICT r5 Missing #7 / Weak #4): the reference
exposes these names at `paddle.*` paths; walking them in CI keeps the
namespace claims from rotting again."""
import importlib

import pytest

import paddle_tpu as paddle

# dotted paths relative to the package root; each must resolve to a
# non-None attribute (reference: python/paddle/__init__.py re-exports)
ALIASED_NAMES = [
    # paddle.callbacks -> hapi.callbacks
    "callbacks.Callback",
    "callbacks.EarlyStopping",
    "callbacks.ModelCheckpoint",
    "callbacks.ProgBarLogger",
    "callbacks.LRScheduler",
    # paddle.distributed dataset re-exports (live on fleet)
    "distributed.InMemoryDataset",
    "distributed.QueueDataset",
    # paddle.incubate optimizer re-exports
    "incubate.LookAhead",
    "incubate.ModelAverage",
]


@pytest.mark.parametrize("dotted", ALIASED_NAMES)
def test_alias_resolves(dotted):
    obj = paddle
    for part in dotted.split("."):
        obj = getattr(obj, part)
    assert obj is not None


def test_callbacks_importable_as_module():
    mod = importlib.import_module("paddle_tpu.callbacks")
    assert mod is paddle.callbacks


def test_aliases_are_the_canonical_objects():
    from paddle_tpu.distributed.fleet.dataset import (InMemoryDataset,
                                                      QueueDataset)
    from paddle_tpu.incubate.optimizer import LookAhead, ModelAverage
    assert paddle.distributed.InMemoryDataset is InMemoryDataset
    assert paddle.distributed.QueueDataset is QueueDataset
    assert paddle.incubate.LookAhead is LookAhead
    assert paddle.incubate.ModelAverage is ModelAverage
    assert paddle.callbacks is paddle.hapi.callbacks
