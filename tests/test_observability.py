"""End-to-end runtime observability (PR 2): per-op host tracing through the
eager dispatch, recorder drain-vs-record thread safety, Benchmark timer
degradation paths, scheduler window edges + chrome-trace schema, collective
byte accounting, DataLoader wait wiring, and the ThroughputMonitor step
JSONL.

All CPU-only — the acceptance bar is that a one-step eager train loop under
an active Profiler yields per-op chrome rows, summary op rows, and a
prometheus snapshot carrying op/collective/retrace counters.
"""
import json
import threading
import time

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn, optimizer
from paddle_tpu import profiler as prof
from paddle_tpu.distributed.topology import HybridCommunicateGroup, build_mesh
from paddle_tpu.profiler import metrics
from paddle_tpu.profiler.monitor import (ThroughputMonitor, make_step_record,
                                         validate_step_record)
from paddle_tpu.profiler.recorder import HostSpan, get_recorder, now_ns
from paddle_tpu.profiler.timer import Benchmark
from paddle_tpu.profiler.watchdog import get_watchdog


@pytest.fixture()
def clean_recorder():
    rec = get_recorder()
    rec.clear()
    yield rec
    rec.enabled = False
    rec.clear()


def _one_step_eager_train(steps=1):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 4))
    opt = optimizer.SGD(parameters=net.parameters(), learning_rate=0.1)
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    y = paddle.to_tensor(np.zeros((4,), np.int64))
    lossf = nn.CrossEntropyLoss()
    for _ in range(steps):
        loss = lossf(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    return float(loss)


class TestOpLevelTracing:
    """Acceptance: eager train loop under RECORD → op spans + summary rows
    + prometheus counters."""

    def test_train_loop_emits_op_spans_and_counters(self, tmp_path,
                                                    clean_recorder):
        p = prof.Profiler(targets=[prof.ProfilerTarget.CPU])
        p.start()
        _one_step_eager_train()
        p.stop()
        path = p.export(str(tmp_path / "trace.json"))
        data = json.load(open(path))
        op_events = [e for e in data["traceEvents"] if e["cat"] == "Operator"]
        assert op_events, "per-op host spans missing from chrome trace"
        names = {e["name"] for e in op_events}
        assert "linear" in names
        lin = next(e for e in op_events if e["name"] == "linear")
        assert lin["args"]["bytes_est"] > 0
        assert lin["args"]["shapes"][0] == [4, 8]
        assert "float32" in lin["args"]["dtypes"][0]
        # summary has op rows
        report = prof.summary_report(p.statistic_data())
        assert "linear" in report and "backward" in report
        # prometheus snapshot carries op/collective/retrace counter families
        txt = metrics.default_registry().to_prometheus_text()
        assert 'paddle_tpu_op_calls_total{op="linear"}' in txt
        assert "paddle_tpu_collective_bytes_total" in txt
        assert "paddle_tpu_jit_retraces_total" in txt

    def test_no_op_spans_outside_record_window(self, clean_recorder):
        _one_step_eager_train()
        assert get_recorder().collect() == []

    def test_metrics_disabled_skips_counters(self, clean_recorder):
        reg = metrics.default_registry()
        metrics.set_enabled(False)
        try:
            before = reg.counter("op_calls_total").total()
            _one_step_eager_train()
            assert reg.counter("op_calls_total").total() == before
        finally:
            metrics.set_enabled(True)

    def test_op_bytes_counter_accumulates(self):
        reg = metrics.default_registry()
        before = reg.counter("op_bytes_total").value(op="matmul")
        x = paddle.to_tensor(np.ones((8, 8), np.float32))
        with paddle.no_grad():
            (x @ x).numpy()
        # 2 inputs + 1 output of 8x8 f32 = 768 bytes minimum
        assert reg.counter("op_bytes_total").value(op="matmul") >= before + 768

    def test_op_flops_counter_exact_for_matmul(self):
        reg = metrics.default_registry()
        before = reg.counter("op_flops_total").value(op="matmul")
        x = paddle.to_tensor(np.ones((8, 8), np.float32))
        with paddle.no_grad():
            (x @ x).numpy()
        # 2*M*K*N = 2*8*8*8 = 1024 for one matmul
        assert reg.counter("op_flops_total").value(op="matmul") \
            == before + 1024

    def test_ops_under_jit_trace_not_counted(self):
        """An op re-entered during a to_static trace executes per compiled
        run, not per Python call — the eager counters must not gain phantom
        dispatches from tracing (nor from cache-hit replays)."""
        reg = metrics.default_registry()
        st = paddle.jit.to_static(nn.Linear(8, 4))
        x = paddle.to_tensor(np.ones((2, 8), np.float32))
        st(x)  # first call: traces the forward with tracer-backed Tensors
        before = reg.counter("op_calls_total").value(op="linear")
        st(x)  # cache hit: no dispatch at all
        st(paddle.to_tensor(np.ones((5, 8), np.float32)))  # re-trace
        assert reg.counter("op_calls_total").value(op="linear") == before

    def test_memory_gauges_honor_kill_switch(self):
        metrics.set_enabled(False)
        try:
            reg = metrics.MetricsRegistry()
            metrics.update_device_memory_gauges(reg)
            assert "device_bytes_in_use" not in reg.names()
        finally:
            metrics.set_enabled(True)


class TestRecorderConcurrency:
    """Satellite: collect() drains per-thread under the buffer lock — spans
    recorded mid-collect are neither lost nor duplicated."""

    def test_concurrent_record_and_collect(self, clean_recorder):
        rec = clean_recorder
        rec.enabled = True
        n_threads, per_thread = 4, 400
        stop_collect = threading.Event()
        collected, errors = [], []

        def producer(tid):
            try:
                for i in range(per_thread):
                    t = now_ns()
                    rec.push(HostSpan(name=f"rectest_{tid}_{i}", start_ns=t,
                                      end_ns=t + 1,
                                      tid=threading.get_ident()))
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def collector():
            while not stop_collect.is_set():
                collected.extend(rec.collect())

        cth = threading.Thread(target=collector)
        cth.start()
        producers = [threading.Thread(target=producer, args=(t,))
                     for t in range(n_threads)]
        for t in producers:
            t.start()
        for t in producers:
            t.join()
        stop_collect.set()
        cth.join()
        collected.extend(rec.collect())  # final drain
        assert not errors
        # count ONLY this test's spans: enabling the global recorder means a
        # background thread leaked by an earlier test (prefetchers, push
        # workers) may add its own op spans to the shared buffers
        names = [s.name for s in collected if s.name.startswith("rectest_")]
        assert len(names) == n_threads * per_thread, \
            f"lost {n_threads * per_thread - len(names)} spans"
        assert len(set(names)) == len(names), "duplicated spans"

    def test_collect_is_draining(self, clean_recorder):
        rec = clean_recorder
        rec.enabled = True
        t = now_ns()
        rec.push(HostSpan("a", t, t + 1, 0))
        assert [s.name for s in rec.collect()] == ["a"]
        assert rec.collect() == []


class TestBenchmarkTimerAudit:
    """Satellite: ips degrades gracefully — no ZeroDivision on any path."""

    def test_step_without_reader_fetch(self):
        bm = Benchmark()
        bm.begin()
        for _ in range(3):
            bm.step(num_samples=8)
        bm.end()
        info = bm.step_info()
        assert "reader_cost: 0.00000" in info and "ips" in info
        rep = bm.report()
        assert rep["reader_cost_avg_s"] == 0.0 and rep["ips"] > 0

    def test_num_samples_none_falls_back_to_steps_per_sec(self):
        bm = Benchmark()
        bm.begin()
        for _ in range(3):
            bm.step()  # no sample counts at all
        bm.end()
        info = bm.step_info()
        assert "steps/s" in info
        rep = bm.report()
        assert rep["ips"] == 0.0 and rep["steps_per_sec"] > 0
        assert rep["total_samples"] == 0

    def test_fresh_benchmark_all_zero_no_raise(self):
        bm = Benchmark()
        assert bm.step_info() == "reader_cost: 0.00000 s, batch_cost: 0.00000 s"
        rep = bm.report()
        assert rep["ips"] == 0.0 and rep["steps_per_sec"] == 0.0

    def test_step_before_begin_arms_only(self):
        bm = Benchmark()
        bm.step(num_samples=16)  # arms the timer; no window to record yet
        assert bm.batch.count == 0 and bm.total_samples == 0
        bm.step(num_samples=16)
        assert bm.batch.count == 1 and bm.total_samples == 16

    def test_end_without_begin(self):
        bm = Benchmark()
        bm.end()
        assert bm.report()["total_time_s"] == 0.0

    def test_reset(self):
        bm = Benchmark()
        bm.begin()
        bm.step(num_samples=4)
        bm.step(num_samples=4)
        bm.reset()
        assert bm.batch.count == 0 and bm.total_samples == 0
        assert bm.report()["ips"] == 0.0


class TestSchedulerEdges:
    """Satellite: make_scheduler window edges."""

    def test_skip_first_shifts_whole_pattern(self):
        S = prof.ProfilerState
        sch = prof.make_scheduler(closed=0, ready=0, record=2, repeat=1,
                                  skip_first=3)
        assert [sch(i) for i in range(6)] == [
            S.CLOSED, S.CLOSED, S.CLOSED, S.RECORD, S.RECORD_AND_RETURN,
            S.CLOSED]

    def test_single_step_record_and_return(self):
        S = prof.ProfilerState
        sch = prof.make_scheduler(closed=0, ready=0, record=1, repeat=0)
        # record=1 means EVERY step is its window's last -> always R&R
        assert [sch(i) for i in range(3)] == [S.RECORD_AND_RETURN] * 3

    def test_repeat_stops_exactly_after_n_periods(self):
        S = prof.ProfilerState
        sch = prof.make_scheduler(closed=1, ready=1, record=1, repeat=2)
        got = [sch(i) for i in range(7)]
        assert got == [S.CLOSED, S.READY, S.RECORD_AND_RETURN,
                       S.CLOSED, S.READY, S.RECORD_AND_RETURN, S.CLOSED]

    def test_ready_window_does_not_record(self, clean_recorder):
        sch = prof.make_scheduler(closed=0, ready=1, record=1, repeat=1)
        traces = []
        p = prof.Profiler(targets=[prof.ProfilerTarget.CPU], scheduler=sch,
                          on_trace_ready=lambda pr: traces.append(
                              len(pr._spans)))
        p.start()
        with prof.RecordEvent("ready_phase"):
            pass
        p.step()
        with prof.RecordEvent("record_phase"):
            pass
        p.step()
        p.stop()
        assert traces == [1]  # only record_phase landed


class TestChromeTraceSchema:
    """Satellite: export is valid JSON with monotonic ts and distinct tids."""

    def test_schema(self, tmp_path, clean_recorder):
        p = prof.Profiler(targets=[prof.ProfilerTarget.CPU])
        p.start()

        def side_thread():
            with prof.RecordEvent("side_span"):
                time.sleep(0.002)

        th = threading.Thread(target=side_thread)
        th.start()
        with prof.RecordEvent("main_span"):
            time.sleep(0.002)
        th.join()
        p.stop()
        path = p.export(str(tmp_path / "schema.json"))
        data = json.load(open(path))  # valid JSON
        evs = data["traceEvents"]
        assert len(evs) >= 2
        for e in evs:
            assert e["ph"] == "X" and e["dur"] >= 0
            assert isinstance(e["ts"], float) and isinstance(e["tid"], int)
        ts = [e["ts"] for e in evs]
        assert ts == sorted(ts), "ts must be monotonic (sorted by start)"
        assert len({e["tid"] for e in evs}) >= 2, \
            "spans from different threads must keep distinct tids"
        assert data["metadata"]["producer"] == "paddle_tpu.profiler"


class TestCollectiveMetrics:
    def setup_method(self, _):
        mesh = build_mesh({"dp": 8})
        hcg = HybridCommunicateGroup(mesh=mesh)
        dist.set_hybrid_communicate_group(hcg)
        dist.destroy_process_group()
        self.mesh = mesh
        self.group = dist.new_group(axis_name="dp")

    def teardown_method(self, _):
        dist.set_hybrid_communicate_group(None)
        dist.destroy_process_group()

    def test_all_reduce_accounted_as_ici_bytes(self):
        reg = metrics.default_registry()
        calls0 = reg.counter("collective_calls_total").value(
            kind="all_reduce", link="ici")
        bytes0 = reg.counter("collective_bytes_total").value(
            kind="all_reduce", link="ici")
        x = paddle.to_tensor(np.ones((8, 4), np.float32))
        x.data = jax.device_put(x.data, NamedSharding(self.mesh, P("dp")))
        dist.all_reduce(x, group=self.group)
        assert reg.counter("collective_calls_total").value(
            kind="all_reduce", link="ici") == calls0 + 1
        assert reg.counter("collective_bytes_total").value(
            kind="all_reduce", link="ici") == bytes0 + 8 * 4 * 4

    def test_broadcast_and_allgather_kinds(self):
        reg = metrics.default_registry()
        b0 = reg.counter("collective_calls_total").value(
            kind="broadcast", link="ici")
        g0 = reg.counter("collective_calls_total").value(
            kind="all_gather", link="ici")
        x = paddle.to_tensor(np.ones((8,), np.float32))
        dist.broadcast(x, src=0, group=self.group)
        dist.all_gather(None, paddle.to_tensor(np.ones((4,), np.float32)),
                        group=self.group)
        assert reg.counter("collective_calls_total").value(
            kind="broadcast", link="ici") == b0 + 1
        assert reg.counter("collective_calls_total").value(
            kind="all_gather", link="ici") == g0 + 1

    def test_traced_collectives_not_counted(self):
        """An all_reduce on a TRACER (inside shard_map/pjit) must NOT hit
        the counters — it executes per compiled run, not per Python call,
        so counting the trace would be meaningless."""
        from paddle_tpu._jax_compat import shard_map
        reg = metrics.default_registry()
        before = reg.counter("collective_calls_total").total()

        def f(a):
            return dist.all_reduce(a, group=self.group)

        import jax.numpy as jnp
        arr = jnp.ones((8, 2), jnp.float32)
        shard_map(f, mesh=self.mesh, in_specs=P("dp"), out_specs=P("dp"),
                  check_vma=False)(arr)
        assert reg.counter("collective_calls_total").total() == before


class TestDataLoaderWait:
    def test_reader_wait_feeds_benchmark_and_metrics(self):
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 16

            def __getitem__(self, i):
                return np.full((4,), i, np.float32)

        reg = metrics.default_registry()
        bm = prof.benchmark()
        reader_cnt0 = bm.reader.count
        batches0 = reg.counter("dataloader_batches_total").total()
        loader = DataLoader(DS(), batch_size=4, num_workers=0)
        out = list(loader)
        assert len(out) == 4
        assert bm.reader.count == reader_cnt0 + 4
        assert reg.counter("dataloader_batches_total").total() == batches0 + 4
        assert reg.counter("dataloader_wait_seconds_total").total() >= 0


class TestThroughputMonitor:
    def test_records_and_jsonl(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        mon = ThroughputMonitor(window=2, jsonl_path=path,
                                samples_per_step=32,
                                flops_per_sample=1e9, peak_flops=1e12)
        mon.on_train_begin()
        mon.on_epoch_begin(0)
        for step in range(5):
            mon.on_train_batch_begin(step)
            time.sleep(0.001)
            mon.on_train_batch_end(step)
        mon.on_epoch_end(0)
        mon.on_train_end()
        # 5 steps, window 2 -> 2 full windows + 1 partial flush
        assert len(mon.records) == 3
        for rec in mon.records:
            validate_step_record(rec)
            assert 0.0 <= rec["data_wait_frac"] <= 1.0
            assert rec["mfu_est"] is not None and rec["mfu_est"] > 0
        assert mon.records[0]["window_steps"] == 2
        assert mon.records[-1]["window_steps"] == 1
        assert mon.records[-1]["step"] == 5
        lines = [json.loads(l) for l in open(path)]
        assert lines == mon.records

    def test_monitor_counts_retraces_in_window(self):
        wd = get_watchdog()
        wd.reset()
        mon = ThroughputMonitor(window=10)
        mon.on_train_begin()
        mon.on_train_batch_begin(0)
        wd.observe("s", "f", [np.ones((2,))])
        wd.observe("s", "f", [np.ones((3,))])  # retrace inside the window
        mon.on_train_batch_end(0)
        mon.on_train_end()
        assert mon.records[-1]["retraces"] == 1
        wd.reset()

    def test_hapi_fit_integration(self):
        """ThroughputMonitor rides Model.fit as a plain callback."""
        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return (np.ones((4,), np.float32),
                        np.array(i % 2, np.int64))

        paddle.seed(0)
        model = paddle.Model(nn.Linear(4, 2))
        model.prepare(optimizer=optimizer.SGD(
            parameters=model.parameters(), learning_rate=0.1),
            loss=nn.CrossEntropyLoss())
        mon = ThroughputMonitor(window=2, samples_per_step=4)
        model.fit(DS(), batch_size=4, epochs=1, verbose=0, callbacks=[mon])
        assert mon.records, "fit must emit at least one step record"
        for rec in mon.records:
            validate_step_record(rec)

    def test_make_step_record_degrades(self):
        rec = make_step_record(step=0, window_steps=0, window_time_s=0.0)
        validate_step_record(rec)
        assert rec["steps_per_sec"] == 0.0 and rec["ips"] is None
        assert rec["mfu_est"] is None and rec["step_time_ms"] == 0.0

    def test_validate_rejects_bad_records(self):
        good = make_step_record(step=1, window_steps=1, window_time_s=0.1)
        bad = dict(good)
        del bad["ts"]
        with pytest.raises(ValueError, match="ts"):
            validate_step_record(bad)
        bad2 = dict(good, extra_key=1)
        with pytest.raises(ValueError, match="extra_key"):
            validate_step_record(bad2)
        bad3 = dict(good, data_wait_frac=1.5)
        with pytest.raises(ValueError, match="data_wait_frac"):
            validate_step_record(bad3)

    def test_step_records_sample_device_memory(self):
        """Per-step device-memory watermarks land in the step record (the
        CPU backend has no memory_stats, so the live-arrays fallback
        feeds them — live tensors exist, so the sample is > 0)."""
        _keepalive = paddle.to_tensor(np.ones((64, 64), np.float32))
        mon = ThroughputMonitor(window=1)
        mon.on_train_begin()
        mon.on_train_batch_begin(0)
        mon.on_train_batch_end(0)
        mon.on_train_end()
        rec = mon.records[-1]
        validate_step_record(rec)
        assert rec["device_mem_bytes"] and rec["device_mem_bytes"] > 0
        assert rec["device_mem_peak_bytes"] >= rec["device_mem_bytes"]


class TestStepDiagnosis:
    """diagnose_window decomposes a window's wall into the registry's cost
    terms, names the dominant one, and emits a step_diagnosis event."""

    def test_dominant_term_from_registry_deltas(self):
        from paddle_tpu.profiler import events as events_mod
        from paddle_tpu.profiler.metrics import default_registry
        from paddle_tpu.profiler.monitor import diag_signals, diagnose_window
        events_mod.default_event_log().clear()
        begin = diag_signals()
        # simulate a compile-bound window: 0.4s of xla_compile_seconds
        default_registry().get("xla_compile_seconds").observe(
            0.4, entry="diag_test", phase="backend_compile")
        rec = diagnose_window(begin, wall_s=0.5, steps=4, step=40)
        assert rec["dominant"] == "compile"
        assert rec["terms"]["compile"] == pytest.approx(0.4)
        assert rec["terms"]["unattributed"] == pytest.approx(0.1)
        assert rec["dominant_frac"] == pytest.approx(0.8)
        assert rec["steps"] == 4 and rec["step"] == 40
        evs = events_mod.recent(10, kind="step_diagnosis")
        assert evs and evs[-1]["dominant"] == "compile"
        events_mod.validate_event(evs[-1])

    def test_unattributed_dominates_idle_window(self):
        from paddle_tpu.profiler.monitor import diag_signals, diagnose_window
        rec = diagnose_window(diag_signals(), wall_s=0.2, steps=1,
                              emit=False)
        assert rec["dominant"] == "unattributed"

    def test_collective_term_fed_by_guarded_collectives(self):
        """The collective_seconds histogram (new in this PR) feeds the
        'collective' diagnosis term for every guarded eager collective."""
        from paddle_tpu.profiler.metrics import default_registry
        from paddle_tpu.profiler.monitor import diag_signals
        begin = diag_signals()
        default_registry().histogram(
            "collective_seconds", "eager collective wall time by "
            "kind").observe(0.05, kind="all_reduce")
        assert diag_signals()["collective"] - begin["collective"] \
            == pytest.approx(0.05)

    def test_monitor_emits_one_diagnosis_per_window(self):
        from paddle_tpu.profiler import events as events_mod
        events_mod.default_event_log().clear()
        mon = ThroughputMonitor(window=2)
        mon.on_train_begin()
        for step in range(4):
            mon.on_train_batch_begin(step)
            mon.on_train_batch_end(step)
        mon.on_train_end()
        assert len(mon.diagnoses) == 2
        assert len(events_mod.recent(20, kind="step_diagnosis")) == 2
        assert all(d["dominant"] for d in mon.diagnoses)

    def test_monitor_diagnose_opt_out(self):
        from paddle_tpu.profiler import events as events_mod
        events_mod.default_event_log().clear()
        mon = ThroughputMonitor(window=1, diagnose=False)
        mon.on_train_begin()
        mon.on_train_batch_begin(0)
        mon.on_train_batch_end(0)
        mon.on_train_end()
        assert not mon.diagnoses
        assert not events_mod.recent(20, kind="step_diagnosis")


class TestDeviceMemorySampling:
    def test_sample_families_and_running_peak(self):
        from paddle_tpu.profiler import metrics as metrics_mod
        big = paddle.to_tensor(np.ones((256, 256), np.float32))
        mem = metrics_mod.sample_device_memory()
        assert mem, "no devices sampled"
        dev, stats = next(iter(mem.items()))
        assert stats["bytes_in_use"] > 0
        assert stats["peak_bytes"] >= stats["bytes_in_use"]
        assert stats["src"] in ("memory_stats", "live_arrays")
        reg = metrics_mod.default_registry()
        assert reg.get("device_memory_bytes_in_use").value(device=dev) \
            == stats["bytes_in_use"]
        peak_before = stats["peak_bytes"]
        del big
        mem2 = metrics_mod.sample_device_memory()
        # the watermark never regresses even when usage drops
        assert mem2[dev]["peak_bytes"] >= peak_before \
            or mem2[dev]["src"] == "memory_stats"

    def test_sample_honors_kill_switch(self):
        from paddle_tpu.profiler import metrics as metrics_mod
        metrics_mod.set_enabled(False)
        try:
            assert metrics_mod.sample_device_memory() == {}
        finally:
            metrics_mod.set_enabled(True)
