"""Reference-checkpoint interop (VERDICT r4 missing #1): `paddle.load`
reads the reference's `.pdparams` pickle format
(`/root/reference/python/paddle/framework/io.py:568` save path:
`_build_saved_state_dict` + `_unpack_saved_dict` big-param splitting +
`reduce_varbase` tuple encoding), name-maps into the zoo, and the loaded
models reproduce golden activations."""
import pickle

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.io import match_state_dict
from paddle_tpu.framework.tensor import Tensor


def _write_reference_pdparams(path, arrays, protocol=2, split_threshold=None):
    """Emit the byte-for-byte layout the reference's paddle.save produces
    for a state_dict: plain ndarray values + StructuredToParameterName@@
    name table, with big params split into key@@.N slices."""
    save_dict = dict(arrays)
    save_dict["StructuredToParameterName@@"] = {
        k: f"param_{i}" for i, k in enumerate(arrays)}
    if split_threshold:
        unpack = {}
        for key in list(save_dict):
            v = save_dict[key]
            if isinstance(v, np.ndarray) and v.size > split_threshold:
                flat = v.flatten()
                parts = []
                for i in range(0, flat.size, split_threshold):
                    pname = f"{key}@@.{len(parts)}"
                    save_dict[pname] = flat[i:i + split_threshold]
                    parts.append(pname)
                unpack[key] = {"OriginShape": v.shape, "slices": parts}
                del save_dict[key]
        if unpack:
            save_dict["UnpackBigParamInfor@@"] = unpack
    with open(path, "wb") as f:
        pickle.dump(save_dict, f, protocol=protocol)


class TestFormatDecoding:
    def test_plain_state_dict(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        w = np.arange(6, dtype="float32").reshape(2, 3)
        _write_reference_pdparams(p, {"lin.weight": w})
        sd = paddle.load(p)
        assert "StructuredToParameterName@@" not in sd
        assert isinstance(sd["lin.weight"], Tensor)
        np.testing.assert_array_equal(sd["lin.weight"].numpy(), w)

    def test_big_param_repack(self, tmp_path):
        p = str(tmp_path / "big.pdparams")
        w = np.random.default_rng(0).normal(size=(32, 16)).astype("float32")
        _write_reference_pdparams(p, {"emb.weight": w}, split_threshold=100)
        sd = paddle.load(p)
        assert "UnpackBigParamInfor@@" not in sd
        assert not any("@@." in k for k in sd)
        np.testing.assert_array_equal(sd["emb.weight"].numpy(), w)

    def test_varbase_tuple_decoding(self, tmp_path):
        """Nested saves pickle Tensors via reduce_varbase -> ((name, arr),)
        (reference io.py:240)."""
        p = str(tmp_path / "nested.pdparams")
        arr = np.ones((3,), "float32")
        obj = {"model": {"w": (("linear_0.w_0", arr),)}, "epoch": 7,
               "StructuredToParameterName@@": {}}
        with open(p, "wb") as f:
            pickle.dump(obj, f, protocol=2)
        got = paddle.load(p)
        assert got["epoch"] == 7
        assert isinstance(got["model"]["w"], Tensor)
        assert got["model"]["w"].name == "linear_0.w_0"
        np.testing.assert_array_equal(got["model"]["w"].numpy(), arr)

    def test_return_numpy(self, tmp_path):
        p = str(tmp_path / "m.pdparams")
        _write_reference_pdparams(p, {"w": np.zeros((2,), "float32")})
        sd = paddle.load(p, return_numpy=True)
        assert isinstance(sd["w"], np.ndarray)

    def test_own_format_roundtrip_still_works(self, tmp_path):
        p = str(tmp_path / "own.pd")
        t = Tensor(np.arange(4, dtype="float32"))
        paddle.save({"a": t, "n": 3}, p)
        back = paddle.load(p)
        assert back["n"] == 3
        np.testing.assert_array_equal(back["a"].numpy(), t.numpy())


class TestZooInterop:
    def test_resnet18_loads_reference_checkpoint(self, tmp_path):
        """A reference-format resnet18 checkpoint (same structured names)
        must load and reproduce the golden logits of the weights it holds
        to 1e-3."""
        from paddle_tpu.models.resnet import resnet18
        paddle.seed(7)
        donor = resnet18()
        donor.eval()
        golden_sd = {k: np.asarray(v.numpy(), "float32")
                     for k, v in donor.state_dict().items()}
        x = paddle.to_tensor(np.random.default_rng(1).normal(
            size=(2, 3, 32, 32)).astype("float32"))
        golden = donor(x).numpy()

        p = str(tmp_path / "resnet18.pdparams")
        _write_reference_pdparams(p, golden_sd, split_threshold=200_000)
        paddle.seed(123)  # fresh, differently-initialized model
        model = resnet18()
        sd = paddle.load(p)
        matched, missing, unexpected = match_state_dict(model, sd)
        assert not missing, missing[:5]
        model.set_state_dict(matched)
        model.eval()
        got = model(x).numpy()
        np.testing.assert_allclose(got, golden, atol=1e-3, rtol=1e-3)

    def test_bert_loads_prefixed_checkpoint(self, tmp_path):
        """Ecosystem BERT checkpoints prefix every key with `bert.` and
        carry `cls.*` head keys; match_state_dict must strip/drop them and
        the loaded model must reproduce golden pooled outputs."""
        from paddle_tpu.models.bert import Bert, BertConfig
        cfg = BertConfig.tiny() if hasattr(BertConfig, "tiny") else \
            BertConfig.base()
        paddle.seed(11)
        donor = Bert(cfg)
        donor.eval()
        sd = {f"bert.{k}": np.asarray(v.numpy(), "float32")
              for k, v in donor.state_dict().items()}
        sd["cls.predictions.decoder_bias"] = np.zeros((4,), "float32")
        ids = paddle.to_tensor(
            np.random.default_rng(2).integers(
                0, cfg.vocab_size, (2, 16)).astype("int32"))
        _, golden_pooled = donor(ids)
        golden = golden_pooled.numpy()

        p = str(tmp_path / "bert.pdparams")
        _write_reference_pdparams(p, sd)
        paddle.seed(99)
        model = Bert(cfg)
        loaded = paddle.load(p)
        matched, missing, unexpected = match_state_dict(model, loaded)
        assert not missing, missing[:5]
        assert "cls.predictions.decoder_bias" in unexpected
        model.set_state_dict(matched)
        model.eval()
        _, pooled = model(ids)
        np.testing.assert_allclose(pooled.numpy(), golden, atol=1e-3,
                                   rtol=1e-3)
