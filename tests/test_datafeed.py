"""Native data-feed tests (reference: MultiSlotDataFeed unit tests,
`paddle/fluid/framework/data_feed_test.cc` and fleet dataset python tests)."""
import numpy as np
import pytest

from paddle_tpu.distributed.fleet import (DataGenerator, InMemoryDataset,
                                          QueueDataset)


def _write_multislot(path, rows):
    """rows: list of instances; each instance: list per slot of value-lists."""
    with open(path, "w") as f:
        for inst in rows:
            parts = []
            for values in inst:
                parts.append(str(len(values)))
                parts.extend(str(v) for v in values)
            f.write(" ".join(parts) + "\n")


@pytest.fixture
def slot_files(tmp_path):
    """2 files x 10 instances, slots: [sparse ids (ragged), label (float 1),
    dense floats (3)]."""
    rng = np.random.default_rng(0)
    all_rows = []
    files = []
    for fi in range(2):
        rows = []
        for i in range(10):
            ids = list(rng.integers(0, 1000, rng.integers(1, 5)))
            label = [float(fi * 10 + i) ]
            dense = [round(float(x), 3) for x in rng.normal(size=3)]
            rows.append([ids, label, dense])
        p = tmp_path / f"part-{fi}.txt"
        _write_multislot(p, rows)
        files.append(str(p))
        all_rows.extend(rows)
    return files, all_rows


def _make(ds_cls, files, batch_size=4, threads=2):
    ds = ds_cls()
    ds.set_batch_size(batch_size)
    ds.set_thread(threads)
    ds.set_filelist(files)
    ds.set_use_var(["ids", "label", "dense"],
                   types=["uint64", "float", "float"])
    return ds


class TestQueueDataset:
    def test_streams_all_instances(self, slot_files):
        files, all_rows = slot_files
        ds = _make(QueueDataset, files)
        total = 0
        labels = []
        for batch in ds:
            total += batch.batch_size
            labels.extend(batch.dense("label").ravel().tolist())
            # ragged sparse slot: lod is consistent
            lod = batch.lod("ids")
            assert lod[0] == 0 and lod[-1] == batch.values("ids").size
        assert total == 20
        assert sorted(labels) == sorted(
            float(r[1][0]) for r in all_rows)

    def test_padded_sparse(self, slot_files):
        files, _ = slot_files
        ds = _make(QueueDataset, files, batch_size=5, threads=1)
        batch = next(iter(ds))
        ids, mask = batch.padded("ids", max_len=6)
        assert ids.shape == (5, 6) and mask.shape == (5, 6)
        lod = batch.lod("ids")
        for i in range(5):
            n = min(int(lod[i + 1] - lod[i]), 6)
            assert mask[i, :n].all() and not mask[i, n:].any()


class TestQueueDatasetLifecycle:
    def test_early_exit_then_full_epoch(self, slot_files):
        """Breaking out of an epoch must not leak batches into the next one."""
        files, _ = slot_files
        ds = _make(QueueDataset, files, batch_size=4, threads=2)
        next(iter(ds))  # abandon the epoch after one batch
        total = sum(b.batch_size for b in ds)
        assert total == 20

    def test_malformed_file_raises(self, tmp_path):
        bad = tmp_path / "bad.txt"
        bad.write_text("2 1 2 1 0.5\nnot-a-count oops\n")
        ds = QueueDataset()
        ds.set_batch_size(2)
        ds.set_thread(1)
        ds.set_filelist([str(bad)])
        ds.set_use_var(["ids", "label"], types=["uint64", "float"])
        with pytest.raises(RuntimeError, match="malformed"):
            list(ds)

    def test_type_length_mismatch_raises(self):
        ds = QueueDataset()
        with pytest.raises(ValueError, match="3 slots but 2 types"):
            ds.set_use_var(["a", "b", "c"], types=["uint64", "float"])


class TestInMemoryDataset:
    def test_load_shuffle_iterate(self, slot_files):
        files, all_rows = slot_files
        ds = _make(InMemoryDataset, files, batch_size=6)
        ds.load_into_memory()
        assert ds.get_memory_data_size() == 20
        order1 = [b.dense("label").ravel().tolist() for b in ds]
        ds.local_shuffle(seed=7)
        order2 = [b.dense("label").ravel().tolist() for b in ds]
        flat1 = [x for b in order1 for x in b]
        flat2 = [x for b in order2 for x in b]
        assert sorted(flat1) == sorted(flat2)
        assert flat1 != flat2  # shuffle changed the order
        # re-iteration after shuffle serves the same epoch again
        flat3 = [x for b in ds for x in b.dense("label").ravel().tolist()]
        assert flat3 == flat2

    def test_dense_slot_rectangular(self, slot_files):
        files, _ = slot_files
        ds = _make(InMemoryDataset, files, batch_size=20)
        ds.load_into_memory()
        batch = next(iter(ds))
        d = batch.dense("dense")
        assert d.shape == (20, 3)


class TestDataGenerator:
    def test_roundtrip_through_feed(self, tmp_path):
        class MyGen(DataGenerator):
            def generate_sample(self, line):
                def gen():
                    toks = line.split()
                    ids = [int(t) for t in toks[:-1]]
                    label = float(toks[-1])
                    yield [("ids", ids), ("label", [label])]
                return gen

        raw = tmp_path / "raw.txt"
        raw.write_text("1 2 3 1.0\n4 5 0.0\n")
        out = tmp_path / "slot.txt"
        MyGen().run_from_file(str(raw), str(out))
        assert out.read_text() == "3 1 2 3 1 1.0\n2 4 5 1 0.0\n"

        ds = QueueDataset()
        ds.set_batch_size(2)
        ds.set_thread(1)
        ds.set_filelist([str(out)])
        ds.set_use_var(["ids", "label"], types=["uint64", "float"])
        batch = next(iter(ds))
        assert batch.batch_size == 2
        np.testing.assert_array_equal(batch.values("ids"),
                                      np.array([1, 2, 3, 4, 5], np.uint64))
        np.testing.assert_allclose(batch.dense("label").ravel(), [1.0, 0.0])
