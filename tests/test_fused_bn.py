"""Fused BN(+residual add)+activation training kernels.

Reference tests: `unittests/test_fused_bn_activation_op.py` /
`test_fused_bn_add_activation_op.py` — the fused op must match the unfused
`batch_norm`+`relu`(+add) composition in forward outputs, running-stat
updates and gradients. The Pallas kernels run under the interpreter here so
CPU CI exercises the kernel path itself, not only the XLA fallback.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.nn import functional as F
from paddle_tpu.ops.pallas import fused_bn as fb

EPS = 1e-5


@pytest.fixture()
def interpret_mode():
    """Run the Pallas kernels in the interpreter (kernel path on CPU)."""
    old = fb._INTERPRET
    fb._INTERPRET = True
    fb._probe_status.clear()
    yield
    fb._INTERPRET = old
    fb._probe_status.clear()


def _ref(x, z, g, b, act="relu"):
    """Unfused numpy composition over channels-last x."""
    axes = tuple(range(x.ndim - 1))
    mean = x.mean(axes)
    var = x.var(axes)
    y = (x - mean) / np.sqrt(var + EPS) * g + b
    if z is not None:
        y = y + z
    if act == "relu":
        y = np.maximum(y, 0.0)
    return y, mean, var


class TestKernelParity:
    """Raw-op parity on Pallas-eligible shapes, kernels interpreted."""

    def test_forward_and_stats_match(self, interpret_mode):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 8, 8, 128)).astype(np.float32))
        g = jnp.asarray(rng.normal(size=(128,)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(128,)).astype(np.float32))
        before = fb._stats["pallas_fwd"]
        y, m, v = fb.fused_bn_relu(x, g, b, epsilon=EPS, data_format="NHWC")
        assert fb._stats["pallas_fwd"] > before, "kernel path not taken"
        ry, rm, rv = _ref(np.asarray(x), None, np.asarray(g), np.asarray(b))
        np.testing.assert_allclose(np.asarray(y), ry, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(m), rm, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(v), rv, rtol=1e-4, atol=1e-5)

    def test_add_forward_matches(self, interpret_mode):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(2, 16, 8, 128)).astype(np.float32))
        z = jnp.asarray(rng.normal(size=(2, 16, 8, 128)).astype(np.float32))
        g = jnp.asarray(rng.normal(size=(128,)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(128,)).astype(np.float32))
        y, _, _ = fb.fused_bn_add_relu(x, z, g, b, epsilon=EPS,
                                       data_format="NHWC")
        ry, _, _ = _ref(np.asarray(x), np.asarray(z), np.asarray(g),
                        np.asarray(b))
        np.testing.assert_allclose(np.asarray(y), ry, rtol=1e-4, atol=1e-4)

    def test_grads_match_unfused_composition(self, interpret_mode):
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(4, 8, 8, 128)).astype(np.float32))
        g = jnp.asarray(rng.normal(size=(128,)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(128,)).astype(np.float32))

        def f(x, g, b):
            y, _, _ = fb.fused_bn_relu(x, g, b, epsilon=EPS,
                                       data_format="NHWC")
            return jnp.sum(y * jnp.cos(y))

        def f_ref(x, g, b):
            mean = jnp.mean(x, (0, 1, 2))
            var = jnp.var(x, (0, 1, 2))
            y = jnp.maximum(
                (x - mean) * jax.lax.rsqrt(var + EPS) * g + b, 0.0)
            return jnp.sum(y * jnp.cos(y))

        before = fb._stats["pallas_bwd"]
        got = jax.grad(f, (0, 1, 2))(x, g, b)
        assert fb._stats["pallas_bwd"] > before, "bwd kernel path not taken"
        want = jax.grad(f_ref, (0, 1, 2))(x, g, b)
        for a, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=1e-3, atol=2e-4)

    def test_add_grads_including_residual(self, interpret_mode):
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(4, 8, 8, 128)).astype(np.float32))
        z = jnp.asarray(rng.normal(size=(4, 8, 8, 128)).astype(np.float32))
        g = jnp.asarray(rng.normal(size=(128,)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(128,)).astype(np.float32))

        def f(x, z, g, b):
            y, _, _ = fb.fused_bn_add_relu(x, z, g, b, epsilon=EPS,
                                           data_format="NHWC")
            return jnp.sum(y * jnp.sin(y))

        def f_ref(x, z, g, b):
            mean = jnp.mean(x, (0, 1, 2))
            var = jnp.var(x, (0, 1, 2))
            y = jnp.maximum(
                (x - mean) * jax.lax.rsqrt(var + EPS) * g + b + z, 0.0)
            return jnp.sum(y * jnp.sin(y))

        got = jax.grad(f, (0, 1, 2, 3))(x, z, g, b)
        want = jax.grad(f_ref, (0, 1, 2, 3))(x, z, g, b)
        for a, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=1e-3, atol=2e-4)

    def test_edge_block_masking(self, interpret_mode):
        """R=320 leaves a 64-row edge block: OOB rows must not pollute the
        channel reductions."""
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.normal(size=(8, 5, 8, 128)).astype(np.float32))
        g = jnp.asarray(rng.normal(size=(128,)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(128,)).astype(np.float32))

        def f(x):
            y, _, _ = fb.fused_bn_relu(x, g, b, epsilon=EPS,
                                       data_format="NHWC")
            return jnp.sum(y * y)

        def f_ref(x):
            mean = jnp.mean(x, (0, 1, 2))
            var = jnp.var(x, (0, 1, 2))
            y = jnp.maximum(
                (x - mean) * jax.lax.rsqrt(var + EPS) * g + b, 0.0)
            return jnp.sum(y * y)

        np.testing.assert_allclose(float(f(x)), float(f_ref(x)), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(jax.grad(f)(x)),
                                   np.asarray(jax.grad(f_ref)(x)),
                                   rtol=1e-3, atol=2e-4)

    def test_bf16_io_fp32_stats(self, interpret_mode):
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(4, 8, 8, 128))).astype(jnp.bfloat16)
        g = jnp.ones((128,), jnp.bfloat16)
        b = jnp.zeros((128,), jnp.bfloat16)
        y, m, v = fb.fused_bn_relu(x, g, b, epsilon=EPS, data_format="NHWC")
        assert y.dtype == jnp.bfloat16
        assert m.dtype == jnp.float32 and v.dtype == jnp.float32
        ry, _, _ = _ref(np.asarray(x, np.float32), None, np.ones(128),
                        np.zeros(128))
        np.testing.assert_allclose(np.asarray(y, np.float32), ry,
                                   rtol=0.05, atol=0.05)

    def test_ineligible_shape_falls_back_to_xla(self, interpret_mode):
        """C=7 (not lane-aligned) must take the XLA composition — and still
        be exactly right."""
        rng = np.random.default_rng(6)
        x = jnp.asarray(rng.normal(size=(3, 5, 5, 7)).astype(np.float32))
        g = jnp.asarray(rng.normal(size=(7,)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(7,)).astype(np.float32))
        before = fb._stats["xla_fwd"]
        y, m, v = fb.fused_bn_relu(x, g, b, epsilon=EPS, data_format="NHWC")
        assert fb._stats["xla_fwd"] > before
        ry, rm, rv = _ref(np.asarray(x), None, np.asarray(g), np.asarray(b))
        np.testing.assert_allclose(np.asarray(y), ry, rtol=1e-4, atol=1e-4)


class TestFunctionalAndLayer:
    """act=/residual= through nn.functional.batch_norm and _BatchNormBase."""

    def test_functional_act_matches_composition(self):
        rng = np.random.default_rng(0)
        paddle.seed(0)
        bn_f = nn.BatchNorm2D(16, act="relu")
        bn_u = nn.BatchNorm2D(16)
        x = paddle.to_tensor(rng.normal(size=(4, 16, 6, 6)).astype("float32"))
        r = paddle.to_tensor(rng.normal(size=(4, 16, 6, 6)).astype("float32"))
        bn_f.train(); bn_u.train()
        yf = bn_f(x, r)
        yu = F.relu(bn_u(x) + r)
        np.testing.assert_allclose(yf.numpy(), yu.numpy(),
                                   rtol=1e-5, atol=1e-5)
        # identical momentum running-stat updates
        np.testing.assert_allclose(np.asarray(bn_f._mean.data),
                                   np.asarray(bn_u._mean.data), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(bn_f._variance.data),
                                   np.asarray(bn_u._variance.data), rtol=1e-6)

    def test_layer_backward_parity(self):
        rng = np.random.default_rng(1)
        paddle.seed(0)
        bn_f = nn.BatchNorm2D(8, act="relu")
        bn_u = nn.BatchNorm2D(8)
        xv = rng.normal(size=(4, 8, 5, 5)).astype("float32")
        rv = rng.normal(size=(4, 8, 5, 5)).astype("float32")

        def run(bn, fused):
            x = paddle.to_tensor(xv, stop_gradient=False)
            r = paddle.to_tensor(rv, stop_gradient=False)
            y = bn(x, r) if fused else F.relu(bn(x) + r)
            (y * y).sum().backward()
            return (x.grad.numpy(), r.grad.numpy(),
                    bn.weight.grad.numpy(), bn.bias.grad.numpy())

        got = run(bn_f, True)
        want = run(bn_u, False)
        for a, w in zip(got, want):
            np.testing.assert_allclose(a, w, rtol=1e-3, atol=1e-4)

    def test_eval_mode_uses_running_stats_with_epilogue(self):
        rng = np.random.default_rng(2)
        paddle.seed(0)
        bn_f = nn.BatchNorm2D(4, act="relu")
        bn_u = nn.BatchNorm2D(4)
        x = paddle.to_tensor(rng.normal(size=(2, 4, 3, 3)).astype("float32"))
        r = paddle.to_tensor(rng.normal(size=(2, 4, 3, 3)).astype("float32"))
        bn_f.train(); bn_u.train()
        bn_f(x, r); F.relu(bn_u(x) + r)  # one stats update each
        bn_f.eval(); bn_u.eval()
        np.testing.assert_allclose(bn_f(x, r).numpy(),
                                   F.relu(bn_u(x) + r).numpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_no_affine_layer(self):
        """weight_attr=False substitutes constant gamma/beta (no grads)."""
        rng = np.random.default_rng(3)
        paddle.seed(0)
        bn = nn.BatchNorm2D(4, weight_attr=False, bias_attr=False, act="relu")
        x = paddle.to_tensor(rng.normal(size=(2, 4, 3, 3)).astype("float32"),
                             stop_gradient=False)
        y = bn(x)
        (y * y).sum().backward()
        assert x.grad is not None
        xn = x.numpy()
        mean = xn.mean((0, 2, 3), keepdims=True)
        var = xn.var((0, 2, 3), keepdims=True)
        want = np.maximum((xn - mean) / np.sqrt(var + 1e-5), 0.0)
        np.testing.assert_allclose(y.numpy(), want, rtol=1e-4, atol=1e-4)

    def test_nhwc_data_format(self):
        rng = np.random.default_rng(4)
        paddle.seed(0)
        bn = nn.BatchNorm2D(8, data_format="NHWC", act="relu")
        bn.train()
        x = paddle.to_tensor(rng.normal(size=(2, 6, 6, 8)).astype("float32"))
        y = bn(x).numpy()
        xn = x.numpy()
        ry, _, _ = _ref(xn, None, np.ones(8, np.float32),
                        np.zeros(8, np.float32))
        np.testing.assert_allclose(y, ry, rtol=1e-4, atol=1e-4)


class TestResNetIntegration:
    def test_block_tails_match_unfused(self):
        from paddle_tpu.models.resnet import BottleneckBlock
        rng = np.random.default_rng(0)
        paddle.seed(0)
        b_f = BottleneckBlock(64, 16)
        paddle.seed(0)
        b_u = BottleneckBlock(64, 16, norm_layer=nn.BatchNorm2D)  # unfused
        x = paddle.to_tensor(rng.normal(size=(2, 64, 8, 8)).astype("float32"))
        b_f.train(); b_u.train()
        np.testing.assert_allclose(b_f(x).numpy(), b_u(x).numpy(),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.slow  # full resnet18 double-trace; block-level tests stay fast
    def test_resnet18_fused_vs_unfused(self):
        from paddle_tpu.models.resnet import resnet18
        rng = np.random.default_rng(1)
        paddle.seed(0)
        m_f = resnet18(num_classes=10)
        paddle.seed(0)
        m_u = resnet18(num_classes=10, fused_bn=False)
        x = paddle.to_tensor(rng.normal(size=(2, 3, 32, 32)).astype("float32"))
        m_f.train(); m_u.train()
        # 18 stacked renormalizations compound fp rounding; per-block parity
        # is 1e-6 (test above), model level gets a looser bound
        np.testing.assert_allclose(m_f(x).numpy(), m_u(x).numpy(),
                                   rtol=1e-3, atol=2e-2)
        m_f.eval(); m_u.eval()
        np.testing.assert_allclose(m_f(x).numpy(), m_u(x).numpy(),
                                   rtol=1e-3, atol=2e-2)

    @pytest.mark.slow
    def test_resnet18_trains_compiled(self):
        from paddle_tpu.jit import TrainStep
        from paddle_tpu.models.resnet import resnet18
        paddle.seed(0)
        model = resnet18(num_classes=10, data_format="NHWC")
        opt = optimizer.Momentum(learning_rate=0.05, momentum=0.9,
                                 parameters=model.parameters())
        step = TrainStep(model, F.cross_entropy, opt)
        rng = np.random.default_rng(2)
        x = paddle.to_tensor(rng.normal(size=(4, 32, 32, 3)).astype("float32"))
        y = paddle.to_tensor((np.arange(4) % 10).astype("int32"))
        losses = [float(step(x, y)) for _ in range(6)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]


class TestDispatchIntegration:
    def test_registered_with_dispatch(self):
        from paddle_tpu.ops import _dispatch
        assert "fused_bn_relu" in _dispatch.KERNELS
        assert "fused_bn_add_relu" in _dispatch.KERNELS

    def test_nan_check_sees_fused_op(self):
        from paddle_tpu.framework import flags
        flags.set_flags({"FLAGS_check_nan_inf": True})
        try:
            paddle.seed(0)
            bn = nn.BatchNorm2D(4, act="relu")
            bn.train()
            bad = np.ones((2, 4, 3, 3), "float32")
            bad[0, 0, 0, 0] = np.nan
            with pytest.raises(FloatingPointError):
                bn(paddle.to_tensor(bad))
        finally:
            flags.set_flags({"FLAGS_check_nan_inf": False})
