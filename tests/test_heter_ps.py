"""Heterogeneous PS training: host sparse PS + one compiled dense step.

Reference: `framework/fleet/heter_ps/`, `ps/service/heter_client.cc` — the
accelerator runs the dense net, the CPU PS owns the sparse tables (VERDICT
r2 missing #1; SURVEY §7 "host PS + TPU dense path"). On the CPU test mesh
the "device" is the CPU XLA backend; the contract under test is identical:
ONE jit step computes fwd+bwd+dense-update, sparse rows pull/push around it.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.ps import PSClient, PSServer
from paddle_tpu.distributed.ps.heter import HeterPSTrainStep
from paddle_tpu.models.wide_deep import WideDeep


@pytest.fixture()
def ps():
    server = PSServer(0)
    client = PSClient([server.endpoint])
    yield client
    client.stop_servers()


def _data(n_batches=15, B=32, vocab=50, slots=4, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_batches):
        ids = rng.integers(0, vocab, (B, slots))
        dense = rng.normal(size=(B, slots)).astype(np.float32)
        y = ((ids.sum(1) % 2) == 0).astype(np.float32)[:, None]
        out.append((ids, dense, y))
    return out


def _model(client, slots=4):
    paddle.seed(0)
    return WideDeep(num_slots=slots, embedding_dim=8, dense_dim=slots,
                    hidden=32, client=client)


class TestHeterPSTrainStep:
    def test_matches_eager_ps_loop(self, ps):
        """The compiled dense step + pull/push must reproduce the eager
        PS training loop loss-for-loss (same seeds, same data)."""
        data = _data()
        model = _model(ps)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())
        crit = nn.BCEWithLogitsLoss()
        eager = []
        for ids, dense, y in data:
            loss = crit(model(paddle.to_tensor(ids.astype(np.int64)),
                              paddle.to_tensor(dense)), paddle.to_tensor(y))
            loss.backward()
            opt.step()
            opt.clear_grad()
            eager.append(float(loss))

        server2 = PSServer(0)
        client2 = PSClient([server2.endpoint])
        try:
            model2 = _model(client2)
            opt2 = optimizer.Adam(learning_rate=1e-2,
                                  parameters=model2.parameters())
            crit2 = nn.BCEWithLogitsLoss()
            step = HeterPSTrainStep(model2, lambda o, y: crit2(o, y), opt2)
            got = [float(step(paddle.to_tensor(i.astype(np.int64)),
                              paddle.to_tensor(d), paddle.to_tensor(y)))
                   for i, d, y in data]
        finally:
            client2.stop_servers()
        np.testing.assert_allclose(got, eager, atol=1e-5)

    def test_dense_params_live_on_device_and_update(self, ps):
        """Dense params are jax device arrays owned by the compiled step
        (not host-side eager tensors), and they move when training."""
        model = _model(ps)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())
        crit = nn.BCEWithLogitsLoss()
        step = HeterPSTrainStep(model, lambda o, y: crit(o, y), opt,
                                donate=False)
        dev = jax.devices()[0]
        for v in step.params.values():
            assert isinstance(v, jax.Array)
            assert v.devices() == {dev}, (v.devices(), dev)
        before = {k: np.asarray(v).copy() for k, v in step.params.items()}
        for ids, dense, y in _data(5):
            step(paddle.to_tensor(ids.astype(np.int64)),
                 paddle.to_tensor(dense), paddle.to_tensor(y))
        moved = sum(not np.allclose(before[k], np.asarray(v))
                    for k, v in step.params.items())
        assert moved == len(before), f"only {moved}/{len(before)} updated"

    def test_sparse_rows_update_on_server(self, ps):
        """push_sparse gradients actually change the PS-resident rows."""
        model = _model(ps)
        opt = optimizer.Adam(learning_rate=1e-2,
                             parameters=model.parameters())
        crit = nn.BCEWithLogitsLoss()
        step = HeterPSTrainStep(model, lambda o, y: crit(o, y), opt)
        ids = np.arange(32).reshape(8, 4)
        dense = np.ones((8, 4), np.float32)
        y = np.ones((8, 1), np.float32)
        emb = model.embeddings[0]
        keys = np.arange(8, dtype=np.uint64)  # slot-0 ids of this batch
        step(paddle.to_tensor(ids.astype(np.int64)),
             paddle.to_tensor(dense), paddle.to_tensor(y))
        rows_after_1 = emb.client.pull_sparse(emb._table_cfg.table_id,
                                              keys).copy()
        for _ in range(3):
            step(paddle.to_tensor(ids.astype(np.int64)),
                 paddle.to_tensor(dense), paddle.to_tensor(y))
        rows_after_4 = emb.client.pull_sparse(emb._table_cfg.table_id, keys)
        assert not np.allclose(rows_after_1, rows_after_4), (
            "sparse rows never moved — push_sparse is not reaching the PS")

    def test_converges_on_learnable_task(self, ps):
        """Label = f(embedding of id): repeated epochs over a small vocab
        must drive the loss well below chance."""
        rng = np.random.default_rng(3)
        vocab = 16
        ids_all = rng.integers(0, vocab, (256, 4))
        dense_all = rng.normal(size=(256, 4)).astype(np.float32)
        y_all = ((ids_all[:, 0] < vocab // 2)).astype(np.float32)[:, None]
        model = _model(ps)
        opt = optimizer.Adam(learning_rate=5e-2,
                             parameters=model.parameters())
        crit = nn.BCEWithLogitsLoss()
        step = HeterPSTrainStep(model, lambda o, y: crit(o, y), opt)
        losses = []
        for ep in range(12):
            for s in range(0, 256, 64):
                losses.append(float(step(
                    paddle.to_tensor(ids_all[s:s + 64].astype(np.int64)),
                    paddle.to_tensor(dense_all[s:s + 64]),
                    paddle.to_tensor(y_all[s:s + 64]))))
        assert losses[-1] < 0.35, (losses[0], losses[-1])

    def test_duplicate_ids_grads_merge(self, ps):
        """A batch full of ONE id must train exactly like the eager path
        (the gather-transpose segment-sum merges duplicates)."""
        model = _model(ps)
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        crit = nn.BCEWithLogitsLoss()
        step = HeterPSTrainStep(model, lambda o, y: crit(o, y), opt)
        ids = np.full((16, 4), 7)
        dense = np.zeros((16, 4), np.float32)
        y = np.ones((16, 1), np.float32)
        l0 = float(step(paddle.to_tensor(ids.astype(np.int64)),
                        paddle.to_tensor(dense), paddle.to_tensor(y)))
        l1 = float(step(paddle.to_tensor(ids.astype(np.int64)),
                        paddle.to_tensor(dense), paddle.to_tensor(y)))
        assert l1 < l0  # one id's row received the merged gradient

    def test_async_mode_converges_and_flushes(self, ps):
        """mode="async" pipelines the push one step behind (reference
        a_sync communicator staleness): it must still converge on the
        learnable task, and flush() must land the final outstanding push."""
        rng = np.random.default_rng(3)
        vocab = 16
        ids_all = rng.integers(0, vocab, (256, 4))
        dense_all = rng.normal(size=(256, 4)).astype(np.float32)
        y_all = ((ids_all[:, 0] < vocab // 2)).astype(np.float32)[:, None]
        model = _model(ps)
        opt = optimizer.Adam(learning_rate=5e-2,
                             parameters=model.parameters())
        crit = nn.BCEWithLogitsLoss()
        step = HeterPSTrainStep(model, lambda o, y: crit(o, y), opt,
                                mode="async")
        losses = []
        for ep in range(12):
            for s in range(0, 256, 64):
                losses.append(float(step(
                    paddle.to_tensor(ids_all[s:s + 64].astype(np.int64)),
                    paddle.to_tensor(dense_all[s:s + 64]),
                    paddle.to_tensor(y_all[s:s + 64]))))
        assert losses[-1] < 0.35, (losses[0], losses[-1])
        # one push is still outstanding; flush must change server rows.
        # Drain the in-flight BACKGROUND push first — it touches the same
        # small vocab and could land between the two reads, masking a
        # flush() that drops the pending push.
        step._drain_fut()
        assert step._pending is not None
        # the pending grads themselves decide what flush() must do: on a
        # well-converged run the last batch's grads can be EXACTLY zero
        # (saturated sigmoid), and near-converged updates fall under
        # np.allclose's rtol — both made the old value-change assert flake
        grows, meta = step._pending
        emb0, uniq0 = meta[0]
        uniq0 = np.asarray(uniq0).astype(np.uint64)
        g0 = np.asarray(jax.device_get(grows[0]), np.float32)[:uniq0.size]
        before = emb0.client.pull_sparse(emb0._table_cfg.table_id,
                                         uniq0).copy()
        step.flush()
        assert step._pending is None
        after = emb0.client.pull_sparse(emb0._table_cfg.table_id, uniq0)
        if np.any(g0 != 0.0):
            # bit-exact comparison: ANY applied update counts as pushed
            assert not np.array_equal(before, after), "flush() pushed nothing"

    def test_batch_shape_change_retraces_router(self, ps):
        """A partial last batch (different B) must retrace cleanly, not
        crash on stale routing state (review r3 finding)."""
        model = _model(ps)
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        crit = nn.BCEWithLogitsLoss()
        step = HeterPSTrainStep(model, lambda o, y: crit(o, y), opt)
        rng = np.random.default_rng(5)
        for B in (32, 20, 32, 7):
            ids = rng.integers(0, 100, (B, 4))
            dense = rng.normal(size=(B, 4)).astype(np.float32)
            y = np.ones((B, 1), np.float32)
            loss = step(paddle.to_tensor(ids.astype(np.int64)),
                        paddle.to_tensor(dense), paddle.to_tensor(y))
            assert np.isfinite(float(loss))
