"""hapi.Model fit/evaluate/predict (reference `hapi/model.py:907`,
tested like `unittests/test_model.py`: LeNet on random data, asserting
fit reduces loss, evaluate returns metrics, predict shapes)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.hapi.callbacks import (Callback, EarlyStopping, LRScheduler,
                                       ModelCheckpoint)
from paddle_tpu.io import TensorDataset
from paddle_tpu.metric import Accuracy
from paddle_tpu.nn import functional as F


def _data(n=64, d=16, nclass=4, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, d).astype(np.float32)
    W = rs.randn(d, nclass).astype(np.float32)
    Y = np.argmax(X @ W + 0.1 * rs.randn(n, nclass), 1).astype(np.int64)
    return TensorDataset([paddle.to_tensor(X), paddle.to_tensor(Y)])


def _mlp(d=16, nclass=4):
    paddle.seed(0)
    return nn.Sequential(nn.Linear(d, 32), nn.ReLU(), nn.Linear(32, nclass))


class TestModelFit:
    def test_fit_reduces_loss_and_evaluate(self):
        model = paddle.Model(_mlp())
        model.prepare(
            optimizer.Adam(learning_rate=1e-2,
                           parameters=model.parameters()),
            nn.CrossEntropyLoss(), metrics=Accuracy())
        ds = _data()
        losses = []

        class Rec(Callback):
            def on_epoch_end(self, epoch, logs=None):
                losses.append(logs["loss"][0])

        model.fit(ds, epochs=4, batch_size=16, verbose=0, callbacks=[Rec()])
        assert losses[-1] < losses[0], losses
        res = model.evaluate(ds, batch_size=16, verbose=0)
        assert "loss" in res and "acc" in res
        assert res["acc"] > 0.5

    def test_predict_shapes(self):
        model = paddle.Model(_mlp())
        model.prepare()
        ds = _data(n=20)
        out = model.predict(ds, batch_size=8, stack_outputs=True)
        assert out[0].shape == (20, 4)

    def test_save_load_roundtrip(self, tmp_path):
        model = paddle.Model(_mlp())
        model.prepare(optimizer.Adam(learning_rate=1e-2,
                                     parameters=model.parameters()),
                      nn.CrossEntropyLoss())
        model.fit(_data(), epochs=1, batch_size=16, verbose=0)
        p = str(tmp_path / "ckpt")
        model.save(p)
        assert os.path.exists(p + ".pdparams")
        assert os.path.exists(p + ".pdopt")
        model2 = paddle.Model(_mlp())
        model2.prepare(optimizer.Adam(learning_rate=1e-2,
                                      parameters=model2.parameters()),
                       nn.CrossEntropyLoss())
        model2.load(p)
        a = model.predict_batch([np.ones((2, 16), np.float32)])[0]
        b = model2.predict_batch([np.ones((2, 16), np.float32)])[0]
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_checkpoint_callback(self, tmp_path):
        model = paddle.Model(_mlp())
        model.prepare(optimizer.SGD(learning_rate=1e-2,
                                    parameters=model.parameters()),
                      nn.CrossEntropyLoss())
        model.fit(_data(), epochs=2, batch_size=32, verbose=0,
                  save_dir=str(tmp_path))
        assert (tmp_path / "0.pdparams").exists()
        assert (tmp_path / "final.pdparams").exists()

    def test_early_stopping(self):
        model = paddle.Model(_mlp())
        model.prepare(optimizer.SGD(learning_rate=0.0,
                                    parameters=model.parameters()),
                      nn.CrossEntropyLoss(), metrics=Accuracy())
        es = EarlyStopping(monitor="loss", patience=1, mode="min")
        ds = _data()
        model.fit(ds, eval_data=ds, epochs=6, batch_size=32, verbose=0,
                  eval_freq=1, callbacks=[es])
        assert es.stop_training  # lr=0 -> no improvement -> stopped

    def test_lr_scheduler_callback(self):
        from paddle_tpu.optimizer.lr import StepDecay
        sched = StepDecay(learning_rate=0.1, step_size=1, gamma=0.5)
        model = paddle.Model(_mlp())
        model.prepare(optimizer.SGD(learning_rate=sched,
                                    parameters=model.parameters()),
                      nn.CrossEntropyLoss())
        model.fit(_data(n=32), epochs=1, batch_size=16, verbose=0,
                  callbacks=[LRScheduler(by_step=True)])
        assert sched.last_epoch >= 2

    def test_summary(self, capsys):
        model = paddle.Model(_mlp())
        info = model.summary()
        assert info["total_params"] == 16 * 32 + 32 + 32 * 4 + 4
        assert "Total params" in capsys.readouterr().out
