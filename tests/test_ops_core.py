"""Core op correctness + gradient checks (OpTest-style)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.tensor import Tensor

from op_test import check_grad, check_output


class TestMathOps:
    def test_binary_outputs(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(3, 4).astype(np.float32)
        check_output(paddle.add, [a, b], np.add)
        check_output(paddle.subtract, [a, b], np.subtract)
        check_output(paddle.multiply, [a, b], np.multiply)
        check_output(paddle.divide, [a, b], np.divide, atol=1e-4)
        check_output(paddle.maximum, [a, b], np.maximum)

    def test_broadcast(self):
        a = np.random.randn(3, 1, 4).astype(np.float32)
        b = np.random.randn(5, 1).astype(np.float32)
        check_output(paddle.add, [a, b], np.add)

    def test_unary_outputs(self):
        a = np.abs(np.random.randn(3, 4).astype(np.float32)) + 0.5
        check_output(paddle.exp, [a], np.exp, rtol=1e-5)
        check_output(paddle.log, [a], np.log)
        check_output(paddle.sqrt, [a], np.sqrt)
        check_output(paddle.tanh, [a], np.tanh)
        check_output(paddle.abs, [a - 1.0], lambda x: np.abs(x))

    def test_matmul_grad(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(4, 5).astype(np.float32)
        check_grad(paddle.matmul, [a, b], wrt=0)
        check_grad(paddle.matmul, [a, b], wrt=1)

    def test_matmul_transpose(self):
        a = np.random.randn(4, 3).astype(np.float32)
        b = np.random.randn(5, 4).astype(np.float32)
        check_output(paddle.matmul, [a, b], lambda x, y: x.T @ y.T,
                     transpose_x=True, transpose_y=True)

    def test_elementwise_grads(self):
        a = np.random.rand(3, 4).astype(np.float32) + 0.5
        b = np.random.rand(3, 4).astype(np.float32) + 0.5
        check_grad(paddle.multiply, [a, b], wrt=0)
        check_grad(paddle.divide, [a, b], wrt=1)
        check_grad(paddle.exp, [a], wrt=0)
        check_grad(paddle.tanh, [a], wrt=0)
        check_grad(paddle.sqrt, [a], wrt=0)

    def test_pow_scale_clip(self):
        a = np.random.rand(4).astype(np.float32) + 1.0
        check_output(paddle.pow, [a], lambda x: x ** 2.0, y=2.0)
        out = paddle.scale(Tensor(a), scale=3.0, bias=1.0)
        np.testing.assert_allclose(out.numpy(), a * 3 + 1, rtol=1e-6)
        out = paddle.clip(Tensor(a), min=1.2, max=1.5)
        np.testing.assert_allclose(out.numpy(), np.clip(a, 1.2, 1.5))

    def test_cumsum_trace(self):
        a = np.random.randn(3, 4).astype(np.float32)
        check_output(paddle.cumsum, [a], lambda x: np.cumsum(x, 1), axis=1)
        check_output(paddle.trace, [np.random.randn(4, 4).astype(np.float32)],
                     lambda x: np.trace(x)[None] if np.isscalar(np.trace(x)) else np.trace(x))


class TestReduceOps:
    def test_outputs(self):
        a = np.random.randn(3, 4, 5).astype(np.float32)
        check_output(paddle.sum, [a], lambda x: x.sum())
        check_output(paddle.sum, [a], lambda x: x.sum(1), axis=1)
        check_output(paddle.mean, [a], lambda x: x.mean(axis=(0, 2)), axis=[0, 2])
        check_output(paddle.max, [a], lambda x: x.max(2), axis=2)
        check_output(paddle.min, [a], lambda x: x.min(), )
        check_output(paddle.prod, [a[:2, :2, 0]], lambda x: x.prod(1), axis=1)
        check_output(paddle.std, [a], lambda x: x.std(ddof=1), )
        check_output(paddle.logsumexp, [a],
                     lambda x: np.log(np.exp(x).sum(-1)), axis=-1, rtol=1e-4)

    def test_grads(self):
        a = np.random.randn(3, 4).astype(np.float32)
        check_grad(paddle.sum, [a])
        check_grad(paddle.mean, [a])
        check_grad(lambda x: paddle.max(x, axis=1), [a])

    def test_argmax_topk_sort(self):
        a = np.random.randn(4, 6).astype(np.float32)
        assert np.array_equal(paddle.argmax(Tensor(a), axis=1).numpy(),
                              a.argmax(1))
        vals, idx = paddle.topk(Tensor(a), 3, axis=1)
        ref = -np.sort(-a, axis=1)[:, :3]
        np.testing.assert_allclose(vals.numpy(), ref, rtol=1e-6)
        s = paddle.sort(Tensor(a), axis=1, descending=True)
        np.testing.assert_allclose(s.numpy(), -np.sort(-a, 1), rtol=1e-6)


class TestManipulationOps:
    def test_reshape_transpose(self):
        a = np.random.randn(2, 3, 4).astype(np.float32)
        check_output(paddle.reshape, [a], lambda x: x.reshape(4, 6), shape=[4, 6])
        check_output(paddle.transpose, [a], lambda x: x.transpose(2, 0, 1),
                     perm=[2, 0, 1])
        check_grad(paddle.reshape, [a], shape=[6, 4])
        check_grad(paddle.transpose, [a], perm=[1, 0, 2])

    def test_concat_split_stack(self):
        a = np.random.randn(2, 3).astype(np.float32)
        b = np.random.randn(2, 3).astype(np.float32)
        out = paddle.concat([Tensor(a), Tensor(b)], axis=1)
        np.testing.assert_allclose(out.numpy(), np.concatenate([a, b], 1))
        parts = paddle.split(Tensor(a), 3, axis=1)
        assert len(parts) == 3 and parts[0].shape == [2, 1]
        parts = paddle.split(Tensor(a), [1, 2], axis=1)
        assert parts[1].shape == [2, 2]
        st = paddle.stack([Tensor(a), Tensor(b)], axis=0)
        assert st.shape == [2, 2, 3]

    def test_gather_scatter(self):
        a = np.random.randn(5, 3).astype(np.float32)
        idx = np.array([0, 2, 4])
        out = paddle.gather(Tensor(a), Tensor(idx), axis=0)
        np.testing.assert_allclose(out.numpy(), a[idx])
        upd = np.ones((3, 3), np.float32)
        out = paddle.scatter(Tensor(a), Tensor(idx), Tensor(upd))
        ref = a.copy(); ref[idx] = 1.0
        np.testing.assert_allclose(out.numpy(), ref)

    def test_where_masked(self):
        a = np.random.randn(3, 4).astype(np.float32)
        cond = a > 0
        out = paddle.where(Tensor(cond), Tensor(a), Tensor(np.zeros_like(a)))
        np.testing.assert_allclose(out.numpy(), np.where(cond, a, 0))
        out = paddle.masked_fill(Tensor(a), Tensor(cond), -1.0)
        np.testing.assert_allclose(out.numpy(), np.where(cond, -1.0, a))

    def test_indexing(self):
        a = np.arange(24).reshape(4, 6).astype(np.float32)
        t = Tensor(a)
        np.testing.assert_allclose(t[1:3, ::2].numpy(), a[1:3, ::2])
        np.testing.assert_allclose(t[Tensor(np.array([0, 3]))].numpy(), a[[0, 3]])
        t[0, 0] = 99.0
        assert t.numpy()[0, 0] == 99.0

    def test_pad_tile_flip(self):
        a = np.random.randn(2, 3, 4, 4).astype(np.float32)
        out = paddle.pad(Tensor(a), [1, 1, 2, 2])
        assert out.shape == [2, 3, 8, 6]
        out = paddle.tile(Tensor(a[:, :, 0, 0]), [2, 3])
        np.testing.assert_allclose(out.numpy(), np.tile(a[:, :, 0, 0], (2, 3)))
        out = paddle.flip(Tensor(a), axis=[2])
        np.testing.assert_allclose(out.numpy(), np.flip(a, 2))


class TestComparisonOps:
    def test_all(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = a.copy(); b[0, 0] += 1
        assert not bool(paddle.equal_all(Tensor(a), Tensor(b)))
        assert bool(paddle.allclose(Tensor(a), Tensor(a + 1e-9)))
        np.testing.assert_array_equal(
            paddle.greater_than(Tensor(a), Tensor(b)).numpy(), a > b)


class TestLinalg:
    def test_basics(self):
        a = np.random.randn(4, 4).astype(np.float32)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        np.testing.assert_allclose(paddle.linalg.cholesky(Tensor(spd)).numpy(),
                                   np.linalg.cholesky(spd), atol=1e-4)
        np.testing.assert_allclose(paddle.linalg.inv(Tensor(spd)).numpy(),
                                   np.linalg.inv(spd), atol=1e-4)
        u, s, v = paddle.linalg.svd(Tensor(a))
        np.testing.assert_allclose(
            (u.numpy() * s.numpy()) @ v.numpy().T, a, atol=1e-4)
        np.testing.assert_allclose(paddle.linalg.det(Tensor(spd)).numpy(),
                                   np.linalg.det(spd), rtol=1e-4)

    def test_norm(self):
        a = np.random.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(paddle.linalg.norm(Tensor(a)).numpy(),
                                   np.linalg.norm(a), rtol=1e-5)
        np.testing.assert_allclose(
            paddle.linalg.norm(Tensor(a), p=1, axis=1).numpy(),
            np.abs(a).sum(1), rtol=1e-5)


class TestCreation:
    def test_creation(self):
        assert paddle.zeros([2, 3]).shape == [2, 3]
        assert paddle.ones([2], dtype="int32").dtype == np.int32
        np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(paddle.eye(3).numpy(), np.eye(3))
        assert paddle.full([2, 2], 7.0).numpy()[0, 0] == 7.0
        tl = paddle.tril(Tensor(np.ones((3, 3), np.float32)))
        np.testing.assert_allclose(tl.numpy(), np.tril(np.ones((3, 3))))

    def test_random(self):
        paddle.seed(7)
        a = paddle.rand([1000])
        assert -1.0 <= float(a.min().item()) and float(a.max().item()) <= 1.0
        b = paddle.randn([2000])
        assert abs(float(b.mean().item())) < 0.1
        r = paddle.randint(0, 10, [100])
        assert 0 <= int(r.min().item()) and int(r.max().item()) < 10
        p = paddle.randperm(10).numpy()
        assert sorted(p.tolist()) == list(range(10))
