"""Request-scoped serving traces (profiler/reqtrace.py) and the
ServingEngine lifecycle hooks that feed them.

The ISSUE-17 contracts: every request gets ONE trace id at submit and
keeps it across preemption + re-prefill (the re-admission span is
labeled `requeue`), decode spans are bucketed per
PADDLE_TPU_REQTRACE_EVERY iterations and carry bucket/path labels,
per-phase durations sum to within noise of the e2e wall time
(contiguous attribution), completed traces land in a bounded ring and
emit one `request_trace` event, the chrome-trace/JSONL exports are
well-formed, and the PADDLE_TPU_REQTRACE kill switch turns every hook
into a no-op.

Tracer unit tests drive the hooks directly (no jax); the engine
integration tests reuse the tiny serving GPT and the shared persistent
compile cache from test_serving_v2.py.
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.profiler import events
from paddle_tpu.profiler import reqtrace
from paddle_tpu.profiler.reqtrace import RequestTracer, to_chrome_trace


@pytest.fixture(autouse=True)
def _clean_events():
    events.default_event_log().clear()
    yield
    events.default_event_log().clear()


@pytest.fixture(scope="module", autouse=True)
def _shared_compile_cache():
    """Same tiny-model engine as test_serving.py/test_serving_v2.py:
    share the one persistent XLA compile cache dir so only the first
    suite in the tier-1 run pays backend compile."""
    import os
    import tempfile
    from paddle_tpu.framework import flags as flags_mod
    cache = os.path.join(tempfile.gettempdir(), "pt_serving_ccache")
    os.makedirs(cache, exist_ok=True)
    flags_mod.set_flags({"FLAGS_compile_cache_dir": cache})
    yield
    flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})


def _model(vocab=512):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=vocab, max_position_embeddings=128,
                    hidden_size=32, num_layers=2, num_heads=2,
                    dropout=0.0, attn_dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m, cfg


def _spans(trace_dict, phase):
    return [s for s in trace_dict["spans"] if s["phase"] == phase]


class TestTracerUnit:
    """Hook-level contracts, no engine: the tracer is plain Python."""

    def _run_one(self, tracer, rid=1, iters=3, bucket=8, path="fused"):
        tracer.submit(rid)
        tracer.admitted(rid, bucket=bucket, prompt_tokens=5)
        tracer.prefill_done(rid)
        for _ in range(iters):
            tracer.decode_iteration(rid, bucket=bucket, path=path)
        tracer.complete(rid, "eos")

    def test_lifecycle_spans_in_order(self):
        tr = RequestTracer("unit", ring=8)
        tid = tr.submit(1)
        assert isinstance(tid, int)
        self_phases = None
        tr.admitted(1, bucket=16, prompt_tokens=9, shared_tokens=4)
        tr.prefill_done(1)
        tr.decode_iteration(1, bucket=16, path="fused")
        tr.complete(1, "eos")
        [rec] = tr.completed()
        assert rec["trace_id"] == tid and rec["state"] == "complete"
        self_phases = [s["phase"] for s in rec["spans"]]
        assert self_phases == ["queued", "prefill", "decode", "complete"]
        pre = _spans(rec, "prefill")[0]
        assert pre["bucket"] == 16 and pre["prompt_tokens"] == 9
        assert pre["shared_prefix_skip"] == 4  # shared-prefix skip noted
        dec = _spans(rec, "decode")[0]
        assert dec["bucket"] == 16 and dec["path"] == "fused"
        # every span closed, marker is zero-width, durations non-negative
        for s in rec["spans"]:
            assert s["end"] is not None and s["end"] >= s["start"]
        assert rec["e2e_s"] >= 0

    def test_decode_spans_bucket_per_every_and_on_label_change(self):
        tr = RequestTracer("unit", ring=8, decode_every=4)
        tr.submit(2)
        tr.admitted(2, bucket=8, prompt_tokens=3)
        tr.prefill_done(2)
        for _ in range(8):  # 8 iters at every=4 -> 2 spans
            tr.decode_iteration(2, bucket=8, path="fused")
        tr.decode_iteration(2, bucket=16, path="fused")  # bucket change
        tr.decode_iteration(2, bucket=16, path="eager")  # path change
        tr.complete(2, "length")
        [rec] = tr.completed()
        decs = _spans(rec, "decode")
        assert len(decs) == 4
        assert [d["iters"] for d in decs] == [4, 4, 1, 1]
        assert decs[2]["bucket"] == 16 and decs[3]["path"] == "eager"
        assert rec["decode_iterations"] == 10
        assert rec["decode_tokens"] == 10

    def test_preemption_keeps_trace_id_and_labels_requeue(self):
        tr = RequestTracer("unit", ring=8)
        tid = tr.submit(3)
        tr.admitted(3, bucket=8, prompt_tokens=4)
        tr.prefill_done(3)
        tr.decode_iteration(3, bucket=8, path="fused")
        tr.preempted(3)
        assert tr.get(3).trace_id == tid  # SAME trace across requeue
        tr.admitted(3, bucket=8, prompt_tokens=6, requeue=True)
        tr.prefill_done(3)
        tr.decode_iteration(3, bucket=8, path="fused")
        tr.complete(3, "eos")
        [rec] = tr.completed()
        assert rec["trace_id"] == tid and rec["preemptions"] == 1
        pres = _spans(rec, "prefill")
        assert len(pres) == 2
        assert "requeue" not in pres[0]
        assert pres[1]["requeue"] is True
        assert len(_spans(rec, "preempted")) == 1
        assert "preempted" in rec["phases"]

    def test_failed_completion_marked_and_event_warns(self):
        tr = RequestTracer("unit", ring=8)
        tr.submit(4)
        tr.admitted(4, bucket=8, prompt_tokens=2)
        tr.complete(4, "error", error="boom")
        [rec] = tr.completed()
        assert rec["state"] == "failed"
        [mark] = _spans(rec, "failed")
        assert mark["error"] == "boom"
        [ev] = events.recent(kind="request_trace")
        assert ev["severity"] == "warn" and ev["finish_reason"] == "error"

    def test_completed_ring_is_bounded(self):
        tr = RequestTracer("unit", ring=3)
        for rid in range(6):
            self._run_one(tr, rid=rid, iters=1)
        done = tr.completed()
        assert len(done) == 3
        assert [d["rid"] for d in done] == [3, 4, 5]
        assert tr.snapshot()["ring_size"] == 3

    def test_request_trace_event_per_completion(self):
        tr = RequestTracer("unit", ring=8)
        self._run_one(tr, rid=7)
        evs = events.recent(kind="request_trace")
        assert len(evs) == 1
        ev = evs[0]
        assert ev["model"] == "unit" and ev["rid"] == 7
        assert ev["finish_reason"] == "eos"
        assert set(ev["phases"]) >= {"queued", "prefill", "decode"}

    def test_kill_switch_disables_every_hook(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_REQTRACE", "0")
        tr = RequestTracer("unit", ring=8)
        assert tr.submit(8) is None
        # hooks on an untracked rid are silent no-ops
        tr.admitted(8, bucket=8, prompt_tokens=1)
        tr.decode_iteration(8, bucket=8, path="fused")
        tr.complete(8, "eos")
        assert tr.completed() == [] and tr.live() == []
        assert tr.snapshot()["enabled"] is False
        assert events.recent(kind="request_trace") == []

    def test_jsonl_log_appends_one_line_per_trace(self, tmp_path):
        log = tmp_path / "traces.jsonl"
        tr = RequestTracer("unit", ring=8, log_path=str(log))
        self._run_one(tr, rid=9)
        self._run_one(tr, rid=10)
        lines = [json.loads(l) for l in
                 log.read_text().strip().splitlines()]
        assert [l["rid"] for l in lines] == [9, 10]
        assert all(l["state"] == "complete" for l in lines)

    def test_export_jsonl_and_chrome_trace(self, tmp_path):
        tr = RequestTracer("unit", ring=8, decode_every=2)
        self._run_one(tr, rid=11, iters=5)
        n = tr.export_jsonl(str(tmp_path / "t.jsonl"))
        assert n == 1
        rec = json.loads((tmp_path / "t.jsonl").read_text())
        assert rec["rid"] == 11
        n = tr.export_chrome_trace(str(tmp_path / "t.json"))
        assert n == 1
        doc = json.loads((tmp_path / "t.json").read_text())
        assert doc["displayTimeUnit"] == "ms"
        names = {e["name"] for e in doc["traceEvents"]}
        assert names >= {"queued", "prefill", "decode", "complete"}
        for e in doc["traceEvents"]:
            assert e["ph"] == "X" and e["dur"] >= 0
            assert e["pid"] == "unit"
            assert e["args"]["rid"] == 11

    def test_chrome_trace_skips_open_spans(self):
        tr = RequestTracer("unit", ring=8)
        tr.submit(12)  # queued span still open
        doc = to_chrome_trace(tr.live())
        assert doc["traceEvents"] == []

    def test_metric_families_observe_per_phase(self):
        from paddle_tpu.profiler import metrics as metrics_mod
        tr = RequestTracer("hist_unit", ring=8)
        tr.submit(13)
        tr.admitted(13, bucket=8, prompt_tokens=2)
        tr.prefill_done(13)
        tr.preempted(13)
        tr.admitted(13, bucket=8, prompt_tokens=3, requeue=True)
        tr.prefill_done(13)
        tr.complete(13, "eos")
        snap = metrics_mod.default_registry().snapshot()
        for fam in ("serving_queue_wait_seconds",
                    "serving_prefill_seconds",
                    "serving_preempt_requeue_seconds"):
            vals = [v for v in snap[fam]["values"]
                    if v["labels"].get("model") == "hist_unit"]
            assert vals and vals[0]["count"] >= 1, fam


class TestEngineTraces:
    """The ServingEngine hooks: traces built by real serving runs."""

    def _serve(self, eng, prompts, max_new=5, sampling=None):
        if sampling is None:
            sampling = [None] * len(prompts)
        reqs = [eng.submit(p, max_new_tokens=max_new, sampling=s)
                for p, s in zip(prompts, sampling)]
        eng.run_until_idle()
        for r in reqs:
            r.result(timeout=10)
        return reqs

    def test_every_phase_present_with_bucket_and_path_labels(self):
        from paddle_tpu.inference.serving import ServingEngine
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="rt_phases")
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, cfg.vocab_size, (n,)).tolist()
                   for n in (7, 12)]
        reqs = self._serve(eng, prompts, max_new=5)
        done = eng.tracer.completed()
        assert len(done) == 2
        for req, rec in zip(reqs, sorted(done, key=lambda d: d["rid"])):
            assert rec["trace_id"] == req.trace_id
            phases = [s["phase"] for s in rec["spans"]]
            for ph in ("queued", "prefill", "decode", "complete"):
                assert ph in phases, (ph, phases)
            for d in _spans(rec, "decode"):
                assert d["bucket"] in eng.decode_buckets or \
                    d["bucket"] == eng.max_batch
                assert d["path"] == "fused"
            assert rec["decode_tokens"] >= 4  # max_new - prefill token
            assert rec["finish_reason"] in ("eos", "length", "stop")

    def test_preemption_trace_continuity(self):
        """THE preemption contract: a preempted+requeued request keeps
        ONE trace id end to end, its re-prefill span is labeled
        `requeue`, and per-phase durations sum to within noise of the
        e2e wall time (contiguous attribution)."""
        from paddle_tpu.inference.serving import ServingEngine
        m, cfg = _model()
        prompt = list(range(1, 15))
        eng = ServingEngine(m, max_batch=2, max_len=64, page_size=8,
                            name="rt_preempt")
        reqs = [eng.submit(prompt, max_new_tokens=6) for _ in range(2)]
        eng.step()  # admit both + first decode iteration
        victim_req = eng._slots[1]
        eng._preempt(victim_req)
        eng.run_until_idle()
        for r in reqs:
            r.result(timeout=10)
        rec = eng.tracer.get(victim_req.rid).to_dict()
        # ONE trace id across the preemption
        assert rec["trace_id"] == victim_req.trace_id
        assert rec["preemptions"] == 1
        ids = {victim_req.trace_id}
        for s in rec["spans"]:
            assert s["end"] is not None
        pres = _spans(rec, "prefill")
        assert len(pres) == 2
        assert pres[1]["requeue"] is True  # re-prefill labeled
        assert len(_spans(rec, "preempted")) == 1
        assert len(ids) == 1
        # contiguous attribution: phases sum ~ e2e (small inter-hook
        # gaps only — the spans cover the request's whole life)
        total = sum(rec["phases"].values())
        assert rec["e2e_s"] is not None
        assert abs(total - rec["e2e_s"]) < max(0.1, 0.05 * rec["e2e_s"]), \
            (total, rec["e2e_s"], rec["phases"])
        # the survivor saw no preemption and exactly one prefill
        other = eng.tracer.get(reqs[0].rid).to_dict()
        assert other["preemptions"] == 0
        assert len(_spans(other, "prefill")) == 1

    def test_phase_durations_sum_to_e2e_without_preemption(self):
        from paddle_tpu.inference.serving import ServingEngine
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="rt_sum")
        self._serve(eng, [list(range(1, 9)), list(range(2, 14))],
                    max_new=5)
        for rec in eng.tracer.completed():
            total = sum(rec["phases"].values())
            assert abs(total - rec["e2e_s"]) < \
                max(0.1, 0.05 * rec["e2e_s"]), (total, rec["e2e_s"])

    def test_requests_snapshot_and_introspection_ring(self):
        from paddle_tpu.inference.serving import ServingEngine
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=2, max_len=48, page_size=8,
                            name="rt_snap")
        self._serve(eng, [list(range(1, 10))], max_new=4)
        snap = eng.requests_snapshot()
        assert snap["model"] == "rt_snap"
        assert snap["queue_depth"] == 0 and snap["live"] == []
        assert len(snap["completed"]) == 1
        intr = snap["introspection"]
        assert intr, "per-iteration introspection ring is empty"
        for row in intr:
            for key in ("iteration", "active", "lanes", "occupancy",
                        "queue_depth", "free_pages", "used_pages",
                        "cow_shared_pages", "decode_mode"):
                assert key in row, key
        assert any(r["active"] >= 1 for r in intr)
        json.dumps(snap)  # endpoint payload must be JSON-serializable

    def test_engine_kill_switch_run_still_serves(self, monkeypatch):
        """PADDLE_TPU_REQTRACE=0: tokens still flow, no traces kept."""
        monkeypatch.setenv("PADDLE_TPU_REQTRACE", "0")
        from paddle_tpu.inference.serving import ServingEngine
        m, cfg = _model()
        eng = ServingEngine(m, max_batch=1, max_len=48, page_size=8,
                            name="rt_off")
        [req] = self._serve(eng, [list(range(1, 8))], max_new=3)
        assert req.trace_id is None
        assert len(req.generated) == 3
        assert eng.tracer.completed() == []
        assert events.recent(kind="request_trace") == []
