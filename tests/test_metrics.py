"""Metrics registry (profiler/metrics.py) + tools/metrics_dump.py.

Reference analog: `paddle/fluid/platform/monitor.h` StatRegistry tests —
here the registry is labeled, typed, and exports Prometheus text + JSON.
"""
import json
import os
import sys
import threading

import pytest

from paddle_tpu.profiler import metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture()
def reg():
    return metrics.MetricsRegistry()


class TestCounterGauge:
    def test_counter_inc_and_labels(self, reg):
        c = reg.counter("requests_total", "demo")
        c.inc()
        c.inc(2, op="matmul")
        c.inc(3, op="matmul")
        assert c.value() == 1
        assert c.value(op="matmul") == 5
        assert c.total() == 6

    def test_counter_rejects_negative(self, reg):
        with pytest.raises(ValueError):
            reg.counter("c").inc(-1)

    def test_gauge_set_inc_dec(self, reg):
        g = reg.gauge("mem_bytes")
        g.set(100, device="tpu:0")
        g.inc(50, device="tpu:0")
        g.dec(25, device="tpu:0")
        assert g.value(device="tpu:0") == 125

    def test_get_or_create_and_type_conflict(self, reg):
        c1 = reg.counter("x_total")
        assert reg.counter("x_total") is c1
        with pytest.raises(TypeError):
            reg.gauge("x_total")

    def test_label_order_irrelevant(self, reg):
        c = reg.counter("c_total")
        c.inc(1, a="1", b="2")
        c.inc(1, b="2", a="1")
        assert c.value(a="1", b="2") == 2


class TestHistogram:
    def test_buckets_and_sum(self, reg):
        h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        (snap,) = h.snapshot()["values"]
        assert snap["count"] == 4
        assert snap["sum"] == pytest.approx(5.555)
        assert snap["buckets"]["0.01"] == 1      # cumulative
        assert snap["buckets"]["0.1"] == 2
        assert snap["buckets"]["1.0"] == 3
        assert snap["buckets"]["+Inf"] == 4


class TestExporters:
    def test_prometheus_text_format(self, reg):
        reg.counter("ops_total", "op calls").inc(3, op="a\"b\n")
        reg.gauge("hot").set(1.5)
        reg.histogram("h_seconds", buckets=(1.0,)).observe(0.5)
        txt = reg.to_prometheus_text()
        assert '# TYPE paddle_tpu_ops_total counter' in txt
        assert 'paddle_tpu_ops_total{op="a\\"b\\n"} 3.0' in txt
        assert 'paddle_tpu_hot 1.5' in txt
        assert 'paddle_tpu_h_seconds_bucket{le="1.0"} 1' in txt
        assert 'paddle_tpu_h_seconds_count 1' in txt

    def test_prometheus_headers_even_without_series(self, reg):
        reg.counter("quiet_total", "never incremented")
        assert "paddle_tpu_quiet_total" in reg.to_prometheus_text()

    def test_snapshot_json_serializable(self, reg):
        reg.counter("a_total").inc(2, k="v")
        reg.histogram("b_seconds").observe(0.1)
        snap = json.loads(json.dumps(reg.snapshot()))
        assert snap["a_total"]["kind"] == "counter"
        assert snap["a_total"]["values"][0] == {"labels": {"k": "v"},
                                                "value": 2.0}
        assert snap["b_seconds"]["values"][0]["count"] == 1

    def test_reset_keeps_families(self, reg):
        reg.counter("a_total").inc(5)
        reg.reset()
        assert reg.counter("a_total").total() == 0
        assert "a_total" in reg.names()


class TestEnableSwitch:
    def test_set_enabled_roundtrip(self):
        was = metrics.enabled()
        try:
            metrics.set_enabled(False)
            assert not metrics.enabled()
            metrics.set_enabled(True)
            assert metrics.enabled()
        finally:
            metrics.set_enabled(was)


class TestThreadSafety:
    def test_concurrent_increments(self, reg):
        c = reg.counter("t_total")
        n, k = 8, 2000

        def work():
            for _ in range(k):
                c.inc(1, tid="x")

        threads = [threading.Thread(target=work) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value(tid="x") == n * k


class TestMetricsDumpTool:
    def _snapshot(self):
        r = metrics.MetricsRegistry()
        r.counter("collective_bytes_total", "bytes").inc(
            4096, kind="all_reduce", link="ici")
        r.histogram("w_seconds").observe(0.2)
        return r.snapshot()

    def test_format_snapshot(self):
        import metrics_dump
        out = metrics_dump.format_snapshot(self._snapshot())
        assert "collective_bytes_total" in out
        assert "kind=all_reduce,link=ici" in out
        assert "4,096" in out
        out2 = metrics_dump.format_snapshot(self._snapshot(), "w_seconds")
        assert "collective_bytes_total" not in out2 and "w_seconds" in out2

    def test_cli_accepts_bench_json(self, tmp_path, capsys):
        import metrics_dump
        bench_doc = {"metric": "x", "value": 1,
                     "observability": {"metrics": self._snapshot()}}
        p = tmp_path / "bench.json"
        p.write_text(json.dumps(bench_doc))
        assert metrics_dump.main([str(p)]) == 0
        assert "collective_bytes_total" in capsys.readouterr().out

    def test_cli_rejects_garbage(self, tmp_path):
        import metrics_dump
        p = tmp_path / "x.json"
        p.write_text("not json at all")
        assert metrics_dump.main([str(p)]) == 2

    def test_histogram_percentile_rendering(self):
        """PR-4: histogram families render p50/p95/p99 estimates from the
        cumulative buckets (the heter pull/push/route latencies)."""
        import metrics_dump
        r = metrics.MetricsRegistry()
        h = r.histogram("heter_pull_seconds")
        for v in [0.001] * 90 + [0.08] * 10:
            h.observe(v, mode="pipelined")
        out = metrics_dump.format_snapshot(r.snapshot())
        assert "p50=" in out and "p95=" in out and "p99=" in out
        # p50 sits in the (0.0005, 0.001] bucket; p95+ in the big one
        assert "mode=pipelined" in out

    def test_hist_quantile_estimator(self):
        import metrics_dump
        buckets = {"0.001": 50, "0.01": 90, "0.1": 100, "+Inf": 100}
        q50 = metrics_dump.hist_quantile(buckets, 0.5)
        q99 = metrics_dump.hist_quantile(buckets, 0.99)
        assert q50 is not None and abs(q50 - 0.001) < 1e-9
        assert q99 is not None and 0.01 < q99 <= 0.1
        assert metrics_dump.hist_quantile({"+Inf": 0}, 0.5) is None


class TestMetricNamingLint:
    """Fleet-observability contract: every registered family is a legal
    Prometheus name and its help string documents the label keys its
    series use — a scraper must never meet an undocumented label."""

    NAME_RE = __import__("re").compile(r"^[a-z][a-z0-9_]*$")

    @staticmethod
    def _import_instrumented_modules():
        # every module that registers metric families at import
        import paddle_tpu  # noqa: F401
        import paddle_tpu.amp  # noqa: F401
        import paddle_tpu.distributed.checkpoint  # noqa: F401
        import paddle_tpu.distributed.collective  # noqa: F401
        import paddle_tpu.distributed.fleet.controller  # noqa: F401
        import paddle_tpu.distributed.fleet.elastic  # noqa: F401
        import paddle_tpu.distributed.fleet.leader  # noqa: F401
        import paddle_tpu.distributed.fleet.telemetry  # noqa: F401
        import paddle_tpu.distributed.ps.cache  # noqa: F401
        import paddle_tpu.distributed.ps.communicator  # noqa: F401
        import paddle_tpu.distributed.ps.heter  # noqa: F401
        import paddle_tpu.fault  # noqa: F401
        import paddle_tpu.inference.disagg  # noqa: F401
        import paddle_tpu.inference.serving  # noqa: F401
        import paddle_tpu.io.dataloader  # noqa: F401
        import paddle_tpu.io.worker  # noqa: F401
        import paddle_tpu.ops._dispatch  # noqa: F401
        import paddle_tpu.ops.pallas.autotune  # noqa: F401
        import paddle_tpu.profiler.compile_watch  # noqa: F401
        import paddle_tpu.profiler.health  # noqa: F401
        import paddle_tpu.profiler.reqtrace  # noqa: F401
        import paddle_tpu.profiler.slo  # noqa: F401
        import paddle_tpu.profiler.watchdog  # noqa: F401

    def test_family_names_match_prometheus_grammar(self):
        self._import_instrumented_modules()
        reg = metrics.default_registry()
        bad = [n for n in reg.names() if not self.NAME_RE.match(n)]
        assert not bad, f"illegal metric family names: {bad}"

    def test_label_keys_are_documented_in_help(self):
        """Each live series' label keys must appear (case-insensitively)
        in the family's help text. Runs over whatever the session has
        populated so far plus a deterministic seed of the core labeled
        families."""
        self._import_instrumented_modules()
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.profiler import compile_watch
        # deterministic seed: exercise core labeled families
        a = paddle.to_tensor(np.ones((4, 4), np.float32))
        paddle.matmul(a, a)  # op_* counters
        from paddle_tpu.profiler.watchdog import RetraceWatchdog
        wd = RetraceWatchdog()
        wd.observe("eager", "lint_op", [np.zeros((2,), np.float32)])
        compile_watch._on_duration(
            "/jax/core/compile/backend_compile_duration", 0.01)
        # deep-profiling PR families: device-memory gauges (device=),
        # capture counter (status=), collective timing (kind=)
        metrics.sample_device_memory()
        from paddle_tpu.profiler import xplane as _xplane
        _xplane._M_CAPTURES.inc(status="complete")
        from paddle_tpu.distributed import collective as _coll
        _coll._M_COLL_SECONDS.observe(0.001, kind="all_reduce")
        # training-health PR families: sentinel gauges (group=), nonfinite
        # counter (src=), monitor alerts (signal=), fleet status (host=),
        # and the AMP scaler pair
        from paddle_tpu.profiler import health as _health
        _health._M_LAYER_GRAD.set(0.5, group="fc1")
        _health._M_NONFINITE.inc(src="sentinel")
        _health._M_ALERTS.inc(signal="loss_spike")
        _health._M_LOSS.set(1.0)
        _health._M_GRAD_NORM.set(1.0)
        _health._M_UPDATE_RATIO.set(0.01)
        _health._M_ROLLBACK.inc()
        from paddle_tpu.distributed.fleet import telemetry as _tel
        _tel._M_HEALTH.set(0, host="trainer-0")
        import paddle_tpu.amp as _amp
        _amp._M_FOUND_INF.inc()
        _amp._M_LOSS_SCALE.set(32768.0)
        # kernel-autotuner families: cache events (event=, op=), tune
        # counter (op=), probe histogram (op=), chosen-config gauge
        # (op=, config=)
        from paddle_tpu.ops.pallas import autotune as _at
        _at._M_EVENTS.inc(event="hit", op="lint_op")
        _at._M_TUNES.inc(op="lint_op")
        _at._M_PROBE_SECONDS.observe(0.001, op="lint_op")
        _at._M_CHOSEN.set(1.0, op="lint_op", config="q256-k512")
        # self-driving fleet controller families: decisions (policy=,
        # outcome=), per-action counters (host=), relaunch-to-first-step
        # gauge (policy=)
        from paddle_tpu.distributed.fleet import controller as _ctl
        _ctl._M_DECISIONS.inc(policy="straggler_evict", outcome="applied")
        _ctl._M_DECISIONS.inc(policy="straggler_skip", outcome="applied")
        _ctl._M_EVICTIONS.inc(host="trainer-1")
        _ctl._M_ROLLBACKS.inc(host="trainer-1")
        _ctl._M_READMISSIONS.inc(host="trainer-1")
        _ctl._M_FIRST_STEP.set(1.5, policy="straggler_evict")
        # HA control plane families: election term gauge, takeovers
        # (reason=), fenced stale actuations (policy=)
        from paddle_tpu.distributed.fleet import leader as _ldr
        _ldr._M_TERM.set(3)
        _ldr._M_TAKEOVERS.inc(reason="lease_expired")
        _ldr._M_FENCED.inc(policy="serving_restart")
        # disaggregated-serving fault-tolerance families: worker
        # respawns + requeues (reason=)
        from paddle_tpu.inference import disagg as _dis
        _dis._M_W_RESTARTS.inc()
        _dis._M_REQUEUE.inc(reason="worker_dead")
        # continuous-batching serving families (model=, latency split by
        # decode path=) + the paged-KV decode kernel's autotune op riding
        # the existing families
        from paddle_tpu.inference import serving as _srv
        _srv._M_QUEUE.set(2, model="gpt")
        _srv._M_OCC.set(1, model="gpt")
        _srv._M_TTFT.observe(0.05, model="gpt", path="fused")
        _srv._M_TPOT.observe(0.01, model="gpt", path="fused")
        _srv._M_TTFT.observe(0.07, model="gpt", path="eager")
        _srv._M_TPOT.observe(0.02, model="gpt", path="eager")
        _srv._M_GOODPUT.inc(8, model="gpt")
        # self-healing serving families: hot-swap lifecycle (model=,
        # outcome=), swap pause histogram + applied-step gauge (model=),
        # watchdog restarts (model=, reason=), suspension gauge (model=)
        _srv._M_SWAP_TOTAL.inc(1.0, model="gpt", outcome="applied")
        _srv._M_SWAP_PAUSE.observe(0.003, model="gpt")
        _srv._M_SWAP_STEP.set(100, model="gpt")
        _srv._M_RESTARTS.inc(model="gpt", reason="wedged")
        _srv._M_SUSPENDED.set(0, model="gpt")
        # disaggregated prefill/decode handoff plane (model=, per-stage
        # occupancy additionally by stage=)
        _srv._M_HANDOFF_DEPTH.set(1, model="gpt")
        _srv._M_HANDOFF_WAIT.observe(0.004, model="gpt")
        _srv._M_HANDOFF_BYTES.inc(4096, model="gpt")
        _srv._M_STAGE_OCC.set(1, model="gpt", stage="prefill")
        _srv._M_STAGE_OCC.set(2, model="gpt", stage="decode")
        _at._M_EVENTS.inc(event="hit", op="paged_attn")
        _at._M_TUNES.inc(op="paged_attn")
        _at._M_CHOSEN.set(1.0, op="paged_attn", config="impl1-heads12")
        # request-trace lifecycle histograms (model=) + SLO plane
        # families (model=, signal=)
        from paddle_tpu.profiler import reqtrace as _rt
        _rt._M_QWAIT.observe(0.01, model="gpt")
        _rt._M_PREFILL.observe(0.05, model="gpt")
        _rt._M_REQUEUE.observe(0.02, model="gpt")
        from paddle_tpu.profiler import slo as _slo
        _slo._M_BREACHES.inc(model="gpt", signal="ttft")
        _slo._M_BREACHED.set(1, model="gpt", signal="ttft")
        _slo._M_P99.set(0.2, model="gpt", signal="ttft")
        _slo._M_P99.set(0.01, model="gpt", signal="handoff_wait")
        reg = metrics.default_registry()
        problems = []
        for name in reg.names():
            fam = reg.get(name)
            help_lc = fam.help.lower()
            keys = set()
            for v in fam.snapshot()["values"]:
                keys.update(v.get("labels", {}))
            for key in keys:
                if key.lower() not in help_lc:
                    problems.append(f"{name}: label {key!r} not mentioned "
                                    f"in help {fam.help!r}")
        assert not problems, "\n".join(problems)
