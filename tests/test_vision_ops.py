"""paddle.vision.ops detection family (reference `python/paddle/vision/ops.py`
+ `paddle/fluid/operators/detection/`): numpy-reference output checks in the
OpTest style (`unittests/op_test.py:289`) and finite-difference grad checks
for the differentiable ops."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.param import Parameter
from paddle_tpu.framework.tensor import Tensor
from paddle_tpu.vision import ops as V

from op_test import numeric_grad


def _feat(n=1, c=2, h=8, w=8, seed=0):
    return np.random.default_rng(seed).normal(size=(n, c, h, w)).astype(
        "float32")


class TestRoiAlign:
    def test_constant_map_averages_to_constant(self):
        x = np.full((1, 1, 16, 16), 3.5, "float32")
        boxes = np.array([[2.0, 2.0, 10.0, 10.0]], "float32")
        out = V.roi_align(Tensor(x), Tensor(boxes),
                          Tensor(np.array([1], "int32")), output_size=4)
        assert tuple(out.shape) == (1, 1, 4, 4)
        np.testing.assert_allclose(out.numpy(), 3.5, atol=1e-5)

    def test_linear_ramp_exact(self):
        """Bilinear sampling of a linear function is exact: roi_align over
        f(y, x) = x must return the x-coordinates of the bin sample means."""
        H = W = 16
        x = np.broadcast_to(np.arange(W, dtype="float32"),
                            (1, 1, H, W)).copy()
        boxes = np.array([[4.0, 4.0, 12.0, 12.0]], "float32")
        out = V.roi_align(Tensor(x), Tensor(boxes),
                          Tensor(np.array([1], "int32")),
                          output_size=2, aligned=True)
        # aligned start 4 - 0.5 = 3.5, two bins of width 4: centers of the
        # 2x2 sample grids sit at x = 5.5 and 9.5
        np.testing.assert_allclose(out.numpy()[0, 0, 0], [5.5, 9.5],
                                   atol=1e-5)

    def test_batch_routing(self):
        x = np.stack([np.full((1, 8, 8), 1.0), np.full((1, 8, 8), 2.0)]
                     ).astype("float32")
        boxes = np.array([[0, 0, 4, 4], [0, 0, 4, 4], [0, 0, 4, 4]],
                         "float32")
        out = V.roi_align(Tensor(x), Tensor(boxes),
                          Tensor(np.array([1, 2], "int32")), output_size=2)
        np.testing.assert_allclose(out.numpy()[0], 1.0, atol=1e-5)
        np.testing.assert_allclose(out.numpy()[1:], 2.0, atol=1e-5)

    def test_grad_matches_finite_diff(self):
        x = _feat(1, 1, 8, 8)
        boxes = np.array([[1.0, 1.0, 6.0, 6.0]], "float32")
        bn = np.array([1], "int32")
        p = Parameter(x)
        out = V.roi_align(p, Tensor(boxes), Tensor(bn), output_size=2)
        paddle.sum(out).backward()
        analytic = p.grad.numpy()

        def fn(xv):
            with paddle.no_grad():
                return V.roi_align(Tensor(xv.astype("float32")),
                                   Tensor(boxes), Tensor(bn),
                                   output_size=2).numpy()

        numeric = numeric_grad(fn, [x], wrt=0)
        np.testing.assert_allclose(analytic, numeric, atol=5e-3, rtol=5e-3)


class TestRoiPool:
    def test_max_of_region(self):
        x = np.zeros((1, 1, 8, 8), "float32")
        x[0, 0, 3, 3] = 7.0
        x[0, 0, 6, 6] = 9.0
        boxes = np.array([[0, 0, 7, 7]], "float32")
        out = V.roi_pool(Tensor(x), Tensor(boxes),
                         Tensor(np.array([1], "int32")), output_size=2)
        # bins split rows/cols [0..3], [4..7]: maxima 7, 0, 0, 9
        np.testing.assert_allclose(out.numpy()[0, 0],
                                   [[7.0, 0.0], [0.0, 9.0]], atol=1e-6)

    def test_spatial_scale(self):
        x = np.arange(64, dtype="float32").reshape(1, 1, 8, 8)
        boxes = np.array([[0, 0, 14, 14]], "float32")  # scaled by 0.5 -> 7
        out = V.roi_pool(Tensor(x), Tensor(boxes),
                         Tensor(np.array([1], "int32")), output_size=1,
                         spatial_scale=0.5)
        assert float(out.numpy()[0, 0, 0, 0]) == 63.0

    def test_partially_outside_roi_bins_unshifted(self):
        """Bin edges come from the UNCLAMPED roi start: a roi hanging off
        the left edge pools only the in-image part of each bin."""
        x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4) + 1.0
        boxes = np.array([[-4.0, 0.0, 3.0, 3.0]], "float32")  # cols -4..3
        out = V.roi_pool(Tensor(x), Tensor(boxes),
                         Tensor(np.array([1], "int32")), output_size=(1, 2))
        # bins split cols [-4..0) and [0..4): first bin has NO in-image col
        # until its end... cols -4..-1 off-image -> empty -> 0; second bin
        # cols 0..3 -> max of each row's cols 0..3 over all rows = 16
        np.testing.assert_allclose(out.numpy()[0, 0, 0], [0.0, 16.0],
                                   atol=1e-6)

    def test_grad_flows_to_max_positions(self):
        x = _feat(1, 1, 8, 8, seed=3)
        boxes = np.array([[0, 0, 7, 7]], "float32")
        p = Parameter(x)
        out = V.roi_pool(p, Tensor(boxes), Tensor(np.array([1], "int32")),
                         output_size=2)
        paddle.sum(out).backward()
        g = p.grad.numpy()
        assert g.sum() == pytest.approx(4.0)  # one max per bin
        assert (g > 0).sum() == 4


class TestPsRoiPool:
    def test_position_sensitive_channels(self):
        # C = 4 = oh*ow with out channel count 1; each bin reads its own
        # channel: fill channel k with value k
        x = np.stack([np.full((8, 8), float(k)) for k in range(4)])[None]
        x = x.astype("float32")
        boxes = np.array([[0, 0, 8, 8]], "float32")
        out = V.psroi_pool(Tensor(x), Tensor(boxes),
                           Tensor(np.array([1], "int32")), output_size=2)
        assert tuple(out.shape) == (1, 1, 2, 2)
        np.testing.assert_allclose(out.numpy()[0, 0],
                                   [[0.0, 1.0], [2.0, 3.0]], atol=1e-5)


class TestDeformConv2d:
    def test_zero_offset_matches_plain_conv(self):
        from paddle_tpu.nn import functional as F
        rng = np.random.default_rng(0)
        x = rng.normal(size=(2, 3, 8, 8)).astype("float32")
        w = rng.normal(size=(4, 3, 3, 3)).astype("float32") * 0.2
        off = np.zeros((2, 2 * 9, 6, 6), "float32")
        got = V.deform_conv2d(Tensor(x), Tensor(off), Tensor(w))
        ref = F.conv2d(Tensor(x), Tensor(w))
        np.testing.assert_allclose(got.numpy(), ref.numpy(), atol=2e-4,
                                   rtol=2e-4)

    def test_integer_offset_shifts_sampling(self):
        x = np.zeros((1, 1, 6, 6), "float32")
        x[0, 0, 2, 3] = 1.0
        w = np.ones((1, 1, 1, 1), "float32")
        off = np.zeros((1, 2, 6, 6), "float32")
        off[0, 0] = 1.0  # sample one row below
        off[0, 1] = 2.0  # two cols right
        got = V.deform_conv2d(Tensor(x), Tensor(off), Tensor(w))
        # output at (1,1) samples input (2,3)
        assert float(got.numpy()[0, 0, 1, 1]) == pytest.approx(1.0)

    def test_v2_mask_modulates(self):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(1, 2, 6, 6)).astype("float32")
        w = rng.normal(size=(2, 2, 3, 3)).astype("float32")
        off = np.zeros((1, 18, 4, 4), "float32")
        m_half = np.full((1, 9, 4, 4), 0.5, "float32")
        full = V.deform_conv2d(Tensor(x), Tensor(off), Tensor(w))
        half = V.deform_conv2d(Tensor(x), Tensor(off), Tensor(w),
                               mask=Tensor(m_half))
        np.testing.assert_allclose(half.numpy(), 0.5 * full.numpy(),
                                   atol=1e-5)

    def test_grad_matches_finite_diff_weight(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(1, 1, 5, 5)).astype("float32")
        w = rng.normal(size=(1, 1, 3, 3)).astype("float32")
        off = (rng.normal(size=(1, 18, 3, 3)) * 0.3).astype("float32")
        pw = Parameter(w)
        out = V.deform_conv2d(Tensor(x), Tensor(off), pw)
        paddle.sum(out).backward()
        analytic = pw.grad.numpy()

        def fn(wv):
            with paddle.no_grad():
                return V.deform_conv2d(Tensor(x), Tensor(off),
                                       Tensor(wv.astype("float32"))).numpy()

        numeric = numeric_grad(fn, [w], wrt=0)
        np.testing.assert_allclose(analytic, numeric, atol=5e-3, rtol=5e-3)

    def test_layer_wrapper(self):
        layer = V.DeformConv2D(3, 8, 3, padding=1)
        x = Tensor(_feat(2, 3, 8, 8))
        off = Tensor(np.zeros((2, 18, 8, 8), "float32"))
        out = layer(x, off)
        assert tuple(out.shape) == (2, 8, 8, 8)


class TestYolo:
    def test_yolo_box_shapes_and_range(self):
        S, cls = 3, 5
        x = np.random.default_rng(0).normal(
            size=(2, S * (cls + 5), 4, 4)).astype("float32")
        img = np.array([[256, 256], [320, 320]], "int32")
        boxes, scores = V.yolo_box(Tensor(x), Tensor(img),
                                   anchors=[10, 13, 16, 30, 33, 23],
                                   class_num=cls, conf_thresh=0.0,
                                   downsample_ratio=32)
        assert tuple(boxes.shape) == (2, S * 16, 4)
        assert tuple(scores.shape) == (2, S * 16, cls)
        b = boxes.numpy()
        assert (b[0] >= 0).all() and (b[0] <= 255.0 + 1e-3).all()

    def test_yolo_box_conf_thresh_zeroes(self):
        S, cls = 1, 2
        x = np.full((1, S * (cls + 5), 2, 2), -10.0, "float32")  # conf ~ 0
        img = np.array([[64, 64]], "int32")
        boxes, scores = V.yolo_box(Tensor(x), Tensor(img), anchors=[10, 13],
                                   class_num=cls, conf_thresh=0.5,
                                   downsample_ratio=32)
        assert np.all(boxes.numpy() == 0)
        assert np.all(scores.numpy() == 0)

    @pytest.mark.slow  # heavy e2e; full-suite only (tier-1 budget)
    def test_yolo_loss_finite_and_decreases(self):
        """The loss must be finite, positive, and trainable: a few SGD steps
        on the raw head tensor should reduce it."""
        rng = np.random.default_rng(0)
        S, cls, H = 3, 4, 4
        x = (rng.normal(size=(2, S * (cls + 5), H, H)) * 0.1).astype(
            "float32")
        gt_box = np.array([[[0.5, 0.5, 0.3, 0.4], [0.25, 0.25, 0.1, 0.1]],
                           [[0.7, 0.3, 0.2, 0.2], [0.0, 0.0, 0.0, 0.0]]],
                          "float32")
        gt_label = np.array([[1, 3], [0, 0]], "int64")
        kw = dict(anchors=[10, 13, 16, 30, 33, 23],
                  anchor_mask=[0, 1, 2], class_num=cls,
                  ignore_thresh=0.7, downsample_ratio=32)
        p = Parameter(x)
        losses = []
        for _ in range(8):
            loss = paddle.sum(V.yolo_loss(p, Tensor(gt_box),
                                          Tensor(gt_label), **kw))
            loss.backward()
            with paddle.no_grad():
                p.data = p.data - 0.01 * p.grad.data
            p.clear_grad()
            losses.append(float(loss))
        assert np.isfinite(losses).all()
        assert losses[0] > 0
        assert losses[-1] < losses[0]


class TestNms:
    def test_suppresses_overlaps(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                         "float32")
        scores = np.array([0.9, 0.8, 0.7], "float32")
        keep = V.nms(Tensor(boxes), iou_threshold=0.5, scores=Tensor(scores))
        k = keep.numpy()
        assert list(k[k >= 0]) == [0, 2]

    def test_categories_do_not_cross_suppress(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], "float32")
        scores = np.array([0.9, 0.8], "float32")
        cats = np.array([0, 1], "int64")
        keep = V.nms(Tensor(boxes), iou_threshold=0.5, scores=Tensor(scores),
                     category_idxs=Tensor(cats), categories=[0, 1])
        k = keep.numpy()
        assert set(k[k >= 0]) == {0, 1}

    def test_negative_coords_do_not_cross_suppress(self):
        """Per-class offset must cover the full coordinate RANGE: a
        negative-coordinate box must not bleed into class 0's block."""
        boxes = np.array([[0, 0, 10, 10], [-11, 0, -1, 10]], "float32")
        scores = np.array([0.9, 0.8], "float32")
        cats = np.array([0, 1], "int64")
        keep = V.nms(Tensor(boxes), iou_threshold=0.3, scores=Tensor(scores),
                     category_idxs=Tensor(cats), categories=[0, 1])
        k = keep.numpy()
        assert set(k[k >= 0]) == {0, 1}

    def test_top_k(self):
        boxes = np.array([[0, 0, 1, 1], [5, 5, 6, 6], [10, 10, 11, 11]],
                         "float32")
        scores = np.array([0.1, 0.9, 0.5], "float32")
        keep = V.nms(Tensor(boxes), iou_threshold=0.5,
                     scores=Tensor(scores), top_k=2)
        assert list(keep.numpy()) == [1, 2]


class TestMulticlassNms:
    def test_reference_docstring_example(self):
        """The reference's own worked example (fluid detection.py:3283):
        two overlapping boxes, three classes, background 0."""
        boxes = np.array([[[2.0, 3.0, 7.0, 5.0], [3.0, 4.0, 8.0, 5.0]]],
                         "float32")
        scores = np.array([[[0.7, 0.3],    # class 0 (background)
                            [0.2, 0.3],    # class 1
                            [0.4, 0.1]]],  # class 2
                          "float32")
        out, counts = V.multiclass_nms(Tensor(boxes), Tensor(scores),
                                       score_threshold=0.0, nms_top_k=-1,
                                       keep_top_k=10, nms_threshold=0.3)
        n = int(counts.numpy()[0])
        assert n == 2
        rows = out.numpy()[0][:n]
        rows = rows[np.argsort(rows[:, 0])]  # by label
        np.testing.assert_allclose(rows[0], [1, 0.3, 3, 4, 8, 5], atol=1e-5)
        np.testing.assert_allclose(rows[1], [2, 0.4, 2, 3, 7, 5], atol=1e-5)

    def test_per_class_suppression_and_keep_top_k(self):
        boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                           [20, 20, 30, 30]]], "float32")
        scores = np.zeros((1, 2, 3), "float32")
        scores[0, 1] = [0.9, 0.8, 0.7]  # class 1: first two overlap
        out, counts = V.multiclass_nms(Tensor(boxes), Tensor(scores),
                                       score_threshold=0.1, nms_top_k=-1,
                                       keep_top_k=1, nms_threshold=0.5,
                                       background_label=0)
        assert int(counts.numpy()[0]) == 1
        row = out.numpy()[0][0]
        assert row[0] == 1 and row[1] == pytest.approx(0.9)

    def test_padded_rows_carry_label_minus_one(self):
        boxes = np.array([[[0, 0, 1, 1]]], "float32")
        scores = np.array([[[0.0], [0.05]]], "float32")  # below threshold
        out, counts = V.multiclass_nms(Tensor(boxes), Tensor(scores),
                                       score_threshold=0.2, nms_top_k=-1,
                                       keep_top_k=4, nms_threshold=0.3)
        assert int(counts.numpy()[0]) == 0
        assert np.all(out.numpy()[0][:, 0] == -1)


class TestIO:
    def test_read_file_decode_jpeg_roundtrip(self, tmp_path):
        from PIL import Image
        arr = (np.random.default_rng(0).random((16, 16, 3)) * 255).astype(
            "uint8")
        path = str(tmp_path / "t.jpg")
        Image.fromarray(arr).save(path, quality=95)
        data = V.read_file(path)
        assert data.numpy().dtype == np.uint8
        img = V.decode_jpeg(data)
        assert tuple(img.shape) == (3, 16, 16)
        # lossy codec: just require gross agreement
        assert abs(img.numpy().astype(int).mean()
                   - arr.transpose(2, 0, 1).astype(int).mean()) < 10


def test_all_reference_names_exist():
    """Audit against the reference module's __all__
    (`/root/reference/python/paddle/vision/ops.py:26`)."""
    ref_all = ["yolo_loss", "yolo_box", "deform_conv2d", "DeformConv2D",
               "read_file", "decode_jpeg", "roi_pool", "RoIPool",
               "psroi_pool", "PSRoIPool", "roi_align", "RoIAlign"]
    missing = [n for n in ref_all if not hasattr(V, n)]
    assert not missing, missing
