"""Fused 1x1-conv + BN(+residual add)+activation training chain.

The r06 perf-round kernel (`ops/pallas/fused_conv_bn.py`): the fused op
must match the unfused `conv2d` -> `batch_norm(+relu)(+add)` composition
in forward outputs, batch statistics, running-stat updates and gradients —
train AND eval mode, with and without the residual add. Kernels run under
the Pallas interpreter so CPU CI exercises the kernel path itself, not
only the XLA fallback.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.nn import functional as F
from paddle_tpu.ops.pallas import autotune
from paddle_tpu.ops.pallas import fused_bn as fb
from paddle_tpu.ops.pallas import fused_conv_bn as fcb

EPS = 1e-5


@pytest.fixture()
def interpret_mode(monkeypatch):
    """Pallas kernels in the interpreter; autotune static picks (the
    impl=1 default = the Pallas kernel, so parity tests exercise it)."""
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
    old_f, old_b = fcb._INTERPRET, fb._INTERPRET
    fcb._INTERPRET = fb._INTERPRET = True
    fcb._probe_status.clear()
    fb._probe_status.clear()
    autotune.reset_for_tests()
    yield
    fcb._INTERPRET, fb._INTERPRET = old_f, old_b
    fcb._probe_status.clear()
    fb._probe_status.clear()
    autotune.reset_for_tests()


def _arrs(rng, N=4, H=8, W=8, Cin=128, Cout=256, dtype=np.float32):
    x = jnp.asarray(rng.normal(size=(N, H, W, Cin)).astype(dtype))
    w = jnp.asarray((rng.normal(size=(Cout, Cin, 1, 1)) * 0.05).astype(dtype))
    g = jnp.asarray(rng.normal(size=(Cout,)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(Cout,)).astype(np.float32))
    z = jnp.asarray(rng.normal(size=(N, H, W, Cout)).astype(dtype))
    return x, w, g, b, z


def _composed(x, w, g, b, z=None, act="relu"):
    """The unfused reference chain in plain jnp (f32)."""
    Cout, Cin = w.shape[0], w.shape[1]
    x2 = x.reshape(-1, Cin).astype(jnp.float32)
    yc = x2 @ w.reshape(Cout, Cin).T.astype(jnp.float32)
    mean = yc.mean(0)
    var = jnp.maximum((yc ** 2).mean(0) - mean ** 2, 0.0)
    y = (yc - mean) * jax.lax.rsqrt(var + EPS) * g + b
    if z is not None:
        y = y + z.reshape(-1, Cout).astype(jnp.float32)
    if act == "relu":
        y = jnp.maximum(y, 0.0)
    return y.reshape(x.shape[:-1] + (Cout,)), mean, var


class TestKernelParity:
    """Raw-op parity on eligible shapes, kernels interpreted."""

    def test_forward_and_stats_match(self, interpret_mode):
        rng = np.random.default_rng(0)
        x, w, g, b, _ = _arrs(rng)
        before = fcb._stats["pallas_fwd"]
        y, m, v = fcb.fused_conv1x1_bn_act(x, w, g, b, epsilon=EPS,
                                           act="relu")
        assert fcb._stats["pallas_fwd"] > before, "kernel path not taken"
        ry, rm, rv = _composed(x, w, g, b)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ry),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(m), np.asarray(rm),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(v), np.asarray(rv),
                                   rtol=1e-4, atol=1e-5)

    def test_add_forward_matches(self, interpret_mode):
        rng = np.random.default_rng(1)
        x, w, g, b, z = _arrs(rng)
        y, m, v = fcb.fused_conv1x1_bn_act(x, w, g, b, residual=z,
                                           epsilon=EPS, act="relu")
        ry, _, _ = _composed(x, w, g, b, z)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ry),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("has_add", [False, True])
    @pytest.mark.parametrize("act", ["relu", None])
    def test_grads_match_composition(self, interpret_mode, has_add, act):
        """fwd+bwd grad-check parity vs the unfused composition for every
        (act, residual) form — the satellite's acceptance matrix."""
        rng = np.random.default_rng(2)
        x, w, g, b, z = _arrs(rng)
        dy = jnp.asarray(rng.normal(size=(4, 8, 8, 256)).astype(np.float32))

        def fused(x, w, g, b, z):
            y, _, _ = fcb.fused_conv1x1_bn_act(
                x, w, g, b, residual=z if has_add else None,
                epsilon=EPS, act=act)
            return jnp.sum(y.astype(jnp.float32) * dy)

        def ref(x, w, g, b, z):
            y, _, _ = _composed(x, w, g, b, z if has_add else None, act=act)
            return jnp.sum(y * dy)

        gf = jax.grad(fused, argnums=(0, 1, 2, 3, 4))(x, w, g, b, z)
        gr = jax.grad(ref, argnums=(0, 1, 2, 3, 4))(x, w, g, b, z)
        names = ("x", "w", "gamma", "beta", "z")
        for name, a, r in zip(names, gf, gr):
            if name == "z" and not has_add:
                continue
            ra = np.asarray(r)
            scale = max(float(np.abs(ra).max()), 1.0)
            np.testing.assert_allclose(
                np.asarray(a), ra, rtol=2e-4, atol=2e-4 * scale,
                err_msg=f"grad {name} mismatch (act={act}, add={has_add})")

    def test_bf16_io_fp32_stats(self, interpret_mode):
        rng = np.random.default_rng(3)
        x, w, g, b, _ = _arrs(rng, dtype=np.float32)
        xb, wb = x.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
        y, m, v = fcb.fused_conv1x1_bn_act(xb, wb, g, b, act="relu")
        assert y.dtype == jnp.bfloat16
        assert m.dtype == jnp.float32 and v.dtype == jnp.float32
        ry, _, _ = _composed(x, w, g, b)
        np.testing.assert_allclose(np.asarray(y, np.float32),
                                   np.asarray(ry), rtol=0.1, atol=0.15)

    def test_tail_block_masking(self, interpret_mode):
        """R not divisible by the row block: tail rows must not leak into
        the statistics (R=320 with the 256-row default block)."""
        rng = np.random.default_rng(4)
        x, w, g, b, _ = _arrs(rng, N=5, H=8, W=8)
        y, m, v = fcb.fused_conv1x1_bn_act(x, w, g, b, act="relu")
        ry, rm, rv = _composed(x, w, g, b)
        np.testing.assert_allclose(np.asarray(m), np.asarray(rm),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ry),
                                   rtol=1e-4, atol=1e-4)

    def test_eligibility_gates(self, interpret_mode):
        f32 = jnp.float32
        ok = fcb.eligible((4, 8, 8, 128), (256, 128, 1, 1), 1, 0, 1, 1,
                          "NHWC", f32)
        assert ok
        # 3x3 kernel, stride, padding, groups, NCHW, non-multiple channels
        assert not fcb.eligible((4, 8, 8, 128), (256, 128, 3, 3), 1, 1, 1,
                                1, "NHWC", f32)
        assert not fcb.eligible((4, 8, 8, 128), (256, 128, 1, 1), 2, 0, 1,
                                1, "NHWC", f32)
        assert not fcb.eligible((4, 8, 8, 128), (256, 128, 1, 1), 1, 1, 1,
                                1, "NHWC", f32)
        assert not fcb.eligible((4, 8, 8, 128), (256, 128, 1, 1), 1, 0, 1,
                                2, "NHWC", f32)
        assert not fcb.eligible((4, 128, 8, 8), (256, 128, 1, 1), 1, 0, 1,
                                1, "NCHW", f32)
        assert not fcb.eligible((4, 8, 8, 96), (256, 96, 1, 1), 1, 0, 1,
                                1, "NHWC", f32)
        # R below the eligibility floor stays on the composition
        assert not fcb.eligible((2, 8, 8, 128), (256, 128, 1, 1), 1, 0, 1,
                                1, "NHWC", f32)


class TestFunctionalWiring:
    """F.conv2d_bn: fused dispatch, running stats, eval mode, fallback."""

    def _layers(self, Cin=128, Cout=256, k=1):
        conv = nn.Conv2D(Cin, Cout, k, bias_attr=False, data_format="NHWC",
                         padding=(k - 1) // 2)
        bn = nn.BatchNorm2D(Cout, data_format="NHWC", act="relu")
        return conv, bn

    def _call(self, conv, bn, x, residual=None, training=True):
        return F.conv2d_bn(
            x, conv.weight, bn._mean, bn._variance, bn.weight, bn.bias,
            training=training, momentum=bn._momentum, epsilon=bn._epsilon,
            stride=conv._stride, padding=conv._padding,
            dilation=conv._dilation, groups=conv._groups,
            data_format="NHWC", act=bn._act, residual=residual)

    def test_train_matches_composition_and_updates_stats(
            self, interpret_mode):
        rng = np.random.default_rng(5)
        paddle.seed(0)
        conv, bn = self._layers()
        conv2, bn2 = self._layers()
        conv2.weight.data = conv.weight.data
        bn2.weight.data, bn2.bias.data = bn.weight.data, bn.bias.data
        x = paddle.to_tensor(rng.normal(size=(4, 8, 8, 128)).astype("f4"))
        before = fcb._stats["pallas_fwd"] + fcb._stats["xla_fwd"]
        out = self._call(conv, bn, x, training=True)
        assert fcb._stats["pallas_fwd"] + fcb._stats["xla_fwd"] > before
        # unfused composition with identical params
        y = F.conv2d(x, conv2.weight, None, data_format="NHWC")
        ref = F.batch_norm(y, bn2._mean, bn2._variance, bn2.weight,
                           bn2.bias, training=True, epsilon=bn2._epsilon,
                           data_format="NHWC", act="relu")
        np.testing.assert_allclose(np.asarray(out.data), np.asarray(ref.data),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(bn._mean.data),
                                   np.asarray(bn2._mean.data),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(bn._variance.data),
                                   np.asarray(bn2._variance.data),
                                   rtol=1e-4, atol=1e-6)

    def test_eval_mode_matches_composition(self, interpret_mode):
        rng = np.random.default_rng(6)
        paddle.seed(0)
        conv, bn = self._layers()
        x = paddle.to_tensor(rng.normal(size=(4, 8, 8, 128)).astype("f4"))
        z = paddle.to_tensor(rng.normal(size=(4, 8, 8, 256)).astype("f4"))
        before = dict(fcb._stats)
        out = self._call(conv, bn, x, residual=z, training=False)
        # eval mode must NOT take the fused train kernel (global stats)
        assert dict(fcb._stats) == before
        y = F.conv2d(x, conv.weight, None, data_format="NHWC")
        ref = F.batch_norm(y, bn._mean, bn._variance, bn.weight, bn.bias,
                           training=False, epsilon=bn._epsilon,
                           data_format="NHWC", act="relu", residual=z)
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.asarray(ref.data),
                                   rtol=1e-5, atol=1e-5)

    def test_3x3_falls_back_to_composition(self, interpret_mode):
        rng = np.random.default_rng(7)
        paddle.seed(0)
        conv, bn = self._layers(k=3)
        x = paddle.to_tensor(rng.normal(size=(4, 8, 8, 128)).astype("f4"))
        before = dict(fcb._stats)
        out = self._call(conv, bn, x, training=True)
        assert dict(fcb._stats) == before, "3x3 must not take the 1x1 path"
        assert tuple(out.shape) == (4, 8, 8, 256)


class TestResNetIntegration:
    def test_bottleneck_fused_vs_unfused_conv(self, interpret_mode):
        """fused_conv_bn=True vs False on an eligible NHWC bottleneck:
        same forward (tolerances), grads flow, running stats agree."""
        from paddle_tpu.models.resnet import BottleneckBlock
        rng = np.random.default_rng(8)

        def build(fused_conv):
            paddle.seed(0)
            # width 128 / inplanes 512: conv1 (512->128) and conv3
            # (128->512) are 1x1s with lane-multiple channels, and
            # 4*8*8=256 rows meets the eligibility floor
            return BottleneckBlock(512, 128, data_format="NHWC",
                                   fused_conv_bn=fused_conv)

        x = paddle.to_tensor(rng.normal(size=(4, 8, 8, 512)).astype("f4"))
        a, b = build(True), build(False)
        a.train(), b.train()
        before = fcb._stats["pallas_fwd"] + fcb._stats["xla_fwd"]
        ya, yb = a(x), b(x)
        assert fcb._stats["pallas_fwd"] + fcb._stats["xla_fwd"] > before, \
            "no conv+BN fusion engaged in the fused block"
        np.testing.assert_allclose(np.asarray(ya.data), np.asarray(yb.data),
                                   rtol=2e-4, atol=2e-4)
        for la, lb in (("bn1", "bn1"), ("bn3", "bn3")):
            np.testing.assert_allclose(
                np.asarray(getattr(a, la)._mean.data),
                np.asarray(getattr(b, lb)._mean.data),
                rtol=1e-4, atol=1e-6)

    def test_bottleneck_backward_parity(self, interpret_mode):
        from paddle_tpu.models.resnet import BottleneckBlock
        rng = np.random.default_rng(9)
        xnp = rng.normal(size=(4, 8, 8, 512)).astype("f4")

        def grads(fused_conv):
            paddle.seed(0)
            blk = BottleneckBlock(512, 128, data_format="NHWC",
                                  fused_conv_bn=fused_conv)
            blk.train()
            x = paddle.to_tensor(xnp)
            loss = (blk(x) ** 2).mean()
            loss.backward()
            return {k: np.asarray(p.grad.data)
                    for k, p in blk.named_parameters()
                    if p.grad is not None}

        ga, gb = grads(True), grads(False)
        assert set(ga) == set(gb) and ga, "grad sets differ or empty"
        for k in ga:
            scale = max(float(np.abs(gb[k]).max()), 1e-3)
            np.testing.assert_allclose(ga[k], gb[k], rtol=3e-4,
                                       atol=3e-4 * scale, err_msg=k)

    @pytest.mark.slow  # whole-resnet18 double trace; bottleneck parity stays fast
    def test_resnet18_knob_off_is_status_quo(self):
        """Without interpret/TPU the knob is inert: fused_conv_bn=True
        must trace the identical composition (CPU tier-1 safety)."""
        from paddle_tpu.models.resnet import ResNet, BasicBlock
        rng = np.random.default_rng(10)
        x = paddle.to_tensor(rng.normal(size=(2, 3, 32, 32)).astype("f4"))

        def run(fused_conv):
            paddle.seed(0)
            m = ResNet(BasicBlock, 18, num_classes=10,
                       fused_conv_bn=fused_conv)
            m.eval()
            return np.asarray(m(x).data)

        np.testing.assert_array_equal(run(True), run(False))


class TestAutotuneIntegration:
    def test_force_mode_tunes_and_caches(self, interpret_mode, monkeypatch,
                                         tmp_path):
        """The measured impl decision: force-mode tune over the candidate
        space (Pallas blocks + the XLA-composed impl=0 rewrite) resolves,
        persists under op "conv_bn", and the memo short-circuits."""
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "force")
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE_DIR", str(tmp_path))
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_REPEATS", "1")
        autotune.reset_for_tests()
        rng = np.random.default_rng(11)
        x, w, g, b, _ = _arrs(rng)
        y, _, _ = fcb.fused_conv1x1_bn_act(x, w, g, b, act="relu")
        ops = [t["op"] for t in autotune.tuned_log()]
        assert "conv_bn" in ops
        assert list(tmp_path.glob("conv_bn-*.json")), "no persisted entry"
        ry, _, _ = _composed(x, w, g, b)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ry),
                                   rtol=1e-4, atol=1e-4)

    def test_xla_impl_candidate_matches(self, interpret_mode):
        """impl=0 (the XLA-composed rewrite) is a legal winner: force the
        config and check output parity with the Pallas impl."""
        rng = np.random.default_rng(12)
        x, w, g, b, _ = _arrs(rng)
        from paddle_tpu.ops.pallas import tiling
        w2d = w.reshape(256, 128).T
        x2d = x.reshape(-1, 128)
        cfg_x = tiling.make_config(impl=0, rows=0, cols=0)
        cfg_p = tiling.make_config(impl=1, rows=256, cols=256)
        yx, mx, vx = fcb._conv_bn_act(x2d, w2d, g, b, EPS, "relu", cfg_x)
        yp, mp, vp = fcb._conv_bn_act(x2d, w2d, g, b, EPS, "relu", cfg_p)
        np.testing.assert_allclose(np.asarray(yx), np.asarray(yp),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(mx), np.asarray(mp),
                                   rtol=1e-4, atol=1e-5)


class TestAffinelessBN:
    def test_no_affine_fused_path(self, interpret_mode):
        """Review regression: weight=None/bias=None on an ELIGIBLE shape
        must size the substitute affine by the conv OUTPUT channels (was
        built from x's Cin -> broadcast crash when Cin != Cout)."""
        rng = np.random.default_rng(13)
        x = paddle.to_tensor(rng.normal(size=(4, 8, 8, 128)).astype("f4"))
        w = paddle.to_tensor(
            (rng.normal(size=(256, 128, 1, 1)) * 0.05).astype("f4"))
        rm = paddle.to_tensor(np.zeros(256, np.float32))
        rv = paddle.to_tensor(np.ones(256, np.float32))
        before = fcb._stats["pallas_fwd"] + fcb._stats["xla_fwd"]
        out = F.conv2d_bn(x, w, rm, rv, weight=None, bias=None,
                          training=True, data_format="NHWC", act="relu")
        assert fcb._stats["pallas_fwd"] + fcb._stats["xla_fwd"] > before
        y = F.conv2d(x, w, None, data_format="NHWC")
        ref = F.batch_norm(y, paddle.to_tensor(np.zeros(256, np.float32)),
                           paddle.to_tensor(np.ones(256, np.float32)),
                           None, None, training=True, epsilon=1e-5,
                           data_format="NHWC", act="relu")
        np.testing.assert_allclose(np.asarray(out.data),
                                   np.asarray(ref.data),
                                   rtol=1e-4, atol=1e-4)


class TestLayerCallSemantics:
    def test_hooks_and_layer_calls_survive_on_ineligible_paths(self):
        """Review regression: with fused_conv_bn=True but the kernel NOT
        engaging (CPU / ineligible shape), the block must still call its
        conv/bn sublayers through Layer.__call__ — forward hooks fire and
        the PR-9 NaN-attribution layer stack keeps sublayer names."""
        from paddle_tpu.models.resnet import BasicBlock
        paddle.seed(0)
        blk = BasicBlock(16, 16, fused_conv_bn=True)
        blk.train()
        fired = []
        blk.bn1.register_forward_post_hook(
            lambda layer, inp, out: fired.append("bn1"))
        blk.conv2.register_forward_post_hook(
            lambda layer, inp, out: fired.append("conv2"))
        rng = np.random.default_rng(14)
        x = paddle.to_tensor(rng.normal(size=(2, 16, 8, 8)).astype("f4"))
        blk(x)
        assert "bn1" in fired and "conv2" in fired, fired
