"""Tensor.register_hook + backward/grad(create_graph=True) — the imperative
autograd edge surface (reference: test_tensor_register_hook.py,
test_imperative_double_grad.py; engines at
/root/reference/paddle/fluid/eager/backward.cc:421 GeneralGrad and
python/paddle/fluid/dygraph/varbase_patch_methods.py:258 register_hook)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.nn import functional as F


class TestRegisterHook:
    def test_leaf_hook_scales_grad(self):
        x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32),
                             stop_gradient=False)
        x.register_hook(lambda g: g * 2)
        y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), 2 * 2 * x.numpy())

    def test_intermediate_hook_affects_upstream(self):
        # hook on an intermediate modifies what flows to producers
        x = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        h = x * 3          # dh/dx = 3
        h.register_hook(lambda g: g * 10)
        y = h * 5          # dy/dh = 5
        y.backward()
        # grad = 5 (into h) -> hook x10 -> 50 -> *3 into x = 150
        np.testing.assert_allclose(x.grad.numpy(), [150.0])

    def test_hook_fires_on_accumulated_fanin(self):
        # the hook must see the TOTAL gradient, not one branch's share
        seen = []
        x = paddle.to_tensor(np.array([1.0], np.float32),
                             stop_gradient=False)
        h = x * 1.0
        h.register_hook(lambda g: seen.append(g.numpy().copy()))
        y = h * 2 + h * 3   # dy/dh = 5 via two consumers
        y.backward()
        assert len(seen) == 1
        np.testing.assert_allclose(seen[0], [5.0])
        np.testing.assert_allclose(x.grad.numpy(), [5.0])

    def test_hook_none_return_keeps_grad(self):
        x = paddle.to_tensor(np.array([4.0], np.float32),
                             stop_gradient=False)
        calls = []
        x.register_hook(lambda g: calls.append(1))
        (x * 2).backward()
        assert calls == [1]
        np.testing.assert_allclose(x.grad.numpy(), [2.0])

    def test_remove_handle(self):
        x = paddle.to_tensor(np.array([1.0], np.float32),
                             stop_gradient=False)
        handle = x.register_hook(lambda g: g * 100)
        handle.remove()
        (x * 2).backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])

    def test_hook_in_training_step_clips(self):
        # reference idiom: per-tensor clipping via hook inside a real step
        lin = nn.Linear(4, 4)
        lin.weight.register_hook(lambda g: g.clip(-1e-3, 1e-3))
        opt = optimizer.SGD(learning_rate=1.0,
                            parameters=lin.parameters())
        x = paddle.to_tensor(np.ones((2, 4), np.float32) * 100)
        loss = (lin(x) ** 2).sum()
        loss.backward()
        assert float(np.abs(lin.weight.grad.numpy()).max()) <= 1e-3 + 1e-8
        opt.step()


class TestCreateGraph:
    def test_double_grad_scalar(self):
        # y = x^3: dy/dx = 3x^2, d2y/dx2 = 6x
        x = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        y = x * x * x
        (gx,) = paddle.grad(y, x, create_graph=True)
        np.testing.assert_allclose(gx.numpy(), [12.0], rtol=1e-6)
        (ggx,) = paddle.grad(gx, x)
        np.testing.assert_allclose(ggx.numpy(), [12.0], rtol=1e-6)

    def test_double_grad_matches_numeric(self):
        rng = np.random.default_rng(0)
        xv = rng.normal(size=(5,)).astype(np.float32)
        x = paddle.to_tensor(xv, stop_gradient=False)
        y = (x.exp() * x.sin()).sum()
        (gx,) = paddle.grad(y, x, create_graph=True)
        (ggx,) = paddle.grad(gx.sum(), x)
        # analytic: d/dx(e^x sin x) = e^x(sin+cos); d2 = e^x(2cos)
        want = np.exp(xv) * 2 * np.cos(xv)
        np.testing.assert_allclose(ggx.numpy(), want, rtol=1e-4, atol=1e-5)

    def test_backward_create_graph_grad_is_on_tape(self):
        x = paddle.to_tensor(np.array([3.0], np.float32),
                             stop_gradient=False)
        y = x * x
        y.backward(create_graph=True)
        g = x.grad          # 2x, differentiable
        assert not g.stop_gradient
        (gg,) = paddle.grad(g, x)
        np.testing.assert_allclose(gg.numpy(), [2.0])

    def test_gradient_penalty_trains(self):
        """WGAN-GP-style loss: ((||d D/d x|| - 1)^2) needs grad-of-grad
        w.r.t. the discriminator's parameters (reference
        test_imperative_double_grad scenario)."""
        paddle.seed(7)
        disc = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 1))
        opt = optimizer.Adam(learning_rate=5e-2,
                             parameters=disc.parameters())
        rng = np.random.default_rng(0)
        xv = rng.normal(size=(16, 8)).astype(np.float32)
        losses = []
        for _ in range(12):
            x = paddle.to_tensor(xv, stop_gradient=False)
            out = disc(x)
            (gx,) = paddle.grad(out.sum(), x, create_graph=True)
            gnorm = (gx * gx).sum(axis=1).sqrt()
            gp = ((gnorm - 1.0) ** 2).mean()
            gp.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(gp))
        assert losses[-1] < losses[0] * 0.5, losses

    def test_grad_of_tensor_with_released_producer(self):
        # y's producing node is freed by an earlier backward; a later
        # paddle.grad(z, y) must still harvest dz/dy from the fresh graph
        x = paddle.to_tensor(np.array([2.0], np.float32),
                             stop_gradient=False)
        y = x * 3
        (y * 1.0).backward()    # releases y's producer (retain_graph=False)
        z = y * 5
        (gy,) = paddle.grad(z, y)
        np.testing.assert_allclose(gy.numpy(), [5.0])

    def test_create_graph_through_pylayer_raises_clearly(self):
        from paddle_tpu.autograd import PyLayer

        class Double(PyLayer):
            @staticmethod
            def forward(ctx, x):
                return x * 2

            @staticmethod
            def backward(ctx, g):
                return g * 2

        x = paddle.to_tensor(np.array([1.0], np.float32),
                             stop_gradient=False)
        y = Double.apply(x)
        with pytest.raises(NotImplementedError, match="create_graph"):
            paddle.grad(y, x, create_graph=True)
