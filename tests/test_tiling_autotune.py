"""Shared tiling/autotune layer (PR-10 tentpole): candidate generation,
cache lifecycle (miss -> tune -> persist -> cross-process hit, corrupt
entry -> re-tune, kill switch -> static picks), and tuned-vs-static
numerical parity for all four refactored kernels.

Kernels run under the Pallas interpreter on the CPU mesh; tuning is
exercised with PADDLE_TPU_AUTOTUNE=force (the CI shortcut — interpret-mode
probes, one repeat, capped candidate count), so the whole tune path runs
in tier-1 without a TPU.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas import autotune, tiling
from paddle_tpu.ops.pallas import flash_attention as fa
from paddle_tpu.ops.pallas import fused_bn as fb
from paddle_tpu.ops.pallas import layer_norm as ln
from paddle_tpu.ops.pallas import softmax_ce as sce


@pytest.fixture
def tuner(monkeypatch, tmp_path):
    """force-mode autotune with a private cache dir; memory cache reset."""
    autotune.reset_for_tests()
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "force")
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_CACHE_DIR", str(tmp_path))
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_REPEATS", "1")
    monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_MAX_CONFIGS", "8")
    yield tmp_path
    autotune.reset_for_tests()


def _ev(event, op):
    return autotune._M_EVENTS.value(event=event, op=op)


class TestBlockConfig:
    def test_roundtrip_and_access(self):
        cfg = tiling.make_config(q=256, k=512)
        assert cfg["q"] == 256 and cfg["k"] == 512
        assert cfg.label == "q256-k512"
        assert tiling.BlockConfig.from_json(cfg.to_json()) == cfg
        assert hash(cfg) == hash(tiling.make_config(q=256, k=512))
        with pytest.raises(KeyError):
            cfg["v"]

    def test_mismatched_lengths_rejected(self):
        with pytest.raises(ValueError):
            tiling.BlockConfig(("a", "b"), (1,))


class TestCandidates:
    def test_axis_candidates_snap_and_clip(self):
        # options snap to the grain and clip to the padded array extent;
        # oversized options collapse into the clipped one
        assert tiling.axis_candidates(1000, (128, 256, 2048)) == [128, 256,
                                                                  1024]
        assert tiling.axis_candidates(100, (256, 512), grain=8) == [104]

    def test_default_first_and_vmem_filter(self):
        default = tiling.make_config(rows=256)
        cands = tiling.candidate_configs(
            ("rows",), [[128, 256, 512]], default,
            vmem_bytes=lambda c: c["rows"] * 1024,
            vmem_budget=300 * 1024)
        assert cands[0] == default
        assert tiling.make_config(rows=512) not in cands  # over budget
        assert tiling.make_config(rows=128) in cands

    def test_max_configs_truncates_after_default(self):
        default = tiling.make_config(rows=256)
        cands = tiling.candidate_configs(
            ("rows",), [[64, 128, 192, 256]], default, max_configs=2)
        assert len(cands) == 2 and cands[0] == default

    def test_shape_bucket_powers_of_two(self):
        assert tiling.shape_bucket(64) == 64
        assert tiling.shape_bucket(65) == 128
        assert tiling.shape_bucket(1024) == 1024
        assert tiling.shape_bucket(1025) == 2048


class TestCacheLifecycle:
    """miss -> tune -> persist -> hit; corrupt -> re-tune; kill switch ->
    static default. The stub bench makes rows=128 measurably fastest so
    the winner is deterministic."""

    def _setup(self, op):
        default = tiling.make_config(rows=256)
        cands = [default, tiling.make_config(rows=128),
                 tiling.make_config(rows=512)]
        calls = []

        def bench(cfg):
            calls.append(cfg.label)
            if cfg["rows"] != 128:
                time.sleep(0.01)

        return default, cands, calls, bench

    def test_miss_tune_persist_then_memory_hit(self, tuner):
        op = "t_lifecycle"
        default, cands, calls, bench = self._setup(op)
        cfg = autotune.get_config(op, (1024, "f32"), cands, default, bench,
                                  interpret=True)
        assert cfg["rows"] == 128          # measured winner, not default
        assert calls, "tune ran no probes"
        assert _ev("miss", op) == 1 and _ev("persist", op) == 1
        files = list(tuner.glob("t_lifecycle-*.json"))
        assert len(files) == 1
        # entry is CRC'd JSON with the full key/config payload
        doc = json.loads(files[0].read_text())
        assert {"crc32", "payload"} <= set(doc)
        assert doc["payload"]["config"] == cfg.to_json()
        assert doc["payload"]["op"] == op
        # second resolve: memory cache, no new probes, no new events
        n = len(calls)
        cfg2 = autotune.get_config(op, (1024, "f32"), cands, default, bench,
                                   interpret=True)
        assert cfg2 == cfg and len(calls) == n
        assert _ev("miss", op) == 1

    def test_disk_hit_skips_probing(self, tuner):
        op = "t_diskhit"
        default, cands, calls, bench = self._setup(op)
        cfg = autotune.get_config(op, (512, "bf16"), cands, default, bench,
                                  interpret=True)
        autotune.reset_for_tests()  # new "process": memory cache gone
        n = len(calls)
        cfg2 = autotune.get_config(op, (512, "bf16"), cands, default, bench,
                                   interpret=True)
        assert cfg2 == cfg
        assert len(calls) == n, "disk hit must not re-probe"
        assert _ev("hit", op) == 1
        assert any(t["source"] == "disk" for t in autotune.tuned_log())

    def test_corrupt_entry_retunes_not_crashes(self, tuner):
        op = "t_corrupt"
        default, cands, calls, bench = self._setup(op)
        autotune.get_config(op, (256, "f32"), cands, default, bench,
                            interpret=True)
        (path,) = tuner.glob("t_corrupt-*.json")
        path.write_text("{not json at all")
        autotune.reset_for_tests()
        n = len(calls)
        cfg = autotune.get_config(op, (256, "f32"), cands, default, bench,
                                  interpret=True)
        assert cfg["rows"] == 128
        assert len(calls) > n, "corrupt entry must trigger a re-tune"
        assert _ev("corrupt", op) == 1
        # re-persisted valid
        doc = json.loads(path.read_text())
        assert doc["payload"]["config"] == cfg.to_json()

    def test_crc_mismatch_detected(self, tuner):
        op = "t_crc"
        default, cands, calls, bench = self._setup(op)
        autotune.get_config(op, (256, "f32"), cands, default, bench,
                            interpret=True)
        (path,) = tuner.glob("t_crc-*.json")
        doc = json.loads(path.read_text())
        doc["payload"]["config"]["dims"] = [512]  # tamper, stale CRC
        path.write_text(json.dumps(doc))
        autotune.reset_for_tests()
        cfg = autotune.get_config(op, (256, "f32"), cands, default, bench,
                                  interpret=True)
        assert _ev("corrupt", op) == 1
        assert cfg["rows"] == 128  # re-tuned, tampered value not trusted

    def test_kill_switch_returns_static_untouched(self, tuner, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        op = "t_killswitch"
        default, cands, calls, bench = self._setup(op)
        cfg = autotune.get_config(op, (128, "f32"), cands, default, bench,
                                  interpret=True)
        assert cfg == default
        assert not calls, "kill switch must not probe"
        assert _ev("disabled", op) >= 1
        assert not list(tuner.glob("t_killswitch-*.json"))

    def test_on_mode_is_static_off_tpu(self, tuner, monkeypatch):
        # default mode ("1"): CPU/interpret dispatch gets static picks
        # untimed — tier-1 never pays interpreter probe sweeps
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
        op = "t_onmode"
        default, cands, calls, bench = self._setup(op)
        cfg = autotune.get_config(op, (128, "f32"), cands, default, bench,
                                  interpret=True)
        assert cfg == default and not calls
        assert _ev("static", op) == 1

    def test_force_after_static_resolution_retunes(self, tuner,
                                                   monkeypatch):
        # the env is read LIVE: a provisional "static" resolution must not
        # pin the config forever once the mode escalates to force
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "1")
        op = "t_escalate"
        default, cands, calls, bench = self._setup(op)
        cfg = autotune.get_config(op, (64, "f32"), cands, default, bench,
                                  interpret=True)
        assert cfg == default and not calls  # static, untimed
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "force")
        cfg2 = autotune.get_config(op, (64, "f32"), cands, default, bench,
                                   interpret=True)
        assert calls, "force after a static resolve must tune"
        assert cfg2["rows"] == 128

    def test_probe_error_candidate_skipped(self, tuner):
        op = "t_probeerr"
        default = tiling.make_config(rows=256)
        cands = [default, tiling.make_config(rows=128)]

        def bench(cfg):
            if cfg["rows"] == 128:
                raise RuntimeError("mosaic says no")
            time.sleep(0.001)

        cfg = autotune.get_config(op, (64, "f32"), cands, default, bench,
                                  interpret=True)
        assert cfg == default
        assert _ev("probe_error", op) == 1

    def test_max_configs_bounds_probe_count(self, tuner, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_MAX_CONFIGS", "1")
        op = "t_bounded"
        default, cands, calls, bench = self._setup(op)
        cfg = autotune.get_config(op, (64, "f32"), cands, default, bench,
                                  interpret=True)
        assert cfg == default  # only the default was timed
        assert set(calls) == {"rows256"}

    def test_summary_shape(self, tuner):
        op = "t_summary"
        default, cands, calls, bench = self._setup(op)
        autotune.get_config(op, (64, "f32"), cands, default, bench,
                            interpret=True)
        s = autotune.summary()
        assert s["enabled"] and s["mode"] == "force"
        assert s["cache_dir"] == str(tuner)
        assert any(t["op"] == op and t["source"] == "tuned"
                   for t in s["tuned"])
        assert s["events"].get("miss", 0) >= 1


_CHILD = r"""
import os, json, sys
os.environ["JAX_PLATFORMS"] = "cpu"
from paddle_tpu.ops.pallas import autotune, tiling
calls = []
def bench(cfg):
    calls.append(cfg.label)
default = tiling.make_config(rows=256)
cands = [default, tiling.make_config(rows=128)]
cfg = autotune.get_config("xproc_op", (1024, "f32"), cands, default, bench,
                          interpret=True)
print("RESULT" + json.dumps({
    "cfg": cfg.label,
    "bench_calls": len(calls),
    "hit": autotune._M_EVENTS.value(event="hit", op="xproc_op"),
    "miss": autotune._M_EVENTS.value(event="miss", op="xproc_op"),
    "persist": autotune._M_EVENTS.value(event="persist", op="xproc_op"),
}))
"""


class TestCrossProcessCache:
    """Acceptance: process A tunes and persists; process B hits the disk
    cache WITHOUT re-probing, and its
    autotune_cache_events_total{event="hit"} counter is > 0."""

    @staticmethod
    def _run_child(cache_dir):
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu",
                    "PADDLE_TPU_AUTOTUNE": "force",
                    "PADDLE_TPU_AUTOTUNE_CACHE_DIR": str(cache_dir),
                    "PADDLE_TPU_AUTOTUNE_REPEATS": "1"})
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stderr[-1500:]
        for line in proc.stdout.splitlines():
            if line.startswith("RESULT"):
                return json.loads(line[len("RESULT"):])
        raise AssertionError(f"child printed no RESULT: {proc.stdout!r}")

    def test_tune_once_hit_everywhere(self, tmp_path):
        a = self._run_child(tmp_path)
        assert a["bench_calls"] > 0 and a["miss"] == 1 and a["persist"] == 1
        assert a["hit"] == 0
        entries = list(tmp_path.glob("xproc_op-*.json"))
        assert len(entries) == 1
        b = self._run_child(tmp_path)
        assert b["cfg"] == a["cfg"]
        assert b["bench_calls"] == 0, "process B re-probed a cached config"
        assert b["hit"] > 0 and b["miss"] == 0


class TestKernelParity:
    """Tuned-vs-static output parity for the four refactored kernels.

    Row-block extents only regroup rows across programs — every row's math
    is identical, so outputs are BIT-compatible across row-block choices
    (layer_norm, fused_bn, softmax_ce block_n, flash block_q). Reduction-
    walk extents (softmax_ce block_v, flash block_k) change the online-
    accumulation grouping, so those assert tight f32 allclose instead.
    """

    def test_layer_norm_block_rows_bitwise(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(512, 256)).astype("float32"))
        g = jnp.asarray(rng.normal(size=(256,)).astype("float32"))
        b = jnp.asarray(rng.normal(size=(256,)).astype("float32"))
        outs = [ln._ln_fwd_pallas(x, g, b, eps=1e-5, block_rows=br,
                                  interpret=True)
                for br in (256, 128, 512)]
        for o in outs[1:]:
            assert np.array_equal(np.asarray(outs[0]), np.asarray(o))

    def test_fused_bn_block_rows_bitwise(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(512, 128)).astype("float32"))
        k = jnp.asarray(rng.normal(size=(128,)).astype("float32"))
        c = jnp.asarray(rng.normal(size=(128,)).astype("float32"))
        fwd = [fb._bn_act_fwd_pallas(x, None, k, c, act="relu",
                                     has_add=False, interpret=True,
                                     block_rows=br)
               for br in (256, 128)]
        assert np.array_equal(np.asarray(fwd[0]), np.asarray(fwd[1]))
        dx = [fb._bn_bwd_dx_pallas(x, fwd[0], x, k, c, c, act="relu",
                                   has_add=False, interpret=True,
                                   block_rows=br)[0]
              for br in (256, 128)]
        assert np.array_equal(np.asarray(dx[0]), np.asarray(dx[1]))
        # the per-channel reductions accumulate across row blocks — block
        # choice changes the f32 addition grouping, so allclose here
        red = [fb._bn_bwd_reduce_pallas(x, fwd[0], x, k, c, act="relu",
                                        interpret=True, block_rows=br)
               for br in (256, 128)]
        np.testing.assert_allclose(np.asarray(red[0][0]),
                                   np.asarray(red[1][0]), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(red[0][1]),
                                   np.asarray(red[1][1]), rtol=1e-5)

    def test_softmax_ce_block_variants(self):
        rng = np.random.default_rng(2)
        N, V = 128, 4096
        lg = jnp.asarray(rng.normal(size=(N, V)).astype("float32") * 3)
        lb = jnp.asarray(rng.integers(0, V, (N,)).astype("int32"))
        base_nll, base_lse = sce._ce_fwd_pallas(lg, lb, blocks=(128, 2048),
                                                interpret=True)
        # row-block change: bit-compatible
        nll_n, _ = sce._ce_fwd_pallas(lg, lb, blocks=(64, 2048),
                                      interpret=True)
        assert np.array_equal(np.asarray(base_nll), np.asarray(nll_n))
        # vocab-walk change: online-lse grouping differs -> tight allclose
        nll_v, _ = sce._ce_fwd_pallas(lg, lb, blocks=(128, 1024),
                                      interpret=True)
        np.testing.assert_allclose(np.asarray(base_nll),
                                   np.asarray(nll_v), rtol=1e-6, atol=1e-6)
        dn = jnp.ones((N,), jnp.float32)
        dl = [sce._ce_bwd_pallas(lg, lb, base_lse, dn, blocks=bl,
                                 interpret=True)
              for bl in ((128, 2048), (64, 1024))]
        # bwd is one pure per-block pass (no cross-block accumulation):
        # bit-compatible across BOTH block dims
        assert np.array_equal(np.asarray(dl[0]), np.asarray(dl[1]))

    def test_flash_block_variants(self):
        rng = np.random.default_rng(3)
        B, L, H, D = 1, 256, 2, 64
        q = jnp.asarray(rng.normal(size=(B, L, H, D)).astype("float32"))
        k = jnp.asarray(rng.normal(size=(B, L, H, D)).astype("float32"))
        v = jnp.asarray(rng.normal(size=(B, L, H, D)).astype("float32"))
        sc = float(1.0 / np.sqrt(D))
        base, base_lse = fa._fa_fwd_pallas(q, k, v, None, True, sc,
                                           interpret=True, blocks=(128, 128))
        # q-block change: rows regroup only -> bit-compatible
        out_q, _ = fa._fa_fwd_pallas(q, k, v, None, True, sc,
                                     interpret=True, blocks=(64, 128))
        assert np.array_equal(np.asarray(base), np.asarray(out_q))
        # k-block change: online-softmax grouping differs -> allclose
        out_k, _ = fa._fa_fwd_pallas(q, k, v, None, True, sc,
                                     interpret=True, blocks=(128, 256))
        np.testing.assert_allclose(np.asarray(base), np.asarray(out_k),
                                   rtol=1e-5, atol=1e-5)
        do = jnp.asarray(rng.normal(size=(B, L, H, D)).astype("float32"))
        g1 = fa._fa_bwd_fused_pallas(q, k, v, base, base_lse, do, None,
                                     True, sc, interpret=True,
                                     blocks=(128, 128))
        g2 = fa._fa_bwd_fused_pallas(q, k, v, base, base_lse, do, None,
                                     True, sc, interpret=True,
                                     blocks=(64, 256))
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestTunedDispatch:
    """End-to-end: force-mode dispatch tunes, records chosen configs, and
    produces outputs matching the kill-switch (static) path."""

    @pytest.fixture
    def fa_interpret(self, monkeypatch):
        monkeypatch.setattr(fa, "_INTERPRET", True)
        # shrink the small-path crossover so a CI-sized seq takes the
        # GRID path (the one with tunable blocks)
        monkeypatch.setattr(fa, "_SMALL_MAX_L", 64)
        fa._pallas_fa_status.clear()
        yield
        fa._pallas_fa_status.clear()

    def test_flash_dispatch_tunes_then_matches_static(
            self, tuner, monkeypatch, fa_interpret):
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_MAX_CONFIGS", "2")
        rng = np.random.default_rng(4)
        B, L, H, D = 1, 128, 2, 64
        q = jnp.asarray(rng.normal(size=(B, L, H, D)).astype("float32"))
        k = jnp.asarray(rng.normal(size=(B, L, H, D)).astype("float32"))
        v = jnp.asarray(rng.normal(size=(B, L, H, D)).astype("float32"))
        p0 = fa._stats["pallas"]
        out_tuned = fa.flash_attention(q, k, v, causal=True)
        assert fa._stats["pallas"] == p0 + 1, "tuned dispatch left Pallas"
        assert autotune._M_TUNES.value(op="flash_fwd") >= 1
        assert autotune._M_TUNES.value(op="flash_bwd_fused") >= 1
        chosen = [v_["labels"] for v_ in
                  autotune._M_CHOSEN.snapshot()["values"]]
        assert any(c.get("op") == "flash_fwd" for c in chosen)
        # kill switch: same dispatch, static picks — numerics must agree
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        autotune.reset_for_tests()
        fa._pallas_fa_status.clear()
        p1 = fa._stats["pallas"]
        out_static = fa.flash_attention(q, k, v, causal=True)
        assert fa._stats["pallas"] == p1 + 1
        np.testing.assert_allclose(np.asarray(out_tuned),
                                   np.asarray(out_static),
                                   rtol=1e-5, atol=1e-5)

    def test_softmax_ce_dispatch_tunes_then_matches_static(
            self, tuner, monkeypatch):
        monkeypatch.setattr(sce, "_INTERPRET", True)
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_MAX_CONFIGS", "2")
        sce._status.clear()
        rng = np.random.default_rng(5)
        N, V = 64, 4096
        lg = jnp.asarray(rng.normal(size=(N, V)).astype("float32"))
        lb = jnp.asarray(rng.integers(0, V, (N,)).astype("int32"))
        assert sce.fused_softmax_ce_eligible(lg, lb)
        nll_tuned = sce.fused_softmax_ce(lg, lb)
        assert autotune._M_TUNES.value(op="softmax_ce") >= 1
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        autotune.reset_for_tests()
        sce._status.clear()
        nll_static = sce.fused_softmax_ce(lg, lb)
        np.testing.assert_allclose(np.asarray(nll_tuned),
                                   np.asarray(nll_static),
                                   rtol=1e-6, atol=1e-6)
        sce._status.clear()

    def test_layer_norm_resolver_static_when_not_forced(self, monkeypatch):
        # default mode on CPU: resolver returns the static pick and the
        # public fused_layer_norm path still works under the interpreter
        monkeypatch.setattr(ln, "_INTERPRET", True)
        monkeypatch.delenv("PADDLE_TPU_AUTOTUNE", raising=False)
        autotune.reset_for_tests()
        ln._pallas_ln_status.clear()
        rng = np.random.default_rng(6)
        x = jnp.asarray(rng.normal(size=(256, 128)).astype("float32"))
        g = jnp.asarray(rng.normal(size=(128,)).astype("float32"))
        b = jnp.asarray(rng.normal(size=(128,)).astype("float32"))
        br = ln._block_rows_for(256, 128, jnp.float32)
        assert br == ln._DEF_BLOCK_ROWS
        y = ln.fused_layer_norm(x, g, b)
        xf = np.asarray(x, np.float32)
        mean = xf.mean(-1, keepdims=True)
        var = xf.var(-1, keepdims=True)
        ref = (xf - mean) / np.sqrt(var + 1e-5) * np.asarray(g) + \
            np.asarray(b)
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4,
                                   atol=1e-4)
        ln._pallas_ln_status.clear()
        autotune.reset_for_tests()

    def test_fused_bn_tuned_path_matches_static(self, tuner, monkeypatch):
        monkeypatch.setattr(fb, "_INTERPRET", True)
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE_MAX_CONFIGS", "2")
        fb._probe_status.clear()
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.normal(size=(2, 16, 8, 128)).astype("float32"))
        g = jnp.asarray(rng.normal(size=(128,)).astype("float32"))
        b = jnp.asarray(rng.normal(size=(128,)).astype("float32"))
        f0 = fb._stats["pallas_fwd"]
        y_tuned, m1, v1 = fb.fused_bn_relu(x, g, b, data_format="NHWC")
        assert fb._stats["pallas_fwd"] > f0
        assert autotune._M_TUNES.value(op="fused_bn") >= 1
        monkeypatch.setenv("PADDLE_TPU_AUTOTUNE", "0")
        autotune.reset_for_tests()
        fb._probe_status.clear()
        y_static, m2, v2 = fb.fused_bn_relu(x, g, b, data_format="NHWC")
        # row-block regrouping only: the fused fwd is bit-compatible
        assert np.array_equal(np.asarray(y_tuned), np.asarray(y_static))
        assert np.array_equal(np.asarray(m1), np.asarray(m2))
        fb._probe_status.clear()


_CONV_BN_CHILD = """
import json
import numpy as np
import jax.numpy as jnp
from paddle_tpu.ops.pallas import autotune, fused_bn as fb
from paddle_tpu.ops.pallas import fused_conv_bn as fcb
fb._INTERPRET = True
fcb._INTERPRET = True
rng = np.random.default_rng(0)
x = jnp.asarray(rng.normal(size=(4, 8, 8, 128)).astype(np.float32))
w = jnp.asarray((rng.normal(size=(256, 128, 1, 1)) * 0.05).astype(np.float32))
g = jnp.ones((256,), jnp.float32)
b = jnp.zeros((256,), jnp.float32)
y, m, v = fcb.fused_conv1x1_bn_act(x, w, g, b, act="relu")
print("RESULT" + json.dumps({
    "y0": float(np.asarray(y).ravel()[0]),
    "hit": autotune._M_EVENTS.value(event="hit", op="conv_bn"),
    "miss": autotune._M_EVENTS.value(event="miss", op="conv_bn"),
    "tunes": autotune._M_TUNES.value(op="conv_bn"),
    "persist": autotune._M_EVENTS.value(event="persist", op="conv_bn"),
}))
"""


class TestConvBnCrossProcessCache:
    """r06 satellite: the NEW conv_bn kernel's autotune resolution hits
    the persistent cache cross-process — process A tunes+persists, B
    resolves with ZERO probes (no tune, hit counter > 0)."""

    @staticmethod
    def _run_child(cache_dir):
        env = dict(os.environ)
        env.update({"JAX_PLATFORMS": "cpu",
                    "PADDLE_TPU_AUTOTUNE": "force",
                    "PADDLE_TPU_AUTOTUNE_CACHE_DIR": str(cache_dir),
                    "PADDLE_TPU_AUTOTUNE_REPEATS": "1",
                    "PADDLE_TPU_AUTOTUNE_MAX_CONFIGS": "3"})
        proc = subprocess.run(
            [sys.executable, "-c", _CONV_BN_CHILD],
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env=env, capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stderr[-2000:]
        for line in proc.stdout.splitlines():
            if line.startswith("RESULT"):
                return json.loads(line[len("RESULT"):])
        raise AssertionError(f"child printed no RESULT: {proc.stdout!r}")

    @pytest.mark.slow  # two child processes; test_changed_space_retunes stays fast
    def test_tune_once_then_hit_without_probes(self, tmp_path):
        a = self._run_child(tmp_path)
        assert a["miss"] == 1 and a["tunes"] == 1 and a["persist"] == 1
        assert list(tmp_path.glob("conv_bn-*.json"))
        b = self._run_child(tmp_path)
        assert b["hit"] > 0, "process B did not hit the persistent cache"
        assert b["miss"] == 0 and b["tunes"] == 0, \
            "process B re-probed a cached conv_bn config"
        assert b["y0"] == a["y0"]


class TestCandidateSpaceFingerprint:
    """Review regression: widening a kernel's candidate space must MISS
    the old space's persisted entry and re-tune — the disk path carries a
    candidate-space fingerprint on top of (op, key, chip)."""

    def test_changed_space_retunes(self, tuner, monkeypatch):
        calls = []

        def bench(cfg):
            calls.append(cfg.label)

        default = tiling.make_config(rows=256)
        narrow = [default, tiling.make_config(rows=128)]
        cfg1 = autotune.get_config("space_op", (1024, "f32"), narrow,
                                   default, bench, interpret=True)
        assert _ev("persist", "space_op") == 1
        n_after_first = len(calls)
        assert n_after_first > 0
        # same space resolves from disk after a memory reset: no probes
        autotune.reset_for_tests()
        cfg2 = autotune.get_config("space_op", (1024, "f32"), narrow,
                                   default, bench, interpret=True)
        assert cfg2 == cfg1 and len(calls) == n_after_first
        assert _ev("hit", "space_op") == 1
        # WIDENED space: the old entry must not satisfy the lookup
        autotune.reset_for_tests()
        wide = narrow + [tiling.make_config(rows=512)]
        autotune.get_config("space_op", (1024, "f32"), wide, default,
                            bench, interpret=True)
        assert len(calls) > n_after_first, \
            "widened candidate space served the stale narrow-space entry"
        assert _ev("persist", "space_op") == 2
