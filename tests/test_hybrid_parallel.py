"""Hybrid-parallel engine: TP/ZeRO/AMP/grad-merge on the 8-device CPU mesh.

Reference test style: hybrid dygraph suites
(`/root/reference/python/paddle/fluid/tests/unittests/
test_parallel_dygraph_tensor_parallel.py`) assert parallel losses equal
single-device losses — same here, with the mesh standing in for ranks.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn, optimizer
from paddle_tpu.nn import functional as F
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.meta_parallel import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy)
from paddle_tpu.distributed.meta_parallel.engine import HybridParallelTrainStep
from paddle_tpu.distributed.topology import (HybridCommunicateGroup,
                                             build_mesh)


@pytest.fixture(autouse=True)
def _clean_topology():
    yield
    dist.set_hybrid_communicate_group(None)
    dist.destroy_process_group()


class MLP(nn.Layer):
    """Megatron block: column-parallel then row-parallel."""

    def __init__(self, d=16, hidden=32, nclass=8):
        super().__init__()
        self.fc1 = ColumnParallelLinear(d, hidden, gather_output=False)
        self.fc2 = RowParallelLinear(hidden, nclass, input_is_parallel=True)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def _make_data(n=16, d=16, nclass=8):
    rs = np.random.RandomState(0)
    X = rs.randn(n, d).astype(np.float32)
    Y = rs.randint(0, nclass, (n,)).astype(np.int32)
    return X, Y


def _run_steps(step, X, Y, n=4):
    losses = []
    for _ in range(n):
        losses.append(float(step(paddle.to_tensor(X), paddle.to_tensor(Y))))
    return losses


def _reference_losses(seed, X, Y, n=4, lr=0.1):
    paddle.seed(seed)
    net = MLP()
    opt = optimizer.SGD(learning_rate=lr, parameters=net.parameters())
    losses = []
    for _ in range(n):
        loss = F.cross_entropy(net(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    return losses


def _engine_losses(seed, X, Y, dims, strategy=None, n=4, lr=0.1):
    fleet.init(is_collective=True, strategy=strategy or DistributedStrategy())
    dist.set_hybrid_communicate_group(HybridCommunicateGroup(dims=dims))
    paddle.seed(seed)
    net = MLP()
    opt = optimizer.SGD(learning_rate=lr, parameters=net.parameters())
    step = HybridParallelTrainStep(
        net, lambda lg, lb: F.cross_entropy(lg, lb), opt,
        strategy=strategy)
    return _run_steps(step, X, Y, n), step


class TestTensorParallel:
    def test_tp_matches_single_device(self):
        X, Y = _make_data()
        ref = _reference_losses(3, X, Y)
        got, step = _engine_losses(3, X, Y, {"dp": 2, "mp": 4})
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)
        # weights really are sharded over mp
        w1 = step.params["fc1.weight"]
        assert "mp" in str(w1.sharding.spec)

    def test_tp_param_sync_back(self):
        X, Y = _make_data()
        _, step = _engine_losses(5, X, Y, {"mp": 8})
        step.sync_to_layer()
        w = dict(step.layer.named_parameters())["fc1.weight"]
        np.testing.assert_allclose(np.asarray(step.params["fc1.weight"]),
                                   w.numpy())

    def test_vocab_parallel_embedding_and_ce(self):
        mesh = build_mesh({"mp": 8})
        dist.set_hybrid_communicate_group(
            HybridCommunicateGroup(mesh=mesh))
        paddle.seed(11)
        emb = VocabParallelEmbedding(64, 16)
        pce = ParallelCrossEntropy()
        ids = paddle.to_tensor(
            np.random.RandomState(2).randint(0, 64, (4, 8)).astype("int32"))
        out = emb(ids)
        assert out.shape == [4, 8, 16]
        # parity with plain embedding math
        ref = emb.weight.numpy()[ids.numpy()]
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)
        logits = paddle.to_tensor(
            np.random.RandomState(3).randn(4, 64).astype("float32"))
        labels = paddle.to_tensor(
            np.random.RandomState(4).randint(0, 64, (4,)).astype("int32"))
        got = pce(logits, labels)
        ref_loss = F.cross_entropy(logits, labels, reduction="none")
        np.testing.assert_allclose(got.numpy(), ref_loss.numpy(), rtol=1e-6)


class TestZeRO:
    @pytest.mark.parametrize("stage", [1, 2, 3])
    def test_sharding_stage_matches_single_device(self, stage):
        X, Y = _make_data()
        ref = _reference_losses(7, X, Y)
        strategy = DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs = {"stage": stage, "degree": 4}
        got, step = _engine_losses(7, X, Y, {"dp": 2, "sharding": 4},
                                   strategy=strategy)
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)
        if stage >= 3:
            w = step.params["fc1.weight"]
            assert "sharding" in str(w.sharding.spec)

    def test_zero1_slots_sharded(self):
        X, Y = _make_data()
        fleet.init()
        dist.set_hybrid_communicate_group(
            HybridCommunicateGroup(dims={"sharding": 8}))
        paddle.seed(1)
        net = MLP()
        opt = optimizer.Adam(learning_rate=0.01,
                             parameters=net.parameters())
        step = HybridParallelTrainStep(
            net, lambda lg, lb: F.cross_entropy(lg, lb), opt)
        step(paddle.to_tensor(X), paddle.to_tensor(Y))
        m = step.opt_state["fc1.weight"]["moment1"] \
            if "moment1" in step.opt_state["fc1.weight"] \
            else list(step.opt_state["fc1.weight"].values())[0]
        assert "sharding" in str(m.sharding.spec)


class TestAMPAndGradMerge:
    def test_amp_bf16_compute(self):
        X, Y = _make_data()
        strategy = DistributedStrategy()
        strategy.amp = True
        got, step = _engine_losses(9, X, Y, {"dp": 8}, strategy=strategy)
        # master params stay fp32
        assert step.params["fc1.weight"].dtype == jnp.float32
        # bf16 training converges same direction
        assert got[-1] < got[0]

    def test_gradient_merge_matches_full_batch_sgd(self):
        X, Y = _make_data(n=16)
        ref = _reference_losses(13, X, Y, n=3)
        strategy = DistributedStrategy()
        strategy.gradient_merge = True
        strategy.gradient_merge_configs = {"k_steps": 4}
        got, _ = _engine_losses(13, X, Y, {"dp": 2}, strategy=strategy, n=3)
        # mean-of-micro-losses == full-batch loss; SGD update identical
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-6)


class TestFleetFacade:
    def test_fleet_init_and_wrappers(self):
        strategy = DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 1, "sharding_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_sharding_parallel_world_size() == 2
        net = MLP()
        model = fleet.distributed_model(net)
        opt = fleet.distributed_optimizer(
            optimizer.SGD(learning_rate=0.1, parameters=net.parameters()))
        assert opt is not None and model is not None
        assert fleet.worker_index() == 0 and fleet.worker_num() == 1

    def test_strategy_roundtrip(self):
        s = DistributedStrategy()
        s.amp = True
        s.sharding = True
        s.sharding_configs = {"stage": 2, "degree": 4}
        s.hybrid_configs = {"mp_degree": 4}
        s2 = DistributedStrategy.from_json(s.to_json())
        assert s2.amp and s2.sharding
        assert s2.sharding_configs["stage"] == 2
        assert s2.hybrid_configs["mp_degree"] == 4


class TestFp16GradScaling:
    """Strategy amp dtype='float16' runs dynamic loss scaling INSIDE the
    compiled step (reference GradScaler/check_finite_and_unscale parity —
    round-1 review flagged the engines as fp16-unsupported)."""

    def _hcg(self, dims):
        from paddle_tpu.distributed.topology import HybridCommunicateGroup
        hcg = HybridCommunicateGroup(dims=dims)
        dist.set_hybrid_communicate_group(hcg)
        return hcg

    def test_fp16_trains_and_keeps_scale(self):
        hcg = self._hcg({"dp": 8})
        try:
            strategy = DistributedStrategy()
            strategy.amp = True
            strategy.amp_configs = {"dtype": "float16",
                                    "init_loss_scaling": 256.0}
            paddle.seed(0)
            model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                                  nn.Linear(32, 4))
            opt = optimizer.SGD(learning_rate=0.1,
                                parameters=model.parameters())
            step = HybridParallelTrainStep(
                model, lambda o, y: F.cross_entropy(o, y), opt, hcg=hcg,
                strategy=strategy)
            rng = np.random.default_rng(0)
            x = paddle.to_tensor(rng.normal(size=(16, 16)).astype(np.float32))
            y = paddle.to_tensor(rng.integers(0, 4, (16,)).astype(np.int32))
            losses = [float(step(x, y)) for _ in range(12)]
            assert all(np.isfinite(losses)), losses
            assert losses[-1] < losses[0], losses
            # healthy fp16 run: scale survives at its initial value
            assert float(step.scaler_state["scale"]) == 256.0
        finally:
            dist.set_hybrid_communicate_group(None)

    def test_overflow_shrinks_scale_and_skips_update(self):
        hcg = self._hcg({"dp": 8})
        try:
            strategy = DistributedStrategy()
            strategy.amp = True
            # absurd scale: fp16 grads overflow -> update skipped, scale
            # halves each step until training can resume
            strategy.amp_configs = {"dtype": "float16",
                                    "init_loss_scaling": 2.0 ** 40}
            paddle.seed(0)
            model = nn.Sequential(nn.Linear(8, 8))
            opt = optimizer.SGD(learning_rate=0.1,
                                parameters=model.parameters())
            step = HybridParallelTrainStep(
                model, lambda o, y: ((o - y) ** 2).mean(), opt, hcg=hcg,
                strategy=strategy)
            w0 = np.asarray(step.params["0.weight"])
            rng = np.random.default_rng(0)
            x = paddle.to_tensor(rng.normal(size=(8, 8)).astype(np.float32))
            y = paddle.to_tensor(rng.normal(size=(8, 8)).astype(np.float32))
            float(step(x, y))
            # overflowed: scale halved, parameters untouched
            assert float(step.scaler_state["scale"]) == 2.0 ** 39
            np.testing.assert_array_equal(
                np.asarray(step.params["0.weight"]), w0)
            for _ in range(40):
                float(step(x, y))
            # scale decayed into fp16 range and updates resumed
            assert float(step.scaler_state["scale"]) < 2.0 ** 20
            assert not np.array_equal(
                np.asarray(step.params["0.weight"]), w0)
        finally:
            dist.set_hybrid_communicate_group(None)


class TestHealthProbeWiring:
    """r06 satellite: the PR-9 in-graph numerics sentinel rides in the
    hybrid engine's own compiled step (it builds its step itself and did
    not carry the TrainStep wiring)."""

    def test_sentinel_records_on_hybrid_step(self):
        from paddle_tpu.profiler import health as health_mod
        X, Y = _make_data()
        fleet.init(is_collective=True, strategy=DistributedStrategy())
        dist.set_hybrid_communicate_group(
            HybridCommunicateGroup(dims={"dp": 2, "mp": 4}))
        paddle.seed(0)
        net = MLP()
        opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        step = HybridParallelTrainStep(
            net, lambda lg, lb: F.cross_entropy(lg, lb), opt, health=True)
        assert step._health_probe is not None
        loss = float(step(paddle.to_tensor(X), paddle.to_tensor(Y)))
        rec = step.last_health
        assert rec is not None and rec["step"] == 1
        assert rec["loss"] == pytest.approx(loss, rel=1e-5)
        assert np.isfinite(rec["grad_norm"]) and rec["grad_norm"] > 0
        assert not rec["nonfinite"]
        assert health_mod.last_stats() is not None

    def test_nan_input_trips_sentinel(self):
        X, Y = _make_data()
        X = X.copy()
        X[0, 0] = np.nan
        fleet.init(is_collective=True, strategy=DistributedStrategy())
        dist.set_hybrid_communicate_group(
            HybridCommunicateGroup(dims={"dp": 2, "mp": 4}))
        paddle.seed(0)
        net = MLP()
        opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        step = HybridParallelTrainStep(
            net, lambda lg, lb: F.cross_entropy(lg, lb), opt, health=True)
        step(paddle.to_tensor(X), paddle.to_tensor(Y))
        assert step.last_health["nonfinite"]
        from paddle_tpu.profiler import health as health_mod
        health_mod.clear_trip()

    def test_health_off_keeps_step_shape(self, monkeypatch):
        monkeypatch.delenv("PADDLE_TPU_HEALTH", raising=False)
        X, Y = _make_data()
        fleet.init(is_collective=True, strategy=DistributedStrategy())
        dist.set_hybrid_communicate_group(
            HybridCommunicateGroup(dims={"dp": 8}))
        paddle.seed(0)
        net = MLP()
        opt = optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        step = HybridParallelTrainStep(
            net, lambda lg, lb: F.cross_entropy(lg, lb), opt)
        assert step._health_probe is None
        float(step(paddle.to_tensor(X), paddle.to_tensor(Y)))
        assert step.last_health is None


class TestHealthUnderFp16:
    """Review regression: under fp16 dynamic loss scaling the sentinel
    must see UNSCALED grads (norms not inflated by the 2^k scale) and a
    scaler overflow event (non-finite scaled grad, update skipped, scale
    halves — GradScaler semantics) must NOT trip the nonfinite flag."""

    def _step(self, init_scale=256.0):
        fleet.init(is_collective=True, strategy=DistributedStrategy())
        dist.set_hybrid_communicate_group(
            HybridCommunicateGroup(dims={"dp": 8}))
        strategy = DistributedStrategy()
        strategy.amp = True
        strategy.amp_configs = {"dtype": "float16",
                                "init_loss_scaling": init_scale}
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 4))
        opt = optimizer.SGD(learning_rate=0.1,
                            parameters=model.parameters())
        return HybridParallelTrainStep(
            model, lambda o, y: F.cross_entropy(o, y), opt,
            strategy=strategy, health=True)

    def test_grad_norm_is_unscaled(self):
        step = self._step()
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(rng.normal(size=(16, 16)).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 4, (16,)).astype(np.int32))
        step(x, y)
        rec = step.last_health
        assert rec is not None and not rec["nonfinite"]
        # a scaled norm would be ~256x; sane unscaled CE-grad norms on
        # this toy model sit well under 100
        assert 0 < rec["grad_norm"] < 100.0, rec["grad_norm"]

    def test_scaler_overflow_does_not_trip_sentinel(self):
        # an absurd initial scale overflows the fp16 scaled grads on the
        # first step; the scaler skips the update and halves — the
        # sentinel must not read that as numeric divergence
        step = self._step(init_scale=2.0 ** 32)
        rng = np.random.default_rng(0)
        x = paddle.to_tensor(
            (rng.normal(size=(16, 16)) * 100).astype(np.float32))
        y = paddle.to_tensor(rng.integers(0, 4, (16,)).astype(np.int32))
        step(x, y)
        rec = step.last_health
        assert rec is not None
        assert not rec["nonfinite"], rec
        assert float(step.scaler_state["scale"]) < 2.0 ** 32  # it fired
        from paddle_tpu.profiler import health as health_mod
        health_mod.clear_trip()
