"""Tensor-parallel decode on the virtual-mesh CI harness: the paged KV
pools and attention heads shard over a 2-device ``Mesh(("tp",))`` (CPU
devices faked via --xla_force_host_platform_device_count in conftest)
and greedy decode must stay BIT-EXACT vs the single-chip fused path —
across prefill-bucket transitions, pool-exhaustion preemption (re-prefill
lands in a larger bucket), a CoW-forked shared prefix, and a weight
hot-swap (sharded-weights staging).  The per-link collective-bytes audit
(analysis satellite) runs over the live TP decode program here too.

Compile-cost note: one module-scoped TP engine serves every test that
doesn't need special shapes (the tiny 2-head GPT puts one head per
shard at tp=2); only the preemption test builds a second, tight-pool
engine.  The hot-swap test runs LAST — it rebinds the shared engine's
weights.

fast-sibling: serving-at-scale TP numbers live in bench.py's
gpt2_decode ``tp_decode`` block.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.profiler import events

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 2,
    reason="TP decode parity needs >=2 (virtual) devices")


@pytest.fixture(autouse=True)
def _clean_events():
    events.default_event_log().clear()
    yield
    events.default_event_log().clear()


@pytest.fixture(scope="module", autouse=True)
def _shared_compile_cache():
    """Same persistent-compile-cache dir as test_serving.py: the mesh
    engines here re-lower the identical tiny-model executables, so only
    the first build across the whole serving test set pays XLA."""
    import os
    import tempfile
    from paddle_tpu.framework import flags as flags_mod
    cache = os.path.join(tempfile.gettempdir(), "pt_serving_ccache")
    os.makedirs(cache, exist_ok=True)
    flags_mod.set_flags({"FLAGS_compile_cache_dir": cache})
    yield
    flags_mod.set_flags({"FLAGS_compile_cache_dir": ""})


def _mesh(n=2):
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:n]), ("tp",))


def _model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, max_position_embeddings=128,
                    hidden_size=32, num_layers=2, num_heads=2,
                    dropout=0.0, attn_dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m, cfg


@pytest.fixture(scope="module")
def shared():
    """(model, cfg, 2-way TP engine) reused across the module — each
    test submits its own requests; pages/slots fully recycle between
    tests (asserted by the CoW test's no-leak audit)."""
    m, cfg = _model()
    eng = ServingEngine(m, max_batch=4, max_len=64, page_size=8,
                        name="tp0", mesh=_mesh())
    yield m, cfg, eng
    eng.close()


def _ref(m, prompt, n, page_size=8):
    """Single-chip reference greedy paged decode (the fused engine is
    pinned to this in test_serving.py; TP pins to the same tokens).
    The model is DISARMED for the reference run — generate_paged on a
    TP-armed model would itself shard, and the parity claim is
    TP-vs-single-chip, not TP-vs-TP."""
    mesh, axis = m.tp_mesh(), getattr(m, "_tp_axis", "tp")
    m.set_tp_mesh(None)
    try:
        ids = paddle.to_tensor(np.asarray([prompt], np.int32))
        out = np.asarray(m.generate_paged(ids, n,
                                          page_size=page_size).data)
    finally:
        m.set_tp_mesh(mesh, axis)
    return out[0, len(prompt):].tolist()


class TestTPParity:
    def test_greedy_bit_exact_across_buckets(self, shared):
        """Prompt lengths spanning all three prefill buckets (16/32/64),
        decode crossing page boundaries — every stream matches the
        single-chip tokens exactly."""
        m, cfg, eng = shared
        assert eng.tp_degree() == 2
        prompts = [[5, 7, 11, 13],                  # bucket 16
                   list(range(1, 18)),              # bucket 32
                   [42] * 30]                       # bucket 64
        reqs = [eng.submit(p, max_new_tokens=12) for p in prompts]
        eng.run_until_idle()
        for p, r in zip(prompts, reqs):
            assert r.result(timeout=5) == _ref(m, p, 12), \
                "TP decode diverged from the single-chip greedy tokens"
        st = eng.status()
        assert st["tp_degree"] == 2 and st["tp_axis"] == "tp"
        # the pools actually shard: each K page pool spans both devices
        assert len(eng.cache.k_pages[0].sharding.device_set) == 2

    def test_parity_with_cow_forked_shared_prefix(self, shared):
        """Exact-duplicate prompts admit onto shared pages (partial
        tail included); the first decode write CoW-forks the shared
        tail page — on SHARDED pools the fork must copy every device's
        head slice, or tokens diverge."""
        m, cfg, eng = shared
        prompt = list(range(1, 13))  # 12 tokens: full page + partial tail
        cow0 = eng.stats["cow_copies"]
        reqs = [eng.submit(prompt, max_new_tokens=6) for _ in range(2)]
        eng.run_until_idle()
        ref = _ref(m, prompt, 6)
        for r in reqs:
            assert r.result(timeout=5) == ref
        assert eng.stats["shared_admissions"] >= 1
        assert eng.stats["cow_copies"] > cow0
        assert not eng.allocator.outstanding()  # no refcount leaks

    @pytest.mark.slow
    def test_parity_under_preemption(self):
        """A pool too small for the whole batch: the preempted request
        re-prefills (prompt + generated prefix, landing in a LARGER
        bucket than its first admission) and still produces the exact
        single-chip tokens on sharded pools.  Slow: builds a SECOND
        mesh engine with its own shapes (batch2/len40/6 pages), a full
        extra set of sharded-program compiles on a cold cache.

        fast-sibling: tests/test_tp_decode.py (bucket parity + CoW on
        the shared engine stay tier-1-fast)."""
        m, cfg = _model()
        rng = np.random.default_rng(17)
        prompts = [rng.integers(1, cfg.vocab_size, (14,)).tolist()
                   for _ in range(2)]
        eng = ServingEngine(m, max_batch=2, max_len=40, page_size=8,
                            num_pages=6, prefill_buckets=(16, 32, 64),
                            name="tppre", mesh=_mesh())
        reqs = [eng.submit(p, max_new_tokens=12) for p in prompts]
        eng.run_until_idle()
        assert eng.stats["preemptions"] >= 1
        for p, r in zip(prompts, reqs):
            out = r.result(timeout=5)
            assert len(out) == 12
            assert out == _ref(m, p, 12), \
                "preemption under TP changed the greedy tokens"
        eng.close()

    def test_audit_emits_per_link_collective_report(self, shared):
        """The static auditor's per-link satellite runs over the live
        TP decode program: a third report with entry='collectives' and
        the ici/dcn byte split (all-ICI on a single virtual slice)."""
        m, cfg, eng = shared
        reports = eng.audit(emit=False)
        assert len(reports) == 3
        link = reports[-1]
        assert link.entry == "collectives"
        assert set(link.link_bytes) == {"ici", "dcn"}
        assert link.link_bytes["ici"] > 0  # head-slice all-gather
        assert link.link_bytes["dcn"] == 0.0  # one virtual slice

    def test_hot_swap_replicates_staged_weights(self, shared):
        """request_swap on a sharded engine: the candidate weights are
        replicated onto the mesh at stage time and post-swap tokens
        match the new model's single-chip reference.  Runs LAST — it
        rebinds the shared engine's weights."""
        m, cfg, eng = shared
        # the manager inherits the engine's mesh: sharded-checkpoint
        # loads reassemble onto the decode mesh without the caller
        # re-plumbing it
        from paddle_tpu.inference.hotswap import HotSwapManager
        hsm = HotSwapManager(eng, "/nonexistent", poll_s=999, canary=False)
        assert hsm.mesh is eng.mesh
        prompt = [9, 8, 7, 6, 5]
        r0 = eng.submit(prompt, max_new_tokens=4)
        eng.run_until_idle()
        assert r0.result(timeout=5) == _ref(m, prompt, 4)
        paddle.seed(1)
        m2 = GPT(cfg)
        m2.eval()
        eng.request_swap({k: p.data for k, p in m2.named_parameters()})
        r1 = eng.submit(prompt, max_new_tokens=4)
        eng.run_until_idle()
        assert r1.result(timeout=5) == _ref(m2, prompt, 4), \
            "post-swap TP tokens must come from the swapped weights"
