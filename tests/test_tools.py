"""Repo tools (reference `tools/CrossStackProfiler/` + the op-benchmark CI
gate `tools/check_op_benchmark_result.py`): trace merging with per-rank
lanes and clock alignment, the cross-rank op summary, and the bench
regression gate against real BENCH_r*.json artifacts."""
import json
import os
import pathlib
import subprocess
import sys

import numpy as np
import pytest

REPO = str(pathlib.Path(__file__).resolve().parent.parent)
sys.path.insert(0, os.path.join(REPO, "tools"))

import check_bench_result as gate  # noqa: E402
import cross_stack_profiler as csp  # noqa: E402


def _trace(events):
    return {"traceEvents": [
        {"name": n, "ph": "X", "cat": "op", "ts": ts, "dur": d,
         "pid": 1234, "tid": 0} for n, ts, d in events]}


class TestCrossStackProfiler:
    def test_merge_assigns_rank_lanes_and_aligns(self, tmp_path):
        (tmp_path / "rank_0.json").write_text(json.dumps(
            _trace([("matmul", 1000.0, 5.0)])))
        (tmp_path / "rank_1.json").write_text(json.dumps(
            _trace([("matmul", 9000.0, 7.0)])))  # different host clock
        traces = csp.load_rank_traces(str(tmp_path))
        merged = csp.merge_traces(traces, align=True)
        xs = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
        assert {e["pid"] for e in xs} == {0, 1}
        assert all(e["ts"] == 0.0 for e in xs)  # aligned to rank t0
        names = [e for e in merged["traceEvents"]
                 if e.get("ph") == "M" and e["name"] == "process_name"]
        assert {m["args"]["name"] for m in names} == {"rank 0", "rank 1"}

    def test_op_summary_aggregates_across_ranks(self):
        traces = {0: _trace([("conv", 0, 10.0), ("conv", 20, 30.0)]),
                  1: _trace([("conv", 0, 20.0), ("relu", 5, 1.0)])}
        rows = csp.op_summary(traces)
        conv = next(r for r in rows if r["name"] == "conv")
        assert conv["calls"] == 3
        assert conv["total_us"] == pytest.approx(60.0)
        assert conv["max_us"] == pytest.approx(30.0)
        assert conv["by_rank"] == {0: 40.0, 1: 20.0}
        assert rows[0]["name"] == "conv"  # sorted by total desc

    def test_cli_end_to_end(self, tmp_path):
        d = tmp_path / "traces"
        d.mkdir()
        (d / "worker_0.json").write_text(json.dumps(
            _trace([("step", 0, 100.0)])))
        out = tmp_path / "merged.json"
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "cross_stack_profiler.py"),
             "--trace_dir", str(d), "--out", str(out), "--summary"],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        assert out.exists()
        assert "step" in r.stdout

    def test_merges_real_profiler_export(self, tmp_path):
        """End-to-end with the actual paddle_tpu profiler output format."""
        import paddle_tpu as paddle
        from paddle_tpu import profiler as P
        prof = P.Profiler()
        prof.start()
        with P.RecordEvent("span_a"):
            paddle.to_tensor(np.ones(4)) * 2
        prof.stop()
        f0 = str(tmp_path / "rank_0.json")
        prof.export(f0)
        traces = csp.load_rank_traces([f0])
        rows = csp.op_summary(traces)
        assert any(r["name"] == "span_a" for r in rows)


class TestBenchGate:
    BASE = {"configs": {
        "gpt": {"tokens_per_sec_chip": 100000.0},
        "resnet": {"samples_per_sec_chip": 2000.0},
        "ps": {"examples_per_sec": 10000.0}}}

    def test_ok_and_improved(self):
        cur = {"configs": {
            "gpt": {"tokens_per_sec_chip": 101000.0},
            "resnet": {"samples_per_sec_chip": 2500.0},
            "ps": {"examples_per_sec": 9900.0}}}
        rows = gate.compare(self.BASE, cur, 0.05)
        by = {r[0]: r[5] for r in rows}
        assert by == {"gpt": "ok", "resnet": "improved", "ps": "ok"}

    def test_regression_detected(self):
        cur = {"configs": {
            "gpt": {"tokens_per_sec_chip": 80000.0},
            "resnet": {"samples_per_sec_chip": 2000.0},
            "ps": {"examples_per_sec": 10000.0}}}
        rows = gate.compare(self.BASE, cur, 0.05)
        assert ("gpt", "tokens_per_sec_chip", 100000.0, 80000.0, -0.2,
                "regressed") in rows

    def test_same_metric_enforced(self):
        """Current config reporting a DIFFERENT (higher-priority) metric
        must read as missing, not compared across units."""
        cur = {"configs": {
            "gpt": {"tokens_per_sec_chip": 100000.0},
            "resnet": {"tokens_per_sec_chip": 500000.0},  # unit switch
            "ps": {"examples_per_sec": 10000.0}}}
        rows = gate.compare(self.BASE, cur, 0.05)
        by = {r[0]: r[5] for r in rows}
        assert by["resnet"] == "missing"

    def test_zero_baseline_unusable(self):
        base = {"configs": {"gpt": {"tokens_per_sec_chip": 0.0}}}
        cur = {"configs": {"gpt": {"tokens_per_sec_chip": 1.0}}}
        rows = gate.compare(base, cur, 0.05)
        assert rows[0][5] == "missing"

    def test_duplicate_rank_files_rejected(self, tmp_path):
        (tmp_path / "rank_0.json").write_text(json.dumps(_trace([])))
        (tmp_path / "worker_0.json").write_text(json.dumps(_trace([])))
        with pytest.raises(ValueError, match="rank 0"):
            csp.load_rank_traces(str(tmp_path))

    def test_missing_config_fails(self):
        cur = {"configs": {"gpt": {"tokens_per_sec_chip": 100000.0}}}
        rows = gate.compare(self.BASE, cur, 0.05)
        assert any(r[5] == "missing" for r in rows)

    def test_cli_on_real_driver_artifacts(self, tmp_path):
        """The gate must parse the actual driver BENCH files in the repo."""
        base = os.path.join(REPO, "BENCH_r02.json")
        cur = os.path.join(REPO, "BENCH_r04.json")
        if not (os.path.exists(base) and os.path.exists(cur)):
            pytest.skip("driver bench artifacts absent")
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "check_bench_result.py"),
             "--baseline", base, "--current", cur, "--threshold", "0.05"],
            capture_output=True, text=True, timeout=120)
        assert r.returncode in (0, 1), r.stderr  # parses + gates
        assert "gpt2_small" in r.stdout

class TestObservabilitySchemaGate:
    """check_bench_result.py validates `observability` sections against the
    step-record and event schemas (fleet-observability satellite)."""

    @staticmethod
    def _good_doc():
        import time as _time
        from paddle_tpu.profiler.monitor import make_step_record
        return {
            "configs": {"gpt": {"tokens_per_sec_chip": 100000.0}},
            "observability": {
                "step_records": [make_step_record(
                    step=10, window_steps=10, window_time_s=1.0)],
                "events_tail": [{"ts": _time.time(), "kind": "retrace",
                                 "host": "trainer-0", "severity": "info"}],
            },
        }

    def test_valid_observability_passes(self):
        doc = self._good_doc()
        assert gate.validate_observability(doc) == []

    def test_bad_step_record_and_event_named(self):
        doc = self._good_doc()
        doc["observability"]["step_records"][0].pop("ts")
        doc["observability"]["events_tail"][0]["kind"] = "Not Legal"
        problems = gate.validate_observability(doc)
        assert len(problems) == 2
        assert any("step_records[0]" in p and "ts" in p for p in problems)
        assert any("events_tail[0]" in p and "kind" in p for p in problems)

    def test_per_config_blocks_validated(self):
        doc = self._good_doc()
        doc["configs"]["gpt"]["observability"] = {
            "step_records": [{"bogus": True}]}
        problems = gate.validate_observability(doc)
        assert any("configs.gpt.observability" in p for p in problems)

    def test_missing_observability_is_fine(self):
        assert gate.validate_observability(
            {"configs": {"gpt": {"tokens_per_sec_chip": 1.0}}}) == []

    def test_gate_fails_on_schema_violation(self, tmp_path, capsys):
        base = tmp_path / "base.json"
        cur = tmp_path / "cur.json"
        base.write_text(json.dumps(self._good_doc()))
        bad = self._good_doc()
        bad["observability"]["events_tail"][0].pop("host")
        cur.write_text(json.dumps(bad))
        rc = gate.main(["--baseline", str(base), "--current", str(cur)])
        assert rc == 1
        out = capsys.readouterr().out
        assert "observability schema violations" in out
        # --no-obs-check restores the old perf-only gate
        assert gate.main(["--baseline", str(base), "--current", str(cur),
                          "--no-obs-check"]) == 0

    def test_real_driver_artifact_validates(self):
        path = os.path.join(REPO, "BENCH_r05.json")
        if not os.path.exists(path):
            pytest.skip("no driver artifact on this box")
        assert gate.validate_observability(gate._load(path)) == []


class TestAsyncCheckpointMetricsGate:
    """checkpoint_async_* families in an observability metrics snapshot
    must be the right kind with a consistent shape (sharded-checkpoint
    satellite)."""

    @staticmethod
    def _doc_with_metrics(metrics):
        doc = TestObservabilitySchemaGate._good_doc()
        doc["observability"]["metrics"] = metrics
        return doc

    @staticmethod
    def _good_metrics():
        return {
            "checkpoint_async_pending": {
                "kind": "gauge", "help": "h",
                "values": [{"labels": {}, "value": 0.0}]},
            "checkpoint_async_bytes": {
                "kind": "counter", "help": "h",
                "values": [{"labels": {}, "value": 1024.0}]},
            "checkpoint_async_seconds": {
                "kind": "histogram", "help": "h",
                "values": [{"labels": {},
                            "buckets": {"0.1": 1, "+Inf": 2},
                            "sum": 0.5, "count": 2}]},
        }

    def test_live_registry_snapshot_validates(self):
        # the REAL families registered by sharded_checkpoint must pass
        import paddle_tpu.distributed.sharded_checkpoint  # noqa: F401
        from paddle_tpu.profiler.metrics import default_registry
        snap = default_registry().snapshot()
        assert set(_k for _k in snap if _k.startswith("checkpoint_async")) \
            == {"checkpoint_async_pending", "checkpoint_async_bytes",
                "checkpoint_async_seconds"}
        doc = self._doc_with_metrics(snap)
        assert gate.validate_observability(doc) == []

    def test_good_families_pass(self):
        assert gate.validate_observability(
            self._doc_with_metrics(self._good_metrics())) == []

    def test_wrong_kind_named(self):
        m = self._good_metrics()
        m["checkpoint_async_pending"]["kind"] = "counter"
        problems = gate.validate_observability(self._doc_with_metrics(m))
        assert any("checkpoint_async_pending" in p and "gauge" in p
                   for p in problems)

    def test_inconsistent_histogram_named(self):
        m = self._good_metrics()
        m["checkpoint_async_seconds"]["values"][0]["buckets"]["+Inf"] = 99
        problems = gate.validate_observability(self._doc_with_metrics(m))
        assert any("checkpoint_async_seconds" in p and "inconsistent" in p
                   for p in problems)

    def test_negative_value_and_unknown_family_named(self):
        m = self._good_metrics()
        m["checkpoint_async_bytes"]["values"][0]["value"] = -1
        m["checkpoint_async_queue"] = {"kind": "gauge", "values": []}
        problems = gate.validate_observability(self._doc_with_metrics(m))
        assert any("checkpoint_async_bytes" in p for p in problems)
        assert any("checkpoint_async_queue" in p and "unknown" in p
                   for p in problems)

    def test_other_families_ignored(self):
        doc = self._doc_with_metrics(
            {"op_calls_total": {"kind": "counter", "values": "garbage"}})
        assert gate.validate_observability(doc) == []

    def test_malformed_values_reported_not_crash(self):
        for bad in ("garbage", [1, 2], [{"value": 1}, "x"]):
            m = {"checkpoint_async_pending": {"kind": "gauge",
                                             "values": bad}}
            problems = gate.validate_observability(self._doc_with_metrics(m))
            assert any("checkpoint_async_pending" in p for p in problems), \
                f"values={bad!r} did not produce a named violation"
